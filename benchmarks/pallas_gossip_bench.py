"""Gossip transport microbenchmark: Pallas RDMA kernels vs XLA ppermute.

On a real multi-chip TPU slice, times one fused-RDMA gossip step vs the XLA
lowering across payload sizes.  Per size it reports the gossip chunk plan
(auto always picks pallas there, splitting oversized payloads into
VMEM-cap-sized kernels) and where the non-chunkable WINDOW transport's
size cutoff flips its routing.  On a single chip only the XLA path
is timed (a shift-0 self-RDMA wedges the axon relay — see the inline note);
on a CPU mesh (no real kernel execution possible) it instead validates the
kernel under TPU-interpret emulation against the XLA path and times only the
XLA side, saying so in the output.

Run:  python benchmarks/pallas_gossip_bench.py [--sizes-kib 64 1024 4096]
Prints one JSON line.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from bluefog_tpu.ops import collectives as C
from bluefog_tpu.ops import pallas_gossip
from bluefog_tpu.parallel.api import shard_map
from bluefog_tpu.topology import ExponentialTwoGraph, RingGraph
from bluefog_tpu.topology.schedule import build_schedule


def _time(fn, x, steps):
    fn(x).block_until_ready()  # compile
    t0 = time.perf_counter()
    out = x
    for _ in range(steps):
        out = fn(out)
    out.block_until_ready()
    return (time.perf_counter() - t0) / steps * 1e3  # ms


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes-kib", type=int, nargs="+",
                    default=[64, 512, 1024, 4096, 16384])
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()

    devs = jax.devices()
    n = len(devs)
    on_tpu = devs[0].platform in ("tpu", "axon")
    mesh = Mesh(np.array(devs), ("bf",))
    if n > 1:
        sched = build_schedule(ExponentialTwoGraph(n))
    else:
        # Single chip: a shift-0 "self-RDMA" schedule is expressible (one
        # (0,0) slot) but empirically WEDGES the axon remote-TPU relay — the
        # kernel never returns and the chip claim goes stale (observed
        # 2026-07-30: two runs, 15 and 25 min, zero output, relay needed
        # recovery).  The RDMA kernel is therefore only timed on real
        # multi-chip slices; on one chip we time the XLA path and validate
        # kernel semantics in interpret mode like the CPU branch.
        from bluefog_tpu.topology.graphs import Topology

        sched = build_schedule(Topology(weights=np.ones((1, 1)),
                                        name="SelfLoop"))

    rows = []
    auto_choice = {}
    for kib in args.sizes_kib:
        elems = kib * 1024 // 4
        x = jnp.ones((n, elems), jnp.float32)
        x = jax.device_put(
            x, jax.sharding.NamedSharding(mesh, P("bf")))

        xla_fn = jax.jit(shard_map(
            lambda v: C.neighbor_allreduce(v, sched, "bf", backend="xla"),
            mesh=mesh, in_specs=(P("bf"),), out_specs=P("bf"),
            check_vma=False))
        row = {"kib": kib, "xla_ms": round(_time(xla_fn, x, args.steps), 3)}
        probe = jnp.zeros((elems,), jnp.float32)
        auto_choice[kib] = {
            "gossip": pallas_gossip.auto_gossip_backend(sched, probe),
            # chunk plan is undefined under a non-positive cap (the
            # "never use the kernels" override; leaf_chunk_count raises)
            "gossip_chunks": (pallas_gossip.leaf_chunk_count(probe)
                              if pallas_gossip.auto_max_bytes() > 0
                              else None),
            "window": pallas_gossip.auto_gossip_backend(
                sched, probe, chunkable=False),
        }

        if on_tpu and n > 1 and pallas_gossip.circulant_shifts(sched):
            pl_fn = jax.jit(shard_map(
                lambda v: C.neighbor_allreduce(v, sched, "bf",
                                               backend="pallas"),
                mesh=mesh, in_specs=(P("bf"),), out_specs=P("bf"),
                check_vma=False))
            row["pallas_ms"] = round(_time(pl_fn, x, args.steps), 3)
            row["pallas_speedup"] = round(row["xla_ms"] / row["pallas_ms"], 3)
        rows.append(row)

    interpret_parity = None
    if n > 1 and not on_tpu:
        # no hardware: prove the kernel's semantics instead (interpret mode)
        elems = 512
        xs = jnp.arange(n * elems, dtype=jnp.float32).reshape(n, elems)
        xs = jax.device_put(xs, jax.sharding.NamedSharding(mesh, P("bf")))
        want = jax.jit(shard_map(
            lambda v: C.neighbor_allreduce(v, sched, "bf", backend="xla"),
            mesh=mesh, in_specs=(P("bf"),), out_specs=P("bf"),
            check_vma=False))(xs)
        got = jax.jit(shard_map(
            lambda v: pallas_gossip.neighbor_allreduce_pallas(
                v[0], sched, "bf", interpret=True)[None],
            mesh=mesh, in_specs=(P("bf"),), out_specs=P("bf"),
            check_vma=False))(xs)
        interpret_parity = bool(np.allclose(np.asarray(got), np.asarray(want),
                                            rtol=1e-6))

    print(json.dumps({
        "metric": "pallas_gossip_vs_xla_ms",
        "platform": devs[0].platform,
        "n_devices": n,
        "rows": rows,
        "auto_backend_by_size": auto_choice,
        "interpret_parity_vs_xla": interpret_parity,
        "note": (None if on_tpu else
                 "no TPU attached: pallas timings require hardware; "
                 "interpret-mode parity validated instead"),
    }))


if __name__ == "__main__":
    main()
