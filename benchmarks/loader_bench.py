"""Input-pipeline overlap benchmark: does prefetch hide host data work?

Builds real on-disk TFRecord shards, then runs a loader+compute loop twice —
``prefetch=0`` (host gather/decode serializes with device compute) and
``prefetch=2`` (a background thread keeps batches ahead) — and reports the
overlap factor.  The compute is a jitted matmul loop sized to take roughly as
long as one batch's host work, the worst case for a non-overlapped pipeline.

``--io-ms`` adds per-batch source latency (sleep), modelling a disk/network-
bound source.  On a CPU-only host that is also the *honest* configuration:
decode and "device" compute share the same cores, so pure-CPU overlap cannot
exceed 1.0x — the prefetch win is hiding IO latency (and, on a real TPU,
hiding all host work under device compute).

Run (8-virtual-device CPU mesh):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  PALLAS_AXON_POOL_IPS= python benchmarks/loader_bench.py
Prints one JSON line.
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

import bluefog_tpu as bf
from bluefog_tpu.data import (
    DistributedLoader,
    TFRecordSource,
    write_image_classification_shards,
)


def run_epochs(loader, compute, epochs):
    # Block on each step's result, as a real train loop effectively does
    # (the next step depends on donated params) — otherwise jax async
    # dispatch pipelines the compute regardless of the loader and the
    # measurement only sees the source.
    t0 = time.perf_counter()
    for e in range(epochs):
        for imgs, labels in loader.epoch(e):
            jax.block_until_ready(compute(imgs))
    return time.perf_counter() - t0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--examples", type=int, default=512)
    ap.add_argument("--hw", type=int, default=48)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--matmul", type=int, default=384,
                    help="device work per step (matmul side)")
    ap.add_argument("--io-ms", type=float, default=10.0,
                    help="simulated per-batch source IO latency")
    args = ap.parse_args()

    n = len(jax.devices())
    bf.init()

    with tempfile.TemporaryDirectory() as d:
        rng = np.random.default_rng(0)
        images = rng.integers(0, 256, size=(args.examples, args.hw, args.hw, 3),
                              dtype=np.uint8)
        labels = rng.integers(0, 10, size=args.examples).astype(np.int64)
        write_image_classification_shards(d, images, labels, shard_size=128,
                                          prefix="train")
        src = TFRecordSource(os.path.join(d, "train-*.tfrecord"))

        if args.io_ms > 0:
            class IOBoundSource:
                """Real source + per-gather IO latency (disk/network model)."""

                def __init__(self, inner, delay_s):
                    self.inner, self.delay = inner, delay_s

                def __len__(self):
                    return len(self.inner)

                def __getitem__(self, idx):
                    time.sleep(self.delay)
                    return self.inner[idx]

            src = IOBoundSource(src, args.io_ms / 1e3)

        m = args.matmul
        w = jnp.ones((m, m), jnp.float32)

        @jax.jit
        def compute(imgs):
            z = w
            for _ in range(8):
                z = jnp.tanh(z @ w)
            return z.sum() + imgs.sum()

        def loader(prefetch):
            return DistributedLoader(src, args.batch, prefetch=prefetch)

        # warm caches/compiles
        run_epochs(loader(0), compute, 1)
        t_serial = run_epochs(loader(0), compute, args.epochs)
        t_overlap = run_epochs(loader(2), compute, args.epochs)

    print(json.dumps({
        "metric": "loader_prefetch_overlap",
        "ranks": n,
        "steps": args.epochs * (args.examples // (n * args.batch)),
        "serial_s": round(t_serial, 3),
        "prefetch2_s": round(t_overlap, 3),
        "overlap_speedup": round(t_serial / t_overlap, 3),
    }))


if __name__ == "__main__":
    main()
