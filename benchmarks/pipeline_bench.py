"""Pipeline schedule comparison: GPipe vs 1F1B on the 8-virtual-device mesh.

What can be measured honestly in this environment (no multi-chip TPU):

- **Activation memory** — THE 1F1B claim.  `compiled.memory_analysis()` for
  the pp=4 training step at growing microbatch counts M: GPipe's temp
  allocation grows with M (all-M activation tape), 1F1B's stays flat (its
  stash is a min(S, M)-slot ring).  This is a compiled-program property of
  the real XLA pipeline, not a simulation.
- **Bubble accounting** — both schedules have the same analytic bubble
  fraction (S-1)/(M+S-1) (non-interleaved schedules; 1F1B's win is memory,
  not bubble).  Reported per M so the table shows the bubble shrinking as
  M grows — the knob 1F1B makes affordable.
- **CPU wall clock** — informational only (8 virtual CPU devices share one
  host; not TPU-representative), flagged as such.

Run: python benchmarks/pipeline_bench.py [--micros 4 8 16 32]
Prints one JSON line.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bluefog_tpu.parallel.api import shard_map
from bluefog_tpu.parallel.pipeline import (
    pipeline_train_step_1f1b,
    pipeline_train_step_gpipe,
    stack_stage_params,
)

S = 4      # stages
D = 256    # width
L = 8      # layers
MB = 8     # microbatch size


def stage_fn(sp, x):
    def body(x, w):
        return jnp.tanh(x @ w), None
    out, _ = lax.scan(body, x, sp["w"])
    return out


def loss_fn(head, y, t):
    del head
    return jnp.sum((y - t) ** 2)


def build(step, mesh, M, **kw):
    layers = {"w": jax.random.normal(jax.random.PRNGKey(0), (L, D, D))
              / np.sqrt(D)}
    staged = stack_stage_params(layers, S)
    xs = jax.random.normal(jax.random.PRNGKey(1), (M, MB, D))
    tgt = jax.random.normal(jax.random.PRNGKey(2), (M, MB, D))

    def body(staged_local, xs):
        sp = jax.tree_util.tree_map(lambda t: t[0], staged_local)
        loss, g, _, _ = step(stage_fn, sp, xs, tgt, loss_fn,
                             pp_axis="pp", num_stages=S, **kw)
        return lax.psum(loss, "pp"), jax.tree_util.tree_map(
            lambda t: t[None], g)

    fn = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P("pp"), P()),
        out_specs=(P(), P("pp")), check_vma=False))
    staged = jax.device_put(staged, NamedSharding(mesh, P("pp")))
    xs = jax.device_put(xs, NamedSharding(mesh, P()))
    return fn, staged, xs


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--micros", type=int, nargs="+", default=[4, 8, 16, 32])
    ap.add_argument("--steps", type=int, default=3)
    args = ap.parse_args()

    devs = jax.devices()
    if len(devs) < S:
        print(json.dumps({"metric": "pipeline_gpipe_vs_1f1b",
                          "error": f"needs {S} devices, got {len(devs)}"}))
        return
    mesh = Mesh(np.array(devs[:S]), ("pp",))

    rows = []
    for M in args.micros:
        row = {"micros": M, "bubble_fraction": round((S - 1) / (M + S - 1), 4)}
        for name, step, kw in [
            ("gpipe", pipeline_train_step_gpipe, {}),
            ("gpipe_remat", pipeline_train_step_gpipe, {"remat": True}),
            ("1f1b", pipeline_train_step_1f1b, {}),
        ]:
            fn, staged, xs = build(step, mesh, M, **kw)
            compiled = fn.lower(staged, xs).compile()
            mem = compiled.memory_analysis()
            temp = getattr(mem, "temp_size_in_bytes", None)
            # None would silently read as 0.0 and vacuously "confirm" the
            # flat-memory claim — report unavailability explicitly
            row[f"{name}_temp_mib"] = (round(temp / (1 << 20), 2)
                                       if temp is not None else None)
            # wall (CPU, informational)
            out = fn(staged, xs)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(args.steps):
                out = fn(staged, xs)
            jax.block_until_ready(out)
            row[f"{name}_wall_ms"] = round(
                (time.perf_counter() - t0) / args.steps * 1e3, 1)
        rows.append(row)
        print(f"M={M}: {row}", file=sys.stderr)

    print(json.dumps({
        "metric": "pipeline_gpipe_vs_1f1b",
        "platform": devs[0].platform,
        "stages": S, "layers": L, "width": D, "micro_batch": MB,
        "rows": rows,
        "note": ("temp_mib is compiled XLA memory analysis (real pipeline "
                 "program); wall is CPU-mesh-only, not TPU-representative. "
                 "Non-interleaved schedules share the analytic bubble "
                 "(S-1)/(M+S-1); 1F1B's win is the flat activation memory "
                 "as M grows."),
    }))


if __name__ == "__main__":
    main()
