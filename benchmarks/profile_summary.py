"""Summarize a jax.profiler trace: top device-time sinks per op category.

``bench.py --profile DIR`` captures a TensorBoard-format trace
(``DIR/plugins/profile/<run>/<host>.trace.json.gz`` — Chrome trace events).
This digests it into the top-N device ops by total duration — the data behind
PROFILE.md's sink table — without needing TensorBoard.

Run:  python benchmarks/profile_summary.py /tmp/bench_profile [--top 15]
"""

import argparse
import glob
import gzip
import json
import os
import re
import sys
from collections import defaultdict


def find_trace(root):
    pats = [os.path.join(root, "plugins", "profile", "*", "*.trace.json.gz"),
            os.path.join(root, "**", "*.trace.json.gz")]
    for p in pats:
        hits = sorted(glob.glob(p, recursive=True))
        if hits:
            return hits[-1]  # latest run
    raise SystemExit(f"no *.trace.json.gz under {root}")


def device_op_totals(trace_dir):
    """Per-op device time from the latest trace under ``trace_dir``.

    Returns ``(path, by_op, total_us, n_lanes, device_events)``: the trace
    file used, total duration (µs) per base op name, their sum across ALL
    contributing lanes, the number of distinct event lanes (one "XLA Ops"
    thread per local device — a per-chip figure must divide by this), and
    whether the events actually came from a device-side lane rather than
    host threads.  ``bench.py`` uses the total as ground truth for its
    wall-clock timing (the device cannot lie about its own op durations the
    way a remote relay's clock can); this CLI uses ``by_op`` for the sink
    table.
    """
    path = find_trace(trace_dir)
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", data if isinstance(data, list) else [])

    # Select per-op device events WITHOUT double counting their enclosing
    # spans: TensorBoard traces put one "XLA Ops" thread (per-instruction
    # events) next to "XLA Modules"/"Steps" threads whose events span whole
    # compiled steps — summing a pid wholesale counts every op twice.
    pid_names, tid_names = {}, {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            pid_names[e["pid"]] = e.get("args", {}).get("name", "")
        elif e.get("name") == "thread_name":
            tid_names[(e.get("pid"), e.get("tid"))] = (
                e.get("args", {}).get("name", ""))
    op_tids = {k for k, v in tid_names.items() if re.search(r"XLA Ops", v)}
    device_pids = {pid for pid, name in pid_names.items()
                   if re.search(r"TPU|device|/device", name, re.I)}

    def selected(e):
        if op_tids:
            return (e.get("pid"), e.get("tid")) in op_tids
        tname = tid_names.get((e.get("pid"), e.get("tid")), "")
        if re.search(r"Modules|Steps", tname):
            return False  # step/module envelopes, not per-op time
        return not device_pids or e.get("pid") in device_pids

    by_op = defaultdict(float)
    total = 0.0
    lanes = set()
    for e in events:
        if e.get("ph") != "X" or "dur" not in e or not selected(e):
            continue
        name = e.get("name", "?")
        # collapse XLA's uniquifier suffixes: fusion.123 -> fusion
        base = re.sub(r"[.\d]+$", "", name) or name
        by_op[base] += e["dur"]
        total += e["dur"]
        lanes.add((e.get("pid"), e.get("tid")))

    # A lane count is only a chip count when the lanes are the labeled
    # per-device "XLA Ops" threads; in the device-pid fallback a pid's
    # extra streams (DMA etc.) would masquerade as chips and understate
    # the per-chip time — report 0 so callers refuse to divide by it.
    n_lanes = len(lanes) if op_tids else 0
    return path, by_op, total, n_lanes, bool(op_tids or device_pids)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir")
    ap.add_argument("--top", type=int, default=15)
    args = ap.parse_args()

    path, by_op, total, _lanes, device_events = device_op_totals(
        args.trace_dir)
    if not by_op:
        raise SystemExit("no device op events found in trace")
    if not device_events:
        print("WARNING: no 'XLA Ops' thread or device pid in this trace — "
              "host-side events are being summed (CPU-only capture?); "
              "capture on a TPU for a meaningful sink table", file=sys.stderr)
    print(f"trace: {path}")
    print(f"total device op time: {total / 1e3:.2f} ms "
          f"(over the captured steps)")
    print(f"{'op':40s} {'ms':>10s} {'share':>7s}")
    for op, dur in sorted(by_op.items(), key=lambda kv: -kv[1])[:args.top]:
        print(f"{op:40s} {dur / 1e3:10.2f} {dur / total:7.1%}")


if __name__ == "__main__":
    main()
