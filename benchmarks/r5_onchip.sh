#!/bin/bash
# Round-5 on-chip session: serialized, harvest-as-you-go (axon playbook).
# Run detached: setsid nohup bash benchmarks/r5_onchip.sh > /tmp/r5_onchip.log 2>&1 &
set -x
cd /root/repo
echo "=== PHASE 1: fresh bench sweep (new trace-first + rescue path) ==="
python bench.py 2>&1
echo "=== PHASE 1 done, rc=$? ==="
echo "=== PHASE 2: conv roofline, ALL shapes (one pass; --top exists for time-boxed partial harvests) ==="
python benchmarks/conv_roofline.py --batch 128 2>&1
echo "=== PHASE 2 done, rc=$? ==="
echo "=== PHASE 4: knee refinement: pinned 96 and 160 ==="
python bench.py --batch 96 2>&1
python bench.py --batch 160 2>&1
echo "=== PHASE 4 done, rc=$? ==="
echo "=== ALL DONE ==="
