"""Fleet health plane bench: publisher overhead + detection latency.

Two numbers with acceptance gates (ISSUE 12), committed as
``BENCH_fleet.json`` — which ``bffleet-tpu --check BENCH_fleet.json``
itself gates (every ``*_ok`` key must be true), making the committed
trajectory the regression baseline:

1. **Publisher overhead** — the per-publish cost of
   :class:`bluefog_tpu.fleet.TelemetryPublisher` (record assembly:
   metrics-family deltas over a realistically sized registry, blackbox
   event counts over a populated ring, ``/proc`` host sample, round
   stats, canonical JSON, one buffered append) measured in-process over
   many publishes, expressed as a fraction of the MEASURED median
   transport round of a live 3-rank tcp dsgd fleet (from the same
   run's own telemetry).  Gate: <= 1% of a round.

2. **Detection latency** — a 3-rank tcp dsgd fleet where rank 2's
   window server runs behind seeded chaos
   (``server:delay:ms=150:rate=1.0`` — a deterministic straggler, live
   from round 0).  The run's telemetry replays through the DEFAULT SLO
   set; the gates: the straggler WARN names rank 2, lands within <= 5
   rounds of injection, the ``--check`` exit is nonzero — and the
   chaos-free twin's exit is 0.  The EXACT push-sum mass audit must
   hold in every run (the publisher reads, never moves, mass).

Run: ``python benchmarks/fleet_bench.py [--steps N] [--out FILE]``
(rc=0 off-TPU; workers are pure numpy — no jax in the hot loop).
Committed results: ``BENCH_fleet.json``.
"""

import argparse
import json
import os
import shutil
import statistics
import subprocess
import sys
import tempfile
import time

N_RANKS = 3
SLOW_RANK = 2
CHAOS_SPEC = "server:delay:ms=150:rate=1.0:seed=1"
# ~50 ms rounds: decisively separated from healthy localhost ack
# latency, and the 150 ms chaos delay lands inside the first few
# rounds' EWMAs (detection measured in rounds, not EWMA warm-up)
SKEW_S = 0.05


def _worker(rank: int, barrier_dir: str, variant: str, steps: int) -> None:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    os.environ["JAX_PLATFORMS"] = "cpu"
    if variant == "chaos" and rank == SLOW_RANK:
        os.environ["BLUEFOG_TPU_CHAOS"] = CHAOS_SPEC

    import numpy as np

    from bluefog_tpu.fleet import FleetConfig
    from bluefog_tpu.runtime.async_windows import (FileBarrier,
                                                   run_async_dsgd_rank)
    from bluefog_tpu.topology import FullyConnectedGraph

    def loss_and_grad(r, step, params):
        return 0.0, {"w": np.zeros_like(np.asarray(params["w"]))}

    rep = run_async_dsgd_rank(
        FullyConnectedGraph(N_RANKS), rank,
        {"w": np.arange(64.0, dtype=np.float64)}, loss_and_grad,
        barrier=FileBarrier(barrier_dir, N_RANKS, rank),
        duration_s=90.0, skew_s=SKEW_S,
        name=f"fleet_bench_{os.path.basename(barrier_dir)}",
        transport="tcp", tcp_bind="127.0.0.1",
        stop_after_steps=steps,
        fleet=FleetConfig(every=1))
    if rank == 0:
        out = {"wall_s": rep.wall_time_s, "total_mass": rep.total_mass,
               "steps_per_rank": rep.steps_per_rank}
        print("BENCH_RESULT " + json.dumps(out), flush=True)


def _run_variant(variant: str, steps: int) -> dict:
    bdir = tempfile.mkdtemp(prefix=f"bf-fleetbench-{variant}-")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker",
         str(r), bdir, variant, str(steps)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=repo) for r in range(N_RANKS)]
    outs = []
    deadline = time.time() + 150
    for p in procs:
        try:
            out, _ = p.communicate(timeout=max(5.0,
                                               deadline - time.time()))
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise SystemExit(f"{variant} trial timed out")
        outs.append(out)
    for r, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise SystemExit(
                f"{variant} worker {r} failed (rc={p.returncode}):\n{out}")
    for line in outs[0].splitlines():
        if line.startswith("BENCH_RESULT "):
            res = json.loads(line[len("BENCH_RESULT "):])
            res["dir"] = bdir
            return res
    raise SystemExit(f"{variant} rank 0 produced no result:\n{outs[0]}")


def _measure_publish_cost(n_publishes: int = 400) -> dict:
    """In-process micro-benchmark of one publish under realistic load:
    a registry with dozens of live series, a blackbox ring carrying
    fresh events between publishes, a 2-peer phase map, and real
    ``/proc`` sampling + file append."""
    from bluefog_tpu.blackbox import recorder as bb
    from bluefog_tpu.fleet import TelemetryPublisher
    from bluefog_tpu.metrics import registry as mreg

    reg = mreg.metrics_start()
    rec = bb.configure(rank=0)
    for i in range(24):  # a realistically populated registry
        reg.counter(f"bf_bench_fam{i}_total").inc(1.0, peer="1")
        reg.counter(f"bf_bench_fam{i}_total").inc(2.0, peer="2")
        reg.gauge(f"bf_bench_g{i}").set(float(i))
    with tempfile.TemporaryDirectory() as d:
        pub = TelemetryPublisher(0, d, every=1)
        peers = {1: {"lag": 0.004, "net": 0.003, "queue": 0.0005,
                     "apply": 0.0005},
                 2: {"lag": 0.005}}
        times = []
        for i in range(n_publishes):
            # fresh per-window activity, as a live round produces
            reg.counter("bf_bench_fam0_total").inc(1.0, peer="1")
            rec.record("tcp_batch_deposit", peer=1, batch=i)
            rec.record("window_read", slot=0)
            pub.note_round(0.05)
            t0 = time.perf_counter()
            pub.publish(i, mass=0.5, z_mean=31.5, dis=0.01,
                        peers=peers)
            times.append(time.perf_counter() - t0)
        pub.close()
        size = os.path.getsize(os.path.join(d, "fleet.0"))
    mreg.metrics_stop()
    bb.reset()
    times.sort()
    return {
        "publishes": n_publishes,
        "publish_mean_s": sum(times) / len(times),
        "publish_p50_s": times[len(times) // 2],
        "publish_p99_s": times[int(len(times) * 0.99) - 1],
        "record_bytes_mean": size / n_publishes,
    }


def _round_time_from_telemetry(dirpath: str) -> float:
    """Median per-round wall time over every rank's records — the
    denominator of the overhead fraction, measured from the SAME fleet
    the publisher ran in."""
    from bluefog_tpu.fleet import FleetView

    view = FleetView.load_dir(dirpath)
    means = []
    for r in view.ranks():
        for rec in view._recs[r].values():
            if rec.round_s.get("count", 0) > 0:
                means.append(rec.round_s["mean"])
    if not means:
        raise SystemExit(f"no round stats in {dirpath}")
    return statistics.median(means)


def main(argv=None) -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        _worker(int(sys.argv[2]), sys.argv[3], sys.argv[4],
                int(sys.argv[5]))
        return 0

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=50,
                    help="step target per rank (default 50)")
    ap.add_argument("--out", default=None,
                    help="write JSON here (default: print only)")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # run as a script: sys.path has benchmarks/, not the repo root
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bluefog_tpu.fleet import SLOEngine, FleetView, default_specs
    from bluefog_tpu.fleet.dash import main as fleet_cli

    # ---- clean fleet: overhead denominator + the clean gate ----
    clean = _run_variant("clean", args.steps)
    round_s = _round_time_from_telemetry(clean["dir"])
    cost = _measure_publish_cost()
    overhead = cost["publish_mean_s"] / round_s
    clean_exit = fleet_cli(["--check", clean["dir"]])
    print(f"clean: wall={clean['wall_s']:.2f}s "
          f"mass={clean['total_mass']:.12f} round_p50={round_s*1e3:.1f}ms "
          f"publish_mean={cost['publish_mean_s']*1e6:.0f}us "
          f"overhead={overhead*100:.3f}% check_exit={clean_exit}")

    # ---- chaos fleet: detection latency + the breach gate ----
    chaos = _run_variant("chaos", args.steps)
    view = FleetView.load_dir(chaos["dir"])
    engine = SLOEngine(default_specs())
    engine.advance(view)
    warns = [t for t in engine.transitions
             if t.slo == "straggler" and t.to >= 1]
    detection_rounds = warns[0].round if warns else None
    named_rank = warns[0].rank if warns else None
    breach_exit = fleet_cli(["--check", chaos["dir"]])
    print(f"chaos: wall={chaos['wall_s']:.2f}s "
          f"mass={chaos['total_mass']:.12f} "
          f"first_warn_round={detection_rounds} named={named_rank} "
          f"check_exit={breach_exit}")

    mass_ok = all(abs(v["total_mass"] - N_RANKS) <= 1e-9 * N_RANKS
                  for v in (clean, chaos))
    result = {
        "scenario": {
            "ranks": N_RANKS, "slow_rank": SLOW_RANK,
            "chaos": CHAOS_SPEC, "skew_s": SKEW_S,
            "steps": args.steps,
            "workload": ("zero-grad push-sum averaging, d=64 f64, tcp "
                         "localhost, fleet publisher every round"),
        },
        "publisher": cost,
        "round_median_s": round_s,
        "publisher_overhead_frac": overhead,
        "overhead_target_frac": 0.01,
        "overhead_ok": overhead <= 0.01,
        "detection_first_warn_round": detection_rounds,
        "detection_target_rounds": 5,
        "detection_ok": (detection_rounds is not None
                         and detection_rounds <= 5),
        "named_rank": named_rank,
        "named_ok": named_rank == SLOW_RANK,
        "breach_check_exit": breach_exit,
        "breach_gate_ok": breach_exit != 0,
        "clean_check_exit": clean_exit,
        "clean_gate_ok": clean_exit == 0,
        "clean_run": {k: clean[k] for k in
                      ("wall_s", "total_mass", "steps_per_rank")},
        "chaos_run": {k: chaos[k] for k in
                      ("wall_s", "total_mass", "steps_per_rank")},
        "mass_exact_ok": mass_ok,
    }
    for v in (clean, chaos):
        shutil.rmtree(v.pop("dir"), ignore_errors=True)
    gates = [k for k, v in result.items()
             if k.endswith("_ok") and not v]
    print(f"\ngates: {'ALL OK' if not gates else 'FAIL ' + str(gates)}")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
        print(f"wrote {args.out}")
    return 0 if not gates else 1


if __name__ == "__main__":
    sys.exit(main())
