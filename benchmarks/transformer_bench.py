"""Transformer-LM training throughput on a single chip (tokens/sec/chip).

The second model-family perf number next to bench.py's ResNet-50 headline:
a GPT-style decoder (``models/transformer.py``) under the SAME decentralized
training step the examples use — ``DistributedNeighborAllreduceOptimizer``
over the exp2 schedule (identity gossip on one chip, real gossip on a mesh)
— with the model layer's ``backend='auto'`` attention, i.e. the tuned-tile
flash kernel on TPU (PROFILE.md §4a).

Timing discipline: device-profiler-trace oracle via
``benchmarks/_trace_util`` (the relay wall clock lies; PROFILE.md §1).
MFU uses XLA's own flop count for the compiled step when available, else
the analytic 6·N·T approximation.

Run (real chip):  python benchmarks/transformer_bench.py --seq-len 2048
Run (CPU smoke):  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python benchmarks/transformer_bench.py --config tiny --batch 2 \
    --seq-len 256 --steps 2

Prints one JSON line: tokens/sec/chip, per-step times, MFU.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from jax.sharding import PartitionSpec as P

import bluefog_tpu as bf
from benchmarks._trace_util import timed_trace
from bluefog_tpu.models import GPTConfig, TransformerLM
from bluefog_tpu.optim import DistributedNeighborAllreduceOptimizer
from bluefog_tpu.parallel.api import shard_map
from bluefog_tpu.topology import ExponentialTwoGraph

NOMINAL_TFLOPS = {"TPU v5 lite": 197.0, "TPU v5p": 459.0, "TPU v4": 275.0,
                  "TPU v6 lite": 918.0}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", choices=["lm", "vit", "bert", "moe", "moe2"],
                    default="lm",
                    help="lm = GPT decoder (tokens/s); vit = ViT classifier "
                         "(images/s); bert = encoder fine-tune step "
                         "(BASELINE config[4] flavor); moe = Switch-MoE "
                         "decoder (top-1 routing); moe2 = GShard top-2 "
                         "routing under the same step")
    ap.add_argument("--config", choices=["tiny", "small", "large", "base"],
                    default="small",
                    help="GPTConfig preset for lm/moe; ViTConfig for vit "
                         "(tiny/base); BertConfig for bert (tiny/base/large)")
    ap.add_argument("--num-experts", type=int, default=None,
                    help="moe only (default: 8, or tiny preset's 4)")
    ap.add_argument("--batch", type=int, default=8, help="per-chip batch")
    ap.add_argument("--seq-len", type=int, default=2048,
                    help="lm only; vit token count is set by image/patch")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--remat", action="store_true",
                    help="rematerialize blocks (long sequences)")
    args = ap.parse_args()

    valid_configs = {"lm": ("tiny", "small", "large"),
                     "vit": ("tiny", "base"),
                     "bert": ("tiny", "base", "large"),
                     "moe": ("tiny", "small", "large"),
                     "moe2": ("tiny", "small", "large")}[args.model]
    if args.config not in valid_configs:
        raise SystemExit(
            f"--model {args.model} has no '{args.config}' preset; "
            f"choose from {valid_configs}")

    devices = jax.devices()
    n = len(devices)
    bf.init(topology=ExponentialTwoGraph(n))
    ctx = bf.get_context()

    import dataclasses

    if args.model == "vit":
        from bluefog_tpu.models import ViT, ViTConfig

        vcfg = getattr(ViTConfig, args.config)()
        if args.remat:
            vcfg = dataclasses.replace(vcfg, remat=True)
        cfg = vcfg.trunk()  # dtype/report fields
        model = ViT(vcfg)
        rng_in = jnp.zeros((args.batch, vcfg.image_size, vcfg.image_size, 3),
                           jnp.bfloat16)
        data = (
            jax.random.normal(jax.random.PRNGKey(1),
                              (n, args.batch, vcfg.image_size,
                               vcfg.image_size, 3)).astype(jnp.bfloat16),
            jax.random.randint(jax.random.PRNGKey(2), (n, args.batch), 0,
                               vcfg.num_classes, dtype=jnp.int32))
        unit, per_step_items = "images/sec/chip", args.batch
        # transformer token positions per step, for the analytic fallback
        fallback_tokens = args.batch * (
            (vcfg.image_size // vcfg.patch_size) ** 2 + 1)
        metric = "vit_images_per_sec_per_chip"
    elif args.model == "bert":
        from bluefog_tpu.models import BertConfig, BertEncoder

        bcfg = getattr(BertConfig, args.config)()
        if args.remat:
            bcfg = dataclasses.replace(bcfg, remat=True)
        cfg = bcfg  # report fields (dtype)
        seq = min(args.seq_len, bcfg.max_position)
        model = BertEncoder(bcfg, num_classes=2)  # fine-tune head
        rng_in = jnp.zeros((args.batch, seq), jnp.int32)
        data = (
            jax.random.randint(jax.random.PRNGKey(1), (n, args.batch, seq),
                               0, bcfg.vocab_size, dtype=jnp.int32),
            jax.random.randint(jax.random.PRNGKey(2), (n, args.batch), 0, 2,
                               dtype=jnp.int32))
        unit, per_step_items = "tokens/sec/chip", args.batch * seq
        fallback_tokens = args.batch * seq  # the CAPPED seq, not --seq-len
        metric = "bert_finetune_tokens_per_sec_per_chip"
    elif args.model in ("moe", "moe2"):
        from bluefog_tpu.models import MoEConfig, MoETransformerLM

        if args.config == "tiny":
            mcfg = MoEConfig.tiny()
        else:
            gpt = getattr(GPTConfig, args.config)()
            mcfg = MoEConfig(gpt=gpt)
        # every flag applies in every branch — the report must never claim
        # a remat'd / N-expert run that did not happen
        if args.remat:
            mcfg = dataclasses.replace(
                mcfg, gpt=dataclasses.replace(mcfg.gpt, remat=True))
        if args.num_experts is not None:
            mcfg = dataclasses.replace(mcfg, num_experts=args.num_experts)
        elif args.config != "tiny":
            mcfg = dataclasses.replace(mcfg, num_experts=8)
        if args.model == "moe2":
            mcfg = dataclasses.replace(mcfg, router="top2")
        cfg = mcfg.gpt
        model = MoETransformerLM(mcfg)
        moe_aux_weight = mcfg.aux_loss_weight
        rng_in = jnp.zeros((args.batch, args.seq_len), jnp.int32)
        data = (jax.random.randint(
            jax.random.PRNGKey(1), (n, args.batch, args.seq_len + 1), 0,
            cfg.vocab_size, dtype=jnp.int32),)
        unit, per_step_items = "tokens/sec/chip", args.batch * args.seq_len
        # 6*N*T over ALL params would count every expert as active though
        # top-1 routing executes one -- no honest analytic fallback exists
        fallback_tokens = None
        metric = f"{args.model}_lm_tokens_per_sec_per_chip"
    else:
        cfg = getattr(GPTConfig, args.config)()
        if args.remat:
            cfg = dataclasses.replace(cfg, remat=True)
        model = TransformerLM(cfg)
        rng_in = jnp.zeros((args.batch, args.seq_len), jnp.int32)
        data = (jax.random.randint(
            jax.random.PRNGKey(1), (n, args.batch, args.seq_len + 1), 0,
            cfg.vocab_size, dtype=jnp.int32),)
        unit, per_step_items = "tokens/sec/chip", args.batch * args.seq_len
        fallback_tokens = args.batch * args.seq_len
        metric = "transformer_lm_tokens_per_sec_per_chip"

    opt = DistributedNeighborAllreduceOptimizer(
        optax.adamw(3e-4, weight_decay=0.01), topology=ctx.schedule,
        axis_name=ctx.axis_name)

    rng = jax.random.PRNGKey(0)
    params = model.init(rng, rng_in)["params"]
    params = bf.rank_shard(bf.rank_stack(params))
    data = tuple(bf.rank_shard(d) for d in data)

    def init_opt(params_blk):
        p = jax.tree_util.tree_map(lambda t: t[0], params_blk)
        st = opt.init(p)
        return jax.tree_util.tree_map(lambda t: jnp.asarray(t)[None], st)

    opt_state = jax.jit(shard_map(
        init_opt, mesh=ctx.mesh, in_specs=(P(ctx.axis_name),),
        out_specs=P(ctx.axis_name), check_vma=False))(params)

    def train_step(params_blk, opt_blk, *data_blks):
        p, st = jax.tree_util.tree_map(lambda t: t[0], (params_blk, opt_blk))
        vals = [d[0] for d in data_blks]

        def loss_fn(p):
            if args.model == "vit":
                imgs, labels = vals
                logits = model.apply({"params": p}, imgs, train=True)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits.astype(jnp.float32), labels).mean()
            if args.model == "bert":
                tok, labels = vals
                logits = model.apply({"params": p}, tok, deterministic=True)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits.astype(jnp.float32), labels).mean()
            (tok,) = vals
            inp, tgt = tok[:, :-1], tok[:, 1:]
            if args.model in ("moe", "moe2"):
                logits, st_aux = model.apply({"params": p}, inp,
                                             mutable=["aux_loss"])
                ce = optax.softmax_cross_entropy_with_integer_labels(
                    logits.astype(jnp.float32), tgt).mean()
                aux = sum(jnp.sum(a) for a in
                          jax.tree_util.tree_leaves(st_aux["aux_loss"]))
                return ce + moe_aux_weight * aux
            logits = model.apply({"params": p}, inp)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), tgt).mean()

        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, st = opt.update(grads, st, p)
        p = optax.apply_updates(p, updates)
        return (jax.tree_util.tree_map(lambda t: t[None], (p, st))
                + (loss[None],))

    # AOT-compile once; the executable serves cost analysis + the timed loop
    step_fn = jax.jit(shard_map(
        train_step, mesh=ctx.mesh,
        in_specs=(P(ctx.axis_name),) * (2 + len(data)),
        out_specs=(P(ctx.axis_name),) * 3, check_vma=False,
    ), donate_argnums=(0, 1)).lower(params, opt_state, *data).compile()

    try:
        flops_per_step = float(step_fn.cost_analysis()["flops"])
        flops_source = "xla_cost_analysis"
    except Exception:  # noqa: BLE001 — platform-dependent availability
        if fallback_tokens is None:
            flops_per_step, flops_source = 0.0, "unavailable"
        else:
            n_params = sum(int(np.prod(x.shape))
                           for x in jax.tree_util.tree_leaves(params)) / n
            flops_per_step = 6.0 * n_params * fallback_tokens
            flops_source = "analytic_6NT"

    state = {"p": params, "o": opt_state}

    def step(*data_):
        state["p"], state["o"], loss = step_fn(state["p"], state["o"],
                                               *data_)
        return loss

    wall_ms, trace_ms = timed_trace(step, data, args.steps)
    headline_ms = trace_ms or wall_ms
    tps = per_step_items / (headline_ms / 1e3)
    achieved = flops_per_step / (headline_ms / 1e3)
    kind = getattr(devices[0], "device_kind", str(devices[0]))
    spec = NOMINAL_TFLOPS.get(kind)

    # dropped-token accounting (moe/moe2): one untimed forward with the
    # metrics collection mutable; reported so a capacity_factor that
    # silently drops tokens is visible in every bench row
    moe_metrics = None
    if args.model in ("moe", "moe2"):
        p0 = jax.tree_util.tree_map(lambda t: t[0], state["p"])
        tok0 = np.asarray(data[0])[0, 0][None]
        _, mstate = model.apply({"params": p0}, jnp.asarray(tok0[:, :-1]),
                                mutable=["aux_loss", "moe_metrics"])
        flat = jax.tree_util.tree_flatten_with_path(mstate["moe_metrics"])[0]
        # exact key segment: 'dropped_frac' is a substring of
        # 'fully_dropped_frac', so match the quoted dict key
        pick = lambda key: [float(jnp.mean(v)) for path, v in flat
                            if f"'{key}'" in jax.tree_util.keystr(path)]
        moe_metrics = {
            "router": mcfg.router,
            "dropped_frac": round(float(np.mean(pick("dropped_frac"))), 4),
            "fully_dropped_frac": round(
                float(np.mean(pick("fully_dropped_frac"))), 4),
            "capacity_factor": mcfg.capacity_factor,
        }

    out = {
        "metric": metric,
        "value": round(tps, 1),
        "unit": unit,
        "model": args.model,
        "config": args.config, "batch": args.batch,
        "seq_len": (None if args.model == "vit"
                    else min(args.seq_len, cfg.max_position)
                    if args.model == "bert" else args.seq_len),
        "remat": bool(args.remat), "dtype": str(cfg.dtype.__name__ if
                                                hasattr(cfg.dtype, "__name__")
                                                else cfg.dtype),
        "wall_ms_per_step": round(wall_ms, 3),
        "trace_ms_per_step": round(trace_ms, 3) if trace_ms else None,
        "timing_source": "profiler_trace" if trace_ms else
                         "wall_clock_uncorroborated",
        "wall_plausible": (wall_ms >= 0.9 * trace_ms) if trace_ms else None,
        "model_tflops_per_sec_per_chip": (round(achieved / 1e12, 2)
                                          if flops_per_step > 0 else None),
        "flops_source": flops_source,
        "device_kind": kind,
        "mfu_vs_nominal": (round(achieved / 1e12 / spec, 4)
                           if spec and flops_per_step > 0 else None),
        "moe": moe_metrics,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
