"""Per-op conv roofline: is each dominant ResNet-50 convolution at ITS bound?

Round-4 verdict weak #1: the ResNet-50 step measures 31 % MFU while the
whole-model roofline (all FLOPs at nominal matmul peak) says ~3x headroom —
a claim that needs per-op evidence, because "all conv FLOPs at matmul peak"
is not attainable for real conv shapes (low channel counts under-fill the
128-lane MXU; strided/spatial tiling costs the systolic array turns a pure
GEMM never pays).

Method, per dominant conv shape of ResNet-50/224 (each unique (HxW, Cin,
Cout, k, stride) with its per-network multiplicity):

- time the convolution standalone (jitted scan loop, device-trace
  corroborated — the relay wall clock is unusable at this scale);
- time its **im2col GEMM twin** — a single ``(M, K) @ (K, N)`` with
  ``M = B*Ho*Wo, K = kh*kw*Cin, N = Cout``, i.e. the same MAC count on the
  same chip.  The twin's rate is the *empirically attainable* ceiling for
  that shape: if conv time ~= twin time, the conv is at its shape's bound
  and no layout/scheduling fix can buy more without changing the model;
- compute the analytic bounds: flops / nominal-peak and min-bytes / HBM-BW.

Aggregate: sum over shapes of (multiplicity x twin time) = the best step
time any scheduler could reach if every conv hit its GEMM-twin rate; the
implied "attainable MFU" is the honest ceiling to compare 31 % against.
Forward convs only (the backward convs are GEMM-twins of the same K/M/N up
to transposition — stated, not measured).

Run (real chip):  python benchmarks/conv_roofline.py [--batch 128]
Prints one JSON line; rows carry wall+trace ms and a bound verdict.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from benchmarks._trace_util import timed_trace

# ResNet-50/224 conv inventory: (label, H, W, Cin, Cout, k, stride, count).
# Counts are per forward pass (bottleneck expansions included; projection
# convs folded into their stage rows).
RESNET50_CONVS = [
    ("stem 7x7/2", 224, 224, 3, 64, 7, 2, 1),
    ("s1 1x1 64>64", 56, 56, 64, 64, 1, 1, 1),
    ("s1 3x3 64", 56, 56, 64, 64, 3, 1, 3),
    ("s1 1x1 64>256", 56, 56, 64, 256, 1, 1, 3),
    ("s1 1x1 256>64", 56, 56, 256, 64, 1, 1, 2),
    ("s1 proj 256", 56, 56, 64, 256, 1, 1, 1),
    ("s2 1x1 256>128", 56, 56, 256, 128, 1, 1, 1),
    ("s2 3x3/2 128", 56, 56, 128, 128, 3, 2, 1),
    ("s2 3x3 128", 28, 28, 128, 128, 3, 1, 3),
    ("s2 1x1 128>512", 28, 28, 128, 512, 1, 1, 4),
    ("s2 1x1 512>128", 28, 28, 512, 128, 1, 1, 3),
    ("s2 proj 512/2", 56, 56, 256, 512, 1, 2, 1),
    ("s3 1x1 512>256", 28, 28, 512, 256, 1, 1, 1),
    ("s3 3x3/2 256", 28, 28, 256, 256, 3, 2, 1),
    ("s3 3x3 256", 14, 14, 256, 256, 3, 1, 5),
    ("s3 1x1 256>1024", 14, 14, 256, 1024, 1, 1, 6),
    ("s3 1x1 1024>256", 14, 14, 1024, 256, 1, 1, 5),
    ("s3 proj 1024/2", 28, 28, 512, 1024, 1, 2, 1),
    ("s4 1x1 1024>512", 14, 14, 1024, 512, 1, 1, 1),
    ("s4 3x3/2 512", 14, 14, 512, 512, 3, 2, 1),
    ("s4 3x3 512", 7, 7, 512, 512, 3, 1, 2),
    ("s4 1x1 512>2048", 7, 7, 512, 2048, 1, 1, 3),
    ("s4 1x1 2048>512", 7, 7, 512, 2048, 1, 1, 0),  # transpose of above
    ("s4 1x1 2048>512b", 7, 7, 2048, 512, 1, 1, 2),
    ("s4 proj 2048/2", 14, 14, 1024, 2048, 1, 2, 1),
]

NOMINAL_TFLOPS = 197.0  # v5e bf16
HBM_GBPS = 819.0        # v5e


def conv_fn(B, H, W, Cin, Cout, k, s):
    pad = "SAME" if k > 1 else "VALID"

    def f(x, w):
        def body(acc, _):
            # the carry perturbs the WEIGHTS so the conv is NOT
            # loop-invariant (XLA would hoist an invariant conv out of the
            # while loop and the 8 "repeats" would time one execution);
            # weights are the smallest operand, and the GEMM twin perturbs
            # its same-sized B matrix — symmetric overhead
            ww = (w.astype(jnp.float32) * (1.0 + acc * 1e-30)).astype(w.dtype)
            y = lax.conv_general_dilated(
                x, ww, (s, s), pad,
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                preferred_element_type=jnp.float32)
            # consume EVERY output element: slicing one element would let
            # XLA push the slice through the conv and compute a single
            # output position (measured: 70x non-physical rates)
            return acc + jnp.sum(y), None

        acc, _ = lax.scan(body, jnp.float32(0), None, length=REPEATS)
        return acc

    return f


def gemm_fn(M, K, N):
    def f(a, b):
        def body(acc, _):
            bb = (b.astype(jnp.float32) * (1.0 + acc * 1e-30)).astype(b.dtype)
            y = jnp.dot(a, bb, preferred_element_type=jnp.float32)
            return acc + jnp.sum(y), None  # full consumption — see conv_fn

        acc, _ = lax.scan(body, jnp.float32(0), None, length=REPEATS)
        return acc

    return f


REPEATS = 8  # convs per jitted call: amortizes per-call dispatch


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--top", type=int, default=0,
                    help="only the N most FLOP-heavy shapes (0 = all)")
    args = ap.parse_args()
    B = args.batch

    shapes = [r for r in RESNET50_CONVS if r[7] > 0]
    if args.top:
        shapes = sorted(
            shapes, key=lambda r: -(r[1] * r[2] * r[3] * r[4] * r[5] ** 2
                                    / r[6] ** 2 * r[7]))[:args.top]

    key = jax.random.PRNGKey(0)
    rows, twin_total_ms, conv_total_ms = [], 0.0, 0.0
    for (label, H, W, Cin, Cout, k, s, count) in shapes:
        Ho, Wo = H // s, W // s
        M, K, N = B * Ho * Wo, k * k * Cin, Cout
        flops = 2.0 * M * K * N
        # TRUE lower bound on HBM traffic: bf16 input + weights only.  The
        # output is deliberately excluded — the timed kernel's jnp.sum
        # consumer fuses into the conv epilogue, so the f32 output need
        # never materialize in HBM; counting it would overstate the floor
        # (and in-model the next layer often fuses the same way).
        bytes_min = 2.0 * (B * H * W * Cin + k * k * Cin * Cout)

        x = jax.random.normal(key, (B, H, W, Cin), jnp.bfloat16)
        w = jax.random.normal(key, (k, k, Cin, Cout), jnp.bfloat16)
        cfn = jax.jit(conv_fn(B, H, W, Cin, Cout, k, s))
        c_wall, c_trace = timed_trace(cfn, (x, w), args.steps)

        a = jax.random.normal(key, (M, K), jnp.bfloat16)
        b = jax.random.normal(key, (K, N), jnp.bfloat16)
        gfn = jax.jit(gemm_fn(M, K, N))
        g_wall, g_trace = timed_trace(gfn, (a, b), args.steps)

        # the conv/twin ratio is only meaningful same-source: comparing a
        # device trace against the relay's wall clock would be cross-source
        # garbage, so fall back to wall for BOTH when either trace is
        # missing (the row is then flagged uncorroborated)
        both_traced = c_trace is not None and g_trace is not None
        c_ms = (c_trace if both_traced else c_wall) / REPEATS
        g_ms = (g_trace if both_traced else g_wall) / REPEATS

        t_peak_ms = flops / (NOMINAL_TFLOPS * 1e12) * 1e3
        t_bw_ms = bytes_min / (HBM_GBPS * 1e9) * 1e3
        ratio = c_ms / g_ms if g_ms > 0 else float("inf")
        bound = ("matmul_equivalent" if ratio <= 1.15 else
                 "bandwidth" if c_ms <= 1.25 * t_bw_ms else
                 "headroom")
        rows.append({
            "label": label, "count": count,
            "conv_ms": round(c_ms, 4), "gemm_twin_ms": round(g_ms, 4),
            "conv_vs_twin": round(ratio, 3),
            "tflops_conv": round(flops / (c_ms * 1e-3) / 1e12, 1),
            "tflops_twin": round(flops / (g_ms * 1e-3) / 1e12, 1),
            "t_nominal_peak_ms": round(t_peak_ms, 4),
            "t_bandwidth_ms": round(t_bw_ms, 4),
            "bound": bound,
            "timing_source": ("profiler_trace" if both_traced
                              else "wall_clock_uncorroborated"),
        })
        conv_total_ms += count * c_ms
        twin_total_ms += count * g_ms
        print(f"{label:>18s}: conv {c_ms:7.3f} ms vs twin {g_ms:7.3f} ms "
              f"({rows[-1]['tflops_conv']:6.1f} vs "
              f"{rows[-1]['tflops_twin']:6.1f} TF/s) -> {bound}",
            file=sys.stderr)

    fwd_flops = sum(2.0 * B * (H // s) * (W // s) * k * k * Cin * Cout * c
                    for (_, H, W, Cin, Cout, k, s, c) in shapes)
    out = {
        "metric": "resnet50_conv_roofline",
        "batch": B,
        "rows": rows,
        "fwd_conv_ms_measured": round(conv_total_ms, 2),
        "fwd_conv_ms_twin_bound": round(twin_total_ms, 2),
        "fwd_conv_tflops_measured": round(
            fwd_flops / (conv_total_ms * 1e-3) / 1e12, 1),
        "fwd_conv_tflops_twin_bound": round(
            fwd_flops / (twin_total_ms * 1e-3) / 1e12, 1),
        "attainable_mfu_vs_nominal": round(
            fwd_flops / (twin_total_ms * 1e-3) / 1e12 / NOMINAL_TFLOPS, 4),
        "note": ("twin = im2col GEMM with identical MAC count; its rate is "
                 "the empirically attainable per-shape ceiling.  Forward "
                 "convs only; backward convs are GEMM-twins of the same "
                 "M/K/N up to transposition."),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
