"""Continuous-profiling overhead + attribution benchmark.

Three questions, all acceptance-gated (ISSUE 18):

1. **What does an armed sampler cost the host path?**  On the PR 4
   transport bench shape (ResNet-50-sized leaf mixture, pipelined
   batched deposits into a remote process's window server), measure
   per-round latency with the profiler OFF and ON (97 Hz, the shipping
   default), interleaved A/B so machine drift is fair to both.  Gate:
   enabled p50 overhead ≤ 1%.

2. **Is the disabled path exactly free?**  Not "cheap": ZERO.  No
   ``bf-prof-sampler`` thread exists, and arming then disarming the
   profiler leaves freshly-jitted HLO byte-identical (the profiler
   must never hook compilation).  Gate: both hold.

3. **Do samples attribute?**  Run the fleet digital twin
   (``FleetSim``, 64 simulated ranks) under the profiler: the sim's
   rounds execute inside ``sim``-source phase spans, so the merged
   profile must attribute ≥ 60% of samples to real phases and its top
   frames must name the simulator's event core (``core.py`` /
   ``fleet.py``) — the bfsim hot path as measured evidence.

Run:  python benchmarks/profiling_bench.py [--small]
Prints one JSON line (committed as BENCH_profiling.json at the repo
root).  rc=0 when every gate holds, rc=1 otherwise.
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

_RESNET50_LEAVES = ([2048 * 1024, 1024 * 1024 * 2, 2359296, 2359296,
                     1179648, 1179648, 589824, 589824, 262144, 262144]
                    + [65536] * 40 + [2048] * 60 + [512] * 50)
_SMALL_LEAVES = [65536] * 4 + [2048] * 8

_OWNER_CODE = """
import os, sys
os.environ.setdefault('JAX_PLATFORMS', 'cpu')
os.environ['PALLAS_AXON_POOL_IPS'] = ''
os.environ.pop('BLUEFOG_TPU_PROFILE', None)  # the owner is unprofiled
import numpy as np
sys.path.insert(0, {repo!r})
from bluefog_tpu.runtime.async_windows import AsyncWindow
from bluefog_tpu.runtime.window_server import WindowServer
sizes = {sizes!r}
wins = [AsyncWindow(f'prb:{{i}}', 1, n, np.float32)
        for i, n in enumerate(sizes)]
srv = WindowServer()
_, port = srv.start('127.0.0.1')
print(f'PORT {{port}}', flush=True)
sys.stdin.readline()
srv.stop()
for w in wins:
    w.free()
print('OWNER_OK', flush=True)
"""


def _percentile(xs, q):
    if not xs:
        return float("nan")
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return xs[i]


# ---------------------------------------------------------------------------
# leg 1 — enabled overhead on the transport round
# ---------------------------------------------------------------------------


def _run_rounds(port, sizes, payloads, rounds, profiled, prof_dir):
    """One client pass: per-round deposit-all-leaves + flush fence,
    returns per-round wall latencies.  ``profiled`` arms the 97 Hz
    sampler for the pass (it samples the main thread, the stream's
    sender thread, and the ack reader — the real enabled cost)."""
    from bluefog_tpu.profiling import sampler as ps
    from bluefog_tpu.runtime.window_server import (DepositStream,
                                                   PipelinedRemoteWindow)

    if profiled:
        ps.configure(prof_dir, rank=0, hz=97.0)
    stream = DepositStream(("127.0.0.1", port), 30.0,
                           max_in_flight=4, max_queue_items=1024,
                           max_batch_bytes=16 << 20)
    rws = [PipelinedRemoteWindow(("127.0.0.1", port), f"prb:{i}",
                                 stream=stream)
           for i in range(len(sizes))]
    for rw, p in zip(rws, payloads):  # warmup
        rw.deposit_async(0, p, accumulate=True)
    stream.flush()
    lat = []
    for _ in range(rounds):
        r0 = time.perf_counter()
        for rw, p in zip(rws, payloads):
            rw.deposit_async(0, p, accumulate=True)
        stream.flush()
        lat.append(time.perf_counter() - r0)
    for rw in rws:
        rw.close()
    if profiled:
        ps.reset()
    return lat


def bench_overhead(sizes, rounds, trials):
    payloads = [np.ones(n, np.float32) for n in sizes]
    owner = subprocess.Popen(
        [sys.executable, "-c",
         _OWNER_CODE.format(repo=os.path.join(os.path.dirname(
             os.path.abspath(__file__)), ".."), sizes=list(sizes))],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
    line = owner.stdout.readline().split()
    assert line[0] == "PORT", line
    port = int(line[1])
    lat = {"off": [], "on": []}
    try:
        with tempfile.TemporaryDirectory(prefix="bf-prof-bench-") as td:
            for _ in range(trials):  # interleaved A/B: fair to drift
                lat["off"] += _run_rounds(port, sizes, payloads, rounds,
                                          False, td)
                lat["on"] += _run_rounds(port, sizes, payloads, rounds,
                                         True, td)
    finally:
        owner.stdin.write("\n")
        owner.stdin.flush()
        owner.wait(timeout=30)
    dense_mb = sum(s * 4 for s in sizes) / 1e6

    def stats(xs):
        p50 = _percentile(xs, 0.50)
        return {"round_p50_ms": round(p50 * 1e3, 3),
                "round_p99_ms": round(_percentile(xs, 0.99) * 1e3, 3),
                "MBps": round(dense_mb / 1e0 / p50, 1),
                "rounds": len(xs)}

    off, on = stats(lat["off"]), stats(lat["on"])
    frac = on["round_p50_ms"] / off["round_p50_ms"] - 1.0
    return {
        "variants": {"profiled_off": off, "profiled_on": on},
        "enabled_overhead_frac": round(frac, 4),
        "dense_mb_per_round": round(dense_mb, 1),
        "hz": 97.0,
        "overhead_ok": frac <= 0.01,
    }


# ---------------------------------------------------------------------------
# leg 2 — the disabled path is exactly zero
# ---------------------------------------------------------------------------


def bench_disabled():
    import jax
    import jax.numpy as jnp
    from bluefog_tpu.profiling import sampler as ps

    name = ps.Profiler.THREAD_NAME
    no_thread_before = not any(t.name == name
                               for t in threading.enumerate())

    @jax.jit
    def fn(x):
        return (x * 2.0 + 1.0).sum()

    x = jnp.arange(64.0)
    hlo_off = fn.lower(x).compile().as_text()
    with tempfile.TemporaryDirectory(prefix="bf-prof-bench-") as td:
        ps.configure(td, rank=0, hz=97.0)
        thread_when_armed = any(t.name == name
                                for t in threading.enumerate())
        hlo_on = fn.lower(x).compile().as_text()
        ps.reset()
    no_thread_after = not any(t.name == name
                              for t in threading.enumerate())
    hlo_identical = hlo_on == hlo_off
    return {
        "sampler_thread_absent_when_disabled": (no_thread_before
                                                and no_thread_after),
        "sampler_thread_present_when_armed": thread_when_armed,
        "hlo_byte_identical": hlo_identical,
        "disabled_ok": (no_thread_before and no_thread_after
                        and thread_when_armed and hlo_identical),
    }


# ---------------------------------------------------------------------------
# leg 3 — phase attribution on the fleet digital twin
# ---------------------------------------------------------------------------


def bench_sim(n_ranks, horizon_s):
    from bluefog_tpu.profiling import report as pr
    from bluefog_tpu.profiling import sampler as ps
    from bluefog_tpu.sim.fleet import FleetSim, SimConfig

    with tempfile.TemporaryDirectory(prefix="bf-prof-bench-") as td:
        ps.configure(td, rank=0, hz=400.0)
        t0 = time.perf_counter()
        sim = FleetSim(SimConfig(n_ranks=n_ranks, seed=3))
        sim.run(horizon_s)
        wall = time.perf_counter() - t0
        ps.reset()
        rep = pr.merge(td)
    top = pr.top_table(rep, n=8)
    core_named = any(("core.py:" in fr or "fleet.py:" in fr)
                     for fr, _, _ in top)
    attributed = rep["attributed_frac"]
    return {
        "sim_ranks": n_ranks,
        "sim_horizon_s": horizon_s,
        "sim_wall_s": round(wall, 2),
        "samples": rep["samples"],
        "phase_frac": rep["phase_frac"],
        "attributed_frac": round(attributed, 4),
        "top_frames": [[fr, n] for fr, n, _ in top],
        "sim_attrib_ok": (attributed >= 0.60 and core_named
                          and rep["samples"] >= 200),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true",
                    help="small leaf set + short sim (CI smoke)")
    ap.add_argument("--rounds", type=int, default=8)
    ap.add_argument("--trials", type=int, default=4)
    args = ap.parse_args()

    sizes = _SMALL_LEAVES if args.small else _RESNET50_LEAVES
    overhead = bench_overhead(sizes, args.rounds, args.trials)
    disabled = bench_disabled()
    sim = bench_sim(n_ranks=16 if args.small else 64,
                    horizon_s=10.0 if args.small else 60.0)

    ok = (overhead["overhead_ok"] and disabled["disabled_ok"]
          and sim["sim_attrib_ok"])
    report = {
        "metric": "profiling_overhead_and_attribution",
        "tree": "small" if args.small else "resnet50",
        "leaves": len(sizes),
        "params": int(sum(sizes)),
        **overhead,
        **disabled,
        **sim,
    }
    print(json.dumps(report))
    return 0 if ok else 1


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    sys.exit(main())
