"""Causal-tracing overhead + critical-path-accuracy benchmark.

Two questions, both acceptance-gated (ISSUE 11):

1. **What does tracing cost the host path?**  On the PR 4 transport
   bench shape (ResNet-50-sized leaf mixture, pipelined batched
   deposits into a remote process's window server), measure per-round
   latency with tracing DISABLED (the shipping default: one env read +
   a None test per hook) and ENABLED (spans buffered + the wire trace
   header + extended acks).  The disabled path's budget is < 2%: the
   bench measures the per-hook disabled cost directly and bounds its
   share of a round, because a same-process A/B of "hooks present,
   disabled" vs "hooks absent" would require checking out the previous
   commit.  The enabled-path ratio is reported for context (tracing is
   opt-in; it has no budget, only honesty).

2. **Does the analyzer name the right edge?**  Against constructed
   ground truths — ring fleets with one KNOWN slow edge injected at a
   random position, server-side phases attached — ``critical_path``
   must name the injected edge in every case (accuracy 1.0), with the
   gating-time selector (a chatty fast edge must not outrank the slow
   edge rounds actually waited on).

Run:  python benchmarks/tracing_bench.py [--small]
Prints one JSON line (committed as BENCH_tracing.json at the repo
root).  No TPU, no jax required; rc=0 on any host, rc=1 when a gate
fails.
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

_RESNET50_LEAVES = ([2048 * 1024, 1024 * 1024 * 2, 2359296, 2359296,
                     1179648, 1179648, 589824, 589824, 262144, 262144]
                    + [65536] * 40 + [2048] * 60 + [512] * 50)
_SMALL_LEAVES = [65536] * 4 + [2048] * 8

_OWNER_CODE = """
import os, sys
os.environ.setdefault('JAX_PLATFORMS', 'cpu')
os.environ['PALLAS_AXON_POOL_IPS'] = ''
os.environ.pop('BLUEFOG_TPU_TRACE', None)  # the owner is untraced
import numpy as np
sys.path.insert(0, {repo!r})
from bluefog_tpu.runtime.async_windows import AsyncWindow
from bluefog_tpu.runtime.window_server import WindowServer
sizes = {sizes!r}
wins = [AsyncWindow(f'trb:{{i}}', 1, n, np.float32)
        for i, n in enumerate(sizes)]
srv = WindowServer()
_, port = srv.start('127.0.0.1')
print(f'PORT {{port}}', flush=True)
sys.stdin.readline()
srv.stop()
for w in wins:
    w.free()
print('OWNER_OK', flush=True)
"""


def _percentile(xs, q):
    if not xs:
        return float("nan")
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return xs[i]


# ---------------------------------------------------------------------------
# overhead leg
# ---------------------------------------------------------------------------


def _run_rounds(port, sizes, payloads, rounds, traced, trace_dir):
    """One client pass: per-round deposit-all-leaves + flush fence,
    returns per-round wall latencies.  ``traced`` arms the process
    recorder BEFORE the stream is built (FEATURE_TRACE is a
    construction-time decision)."""
    from bluefog_tpu.runtime.window_server import (DepositStream,
                                                   PipelinedRemoteWindow)
    from bluefog_tpu.tracing import recorder as trc

    if traced:
        trc.configure(trace_dir, rank=0, job="tracing_bench")
    else:
        trc.reset()
    stream = DepositStream(("127.0.0.1", port), 30.0,
                           max_in_flight=4, max_queue_items=1024,
                           max_batch_bytes=16 << 20)
    rws = [PipelinedRemoteWindow(("127.0.0.1", port), f"trb:{i}",
                                 stream=stream)
           for i in range(len(sizes))]
    assert stream._trace_on == traced
    for rw, p in zip(rws, payloads):  # warmup
        rw.deposit_async(0, p, accumulate=True)
    stream.flush()
    lat = []
    for k in range(rounds):
        r0 = time.perf_counter()
        if traced:
            with trc.span("round", "dsgd", round_=k):
                for rw, p in zip(rws, payloads):
                    rw.deposit_async(0, p, accumulate=True)
                stream.flush()
        else:
            for rw, p in zip(rws, payloads):
                rw.deposit_async(0, p, accumulate=True)
            stream.flush()
        lat.append(time.perf_counter() - r0)
    for rw in rws:
        rw.close()
    if traced:
        trc.flush()
        trc.reset()
    return lat


def bench_overhead(sizes, rounds, trials):
    payloads = [np.ones(n, np.float32) for n in sizes]
    owner = subprocess.Popen(
        [sys.executable, "-c",
         _OWNER_CODE.format(repo=os.path.join(os.path.dirname(
             os.path.abspath(__file__)), ".."), sizes=list(sizes))],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
    line = owner.stdout.readline().split()
    assert line[0] == "PORT", line
    port = int(line[1])
    lat = {"off": [], "on": []}
    try:
        with tempfile.TemporaryDirectory(prefix="bf-trace-bench-") as td:
            for _ in range(trials):  # interleaved A/B: fair to drift
                lat["off"] += _run_rounds(port, sizes, payloads, rounds,
                                          False, td)
                lat["on"] += _run_rounds(port, sizes, payloads, rounds,
                                         True, td)
    finally:
        owner.stdin.write("\n")
        owner.stdin.flush()
        owner.wait(timeout=30)
    dense_mb = sum(s * 4 for s in sizes) / 1e6

    def stats(xs):
        p50 = _percentile(xs, 0.50)
        return {"round_p50_ms": round(p50 * 1e3, 3),
                "round_p99_ms": round(_percentile(xs, 0.99) * 1e3, 3),
                "MBps": round(dense_mb / 1e0 / p50, 1),
                "rounds": len(xs)}

    off, on = stats(lat["off"]), stats(lat["on"])
    return {
        "variants": {"traced_off": off, "traced_on": on},
        "enabled_overhead_frac": round(
            on["round_p50_ms"] / off["round_p50_ms"] - 1.0, 4),
        "dense_mb_per_round": round(dense_mb, 1),
    }


def bench_disabled_hook(sizes, round_p50_ms):
    """The disabled path, measured directly: ns per hook when no
    recorder exists, times the hooks one transport round executes,
    as a fraction of the measured round — the honest < 2% bound."""
    from bluefog_tpu.tracing import recorder as trc

    trc.reset()
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        trc.get()
    get_ns = (time.perf_counter() - t0) / n * 1e9
    t0 = time.perf_counter()
    for _ in range(n):
        with trc.span("x"):
            pass
    span_ns = (time.perf_counter() - t0) / n * 1e9
    # hooks per round on this shape: deposit_async does ONE trc.get()
    # per leaf; the sender/ack threads one per batch; the dsgd loop a
    # handful of span() shells
    hooks = len(sizes) + 16
    bound = (hooks * get_ns + 8 * span_ns) / (round_p50_ms * 1e6)
    return {"disabled_get_ns": round(get_ns, 1),
            "disabled_span_ns": round(span_ns, 1),
            "hooks_per_round": hooks,
            "disabled_overhead_frac_bound": round(bound, 6)}


# ---------------------------------------------------------------------------
# critical-path accuracy leg
# ---------------------------------------------------------------------------


def _ring_trace(n_ranks, slow_src, rounds, rng):
    """A ring fleet (r deposits to (r+1) % n) with ONE slow edge
    injected at slow_src -> (slow_src+1) % n; returns (spans, edge)."""
    dst = (slow_src + 1) % n_ranks
    spans, sid = [], 1
    for k in range(rounds):
        for r in range(n_ranks):
            slow = r == slow_src
            rdur = 0.9 if (r == dst) else 0.3 + rng.uniform(0, 0.05)
            spans.append(dict(sid=sid, par=0, tid=5, name="round",
                              cat="dsgd", rank=r, round=k, t0=float(k),
                              dur=rdur))
            sid += 1
            wdur = 0.7 if slow else 0.08 + rng.uniform(0, 0.03)
            wire = dict(sid=sid, par=0, tid=5, name="wire", cat="tcp",
                        rank=r, round=k, t0=k + 0.05, dur=wdur,
                        dst=f"w:{(r + 1) % n_ranks}", seq=k)
            sid += 1
            spans.append(wire)
            t_apply = k + (0.8 if slow else 0.15)
            spans.append(dict(sid=sid, par=wire["sid"], tid=5,
                              name="apply", cat="tcp_srv",
                              rank=(r + 1) % n_ranks, round=k,
                              t0=t_apply, dur=0.02))
            sid += 1
    return spans, [slow_src, dst]


def bench_accuracy(cases=20, seed=7):
    import bluefog_tpu.tracing.analyze as tan

    rng = np.random.default_rng(seed)
    correct = 0
    details = []
    for c in range(cases):
        n = int(rng.choice([3, 4, 6]))
        slow_src = int(rng.integers(0, n))
        spans, truth = _ring_trace(n, slow_src, rounds=6,
                                   rng=np.random.default_rng(seed + c))
        cp = tan.critical_path(tan.build_graph(spans))
        got = cp.get("gating_edge")
        ok = got == truth
        correct += ok
        details.append({"ranks": n, "truth": truth, "got": got})
    return {"cases": cases, "correct": correct,
            "accuracy": correct / cases, "details": details}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true",
                    help="small leaf set (CI smoke)")
    ap.add_argument("--rounds", type=int, default=8)
    ap.add_argument("--trials", type=int, default=3)
    args = ap.parse_args()

    sizes = _SMALL_LEAVES if args.small else _RESNET50_LEAVES
    overhead = bench_overhead(sizes, args.rounds, args.trials)
    disabled = bench_disabled_hook(
        sizes, overhead["variants"]["traced_off"]["round_p50_ms"])
    accuracy = bench_accuracy()

    ok_disabled = disabled["disabled_overhead_frac_bound"] < 0.02
    ok_accuracy = accuracy["accuracy"] == 1.0
    report = {
        "metric": "tracing_overhead_and_attribution",
        "tree": "small" if args.small else "resnet50",
        "leaves": len(sizes),
        "params": int(sum(sizes)),
        **overhead,
        **disabled,
        "critical_path_accuracy": {k: v for k, v in accuracy.items()
                                   if k != "details"},
        "gates": {"disabled_overhead_under_2pct": ok_disabled,
                  "accuracy_1_0": ok_accuracy},
    }
    print(json.dumps(report))
    return 0 if (ok_disabled and ok_accuracy) else 1


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    sys.exit(main())
