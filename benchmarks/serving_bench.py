"""Serving read-path bench: snapshot latency, publish overhead, fan-out.

Measures the three costs the serve-while-training tier adds:

1. **publish** — what the TRAINING loop pays per round-stamped publish
   (the double-buffer copy + swap; this is the only serving cost on the
   hot path);
2. **snapshot** — end-to-end `SNAPSHOT` wire read latency (p50/p99) of
   a model-sized group under a live publisher racing it across round
   boundaries (every reply is audited round-consistent via the in-band
   `round` stamp leaf);
3. **fan-out** — N concurrent subscribers on one server: delivered
   rounds/s per subscriber and the slow-reader skip behavior, while the
   publisher's cadence stays fixed (readers must never throttle it).

Self-contained and fast (~15 s), no jax, rc=0 off-TPU.

Run:
  python benchmarks/serving_bench.py [--dim 1000000] [--subs 8]
      [--out BENCH_serving.json]
"""

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def _pct(xs, q):
    if not xs:
        return float("nan")
    return float(np.percentile(np.asarray(xs), q))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=1_000_000,
                    help="model-vector elements (f64)")
    ap.add_argument("--subs", type=int, default=8,
                    help="concurrent subscribers in the fan-out phase")
    ap.add_argument("--reads", type=int, default=200,
                    help="snapshot reads in the latency phase")
    ap.add_argument("--out", default=None, help="write JSON here")
    args = ap.parse_args()

    from bluefog_tpu.serving import table
    from bluefog_tpu.serving.client import SnapshotClient
    from bluefog_tpu.serving.subscriber import Subscriber
    from bluefog_tpu.runtime.window_server import WindowServer

    tbl = table()
    group = f"serving_bench_{os.getpid()}"
    x = np.random.default_rng(0).standard_normal(args.dim)
    p = np.array([1.0])

    # ------------------------------------------------- 1. publish cost
    t_pub = []
    for rnd in range(30):
        t0 = time.perf_counter()
        tbl.publish(group, rnd, {"x": x, "p": p,
                                 "round": np.array([float(rnd)])})
        t_pub.append(time.perf_counter() - t0)
    pub_ms = {"p50_ms": 1e3 * _pct(t_pub, 50),
              "p99_ms": 1e3 * _pct(t_pub, 99)}

    srv = WindowServer()
    addr = srv.start("127.0.0.1")

    # a publisher thread keeps rolling rounds under the readers
    stop = threading.Event()
    round_box = [30]

    def publisher():
        while not stop.is_set():
            rnd = round_box[0]
            tbl.publish(group, rnd, {"x": x, "p": p,
                                     "round": np.array([float(rnd)])})
            round_box[0] = rnd + 1
            time.sleep(0.002)

    pub_thread = threading.Thread(target=publisher, daemon=True)
    pub_thread.start()

    # ------------------------------------------- 2. snapshot latency
    client = SnapshotClient(addr, group)
    lat = []
    torn = 0
    for _ in range(args.reads):
        t0 = time.perf_counter()
        snap = client.snapshot(min_round=0)
        lat.append(time.perf_counter() - t0)
        if int(snap.leaves["round"][0]) != snap.round:
            torn += 1
    client.close()
    nbytes = x.nbytes + p.nbytes + 8
    snap_res = {
        "p50_ms": 1e3 * _pct(lat, 50), "p99_ms": 1e3 * _pct(lat, 99),
        "MB_per_s": (nbytes / max(_pct(lat, 50), 1e-9)) / 1e6,
        "torn_replies": torn,
    }

    # ------------------------------------------------- 3. fan-out
    counts = [0] * args.subs
    subs = []

    def make_cb(i):
        def cb(snap):
            counts[i] += 1
        return cb

    t0 = time.perf_counter()
    r0 = round_box[0]
    for i in range(args.subs):
        subs.append(Subscriber(addr, group, every=1,
                               on_snapshot=make_cb(i), queue_max=2))
    time.sleep(5.0)
    dt = time.perf_counter() - t0
    rounds_rolled = round_box[0] - r0
    fan_res = {
        "subscribers": args.subs,
        "publisher_rounds_per_s": rounds_rolled / dt,
        "delivered_per_sub_per_s": [round(c / dt, 1) for c in counts],
        "skipped_rounds": [s.skipped_rounds for s in subs],
    }
    for s in subs:
        s.close()
    stop.set()
    pub_thread.join(timeout=5)
    srv.stop()
    tbl.drop(group)

    result = {
        "dim": args.dim,
        "leaf_bytes": int(nbytes),
        "publish": pub_ms,
        "snapshot_read": snap_res,
        "fanout": fan_res,
    }
    print(json.dumps(result, indent=2))
    if torn:
        print("FAIL: torn (round-inconsistent) snapshot replies", torn,
              file=sys.stderr)
        return 1
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
