"""One-shot on-chip evidence capture — run this the moment the TPU answers.

The axon relay wedges unpredictably (PROFILE.md §1), so when a chip claim
succeeds, EVERYTHING must be harvested in that window, in dependency order,
each stage in its own process (a clean exit releases the claim; only killed
processes leave it stale — never run this under a timeout that kills):

  1. `python bench.py --profile <dir>` — batch sweep, MFU + sanity gates,
     jax.profiler trace at the best batch (also refreshes BENCH_CACHE.json);
  2. `benchmarks/profile_summary.py <dir>` — per-op sink table for
     PROFILE.md §4;
  3. `tests/test_flash_attention.py` run DIRECTLY (no conftest) — converts
     the suite's 3 TPU-gated skips into on-chip numerics evidence;
  4. single-chip routing probe — asserts `backend='auto'` never selects the
     pallas RDMA kernels on one chip (wedge-avoidance by construction).

Prints one JSON line per stage plus a final summary line; exits nonzero if
stage 1 fails (the rest are best-effort evidence).

Run:  python benchmarks/capture_onchip.py [--profile-dir /tmp/profile_r4]
"""

import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_stage(name, argv, timeout_s):
    import signal

    t0 = time.time()
    stdout = ""
    # Popen (not run): on timeout, subprocess.run's TimeoutExpired carries
    # NO partial output on this Python — kill + drain explicitly, because
    # for a stage that wedged the relay that partial output is the only
    # diagnostic there will ever be.  start_new_session: the kill must
    # take the whole process GROUP — a wedged grandchild still holding the
    # relay claim (or the pipe write-end, which would hang the drain)
    # survives a plain proc.kill().
    proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True, cwd=_REPO,
                            start_new_session=True)
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
        ok = proc.returncode == 0
        tail = ((stdout or "") + (stderr or ""))[-2000:]
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        ok = False
        try:
            # bounded: a surviving pipe-holder must not convert a stage
            # timeout into an orchestrator-wide hang
            stdout, stderr = proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            stdout, stderr = "", ""
        tail = (f"TIMEOUT after {timeout_s}s | " +
                ((stdout or "") + (stderr or ""))[-2000:])
    result = {"stage": name, "ok": ok, "wall_s": round(time.time() - t0, 1),
              "tail": tail[-500:]}
    print(json.dumps(result), flush=True)
    return ok, stdout or ""


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--profile-dir", default="/tmp/profile_onchip")
    ap.add_argument("--skip-bench", action="store_true",
                    help="profile/flash/routing only (bench already captured)")
    args = ap.parse_args()

    results = {}
    if not args.skip_bench:
        ok, stdout = run_stage(
            "bench_sweep_profile",
            [sys.executable, "-u", "bench.py", "--profile", args.profile_dir],
            timeout_s=4 * 3600)
        results["bench"] = ok
        if not ok:
            print(json.dumps({"summary": "bench failed; aborting capture",
                              "results": results}))
            sys.exit(1)
        # scan FULL stdout for the degraded marker (the stale flag leads the
        # final JSON line; a truncated tail could hide it and send the later
        # stages into the wedged relay)
        if '"stale": true' in stdout:
            print(json.dumps({
                "summary": "bench DEGRADED (relay refused init) — no chip "
                           "window; stopping before stages that would also "
                           "hang", "results": results}))
            sys.exit(0)

    ok, _ = run_stage(
        "profile_summary",
        [sys.executable, os.path.join("benchmarks", "profile_summary.py"),
         args.profile_dir],
        timeout_s=600)
    results["profile_summary"] = ok

    ok, _ = run_stage(
        "flash_attention_onchip",
        [sys.executable, os.path.join("tests", "test_flash_attention.py")],
        timeout_s=3600)
    results["flash_onchip"] = ok

    probe = (
        "import bluefog_tpu as bf\n"
        "import jax\n"
        "from bluefog_tpu.ops import pallas_gossip as pg\n"
        "from bluefog_tpu.topology import RingGraph\n"
        "from bluefog_tpu.topology.schedule import build_schedule\n"
        "import jax.numpy as jnp\n"
        "n = len(jax.devices())\n"
        "assert n == 1, f'expected the single relay chip, got {n}'\n"
        "assert pg.on_tpu_platform(), jax.default_backend()\n"
        "sched = build_schedule(RingGraph(1))\n"
        "assert pg.auto_gossip_backend(sched, jnp.ones(8)) == 'xla'\n"
        "assert not pg.is_pallas_supported(sched)\n"
        "print('ROUTING_OK: auto never selects pallas on one chip')\n"
    )
    ok, _ = run_stage(
        "single_chip_routing", [sys.executable, "-c", probe], timeout_s=1800)
    results["routing"] = ok

    print(json.dumps({"summary": "capture complete", "results": results}))


if __name__ == "__main__":
    main()
