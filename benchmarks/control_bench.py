"""Self-tuning control plane A/B bench: chaos slow-peer + lossy-link.

The question this answers with a number: when ONE peer's link goes bad
(delayed + dropped frames), does the communication controller
(:mod:`bluefog_tpu.control`) recover the fleet's throughput that a
frozen launch config cannot?

Scenario (4 rank processes, tcp window transport, bounded deposit
queues AND a bounded coalescing window — the latency-bound link
regime, where a per-frame link delay is an honest per-deposit cost the
16 MB default coalescing cap would otherwise amortize away — so the
slow link back-pressures its senders honestly: the "whole fleet
degrades to the worst link's pace" failure mode):

- rank 3's window SERVER runs behind a scripted chaos link:
  ``server:delay:ms=120:rate=0.95`` (95% of inbound frames delayed
  120 ms — a slow peer) + ``server:drop:rate=0.01`` (a 1%-loss lossy
  link, exercising reconnect+replay) — both seeded, deterministic per
  traffic;
- every rank runs zero-gradient async DSGD (pure push-sum averaging —
  consensus dynamics, no model noise; small f64 payloads so the run is
  link-latency-bound, not CPU-bound) over a fully-connected capacity-4
  elastic fleet; RANK 0 carries the step
  TARGET (``stop_after_steps``) and the other ranks converge at the
  stop barrier as soon as it finishes (the elastic stopped-detection
  path), so rank 0's reported wall time IS the fleet's time-to-target;
- variants run INTERLEAVED per trial (this container's CPU drifts over
  tens of seconds; PR-4 lesson) and the headline is the MEDIAN of
  per-trial ratios:

  * ``static``  — the frozen launch config (control=None);
  * ``control`` — ``control=ControlConfig(...)``: evidence disseminates
    through barrier-dir records, the controllers converge on a plan
    that reduces rank 3 to the ring spine, and the senders stop
    queueing into the bad link.

Acceptance (ISSUE 8): control reaches the target in <= 0.6x the static
wall time (median of interleaved trials), AND the exact push-sum mass
audit of every run — chaos or not, controller or not — matches the
chaos-free baseline: total mass == 4 to 1e-9·n.  A plan moves edges;
it never creates or destroys mass, and reconnect/replay keeps the
lossy link exactly-once.

Run: ``python benchmarks/control_bench.py [--trials N] [--out FILE]``
(rc=0 off-TPU; workers are pure numpy — no jax in the hot loop).
Committed results: ``BENCH_control.json``.
"""

import argparse
import json
import os
import statistics
import subprocess
import sys
import tempfile
import time

STEP_TARGET = 200
CAPACITY = 4
SLOW_RANK = 3
DIM = 64  # cheap payloads: the scenario is LINK-latency-bound, not CPU
# 120 ms per frame: decisively separated from the healthy links' ack
# latency even under CPU contention (tens of ms on a loaded 2-core
# host), so the median-relative hysteresis band cannot ride into the
# slow peer's lag and flap the plan
CHAOS_SPEC = ("server:delay:ms=120:rate=0.95:seed=1;"
              "server:drop:rate=0.01:seed=2")
# strict near-stop-and-wait stream shape: one frame in flight, two
# deposits of queue — so a 60 ms per-frame link delay is an honest
# ~30 ms per-deposit cost that back-pressures the producer (the
# latency-bound link regime; the 16 MB default coalescing cap would
# amortize the delay away and hide the slow peer entirely)
STREAM = dict(max_in_flight=1, max_queue_items=2,
              max_batch_bytes=1 << 16)


def _worker(rank: int, barrier_dir: str, variant: str) -> None:
    # run as a script: sys.path has benchmarks/, not the repo root
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    os.environ["JAX_PLATFORMS"] = "cpu"
    if variant != "clean" and rank == SLOW_RANK:
        os.environ["BLUEFOG_TPU_CHAOS"] = CHAOS_SPEC

    import numpy as np

    from bluefog_tpu.control import ControlConfig
    from bluefog_tpu.runtime.async_windows import (FileBarrier,
                                                   run_async_dsgd_rank)
    from bluefog_tpu.runtime.resilience import ResilienceConfig
    from bluefog_tpu.topology import FullyConnectedGraph

    def loss_and_grad(r, step, params):
        return 0.0, {"w": np.zeros_like(np.asarray(params["w"]))}

    rep = run_async_dsgd_rank(
        FullyConnectedGraph(CAPACITY), rank,
        {"w": np.arange(float(DIM), dtype=np.float64)}, loss_and_grad,
        barrier=FileBarrier(barrier_dir, CAPACITY, rank),
        duration_s=120.0, skew_s=0.004,
        name=f"ctl_bench_{os.path.basename(barrier_dir)}",
        transport="tcp", tcp_bind="127.0.0.1",
        resilience=ResilienceConfig(
            barrier_timeout_s=120.0, reconnect_budget=8, seed=rank),
        # elastic fleet (all four are initial members): rank 0 hitting
        # its target ends the run for everyone via the membership
        # stopped-detection — fleet time-to-target, not per-rank
        initial_members=list(range(CAPACITY)),
        # cadence_max=1 pins the gossip-cadence knob: this scenario
        # measures the EDGE-DROP mechanism, and on a zero-gradient
        # averaging workload the stretch/shrink growth band can limit-
        # cycle (stretching raises disagreement, which un-stretches) —
        # an operator pins knobs a scenario does not need
        control=(ControlConfig(evidence_every=8, cooldown_rounds=16,
                               min_lag_s=0.02, cadence_max=1)
                 if variant == "control" else None),
        stop_after_steps=STEP_TARGET if rank == 0 else None,
        stream_options=STREAM)
    if rank == 0:
        out = {
            "wall_s": rep.wall_time_s,
            "total_mass": rep.total_mass,
            "steps_per_rank": rep.steps_per_rank,
            "consensus_gap": rep.consensus_gap,
            "dead_ranks": rep.dead_ranks,
            "plan_changes": rep.plan_changes,
            "final_plan": (json.loads(rep.control_plan.to_bytes())
                           if rep.control_plan is not None else None),
        }
        print("BENCH_RESULT " + json.dumps(out), flush=True)


def _run_variant(variant: str) -> dict:
    bdir = tempfile.mkdtemp(prefix=f"bf-ctlbench-{variant}-")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker",
         str(r), bdir, variant],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=repo) for r in range(CAPACITY)]
    outs = []
    deadline = time.time() + 170
    for p in procs:
        try:
            out, _ = p.communicate(timeout=max(5.0,
                                               deadline - time.time()))
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise SystemExit(f"{variant} trial timed out")
        outs.append(out)
    for r, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise SystemExit(
                f"{variant} worker {r} failed (rc={p.returncode}):\n{out}")
    for line in outs[0].splitlines():
        if line.startswith("BENCH_RESULT "):
            return json.loads(line[len("BENCH_RESULT "):])
    raise SystemExit(f"{variant} rank 0 produced no result:\n{outs[0]}")


def main(argv=None) -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        _worker(int(sys.argv[2]), sys.argv[3], sys.argv[4])
        return 0

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trials", type=int, default=5,
                    help="interleaved (static, control) trial pairs")
    ap.add_argument("--out", default=None,
                    help="write JSON here (default: print only)")
    args = ap.parse_args(argv)

    # audit baseline: one chaos-free static run
    clean = _run_variant("clean")
    print(f"chaos-free: wall={clean['wall_s']:.2f}s "
          f"mass={clean['total_mass']:.12f}")

    trials = []
    for t in range(args.trials):
        static = _run_variant("static")
        control = _run_variant("control")
        ratio = control["wall_s"] / static["wall_s"]
        trials.append({"static": static, "control": control,
                       "ratio": round(ratio, 4)})
        print(f"trial {t}: static={static['wall_s']:.2f}s "
              f"control={control['wall_s']:.2f}s ratio={ratio:.3f} "
              f"plan={control['final_plan']}")

    ratios = [tr["ratio"] for tr in trials]
    median_ratio = statistics.median(ratios)
    # the exact audit must hold EVERYWHERE: chaos-free, chaos-static,
    # chaos-control — a plan change moves edges, never mass
    audits = [clean["total_mass"]] + [
        tr[v]["total_mass"] for tr in trials for v in ("static", "control")]
    audit_ok = all(abs(m - CAPACITY) <= 1e-9 * CAPACITY for m in audits)
    result = {
        "metric": "time_to_target_wall_s",
        "scenario": {
            "ranks": CAPACITY, "slow_rank": SLOW_RANK,
            "chaos": CHAOS_SPEC, "step_target": STEP_TARGET,
            "stream": STREAM,
            "workload": (f"zero-grad push-sum averaging, d={DIM} f64, "
                         "elastic FC capacity, fleet time-to-target on "
                         "rank 0"),
        },
        "chaos_free": clean,
        "trials": trials,
        "median_ratio_control_vs_static": median_ratio,
        "target_ratio": 0.6,
        "ratio_ok": median_ratio <= 0.6,
        "mass_audit_exact_everywhere": audit_ok,
    }
    print(f"\nmedian ratio (control/static): {median_ratio:.3f} "
          f"(target <= 0.6) — {'OK' if result['ratio_ok'] else 'MISS'}; "
          f"exact mass audit everywhere: {audit_ok}")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
        print(f"wrote {args.out}")
    return 0 if (result["ratio_ok"] and audit_ok) else 1


if __name__ == "__main__":
    sys.exit(main())
