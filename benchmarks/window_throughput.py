"""Deposit throughput of the native passive-target window table.

Measures sustained one-sided deposit bandwidth (MB/s) into an AsyncWindow for
model-sized payloads (default 4 MiB — a LeNet is ~0.2 MiB, a ResNet-50 ~100
MiB f32), single writer and 4 concurrent writers (distinct slots, the
multi-neighbor landing pattern).  Also measures the TreePacker pack/unpack
bridge on a ResNet-50-sized parameter tree stand-in.

Run:  python benchmarks/window_throughput.py
Prints one JSON line.
"""

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from bluefog_tpu.runtime.async_windows import AsyncWindow, TreePacker


def deposit_bw(n_elems, reps, writers=1):
    win = AsyncWindow(f"bw_test_{n_elems}_{writers}", writers, n_elems,
                      np.float64)
    payload = np.random.default_rng(0).standard_normal(n_elems)
    t0 = time.perf_counter()
    if writers == 1:
        for _ in range(reps):
            win.deposit(0, payload, accumulate=True)
    else:
        def loop(slot):
            for _ in range(reps):
                win.deposit(slot, payload, accumulate=True)
        ts = [threading.Thread(target=loop, args=(s,)) for s in range(writers)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    dt = time.perf_counter() - t0
    win.free()
    nbytes = n_elems * 8 * reps * writers
    return nbytes / dt / 1e6  # MB/s


def packer_bw(reps=10):
    import jax
    import jax.numpy as jnp

    # ~25.6M params f32 (ResNet-50 scale) as a small tree of big leaves
    tree = {f"w{i}": jnp.ones((1600, 1600), jnp.float32) for i in range(10)}
    packer = TreePacker(tree, np.float64)
    # steady state, as the async-DSGD hot loop actually runs it: the wire
    # buffer is allocated once and reused (run_async_dsgd passes out=),
    # and the first call's jit/compile warmup is excluded
    vec = packer.pack(tree)
    out = packer.unpack(vec)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        packer.pack(tree, out=vec)
    pack_dt = (time.perf_counter() - t0) / reps
    t0 = time.perf_counter()
    for _ in range(reps):
        out = packer.unpack(vec)
    jax.block_until_ready(out)
    unpack_dt = (time.perf_counter() - t0) / reps
    nbytes = packer.size * 4  # payload in its source dtype
    return packer.size, nbytes / pack_dt / 1e6, nbytes / unpack_dt / 1e6


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--payload-mib", type=float, default=4.0)
    ap.add_argument("--reps", type=int, default=50)
    args = ap.parse_args()

    n_elems = int(args.payload_mib * (1 << 20) / 8)
    bw1 = deposit_bw(n_elems, args.reps, writers=1)
    bw4 = deposit_bw(n_elems, max(args.reps // 4, 5), writers=4)
    nparams, pack_mbs, unpack_mbs = packer_bw()
    print(json.dumps({
        "metric": "async_window_deposit_MBps",
        "payload_mib": args.payload_mib,
        "deposit_MBps_1writer": round(bw1, 1),
        "deposit_MBps_4writers": round(bw4, 1),
        "treepacker_params": nparams,
        "pack_MBps": round(pack_mbs, 1),
        "unpack_MBps": round(unpack_mbs, 1),
    }))


if __name__ == "__main__":
    main()
