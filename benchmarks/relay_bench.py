"""Relay-tree read-path bench: fan-out through tiers, delta wire savings.

Measures what `bluefog_tpu/relay/` exists to buy over the flat fan-out
ceiling `BENCH_serving.json` recorded (8 direct subscribers -> ~7
rounds/s each while the publisher does 15.5):

1. **fan-out** — ``--readers`` (default 32) real subscriber threads,
   split over reader worker PROCESSES (so the measurement sees the
   tree, not one reader process's GIL), behind a relay tree at depth 1
   and depth 2 (the acceptance shape), relays as separate
   ``bfrelay-tpu`` processes: delivered rounds/s per reader vs the
   publisher's unthrottled cadence, which every reader must sustain;
2. **staleness** — worst observed leaf staleness in rounds (the
   publisher runs on an absolute schedule from a shared ``t0``, so a
   delivery's lag is measurable in any process) against the declared
   additive per-tier budget;
3. **delta wire ratio** — dense-equivalent bytes / actual wire bytes
   on the trainer's own push channels (op-10 topk deltas with error
   feedback vs full anchors), gated >= 2x;
4. **consistency** — every delivered snapshot passes the exact
   round-stamp audit (the in-band ``round`` leaf equals the frame
   stamp); any mismatch is a torn read and fails the bench.

Self-contained, no jax, rc=0 off-TPU (~30 s; sized for a 1-core CI
container).  The committed run is ``BENCH_relay.json`` at the repo
root; its ``*_ok`` gates ride the ``bffleet-tpu --check`` BENCH mode
and the tier-1 ``TestCommittedBenchGates`` sweep.

Run:
  python benchmarks/relay_bench.py [--dim 50000] [--readers 32]
      [--out BENCH_relay.json]
"""

import argparse
import json
import os
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

#: the declared additive staleness budget, in rounds per tier (a leaf
#: behind a depth-d tree consumes at tier d+1, so its budget is
#: (d + 1) * this) — generous for a single-core CI container, tight
#: enough that a wedged tier would blow it
STALE_BUDGET_PER_TIER = 6.0
#: every reader must deliver at least this fraction of the publisher's
#: unthrottled cadence (skip-to-latest makes the remainder `skipped`,
#: never lag)
SUSTAIN_FRAC = 0.7


def _spawn_relay(upstream, group, tier, full_every):
    """One bfrelay-tpu subprocess; returns (proc, (host, port))."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "bluefog_tpu.relay",
         f"{upstream[0]}:{upstream[1]}", "--group", group,
         "--host", "127.0.0.1", "--tier", str(tier),
         "--full-every", str(full_every), "--codec", "topk"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=_REPO)
    line = proc.stdout.readline().strip()
    if not line.startswith("RELAY_READY"):
        proc.kill()
        raise RuntimeError(f"relay failed to start: {line!r}")
    _, host, port = line.split()
    return proc, (host, int(port))


# ---------------------------------------------------------------------------
# reader worker (subprocess mode)
# ---------------------------------------------------------------------------


def _worker(args) -> int:
    """``--worker``: run N subscriber threads against the given leaf
    addresses for ``--seconds``, then print one JSON line with
    per-reader delivered counts, worst staleness, and the torn-read
    audit.  Staleness per delivery = the publisher's live round (from
    the shared absolute schedule ``t0 + k * publish_dt``) minus the
    delivered round — the cursor-stamped freshness the tree promises."""
    from bluefog_tpu.serving.subscriber import Subscriber

    addrs = [(h, int(p)) for h, p in
             (a.split(":") for a in args.addrs.split(","))]
    counts = [0] * args.n
    stale = [0.0]
    torn = [0]
    mu = threading.Lock()

    def cb(i):
        def on_snap(snap):
            if int(snap["round"][0]) != snap.round:
                with mu:
                    torn[0] += 1
            live = (time.time() - args.t0) / args.publish_dt
            lag = max(0.0, live - snap.round)
            with mu:
                counts[i] += 1
                if lag > stale[0]:
                    stale[0] = lag
        return on_snap

    subs = [Subscriber(addrs[i % len(addrs)], args.group, delta=True,
                       queue_max=2, on_snapshot=cb(i))
            for i in range(args.n)]
    # the measurement window is the worker's OWN steady-state span —
    # process startup and subscribe handshakes are excluded, so the
    # reported rate is deliveries over the time the readers were live
    t_start = time.perf_counter()
    time.sleep(args.seconds)
    elapsed = time.perf_counter() - t_start
    for s in subs:
        s.close()
    print("WORKER " + json.dumps(
        {"counts": counts, "elapsed_s": elapsed,
         "worst_staleness_rounds": round(stale[0], 1),
         "torn": torn[0]}), flush=True)
    return 0


def _run_phase(leaf_addrs, group, round_box, readers, seconds, t0,
               publish_dt, n_workers=4):
    addr_arg = ",".join(f"{h}:{p}" for h, p in leaf_addrs)
    per = [readers // n_workers + (1 if i < readers % n_workers else 0)
           for i in range(n_workers)]
    r0 = round_box[0]
    t_start = time.perf_counter()
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker",
         "--addrs", addr_arg, "--group", group, "--n", str(n),
         "--seconds", str(seconds), "--t0", repr(t0),
         "--publish-dt", str(publish_dt)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=_REPO) for n in per if n > 0]
    rates, stale, torn = [], 0.0, 0
    for proc in procs:
        out, _ = proc.communicate(timeout=seconds + 60)
        line = next((ln for ln in out.splitlines()
                     if ln.startswith("WORKER ")), None)
        if proc.returncode != 0 or line is None:
            raise RuntimeError(f"reader worker failed:\n{out}")
        doc = json.loads(line[len("WORKER "):])
        rates += [round(c / doc["elapsed_s"], 2)
                  for c in doc["counts"]]
        stale = max(stale, doc["worst_staleness_rounds"])
        torn += doc["torn"]
    dt = time.perf_counter() - t_start
    published = round_box[0] - r0
    return {
        "readers": len(rates),
        "publisher_rounds_per_s": round(published / dt, 2),
        "delivered_per_reader_per_s_mean": round(
            sum(rates) / len(rates), 2),
        "delivered_per_reader_per_s_min": min(rates),
        "worst_staleness_rounds": stale,
        "torn": torn,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=50_000,
                    help="model-vector elements (f64)")
    ap.add_argument("--readers", type=int, default=32,
                    help="leaf subscriber threads per phase (>= 32 is "
                    "the acceptance scale)")
    ap.add_argument("--seconds", type=float, default=6.0,
                    help="measurement window per phase")
    ap.add_argument("--publish-dt", type=float, default=0.1,
                    help="publisher cadence (s/round)")
    ap.add_argument("--full-every", type=int, default=8,
                    help="delta resync-anchor cadence")
    ap.add_argument("--out", default=None, help="write JSON here")
    # worker mode (internal)
    ap.add_argument("--worker", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--addrs", default="", help=argparse.SUPPRESS)
    ap.add_argument("--group", default="", help=argparse.SUPPRESS)
    ap.add_argument("--n", type=int, default=0, help=argparse.SUPPRESS)
    ap.add_argument("--t0", type=float, default=0.0,
                    help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.worker:
        return _worker(args)

    from bluefog_tpu.metrics.registry import metrics_start, metrics_stop
    from bluefog_tpu.runtime.delta import DeltaConfig
    from bluefog_tpu.runtime.window_server import WindowServer
    from bluefog_tpu.serving.snapshots import SnapshotTable

    reg = metrics_start()
    tbl = SnapshotTable()
    srv = WindowServer(
        snapshots=tbl,
        delta=DeltaConfig(full_every=args.full_every, codec="topk",
                          topk_ratio=0.05, min_delta_elems=1024))
    addr = srv.start("127.0.0.1")
    group = f"relay_bench_{os.getpid()}"
    rng = np.random.default_rng(0)
    x = rng.standard_normal(args.dim)
    dense_frame = x.nbytes + 8 + 8  # x + p + round leaves

    stop = threading.Event()
    round_box = [0]
    t0 = time.time()
    tbl.publish(group, 0, {"x": x, "p": np.array([1.0]),
                           "round": np.array([0.0])})

    def publisher():
        # absolute schedule: round k is published at t0 + k*dt, so any
        # process can convert a delivery time into a staleness measure
        while not stop.is_set():
            rnd = round_box[0] + 1
            # the model moves a little every round: the delta codec's
            # steady state (anchors resync it exactly every Nth push)
            np.add(x, 0.001 * rng.standard_normal(args.dim), out=x)
            tbl.publish(group, rnd, {"x": x, "p": np.array([1.0]),
                                     "round": np.array([float(rnd)])})
            round_box[0] = rnd
            next_t = t0 + (rnd + 1) * args.publish_dt
            delay = next_t - time.time()
            if delay > 0:
                time.sleep(delay)

    pub = threading.Thread(target=publisher, daemon=True)
    pub.start()

    relays = []
    result = {"dim": args.dim, "leaf_bytes": int(dense_frame),
              "publish_dt_s": args.publish_dt,
              "full_every": args.full_every,
              "stale_budget_per_tier": STALE_BUDGET_PER_TIER,
              "sustain_frac": SUSTAIN_FRAC}
    try:
        # ---------------------------------------------- depth 1 tree
        t1 = [_spawn_relay(addr, group, 1, args.full_every)
              for _ in range(4)]
        relays += t1
        time.sleep(1.0)  # let the tier land its first rounds
        result["depth1"] = _run_phase(
            [a for _, a in t1], group, round_box, args.readers,
            args.seconds, t0, args.publish_dt)

        # ---------------------------------------------- depth 2 tree
        t2 = [_spawn_relay(t1[i % len(t1)][1], group, 2,
                           args.full_every) for i in range(4)]
        relays += t2
        time.sleep(1.0)
        result["depth2"] = _run_phase(
            [a for _, a in t2], group, round_box, args.readers,
            args.seconds, t0, args.publish_dt)
    finally:
        stop.set()
        pub.join(timeout=5)
        for proc, _ in relays:
            proc.terminate()
        for proc, _ in relays:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        srv.stop()
        tbl.drop(group)

    # ------------------------------------------- delta wire accounting
    # the trainer's own push channels (to the tier-1 relays) run in this
    # process: bf_push_bytes_total{kind=...} counts actual wire bytes;
    # fulls are exactly dense_frame bytes each, and in steady state
    # every full anchor is followed by (full_every - 1) deltas, so the
    # dense-equivalent traffic is (fulls + deltas) x dense_frame
    snap = reg.snapshot()
    wire_full = sum(v for k, v in snap.items()
                    if k.startswith("bf_push_bytes_total")
                    and 'kind="full"' in k)
    wire_delta = sum(v for k, v in snap.items()
                     if k.startswith("bf_push_bytes_total")
                     and 'kind="delta"' in k)
    metrics_stop()
    full_frames = wire_full / dense_frame if dense_frame else 0.0
    delta_frames = full_frames * max(0, args.full_every - 1)
    dense_equiv = (full_frames + delta_frames) * dense_frame
    wire_total = wire_full + wire_delta
    ratio = dense_equiv / wire_total if wire_total else float("nan")
    result["delta"] = {
        "wire_full_bytes": int(wire_full),
        "wire_delta_bytes": int(wire_delta),
        "dense_equivalent_bytes": int(dense_equiv),
        "wire_ratio": round(ratio, 2),
    }

    # ---------------------------------------------------------- gates
    d1, d2 = result["depth1"], result["depth2"]
    result["depth1_sustained_ok"] = bool(
        d1["delivered_per_reader_per_s_min"]
        >= SUSTAIN_FRAC * d1["publisher_rounds_per_s"])
    result["depth2_sustained_ok"] = bool(
        d2["delivered_per_reader_per_s_min"]
        >= SUSTAIN_FRAC * d2["publisher_rounds_per_s"])
    result["staleness_ok"] = bool(
        d1["worst_staleness_rounds"] <= 2 * STALE_BUDGET_PER_TIER
        and d2["worst_staleness_rounds"] <= 3 * STALE_BUDGET_PER_TIER)
    result["torn_ok"] = bool(d1["torn"] == 0 and d2["torn"] == 0)
    result["delta_ratio_ok"] = bool(ratio >= 2.0)
    result["ok"] = bool(
        result["depth1_sustained_ok"] and result["depth2_sustained_ok"]
        and result["staleness_ok"] and result["torn_ok"]
        and result["delta_ratio_ok"])

    print(json.dumps(result, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
