"""Comm/compute overlap evidence from the compiled TPU schedule.

The reference's performance contract is that gossip overlaps backprop (hooks
+ background thread, SURVEY.md §3.3).  The XLA analog is compiler-scheduled:
collectives lower to ``-start``/``-done`` pairs and the latency-hiding
scheduler places compute inside the window.  This script AOT-compiles the
real decentralized training step (ResNet-18, AWC gossip optimizer) for an
8-chip v5e topology — no hardware needed, the PJRT topology API compiles
offline — and reports, straight from the scheduled HLO, how many compute
instructions execute while each gossip transfer is in flight.

Run:  python benchmarks/overlap_report.py
Prints one JSON line (plus a per-window histogram to stderr).
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def build_step(mesh, axis_name="bf"):
    from bluefog_tpu.models import ResNet18
    from bluefog_tpu.optim.optimizers import DistributedNeighborAllreduceOptimizer
    from bluefog_tpu.topology.graphs import ExponentialTwoGraph
    from bluefog_tpu.topology.schedule import build_schedule

    n = len(mesh.devices.flat)
    model = ResNet18(num_classes=1000, dtype=jnp.bfloat16)
    sched = build_schedule(ExponentialTwoGraph(n))
    opt = DistributedNeighborAllreduceOptimizer(
        optax.sgd(0.1), topology=sched, axis_name=axis_name)

    def step(p_blk, bs_blk, x_blk, y_blk):
        p, bs = jax.tree_util.tree_map(lambda t: t[0], (p_blk, bs_blk))
        x, y = x_blk[0], y_blk[0]
        st = opt.init(p)

        def loss_fn(p):
            logits, mut = model.apply(
                {"params": p, "batch_stats": bs}, x, train=True,
                mutable=["batch_stats"])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean(), mut["batch_stats"]

        (loss, new_bs), g = jax.value_and_grad(loss_fn, has_aux=True)(p)
        upd, st = opt.update(g, st, p)
        p = optax.apply_updates(p, upd)
        return (jax.tree_util.tree_map(lambda t: t[None], (p, new_bs))
                + (loss[None],))

    from bluefog_tpu.parallel.api import shard_map

    return jax.jit(shard_map(
        step, mesh=mesh, in_specs=(P(axis_name),) * 4,
        out_specs=(P(axis_name),) * 3, check_vma=False))


def main():
    from jax.experimental import topologies

    from bluefog_tpu.models import ResNet18
    from bluefog_tpu.utils.inspect import collective_overlap_report

    topo_name = os.environ.get("BFTPU_AOT_TOPOLOGY", "v5e:2x4")
    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name=topo_name)
    n = len(topo.devices)
    mesh = Mesh(np.array(topo.devices), ("bf",))
    fn = build_step(mesh)

    batch, img = 64, 224
    model = ResNet18(num_classes=1000, dtype=jnp.bfloat16)
    x0 = jnp.zeros((batch, img, img, 3), jnp.bfloat16)
    variables = jax.eval_shape(
        lambda k: model.init(k, x0, train=True), jax.random.PRNGKey(0))

    def stacked(tree):
        return jax.tree_util.tree_map(
            lambda t: jax.ShapeDtypeStruct(
                (n,) + t.shape, t.dtype,
                sharding=NamedSharding(mesh, P("bf"))), tree)

    args = (
        stacked(variables["params"]),
        stacked(variables["batch_stats"]),
        jax.ShapeDtypeStruct((n, batch, img, img, 3), jnp.bfloat16,
                             sharding=NamedSharding(mesh, P("bf"))),
        jax.ShapeDtypeStruct((n, batch), jnp.int32,
                             sharding=NamedSharding(mesh, P("bf"))),
    )
    rep = collective_overlap_report(fn, *args)
    hist = {}
    for w in rep["windows"]:
        hist[w] = hist.get(w, 0) + 1
    print(json.dumps({
        "metric": "gossip_overlap_compiled_schedule",
        "topology": topo_name,
        "collective_windows": rep["pairs"],
        "mean_compute_in_flight": round(rep["mean_compute_in_flight"], 1),
        "overlapped_fraction": round(rep["overlapped_fraction"], 3),
    }))
    print(f"window histogram {{compute_ops: windows}}: {dict(sorted(hist.items()))}",
          file=sys.stderr)


if __name__ == "__main__":
    main()
