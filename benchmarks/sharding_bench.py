"""Gossip-of-meshes wire A/B: gather-then-gossip vs shard-local.

Measures, on a ResNet-shaped parameter tree, what the unified sharding
subsystem buys on the gossip wire:

1. **gather-then-gossip** (the pre-sharding baseline): every deposit
   ships the FULL packed tree — ``run_sharded_gossip`` with ``axes={}``,
   which is also the numerical reference;
2. **shard-local** (gossip-of-meshes): each inner-mesh coordinate ships
   only its own shard to the same coordinate on neighbor meshes —
   ``axes={'fsdp': F, 'tp': Tp}`` — with the gather paid ONCE at the
   read boundary instead of per deposit.

Reported per mode: bytes per deposit, total wire bytes, wall per round;
plus the read-boundary reassembly cost, the savings ratio, and the
max |shard-local - reference| error (must be ~1e-12: gossip is
element-wise, the two runs are the same floating-point program).

Self-contained and fast (~10 s), CPU-only, rc=0 off-TPU.

Run:
  python benchmarks/sharding_bench.py [--ranks 8] [--rounds 5]
      [--fsdp 2] [--tp 2] [--out BENCH_sharding.json]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def resnet_tree(width: int = 1):
    """A ResNet-50-shaped pytree (4 stages of bottleneck blocks), scaled
    by ``width`` — shapes matter for the sharding arithmetic, depth is
    trimmed so the bench stays CI-fast."""
    rng = np.random.default_rng(0)

    def conv(cin, cout, k=3):
        return rng.standard_normal((k, k, cin, cout)).astype(np.float64)

    tree = {"stem": {"conv": conv(4, 64 * width, 7),
                     "bn_scale": np.ones((64 * width,)),
                     "bn_bias": np.zeros((64 * width,))}}
    stages = [(64, 2), (128, 2), (256, 2), (512, 2)]
    cin = 64 * width
    for si, (c, blocks) in enumerate(stages):
        c *= width
        for bi in range(blocks):
            blk = {
                "conv1": conv(cin, c, 1),
                "conv2": conv(c, c, 3),
                "conv3": conv(c, 4 * c, 1),
                "bn_scale": np.ones((4 * c,)),
                "bn_bias": np.zeros((4 * c,)),
            }
            tree[f"stage{si}/block{bi}"] = blk
            cin = 4 * c
    tree["fc"] = {"kernel": rng.standard_normal((cin, 1000)),
                  "bias": np.zeros((1000,))}
    return tree


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=8)
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--fsdp", type=int, default=2)
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--width", type=int, default=1)
    ap.add_argument("--out", default=None, help="write JSON here")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    from bluefog_tpu import topology as T
    from bluefog_tpu.sharding import (RuleTable, inner_coords,
                                      run_sharded_gossip, tree_wire_bytes)
    from jax.sharding import PartitionSpec as P

    axes = {"fsdp": args.fsdp, "tp": args.tp}
    # conv kernels sharded over cout (fsdp x tp), fc column-parallel,
    # bn/bias replicated — the one table, ResNet spelling
    table = RuleTable([
        (r"conv\d?$", P(None, None, None, ("fsdp", "tp"))),
        (r"fc/kernel$", P(None, ("fsdp", "tp"))),
        (".*", P()),
    ], axes=axes)

    template = resnet_tree(args.width)
    n_elems = sum(int(np.asarray(x).size)
                  for x in jax.tree_util.tree_leaves(template))
    rng = np.random.default_rng(1)
    p0 = [jax.tree_util.tree_map(
        lambda a: np.asarray(a, np.float64)
        + rng.standard_normal(np.shape(a)), template)
        for _ in range(args.ranks)]
    topo = T.ExponentialTwoGraph(args.ranks)
    shard_b, full_b = tree_wire_bytes(template,
                                      table.resolve_tree(template), axes)

    def run(mode_axes):
        t0 = time.perf_counter()
        rep = run_sharded_gossip(topo, p0, table, mode_axes,
                                 rounds=args.rounds, name="bench")
        wall = time.perf_counter() - t0
        return rep, wall

    ref, wall_full = run({})
    shd, wall_shard = run(axes)

    err = 0.0
    for a, b in zip(ref.params, shd.params):
        fa = np.concatenate([np.asarray(x).ravel()
                             for x in jax.tree_util.tree_leaves(a)])
        fb = np.concatenate([np.asarray(x).ravel()
                             for x in jax.tree_util.tree_leaves(b)])
        err = max(err, float(np.abs(fa - fb).max()))

    result = {
        "config": {"ranks": args.ranks, "rounds": args.rounds,
                   "axes": axes, "topology": topo.name,
                   "tree": f"resnet50-shaped x{args.width}",
                   "elements": n_elems,
                   "shards_per_rank": len(inner_coords(axes))},
        "gather_then_gossip": {
            "bytes_per_deposit": ref.shard_bytes_per_deposit,
            "total_wire_bytes": ref.shard_bytes_per_deposit * ref.deposits,
            "wall_s_per_round": wall_full / args.rounds,
        },
        "shard_local": {
            "bytes_per_deposit": shd.shard_bytes_per_deposit,
            "total_wire_bytes": shd.shard_bytes_per_deposit * shd.deposits,
            "wall_s_per_round": wall_shard / args.rounds,
        },
        "wire_savings_ratio": full_b / shard_b,
        "saved_bytes_per_deposit": shd.saved_bytes_per_deposit,
        "max_abs_err_vs_reference": err,
        "equivalent": bool(err < 1e-11),
    }
    print(json.dumps(result, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
    if not result["equivalent"]:
        print("FAIL: shard-local gossip diverged from the reference",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
