"""Pod-scale wire census: gossip vs allreduce, from compiled programs.

No multi-chip hardware is needed for the SCALING story: lower the real
gossip step on abstract meshes of growing size, read the collective-permute
op count from the StableHLO, and put it next to the analytic byte model for
each strategy (ring allreduce uses the standard cost model throughout).  This is the
reference's core claim made concrete (neighbor_allreduce scales better at
high node counts because its per-step wire cost and dependency depth do
not grow with the mesh):

- ring allreduce moves ``2P(n-1)/n`` bytes/chip in ``2(n-1)`` serial hops
  — DEPTH grows linearly with the mesh (and any straggler stalls all);
- static exp2 gossip moves ``P*log2(n)`` bytes/chip in ``log2(n)`` hops;
- one-peer dynamic gossip moves ``P`` bytes/chip in ONE hop, step after
  step, independent of mesh size.

Run:  python benchmarks/scaling_census.py [--param-mib 97.6]
Prints one JSON line per mesh size (plus a table to stderr).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_platforms", "cpu")  # compile-only analysis: never
# touch an accelerator backend (the axon relay can hang device init)

import jax.numpy as jnp
from jax.sharding import AbstractMesh, PartitionSpec as P

from bluefog_tpu.ops import collectives as C
from bluefog_tpu.parallel.api import shard_map
from bluefog_tpu.topology import ExponentialTwoGraph
from bluefog_tpu.topology.schedule import build_schedule


def census(n: int, param_bytes: int):
    mesh = AbstractMesh((n,), ("bf",))
    leaf = jax.ShapeDtypeStruct((n, param_bytes // 4), jnp.float32)
    sched = build_schedule(ExponentialTwoGraph(n))

    fn = jax.jit(shard_map(
        lambda v: C.neighbor_allreduce(v, sched, "bf", backend="xla"),
        mesh=mesh, in_specs=(P("bf"),), out_specs=P("bf"), check_vma=False))
    hlo = fn.lower(leaf).as_text()
    k = hlo.count("collective_permute") or hlo.count("collective-permute")
    # lowering text is StableHLO; count ops there, model bytes analytically
    # (each slot ships the full payload once)
    num_slots = sched.num_slots
    return {
        "mesh": n,
        "param_mib": round(param_bytes / 2**20, 1),
        "exp2_gossip": {
            "hops": num_slots,
            "bytes_per_chip": num_slots * param_bytes,
            "ops_in_program": k,
        },
        "one_peer_gossip": {"hops": 1, "bytes_per_chip": param_bytes},
        "ring_allreduce_model": {
            "hops": 2 * (n - 1),
            "bytes_per_chip": int(2 * param_bytes * (n - 1) / n),
        },
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--param-mib", type=float, default=97.66,
                    help="parameter payload per chip (default ResNet-50 f32)")
    ap.add_argument("--sizes", type=int, nargs="*",
                    default=[8, 16, 32, 64, 128])
    args = ap.parse_args()
    pbytes = int(args.param_mib * 2**20)

    print(f"{'n':>4} {'exp2 hops':>10} {'exp2 MiB':>9} {'1peer MiB':>10} "
          f"{'ring hops':>10} {'ring MiB':>9}", file=sys.stderr)
    for n in args.sizes:
        row = census(n, pbytes)
        g, o, r = (row["exp2_gossip"], row["one_peer_gossip"],
                   row["ring_allreduce_model"])
        print(f"{n:>4} {g['hops']:>10} {g['bytes_per_chip']/2**20:>9.0f} "
              f"{o['bytes_per_chip']/2**20:>10.0f} {r['hops']:>10} "
              f"{r['bytes_per_chip']/2**20:>9.0f}", file=sys.stderr)
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
