"""Cross-host (TCP/DCN) window deposit transport benchmark.

Measures the host leg the device profile cannot see (PROFILE §6): sustained
one-sided deposit throughput and per-round latency into a REMOTE process's
window table over loopback TCP, for a ResNet-50-sized parameter tree split
into per-leaf windows — the deposit shape of one async-dsgd gossip round
toward one out-neighbor.

Three variants, same byte stream:

- ``sync``       — the v1-wire-equivalent baseline: one blocking
                   request/response round-trip per leaf with v1's client
                   copy discipline (tobytes + frame join) — what every
                   dsgd round paid before this transport existed.
- ``pipelined``  — :class:`PipelinedRemoteWindow`: fire-and-forget
                   ``deposit_async`` per leaf, ONE batched frame + one ack
                   per round, bounded in-flight window, ``flush()`` fence
                   at the end of the run.
- ``pipelined_f32`` — pipelined + f32 wire codec (halves f64 bytes; the
                   compression leg of the DCN story).  ``--codec topk``
                   swaps in the top-k codec.

The server runs in a SEPARATE OS process (like production: the owner's
daemon thread receives while the owner computes), so client and server do
not share a GIL.  Round latency: for ``sync``, wall time per round; for
the pipelined variants, the send→ack latency of each round's batch (the
fence a round would pay if it fenced every round).

Run:  python benchmarks/window_transport_bench.py [--small]
Prints one JSON line (committed as BENCH_transport.json at the repo root).
No TPU, no jax required; rc=0 on any host.
"""

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

# ResNet-50-ish split: ~25.6M params across a few big conv/fc-scale leaves
# and many small bn/bias-scale ones — the mixture is what batching earns
# its keep on (small leaves are pure round-trip overhead when sync).
_RESNET50_LEAVES = ([2048 * 1024, 1024 * 1024 * 2, 2359296, 2359296,
                     1179648, 1179648, 589824, 589824, 262144, 262144]
                    + [65536] * 40 + [2048] * 60 + [512] * 50)
_SMALL_LEAVES = [65536] * 4 + [2048] * 8


_OWNER_CODE = """
import os, socket, struct, sys, threading
os.environ.setdefault('JAX_PLATFORMS', 'cpu')
os.environ['PALLAS_AXON_POOL_IPS'] = ''
import numpy as np
sys.path.insert(0, {repo!r})
from bluefog_tpu.runtime.async_windows import AsyncWindow, _fallback
from bluefog_tpu.runtime import native
from bluefog_tpu.runtime.window_server import WindowServer
sizes = {sizes!r}
wins = [AsyncWindow(f'tpb:{{i}}', 1, n, np.{dtype}) for i, n in enumerate(sizes)]
srv = WindowServer()
_, port = srv.start('127.0.0.1')

# v1-compat listener for the sync baseline: the deposit path of the
# PRE-pipelining server, copy discipline included (_recv_exact builds a
# bytes() of every payload before frombuffer) — what a v1 peer actually
# cost the owner per deposit.
_HDR = struct.Struct('<IBH'); _BODY = struct.Struct('<iBBq')
_STATUS = struct.Struct('<q')
_lib = native.load()

def _recv_exact(sock, n):
    buf = bytearray(n); view = memoryview(buf); got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError()
        got += r
    return bytes(buf)

def _v1_conn(sock):
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    dtypes = {{0: np.dtype(np.float32), 1: np.dtype(np.float64)}}
    try:
        while True:
            magic, op, name_len = _HDR.unpack(_recv_exact(sock, _HDR.size))
            name = _recv_exact(sock, name_len)
            slot, flags, dtype, n_elems = _BODY.unpack(
                _recv_exact(sock, _BODY.size))
            payload = _recv_exact(sock, n_elems * dtypes[dtype].itemsize)
            arr = np.frombuffer(payload, dtypes[dtype])
            if _lib is not None:
                rc = _lib.bf_win_deposit(name, slot, arr.ctypes.data,
                                         n_elems, flags & 1)
            else:
                rc = _fallback().deposit(name.decode(), slot, arr,
                                         bool(flags & 1))
            sock.sendall(_STATUS.pack(rc))
    except (ConnectionError, OSError):
        return

def _v1_listen(ls):
    while True:
        try:
            c, _ = ls.accept()
        except OSError:
            return
        threading.Thread(target=_v1_conn, args=(c,), daemon=True).start()

ls = socket.socket(); ls.bind(('127.0.0.1', 0)); ls.listen(64)
v1_port = ls.getsockname()[1]
threading.Thread(target=_v1_listen, args=(ls,), daemon=True).start()

print(f'PORT {{port}} {{v1_port}}', flush=True)
sys.stdin.readline()          # parent: all variants done
ls.close()
srv.stop()
for w in wins:
    w.free()
print('OWNER_OK', flush=True)
"""


def _percentile(xs, q):
    if not xs:
        return float("nan")
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return xs[i]


class _V1SyncClient:
    """The pre-pipelining wire, faithfully: one persistent connection per
    window handle, one BLOCKING request/response round-trip per deposit,
    and v1's client copy discipline — ``arr.tobytes()`` then a joined
    ``hdr + name + body + payload`` frame (two full-payload copies the v2
    clients eliminated).  Paired with the owner process's v1-compat
    listener, which reproduces the v1 server's copy discipline too
    (``_recv_exact`` materializes a ``bytes`` of every payload), so the
    baseline is the pre-pipelining path end to end."""

    def __init__(self, port, name):
        import socket as _socket

        from bluefog_tpu.runtime import window_server as ws

        self._ws = ws
        self._name_b = name.encode()
        self._sock = _socket.create_connection(("127.0.0.1", port),
                                               timeout=30)
        self._sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)

    def deposit(self, slot, arr, *, accumulate=True):
        ws = self._ws
        payload = arr.tobytes()  # v1 copy #1
        msg = (ws._HDR.pack(ws._MAGIC, ws._OP_DEPOSIT, len(self._name_b))
               + self._name_b
               + ws._BODY.pack(slot, 1 if accumulate else 0,
                               1 if arr.dtype == np.float64 else 0,
                               arr.size)
               + payload)       # v1 copy #2: the frame join
        self._sock.sendall(msg)
        buf = b""
        while len(buf) < 8:
            got = self._sock.recv(8 - len(buf))
            if not got:
                raise ConnectionError("server closed")
            buf += got
        (rc,) = ws._STATUS.unpack(buf)
        if rc < 0:
            raise RuntimeError(f"v1-style deposit failed ({rc})")
        return rc

    def close(self):
        self._sock.close()


def bench_sync(port, sizes, payloads, rounds, dtype):
    """The synchronous per-deposit baseline (v1-wire-equivalent): round
    latency and sustained throughput coincide, nothing overlaps
    anything."""
    rws = [_V1SyncClient(port, f"tpb:{i}") for i in range(len(sizes))]
    for rw, p in zip(rws, payloads):  # warmup (connections, buffers)
        rw.deposit(0, p, accumulate=True)
    lat = []
    t0 = time.perf_counter()
    for _ in range(rounds):
        r0 = time.perf_counter()
        for rw, p in zip(rws, payloads):
            rw.deposit(0, p, accumulate=True)
        lat.append(time.perf_counter() - r0)
    dt = time.perf_counter() - t0
    for rw in rws:
        rw.close()
    return dt, lat


def bench_pipelined(port, sizes, payloads, rounds, dtype, codec=None):
    """ONE :class:`DepositStream` to the peer: a round's leaves coalesce
    into batched multi-deposit frames (the per-peer progress-engine
    deployment shape).  Two phases: round LATENCY is measured honestly —
    a fence (``flush``) per round, so each sample is enqueue->applied —
    then sustained THROUGHPUT with the fence only at the end, which is
    how the dsgd loop actually runs (one fence per training run, not per
    round)."""
    from bluefog_tpu.runtime.window_server import DepositStream

    stream = DepositStream(("127.0.0.1", port), codec=codec,
                           max_in_flight=8)
    names = [f"tpb:{i}".encode() for i in range(len(sizes))]

    def one_round():
        for nm, p in zip(names, payloads):
            # copy=False: the bench payloads are immutable, so the wire
            # path is measured without the snapshot memcpy the reusing
            # dsgd loop pays
            stream.deposit_async(nm, 0, p, accumulate=True, copy=False)

    one_round()               # warmup (threads, buffers, cwnd)
    stream.flush(timeout_s=600)
    lat = []
    for _ in range(rounds):   # latency phase: fence every round
        r0 = time.perf_counter()
        one_round()
        stream.flush(timeout_s=600)
        lat.append(time.perf_counter() - r0)
    t0 = time.perf_counter()
    for _ in range(rounds):   # throughput phase: fence once at the end
        one_round()
    stream.flush(timeout_s=600)
    dt = time.perf_counter() - t0
    stream.close()
    return dt, lat


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=12)
    ap.add_argument("--trials", type=int, default=3,
                    help="trials per variant; the reported numbers are the "
                    "best trial (interference-minimal), all trials listed")
    ap.add_argument("--small", action="store_true",
                    help="tiny tree for CI smoke (seconds, not minutes)")
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "float64"])
    ap.add_argument("--codec", default="f32", choices=["f32", "topk"],
                    help="wire codec for the compressed variant")
    args = ap.parse_args()

    sizes = _SMALL_LEAVES if args.small else _RESNET50_LEAVES
    rounds = max(3, args.rounds // 3) if args.small else args.rounds
    dtype = np.dtype(args.dtype)
    rng = np.random.default_rng(0)
    payloads = [np.ascontiguousarray(rng.standard_normal(n), dtype)
                for n in sizes]
    dense_mb = sum(n * dtype.itemsize for n in sizes) / 1e6

    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PALLAS_AXON_POOL_IPS"] = ""
    owner = subprocess.Popen(
        [sys.executable, "-c", _OWNER_CODE.format(
            repo=repo, sizes=sizes, dtype=args.dtype)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, env=env, cwd=repo)
    try:
        port = v1_port = None
        for line in owner.stdout:
            if line.startswith("PORT "):
                _, a, b = line.split()
                port, v1_port = int(a), int(b)
                break
        assert port and v1_port, "owner never published its ports"

        # variants are INTERLEAVED per trial and the headline speedup is
        # the median of per-trial ratios: shared/throttled hosts drift by
        # 2-3x over tens of seconds, so only temporally adjacent runs
        # compare fairly.  Per-variant stats come from its best trial.
        bench_fns = [
            ("sync", lambda: bench_sync(
                v1_port, sizes, payloads, rounds, dtype)),
            ("pipelined", lambda: bench_pipelined(
                port, sizes, payloads, rounds, dtype)),
            (f"pipelined_{args.codec}", lambda: bench_pipelined(
                port, sizes, payloads, rounds, dtype, codec=args.codec)),
        ]
        trials = max(1, args.trials)
        runs = {name: [] for name, _ in bench_fns}
        for _ in range(trials):
            for name, fn in bench_fns:
                runs[name].append(fn())
        variants = {}
        for name, _ in bench_fns:
            dt, lat = min(runs[name], key=lambda r: r[0])
            variants[name] = {
                "MBps": round(dense_mb * rounds / dt, 1),
                "round_p50_ms": round(_percentile(lat, 0.50) * 1e3, 2),
                "round_p99_ms": round(_percentile(lat, 0.99) * 1e3, 2),
                "wall_s": round(dt, 3),
                "trial_MBps": [round(dense_mb * rounds / d, 1)
                               for d, _ in runs[name]],
            }
        ratios = sorted(s / p for (p, _), (s, _)
                        in zip(runs["pipelined"], runs["sync"]))
        owner.stdin.write("done\n")
        owner.stdin.flush()
        tail = owner.stdout.read()
        assert owner.wait(timeout=60) == 0 and "OWNER_OK" in tail, tail
    finally:
        if owner.poll() is None:
            owner.kill()
            owner.wait()

    speedup = ratios[len(ratios) // 2]  # median of per-trial ratios
    print(json.dumps({
        "metric": "window_transport_MBps",
        "sync_baseline": "v1 wire end to end: per-deposit blocking ack, "
                         "client tobytes + frame-join copies, server "
                         "recv-buffer bytes() copy",
        "tree": "small" if args.small else "resnet50",
        "leaves": len(sizes),
        "params": int(sum(sizes)),
        "dense_mb_per_round": round(dense_mb, 1),
        "rounds": rounds,
        "dtype": args.dtype,
        "codec": args.codec,
        "variants": variants,
        "trial_speedups": [round(r, 2) for r in ratios],
        "speedup_pipelined_vs_sync": round(speedup, 2),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
