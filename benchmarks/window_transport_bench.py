"""Cross-host (TCP/DCN) window deposit transport benchmark.

Measures the host leg the device profile cannot see (PROFILE §6): sustained
one-sided deposit throughput and per-round latency into a REMOTE process's
window table over loopback TCP, for a ResNet-50-sized parameter tree split
into per-leaf windows — the deposit shape of one async-dsgd gossip round
toward one out-neighbor.

Five deposit variants, same byte stream:

- ``sync``       — the v1-wire-equivalent baseline: one blocking
                   request/response round-trip per leaf with v1's client
                   copy discipline (tobytes + frame join) — what every
                   dsgd round paid before this transport existed.
- ``pipelined``  — :class:`PipelinedRemoteWindow`: fire-and-forget
                   ``deposit_async`` per leaf, ONE batched frame + one ack
                   per round, bounded in-flight window, ``flush()`` fence
                   at the end of the run.
- ``pipelined_f32`` — pipelined + f32 wire codec (halves f64 bytes; the
                   compression leg of the DCN story).  ``--codec topk``
                   swaps in the top-k codec.
- ``shm``        — same stream, ``shm=True``: the owner is co-located, so
                   deposits route through the named-shm window table and
                   the loopback TCP hop disappears (skipped when the
                   native runtime is unavailable).
- ``striped``    — :class:`StripedDepositStream`: N parallel connections
                   to the one peer, window names spread by
                   :func:`stripe_of` — N senders and N server-side
                   appliers instead of one of each (``--stripes``).

Plus a compute/gossip **overlap** A/B (``--no-overlap`` to skip): a real
3-rank mp-dsgd run, traced, serial vs ``overlap=True`` — the tracer's
per-round ``overlap`` field is the measured hidden-fold fraction, and the
before/after :func:`bluefog_tpu.tracing.analyze.analyze` reports are the
PROFILE §6 evidence (``--profiles DIR`` writes them as
``TRACE_transport_before.json`` / ``TRACE_transport_after.json``).

The committed ``BENCH_transport.json`` carries ``*_ok`` gate booleans
(pipelined/shm/striped beat their single-stream baselines on the median
of interleaved per-trial ratios; measured overlap fraction > 0), which
``bffleet-tpu --check`` and the tier-1 suite verify like every other
committed bench trajectory.

The server runs in a SEPARATE OS process (like production: the owner's
daemon thread receives while the owner computes), so client and server do
not share a GIL.  Round latency: for ``sync``, wall time per round; for
the pipelined variants, the send→ack latency of each round's batch (the
fence a round would pay if it fenced every round).

Run:  python benchmarks/window_transport_bench.py [--small]
Prints one JSON line (committed as BENCH_transport.json at the repo root).
No TPU, no jax required; rc=0 on any host.
"""

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

# ResNet-50-ish split: ~25.6M params across a few big conv/fc-scale leaves
# and many small bn/bias-scale ones — the mixture is what batching earns
# its keep on (small leaves are pure round-trip overhead when sync).
_RESNET50_LEAVES = ([2048 * 1024, 1024 * 1024 * 2, 2359296, 2359296,
                     1179648, 1179648, 589824, 589824, 262144, 262144]
                    + [65536] * 40 + [2048] * 60 + [512] * 50)
_SMALL_LEAVES = [65536] * 4 + [2048] * 8


_OWNER_CODE = """
import os, socket, struct, sys, threading
os.environ.setdefault('JAX_PLATFORMS', 'cpu')
os.environ['PALLAS_AXON_POOL_IPS'] = ''
import numpy as np
sys.path.insert(0, {repo!r})
from bluefog_tpu.runtime.async_windows import (AsyncWindow, _fallback,
                                               shm_unlink_window)
from bluefog_tpu.runtime import native
from bluefog_tpu.runtime.window_server import WindowServer
sizes = {sizes!r}
# shm-backed windows when the native runtime allows: the same window
# table serves both the TCP variants (server-side apply lands in shm)
# and the shm fast-path variant (client-side apply, no wire)
shm_ok = native.load() is not None
if shm_ok:
    for i in range(len(sizes)):
        shm_unlink_window(f'tpb:{{i}}')
wins = [AsyncWindow(f'tpb:{{i}}', 1, n, np.{dtype}, shm=shm_ok)
        for i, n in enumerate(sizes)]
srv = WindowServer()
_, port = srv.start('127.0.0.1')

# v1-compat listener for the sync baseline: the deposit path of the
# PRE-pipelining server, copy discipline included (_recv_exact builds a
# bytes() of every payload before frombuffer) — what a v1 peer actually
# cost the owner per deposit.
_HDR = struct.Struct('<IBH'); _BODY = struct.Struct('<iBBq')
_STATUS = struct.Struct('<q')
_lib = native.load()

def _recv_exact(sock, n):
    buf = bytearray(n); view = memoryview(buf); got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError()
        got += r
    return bytes(buf)

def _v1_conn(sock):
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    dtypes = {{0: np.dtype(np.float32), 1: np.dtype(np.float64)}}
    try:
        while True:
            magic, op, name_len = _HDR.unpack(_recv_exact(sock, _HDR.size))
            name = _recv_exact(sock, name_len)
            slot, flags, dtype, n_elems = _BODY.unpack(
                _recv_exact(sock, _BODY.size))
            payload = _recv_exact(sock, n_elems * dtypes[dtype].itemsize)
            arr = np.frombuffer(payload, dtypes[dtype])
            if _lib is not None:
                rc = _lib.bf_win_deposit(name, slot, arr.ctypes.data,
                                         n_elems, flags & 1)
            else:
                rc = _fallback().deposit(name.decode(), slot, arr,
                                         bool(flags & 1))
            sock.sendall(_STATUS.pack(rc))
    except (ConnectionError, OSError):
        return

def _v1_listen(ls):
    while True:
        try:
            c, _ = ls.accept()
        except OSError:
            return
        threading.Thread(target=_v1_conn, args=(c,), daemon=True).start()

ls = socket.socket(); ls.bind(('127.0.0.1', 0)); ls.listen(64)
v1_port = ls.getsockname()[1]
threading.Thread(target=_v1_listen, args=(ls,), daemon=True).start()

print(f'PORT {{port}} {{v1_port}} {{int(shm_ok)}}', flush=True)
sys.stdin.readline()          # parent: all variants done
ls.close()
srv.stop()
for w in wins:
    w.free()
print('OWNER_OK', flush=True)
"""


def _percentile(xs, q):
    if not xs:
        return float("nan")
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return xs[i]


class _V1SyncClient:
    """The pre-pipelining wire, faithfully: one persistent connection per
    window handle, one BLOCKING request/response round-trip per deposit,
    and v1's client copy discipline — ``arr.tobytes()`` then a joined
    ``hdr + name + body + payload`` frame (two full-payload copies the v2
    clients eliminated).  Paired with the owner process's v1-compat
    listener, which reproduces the v1 server's copy discipline too
    (``_recv_exact`` materializes a ``bytes`` of every payload), so the
    baseline is the pre-pipelining path end to end."""

    def __init__(self, port, name):
        import socket as _socket

        from bluefog_tpu.runtime import window_server as ws

        self._ws = ws
        self._name_b = name.encode()
        self._sock = _socket.create_connection(("127.0.0.1", port),
                                               timeout=30)
        self._sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)

    def deposit(self, slot, arr, *, accumulate=True):
        ws = self._ws
        payload = arr.tobytes()  # v1 copy #1
        msg = (ws._HDR.pack(ws._MAGIC, ws._OP_DEPOSIT, len(self._name_b))
               + self._name_b
               + ws._BODY.pack(slot, 1 if accumulate else 0,
                               1 if arr.dtype == np.float64 else 0,
                               arr.size)
               + payload)       # v1 copy #2: the frame join
        self._sock.sendall(msg)
        buf = b""
        while len(buf) < 8:
            got = self._sock.recv(8 - len(buf))
            if not got:
                raise ConnectionError("server closed")
            buf += got
        (rc,) = ws._STATUS.unpack(buf)
        if rc < 0:
            raise RuntimeError(f"v1-style deposit failed ({rc})")
        return rc

    def close(self):
        self._sock.close()


def bench_sync(port, sizes, payloads, rounds, dtype):
    """The synchronous per-deposit baseline (v1-wire-equivalent): round
    latency and sustained throughput coincide, nothing overlaps
    anything."""
    rws = [_V1SyncClient(port, f"tpb:{i}") for i in range(len(sizes))]
    for rw, p in zip(rws, payloads):  # warmup (connections, buffers)
        rw.deposit(0, p, accumulate=True)
    lat = []
    t0 = time.perf_counter()
    for _ in range(rounds):
        r0 = time.perf_counter()
        for rw, p in zip(rws, payloads):
            rw.deposit(0, p, accumulate=True)
        lat.append(time.perf_counter() - r0)
    dt = time.perf_counter() - t0
    for rw in rws:
        rw.close()
    return dt, lat


def bench_pipelined(port, sizes, payloads, rounds, dtype, codec=None,
                    shm=False):
    """ONE :class:`DepositStream` to the peer: a round's leaves coalesce
    into batched multi-deposit frames (the per-peer progress-engine
    deployment shape).  Two phases: round LATENCY is measured honestly —
    a fence (``flush``) per round, so each sample is enqueue->applied —
    then sustained THROUGHPUT with the fence only at the end, which is
    how the dsgd loop actually runs (one fence per training run, not per
    round)."""
    from bluefog_tpu.runtime.window_server import DepositStream

    stream = DepositStream(("127.0.0.1", port), codec=codec,
                           max_in_flight=8, shm=shm)
    names = [f"tpb:{i}".encode() for i in range(len(sizes))]

    def one_round():
        for nm, p in zip(names, payloads):
            # copy=False: the bench payloads are immutable, so the wire
            # path is measured without the snapshot memcpy the reusing
            # dsgd loop pays
            stream.deposit_async(nm, 0, p, accumulate=True, copy=False)

    one_round()               # warmup (threads, buffers, cwnd)
    stream.flush(timeout_s=600)
    lat = []
    for _ in range(rounds):   # latency phase: fence every round
        r0 = time.perf_counter()
        one_round()
        stream.flush(timeout_s=600)
        lat.append(time.perf_counter() - r0)
    t0 = time.perf_counter()
    for _ in range(rounds):   # throughput phase: fence once at the end
        one_round()
    stream.flush(timeout_s=600)
    dt = time.perf_counter() - t0
    if shm:
        # the variant must measure what it claims: every deposit after
        # warmup routed through the shm table, none fell back to TCP
        assert stream.shm_deposits > 0, "shm fast path never engaged"
    stream.close()
    return dt, lat


def bench_striped(port, sizes, payloads, rounds, dtype, n_stripes):
    """:class:`StripedDepositStream`: the line-rate DCN shape — N
    parallel connections to the one peer, window names spread across
    stripes by :func:`stripe_of`, one fence across all stripes at the
    end (same audit discipline as one stream)."""
    from bluefog_tpu.runtime.window_server import StripedDepositStream

    stream = StripedDepositStream(("127.0.0.1", port),
                                  n_stripes=n_stripes,
                                  max_in_flight=8)
    names = [f"tpb:{i}".encode() for i in range(len(sizes))]

    def one_round():
        for nm, p in zip(names, payloads):
            stream.deposit_async(nm, 0, p, accumulate=True, copy=False)

    one_round()               # warmup (threads, buffers, cwnd)
    stream.flush(timeout_s=600)
    lat = []
    for _ in range(rounds):   # latency phase: fence every round
        r0 = time.perf_counter()
        one_round()
        stream.flush(timeout_s=600)
        lat.append(time.perf_counter() - r0)
    t0 = time.perf_counter()
    for _ in range(rounds):   # throughput phase: fence once at the end
        one_round()
    stream.flush(timeout_s=600)
    dt = time.perf_counter() - t0
    stream.close()
    return dt, lat


_AB_WORKER_CODE = """
import os, sys
os.environ['JAX_PLATFORMS'] = 'cpu'
os.environ['PALLAS_AXON_POOL_IPS'] = ''
os.environ['BLUEFOG_TPU_TRACE'] = {tdir!r}
sys.path.insert(0, {repo!r})
import numpy as np
from bluefog_tpu.runtime.async_windows import FileBarrier, run_async_dsgd_rank
from bluefog_tpu.topology.graphs import RingGraph

def lg(rank, step, z):
    acc = z
    for _ in range({spin}):          # compute leg: the overlap's cover
        acc = acc * 0.999 + z * 0.001
    return float(np.sum(acc ** 2)), 2 * acc

rep = run_async_dsgd_rank(
    RingGraph(3), {rank}, np.ones({d}), lg,
    barrier=FileBarrier({bdir!r}, 3, {rank}), duration_s=120.0,
    stop_after_steps={steps}, transport='tcp', name={name!r},
    stream_options={stream_options!r}, overlap={overlap!r})
print('MASS', rep.total_mass if rep is not None else None, flush=True)
"""


def bench_overlap_ab(repo, env, *, small, profiles_dir=None):
    """Compute/gossip overlap, measured on the real thing: a 3-rank
    mp-dsgd ring over loopback TCP, traced, run twice — serial
    (``overlap=False``, plain single-stream TCP: the BEFORE profile)
    and with the full hot path on (``overlap=True`` + shm fast path +
    2 stripes: the AFTER profile).  The tracer's per-round ``overlap``
    field is the measured hidden-fold fraction (exactly 0 in the
    before run); the two :func:`~bluefog_tpu.tracing.analyze.analyze`
    reports are the PROFILE §6 critical-path evidence."""
    import shutil
    import tempfile

    from bluefog_tpu.tracing.analyze import analyze

    d = 4096 if small else 65536
    steps = 12 if small else 30
    spin = 4 if small else 12
    out = {}
    reports = {}
    for tag, overlap, opts in (
            ("before", False, {}),
            ("after", True, {"shm": True, "stripes": 2})):
        tdir = tempfile.mkdtemp(prefix=f"tpb_trace_{tag}_")
        bdir = tempfile.mkdtemp(prefix=f"tpb_bar_{tag}_")
        try:
            procs = [subprocess.Popen(
                [sys.executable, "-c", _AB_WORKER_CODE.format(
                    tdir=tdir, repo=repo, spin=spin, rank=r, d=d,
                    bdir=bdir, steps=steps, name=f"tpov_{tag}",
                    stream_options=opts, overlap=overlap)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env, cwd=repo) for r in range(3)]
            outs = [p.communicate(timeout=300)[0] for p in procs]
            assert all(p.returncode == 0 for p in procs), outs
            assert any("MASS 3.0" in o or "MASS 2.99" in o
                       for o in outs), outs
            rep = analyze(tdir)
            reports[tag] = rep
            rr = rep["rounds"]["per_rank"]
            ovs = [st["overlap_mean"] for st in rr.values()
                   if "overlap_mean" in st]
            out[tag] = {
                "round_mean_ms": round(1e3 * sum(
                    st["round_mean_s"] for st in rr.values())
                    / max(1, len(rr)), 2),
                "overlap_mean": round(sum(ovs) / len(ovs), 4) if ovs
                                else 0.0,
                "gating_edge": rep["critical_path"].get("gating_edge"),
                "dominant_phase":
                    rep["critical_path"].get("dominant_phase"),
            }
        finally:
            if profiles_dir and tag in reports:
                with open(os.path.join(
                        profiles_dir,
                        f"TRACE_transport_{tag}.json"), "w") as f:
                    json.dump(reports[tag], f, indent=1, sort_keys=True)
            shutil.rmtree(tdir, ignore_errors=True)
            shutil.rmtree(bdir, ignore_errors=True)
    out["overlap_ok"] = out["after"]["overlap_mean"] > 0.0
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=12)
    ap.add_argument("--trials", type=int, default=3,
                    help="trials per variant; the reported numbers are the "
                    "best trial (interference-minimal), all trials listed")
    ap.add_argument("--small", action="store_true",
                    help="tiny tree for CI smoke (seconds, not minutes)")
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "float64"])
    ap.add_argument("--codec", default="f32", choices=["f32", "topk"],
                    help="wire codec for the compressed variant")
    ap.add_argument("--stripes", type=int, default=2,
                    help="stripe count for the striped variant (the "
                    "autotuner's first widening step; raise on multi-core "
                    "DCN hosts where parallel appliers pay off)")
    ap.add_argument("--no-overlap", action="store_true",
                    help="skip the traced compute/gossip overlap A/B")
    ap.add_argument("--profiles", default=None, metavar="DIR",
                    help="write TRACE_transport_{before,after}.json "
                    "(full bftrace analyze reports) into DIR")
    args = ap.parse_args()

    sizes = _SMALL_LEAVES if args.small else _RESNET50_LEAVES
    rounds = max(3, args.rounds // 3) if args.small else args.rounds
    dtype = np.dtype(args.dtype)
    rng = np.random.default_rng(0)
    payloads = [np.ascontiguousarray(rng.standard_normal(n), dtype)
                for n in sizes]
    dense_mb = sum(n * dtype.itemsize for n in sizes) / 1e6

    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PALLAS_AXON_POOL_IPS"] = ""
    owner = subprocess.Popen(
        [sys.executable, "-c", _OWNER_CODE.format(
            repo=repo, sizes=sizes, dtype=args.dtype)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, env=env, cwd=repo)
    try:
        port = v1_port = None
        shm_capable = False
        for line in owner.stdout:
            if line.startswith("PORT "):
                _, a, b, c = line.split()
                port, v1_port = int(a), int(b)
                shm_capable = bool(int(c))
                break
        assert port and v1_port, "owner never published its ports"

        # variants are INTERLEAVED per trial and the headline speedup is
        # the median of per-trial ratios: shared/throttled hosts drift by
        # 2-3x over tens of seconds, so only temporally adjacent runs
        # compare fairly.  Per-variant stats come from its best trial.
        bench_fns = [
            ("sync", lambda: bench_sync(
                v1_port, sizes, payloads, rounds, dtype)),
            ("pipelined", lambda: bench_pipelined(
                port, sizes, payloads, rounds, dtype)),
            (f"pipelined_{args.codec}", lambda: bench_pipelined(
                port, sizes, payloads, rounds, dtype, codec=args.codec)),
            ("striped", lambda: bench_striped(
                port, sizes, payloads, rounds, dtype, args.stripes)),
        ]
        if shm_capable:
            bench_fns.append(("shm", lambda: bench_pipelined(
                port, sizes, payloads, rounds, dtype, shm=True)))
        trials = max(1, args.trials)
        runs = {name: [] for name, _ in bench_fns}
        for _ in range(trials):
            for name, fn in bench_fns:
                runs[name].append(fn())
        variants = {}
        for name, _ in bench_fns:
            dt, lat = min(runs[name], key=lambda r: r[0])
            variants[name] = {
                "MBps": round(dense_mb * rounds / dt, 1),
                "round_p50_ms": round(_percentile(lat, 0.50) * 1e3, 2),
                "round_p99_ms": round(_percentile(lat, 0.99) * 1e3, 2),
                "wall_s": round(dt, 3),
                "trial_MBps": [round(dense_mb * rounds / d, 1)
                               for d, _ in runs[name]],
            }

        def _median_ratio(fast, slow):
            # per-trial ratios of temporally adjacent runs (see above)
            rs = sorted(s / f for (f, _), (s, _)
                        in zip(runs[fast], runs[slow]))
            return rs, rs[len(rs) // 2]

        ratios, speedup = _median_ratio("pipelined", "sync")
        _, striped_speedup = _median_ratio("striped", "pipelined")
        shm_speedup = None
        if shm_capable:
            _, shm_speedup = _median_ratio("shm", "pipelined")
        owner.stdin.write("done\n")
        owner.stdin.flush()
        tail = owner.stdout.read()
        assert owner.wait(timeout=60) == 0 and "OWNER_OK" in tail, tail
    finally:
        if owner.poll() is None:
            owner.kill()
            owner.wait()

    doc = {
        "metric": "window_transport_MBps",
        "sync_baseline": "v1 wire end to end: per-deposit blocking ack, "
                         "client tobytes + frame-join copies, server "
                         "recv-buffer bytes() copy",
        "tree": "small" if args.small else "resnet50",
        "leaves": len(sizes),
        "params": int(sum(sizes)),
        "dense_mb_per_round": round(dense_mb, 1),
        "rounds": rounds,
        "dtype": args.dtype,
        "codec": args.codec,
        "stripes": args.stripes,
        "variants": variants,
        "trial_speedups": [round(r, 2) for r in ratios],
        "speedup_pipelined_vs_sync": round(speedup, 2),
        "pipelined_ok": speedup > 1.0,
        "speedup_striped_vs_pipelined": round(striped_speedup, 2),
        "striped_ok": striped_speedup > 1.0,
    }
    if shm_speedup is not None:
        doc["speedup_shm_vs_tcp"] = round(shm_speedup, 2)
        doc["shm_ok"] = shm_speedup > 1.0
    if not args.no_overlap:
        repo_env = dict(env)
        doc["overlap"] = bench_overlap_ab(
            repo, repo_env, small=args.small,
            profiles_dir=args.profiles)
    print(json.dumps(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
