"""Flash-attention micro-benchmark on a single chip: Pallas fused kernel
vs the dense softmax path, over sequence length.

This is the single-chip half of the long-context story (the multi-chip half
— ring/zigzag sequence parallelism — is `ring_attention_bench.py`, which
needs a mesh).  It measures the kernel the model layer's ``backend='auto'``
opts into (``ops/ring_attention.py::local_attention``): forward + backward
through a jitted loss, bf16, causal, shapes eligible for the fused kernel.

Timing discipline mirrors bench.py (PROFILE.md §1): through this
environment's relay the wall clock is corrupt at microbenchmark scale (a
first cut of this script measured a *decreasing* dense time as T scaled
16x — sub-physical), so each (T, backend) variant captures its own
``jax.profiler`` trace and the headline per-step time is the device's own
op-time total divided by the traced step count.  Wall clock is reported
alongside with a ``wall_plausible`` flag, same contract as bench.py.

Run (real chip):      python benchmarks/flash_attention_bench.py
Run (CPU, dense only): JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
                       python benchmarks/flash_attention_bench.py --dense-only

Prints one JSON line: per-seq-len step times and ``flash_speedup``.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp

from bluefog_tpu.ops.ring_attention import local_attention


from benchmarks._trace_util import timed_trace as step_time  # noqa: E402


def make_step(backend, causal=True, flash_block=None):
    @jax.jit
    def step(q, k, v):
        def loss(q, k, v):
            o = local_attention(q, k, v, causal=causal, backend=backend,
                                flash_block=flash_block)
            return jnp.sum(o.astype(jnp.float32) ** 2)

        l, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
        return l, grads

    return step


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--seq-lens", type=int, nargs="+",
                    default=[1024, 2048, 4096, 8192])
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--dense-only", action="store_true")
    ap.add_argument("--tune", action="store_true",
                    help="sweep flash kernel tile edges (128..1024) per seq "
                         "len instead of the dense/flash comparison")
    args = ap.parse_args()

    if args.tune:
        rows = []
        for t in args.seq_lens:
            shape = (args.batch, t, args.heads, args.head_dim)
            ks = jax.random.split(jax.random.PRNGKey(0), 3)
            q, k, v = (jax.random.normal(kk, shape, jnp.bfloat16)
                       for kk in ks)
            row = {"seq_len": t}
            for blk in (128, 256, 512, 1024):
                if blk > t:
                    continue
                try:
                    wall_ms, trace_ms = step_time(
                        make_step("flash", flash_block=blk), (q, k, v),
                        args.steps)
                    row[f"block{blk}_ms"] = round(trace_ms or wall_ms, 3)
                    if trace_ms is None:
                        # same contract as the main path: a relay wall clock
                        # with no device trace behind it is not a result
                        row[f"block{blk}_timing_source"] = (
                            "wall_clock_uncorroborated")
                except Exception as e:  # noqa: BLE001
                    row[f"block{blk}_error"] = (
                        f"{type(e).__name__}: {str(e)[:100]}")
            rows.append(row)
            print(f"tune: T={t}: {row}", file=sys.stderr)
        print(json.dumps({"metric": "flash_block_tune", "rows": rows}))
        return

    dev = jax.devices()[0]
    rows = []
    for t in args.seq_lens:
        shape = (args.batch, t, args.heads, args.head_dim)
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(kk, shape, jnp.bfloat16) for kk in ks)
        row = {"seq_len": t}
        # dense first: at long T it OOMs before flash does — record that
        # honestly instead of dying
        for name, backend in [("dense", "dense")] + (
                [] if args.dense_only else [("flash", "flash")]):
            try:
                wall_ms, trace_ms = step_time(
                    make_step(backend), (q, k, v), args.steps)
            except Exception as e:  # noqa: BLE001 — expected O(T^2) OOM path
                row[f"{name}_error"] = f"{type(e).__name__}: {str(e)[:120]}"
                continue
            row[f"{name}_wall_ms"] = round(wall_ms, 3)
            if trace_ms:
                # device op time is the oracle; a wall clock faster than it
                # is relay corruption (bench.py contract)
                row[f"{name}_ms"] = round(trace_ms, 3)
                row[f"{name}_wall_plausible"] = wall_ms >= 0.9 * trace_ms
            else:
                row[f"{name}_ms"] = round(wall_ms, 3)
                row[f"{name}_timing_source"] = "wall_clock_uncorroborated"
        if "dense_ms" in row and "flash_ms" in row and row["flash_ms"] > 0:
            row["flash_speedup"] = round(row["dense_ms"] / row["flash_ms"], 3)
        rows.append(row)
        print(f"bench: T={t}: {row}", file=sys.stderr)

    speedups = [r["flash_speedup"] for r in rows if "flash_speedup" in r]
    out = {
        "metric": "flash_attention_fwd_bwd",
        "device_kind": getattr(dev, "device_kind", str(dev)),
        "batch": args.batch, "heads": args.heads, "head_dim": args.head_dim,
        "causal": True, "dtype": "bfloat16",
        "rows": rows,
        "flash_speedup_max": max(speedups) if speedups else None,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
