"""Shared device-trace timing for the single-chip micro-benchmarks.

Through this environment's relay the host wall clock is unreliable at
microbenchmark scale (PROFILE.md §1), so every benchmark times a
``jax.profiler`` trace window and takes the device's own op-time total as
the oracle (`profile_summary.device_op_totals`, the same parser bench.py
uses for its corroboration check).
"""

import importlib.util
import os
import tempfile
import time

import jax


def trace_step_ms(trace_dir, steps):
    """Per-step per-chip device op time (ms), or None when the trace is
    missing/host-only (CPU runs)."""
    summary_py = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "profile_summary.py")
    try:
        spec = importlib.util.spec_from_file_location(
            "bftpu_profile_summary", summary_py)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        (_path, by_op, total_us, n_lanes,
         device_events) = mod.device_op_totals(trace_dir)
    except (Exception, SystemExit):
        return None
    if not by_op or not device_events or n_lanes <= 0:
        return None
    return total_us / 1e3 / steps / n_lanes


def timed_trace(fn, args_, steps, trace_steps: int = 3):
    """Time ``steps`` untraced calls, then trace ``trace_steps`` more.

    bench.py's discipline: the wall clock is measured WITHOUT the profiler
    running (host-side tracing overhead would land in it), and a separate
    short traced window supplies the device op-time oracle.  Returns
    ``(wall_ms_per_step, trace_ms_per_step | None)``; callers headline the
    trace figure and report the wall clock alongside (plausible iff
    wall >= 0.9 x trace).  Compile happens outside both clocks.
    """
    jax.tree_util.tree_leaves(fn(*args_))[0].block_until_ready()
    t0 = time.perf_counter()
    out = None
    for _ in range(steps):
        out = fn(*args_)
    jax.tree_util.tree_leaves(out)[0].block_until_ready()
    wall_ms = (time.perf_counter() - t0) / steps * 1e3
    trace_dir = tempfile.mkdtemp(prefix="bftpu_trace_")
    with jax.profiler.trace(trace_dir):
        for _ in range(trace_steps):
            out = fn(*args_)
        jax.tree_util.tree_leaves(out)[0].block_until_ready()
    return wall_ms, trace_step_ms(trace_dir, trace_steps)
