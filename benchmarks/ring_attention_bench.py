"""Ring-attention micro-benchmark: causal block skipping vs full work.

The causal ring dispatches each arriving KV block through a ``lax.switch``
(skip / unmasked / diagonal-masked) so strictly-future blocks execute nothing
— at n shards that is ~(n-1)/2n of the block work skipped (≈ half for large
n).  This script measures it: wall-clock per ring-attention forward, causal
vs non-causal, on whatever devices are visible (8-virtual-CPU mesh or a TPU
slice).

Run (CPU mesh):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  PALLAS_AXON_POOL_IPS= python benchmarks/ring_attention_bench.py

Prints one JSON line; `causal_speedup` is the headline (→ ~2x as n grows).
"""

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from bluefog_tpu.ops.ring_attention import ring_attention
from bluefog_tpu.parallel.api import shard_map


def bench_one(mesh, causal, args, layout="contiguous"):
    n = len(mesh.devices.flat)
    fn = jax.jit(shard_map(
        functools.partial(ring_attention, axis_name="sp", causal=causal,
                          kv_tile=args.kv_tile, layout=layout),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"),
        check_vma=False,
    ))
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    shape = (args.batch, n * args.t_local, args.heads, args.head_dim)
    q, k, v = (jax.random.normal(kk, shape, jnp.float32) for kk in ks)
    fn(q, k, v).block_until_ready()  # compile
    t0 = time.perf_counter()
    for _ in range(args.steps):
        out = fn(q, k, v)
    out.block_until_ready()
    return (time.perf_counter() - t0) / args.steps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--t-local", type=int, default=512)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--kv-tile", type=int, default=512)
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args()

    devs = jax.devices()
    mesh = Mesh(np.array(devs), ("sp",))
    n = len(devs)

    dt_full = bench_one(mesh, False, args)
    dt_causal = bench_one(mesh, True, args)
    # zigzag: the load-balanced causal layout — every rank folds exactly 2
    # half-chunks/step, so on a lock-stepped slice the FLOP saving is
    # wall-clock; input layout conversion is outside the timed region (it is
    # a one-time data layout choice, not per-step work)
    dt_zigzag = bench_one(mesh, True, args, layout="zigzag")
    print(json.dumps({
        "metric": "ring_attention_step_ms",
        "n_shards": n,
        "t_global": n * args.t_local,
        "full_ms": round(dt_full * 1e3, 2),
        "causal_ms": round(dt_causal * 1e3, 2),
        "causal_zigzag_ms": round(dt_zigzag * 1e3, 2),
        "causal_speedup": round(dt_full / dt_causal, 3),
        "zigzag_speedup": round(dt_full / dt_zigzag, 3),
        "expected_flop_ratio": round(2 * n / (n + 1), 3),
    }))


if __name__ == "__main__":
    main()
