"""Benchmark harness — north-star metric (BASELINE.md): ResNet-50
decentralized-SGD **images/sec/chip**, plus an honest MFU.

Runs the full decentralized train step (fwd + bwd + gossip + SGD update) as
one jitted shard_map program over all visible devices and reports throughput
per chip.  On the driver's single real TPU chip the gossip degenerates to the
identity (size-1 mesh) — the compute path is the genuine benchmark; on a pod
the same program gossips over ICI.

Default mode **sweeps the per-chip batch** (128 → 2048, doubling; an OOM ends
the sweep upward) and reports the best-throughput point; ``--batch N`` pins a
single batch instead (halve-on-OOM downward so the driver always gets a
number).

Prints ONE JSON line:
  {"metric": "resnet50_images_per_sec_per_chip", "value": N,
   "unit": "images/sec/chip", "vs_baseline": R, "mfu": M, ...}

- ``mfu``: achieved model FLOP/s divided by the **measured** bf16 matmul peak
  of this chip (chained 8192^2 matmuls — the MXU roofline as this machine
  actually delivers it, not a spec-sheet constant).  Model FLOPs come from
  XLA's own cost analysis of the compiled step when available, else the
  standard analytic ResNet-50 estimate (3x forward, 4.09 GFLOP/img fwd).
- ``vs_baseline``: secondary field, ratio against the reference's per-GPU
  ResNet-50 throughput on V100 (BASELINE.md records no machine-readable
  number from the reference; 360 img/s/V100 is the standard fp16 figure for
  the 128xV100-era stack the reference paper benchmarked on).

``--profile DIR`` additionally captures a jax.profiler trace of a few steps
at the chosen batch (view with Perfetto / TensorBoard; see PROFILE.md).
"""

import argparse
import json
import os
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

import bluefog_tpu as bf
from bluefog_tpu.models import ResNet50
from bluefog_tpu.optim import DistributedNeighborAllreduceOptimizer
from bluefog_tpu.parallel.api import shard_map
from bluefog_tpu.topology import ExponentialTwoGraph

V100_BASELINE_IMG_PER_SEC = 360.0
# Steps recorded inside the jax.profiler trace window (and the divisor that
# turns the trace's total device op time into a per-step figure).
PROFILE_STEPS = 3
# Standard analytic ResNet-50 cost at 224x224: ~4.09 GFLOP forward per image,
# training step ~= 3x forward (fwd + grad wrt activations + grad wrt weights).
RESNET50_TRAIN_FLOPS_PER_IMG_224 = 3 * 4.09e9

# Last-good results cache: written after every successful TPU run, emitted
# with "stale": true when the TPU relay refuses device init (degraded mode)
# — a capture must never end with *nothing* (VERDICT r3 missing #2).
# BFTPU_BENCH_CACHE overrides the location (tests).
CACHE_PATH = os.environ.get(
    "BFTPU_BENCH_CACHE",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "BENCH_CACHE.json"))

# Nominal public spec sheets (bf16 dense peak TFLOP/s, HBM GB/s) keyed by
# device_kind substring — the cross-check for the measured peak.  The relay
# has produced non-physical measured peaks (58 -> ~1000 PFLOP/s round to
# round on one chip, PROFILE.md §2); numbers derived from an implausible
# denominator are flagged, not silently reported.
NOMINAL_SPECS = {
    "v6 lite": (918.0, 1640.0), "v6e": (918.0, 1640.0),
    "v5 lite": (197.0, 819.0), "v5e": (197.0, 819.0),
    "v5p": (459.0, 2765.0),
    "v4": (275.0, 1228.0),
    "v3": (123.0, 900.0),
    "v2": (46.0, 700.0),
}


def nominal_spec(devices):
    """(bf16 peak TFLOP/s, HBM GB/s) from the public spec sheet for this
    chip, or (None, None) when the device kind is unrecognized."""
    kind = getattr(devices[0], "device_kind", "").lower()
    for key in sorted(NOMINAL_SPECS, key=len, reverse=True):
        if key in kind:
            return NOMINAL_SPECS[key]
    return None, None


def measure_peak_flops(steps: int = 8, chain: int = 32, n: int = 8192) -> float:
    """Measured bf16 matmul roofline of one chip: FLOP/s sustained by a
    chain of (n,n)@(n,n) matmuls (each iteration depends on the previous, so
    nothing folds away).  This is the denominator of ``mfu``."""
    x = jax.random.normal(jax.random.PRNGKey(0), (n, n)).astype(jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(1), (n, n)).astype(jnp.bfloat16)

    @jax.jit
    def run_chain(x, w):
        return lax.fori_loop(0, chain, lambda _, z: z @ w, x)

    run_chain(x, w).block_until_ready()  # compile
    t0 = time.perf_counter()
    for _ in range(steps):
        out = run_chain(x, w)
    out.block_until_ready()
    dt = time.perf_counter() - t0
    return 2.0 * n * n * n * chain * steps / dt


def _cost_flops(compiled) -> float:
    """Per-invocation FLOPs of a compiled executable per XLA's cost
    analysis; 0.0 when the backend doesn't expose one.  Under SPMD this is
    the **per-device** module's count (batch images worth of work)."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return float(ca.get("flops", 0.0))
    except Exception:
        return 0.0


def run(args, batch: int):
    """One full measurement at the given per-chip batch.

    Returns ``(img_per_sec_per_chip, flops_per_step_per_chip)``; the FLOP
    count is XLA's for one device's share of the step (0.0 if unavailable).
    """
    n = len(jax.devices())
    ctx = bf.get_context()

    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16,
                     stem=getattr(args, "stem", "conv"))
    opt = DistributedNeighborAllreduceOptimizer(
        optax.sgd(0.1, momentum=0.9), topology=ctx.schedule,
        axis_name=ctx.axis_name, atc=False, backend=args.backend,
    )

    rng = jax.random.PRNGKey(0)
    x0 = jnp.zeros((batch, args.image_size, args.image_size, 3), jnp.bfloat16)
    variables = model.init(rng, x0, train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    params = bf.rank_shard(bf.rank_stack(params))
    batch_stats = bf.rank_shard(bf.rank_stack(batch_stats))

    imgs = jax.random.normal(
        jax.random.PRNGKey(1), (n, batch, args.image_size, args.image_size, 3)
    ).astype(jnp.bfloat16)
    labels = jax.random.randint(jax.random.PRNGKey(2), (n, batch), 0, 1000)
    imgs, labels = bf.rank_shard(imgs), bf.rank_shard(labels)

    def init_opt(params_blk):
        p = jax.tree_util.tree_map(lambda t: t[0], params_blk)
        st = opt.init(p)
        return jax.tree_util.tree_map(lambda t: jnp.asarray(t)[None], st)

    opt_state = jax.jit(shard_map(
        init_opt, mesh=ctx.mesh, in_specs=(P(ctx.axis_name),),
        out_specs=P(ctx.axis_name), check_vma=False,
    ))(params)

    def train_step(params_blk, stats_blk, opt_blk, x_blk, y_blk):
        p, bs, st = jax.tree_util.tree_map(lambda t: t[0],
                                           (params_blk, stats_blk, opt_blk))
        x, y = x_blk[0], y_blk[0]

        def loss_fn(p):
            logits, mut = model.apply(
                {"params": p, "batch_stats": bs}, x, train=True,
                mutable=["batch_stats"],
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()
            return loss, mut["batch_stats"]

        (loss, new_bs), g = jax.value_and_grad(loss_fn, has_aux=True)(p)
        upd, st = opt.update(g, st, p)
        p = optax.apply_updates(p, upd)
        return (jax.tree_util.tree_map(lambda t: t[None], (p, new_bs, st))
                + (loss[None],))

    # AOT-compile once; the same executable serves cost analysis, warmup,
    # profiling, and the timed loop (no second trace/compile anywhere).
    step_fn = jax.jit(shard_map(
        train_step, mesh=ctx.mesh, in_specs=(P(ctx.axis_name),) * 5,
        out_specs=(P(ctx.axis_name),) * 4, check_vma=False,
    ), donate_argnums=(0, 1, 2)).lower(
        params, batch_stats, opt_state, imgs, labels).compile()

    flops_per_step = _cost_flops(step_fn)
    try:
        ma = step_fn.memory_analysis()
        if isinstance(ma, (list, tuple)):
            ma = ma[0]
        mem = {"temp": int(ma.temp_size_in_bytes),
               "args": int(ma.argument_size_in_bytes)}
    except Exception:
        mem = None

    for _ in range(max(args.warmup, 1)):
        params, batch_stats, opt_state, loss = step_fn(
            params, batch_stats, opt_state, imgs, labels
        )
    jax.block_until_ready(loss)

    if args.profile:
        with jax.profiler.trace(args.profile):
            for _ in range(PROFILE_STEPS):
                params, batch_stats, opt_state, loss = step_fn(
                    params, batch_stats, opt_state, imgs, labels
                )
            jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(args.steps):
        params, batch_stats, opt_state, loss = step_fn(
            params, batch_stats, opt_state, imgs, labels
        )
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    total_images = args.steps * batch * n
    return total_images / dt / n, flops_per_step, mem


def _free_device_memory() -> int:
    """Delete every live device buffer (and collect garbage) so the next
    compile starts against an empty HBM.  Round 4's fresh sweep died
    RESOURCE_EXHAUSTED at every batch because each failed attempt left its
    arguments + donated buffers resident; run() rebuilds everything from
    scratch per call, so nothing here is needed again.  Returns the number
    of buffers deleted (diagnostic)."""
    import gc

    n = 0
    for arr in jax.live_arrays():
        try:
            arr.delete()
            n += 1
        except Exception:  # noqa: BLE001 — already-deleted/donated is fine
            pass
    gc.collect()
    return n


def rescue_ladder(attempt, batches=(128, 64, 32, 16), free=None,
                  log=lambda msg: print(msg, file=sys.stderr)):
    """Last-resort descending-batch walk after a failed sweep (round-4
    verdict #1): free device memory, try the next smaller batch, return
    ``(batch, result)`` for the FIRST success or ``None`` when the whole
    ladder fails.  Every attempt is isolated: any exception moves down a
    rung, so a wedged relay or leftover HBM pressure cannot cost the round
    its fresh number while any batch at all still fits."""
    for b in batches:
        if free is not None:
            freed = free()
            log(f"bench: rescue freed {freed} device buffers before "
                f"batch {b}")
        try:
            result = attempt(b)
        except Exception as e:  # noqa: BLE001 — any failure -> next rung
            log(f"bench: rescue batch {b} failed "
                f"({type(e).__name__}: {str(e)[:120]})")
            continue
        log(f"bench: rescue landed batch {b}")
        return b, result
    return None


def _hbm_limit_bytes() -> int:
    """Per-chip accelerator memory capacity, or 0 if the platform doesn't
    expose it (``BFTPU_HBM_BYTES`` overrides for relays that hide it)."""
    env = os.environ.get("BFTPU_HBM_BYTES")
    if env:
        return int(env)
    try:
        stats = jax.local_devices()[0].memory_stats()
        return int(stats.get("bytes_limit", 0)) if stats else 0
    except Exception:
        return 0


def _predicts_oom(mem, limit: int) -> bool:
    """Would doubling the batch exceed HBM?  Temp (activation) memory scales
    ~linearly with batch; arguments are mostly batch-independent params.
    Deliberately conservative (1.9x, 95% of capacity): a false 'fits' just
    pays the compile-and-fail we would have paid anyway, while a false
    'OOM' would silently drop a feasible sweep point."""
    if not mem or not limit:
        return False
    return 1.9 * mem["temp"] + mem["args"] > 0.95 * limit


def _is_oom(e: BaseException) -> bool:
    """Anchored on the canonical signals, not substrings of arbitrary
    messages: host OOM is MemoryError; device OOM is an XLA runtime error
    whose status is RESOURCE_EXHAUSTED (the message is the status string,
    'RESOURCE_EXHAUSTED: ...').  One relay-specific case: compile-time HBM
    exhaustion through the axon remote-compile proxy arrives as a
    JaxRuntimeError whose status is INTERNAL (the HTTP hop erases it), so
    for that type only we accept XLA:TPU's canonical compile-OOM sentence
    ('Ran out of memory in memory space hbm')."""
    if isinstance(e, MemoryError):
        return True
    if (type(e).__name__ in ("XlaRuntimeError", "JaxRuntimeError")
            and "Ran out of memory in memory space" in str(e)):
        return True
    return (type(e).__name__ == "XlaRuntimeError"
            and str(e).lstrip().startswith("RESOURCE_EXHAUSTED"))


def perf_sanity_fields(devices, peak_flops, achieved_flops, best_mem,
                       flops_per_step, best_batch, best_ips) -> dict:
    """Sanity-gated peak / MFU / roofline fields (VERDICT r3 weak #1).

    The relay has produced non-physical measured peaks (58 TFLOP/s to
    ~1000 PFLOP/s on one chip); a reader must be able to tell relay noise
    from regression, so the JSON carries BOTH denominators (measured and
    nominal-spec), a plausibility verdict choosing between them, and a
    bytes-moved roofline estimate."""
    out: dict = {}
    nom_peak_tf, nom_hbm_gbps = nominal_spec(devices)
    if nom_peak_tf is not None:
        out["nominal_peak_tflops_per_sec"] = nom_peak_tf
        out["device_kind"] = getattr(devices[0], "device_kind", "?")
    if peak_flops is not None:
        measured_tf = peak_flops / 1e12
        out["measured_peak_tflops_per_sec"] = round(measured_tf, 2)
        out["mfu_vs_measured"] = round(achieved_flops / peak_flops, 4)
        if nom_peak_tf is not None:
            # a real chip cannot beat its spec by >1.5x or deliver <20% of
            # it on a pure matmul chain; outside that band the measurement
            # is relay noise (caching/eliding through the remote hop)
            plausible = 0.2 * nom_peak_tf <= measured_tf <= 1.5 * nom_peak_tf
            out["measured_peak_plausible"] = plausible
            out["mfu_vs_nominal"] = round(
                achieved_flops / (nom_peak_tf * 1e12), 4)
            out["mfu"] = (out["mfu_vs_measured"] if plausible
                          else out["mfu_vs_nominal"])
            out["mfu_denominator"] = ("measured_peak" if plausible
                                      else "nominal_spec")
            if not plausible:
                print(f"bench: measured peak {measured_tf:.0f} TFLOP/s is "
                      f"NON-PHYSICAL for {out.get('device_kind')} (spec "
                      f"{nom_peak_tf:.0f}); mfu reported against the spec",
                      file=sys.stderr)
        else:
            out["mfu"] = out["mfu_vs_measured"]
            out["mfu_denominator"] = "measured_peak_unverified"
        out["mfu_plausible"] = out["mfu"] <= 1.0  # >100% of peak: not physical
    if best_mem and nom_hbm_gbps:
        # crude per-step roofline: HBM traffic ~ activations (temp) + one
        # read of the arguments; compute bound from the nominal peak
        # (a known HBM spec implies a known FLOP spec — same table row)
        bytes_est = best_mem["temp"] + best_mem["args"]
        mem_ms = bytes_est / (nom_hbm_gbps * 1e9) * 1e3
        comp_ms = (flops_per_step / (nom_peak_tf * 1e12) * 1e3
                   if flops_per_step else None)
        measured_ms = best_batch / best_ips * 1e3
        out["roofline_estimate"] = {
            "hbm_bytes_per_step_est": int(bytes_est),
            "min_step_ms_memory": round(mem_ms, 2),
            "min_step_ms_compute": (round(comp_ms, 2)
                                    if comp_ms is not None else None),
            "measured_step_ms": round(measured_ms, 2),
            "bound": ("memory" if comp_ms is None or mem_ms > comp_ms
                      else "compute"),
        }
    return out


def _trace_device_step_ms(trace_dir):
    """Per-step per-chip device op time (ms) from the jax.profiler trace
    captured at ``trace_dir``, or None when the trace is missing/host-only.

    This is the timing ground truth: the device's own op durations cannot be
    skewed by the relay's RPC clock, whereas the host wall clock through the
    axon relay has produced step times far below what the chip physically
    spent (PROFILE.md §1).  The trace carries one "XLA Ops" lane per local
    device; under SPMD each lane holds one chip's copy of the step, so the
    per-chip figure divides the lane-summed total by the lane count."""
    import importlib.util

    summary_py = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "benchmarks", "profile_summary.py")
    # SystemExit included: find_trace raises it for a missing trace, and
    # best-effort corroboration must not kill the benchmark over that —
    # but a Ctrl-C during the (multi-MB) parse still aborts.
    try:
        spec = importlib.util.spec_from_file_location(
            "bftpu_profile_summary", summary_py)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        (_path, by_op, total_us, n_lanes,
         device_events) = mod.device_op_totals(trace_dir)
    except (Exception, SystemExit) as e:
        print(f"bench: trace corroboration unavailable "
              f"({type(e).__name__}: {str(e)[:120]})", file=sys.stderr)
        return None
    if not by_op or not device_events or n_lanes <= 0:
        return None
    return total_us / 1e3 / PROFILE_STEPS / n_lanes


def reconcile_timing(batch: int, wall_ips: float, trace_step_ms):
    """Cross-check the wall-clock throughput against the profiler trace.

    Pure decision logic (unit-tested): the device op time per step is a hard
    floor on the real step time — a wall clock that claims a FASTER step than
    the device itself spent executing ops is corrupt (observed through the
    relay: 3.6 ms claimed vs 98 ms of device op time at identical batch).
    Returns ``(chosen_ips, fields)``; the trace-derived throughput becomes
    the headline value only when the wall clock is impossible, because the
    trace total omits host/dispatch gaps and so *overstates* throughput
    slightly when the wall clock is healthy."""
    fields = {"value_source": "wall_clock"}
    if not trace_step_ms or trace_step_ms <= 0 or wall_ips <= 0:
        return wall_ips, fields
    wall_step_ms = batch / wall_ips * 1e3
    trace_ips = batch / (trace_step_ms / 1e3)
    fields.update({
        "trace_device_step_ms": round(trace_step_ms, 2),
        "wall_clock_step_ms": round(wall_step_ms, 2),
        "img_per_sec_per_chip_trace": round(trace_ips, 2),
        # healthy wall clock >= device op time (it adds overhead, never
        # removes work); 0.9 tolerates trace envelope jitter
        "wall_clock_plausible": wall_step_ms >= 0.9 * trace_step_ms,
    })
    if not fields["wall_clock_plausible"]:
        print(f"bench: wall-clock step {wall_step_ms:.2f} ms is FASTER than "
              f"the device's own op time {trace_step_ms:.2f} ms — relay "
              "clock corruption; reporting trace-derived throughput",
              file=sys.stderr)
        fields["value_source"] = "profiler_trace"
        fields["value_wall_clock"] = round(wall_ips, 2)
        return trace_ips, fields
    return wall_ips, fields


def _device_init_watchdog(timeout_s: float):
    """Bound the first device query.  The axon relay can hold a stale chip
    claim that makes ``jax.devices()`` block FOREVER (observed twice in
    round 3); a benchmark that hangs is worse than one that fails, and one
    that fails with *nothing* is almost as bad — so both failure shapes
    (hang past the timeout, UNAVAILABLE error) route to the degraded-mode
    emitter instead of a bare nonzero exit."""
    out = {}

    def probe():
        try:
            out["devices"] = jax.devices()
        except BaseException as e:  # noqa: BLE001 — report, don't misdiagnose
            out["error"] = e

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if "error" in out:
        err = out["error"]
        if not _is_relay_unavailable(err):
            # a genuine environment breakage (no TPU installed, broken
            # jax/libtpu) must fail LOUD, not masquerade as a transient
            # relay wedge with stale-but-rc-0 numbers round after round
            raise err
        _degraded_exit(f"device init failed: {type(err).__name__}: "
                       f"{str(err)[:200]}")
    if "devices" not in out:
        print(f"bench: device init did not complete within {timeout_s:.0f}s "
              "— the TPU relay likely holds a stale claim (see PROFILE.md); "
              "set BFTPU_DEVICE_INIT_TIMEOUT_S (seconds) to wait longer",
              file=sys.stderr, flush=True)
        # the probe thread is still BLOCKED inside jax.devices() holding
        # jax's backend-init lock; sys.exit would run jax atexit teardown
        # against that lock and hang after emitting — hard-exit instead
        _degraded_exit(
            f"device init hung past {timeout_s:.0f}s (stale relay claim)",
            hard=True)
    return out["devices"]


def _is_relay_unavailable(e: BaseException) -> bool:
    """True for the relay-shaped init failures (transient, degrade-worthy):
    the axon relay surfaces a wedged/stale chip claim as UNAVAILABLE or
    DEADLINE_EXCEEDED canonical statuses (possibly wrapped in jax's
    'Unable to initialize backend' RuntimeError)."""
    msg = str(e)
    return ("UNAVAILABLE" in msg or "DEADLINE_EXCEEDED" in msg
            or "Unavailable" in msg)


def _aot_overlap_evidence(timeout_s: float = 900.0):
    """Compile-only evidence that survives a wedged chip claim: the AOT
    topology API (v5e:2x4) keeps working while ``jax.devices()`` hangs, so
    degraded mode still proves the compiled schedule overlaps gossip with
    compute (benchmarks/overlap_report.py, run out-of-process so a hang
    cannot take the bench down with it)."""
    import subprocess

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "benchmarks", "overlap_report.py")
    # The child only needs libtpu's AOT compiler (get_topology_desc), not a
    # TPU backend: pin its runtime platform to CPU so it can neither fight
    # the parent for the libtpu lockfile nor touch the (possibly wedged)
    # relay claim.
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    # the parent's failed init may still hold the libtpu lockfile; AOT
    # compilation needs no exclusive TPU system, so opt out of the lock
    env["ALLOW_MULTIPLE_LIBTPU_LOAD"] = "1"
    try:
        proc = subprocess.run(
            [sys.executable, script], capture_output=True, text=True,
            timeout=timeout_s, env=env)
    except (OSError, subprocess.TimeoutExpired) as e:
        return {"error": f"{type(e).__name__}: {e}"}
    if proc.returncode != 0:
        return {"error": f"rc={proc.returncode}: {proc.stderr[-300:]}"}
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    return {"error": "no JSON in overlap report output"}


def _degraded_exit(reason: str, hard: bool = False):
    """The TPU refused to initialize.  Emit the last-good cached metrics
    flagged stale plus AOT compile-only evidence and exit 0 — a wedged
    relay must never end a round with no perf artifact (VERDICT r3 #2).

    ``hard`` exits via os._exit (no interpreter teardown) for the hung-probe
    path, where a blocked jax.devices() thread would deadlock atexit."""
    out = {"stale": True, "degraded_reason": reason}
    try:
        with open(CACHE_PATH) as f:
            out.update(json.load(f))
        out["stale"] = True  # cache must not un-flag the degradation
    except (OSError, json.JSONDecodeError) as e:
        out.update({
            "metric": "resnet50_images_per_sec_per_chip",
            "value": None, "unit": "images/sec/chip",
            "cache_error": f"{type(e).__name__}: {e}",
        })
    print("bench: DEGRADED MODE — emitting last-good cached metrics + AOT "
          f"overlap evidence ({reason})", file=sys.stderr, flush=True)
    out["aot_overlap"] = _aot_overlap_evidence()
    print(json.dumps(out), flush=True)
    if hard:
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(0)
    sys.exit(0)


def _headline_provably_corrupt(out) -> bool:
    """Corrupt beyond repair: the wall clock claims MORE than the chip's
    physical peak (mfu vs the nominal spec > 1) AND no device trace exists
    to demote the headline to (``wall_clock_plausible`` absent — observed:
    the relay exported host-only traces during the same episode that
    corrupted its clock).  CPU runs never trip this (no nominal spec)."""
    return bool(
        out.get("value_source") == "wall_clock"
        and "wall_clock_plausible" not in out
        and (out.get("mfu_vs_nominal") or 0) > 1.0)


def _credible(entry) -> bool:
    """A bench result whose headline value is device-trace-backed: either
    its wall clock was corroborated by the trace, or the value itself was
    DERIVED from the trace after the wall clock failed the check
    (``reconcile_timing`` demotion paths)."""
    if not entry:
        return False
    if entry.get("wall_clock_plausible"):
        return True
    return entry.get("value_source") in ("profiler_trace",
                                         "trace_corroborated_fallback")


def _cached_beats(prev, out) -> bool:
    """True when the existing cache entry should SURVIVE this run.

    Best-credible-wins, where credible = device-trace-backed
    (:func:`_credible`):

    - a credible cache NEVER yields to an uncredible run — a TPU run whose
      trace capture failed entirely carries exactly the corrupt-wall-clock
      risk the cache policy exists to keep out of the headline;
    - two credible entries compare by value (a pinned A/B at a deliberately
      suboptimal batch/stem must not clobber the sweep optimum);
    - an uncredible or missing cache always yields (latest-wins, the CPU
      debug-path behavior the force-flag tests rely on).
    """
    try:
        if not prev or prev.get("metric") != out.get("metric"):
            return False
        if not _credible(prev):
            return False
        if not _credible(out):
            return True
        return float(prev.get("value", 0)) > float(out.get("value", 0))
    except (TypeError, ValueError):
        return False


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=None,
                    help="pin one per-chip batch (halve-on-OOM); default "
                         "sweeps 128..2048 and reports the best")
    ap.add_argument("--sweep-max", type=int, default=2048)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--profile", default=None, metavar="DIR",
                    help="capture a jax.profiler trace at the chosen batch")
    ap.add_argument("--skip-peak", action="store_true",
                    help="skip the matmul-peak measurement (mfu omitted)")
    ap.add_argument("--backend", choices=["auto", "xla", "pallas"],
                    default="auto",
                    help="gossip transport (pallas = fused RDMA kernels)")
    ap.add_argument("--stem", choices=["conv", "s2d"], default="conv",
                    help="ResNet stem: reference 7x7/s2 conv, or the "
                         "MXU-friendly space-to-depth 4x4/s1 equivalent "
                         "(exact same function class; see models/resnet.py)")
    args = ap.parse_args()

    try:
        init_timeout = float(
            os.environ.get("BFTPU_DEVICE_INIT_TIMEOUT_S", 1800))
    except ValueError:
        raise SystemExit(
            "bench: BFTPU_DEVICE_INIT_TIMEOUT_S must be a number of seconds, "
            f"got {os.environ['BFTPU_DEVICE_INIT_TIMEOUT_S']!r}")
    devices = _device_init_watchdog(init_timeout)
    bf.init(topology=ExponentialTwoGraph(len(devices)))

    peak_flops = None if args.skip_peak else measure_peak_flops()
    if peak_flops is not None:
        print(f"bench: measured bf16 matmul peak "
              f"{peak_flops / 1e12:.1f} TFLOP/s/chip", file=sys.stderr)

    platform = getattr(devices[0], "platform", "")
    if args.profile is None and platform in ("tpu", "axon"):
        # No --profile given, but on a TPU the trace doubles as the timing
        # ground truth (the relay's wall clock has reported steps 27x
        # faster than the device's own op time — PROFILE.md §1), so always
        # capture a corroboration trace.  Set before the measurement runs
        # so pinned mode traces its one run inline instead of paying a
        # second lower+compile through the (slow) remote-compile relay.
        import tempfile

        args.profile = tempfile.mkdtemp(prefix="bftpu_corrob_trace_")
        print(f"bench: corroboration trace -> {args.profile}",
              file=sys.stderr)

    profile_dir = args.profile
    traced_dir, traced_batch = None, None  # set once a traced run completes
    results = []  # (batch, img/s/chip, flops_per_step, mem_info)
    oom_bound = None       # smallest batch known to OOM (sweep mode)
    sweep_error = None     # first-point failure that emptied the sweep
    if args.batch is not None:
        # pinned mode has exactly one successful run — trace it inline
        batch = args.batch
        while True:
            try:
                results.append((batch,) + run(args, batch))
                traced_dir, traced_batch = args.profile, batch
                profile_dir = None  # captured inline; skip the re-run
                break
            except Exception as e:  # noqa: BLE001 — halve batch only on OOM
                if _is_oom(e) and batch > 8:
                    print(f"bench: batch {batch} exhausted memory; retrying "
                          f"at {batch // 2}", file=sys.stderr)
                    _free_device_memory()
                    batch //= 2
                    continue
                raise
    else:
        # Sweep mode: the FIRST successful point is traced inline
        # (trace-first, round-4 verdict #1) so even a sweep that collapses
        # later still holds one trace-corroborated point; subsequent
        # points run untraced and the best batch is re-traced at the end
        # into profile_dir (the user's --profile directory when given).
        import tempfile

        first_trace_dir = (tempfile.mkdtemp(prefix="bftpu_first_trace_")
                           if profile_dir else None)
        args.profile = first_trace_dir
        batch = min(128, args.sweep_max)
        while batch <= args.sweep_max:
            if oom_bound is not None and batch >= oom_bound:
                break  # deterministic OOM — don't pay the compile again
            try:
                r = (batch,) + run(args, batch)
            except Exception as e:  # noqa: BLE001 — OOM steers the sweep
                if _is_oom(e):
                    oom_bound = batch
                    if not results and batch > 8:
                        # even the smallest sweep point doesn't fit: halve
                        # downward so the driver still gets a number
                        print(f"bench: batch {batch} exhausted memory; "
                              f"retrying at {batch // 2}", file=sys.stderr)
                        _free_device_memory()
                        batch //= 2
                        continue
                    print(f"bench: batch {batch} exhausted memory; sweep ends",
                          file=sys.stderr)
                    break
                if results:
                    # A bigger point failing for any other reason (remote
                    # compile relays surface HBM exhaustion as opaque
                    # UNAVAILABLE/INTERNAL errors) must not cost the sweep
                    # its already-measured result — report what we have.
                    print(f"bench: batch {batch} failed "
                          f"({type(e).__name__}: {str(e)[:120]}); sweep ends "
                          f"with measured points", file=sys.stderr)
                    break
                sweep_error = e  # first point failed non-OOM: the rescue
                break            # ladder decides (transient) or re-raises
            print(f"bench: batch {r[0]:5d} -> {r[1]:,.0f} img/s/chip",
                  file=sys.stderr)
            results.append(r)
            if args.profile:
                # first point captured inline (its own tempdir — the user's
                # --profile directory stays reserved for the end-of-sweep
                # BEST-batch trace) — validate and keep as the fallback
                # corroboration if the end-of-sweep trace dies
                if _trace_device_step_ms(first_trace_dir) is not None:
                    traced_dir, traced_batch = first_trace_dir, r[0]
                    print(f"bench: first-point trace captured (batch "
                          f"{r[0]})", file=sys.stderr)
                args.profile = None
            # Past the knee: throughput here declines monotonically with
            # batch once XLA starts rematerializing under HBM pressure
            # (measured round 4: 256 -> 2,510; 512 -> 2,394; 1024 -> 2,054
            # img/s/chip, per-image flops rising 23.9 -> 31.6 GF).  A point
            # >3% below the best so far means every larger one loses too —
            # stop rather than pay ~6-17 min of remote compile per doomed
            # point.  (3% margin so run-to-run noise can't end the sweep
            # before the real knee.)
            best_so_far = max(x[1] for x in results)
            if r[1] < 0.97 * best_so_far:
                print(f"bench: batch {r[0]} is {100 * (1 - r[1] / best_so_far):.1f}% "
                      f"below the best point — past the knee, sweep ends",
                      file=sys.stderr)
                break
            # Skip a doomed next point: a compile that only discovers OOM
            # costs many minutes on remote-compile relays.
            if batch * 2 <= args.sweep_max and _predicts_oom(
                    r[3], _hbm_limit_bytes()):
                print(f"bench: batch {batch * 2} predicted to exceed HBM "
                      f"(temp {r[3]['temp'] / 2**30:.1f} GiB at {batch}); "
                      f"sweep ends", file=sys.stderr)
                break
            batch *= 2

    if not results:
        # Round-4 verdict #1: never end a round on the cache while ANY
        # batch still fits.  Descending ladder with device buffers freed
        # between compiles; the rescue run traces inline (trace-first) so
        # its single point lands corroborated.
        if sweep_error is not None and not (
                _is_oom(sweep_error) or _is_relay_unavailable(sweep_error)
                or any(tag in str(sweep_error) for tag in
                       ("INTERNAL", "DEADLINE", "UNAVAILABLE", "timed out",
                        "Connection", "Socket"))):
            # a deterministic Python/shape bug would fail identically on
            # every rung — re-raise with the real traceback instead of
            # burning 4 multi-minute compiles and misblaming memory
            raise sweep_error
        # rungs respect --sweep-max (never headline an excluded batch) and
        # the sweep's proven OOM bound; 8 is the final rung — the smallest
        # batch the pinned-mode halver also bottoms out at
        rungs = [b for b in (128, 64, 32, 16, 8)
                 if b <= args.sweep_max
                 and (oom_bound is None or b < oom_bound)]
        rescue_state = {}

        def rescue_attempt(b):
            import tempfile

            # fresh trace dir per rung: a failed attempt must not leave
            # partial events for the next one to mis-parse
            d = tempfile.mkdtemp(prefix="bftpu_rescue_trace_")
            args.profile = d
            args.steps, args.warmup = max(args.steps, 5), 1
            out = run(args, b)
            rescue_state["dir"] = d
            return out

        landed = rescue_ladder(rescue_attempt, batches=rungs,
                               free=_free_device_memory)
        if landed is None:
            detail = (f" (first sweep failure: "
                      f"{type(sweep_error).__name__}: "
                      f"{str(sweep_error)[:200]})" if sweep_error else "")
            raise SystemExit(
                f"bench: rescue ladder {rungs} exhausted — no batch "
                f"fit{detail}")
        b, r = landed
        results.append((b,) + r)
        d = rescue_state.get("dir")
        if d and _trace_device_step_ms(d) is not None:
            traced_dir, traced_batch = d, b
            if profile_dir and profile_dir != d:
                # honor a user-supplied --profile directory: mirror the
                # landed trace there
                import shutil

                try:
                    shutil.copytree(d, profile_dir, dirs_exist_ok=True)
                except OSError as ce:
                    print(f"bench: could not mirror rescue trace to "
                          f"{profile_dir}: {ce}", file=sys.stderr)
        profile_dir = None  # traced inline (or trace unusable) — no re-run
    best_batch, best_ips, flops_per_step, best_mem = max(
        results, key=lambda r: r[1])

    if traced_batch == best_batch:
        profile_dir = None  # already corroborated at the headline batch

    if profile_dir:
        # trace-only re-run: run() captures PROFILE_STEPS traced steps;
        # steps=0 skips the (discarded) timing loop, warmup=1 covers compile.
        # A "successful" capture can still come back with NO device lane
        # (observed: the relay exported host-only events at batch 1024 while
        # its wall clock was corrupt — the exact run that most needs the
        # oracle), so validate the trace parses to device time before
        # trusting it, and walk down the measured batches until one does.
        args.profile, args.steps, args.warmup = profile_dir, 0, 1
        for try_batch in sorted((r[0] for r in results), reverse=True):
            if try_batch > best_batch:
                continue
            try:
                run(args, try_batch)
            except Exception as e:  # noqa: BLE001 — the sweep result
                # survives: tracing can RESOURCE_EXHAUST (profiler buffers
                # ride on top of a near-full HBM)
                print(f"bench: trace at batch {try_batch} failed "
                      f"({type(e).__name__}: {str(e)[:120]})",
                      file=sys.stderr)
                continue
            if _trace_device_step_ms(profile_dir) is None:
                print(f"bench: trace at batch {try_batch} has no device "
                      "events — retrying smaller", file=sys.stderr)
                continue
            traced_dir, traced_batch = profile_dir, try_batch
            print(f"bench: profiler trace written to {profile_dir} "
                  f"(batch {try_batch})", file=sys.stderr)
            break
        else:
            print("bench: no batch yielded a device trace", file=sys.stderr)

    # Timing ground truth: the device's own per-op durations.  The trace
    # corroborates the batch it was captured at directly; when that batch
    # is not the headline batch (trace fallback after an OOM), a per-image
    # floor check still guards the headline — otherwise a corrupt
    # best-batch wall clock would ship behind a healthy fallback trace.
    timing_fields = {"value_source": "wall_clock"}
    if traced_dir:
        trace_step_ms = _trace_device_step_ms(traced_dir)
        wall_at_traced = next(
            (r[1] for r in results if r[0] == traced_batch), None)
        if trace_step_ms and wall_at_traced:
            chosen, timing_fields = reconcile_timing(
                traced_batch, wall_at_traced, trace_step_ms)
            timing_fields["corroborated_batch"] = traced_batch
            corrupt = timing_fields["value_source"] == "profiler_trace"
            if not corrupt and traced_batch != best_batch:
                # Larger batches amortize fixed work, but per-image device
                # time cannot shrink 4x between sweep points of the same
                # model; a headline per-image wall time under a quarter of
                # the trace-corroborated per-image time is relay corruption.
                t_img_us = trace_step_ms * 1e3 / traced_batch
                w_img_us = 1e6 / best_ips
                timing_fields["headline_vs_trace_per_image_ratio"] = round(
                    w_img_us / t_img_us, 4)
                if w_img_us < 0.25 * t_img_us:
                    corrupt = True
                    timing_fields["value_source"] = (
                        "trace_corroborated_fallback")
                    print(f"bench: headline batch {best_batch} claims "
                          f"{w_img_us:.1f} us/img but the device trace at "
                          f"batch {traced_batch} shows {t_img_us:.1f} us/img "
                          "— relay clock corruption; demoting the headline "
                          "to the corroborated batch", file=sys.stderr)
            if corrupt:
                # the uncorroborated sweep best is recorded under its own
                # key (value_wall_clock from reconcile_timing refers to the
                # traced batch and stays consistent with wall_clock_step_ms)
                timing_fields["sweep_best_wall_clock"] = {
                    "batch": best_batch,
                    "img_per_sec_per_chip": round(best_ips, 2)}
                timing_fields["sweep_timing"] = "wall_clock_suspect"
                best_batch, best_ips = traced_batch, chosen
                flops_per_step, best_mem = next(
                    (r[2], r[3]) for r in results if r[0] == traced_batch)

    if flops_per_step > 0:
        # cost_analysis counts the per-device SPMD module = `batch` images
        flops_per_img = flops_per_step / best_batch
    else:
        flops_per_img = RESNET50_TRAIN_FLOPS_PER_IMG_224 * (
            args.image_size / 224.0) ** 2
    achieved_flops = best_ips * flops_per_img

    out = {
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(best_ips, 2),
        "unit": "images/sec/chip",
        "batch": best_batch,
        "backend": args.backend,
        "stem": args.stem,
        "vs_baseline": round(best_ips / V100_BASELINE_IMG_PER_SEC, 3),
        "sweep": [{"batch": r[0], "img_per_sec_per_chip": round(r[1], 2)}
                  for r in results],
        "model_tflops_per_sec_per_chip": round(achieved_flops / 1e12, 2),
        "flops_source": "xla_cost_analysis" if flops_per_step > 0 else "analytic",
    }
    out.update(timing_fields)
    out.update(perf_sanity_fields(
        devices, peak_flops, achieved_flops, best_mem, flops_per_step,
        best_batch, best_ips))
    if _headline_provably_corrupt(out):
        # The cache holds the last trace-corroborated truth; shipping this
        # value as the headline would be worse than degrading.
        _degraded_exit(
            f"fresh sweep wall clock is non-physical (mfu "
            f"{out['mfu_vs_nominal']:.1f} vs nominal spec) with no device-"
            "trace corroboration; refusing to headline a provably corrupt "
            "number")
    print(json.dumps(out))
    # cache ONLY real-TPU numbers: a CPU/test run must never replace the
    # last-good on-chip value that degraded mode would later emit as stale.
    # BFTPU_BENCH_CACHE only redirects the path; the platform gate stays
    # authoritative unless BFTPU_BENCH_CACHE_FORCE=1 (tests).
    if (platform in ("tpu", "axon")
            or os.environ.get("BFTPU_BENCH_CACHE_FORCE") == "1"):
        # Best-corroborated-wins: the cache is degraded mode's fallback, so
        # it should hold the best credible number, not merely the latest —
        # a pinned A/B run at a deliberately suboptimal batch/stem must not
        # clobber the sweep's optimum.  A new run only replaces a cached one
        # that beats it when the cached entry is itself suspect (wall clock
        # uncorroborated by its trace).
        prev = None
        try:
            with open(CACHE_PATH) as f:
                prev = json.load(f)
        except (OSError, ValueError):
            prev = None
        if _cached_beats(prev, out):
            print(f"bench: cached value {prev.get('value')} "
                  f"(batch {prev.get('batch')}, stem "
                  f"{prev.get('stem', 'conv')}) beats this run's "
                  f"{out.get('value')} — keeping the cache", file=sys.stderr)
        else:
            try:
                with open(CACHE_PATH, "w") as f:
                    json.dump({**out, "cached_at": time.strftime(
                        "%Y-%m-%dT%H:%M:%S%z")}, f, indent=1)
            except OSError as e:
                print(f"bench: could not write {CACHE_PATH}: {e}",
                      file=sys.stderr)
    else:
        print(f"bench: platform {platform!r} is not a TPU — not updating "
              "the last-good cache", file=sys.stderr)


if __name__ == "__main__":
    main()
