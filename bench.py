"""Benchmark harness — north-star metric (BASELINE.md): ResNet-50
decentralized-SGD **images/sec/chip**.

Runs the full decentralized train step (fwd + bwd + gossip + SGD update) as
one jitted shard_map program over all visible devices and reports throughput
per chip.  On the driver's single real TPU chip the gossip degenerates to the
identity (size-1 mesh) — the compute path is the genuine benchmark; on a pod
the same program gossips over ICI.

Prints ONE JSON line:
  {"metric": "resnet50_images_per_sec_per_chip", "value": N,
   "unit": "images/sec/chip", "vs_baseline": R}

If the requested per-chip batch exhausts device memory, the harness halves
it and retries (recorded in the "batch" field) so the driver always gets a
number.

vs_baseline: ratio against the reference's per-GPU ResNet-50 throughput on
V100 (BASELINE.md records no machine-readable number from the reference;
360 img/s/V100 is the standard fp16 ResNet-50 figure for the 128xV100-era
stack the reference paper benchmarked on — see BASELINE.md caveats).
"""

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

import bluefog_tpu as bf
from bluefog_tpu.models import ResNet50
from bluefog_tpu.optim import DistributedNeighborAllreduceOptimizer
from bluefog_tpu.parallel.api import shard_map
from bluefog_tpu.topology import ExponentialTwoGraph

V100_BASELINE_IMG_PER_SEC = 360.0


def run(args, batch: int) -> float:
    """One full measurement at the given per-chip batch; img/s/chip."""
    n = len(jax.devices())
    ctx = bf.get_context()

    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    opt = DistributedNeighborAllreduceOptimizer(
        optax.sgd(0.1, momentum=0.9), topology=ctx.schedule,
        axis_name=ctx.axis_name, atc=False,
    )

    rng = jax.random.PRNGKey(0)
    x0 = jnp.zeros((batch, args.image_size, args.image_size, 3), jnp.bfloat16)
    variables = model.init(rng, x0, train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    params = bf.rank_shard(bf.rank_stack(params))
    batch_stats = bf.rank_shard(bf.rank_stack(batch_stats))

    imgs = jax.random.normal(
        jax.random.PRNGKey(1), (n, batch, args.image_size, args.image_size, 3)
    ).astype(jnp.bfloat16)
    labels = jax.random.randint(jax.random.PRNGKey(2), (n, batch), 0, 1000)
    imgs, labels = bf.rank_shard(imgs), bf.rank_shard(labels)

    def init_opt(params_blk):
        p = jax.tree_util.tree_map(lambda t: t[0], params_blk)
        st = opt.init(p)
        return jax.tree_util.tree_map(lambda t: jnp.asarray(t)[None], st)

    opt_state = jax.jit(shard_map(
        init_opt, mesh=ctx.mesh, in_specs=(P(ctx.axis_name),),
        out_specs=P(ctx.axis_name), check_vma=False,
    ))(params)

    def train_step(params_blk, stats_blk, opt_blk, x_blk, y_blk):
        p, bs, st = jax.tree_util.tree_map(lambda t: t[0],
                                           (params_blk, stats_blk, opt_blk))
        x, y = x_blk[0], y_blk[0]

        def loss_fn(p):
            logits, mut = model.apply(
                {"params": p, "batch_stats": bs}, x, train=True,
                mutable=["batch_stats"],
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()
            return loss, mut["batch_stats"]

        (loss, new_bs), g = jax.value_and_grad(loss_fn, has_aux=True)(p)
        upd, st = opt.update(g, st, p)
        p = optax.apply_updates(p, upd)
        return (jax.tree_util.tree_map(lambda t: t[None], (p, new_bs, st))
                + (loss[None],))

    step_fn = jax.jit(shard_map(
        train_step, mesh=ctx.mesh, in_specs=(P(ctx.axis_name),) * 5,
        out_specs=(P(ctx.axis_name),) * 4, check_vma=False,
    ), donate_argnums=(0, 1, 2))

    for _ in range(max(args.warmup, 1)):  # >=1: first call pays compilation
        params, batch_stats, opt_state, loss = step_fn(
            params, batch_stats, opt_state, imgs, labels
        )
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(args.steps):
        params, batch_stats, opt_state, loss = step_fn(
            params, batch_stats, opt_state, imgs, labels
        )
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    total_images = args.steps * batch * n
    return total_images / dt / n


def _is_oom(e: Exception) -> bool:
    msg = str(e).upper()
    return ("RESOURCE_EXHAUSTED" in msg or "OUT OF MEMORY" in msg
            or "ALLOCATION" in msg and "FAILED" in msg)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128, help="per-chip batch")
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=5)
    args = ap.parse_args()

    bf.init(topology=ExponentialTwoGraph(len(jax.devices())))

    batch = args.batch
    while True:
        try:
            img_per_sec_per_chip = run(args, batch)
            break
        except Exception as e:  # noqa: BLE001 — halve batch only on OOM
            if _is_oom(e) and batch > 8:
                print(f"bench: batch {batch} exhausted memory; retrying at "
                      f"{batch // 2}", file=sys.stderr)
                batch //= 2
                continue
            raise

    print(json.dumps({
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(img_per_sec_per_chip, 2),
        "unit": "images/sec/chip",
        "batch": batch,
        "vs_baseline": round(img_per_sec_per_chip / V100_BASELINE_IMG_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
