"""Elastic membership demo: ranks join, leave, and warm-start mid-run.

An autoscaling decentralized fleet in one process: a capacity-5 job
starts with 3 member ranks training a quadratic consensus problem over
asynchronous push-sum windows.  At t=0.5s a 4th rank JOINS — it
warm-starts by reading a live member's published (x, p) window snapshot
(no checkpoint file anywhere) and is admitted at a round boundary.  At
t=1.5s one of the original ranks LEAVES gracefully — it hands its
entire push-sum mass to its out-neighbors in drain-flagged deposits, so
the mass audit stays exact (a leaver's mass is conserved, unlike a
corpse's, which is written off).  The mixing graph re-plans over the
live member set at every membership boundary
(``topology.replan`` — deterministic in the member list).

Self-asserting; exits nonzero on failure.

Run:
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
  python examples/elastic_membership.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

from bluefog_tpu import topology as T
from bluefog_tpu.runtime.async_windows import run_async_dsgd
from bluefog_tpu.runtime.resilience import ResilienceConfig

CAPACITY = 5
DIM = 6


def main() -> int:
    # each rank pulls toward its own target; consensus lands on the mean
    targets = np.stack([np.full(DIM, float(r + 1))
                        for r in range(CAPACITY)])

    def loss_and_grad(r, step, params):
        w = np.asarray(params["w"], np.float64)
        diff = w - targets[r]
        return 0.5 * float(diff @ diff), {"w": diff}

    report = run_async_dsgd(
        T.FullyConnectedGraph(CAPACITY),       # the job's CAPACITY
        {"w": np.zeros(DIM, np.float32)},
        loss_and_grad,
        lr=0.05,
        duration_s=2.5,
        skew=[0.001] * CAPACITY,
        name="elastic_membership_demo",
        resilience=ResilienceConfig(suspect_after_s=0.2, dead_after_s=0.6),
        join_at_s={3: 0.5,                     # rank 3 attaches at 0.5 s
                   4: []},                     # rank 4: reserved capacity
        leave_at_s={1: 1.5},                   # rank 1 drains at 1.5 s
    )

    print(f"steps per rank : {report.steps_per_rank}")
    print(f"joined         : {report.joined_ranks}")
    print(f"left           : {report.left_ranks}")
    print(f"consensus gap  : {report.consensus_gap:.2e}")
    print(f"mass audit     : total={report.total_mass:.12f} "
          f"baseline={report.baseline_mass}")

    # the elastic lifecycle happened...
    assert report.joined_ranks == [3], report.joined_ranks
    assert report.left_ranks == [1], report.left_ranks
    assert report.dead_ranks == [], report.dead_ranks
    # ...the joiner trained meaningfully after its warm-start...
    assert report.steps_per_rank[3] > 20, report.steps_per_rank
    # ...the final members reached consensus...
    assert report.consensus_gap < 0.5, report.consensus_gap
    # ...and the push-sum mass audit is EXACT over the churn: 3 initial
    # units + 1 admission, the leaver's unit conserved via its handoff
    assert report.baseline_mass == 4.0, report.baseline_mass
    assert abs(report.total_mass - report.baseline_mass) < 1e-9, \
        report.total_mass
    print("elastic_membership: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
