"""Conv-net-scale convergence gate: decentralized ResNet-18 vs allreduce,
through the REAL TFRecord + DistributedLoader pipeline — self-asserting.

Round-4 verdict, Missing #4: the accuracy story for the north-star config
(ResNet-50/ImageNet, BASELINE config[1]) rested on a LeNet/MNIST gate.
This closes the conv-net-scale half of that gap in-environment: a genuine
ResNet-18 (4 stages, residuals, BatchNorm — the CIFAR 3x3/s1 stem) trained
decentralized (exp2 ``neighbor_allreduce``, the north-star's optimizer) vs
the centralized allreduce baseline on a CIFAR-shaped dataset, same init,
same data order, fixed epoch budget, one-sided 0.5-point parity gate like
``mnist_epoch_gate.py``.

The dataset is a deterministic CIFAR stand-in (no network egress): 10
random 32x32x3 prototypes; each sample a randomly shifted, channel-jittered
prototype plus Gaussian noise, quantized to uint8.  Real CIFAR-10 drops in
via --data-dir pointing at TFRecord shards.  BatchNorm statistics are part
of the consensus: the evaluated model averages params AND batch_stats over
ranks, exactly what ``bf.allreduce_parameters`` does after training.

--filters 16 (default) scales the network for the 8-virtual-device CPU
mesh CI budget; --filters 64 is the full ResNet-18 for real-chip runs.

Asserts (exits nonzero on failure):
  1. decentralized consensus ResNet reaches >= --target test accuracy
     within the epoch budget;
  2. decentralized accuracy within --parity-pt of allreduce (one-sided).

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      PALLAS_AXON_POOL_IPS= python examples/cifar_resnet_gate.py
"""

import argparse
import os
import sys
import tempfile
import time
from functools import partial

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import bluefog_tpu as bf
from bluefog_tpu.data import (DistributedLoader, Subset,
                              TFRecordSource)
from bluefog_tpu.data.tfrecord import write_image_classification_shards
from bluefog_tpu.models.resnet import ResNet18
from bluefog_tpu.optim import (DistributedGradientAllreduceOptimizer,
                               DistributedNeighborAllreduceOptimizer)
from bluefog_tpu.parallel.api import shard_map
from bluefog_tpu.topology import ExponentialTwoGraph


def _smooth(p: np.ndarray, k: int = 3) -> np.ndarray:
    """Separable box blur, k passes per spatial axis (periodic edges)."""
    for ax in (1, 2):
        for _ in range(k):
            p = (np.roll(p, 1, ax) + p + np.roll(p, -1, ax)) / 3.0
    return p


def synth_cifar(n: int, seed: int, noise: float = 0.5):
    """Deterministic CIFAR stand-in: SMOOTH (blurred) shifted + channel-
    jittered prototypes plus pixel noise, uint8.

    The blur is load-bearing: with raw white-noise prototypes a ResNet
    memorizes the 12k noisy training samples and tests at chance (measured
    — train loss 0.002, test 11%) even though a nearest-prototype oracle
    scores 100%, because nothing about high-frequency random templates
    matches the conv-net inductive bias.  Low-frequency prototypes are
    what the architecture pools and generalizes over — like actual CIFAR
    images (same recipe, measured 90% test under the same budget)."""
    rng = np.random.default_rng(seed)
    protos = np.random.default_rng(11).standard_normal((10, 32, 32, 3))
    protos = _smooth(protos)
    protos = protos / protos.std()  # restore contrast lost to the blur
    labels = rng.integers(0, 10, n)
    imgs = protos[labels]
    dx, dy = rng.integers(-3, 4, n), rng.integers(-3, 4, n)
    imgs = np.stack([np.roll(im, (a, b), (0, 1))
                     for im, a, b in zip(imgs, dx, dy)])
    # per-sample channel gain: breaks pure template matching in any one
    # channel, conv stays invariant enough
    gain = 1.0 + 0.2 * rng.standard_normal((n, 1, 1, 3))
    imgs = imgs * gain + noise * rng.standard_normal(imgs.shape)
    lo, hi = imgs.min(), imgs.max()
    return (((imgs - lo) / (hi - lo)) * 255).astype(np.uint8), (
        labels.astype(np.int64))


def train(loader, model, opt, init_vars, epochs, ctx):
    params = bf.rank_shard(bf.rank_stack(init_vars["params"]))
    stats = bf.rank_shard(bf.rank_stack(init_vars["batch_stats"]))

    def init_fn(p_blk):
        st = opt.init(jax.tree_util.tree_map(lambda t: t[0], p_blk))
        return jax.tree_util.tree_map(lambda t: jnp.asarray(t)[None], st)

    opt_state = jax.jit(shard_map(
        init_fn, mesh=ctx.mesh, in_specs=(P(ctx.axis_name),),
        out_specs=P(ctx.axis_name), check_vma=False))(params)

    def step(p_blk, bs_blk, st_blk, x_blk, y_blk):
        p, bs, st = jax.tree_util.tree_map(
            lambda t: t[0], (p_blk, bs_blk, st_blk))
        x = x_blk[0].astype(jnp.float32) / 255.0 - 0.5
        y = y_blk[0]

        def loss_fn(p):
            logits, mut = model.apply(
                {"params": p, "batch_stats": bs}, x, train=True,
                mutable=["batch_stats"])
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()
            return loss, mut["batch_stats"]

        (loss, new_bs), g = jax.value_and_grad(loss_fn, has_aux=True)(p)
        upd, st = opt.update(g, st, p)
        p = optax.apply_updates(p, upd)
        return (jax.tree_util.tree_map(lambda t: t[None],
                                       (p, new_bs, st)) + (loss[None],))

    jitted = jax.jit(shard_map(
        step, mesh=ctx.mesh, in_specs=(P(ctx.axis_name),) * 5,
        out_specs=(P(ctx.axis_name),) * 4, check_vma=False),
        donate_argnums=(0, 1, 2))

    loss = None
    for epoch in range(epochs):
        losses = []
        for x, y in loader.epoch(epoch):
            params, stats, opt_state, loss = jitted(
                params, stats, opt_state, x, y)
            losses.append(loss)
        print(f"  epoch {epoch}: mean loss "
              f"{float(np.mean([np.asarray(l).mean() for l in losses])):.4f}")
    jax.block_until_ready(loss)
    # consensus model: params AND BatchNorm statistics averaged over ranks
    # (bf.allreduce_parameters semantics post-training)
    mean = lambda tree: jax.tree_util.tree_map(
        lambda t: np.asarray(t, np.float32).mean(axis=0), tree)
    return {"params": mean(params), "batch_stats": mean(stats)}


def accuracy(model, consensus, imgs, labels, batch=512) -> float:
    fn = jax.jit(lambda x: jnp.argmax(
        model.apply(consensus, x, train=False), -1))
    hits = 0
    for lo in range(0, len(labels), batch):
        x = jnp.asarray(imgs[lo:lo + batch], jnp.float32) / 255.0 - 0.5
        hits += int((np.asarray(fn(x)) == labels[lo:lo + batch]).sum())
    return hits / len(labels)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--train-size", type=int, default=12288)
    ap.add_argument("--test-size", type=int, default=2048)
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--batch-size", type=int, default=32, help="per rank")
    # linear-scaling-rule lr for the 8x32=256 effective batch; 144 updates
    # at lr 0.05 measured still on the loss plateau
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--weight-decay", type=float, default=1e-4)
    ap.add_argument("--filters", type=int, default=16,
                    help="ResNet-18 width (16 = CI budget; 64 = full)")
    ap.add_argument("--noise", type=float, default=0.5,
                    help="pixel-noise scale of the stand-in (0.5 saturates "
                         "both arms under the default budget; ~0.8 lands "
                         "them below ceiling, making the parity comparison "
                         "discriminative — pair with --target 0.85)")
    ap.add_argument("--shards", type=int, default=4)
    ap.add_argument("--data-dir", default=None,
                    help="existing TFRecord dir of real CIFAR shards")
    ap.add_argument("--prefetch", type=int, default=0)
    ap.add_argument("--target", type=float, default=0.90)
    ap.add_argument("--parity-pt", type=float, default=0.5)
    args = ap.parse_args()

    n = len(jax.devices())
    bf.init(topology=ExponentialTwoGraph(n))
    ctx = bf.get_context()
    t0 = time.time()

    with tempfile.TemporaryDirectory() as tmp:
        if args.data_dir:
            import glob as _glob

            paths = sorted(
                _glob.glob(os.path.join(args.data_dir, "*.tfr"))
                + _glob.glob(os.path.join(args.data_dir, "*.tfrecord")))
            full = TFRecordSource(paths)
            if len(full) <= args.test_size:
                raise SystemExit(
                    f"--data-dir holds {len(full)} examples <= test split "
                    f"{args.test_size}")
            split = len(full) - args.test_size
            test_imgs, test_labels = full[np.arange(split, len(full))]
            # train strictly excludes the held-out tail (mnist gate's
            # _Subset pattern): accuracy on trained-on data is no gate
            train_src = Subset(full, 0, split)
        else:
            imgs, labels = synth_cifar(args.train_size, seed=1,
                                       noise=args.noise)
            test_imgs, test_labels = synth_cifar(args.test_size, seed=999,
                                                 noise=args.noise)
            shard_size = (len(labels) + args.shards - 1) // args.shards
            paths = write_image_classification_shards(
                tmp, imgs, labels, shard_size=shard_size)
            train_src = TFRecordSource(paths)

        print(f"{len(train_src)} train examples; {n} ranks; "
              f"ResNet-18/{args.filters}w (cifar stem)")
        loader = DistributedLoader(train_src, args.batch_size, seed=5,
                                   prefetch=args.prefetch)

        model = ResNet18(num_classes=10, num_filters=args.filters,
                         dtype=jnp.float32, stem="cifar")
        init_vars = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 32, 32, 3)), train=True)

        base = optax.chain(optax.add_decayed_weights(args.weight_decay),
                           optax.sgd(args.lr, momentum=0.9))
        dec = DistributedNeighborAllreduceOptimizer(
            base, topology=ctx.schedule, axis_name=ctx.axis_name)
        c_dec = train(loader, model, dec, init_vars, args.epochs, ctx)
        acc_dec = accuracy(model, c_dec, test_imgs, test_labels)
        print(f"decentralized (exp2): test acc {acc_dec:.4f}")

        allr = DistributedGradientAllreduceOptimizer(
            base, axis_name=ctx.axis_name)
        c_all = train(loader, model, allr, init_vars, args.epochs, ctx)
        acc_all = accuracy(model, c_all, test_imgs, test_labels)
        print(f"allreduce:            test acc {acc_all:.4f}")

    print(f"wall time {time.time() - t0:.0f}s "
          f"({args.epochs} epochs x {loader.steps_per_epoch} steps x 2 runs)")
    assert acc_dec >= args.target, (
        f"FAIL: decentralized accuracy {acc_dec:.4f} < {args.target}")
    assert acc_dec >= acc_all - args.parity_pt / 100.0, (
        f"FAIL: decentralized {acc_dec:.4f} trails allreduce {acc_all:.4f} "
        f"by more than {args.parity_pt}pt")
    print(f"OK — conv-scale gate: decentralized ResNet-18 {acc_dec:.1%} >= "
          f"{args.target:.0%} and not trailing allreduce ({acc_all:.1%}) by "
          f"more than {args.parity_pt}pt, through TFRecord + "
          "DistributedLoader")


if __name__ == "__main__":
    main()
