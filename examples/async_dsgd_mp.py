"""Cross-process asynchronous decentralized SGD — self-asserting.

``examples/async_dsgd.py`` runs the reference's asynchronous execution model
(``DistributedWinPutOptimizer``, SURVEY.md §3.4) with rank *threads*.  This
example runs it the way the reference actually deploys — **one OS process
per rank** (``mpirun -np N``): each process exposes its landing window and
deposits into its neighbors' windows directly (``MPI_Put`` crossing a real
process boundary, no receiver involvement, no barrier anywhere in the
training loop).  ``--transport shm`` (default) backs the windows with named
POSIX shared memory (same-host ranks); ``--transport tcp`` serves each
process's windows over the TCP window server — the cross-host/DCN shape,
demoed here on loopback.

Each rank-process trains a small MLP regressor on its own shard of a
synthetic linear problem, with deliberately skewed step rates.  The parent
re-execs this file with ``--worker R`` per rank and asserts from rank 0's
report:

  1. the skew materialized (fastest rank >= 1.5x the steps of the slowest),
  2. push-sum mass is conserved exactly (sum of p == n to 1e-9),
  3. rank 0's loss fell by >= 50%,
  4. ranks agree: consensus gap small relative to parameter scale.

Run:  python examples/async_dsgd_mp.py [--ranks 2] [--duration 3]

``--resilient`` (tcp transport) arms the peer-fault-tolerance layer
(docs/resilience.md): deposit streams reconnect with bounded backoff and
replay idempotently, a dead peer is healed out of the mixing weights, and
the surviving set's mass audit stays exact.  Pair it with the chaos CLI
to watch one of three ranks get SIGKILLed mid-run and the survivors
finish anyway::

    bfchaos-tpu --spec "rank2:sigkill:at_step=25" -- \\
        python examples/async_dsgd_mp.py --ranks 3 --transport tcp \\
        --duration 4 --resilient
"""

import argparse
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def worker(rank: int, n: int, bdir: str, duration_s: float, lr: float,
           transport: str, resilient: bool = False):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from bluefog_tpu.runtime.async_windows import (FileBarrier,
                                                   run_async_dsgd_rank)
    from bluefog_tpu.topology import RingGraph

    # shard r of a synthetic linear regression y = X @ w* + noise
    rng = np.random.default_rng(1234)
    w_star = rng.standard_normal(16).astype(np.float32)
    X = rng.standard_normal((n * 64, 16)).astype(np.float32)
    y = X @ w_star + 0.01 * rng.standard_normal(n * 64).astype(np.float32)
    Xr = jnp.asarray(X[rank * 64:(rank + 1) * 64])
    yr = jnp.asarray(y[rank * 64:(rank + 1) * 64])

    params0 = {"w": jnp.zeros(16, jnp.float32), "b": jnp.zeros((), jnp.float32)}

    @jax.jit
    def lag(params):
        def loss_fn(p):
            pred = Xr @ p["w"] + p["b"]
            return jnp.mean((pred - yr) ** 2)

        return jax.value_and_grad(loss_fn)(params)

    def loss_and_grad(r, step, params):
        loss, grads = lag(params)
        return float(loss), grads

    # base sleep scales with rank count so the skew stays visible above
    # scheduler contention when many rank processes share few cores; the
    # pipelined tcp transport runs background sender/ack threads that
    # raise every rank's per-step floor by several ms, so its skew must
    # be an order larger to dominate
    base = 0.004 if transport == "tcp" else 0.0005
    skew_s = base * max(n - 1, 1) * (1.0 + 4.0 * rank / max(n - 1, 1))
    resilience = None
    if resilient:
        from bluefog_tpu.runtime.resilience import ResilienceConfig

        # a complete graph gives every survivor a direct stream to the
        # victim, so detection is transport-native on all of them
        resilience = ResilienceConfig(reconnect_base_s=0.05,
                                      reconnect_cap_s=0.3,
                                      reconnect_budget=4, seed=rank,
                                      barrier_timeout_s=20.0)
    from bluefog_tpu.topology import FullyConnectedGraph

    topo = (FullyConnectedGraph(n) if resilient and n > 2
            else RingGraph(n))
    report = run_async_dsgd_rank(
        topo, rank, params0, loss_and_grad,
        barrier=FileBarrier(bdir, n, rank), lr=lr, duration_s=duration_s,
        skew_s=skew_s, name=f"async_dsgd_mp_{os.path.basename(bdir)}",
        transport=transport, tcp_bind="127.0.0.1", resilience=resilience)

    if rank == 0:
        steps = report.steps_per_rank
        if report.dead_ranks:
            alive = [r for r in range(n) if r not in report.dead_ranks]
            assert min(steps[r] for r in alive) >= 5, steps
            if report.baseline_mass is not None:
                assert abs(report.total_mass - report.baseline_mass) \
                    <= 1e-9 * n, (report.total_mass, report.baseline_mass)
            print(f"steps/rank: {steps}  (rank(s) {report.dead_ranks} "
                  "died mid-run; survivors healed and finished)")
            print(f"surviving mass: {report.total_mass:.12f}  "
                  f"(post-heal baseline {report.baseline_mass})")
            print(f"OK — survived peer death over {transport}; audit "
                  "exact over the surviving set")
        else:
            assert min(steps) >= 5, f"a rank starved: {steps}"
            assert max(steps) >= 1.5 * min(steps), f"no skew in {steps}"
            assert abs(report.total_mass - n) < 1e-9 * n, report.total_mass
            l0 = report.losses[0]
            assert l0[-1] < 0.5 * l0[0], (l0[0], l0[-1])
            import numpy as np

            scale = float(np.abs(w_star).max())
            assert report.consensus_gap < 0.05 * scale, \
                report.consensus_gap
            print(f"steps/rank: {steps}  (skewed, barrier-free)")
            print(f"push-sum mass: {report.total_mass:.12f}  "
                  f"(== {n} exactly)")
            print(f"rank-0 loss: {l0[0]:.3f} -> {l0[-1]:.4f}")
            print(f"consensus gap: {report.consensus_gap:.2e}")
            print(f"OK — async DSGD spanned real OS processes over "
                  f"{transport} with no barrier")
    print(f"WORKER_DONE {rank}", flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=2)
    ap.add_argument("--duration", type=float, default=3.0, metavar="SECONDS")
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--transport", choices=["shm", "tcp"], default="shm",
                    help="deposit fabric: shm (same host) or tcp (the\n                    cross-host/DCN window server, demoed on loopback)")
    ap.add_argument("--resilient", action="store_true",
                    help="arm peer-fault tolerance (tcp): reconnect/"
                         "replay, self-healing gossip — pair with "
                         "bfchaos-tpu to kill a rank mid-run")
    ap.add_argument("--worker", type=int, default=None, help=argparse.SUPPRESS)
    ap.add_argument("--bdir", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.resilient and args.transport != "tcp":
        ap.error("--resilient requires --transport tcp (detection is "
                 "transport-native on the deposit streams)")

    if args.worker is not None:
        worker(args.worker, args.ranks, args.bdir, args.duration, args.lr,
               args.transport, args.resilient)
        return

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    with tempfile.TemporaryDirectory() as bdir:
        procs = [
            subprocess.Popen(
                [sys.executable, os.path.abspath(__file__),
                 "--ranks", str(args.ranks), "--duration", str(args.duration),
                 "--lr", str(args.lr), "--transport", args.transport,
                 "--worker", str(r), "--bdir", bdir]
                + (["--resilient"] if args.resilient else []),
                env=env, cwd=_REPO)
            for r in range(args.ranks)
        ]
        try:
            rcs = [p.wait(timeout=120 + args.duration * 4) for p in procs]
        except subprocess.TimeoutExpired:
            # one hung worker (e.g. stuck at a barrier because a peer died)
            # must not orphan the rest against a vanishing barrier dir
            for p in procs:
                p.kill()
            for p in procs:
                p.wait()
            print("FAILED: a worker timed out; all workers killed",
                  file=sys.stderr)
            sys.exit(1)
    if args.resilient:
        # under chaos a rank may legitimately die mid-run (that is the
        # demo); the verdict is rank 0's — it audits the survivors
        if rcs[0] != 0:
            print(f"FAILED: reporting rank exit codes {rcs}",
                  file=sys.stderr)
            sys.exit(1)
        dead = [r for r, rc in enumerate(rcs) if rc]
        if dead:
            print(f"(rank(s) {dead} were killed by chaos; survivors "
                  "audited clean)")
    elif any(rcs):
        print(f"FAILED: worker exit codes {rcs}", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
