"""Decentralized LeNet training — the reference's ``examples/pytorch_mnist.py``
(BASELINE.json config[0]: LeNet on ring topology, neighbor_allreduce),
TPU-native.

Each rank holds its own LeNet replica and a disjoint data shard; every step
runs local forward/backward and gossips parameters with ring neighbors via
``DistributedNeighborAllreduceOptimizer``.  The whole per-rank step (compute +
gossip) is one jitted ``shard_map`` program, so XLA overlaps the ppermute
traffic with backprop — the TPU equivalent of the reference's
hook-based comm/compute overlap (SURVEY.md §3.3).

This environment has no network, so MNIST is synthesized: 10 fixed random
class prototypes + noise.  Real MNIST drops in by replacing ``make_dataset``.

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      PALLAS_AXON_POOL_IPS= python examples/mnist_decentralized.py
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))  # repo-root run

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

import bluefog_tpu as bf
from bluefog_tpu.metrics import health as bf_health
from bluefog_tpu.models import LeNet5
from bluefog_tpu.optim import DistributedNeighborAllreduceOptimizer
from bluefog_tpu.parallel.api import shard_map
from bluefog_tpu.topology import RingGraph


def make_dataset(n_per_rank, n_ranks, key, noise=0.35):
    """Synthetic MNIST: 10 random 28x28 prototypes + Gaussian noise."""
    kp, kx, ky = jax.random.split(key, 3)
    protos = jax.random.normal(kp, (10, 28, 28, 1)) * 0.8
    labels = jax.random.randint(ky, (n_ranks, n_per_rank), 0, 10)
    imgs = protos[labels] + noise * jax.random.normal(
        kx, (n_ranks, n_per_rank, 28, 28, 1)
    )
    return imgs, labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=32, help="per-rank batch")
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--n-per-rank", type=int, default=512)
    ap.add_argument("--atc", action="store_true", help="adapt-then-combine")
    args = ap.parse_args()

    n = len(jax.devices())
    bf.init(topology=RingGraph(n))
    ctx = bf.get_context()
    print(f"ranks={n} topology={bf.load_topology().name}")

    model = LeNet5()
    opt = DistributedNeighborAllreduceOptimizer(
        optax.sgd(args.lr, momentum=0.9),
        topology=bf.get_context().schedule,
        axis_name=ctx.axis_name,
        atc=args.atc,
    )

    key = jax.random.PRNGKey(42)
    imgs, labels = make_dataset(args.n_per_rank, n, key)
    init_params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))

    # all ranks start from identical params (reference: broadcast_parameters)
    params = bf.rank_shard(bf.rank_stack(init_params))
    imgs = bf.rank_shard(imgs)
    labels = bf.rank_shard(labels)

    steps_per_epoch = args.n_per_rank // args.batch_size

    def init_opt(params_blk):
        st = opt.init(jax.tree_util.tree_map(lambda t: t[0], params_blk))
        return jax.tree_util.tree_map(lambda t: jnp.asarray(t)[None], st)

    opt_state = jax.jit(shard_map(
        init_opt, mesh=ctx.mesh, in_specs=(P(ctx.axis_name),),
        out_specs=P(ctx.axis_name), check_vma=False,
    ))(params)

    def epoch_body(params_blk, opt_blk, imgs_blk, labels_blk):
        """One epoch for this rank (block leading dim 1); optimizer state
        (momentum, gossip counters) persists across epochs."""
        p, st = jax.tree_util.tree_map(lambda t: t[0], (params_blk, opt_blk))
        x, y = imgs_blk[0], labels_blk[0]

        def loss_fn(p, xb, yb):
            logits = model.apply(p, xb)
            return optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean()

        def step(carry, i):
            p, st = carry
            xb = lax.dynamic_slice_in_dim(x, i * args.batch_size, args.batch_size)
            yb = lax.dynamic_slice_in_dim(y, i * args.batch_size, args.batch_size)
            loss, g = jax.value_and_grad(loss_fn)(p, xb, yb)
            upd, st = opt.update(g, st, p)
            return (optax.apply_updates(p, upd), st), loss

        (p, st), losses = lax.scan(step, (p, st), jnp.arange(steps_per_epoch))
        acc = (model.apply(p, x).argmax(-1) == y).mean()
        return (jax.tree_util.tree_map(lambda t: t[None], (p, st))
                + (losses.mean()[None], acc[None]))

    train_epoch = jax.jit(shard_map(
        epoch_body, mesh=ctx.mesh,
        in_specs=(P(ctx.axis_name),) * 4,
        out_specs=(P(ctx.axis_name),) * 4,
        check_vma=False,
    ))

    # observability (active only under BLUEFOG_TPU_METRICS=<file.jsonl> or
    # bf.metrics_start()): the instrumented collectives count gossip bytes
    # from inside the jitted epoch; the health gauges below add consensus
    # distance and measured-vs-predicted mixing contraction per epoch
    # fed once per EPOCH while each jitted epoch runs steps_per_epoch
    # gossip rounds — rounds_per_update scales the spectral-gap
    # prediction to the same cadence (|lambda_2|^R)
    mixing = bf_health.MixingTracker(ctx.schedule,
                                     rounds_per_update=steps_per_epoch)
    for epoch in range(args.epochs):
        params, opt_state, losses, accs = train_epoch(params, opt_state, imgs, labels)
        if bf.metrics_active():
            mixing.update(bf_health.consensus_distance_stacked(
                jax.device_get(params)))
            bf.metrics.step(epoch)
        print(f"epoch {epoch}: mean loss {np.asarray(losses).mean():.4f}  "
              f"mean local acc {np.asarray(accs).mean():.3f}")

    # post-training consensus average (reference: bf.allreduce_parameters)
    params = bf.allreduce_parameters(params)
    final_acc = float(np.asarray(accs).mean())
    total_steps = steps_per_epoch * args.epochs
    if total_steps >= 30:
        assert final_acc > 0.9, f"training failed to learn (acc={final_acc})"
        print("OK")
    else:
        print(f"OK (only {total_steps} steps run; acc={final_acc:.3f} — "
              "too few steps for the convergence check)")


if __name__ == "__main__":
    main()
