"""Elastic re-topology demo: lose half the slice, resume, keep converging.

The reference has no elastic story — a rank failure kills the MPI job
(SURVEY.md §5).  Here the same checkpoint drives training across a world
change: 8 gossip ranks train a quadratic consensus problem, checkpoint, and
then a "failure" takes half the slice away — the run resumes on 4 ranks via
``run_with_restart``'s automatic rank-axis resize (orphaned replicas fold
into survivors by averaging, so no rank's progress is lost) and converges to
the same optimum.

Self-asserting; exits nonzero on failure.

Run:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  PALLAS_AXON_POOL_IPS= python examples/elastic_resume.py
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

import bluefog_tpu as bf
from bluefog_tpu.optim import DistributedNeighborAllreduceOptimizer
from bluefog_tpu.parallel.api import shard_map
from bluefog_tpu.topology import ExponentialTwoGraph, RingGraph
from bluefog_tpu.utils.checkpoint import CheckpointManager, run_with_restart

DIM = 6


def targets(n):
    """Rank r's local objective is ||w - c_r||^2; the consensus optimum is
    mean(c) — identical no matter how many ranks share the work."""
    return jnp.stack([jnp.full((DIM,), float(r)) for r in range(n)])


def make_phase(n, devices, steps, ckpt_every, mgr, seen=None):
    """A training phase at world size n: returns train_fn for
    run_with_restart (state = rank-stacked params).  ``seen`` (optional
    dict) records the start step the phase was entered at."""
    bf.shutdown()
    ctx = bf.init(topology=(ExponentialTwoGraph(n) if n > 2 else RingGraph(n)),
                  devices=devices)
    opt = DistributedNeighborAllreduceOptimizer(
        optax.sgd(0.05), topology=ctx.schedule, axis_name=ctx.axis_name)
    c = bf.rank_shard(targets(n))

    def body(w_blk, c_blk):
        w = w_blk[0]
        st = opt.init(w)

        def one(carry, _):
            w, st = carry
            g = w - c_blk[0]
            upd, st = opt.update(g, st, w)
            return (optax.apply_updates(w, upd), st), None

        (w, _), _ = lax.scan(one, (w, st), None, length=ckpt_every)
        return w[None]

    step_fn = jax.jit(shard_map(
        body, mesh=ctx.mesh, in_specs=(P(ctx.axis_name),) * 2,
        out_specs=P(ctx.axis_name), check_vma=False))

    def train_fn(state, start):
        if seen is not None:
            seen["start"] = start
        # state = {"w": (n, DIM)} — Orbax stores containers, not bare arrays
        w = bf.rank_shard(jnp.asarray(np.asarray(state["w"])))
        for s in range(start, steps // ckpt_every):
            w = step_fn(w, c)
            mgr.save(s + 1, {"w": w})
        mgr.wait()
        return {"w": w}

    return train_fn


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=120,
                    help="total scan steps per phase")
    ap.add_argument("--ckpt-every", type=int, default=30)
    args = ap.parse_args()

    devs = jax.devices()
    if len(devs) < 8:
        raise SystemExit("need 8 devices (use the CPU-mesh env, see docstring)")
    ckdir = tempfile.mkdtemp(prefix="elastic_")
    mgr = CheckpointManager(ckdir, async_save=False)

    # ---- phase 1: world 8 ------------------------------------------------
    train8 = make_phase(8, devs[:8], args.steps, args.ckpt_every, mgr)
    w8 = np.asarray(run_with_restart(train8, mgr,
                                     {"w": jnp.zeros((8, DIM))})["w"])
    print(f"world 8 after {args.steps} steps: mean w = {w8.mean(0)[:3]}...")

    # ---- "failure": half the slice is gone; resume at world 4 ------------
    # run_with_restart restores the latest world-8 checkpoint and resizes it
    # onto the 4-rank template (rank j folds ranks j and j+4 by mean).
    seen = {}
    train4 = make_phase(4, devs[:4], 2 * args.steps, args.ckpt_every, mgr,
                        seen=seen)
    w4 = np.asarray(run_with_restart(train4, mgr,
                                     {"w": jnp.zeros((4, DIM))})["w"])

    # World 8's optimum is mean(0..7) = 3.5; world 4's local targets alone
    # would give 1.5 — reaching ~1.5 after resume proves training CONTINUED
    # on the new world (re-anchored to its objective) from folded state, not
    # from scratch (folded start = 3.5-ish, far from 0).
    print(f"world 4 after resume: mean w = {w4.mean(0)[:3]}...")
    gap = np.abs(w4.mean(0) - 1.5).max()
    spread = (w4.max(0) - w4.min(0)).max()
    print(f"optimum gap {gap:.3f}, consensus spread {spread:.3f}, "
          f"phase-2 entered at checkpoint step {seen.get('start')}")

    ok = True
    if not seen.get("start"):
        ok = False
        print("FAIL: phase 2 did not resume from the world-8 checkpoint "
              "(started from scratch)")
    if gap > 0.3:
        ok = False
        print("FAIL: resumed world did not converge to its consensus optimum")
    if spread > 0.3:
        ok = False
        print("FAIL: resumed ranks did not reach consensus")
    mgr.close()
    if not ok:
        sys.exit(1)
    print("OK — resumed on half the world from the same checkpoint and "
          "converged (elastic re-topology)")


if __name__ == "__main__":
    main()
