"""Decentralized ResNet-50 ImageNet training — BASELINE.json config[1]
(ResNet-50/ImageNet, ExponentialTwoGraph, DistributedNeighborAllreduceOptimizer),
the reference's ImageNet example (upstream ``examples/pytorch_imagenet_resnet50.py``;
SURVEY.md §2.2 "Examples") rebuilt TPU-native.

Each rank trains its own ResNet replica on a disjoint shard and gossips
parameters with its exp2 neighbors every step; compute + gossip is one jitted
``shard_map`` program so XLA overlaps the permutes with backprop (the TPU
equivalent of the reference's hook overlap, SURVEY.md §3.3).  The standard
90-epoch recipe pieces are here: per-rank batch, 5-epoch linear warmup →
cosine decay, label smoothing, SGD momentum + weight decay, top-1 eval, and
periodic (optionally consensus-mode) checkpoints.

Data: ``--data-dir`` pointing at ``train-*.tfrecord / val-*.tfrecord`` shards
(tf.Example with raw uint8 image/shape/label — see
``bluefog_tpu.data.write_image_classification_shards``) or at
``{train,val}_{images,labels}.npy`` pairs (memory-mapped) trains real
ImageNet; without it a deterministic synthetic stand-in of the same shapes
keeps the example runnable in this offline environment.

Run (8 virtual devices):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  PALLAS_AXON_POOL_IPS= python examples/imagenet_resnet.py \
      --image-size 64 --batch-size 8 --steps-per-epoch 4 --epochs 2
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))  # repo-root run

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import bluefog_tpu as bf
from bluefog_tpu.data import (
    ArraySource,
    DistributedLoader,
    SyntheticClassificationSource,
)
from bluefog_tpu.models import ResNet50
from bluefog_tpu.optim import (
    DistributedGradientAllreduceOptimizer,
    DistributedNeighborAllreduceOptimizer,
)
from bluefog_tpu.parallel.api import shard_map
from bluefog_tpu.topology import ExponentialTwoGraph, MeshGrid2DGraph, RingGraph
from bluefog_tpu.utils.checkpoint import CheckpointManager

TOPOLOGIES = {
    "exp2": ExponentialTwoGraph,
    "ring": RingGraph,
    "grid": MeshGrid2DGraph,
}


def make_sources(args, n_ranks):
    if args.data_dir:
        import glob

        from bluefog_tpu.data import TFRecordSource

        # TFRecord shards take precedence (train-*.tfrecord / val-*.tfrecord,
        # e.g. from bluefog_tpu.data.write_image_classification_shards);
        # otherwise fall back to memory-mapped .npy pairs.
        if glob.glob(os.path.join(args.data_dir, "train-*.tfrecord")):
            train = TFRecordSource(
                os.path.join(args.data_dir, "train-*.tfrecord"))
            val = TFRecordSource(os.path.join(args.data_dir, "val-*.tfrecord"))
            return train, val

        def load(name):
            return np.load(os.path.join(args.data_dir, name), mmap_mode="r")

        train = ArraySource(load("train_images.npy"), load("train_labels.npy"))
        val = ArraySource(load("val_images.npy"), load("val_labels.npy"))
        return train, val
    shape = (args.image_size, args.image_size, 3)
    n_train = args.steps_per_epoch * args.batch_size * n_ranks
    train = SyntheticClassificationSource(
        n_train, shape=shape, num_classes=args.num_classes, seed=0)
    val = SyntheticClassificationSource(
        max(n_train // 8, args.batch_size * n_ranks), shape=shape,
        num_classes=args.num_classes, seed=1)
    return train, val


def lr_schedule(args, steps_per_epoch):
    base = args.lr * args.batch_size / 256.0  # linear scaling rule
    warmup = optax.linear_schedule(0.0, base, args.warmup_epochs * steps_per_epoch)
    cosine = optax.cosine_decay_schedule(
        base, max((args.epochs - args.warmup_epochs), 1) * steps_per_epoch)
    return optax.join_schedules([warmup, cosine],
                                [args.warmup_epochs * steps_per_epoch])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default=None,
                    help="dir with {train,val}-*.tfrecord shards or "
                         "{train,val}_{images,labels}.npy; synthetic if unset")
    ap.add_argument("--epochs", type=int, default=90)
    ap.add_argument("--steps-per-epoch", type=int, default=32,
                    help="synthetic epoch length (ignored with --data-dir)")
    ap.add_argument("--batch-size", type=int, default=128, help="per-rank batch")
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--num-classes", type=int, default=1000)
    ap.add_argument("--lr", type=float, default=0.1, help="base lr at batch 256")
    ap.add_argument("--warmup-epochs", type=int, default=5)
    ap.add_argument("--weight-decay", type=float, default=1e-4)
    ap.add_argument("--label-smoothing", type=float, default=0.1)
    ap.add_argument("--topology", choices=sorted(TOPOLOGIES), default="exp2")
    ap.add_argument("--optimizer", choices=["neighbor", "allreduce"],
                    default="neighbor",
                    help="decentralized gossip vs centralized baseline")
    ap.add_argument("--atc", action="store_true", help="adapt-then-combine")
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--checkpoint-every", type=int, default=1, metavar="EPOCHS")
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--eval-every", type=int, default=1, metavar="EPOCHS")
    ap.add_argument("--stem", choices=["conv", "s2d"], default="conv",
                    help="s2d = space-to-depth stem (same function class, "
                         "4x MXU input-lane occupancy on the stem conv)")
    ap.add_argument("--fp32", action="store_true",
                    help="train in float32 (default bfloat16)")
    args = ap.parse_args()

    n = len(jax.devices())
    bf.init(topology=TOPOLOGIES[args.topology](n))
    ctx = bf.get_context()
    dtype = jnp.float32 if args.fp32 else jnp.bfloat16
    print(f"ranks={n} topology={args.topology} optimizer={args.optimizer} "
          f"dtype={dtype.__name__}")

    train_src, val_src = make_sources(args, n)
    loader = DistributedLoader(train_src, args.batch_size)
    val_loader = DistributedLoader(val_src, args.batch_size, shuffle=False)
    steps_per_epoch = loader.steps_per_epoch

    model = ResNet50(num_classes=args.num_classes, dtype=dtype, stem=args.stem)
    sched = lr_schedule(args, steps_per_epoch)
    base_opt = optax.chain(
        optax.add_decayed_weights(args.weight_decay),
        optax.sgd(sched, momentum=0.9, nesterov=True),
    )
    if args.optimizer == "neighbor":
        opt = DistributedNeighborAllreduceOptimizer(
            base_opt, topology=ctx.schedule, axis_name=ctx.axis_name,
            atc=args.atc)
    else:
        opt = DistributedGradientAllreduceOptimizer(
            base_opt, axis_name=ctx.axis_name)

    x0 = jnp.zeros((1, args.image_size, args.image_size, 3), dtype)
    variables = model.init(jax.random.PRNGKey(0), x0, train=True)
    # identical start on every rank — the reference's broadcast_parameters
    params = bf.rank_shard(bf.rank_stack(variables["params"]))
    batch_stats = bf.rank_shard(bf.rank_stack(variables["batch_stats"]))

    def init_opt(p_blk):
        p = jax.tree_util.tree_map(lambda t: t[0], p_blk)
        st = opt.init(p)
        return jax.tree_util.tree_map(lambda t: jnp.asarray(t)[None], st)

    opt_state = jax.jit(shard_map(
        init_opt, mesh=ctx.mesh, in_specs=(P(ctx.axis_name),),
        out_specs=P(ctx.axis_name), check_vma=False))(params)

    def prep(x):
        if x.dtype == jnp.uint8:  # raw images: map [0,255] → [-1,1]
            x = x.astype(dtype) / 127.5 - 1.0
        return x.astype(dtype)

    def train_step(p_blk, bs_blk, opt_blk, x_blk, y_blk):
        p, bs, st = jax.tree_util.tree_map(
            lambda t: t[0], (p_blk, bs_blk, opt_blk))
        x, y = prep(x_blk[0]), y_blk[0]

        def loss_fn(p):
            logits, mut = model.apply(
                {"params": p, "batch_stats": bs}, x, train=True,
                mutable=["batch_stats"])
            loss = optax.softmax_cross_entropy(
                logits,
                optax.smooth_labels(
                    jax.nn.one_hot(y, args.num_classes),
                    args.label_smoothing)).mean()
            return loss, (mut["batch_stats"], logits)

        (loss, (new_bs, logits)), g = jax.value_and_grad(
            loss_fn, has_aux=True)(p)
        upd, st = opt.update(g, st, p)
        p = optax.apply_updates(p, upd)
        acc = (jnp.argmax(logits, -1) == y).mean()
        out = jax.tree_util.tree_map(lambda t: t[None], (p, new_bs, st))
        return out + (loss[None], acc[None])

    step_fn = jax.jit(shard_map(
        train_step, mesh=ctx.mesh, in_specs=(P(ctx.axis_name),) * 5,
        out_specs=(P(ctx.axis_name),) * 5, check_vma=False,
    ), donate_argnums=(0, 1, 2))

    def eval_step(p_blk, bs_blk, x_blk, y_blk):
        p, bs = jax.tree_util.tree_map(lambda t: t[0], (p_blk, bs_blk))
        logits = model.apply(
            {"params": p, "batch_stats": bs}, prep(x_blk[0]), train=False)
        hits = (jnp.argmax(logits, -1) == y_blk[0]).sum()
        return hits[None]

    eval_fn = jax.jit(shard_map(
        eval_step, mesh=ctx.mesh, in_specs=(P(ctx.axis_name),) * 4,
        out_specs=P(ctx.axis_name), check_vma=False))

    mgr = None
    start_epoch = 0
    if args.checkpoint_dir:
        mgr = CheckpointManager(args.checkpoint_dir)
        if args.resume and mgr.latest_step() is not None:
            state = mgr.restore(template={
                "params": params, "batch_stats": batch_stats,
                "opt_state": opt_state,
            })
            params, batch_stats, opt_state = (
                bf.rank_shard(state["params"]),
                bf.rank_shard(state["batch_stats"]),
                bf.rank_shard(state["opt_state"]),
            )
            start_epoch = mgr.latest_step()
            print(f"resumed from epoch {start_epoch}")

    for epoch in range(start_epoch, args.epochs):
        t0 = time.perf_counter()
        loss = acc = None
        for x, y in loader.epoch(epoch):
            params, batch_stats, opt_state, loss, acc = step_fn(
                params, batch_stats, opt_state, x, y)
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        ips = steps_per_epoch * args.batch_size * n / dt
        print(f"epoch {epoch:3d}  loss {np.mean(loss):.4f}  "
              f"train-acc {np.mean(acc):.3f}  "
              f"{ips:,.0f} img/s ({ips / n:,.0f}/chip)  "
              f"lr {sched(epoch * steps_per_epoch + steps_per_epoch - 1):.4f}")

        # BN running stats never appear in the gossip (only params do), so
        # each rank's batch_stats drift apart on disjoint shards.  Average
        # them across ranks before they are consumed (eval / checkpoint) —
        # the analog of the reference re-synchronizing buffers with
        # broadcast_parameters before evaluation.
        synced_bs = batch_stats
        if (args.eval_every and (epoch + 1) % args.eval_every == 0) or (
                mgr and (epoch + 1) % args.checkpoint_every == 0):
            synced_bs = bf.allreduce(batch_stats)

        if args.eval_every and (epoch + 1) % args.eval_every == 0:
            hits = 0
            for x, y in val_loader.epoch(0):
                hits += int(np.sum(eval_fn(params, synced_bs, x, y)))
            total = val_loader.steps_per_epoch * args.batch_size * n
            print(f"          val top-1 {hits / total:.4f}  "
                  f"({hits}/{total})")

        if mgr and (epoch + 1) % args.checkpoint_every == 0:
            mgr.save(epoch + 1, {
                "params": params, "batch_stats": synced_bs,
                "opt_state": opt_state,
            })
    if mgr:
        mgr.wait()
        mgr.close()
    print("OK")


if __name__ == "__main__":
    main()
