"""Long-context LM training with sequence parallelism (ring / Ulysses).

No reference counterpart (SURVEY.md §5: long-context absent upstream) — this
is the capability the framework adds on top of the gossip machinery: the
global sequence is sharded over the mesh axis, KV blocks rotate around the
ICI ring (:func:`bluefog_tpu.ops.ring_attention.ring_attention`), and each
device holds O(T/n) activations, n× longer context than a single chip.  With
``--attn ulysses`` the same model trains with all-to-all head/sequence
resharding instead; ``--remat`` additionally checkpoints each block.

Task: synthetic induction — the sequence is periodic with period P, so the
model can drive next-token loss to ~0 only by attending ≥ P tokens back;
with the period spanning multiple shards, learning proves the cross-shard
attention path works.

Run (8 virtual devices, global sequence 512 = 8 x 64):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  PALLAS_AXON_POOL_IPS= python examples/longcontext_lm.py --steps 60
"""

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))  # repo-root run

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

import bluefog_tpu as bf
from bluefog_tpu.models import GPTConfig, TransformerLM
from bluefog_tpu.ops.ring_attention import all_to_all_attention, ring_attention
from bluefog_tpu.parallel.api import shard_map


def make_batch(key, batch, t_global, vocab, period):
    """Periodic sequences: tokens repeat with the given period."""
    motif = jax.random.randint(key, (batch, period), 1, vocab)
    reps = -(-t_global // period)
    return jnp.tile(motif, (1, reps))[:, :t_global]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--attn", choices=["ring", "ring-zigzag", "ulysses"],
                    default="ring")
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--t-local", type=int, default=64,
                    help="sequence tokens per device")
    ap.add_argument("--period", type=int, default=128,
                    help="repeat period; must divide the global length and "
                         "exceed t-local to force cross-shard attention")
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--remat", action="store_true")
    args = ap.parse_args()

    n = len(jax.devices())
    bf.init()
    ctx = bf.get_context()
    t_global = n * args.t_local
    if args.period >= t_global:
        raise SystemExit("--period must be < global sequence length")
    if t_global % args.period:
        # otherwise the wrap-around target at the last position breaks the
        # periodicity and carries irreducible loss
        raise SystemExit(f"--period {args.period} must divide the global "
                         f"sequence length {t_global}")

    cfg = GPTConfig(vocab_size=256, hidden_size=128, num_layers=2,
                    num_heads=8, max_position=t_global, dtype=jnp.float32,
                    remat=args.remat)
    lm = TransformerLM(cfg)
    print(f"ranks={n} global_seq={t_global} attn={args.attn} "
          f"period={args.period} remat={args.remat}")

    zigzag = args.attn == "ring-zigzag"
    if args.attn == "ring":
        attn = functools.partial(ring_attention, axis_name=ctx.axis_name,
                                 causal=True)
    elif zigzag:
        attn = functools.partial(ring_attention, axis_name=ctx.axis_name,
                                 causal=True, layout="zigzag")
    else:
        attn = functools.partial(all_to_all_attention,
                                 axis_name=ctx.axis_name, causal=True,
                                 backend="auto")

    tokens = make_batch(jax.random.PRNGKey(1), args.batch, t_global, 256,
                        args.period)
    params = lm.init(jax.random.PRNGKey(0), tokens[:, :args.t_local])
    opt = optax.adam(args.lr)
    opt_state = opt.init(params)

    if zigzag:
        # the load-balanced layout's local block is NOT contiguous (front
        # chunk r + mirrored back chunk 2n-1-r), so global next-token
        # targets are computed in global order then resharded like the
        # tokens, and per-token global positions are built from the rank id
        from bluefog_tpu.ops.ring_attention import zigzag_shard

        if args.t_local % 2:
            raise SystemExit("--t-local must be even for ring-zigzag")
        targets_global = jnp.concatenate([tokens[:, 1:], tokens[:, :1]], 1)
        tokens_in = zigzag_shard(tokens, n)
        targets_in = zigzag_shard(targets_global, n)
        c = args.t_local // 2
    else:
        tokens_in, targets_in = tokens, tokens  # targets via ppermute below

    def lm_step(params, opt_state, tokens_blk, tgt_blk):
        # tokens_blk: (B, T_local) — this shard's block of the sequence
        r = lax.axis_index(ctx.axis_name)

        def loss_fn(p):
            if zigzag:
                pos = jnp.concatenate(
                    [r * c + jnp.arange(c),
                     (2 * n - 1 - r) * c + jnp.arange(c)])[None, :]
                logits = lm.apply(p, tokens_blk, attn_fn=attn, positions=pos)
                tgt = tgt_blk
            else:
                logits = lm.apply(p, tokens_blk, attn_fn=attn,
                                  position_offset=r * tokens_blk.shape[1])
                # next-token targets across shard boundaries: first token of
                # the NEXT rank's block wraps in (global periodic sequence)
                nxt = lax.ppermute(
                    tokens_blk[:, :1], ctx.axis_name,
                    [(i, (i - 1) % n) for i in range(n)])
                tgt = jnp.concatenate([tokens_blk[:, 1:], nxt], axis=1)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tgt).mean()

        loss, g = jax.value_and_grad(loss_fn)(params)
        g = jax.tree_util.tree_map(lambda t: lax.pmean(t, ctx.axis_name), g)
        upd, opt_state = opt.update(g, opt_state)
        return (optax.apply_updates(params, upd), opt_state,
                lax.pmean(loss, ctx.axis_name))

    step = jax.jit(shard_map(
        lm_step, mesh=ctx.mesh,
        in_specs=(P(), P(), P(None, ctx.axis_name), P(None, ctx.axis_name)),
        out_specs=(P(), P(), P()), check_vma=False,
    ), donate_argnums=(0, 1))

    first = last = None
    t0 = time.perf_counter()
    for s in range(args.steps):
        params, opt_state, loss = step(params, opt_state, tokens_in,
                                       targets_in)
        loss = float(loss)
        first = first if first is not None else loss
        last = loss
        if s % 10 == 0 or s == args.steps - 1:
            print(f"step {s:4d}  loss {loss:.4f}")
    jax.block_until_ready(params)
    dt = time.perf_counter() - t0
    tps = args.steps * args.batch * t_global / dt
    print(f"\n{tps:,.0f} tokens/s total ({tps / n:,.0f}/chip)  "
          f"loss {first:.3f} -> {last:.3f}")
    if last > 0.7 * first:
        print("FAIL: loss barely moved — cross-shard attention suspect")
        sys.exit(1)
    print("OK — induction learned across shard boundaries")


if __name__ == "__main__":
    main()
