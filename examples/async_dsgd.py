"""Asynchronous decentralized SGD on a real model — self-asserting.

The reference's production asynchronous path is ``DistributedWinPutOptimizer``:
each rank pushes its parameters one-sidedly into neighbors' MPI windows every
step and merges whatever has landed, with no global barrier — ranks step at
whatever rate their hardware allows (``bluefog/torch/optimizers.py`` +
``bluefog/torch/mpi_win_ops.cc``, SURVEY.md §3.4).

This example runs the same execution model on the TPU build's host runtime:
``DistributedWinPutOptimizer(async_=True)`` drives 8 rank threads training
**LeNet-5** on disjoint synthetic shards with a deliberate 5x step-rate skew.
Gradients are jitted jax on real model parameter pytrees (bridged into the
native C++ window table by ``TreePacker``); deposits are passive-target
(receivers need not be listening); consumes are exactly-once.

Asserts, and exits nonzero on failure:
  1. the skew materialized (fastest rank took >= 2x the steps of the slowest),
  2. loss fell by >= 35% on every rank that got scheduled (>= 25% of the
     median step count — a rank starved by host load takes its model from
     neighbors' deposits; the consensus checks still bind for it),
  3. push-sum mass is conserved exactly (sum of p == n to 1e-9),
  4. ranks agree: consensus gap is small relative to parameter scale.

Run:  python examples/async_dsgd.py            (any backend; CPU is fine)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from bluefog_tpu.models import LeNet5
from bluefog_tpu.optim import DistributedWinPutOptimizer
from bluefog_tpu.topology import ExponentialTwoGraph


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=8)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--duration", type=float, default=10.0, metavar="SECONDS")
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()
    n = args.ranks

    model = LeNet5(num_classes=10)
    params0 = model.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, 28, 28, 1), jnp.float32))

    # Disjoint per-rank shards of a learnable synthetic problem: class = which
    # of 10 fixed random templates the (noisy) image correlates with most.
    rng = np.random.default_rng(0)
    templates = rng.standard_normal((10, 28, 28, 1)).astype(np.float32)
    per_rank_batches = 16
    data = []
    for r in range(n):
        labels = rng.integers(0, 10, size=(per_rank_batches, args.batch))
        noise = rng.standard_normal(
            (per_rank_batches, args.batch, 28, 28, 1)).astype(np.float32)
        imgs = 0.7 * templates[labels] + 0.5 * noise
        data.append((jnp.asarray(imgs), jnp.asarray(labels)))

    @jax.jit
    def loss_grad(params, x, y):
        def loss_fn(p):
            logits = model.apply(p, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()
        return jax.value_and_grad(loss_fn)(params)

    def loss_and_grad(rank, step, params):
        x, y = data[rank]
        b = step % per_rank_batches
        loss, g = loss_grad(params, x[b], y[b])
        return float(loss), g

    opt = DistributedWinPutOptimizer(
        optax.sgd(args.lr), topology=ExponentialTwoGraph(n),
        axis_name="bf", async_=True, lr=args.lr)

    print(f"async DSGD: {n} rank threads, LeNet-5, exp2 topology, "
          f"rank-dependent compute skew, {args.duration:.0f}s budget")
    # Rank-dependent extra compute time per step (the gradient itself costs
    # ~the same everywhere, so the skew must dominate it to be observable).
    skew = [0.3 * r / max(n - 1, 1) for r in range(n)]
    report = opt.run(params0, loss_and_grad, duration_s=args.duration,
                     skew=skew)

    # Judge the *drained* final model (all in-flight mass folded in): the
    # in-loop curve of a fast rank is noisy by construction — a slow
    # neighbor's deposit carries large mass from an older model and yanks
    # the de-biased iterate until gossip re-absorbs it.
    first = [ls[0] for ls in report.losses]
    last = []
    for r in range(n):
        x, y = data[r]
        fl = [float(loss_grad(report.final_params[r], x[b], y[b])[0])
              for b in range(4)]
        last.append(float(np.mean(fl)))
    drop = [1 - l / f for f, l in zip(first, last)]
    scale = max(float(np.abs(np.asarray(jax.device_get(l))).max())
                for l in jax.tree_util.tree_leaves(report.final_params[0]))
    print(f"steps/rank: {report.steps_per_rank}")
    print(f"loss first->last per rank: " +
          " ".join(f"{f:.2f}->{l:.2f}" for f, l in zip(first, last)))
    print(f"total mass: {report.total_mass:.9f} (expect {n})")
    print(f"consensus gap: {report.consensus_gap:.4f} "
          f"(param scale {scale:.2f})")

    ok = True
    ratio = max(report.steps_per_rank) / max(min(report.steps_per_rank), 1)
    if ratio < 2.0:
        ok = False
        print(f"FAIL: step-rate skew did not materialize (ratio {ratio:.1f})")
    # Per-rank convergence is required of every rank that actually got
    # scheduled (>= 25% of the median step count).  A rank starved by host
    # load takes its model almost entirely from neighbors' deposits, so its
    # LOCAL loss can lag while the consensus checks below still hold —
    # observed as a flake when several heavy jobs share this host's cores.
    med = float(np.median(report.steps_per_rank))
    active = [r for r in range(n)
              if report.steps_per_rank[r] >= 0.25 * med]
    active_drop = [drop[r] for r in active]
    if min(active_drop) < 0.35:
        ok = False
        print(f"FAIL: loss did not converge "
              f"(min active-rank drop {min(active_drop):.0%})")
    if len(active) < n:
        print(f"note: {n - len(active)} rank(s) starved by host load "
              f"(steps {report.steps_per_rank}); their local-loss check "
              "was waived, consensus checks still apply")
    if abs(report.total_mass - n) > 1e-9:
        ok = False
        print(f"FAIL: mass not conserved: {report.total_mass!r} != {n}")
    if report.consensus_gap > 0.25 * scale:
        ok = False
        print("FAIL: ranks did not reach consensus")
    if not ok:
        sys.exit(1)
    print("OK — asynchronous decentralized training: skewed ranks converged, "
          "mass conserved")


if __name__ == "__main__":
    main()
