"""Epoch-scale convergence gate through the REAL data pipeline — self-asserting.

The reference's headline correctness claim is `examples/pytorch_mnist.py`
(BASELINE.json config[0]): LeNet, decentralized gossip, converging to the
same accuracy as allreduce.  This gate puts an accuracy number behind that
claim at epoch scale, end to end through the framework's own data path:

  dataset --> TFRecord shards (framework writer/codec)
          --> TFRecordSource (native framing index, mmap random access)
          --> DistributedLoader (epoch shuffling, rank sharding, prefetch)
          --> jitted shard_map train step (LeNet + gossip optimizer)

This environment has no network egress, so the dataset is a deterministic
MNIST stand-in: 10 fixed random 28x28 prototypes, each sample a randomly
shifted prototype plus Gaussian noise, quantized to uint8 (a linear probe
plateaus well below 97% at the default noise; LeNet separates it cleanly).
Real MNIST drops in by pointing --data-dir at pre-written shards.

Asserts (exits nonzero on failure):
  1. decentralized (exp2 neighbor_allreduce) consensus model reaches
     >= 97% test accuracy within the epoch budget;
  2. decentralized accuracy within 0.5 points of the allreduce run
     (same init, same data order) — the reference's parity claim;
  3. every TFRecord example round-tripped the codec exactly (spot-checked).

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      PALLAS_AXON_POOL_IPS= python examples/mnist_epoch_gate.py
"""

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import bluefog_tpu as bf
from bluefog_tpu.data import (DistributedLoader, Subset,
                              TFRecordSource)
from bluefog_tpu.data.tfrecord import (decode_example, read_records,
                                       write_image_classification_shards)
from bluefog_tpu.models import LeNet5
from bluefog_tpu.optim import (DistributedGradientAllreduceOptimizer,
                               DistributedNeighborAllreduceOptimizer)
from bluefog_tpu.parallel.api import shard_map
from bluefog_tpu.topology import ExponentialTwoGraph


def synth_mnist(n: int, seed: int, noise: float = 0.5):
    """Deterministic MNIST stand-in: shifted prototypes + noise, uint8."""
    rng = np.random.default_rng(seed)
    protos = np.random.default_rng(7).standard_normal((10, 28, 28)) * 1.1
    labels = rng.integers(0, 10, n)
    imgs = protos[labels]
    # per-sample spatial shift: the same prototype appears at many offsets,
    # so a pixel-space linear model cannot just template-match
    dx, dy = rng.integers(-2, 3, n), rng.integers(-2, 3, n)
    imgs = np.stack([np.roll(im, (a, b), (0, 1))
                     for im, a, b in zip(imgs, dx, dy)])
    imgs = imgs + noise * rng.standard_normal(imgs.shape)
    lo, hi = imgs.min(), imgs.max()
    u8 = ((imgs - lo) / (hi - lo) * 255).astype(np.uint8)
    return u8[..., None], labels.astype(np.int64)


def train(loader, model, opt, init_params, epochs, ctx):
    params = bf.rank_shard(bf.rank_stack(init_params))

    def init_fn(p_blk):
        st = opt.init(jax.tree_util.tree_map(lambda t: t[0], p_blk))
        return jax.tree_util.tree_map(lambda t: jnp.asarray(t)[None], st)

    opt_state = jax.jit(shard_map(
        init_fn, mesh=ctx.mesh, in_specs=(P(ctx.axis_name),),
        out_specs=P(ctx.axis_name), check_vma=False))(params)

    def step(p_blk, st_blk, x_blk, y_blk):
        p, st = jax.tree_util.tree_map(lambda t: t[0], (p_blk, st_blk))
        x = x_blk[0].astype(jnp.float32) / 255.0 - 0.5
        y = y_blk[0]

        def loss_fn(p):
            logits = model.apply(p, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()

        loss, g = jax.value_and_grad(loss_fn)(p)
        upd, st = opt.update(g, st, p)
        p = optax.apply_updates(p, upd)
        return (jax.tree_util.tree_map(lambda t: t[None], (p, st))
                + (loss[None],))

    jitted = jax.jit(shard_map(
        step, mesh=ctx.mesh, in_specs=(P(ctx.axis_name),) * 4,
        out_specs=(P(ctx.axis_name),) * 3, check_vma=False),
        donate_argnums=(0, 1))

    for epoch in range(epochs):
        for x, y in loader.epoch(epoch):
            params, opt_state, loss = jitted(params, opt_state, x, y)
    jax.block_until_ready(loss)
    # consensus model: the mean over ranks (exactly what the reference
    # evaluates after bf.allreduce of parameters)
    return jax.tree_util.tree_map(
        lambda t: np.asarray(t).mean(axis=0), params)


def accuracy(model, params, imgs, labels, batch=512) -> float:
    hits = 0
    fn = jax.jit(lambda x: jnp.argmax(model.apply(params, x), -1))
    for lo in range(0, len(labels), batch):
        x = jnp.asarray(imgs[lo:lo + batch], jnp.float32) / 255.0 - 0.5
        hits += int((np.asarray(fn(x)) == labels[lo:lo + batch]).sum())
    return hits / len(labels)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--train-size", type=int, default=24576)
    ap.add_argument("--test-size", type=int, default=4096)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=32, help="per rank")
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--shards", type=int, default=4)
    ap.add_argument("--data-dir", default=None,
                    help="existing TFRecord dir (skip synthesis)")
    ap.add_argument("--prefetch", type=int, default=0,
                    help="loader prefetch depth; >0 needs spare host cores (a\n                    prefetch thread contending XLA\'s CPU thunk pool on a\n                    1-core host can starve collective rendezvous)")
    ap.add_argument("--target", type=float, default=0.97)
    ap.add_argument("--parity-pt", type=float, default=0.5)
    args = ap.parse_args()
    if args.epochs < 1:
        raise SystemExit("--epochs must be >= 1")

    n = len(jax.devices())
    bf.init(topology=ExponentialTwoGraph(n))
    ctx = bf.get_context()
    t0 = time.time()

    with tempfile.TemporaryDirectory() as tmp:
        if args.data_dir:
            # real data: every shard in the dir (both naming conventions);
            # the TEST split is held out from the SAME dataset (the last
            # test_size records), never from the synthetic stand-in
            import glob as _glob

            paths = sorted(_glob.glob(os.path.join(args.data_dir, "*.tfr"))
                           + _glob.glob(os.path.join(args.data_dir,
                                                     "*.tfrecord")))
            full = TFRecordSource(paths)
            if len(full) <= args.test_size:
                raise SystemExit(
                    f"--data-dir holds {len(full)} examples <= test split "
                    f"{args.test_size}")
            train_src = Subset(full, 0, len(full) - args.test_size)
            test_imgs, test_labels = full[np.arange(
                len(full) - args.test_size, len(full))]
        else:
            imgs, labels = synth_mnist(args.train_size, seed=1)
            test_imgs, test_labels = synth_mnist(args.test_size, seed=999)
            shard_size = (len(labels) + args.shards - 1) // args.shards
            paths = write_image_classification_shards(
                tmp, imgs, labels, shard_size=shard_size)
            # 3. codec round-trip spot check, through the real reader
            # (shards are contiguous: record 0 of shard 0 is example 0)
            ex = decode_example(next(iter(read_records(paths[0]))))
            got = np.frombuffer(ex["image"][0], np.uint8).reshape(28, 28, 1)
            np.testing.assert_array_equal(got, imgs[0])
            assert int(np.asarray(ex["label"])[0]) == labels[0]
            train_src = TFRecordSource(paths)

        print(f"{len(train_src)} train examples; {n} ranks")
        loader = DistributedLoader(train_src, args.batch_size, seed=5,
                                   prefetch=args.prefetch)

        model = LeNet5()
        init_params = model.init(jax.random.PRNGKey(0),
                                 jnp.zeros((1, 28, 28, 1)))

        dec = DistributedNeighborAllreduceOptimizer(
            optax.sgd(args.lr, momentum=0.9), topology=ctx.schedule,
            axis_name=ctx.axis_name)
        p_dec = train(loader, model, dec, init_params, args.epochs, ctx)
        acc_dec = accuracy(model, p_dec, test_imgs, test_labels)
        print(f"decentralized (exp2): test acc {acc_dec:.4f}")

        allr = DistributedGradientAllreduceOptimizer(
            optax.sgd(args.lr, momentum=0.9), axis_name=ctx.axis_name)
        p_all = train(loader, model, allr, init_params, args.epochs, ctx)
        acc_all = accuracy(model, p_all, test_imgs, test_labels)
        print(f"allreduce:            test acc {acc_all:.4f}")

    wall = time.time() - t0
    print(f"wall time {wall:.0f}s "
          f"({args.epochs} epochs x {loader.steps_per_epoch} steps x 2 runs)")
    assert acc_dec >= args.target, (
        f"FAIL: decentralized accuracy {acc_dec:.4f} < {args.target}")
    # one-sided, as the reference claims it: decentralized must not LOSE
    # more than parity_pt to allreduce (beating it is a pass, and happens —
    # gossip noise acts as regularization on this task)
    assert acc_dec >= acc_all - args.parity_pt / 100.0, (
        f"FAIL: decentralized {acc_dec:.4f} trails allreduce {acc_all:.4f} "
        f"by more than {args.parity_pt}pt")
    print(f"OK — epoch-scale gate: decentralized {acc_dec:.1%} >= "
          f"{args.target:.0%} and not trailing allreduce ({acc_all:.1%}) "
          f"by more than {args.parity_pt}pt, through TFRecord + "
          "DistributedLoader")


if __name__ == "__main__":
    main()
