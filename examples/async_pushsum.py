"""Asynchronous push-sum: ranks step at DIFFERENT rates and still converge.

This is the execution model the reference's one-sided MPI path enables
(upstream ``bluefog/common/mpi_controller.cc`` Win ops: ``MPI_Put`` lands
with no receiver involvement; SURVEY.md §3.4 "No global synchronization
anywhere in the step") and that no SPMD program can express: every rank here
runs its own loop, with rank-dependent compute time (the slowest rank ~5x
the fastest), depositing weighted (x, p) mass into neighbors' passive-target
windows (``csrc/windows.cc``) and consuming whatever happens to have landed
whenever it steps.

Self-asserting: exits nonzero unless
  * every rank's x/p estimate reaches the true global mean (skew-tolerant
    convergence), despite ranks having taken very different step counts;
  * push-sum mass is conserved exactly (sum of p == n) — the
    consume-exactly-once window semantics under real thread interleaving.

Run:  python examples/async_pushsum.py [--ranks 8] [--dim 16]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))  # repo-root run

import numpy as np

from bluefog_tpu.runtime.async_windows import run_async_pushsum
from bluefog_tpu.topology import ExponentialTwoGraph


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=8)
    ap.add_argument("--dim", type=int, default=16)
    ap.add_argument("--tol", type=float, default=1e-3)
    ap.add_argument("--timeout", type=float, default=60.0)
    args = ap.parse_args()

    topo = ExponentialTwoGraph(args.ranks)
    rng = np.random.default_rng(0)
    x0 = rng.normal(size=(args.ranks, args.dim)) * 10.0

    report = run_async_pushsum(
        topo, x0, tol=args.tol, timeout_s=args.timeout,
        name="async_pushsum_demo")

    steps = report.steps_per_rank
    print(f"converged={report.converged} in {report.wall_time_s:.2f}s")
    print(f"steps per rank: {steps}  (skew ratio "
          f"{max(steps) / max(min(steps), 1):.1f}x)")
    print(f"max |x/p - mean| = {report.max_abs_err:.2e}")
    print(f"total mass = {report.total_mass:.12f} (want {args.ranks})")

    ok = True
    if not report.converged:
        print("FAIL: did not converge to the global mean", file=sys.stderr)
        ok = False
    if max(steps) < 2 * min(steps):
        # the demonstration requires real skew, not lockstep-by-accident
        print("FAIL: ranks advanced at similar rates; no skew demonstrated",
              file=sys.stderr)
        ok = False
    if abs(report.total_mass - args.ranks) > 1e-6:
        print("FAIL: push-sum mass not conserved", file=sys.stderr)
        ok = False
    print("PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
