"""Synthetic throughput benchmark — the reference's
``examples/pytorch_benchmark.py`` (Horovod-style: fixed model, synthetic data,
report images/sec mean ± stddev; SURVEY.md §2.2 "Examples") rebuilt TPU-native.

Any model from the zoo x any communication flavor, so gossip overhead can be
compared against the centralized baseline and against no communication at
all — the experiment the reference's benchmark exists for:

  models:  lenet | resnet18 | resnet50 | bert-base | bert-large | gpt-small
  comm:    none | allreduce | neighbor | hierarchical | winput
  topology: exp2 | ring | grid   (for the gossip flavors)

Each timed iteration runs ``--inner`` jitted decentralized train steps; we
report per-chip examples/sec over ``--iters`` iterations, mean ± stddev,
mirroring the reference benchmark's output format.

Run (8 virtual devices):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  PALLAS_AXON_POOL_IPS= python examples/synthetic_benchmark.py \
      --model lenet --comm neighbor --iters 3 --inner 2
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))  # repo-root run

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import bluefog_tpu as bf
from bluefog_tpu.optim import (
    CommunicationType,
    DistributedHierarchicalNeighborAllreduceOptimizer,
    DistributedNeighborAllreduceOptimizer,
    DistributedWinPutOptimizer,
    decentralized_optimizer,
)
from bluefog_tpu.parallel.api import shard_map
from bluefog_tpu.topology import ExponentialTwoGraph, MeshGrid2DGraph, RingGraph

TOPOLOGIES = {"exp2": ExponentialTwoGraph, "ring": RingGraph,
              "grid": MeshGrid2DGraph}


def build_model(name, image_size, seq_len, dtype):
    """Returns (apply_fn(params, batch) -> loss, init_params, batch_maker)."""
    from bluefog_tpu.models import (
        BertConfig, BertEncoder, GPTConfig, LeNet5, ResNet18, ResNet50,
        TransformerLM)

    rng = jax.random.PRNGKey(0)
    if name in ("lenet", "resnet18", "resnet50"):
        if name == "lenet":
            model, hw, ch, classes = LeNet5(), 28, 1, 10
        else:
            cls = ResNet18 if name == "resnet18" else ResNet50
            model, hw, ch, classes = (cls(num_classes=1000, dtype=dtype),
                                      image_size, 3, 1000)

        def make_batch(key, n, b):
            return (jax.random.normal(key, (n, b, hw, hw, ch), dtype),
                    jax.random.randint(key, (n, b), 0, classes))

        x0 = jnp.zeros((1, hw, hw, ch), dtype)
        if name == "lenet":
            params = model.init(rng, x0)

            def loss_fn(p, batch):
                x, y = batch
                logits = model.apply(p, x)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, y).mean()
        else:
            variables = model.init(rng, x0, train=False)
            params = variables  # fold batch_stats in; frozen for benchmarking

            def loss_fn(p, batch):
                x, y = batch
                logits = model.apply(p, x, train=False)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, y).mean()

        return loss_fn, params, make_batch

    if name.startswith("vit"):
        import dataclasses

        from bluefog_tpu.models import ViT, ViTConfig

        cfg = (ViTConfig.base() if name == "vit-base"
               else ViTConfig.tiny())
        # honor --image-size like the resnet branch (must stay a multiple of
        # the patch size for the patchify conv to tile exactly)
        image_size = image_size - (image_size % cfg.patch_size)
        cfg = dataclasses.replace(cfg, dtype=dtype, image_size=image_size)
        model = ViT(cfg)
        hw, classes = cfg.image_size, cfg.num_classes
        params = model.init(rng, jnp.zeros((1, hw, hw, 3), dtype))

        def make_batch(key, n, b):
            return (jax.random.normal(key, (n, b, hw, hw, 3), dtype),
                    jax.random.randint(key, (n, b), 0, classes))

        def loss_fn(p, batch):
            x, y = batch
            logits = model.apply(p, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()

        return loss_fn, params, make_batch

    if name.startswith("bert"):
        cfg = BertConfig.large() if name == "bert-large" else BertConfig.base()
        model = BertEncoder(cfg, num_classes=2)
        seq = min(seq_len, cfg.max_position)
        params = model.init(rng, jnp.zeros((1, seq), jnp.int32))

        def make_batch(key, n, b):
            return (jax.random.randint(key, (n, b, seq), 0, cfg.vocab_size),
                    jax.random.randint(key, (n, b), 0, 2))

        def loss_fn(p, batch):
            ids, y = batch
            logits = model.apply(p, ids)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()

        return loss_fn, params, make_batch

    if name == "gpt-small":
        cfg = GPTConfig.small()
        model = TransformerLM(cfg)
        seq = min(seq_len, cfg.max_position)
        params = model.init(rng, jnp.zeros((1, seq), jnp.int32))

        def make_batch(key, n, b):
            return (jax.random.randint(key, (n, b, seq), 0, cfg.vocab_size),)

        def loss_fn(p, batch):
            (ids,) = batch
            logits = model.apply(p, ids)
            tgt = jnp.roll(ids, -1, axis=-1)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tgt).mean()

        return loss_fn, params, make_batch

    raise SystemExit(f"unknown model {name}")


def build_optimizer(args, ctx):
    base = optax.sgd(0.01, momentum=0.9)
    if args.comm == "none":
        return decentralized_optimizer(
            base, None, ctx.axis_name,
            communication_type=CommunicationType.empty)
    if args.comm == "allreduce":
        return decentralized_optimizer(
            base, None, ctx.axis_name,
            communication_type=CommunicationType.allreduce)
    if args.comm == "neighbor":
        return DistributedNeighborAllreduceOptimizer(
            base, topology=ctx.schedule, axis_name=ctx.axis_name)
    if args.comm == "winput":
        return DistributedWinPutOptimizer(
            base, topology=ctx.schedule, axis_name=ctx.axis_name)
    if args.comm == "hierarchical":
        if ctx.machine_schedule is None:
            raise SystemExit("--comm hierarchical needs --local-size > 1 "
                             "dividing the device count")
        return DistributedHierarchicalNeighborAllreduceOptimizer(
            base, machine_topology=ctx.machine_schedule,
            local_size=ctx.local_size, axis_name=ctx.axis_name)
    raise SystemExit(f"unknown comm {args.comm}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50",
                    choices=["lenet", "resnet18", "resnet50", "bert-base",
                             "bert-large", "gpt-small", "vit-tiny",
                             "vit-base"])
    ap.add_argument("--comm", default="neighbor",
                    choices=["none", "allreduce", "neighbor", "hierarchical",
                             "winput"])
    ap.add_argument("--topology", choices=sorted(TOPOLOGIES), default="exp2")
    ap.add_argument("--batch-size", type=int, default=32, help="per rank")
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--inner", type=int, default=10,
                    help="train steps per timed iteration")
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--local-size", type=int, default=1)
    ap.add_argument("--fp32", action="store_true")
    args = ap.parse_args()

    n = len(jax.devices())
    n_machines = n // args.local_size if args.local_size > 1 else n
    bf.init(
        topology=TOPOLOGIES[args.topology](n),
        machine_topology=(RingGraph(n_machines)
                          if args.local_size > 1 and n_machines > 1 else None),
        local_size=args.local_size if args.local_size > 1 else None,
    )
    ctx = bf.get_context()
    dtype = jnp.float32 if args.fp32 else jnp.bfloat16

    loss_fn, params, make_batch = build_model(
        args.model, args.image_size, args.seq_len, dtype)
    opt = build_optimizer(args, ctx)

    params = bf.rank_shard(bf.rank_stack(params))
    batch = bf.rank_shard(make_batch(jax.random.PRNGKey(1), n,
                                     args.batch_size))

    def init_opt(p_blk):
        p = jax.tree_util.tree_map(lambda t: t[0], p_blk)
        return jax.tree_util.tree_map(lambda t: jnp.asarray(t)[None],
                                      opt.init(p))

    opt_state = jax.jit(shard_map(
        init_opt, mesh=ctx.mesh, in_specs=(P(ctx.axis_name),),
        out_specs=P(ctx.axis_name), check_vma=False))(params)

    def train_step(p_blk, opt_blk, *batch_blk):
        p, st = jax.tree_util.tree_map(lambda t: t[0], (p_blk, opt_blk))
        local = tuple(b[0] for b in batch_blk)
        loss, g = jax.value_and_grad(loss_fn)(p, local)
        upd, st = opt.update(g, st, p)
        p = optax.apply_updates(p, upd)
        out = jax.tree_util.tree_map(lambda t: t[None], (p, st))
        return out + (loss[None],)

    nb = len(batch)
    step_fn = jax.jit(shard_map(
        train_step, mesh=ctx.mesh, in_specs=(P(ctx.axis_name),) * (2 + nb),
        out_specs=(P(ctx.axis_name),) * 3, check_vma=False,
    ), donate_argnums=(0, 1))

    def run_inner():
        nonlocal params, opt_state
        loss = None
        for _ in range(args.inner):
            params, opt_state, loss = step_fn(params, opt_state, *batch)
        jax.block_until_ready(loss)

    for _ in range(args.warmup):
        run_inner()

    rates = []
    for it in range(args.iters):
        t0 = time.perf_counter()
        run_inner()
        dt = time.perf_counter() - t0
        rate = args.inner * args.batch_size * n / dt / n  # per chip
        rates.append(rate)
        if bf.metrics_active():
            # one JSONL snapshot per timed iteration: gossip byte counters
            # (from the instrumented collectives) plus throughput
            bf.metrics.comm.set("bf_bench_examples_per_sec_per_chip", rate,
                                model=args.model, comm=args.comm)
            bf.metrics.step(it)
        print(f"iter {it:3d}: {rate:,.1f} ex/s/chip")

    unit = "img" if args.model in ("lenet", "resnet18", "resnet50") else "seq"
    print(f"\nmodel={args.model} comm={args.comm} topology={args.topology} "
          f"ranks={n} batch={args.batch_size}")
    print(f"{unit}/sec/chip: {np.mean(rates):,.1f} ± {np.std(rates):,.1f}   "
          f"total: {np.mean(rates) * n:,.1f}")


if __name__ == "__main__":
    main()
