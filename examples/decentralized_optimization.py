"""Decentralized optimization algorithms on the one-sided / gossip layers —
the reference's decentralized-optimization example scripts (upstream
``examples/pytorch_least_squares*.py`` family; BASELINE.json configs[2,3]:
push-sum DSGD on a time-varying directed graph via win_accumulate, and
gradient-tracking / EXTRA-style methods on MeshGrid2DGraph via win_get).

Problem: distributed least squares.  Rank r holds (A_r, b_r); the network
minimizes  f(x) = sum_r ||A_r x - b_r||^2 / 2  whose optimum x* solves
(sum A_r^T A_r) x* = sum A_r^T b_r — computed in closed form for validation.

Algorithms:
- ``push_sum``      — directed ring, mass-weighted gossip via win_accumulate;
                      handles non-doubly-stochastic (directed) topologies.
- ``gradient_tracking`` — MeshGrid2D, tracks the global average gradient via
                      an auxiliary variable; converges to the *exact* optimum
                      with a constant step size (win_get path).
- ``exact_diffusion``  — correction-term diffusion, exact convergence on
                      doubly-stochastic topologies.

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      PALLAS_AXON_POOL_IPS= python examples/decentralized_optimization.py \
      --algorithm gradient_tracking
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))  # repo-root run

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

import bluefog_tpu as bf
from bluefog_tpu.ops import collectives as C
from bluefog_tpu.ops import windows as W
from bluefog_tpu.parallel.api import shard_map
from bluefog_tpu.topology import MeshGrid2DGraph, RingGraph, build_schedule

DIM = 6


def make_problem(n, key):
    ka, kb = jax.random.split(key)
    A = jax.random.normal(ka, (n, 12, DIM))
    b = jax.random.normal(kb, (n, 12))
    AtA = np.einsum("rmi,rmj->ij", np.asarray(A), np.asarray(A))
    Atb = np.einsum("rmi,rm->i", np.asarray(A), np.asarray(b))
    x_star = np.linalg.solve(AtA, Atb)
    return A, b, x_star


def grad(A, b, x):
    return A.T @ (A @ x - b)


def push_sum(n, A, b, steps, lr):
    """Push-sum subgradient method on the directed ring (win_accumulate with
    the associated push-sum scalar — the reference's win-ops-with-associated-p
    mode: the weight ``p`` rides every transfer automatically)."""
    topo = RingGraph(n, connect_style=1)
    sched = build_schedule(topo)

    def body(A_blk, b_blk):
        Ar, br = A_blk[0], b_blk[0]
        win = W.win_create(jnp.zeros((DIM,)), sched, "bf", associated_p=True)

        def step(win, t):
            x, p = win.self_buf, W.win_associated_p(win)
            z = x / jnp.maximum(p, 1e-12)       # de-biased estimate
            lr_t = lr / jnp.sqrt(1.0 + t / 100.0)  # diminishing step: exact limit
            x = x - lr_t * grad(Ar, br, z) * p  # scaled subgradient step
            win = W.win_sync(win, x)            # republish post-gradient mass
            # send half the (value, p) mass to the out-neighbor — p ships
            # automatically with the same dst_weight
            win = W.win_accumulate(win, None, "bf", dst_weight=0.5)
            win = win.replace(self_buf=0.5 * win.self_buf,
                              assoc_self=0.5 * win.assoc_self)
            _, win = W.win_update_then_collect(win, "bf")
            return win, None

        win, _ = lax.scan(step, win, jnp.arange(steps))
        p = W.win_associated_p(win)
        return (win.self_buf / jnp.maximum(p, 1e-12))[None]

    return body


def gradient_tracking(n, A, b, steps, lr):
    """Gradient tracking on MeshGrid2D — the win_get config: each rank
    publishes (x, y) in a window, pulls neighbors' copies, and mixes."""
    topo = MeshGrid2DGraph(n)
    sched = build_schedule(topo)

    def body(A_blk, b_blk):
        Ar, br = A_blk[0], b_blk[0]
        x = jnp.zeros((DIM,))
        g = grad(Ar, br, x)
        y = g
        win = W.win_create({"x": x, "y": y}, sched, "bf")

        def step(carry, t):
            x, y, g_prev, win = carry
            win = W.win_sync(win, {"x": x, "y": y})        # publish
            win = W.win_get(win, "bf")                     # one-sided pull
            mixed, win = W.win_update(win, "bf")           # weighted mix
            x_new = mixed["x"] - lr * y
            g_new = grad(Ar, br, x_new)
            y_new = mixed["y"] + g_new - g_prev
            return (x_new, y_new, g_new, win), None

        (x, y, _, _), _ = lax.scan(step, (x, y, g, win), jnp.arange(steps))
        return x[None]

    return body


def exact_diffusion(n, A, b, steps, lr):
    """Exact diffusion (ATC form) on the bidirectional ring (gossip layer)."""
    topo = RingGraph(n, connect_style=0)
    sched = build_schedule(topo)

    def body(A_blk, b_blk):
        Ar, br = A_blk[0], b_blk[0]
        x = jnp.zeros((DIM,))
        psi_prev = x

        def step(carry, t):
            x, psi_prev = carry
            phi = x - lr * grad(Ar, br, x)
            psi = phi + x - psi_prev
            x_new = C.neighbor_allreduce(psi, sched, "bf")
            return (x_new, phi), None

        (x, _), _ = lax.scan(step, (x, psi_prev), jnp.arange(steps))
        return x[None]

    return body


ALGORITHMS = {
    # (builder, steps, lr, tolerance) — lr bounded by the topology's spectral
    # gap x local curvature; gradient tracking diverges past ~0.008 on the
    # 2x4 grid with this problem scale (verified against a numpy oracle)
    "push_sum": (push_sum, 6000, 0.01, 2e-2),
    "gradient_tracking": (gradient_tracking, 2500, 0.004, 1e-5),
    "exact_diffusion": (exact_diffusion, 800, 0.02, 1e-3),
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--algorithm", choices=sorted(ALGORITHMS), default="gradient_tracking")
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--lr", type=float, default=None)
    args = ap.parse_args()

    n = len(jax.devices())
    bf.init()
    ctx = bf.get_context()

    builder, d_steps, d_lr, tol = ALGORITHMS[args.algorithm]
    steps = args.steps or d_steps
    lr = args.lr or d_lr

    A, b, x_star = make_problem(n, jax.random.PRNGKey(7))
    body = builder(n, A, b, steps, lr)
    f = jax.jit(shard_map(
        body, mesh=ctx.mesh, in_specs=(P("bf"), P("bf")), out_specs=P("bf"),
        check_vma=False,
    ))
    xs = np.asarray(f(A, b))

    err = np.abs(xs - x_star).max()
    consensus = (xs.max(axis=0) - xs.min(axis=0)).max()
    print(f"{args.algorithm}: steps={steps} lr={lr}")
    print(f"  max|x_r - x*|     = {err:.3e}")
    print(f"  consensus spread  = {consensus:.3e}")
    print(f"  x*                = {np.round(x_star, 4)}")
    assert err < tol, f"failed to reach optimum (err={err:.3e}, tol={tol})"
    print("OK")


if __name__ == "__main__":
    main()
