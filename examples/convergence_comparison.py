"""Convergence parity: decentralized gossip vs centralized allreduce.

The reference's core claim (Bluefog paper, arXiv:2111.04287; BASELINE.md
north star) is that decentralized SGD over a well-chosen topology matches
centralized allreduce SGD in final accuracy while communicating less.  This
script reproduces that comparison end-to-end on the simulated slice: the same
LeNet, same per-rank data shards, same seeds — trained under each
communication flavor — then evaluated on one shared held-out set.

Expected shape of the results (and asserted): exp2/ring gossip land within a
small gap of allreduce, while no-communication ranks (each stuck on its own
shard) trail behind and disagree with each other.

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      PALLAS_AXON_POOL_IPS= python examples/convergence_comparison.py
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))  # repo-root run

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import bluefog_tpu as bf
from bluefog_tpu.models import LeNet5
from bluefog_tpu.optim import CommunicationType, decentralized_optimizer
from bluefog_tpu.parallel.api import shard_map
from bluefog_tpu.topology import ExponentialTwoGraph, RingGraph


def make_dataset(n_per_rank, n_ranks, key, noise=0.6):
    """Prototype MNIST stand-in, heterogeneous shards: each rank's label
    distribution is skewed (decentralized training's hard case).  Returns
    ``(imgs, labels, protos)`` — protos so callers build eval sets from the
    same distribution."""
    kp, kx, ky = jax.random.split(key, 3)
    protos = jax.random.normal(kp, (10, 28, 28, 1)) * 0.8
    # rank r over-samples classes around r: sharpness controls heterogeneity
    logits = -0.5 * ((jnp.arange(10)[None, :] -
                      jnp.linspace(0, 9, n_ranks)[:, None]) ** 2)
    labels = jax.vmap(
        lambda k, lg: jax.random.categorical(k, lg, shape=(n_per_rank,))
    )(jax.random.split(ky, n_ranks), logits)
    imgs = protos[labels] + noise * jax.random.normal(
        kx, (n_ranks, n_per_rank, 28, 28, 1))
    return imgs, labels.astype(jnp.int32), protos


def train_flavor(comm_type, topology, ctx, data, eval_data, args):
    model = LeNet5()
    opt = decentralized_optimizer(
        optax.sgd(args.lr, momentum=0.9), topology, ctx.axis_name,
        communication_type=comm_type)

    init = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))
    params = bf.rank_shard(bf.rank_stack(init))
    imgs, labels = data

    def init_opt(p_blk):
        p = jax.tree_util.tree_map(lambda t: t[0], p_blk)
        return jax.tree_util.tree_map(lambda t: jnp.asarray(t)[None],
                                      opt.init(p))

    opt_state = jax.jit(shard_map(
        init_opt, mesh=ctx.mesh, in_specs=(P(ctx.axis_name),),
        out_specs=P(ctx.axis_name), check_vma=False))(params)

    def epoch_fn(p_blk, opt_blk, x_blk, y_blk, perm):
        p, st = jax.tree_util.tree_map(lambda t: t[0], (p_blk, opt_blk))
        x, y = x_blk[0][perm], y_blk[0][perm]
        nb = x.shape[0] // args.batch
        if nb < 1:
            raise ValueError(
                f"--batch {args.batch} > examples per rank {x.shape[0]}")

        def body(carry, i):
            p, st = carry
            xb = jax.lax.dynamic_slice_in_dim(x, i * args.batch, args.batch)
            yb = jax.lax.dynamic_slice_in_dim(y, i * args.batch, args.batch)

            def loss_fn(p):
                logits = model.apply(p, xb)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, yb).mean()

            loss, g = jax.value_and_grad(loss_fn)(p)
            upd, st = opt.update(g, st, p)
            return (optax.apply_updates(p, upd), st), loss

        (p, st), losses = jax.lax.scan(body, (p, st), jnp.arange(nb))
        out = jax.tree_util.tree_map(lambda t: t[None], (p, st))
        return out + (losses.mean()[None],)

    step = jax.jit(shard_map(
        epoch_fn, mesh=ctx.mesh, in_specs=(P(ctx.axis_name),) * 4 + (P(),),
        out_specs=(P(ctx.axis_name),) * 3, check_vma=False,
    ), donate_argnums=(0, 1))

    loss = None
    for e in range(args.epochs):
        perm = jax.random.permutation(jax.random.fold_in(
            jax.random.PRNGKey(13), e), imgs.shape[1])
        params, opt_state, loss = step(params, opt_state, imgs, labels, perm)

    # evaluate every rank's model on the SHARED eval set
    ex, ey = eval_data

    def eval_fn(p_blk):
        p = jax.tree_util.tree_map(lambda t: t[0], p_blk)
        logits = model.apply(p, ex)
        return ((jnp.argmax(logits, -1) == ey).mean())[None]

    accs = np.asarray(jax.jit(shard_map(
        eval_fn, mesh=ctx.mesh, in_specs=(P(ctx.axis_name),),
        out_specs=P(ctx.axis_name), check_vma=False))(params))
    return float(np.mean(accs)), float(np.min(accs)), float(np.max(accs)), \
        float(np.mean(loss))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.02)
    ap.add_argument("--n-per-rank", type=int, default=512)
    args = ap.parse_args()

    n = len(jax.devices())
    bf.init(topology=ExponentialTwoGraph(n))
    ctx = bf.get_context()

    imgs, labels, protos = make_dataset(args.n_per_rank, n,
                                        jax.random.PRNGKey(1))
    data = (bf.rank_shard(imgs), bf.rank_shard(labels))  # place once
    # shared balanced eval set drawn from the SAME prototypes
    ey = jnp.tile(jnp.arange(10), 40).astype(jnp.int32)
    ex = protos[ey] + 0.6 * jax.random.normal(
        jax.random.PRNGKey(99), (ey.shape[0], 28, 28, 1))

    flavors = [
        ("allreduce", CommunicationType.allreduce, None),
        ("exp2 gossip", CommunicationType.neighbor_allreduce,
         ExponentialTwoGraph(n)),
        ("ring gossip", CommunicationType.neighbor_allreduce, RingGraph(n)),
        ("no comm", CommunicationType.empty, None),
    ]
    print(f"ranks={n} epochs={args.epochs} per-rank={args.n_per_rank} "
          f"(heterogeneous shards)\n")
    print(f"{'flavor':<14} {'eval acc':>9} {'min rank':>9} {'max rank':>9} "
          f"{'train loss':>11}")
    results = {}
    for name, ct, topo in flavors:
        acc, lo, hi, loss = train_flavor(ct, topo, ctx, data, (ex, ey), args)
        results[name] = acc
        print(f"{name:<14} {acc:>9.4f} {lo:>9.4f} {hi:>9.4f} {loss:>11.4f}")

    gap_exp2 = results["allreduce"] - results["exp2 gossip"]
    gap_ring = results["allreduce"] - results["ring gossip"]
    print(f"\ngossip-vs-allreduce gap: exp2 {gap_exp2:+.4f}, "
          f"ring {gap_ring:+.4f}")
    if gap_exp2 > 0.05 or gap_ring > 0.08:
        print("FAIL: gossip trails allreduce beyond tolerance "
              "(short run? try more --epochs)")
        sys.exit(1)
    print("OK — decentralized matches centralized (reference's claim)")


if __name__ == "__main__":
    main()
