"""Average consensus via gossip — the reference's
``examples/pytorch_average_consensus.py`` (upstream-relative), TPU-native.

Each rank starts with a random vector; repeated ``neighbor_allreduce`` steps
drive every rank to the global average.  Demonstrates the stacked-array API
and topology switching.

Run (any host, no launcher needed — SPMD replaces mpirun/bfrun):

    python examples/average_consensus.py [--size 8] [--steps 50] \
        [--topology exp2|ring|grid|star|full]

On a CPU-only host, set
``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8``
to simulate an 8-chip slice.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))  # repo-root run

import jax
import jax.numpy as jnp
import numpy as np

import bluefog_tpu as bf
from bluefog_tpu.topology import (
    ExponentialTwoGraph,
    FullyConnectedGraph,
    MeshGrid2DGraph,
    RingGraph,
    StarGraph,
)

TOPOLOGIES = {
    "exp2": ExponentialTwoGraph,
    "ring": RingGraph,
    "grid": MeshGrid2DGraph,
    "star": StarGraph,
    "full": FullyConnectedGraph,
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=None, help="ranks (default: all devices)")
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--dim", type=int, default=1000)
    ap.add_argument("--topology", choices=sorted(TOPOLOGIES), default="exp2")
    args = ap.parse_args()

    n = args.size or len(jax.devices())
    bf.init(topology=TOPOLOGIES[args.topology](n), size=n)
    print(f"ranks={bf.size()} topology={bf.load_topology().name}")

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (n, args.dim))  # stacked: row r = rank r's vector
    x = bf.rank_shard(x)
    target = np.asarray(x).mean(axis=0)

    for step in range(args.steps):
        x = bf.neighbor_allreduce(x)
        if step % 10 == 0 or step == args.steps - 1:
            err = float(np.max(np.abs(np.asarray(x) - target)))
            print(f"step {step:4d}  max|x - avg| = {err:.3e}")

    err = float(np.max(np.abs(np.asarray(x) - target)))
    print(f"final consensus error: {err:.3e}")
    assert err < 1e-3, "consensus failed to converge"
    print("OK")


if __name__ == "__main__":
    main()
