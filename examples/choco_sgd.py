"""CHOCO-SGD: decentralized training over a 10x-compressed wire.

Beyond-reference example (upstream has no communication compression):
least-squares regression with per-rank data on a ring, gossiping only a
compressed innovation each round (CHOCO-SGD, Koloskova et al., ICML 2019 —
see ops/compression.py).  Self-asserting: every rank must reach the SHARED
least-squares optimum, which plain compressed gossip cannot do (compression
noise accumulates; CHOCO's mirror copies cancel it).

Run (8-rank CPU mesh):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  PALLAS_AXON_POOL_IPS= python examples/choco_sgd.py
"""

import argparse
import functools
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P

from bluefog_tpu.ops import compression as CP
from bluefog_tpu.optim import DistributedChocoSGDOptimizer
from bluefog_tpu.parallel.api import shard_map
from bluefog_tpu.topology.graphs import RingGraph
from bluefog_tpu.topology.schedule import build_schedule


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=8)
    ap.add_argument("--dim", type=int, default=8)
    ap.add_argument("--rows", type=int, default=32, help="data rows per rank")
    ap.add_argument("--steps", type=int, default=1500)
    ap.add_argument("--ratio", type=float, default=0.1,
                    help="kept fraction of wire bytes (0.1 = 10x compression)")
    ap.add_argument("--compressor", choices=["random_block_k", "top_k"],
                    default="random_block_k")
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()

    n = args.ranks
    if len(jax.devices()) < n:
        raise SystemExit(f"need {n} devices, have {len(jax.devices())} "
                         "(set XLA_FLAGS=--xla_force_host_platform_device_count)")
    mesh = Mesh(np.array(jax.devices()[:n]), ("g",))
    sched = build_schedule(RingGraph(n))
    comp = getattr(CP, args.compressor)(args.ratio)

    rng = np.random.default_rng(0)
    A = jnp.asarray(rng.normal(size=(n, args.rows, args.dim)))
    w_star = jnp.asarray(rng.normal(size=(args.dim,)))
    b = jnp.einsum("nij,j->ni", A, w_star)

    opt = DistributedChocoSGDOptimizer(
        optax.sgd(args.lr), sched, "g", compressor=comp)  # gamma = delta

    @jax.jit
    @functools.partial(shard_map, mesh=mesh, in_specs=(P("g"), P("g")),
                       out_specs=P("g"), check_vma=False)
    def train(A_blk, b_blk):
        Ai, bi = A_blk[0], b_blk[0]
        params = jnp.zeros((args.dim,))
        state = opt.init(params)

        def body(carry, _):
            params, state = carry
            g = jax.grad(lambda w: jnp.mean((Ai @ w - bi) ** 2))(params)
            upd, state = opt.update(g, state, params)
            return (optax.apply_updates(params, upd), state), None

        (params, _), _ = jax.lax.scan(body, (params, state), None,
                                      length=args.steps)
        return params[None]

    out = np.asarray(train(A, b))
    err = np.abs(out - np.asarray(w_star)).max()
    spread = np.abs(out - out.mean(axis=0)).max()
    wire = comp.wire_ratio(np.zeros(args.dim, np.float32))
    print(f"ranks={n} compressor={comp.name} ratio={args.ratio} "
          f"(wire = {wire:.0%} of dense bytes)")
    print(f"max|w_i - w*|      = {err:.2e}")
    print(f"max rank spread    = {spread:.2e}")
    assert err < 0.05, f"did not reach the shared optimum: {err}"
    assert spread < 0.01, f"ranks did not agree: {spread}"
    print("OK")


if __name__ == "__main__":
    main()
