"""Serve-while-training demo: a replica follows a live training fleet.

Three rank-threads train a quadratic consensus problem with
asynchronous push-sum (no barrier anywhere) while publishing
ROUND-STAMPED ``(round, x, p)`` snapshots every round.  A
:class:`~bluefog_tpu.runtime.window_server.WindowServer` in the same
process serves those snapshots over TCP, and a
:class:`~bluefog_tpu.serving.replica.ServingReplica` — the shape a
prediction server embeds — subscribes to rank 0's model and serves
predictions from it WHILE it trains.

Self-asserted invariants:

- every snapshot the replica adopts is round-consistent (the in-band
  ``round`` stamp leaf equals the pushed round, exactly);
- the served model's STALENESS is bounded: sampled repeatedly during
  training, the replica is never more than K rounds behind the
  trainer's live round (K = subscription stride + delivery slack);
- predictions from the served weights track the training objective
  (the replica's final model is close to the fleet's consensus).

Exits nonzero on failure.

Run:
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
  python examples/serving_replica.py
"""

import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

from bluefog_tpu import serving
from bluefog_tpu import topology as T
from bluefog_tpu.runtime.async_windows import run_async_dsgd
from bluefog_tpu.runtime.window_server import WindowServer
from bluefog_tpu.serving.replica import ServingReplica
from bluefog_tpu.serving.subscriber import Subscriber

N_RANKS = 3
DIM = 8
EVERY = 2          # subscription stride: push every 2nd round
STALENESS_K = 60   # rounds of slack the SLO allows: the stride plus
                   # delivery lag — at ~5 ms/round that is ~300 ms of
                   # scheduler noise headroom on a loaded CI host
NAME = "serving_replica_demo"
GROUP = f"{NAME}:0"


def main() -> int:
    targets = np.stack([np.full(DIM, float(r + 1)) for r in range(N_RANKS)])

    def loss_and_grad(r, step, params):
        w = np.asarray(params["w"], np.float64)
        diff = w - targets[r]
        return 0.5 * float(diff @ diff), {"w": diff}

    template = {"w": np.zeros(DIM, np.float32)}

    # the training fleet runs in a background thread; the "service" is
    # the main thread — the two touch ONLY through the snapshot fabric
    report_box = {}

    def train():
        report_box["report"] = run_async_dsgd(
            T.FullyConnectedGraph(N_RANKS), template, loss_and_grad,
            lr=0.05, duration_s=4.0, skew=[0.005] * N_RANKS,
            name=NAME, snapshot_every=1)

    trainer = threading.Thread(target=train, daemon=True)
    trainer.start()

    srv = WindowServer()
    addr = srv.start("127.0.0.1")

    # an auditing subscriber rides alongside the replica: every pushed
    # snapshot's in-band `round` stamp leaf must equal the frame's round
    audit = {"frames": 0, "mismatches": 0}

    def check_stamp(snap):
        audit["frames"] += 1
        if int(snap.leaves["round"][0]) != snap.round:
            audit["mismatches"] += 1

    auditor = Subscriber(addr, GROUP, every=1, on_snapshot=check_stamp)

    replica = ServingReplica(addr, GROUP, template, every=EVERY)
    replica.wait_ready(timeout_s=20.0)

    # sample the staleness SLO while training progresses
    tbl = serving.table()
    worst_age = 0
    samples = 0
    first_round = replica.round
    while trainer.is_alive() and tbl.current_round(GROUP) >= 0:
        live = tbl.current_round(GROUP)
        if live < 0:
            break  # training finished and dropped its groups
        age = replica.staleness_rounds(live)
        worst_age = max(worst_age, age)
        samples += 1
        assert age <= STALENESS_K, (
            f"staleness SLO violated: replica at round {replica.round}, "
            f"trainer at {live} (age {age} > K={STALENESS_K})")
        # serve a "prediction" from the live weights: the de-biased
        # model applied to a probe input
        w = np.asarray(replica.params()["w"], np.float64)
        _ = float(w @ np.ones(DIM))
        time.sleep(0.05)
    trainer.join(timeout=30)
    final_round = replica.round

    report = report_box["report"]
    auditor.close()
    replica.close()
    srv.stop()

    print(f"steps per rank   : {report.steps_per_rank}")
    print(f"replica rounds   : first={first_round} final={final_round} "
          f"adopted={replica.adopted}")
    print(f"staleness        : worst={worst_age} over {samples} samples "
          f"(SLO K={STALENESS_K})")
    print(f"round-stamp audit: {audit['frames']} frames, "
          f"{audit['mismatches']} mismatches")

    # the replica followed a LIVE model...
    assert final_round > first_round, (first_round, final_round)
    assert replica.adopted >= 3, replica.adopted
    assert samples >= 3 and worst_age <= STALENESS_K, (samples, worst_age)
    # ...every delivered snapshot was round-consistent, exactly...
    assert audit["frames"] >= 3 and audit["mismatches"] == 0, audit
    # ...training was never perturbed by the readers (exact mass audit)...
    assert abs(report.total_mass - N_RANKS) < 1e-9 * N_RANKS, \
        report.total_mass
    # ...and the served model converged with the fleet: close to the
    # consensus optimum (the mean of the rank targets)
    w = np.asarray(replica.params()["w"], np.float64)
    optimum = targets.mean(axis=0)
    err = float(np.abs(w - optimum).max())
    print(f"served model err : {err:.3e} vs consensus optimum")
    assert err < 0.5, err
    print("serving_replica: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
