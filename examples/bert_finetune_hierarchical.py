"""Decentralized BERT fine-tuning with hierarchical gossip — BASELINE.json
config[4] (BERT-large decentralized fine-tune, hierarchical_neighbor_allreduce:
intra-host allreduce + inter-host gossip), the TPU rebuild of the reference's
hierarchical mode (SURVEY.md §0, §2.1 "MPI controller" local/cross
communicators).

The device mesh is split into "machines" of ``--local-size`` chips (a TPU
host / ICI island).  Every step: exact ``psum`` average within each machine
(cheap, rides ICI), then one gossip round between machine leaders on a
machine-level ring (the DCN hop on a real multi-host pod) — all fused into the
single jitted ``shard_map`` train step via
``DistributedHierarchicalNeighborAllreduceOptimizer``.

Task: synthetic sequence classification (GLUE-style shape).  Each example is
a token sequence carrying a class-marker token at random positions; BERT
fine-tunes to detect it.  Real data drops in via ``ArraySource`` over
tokenized ``.npy`` files exactly as in examples/imagenet_resnet.py.

Run (8 virtual devices = 4 machines x 2 chips):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  PALLAS_AXON_POOL_IPS= python examples/bert_finetune_hierarchical.py \
      --local-size 2 --epochs 3
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))  # repo-root run

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import bluefog_tpu as bf
from bluefog_tpu.data import ArraySource, DistributedLoader
from bluefog_tpu.models import BertConfig, BertEncoder
from bluefog_tpu.optim import DistributedHierarchicalNeighborAllreduceOptimizer
from bluefog_tpu.parallel.api import shard_map
from bluefog_tpu.topology import ExponentialTwoGraph, RingGraph


def make_task(n_examples, seq_len, vocab, num_classes, seed):
    """Marker-token classification: class c plants token ``vocab-1-c`` at
    3 random positions; everything else is uniform noise."""
    rng = np.random.default_rng(seed)
    ids = rng.integers(1, vocab - num_classes - 1,
                       (n_examples, seq_len)).astype(np.int32)
    labels = rng.integers(0, num_classes, n_examples).astype(np.int32)
    for i in range(n_examples):
        pos = rng.choice(seq_len, 3, replace=False)
        ids[i, pos] = vocab - 1 - labels[i]
    return ArraySource(ids, labels)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", choices=["tiny", "base", "large"],
                    default="tiny")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=8, help="per-rank")
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--num-classes", type=int, default=4)
    ap.add_argument("--n-per-rank", type=int, default=128)
    ap.add_argument("--lr", type=float, default=5e-4)
    ap.add_argument("--local-size", type=int, default=2,
                    help="chips per machine (intra-machine exact average)")
    ap.add_argument("--atc", action="store_true")
    ap.add_argument("--two-level-mesh", action="store_true",
                    help="run over the explicit (machine, local) mesh — the "
                         "multi-slice/DCN deployment form (machine hops on "
                         "the outer axis)")
    args = ap.parse_args()

    n = len(jax.devices())
    if n % args.local_size:
        raise SystemExit(f"--local-size {args.local_size} must divide {n}")
    n_machines = n // args.local_size
    bf.init(
        topology=ExponentialTwoGraph(n),
        machine_topology=(RingGraph(n_machines) if n_machines > 1 else None),
        local_size=args.local_size,
    )
    ctx = bf.get_context()
    print(f"ranks={n} machines={n_machines} local_size={args.local_size}")

    cfg = {"tiny": BertConfig.tiny, "base": BertConfig.base,
           "large": BertConfig.large}[args.model]()
    seq_len = min(args.seq_len, cfg.max_position)
    model = BertEncoder(cfg, num_classes=args.num_classes)

    src = make_task(args.n_per_rank * n, seq_len, cfg.vocab_size,
                    args.num_classes, seed=0)
    loader = DistributedLoader(src, args.batch_size)

    two_level = args.two_level_mesh and ctx.machine_schedule is not None
    if args.two_level_mesh and ctx.machine_schedule is None:
        print("WARNING: --two-level-mesh ignored: only one machine "
              "(raise the device count or lower --local-size)")
    # the step's mesh/specs are the only thing the two-level form changes:
    # same model, same optimizer API — axis_name becomes the axis pair
    axis = ((ctx.machine_axis_name, ctx.local_axis_name) if two_level
            else ctx.axis_name)
    mesh = ctx.hier_mesh if two_level else ctx.mesh
    spec = P(axis)
    if ctx.machine_schedule is not None:
        opt = DistributedHierarchicalNeighborAllreduceOptimizer(
            optax.adamw(args.lr), machine_topology=ctx.machine_schedule,
            local_size=args.local_size, axis_name=axis, atc=args.atc)
    else:  # single machine: degenerate to plain gossip
        from bluefog_tpu.optim import DistributedNeighborAllreduceOptimizer
        opt = DistributedNeighborAllreduceOptimizer(
            optax.adamw(args.lr), topology=ctx.schedule,
            axis_name=ctx.axis_name, atc=args.atc)

    x0 = jnp.zeros((1, seq_len), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), x0)["params"]
    params = bf.rank_shard(bf.rank_stack(params))

    def init_opt(p_blk):
        p = jax.tree_util.tree_map(lambda t: t[0], p_blk)
        return jax.tree_util.tree_map(lambda t: jnp.asarray(t)[None],
                                      opt.init(p))

    opt_state = jax.jit(shard_map(
        init_opt, mesh=mesh, in_specs=(spec,),
        out_specs=spec, check_vma=False))(params)

    def train_step(p_blk, opt_blk, ids_blk, y_blk):
        p, st = jax.tree_util.tree_map(lambda t: t[0], (p_blk, opt_blk))
        ids, y = ids_blk[0], y_blk[0]

        def loss_fn(p):
            logits = model.apply({"params": p}, ids)
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()
            return loss, logits

        (loss, logits), g = jax.value_and_grad(loss_fn, has_aux=True)(p)
        upd, st = opt.update(g, st, p)
        p = optax.apply_updates(p, upd)
        acc = (jnp.argmax(logits, -1) == y).mean()
        out = jax.tree_util.tree_map(lambda t: t[None], (p, st))
        return out + (loss[None], acc[None])

    step_fn = jax.jit(shard_map(
        train_step, mesh=mesh, in_specs=(spec,) * 4,
        out_specs=(spec,) * 4, check_vma=False,
    ), donate_argnums=(0, 1))

    for epoch in range(args.epochs):
        t0 = time.perf_counter()
        loss = acc = None
        for ids, y in loader.epoch(epoch):
            params, opt_state, loss, acc = step_fn(params, opt_state, ids, y)
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        sps = loader.steps_per_epoch * args.batch_size * n / dt
        print(f"epoch {epoch}  loss {np.mean(loss):.4f}  "
              f"acc {np.mean(acc):.3f}  {sps:,.0f} seq/s")

    # consensus check: ranks should stay close (gossip contracts disagreement)
    spread = jax.tree_util.tree_reduce(
        max, jax.tree_util.tree_map(
            lambda t: float(np.max(np.abs(
                np.asarray(t, np.float32) -
                np.asarray(t, np.float32).mean(0, keepdims=True)))), params))
    print(f"max param spread across ranks: {spread:.3e}")
    final_acc = float(np.mean(acc))
    if final_acc <= 0.5:
        # short runs legitimately stop before convergence — report, don't die
        print(f"WARNING: accuracy {final_acc:.3f} <= 0.5 "
              f"(train longer: --epochs/--n-per-rank)")
    print("OK")


if __name__ == "__main__":
    main()
