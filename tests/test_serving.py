"""Serve-while-training read fabric (round-consistent snapshots,
resumable subscriptions, reader fault tolerance).

Covers the tentpole surfaces of `bluefog_tpu.serving` + the wire-v2
SNAPSHOT/SUBSCRIBE ops (`runtime/window_server.py`):

- the torn-read fuzzer: concurrent publishes racing SNAPSHOT reads
  across round boundaries never yield mixed-round leaves (60+ seeded
  cases — the double-buffer swap-under-lock contract);
- round pinning: a pinned read that lost its race gets the RETRIABLE
  round-rolled status, never a torn or silently-newer snapshot;
- resumable subscriptions: every-Nth-round stride, reconnect-and-resume
  across injected connection cuts with no missed or duplicated
  promised round (cursor + epoch quiesce), slow-reader skip-to-latest
  that never throttles the publisher;
- reader fault injection: the new `read:*`/`sub:*` chaos sites tear
  replies mid-frame, stall and cut them — clients recover under
  bounded backoff; the synchronous read path gets a real deadline,
  idempotent-read retry, and DepositStream-style error latching;
- malformed/truncated SNAPSHOT and SUBSCRIBE frame fuzz (the PR-4
  harness shape): garbage never takes the serving process down;
- the acceptance scenario: 3 tcp dsgd ranks + 4 subscriber processes
  under reader kills/stalls/torn frames — every delivered snapshot
  passes an exact round-stamp audit, and training's push-sum mass
  audit is identical to a chaos-free run.

Like the transport tests, everything here runs against whichever window
table the host has (native or pure-Python fallback).
"""

import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from tests._util import REPO as _REPO, clean_env, uniq as _uniq


@pytest.fixture(autouse=True)
def _chaos_isolated():
    from bluefog_tpu import chaos

    chaos.reset()
    yield
    chaos.reset()


def _serve():
    from bluefog_tpu.runtime.window_server import WindowServer

    srv = WindowServer()
    addr = srv.start("127.0.0.1")
    return srv, addr


def _stamped(rnd: float, dim: int = 64):
    v = float(rnd)
    return {"x": np.full(dim, v), "p": np.array([v + 1.0]),
            "round": np.array([v])}


# ---------------------------------------------------------------------------
# snapshot table + SNAPSHOT wire op
# ---------------------------------------------------------------------------


class TestSnapshotTable:
    def test_publish_read_round_consistent(self):
        from bluefog_tpu.serving import (RoundRolled, SnapshotUnavailable,
                                         table)

        tbl = table()
        g = _uniq("tbl")
        with pytest.raises(SnapshotUnavailable):
            tbl.read(g)
        tbl.publish(g, 3, _stamped(3))
        rnd, leaves = tbl.read(g)
        assert rnd == 3
        got = dict(leaves)
        assert (got["x"] == 3.0).all() and got["round"][0] == 3.0
        # leaf subset + unknown leaf
        rnd, leaves = tbl.read(g, ["p"])
        assert rnd == 3 and leaves[0][0] == "p"
        with pytest.raises(SnapshotUnavailable):
            tbl.read(g, ["nope"])
        # pin the live round: fine; pin a stale one: retriable roll
        assert tbl.read(g, want_round=3)[0] == 3
        tbl.publish(g, 4, _stamped(4))
        with pytest.raises(RoundRolled):
            tbl.read(g, want_round=3)
        assert tbl.current_round(g) == 4
        assert tbl.generation(g) == 2
        tbl.drop(g)

    def test_non_float_leaves_rejected(self):
        from bluefog_tpu.serving import table

        g = _uniq("tbl_dtype")
        with pytest.raises(TypeError, match="f32/f64"):
            table().publish(g, 0, {"x": np.arange(4, dtype=np.int32)})

    def test_reader_copy_is_isolated_from_later_publishes(self):
        from bluefog_tpu.serving import table

        tbl = table()
        g = _uniq("tbl_copy")
        tbl.publish(g, 0, _stamped(0))
        _, leaves = tbl.read(g)
        held = dict(leaves)["x"]
        for rnd in range(1, 4):
            tbl.publish(g, rnd, _stamped(rnd))
        assert (held == 0.0).all()  # a served copy can never mutate
        tbl.drop(g)


class TestSnapshotWire:
    def test_hello_grants_serving_features(self):
        from bluefog_tpu.runtime import window_server as ws

        srv, addr = _serve()
        try:
            with socket.create_connection(addr, timeout=10) as s:
                s.sendall(ws._HDR.pack(ws._MAGIC, ws._OP_HELLO, 0)
                          + ws._HELLO.pack(
                              ws.PROTOCOL_VERSION,
                              ws.FEATURE_SNAPSHOT | ws.FEATURE_SUBSCRIBE))
                (granted,) = ws._STATUS.unpack(s.recv(8))
            assert granted & ws.FEATURE_SNAPSHOT
            assert granted & ws.FEATURE_SUBSCRIBE
        finally:
            srv.stop()

    def test_snapshot_roundtrip_and_min_round(self):
        from bluefog_tpu.serving import SnapshotUnavailable, table
        from bluefog_tpu.serving.client import SnapshotClient

        tbl = table()
        g = _uniq("wire")
        srv, addr = _serve()
        try:
            c = SnapshotClient(addr, g)
            # nothing published yet: retriable, and wait_s bounds it
            with pytest.raises(SnapshotUnavailable):
                c.snapshot()
            tbl.publish(g, 5, _stamped(5))
            snap = c.snapshot()
            assert snap.round == 5
            assert (snap["x"] == 5.0).all()
            assert int(snap["round"][0]) == 5
            # min_round: stale serves rejected after the wait budget
            with pytest.raises(SnapshotUnavailable, match="stale"):
                c.snapshot(min_round=9, wait_s=0.2)
            tbl.publish(g, 9, _stamped(9))
            assert c.snapshot(min_round=9).round == 9
            c.close()
        finally:
            srv.stop()
            tbl.drop(g)

    def test_pinned_read_rolls_retriably(self):
        from bluefog_tpu.serving import RoundRolled, table
        from bluefog_tpu.serving.client import SnapshotClient

        tbl = table()
        g = _uniq("pin")
        srv, addr = _serve()
        try:
            tbl.publish(g, 1, _stamped(1))
            c = SnapshotClient(addr, g)
            assert c.snapshot(pin_round=1).round == 1
            tbl.publish(g, 2, _stamped(2))
            with pytest.raises(RoundRolled):
                c.snapshot(pin_round=1)
            # the protocol: re-pin at the new round and continue
            assert c.snapshot(pin_round=2).round == 2
            c.close()
        finally:
            srv.stop()
            tbl.drop(g)

    def test_torn_read_fuzzer_never_mixes_rounds(self):
        """THE consistency test: a publisher rolling rounds as fast as
        it can races concurrent SNAPSHOT reads; every reply must be
        entirely one round (every leaf value equals the reply's round
        stamp).  60 seeded interleavings."""
        from bluefog_tpu.serving import SnapshotUnavailable, table
        from bluefog_tpu.serving.client import SnapshotClient

        tbl = table()
        g = _uniq("fuzz_torn")
        srv, addr = _serve()
        dim = 512
        reads = [0]
        try:
            c = SnapshotClient(addr, g)
            for seed in range(60):
                rng = np.random.default_rng(seed)
                rounds = int(rng.integers(10, 40))

                def publisher():
                    for rnd in range(rounds):
                        v = float(rnd)
                        tbl.publish(g, rnd, {
                            "x": np.full(dim, v), "p": np.array([v]),
                            "round": np.array([v])})
                        if rng.random() < 0.3:
                            time.sleep(float(rng.random()) * 5e-4)

                t = threading.Thread(target=publisher)
                t.start()
                while t.is_alive():
                    try:
                        snap = c.snapshot()
                    except SnapshotUnavailable:
                        continue
                    r = float(snap.round)
                    x = snap["x"]
                    # all-of-one-round, exactly: any torn mix would
                    # break one of these equalities
                    assert float(snap["round"][0]) == r, seed
                    assert float(snap["p"][0]) == r, seed
                    assert x[0] == r and (x == x[0]).all(), seed
                    reads[0] += 1
                t.join()
                tbl.drop(g)  # next seed restarts its round counter
            assert reads[0] >= 120, f"only {reads[0]} racing reads"
            c.close()
        finally:
            srv.stop()
            tbl.drop(g)

    def test_client_survives_torn_reply(self):
        """Chaos read:truncate tears the reply mid-frame: the client
        must record a torn_read_retry and recover on a fresh
        connection, never consume the fragment."""
        from bluefog_tpu import chaos
        from bluefog_tpu.serving import table
        from bluefog_tpu.serving.client import SnapshotClient

        tbl = table()
        g = _uniq("torn_reply")
        srv, addr = _serve()
        try:
            tbl.publish(g, 7, _stamped(7))
            chaos.configure("read:truncate:after_frames=1")
            c = SnapshotClient(addr, g,
                               retry=dict(base_s=0.01, cap_s=0.05,
                                          budget=5, seed=0))
            snap = c.snapshot()
            assert snap.round == 7 and (snap["x"] == 7.0).all()
            c.close()
        finally:
            srv.stop()
            tbl.drop(g)


# ---------------------------------------------------------------------------
# satellite: sync reads — deadline, bounded retry, latched errors
# ---------------------------------------------------------------------------


class TestSyncReadResilience:
    def _win(self, name, val=3.5):
        from bluefog_tpu.runtime.async_windows import AsyncWindow

        win = AsyncWindow(name, n_slots=1, n_elems=4, dtype=np.float64)
        win.set_self(np.full(4, val))
        return win

    def test_wedged_owner_times_out_not_hangs(self):
        from bluefog_tpu import chaos
        from bluefog_tpu.runtime.window_server import RemoteWindow

        name = _uniq("sync_stall")
        win = self._win(name)
        srv, addr = _serve()
        try:
            chaos.configure("read:stall:s=30:after_frames=1")
            rw = RemoteWindow(addr, name, timeout_s=0.6)
            t0 = time.monotonic()
            with pytest.raises(TimeoutError, match="wedged owner"):
                rw.read_self(4)
            assert time.monotonic() - t0 < 10  # a deadline, not a hang
            # and the error LATCHED: the next call refuses immediately
            with pytest.raises(RuntimeError, match="latched"):
                rw.read_self(4)
            rw.close()
        finally:
            srv.stop()
            win.free()

    def test_idempotent_read_retries_through_stall(self):
        from bluefog_tpu import chaos
        from bluefog_tpu.runtime.window_server import RemoteWindow

        name = _uniq("sync_retry")
        win = self._win(name, 9.25)
        srv, addr = _serve()
        try:
            # the FIRST reply stalls past the deadline; the retry's
            # fresh connection is frame 2 and sails through
            chaos.configure("read:stall:s=30:after_frames=1")
            rw = RemoteWindow(addr, name, timeout_s=0.6,
                              retry=dict(base_s=0.01, cap_s=0.05,
                                         budget=4, seed=0))
            got = rw.read_self(4)
            np.testing.assert_allclose(got, 9.25)
            # a truncated reply is recovered the same way
            chaos.configure("read:truncate:after_frames=1")
            got, fresh = rw.read(0, 4, consume=False)
            assert fresh == 0
            rw.close()
        finally:
            srv.stop()
            win.free()

    def test_budget_exhaustion_latches(self):
        from bluefog_tpu import chaos
        from bluefog_tpu.runtime.window_server import RemoteWindow

        name = _uniq("sync_latch")
        win = self._win(name)
        srv, addr = _serve()
        try:
            chaos.configure("read:drop:every=1")  # every read reply dies
            rw = RemoteWindow(addr, name, timeout_s=1.0,
                              retry=dict(base_s=0.01, cap_s=0.02,
                                         budget=2, seed=0))
            with pytest.raises(RuntimeError, match="budget"):
                rw.read_self(4)
            with pytest.raises(RuntimeError, match="latched"):
                rw.read_self(4)
            rw.close()
        finally:
            srv.stop()
            win.free()

    def test_consuming_read_is_never_silently_retried(self):
        from bluefog_tpu import chaos
        from bluefog_tpu.runtime.window_server import RemoteWindow

        name = _uniq("sync_consume")
        win = self._win(name)
        srv, addr = _serve()
        try:
            chaos.configure("read:drop:after_frames=1")
            rw = RemoteWindow(addr, name, timeout_s=1.0,
                              retry=dict(base_s=0.01, budget=4))
            # a consume read is NOT idempotent: the drop surfaces as a
            # connection error instead of a silent re-consume
            with pytest.raises((ConnectionError, RuntimeError)):
                rw.read(0, 4, consume=True)
            rw.close()
        finally:
            srv.stop()
            win.free()


# ---------------------------------------------------------------------------
# subscriptions
# ---------------------------------------------------------------------------


class TestSubscriptions:
    def test_every_nth_round_stride(self):
        from bluefog_tpu.serving import table
        from bluefog_tpu.serving.subscriber import Subscriber

        tbl = table()
        g = _uniq("sub_nth")
        srv, addr = _serve()
        got = []
        try:
            sub = Subscriber(addr, g, every=3,
                             on_snapshot=lambda s: got.append(s.round))
            time.sleep(0.2)
            for rnd in range(30):
                tbl.publish(g, rnd, _stamped(rnd))
                time.sleep(0.01)
            time.sleep(0.5)
            sub.close()
            assert got, "no rounds delivered"
            assert got == sorted(set(got))  # strictly increasing
            for a, b in zip(got, got[1:]):
                assert b - a >= 3, got  # the promised stride
        finally:
            srv.stop()
            tbl.drop(g)

    def test_reconnect_resumes_exactly_once_per_promised_round(self):
        """Chaos cuts the push channel repeatedly; the subscriber's
        cursor + the epoch quiesce must make delivery exactly-once:
        rounds strictly increasing across every resume, no duplicates,
        and delivery continues after each cut."""
        from bluefog_tpu import chaos
        from bluefog_tpu.serving import table
        from bluefog_tpu.serving.subscriber import Subscriber

        tbl = table()
        g = _uniq("sub_resume")
        srv, addr = _serve()
        got = []
        try:
            chaos.configure("sub:drop:every=5")
            sub = Subscriber(addr, g, every=1,
                             on_snapshot=lambda s: got.append(s.round),
                             reconnect=dict(base_s=0.02, cap_s=0.1,
                                            budget=8, seed=1),
                             idle_timeout_s=2.0)
            time.sleep(0.2)
            for rnd in range(40):
                tbl.publish(g, rnd, _stamped(rnd))
                time.sleep(0.03)
            time.sleep(1.0)
            resumes = sub.resumes
            err = sub.error
            sub.close()
            assert err is None, err
            assert resumes >= 1, "chaos never forced a resume"
            assert len(got) >= 8, got
            assert got == sorted(set(got)), (
                f"duplicated/regressed rounds across resumes: {got}")
            # delivery continued AFTER the last injected cut
            assert got[-1] >= 30, got
        finally:
            srv.stop()
            tbl.drop(g)

    def test_slow_reader_skips_but_never_blocks_publisher(self):
        from bluefog_tpu.serving import table
        from bluefog_tpu.serving.subscriber import Subscriber

        tbl = table()
        g = _uniq("sub_slow")
        srv, addr = _serve()
        got = []
        rounds = 20
        # MODEL-SIZED frames: small ones vanish into kernel socket
        # buffers and a lagging reader is invisible — 8 MB per push is
        # what makes the sender actually fall behind a slow consumer
        big = np.zeros(1 << 20, np.float64)

        def slow(snap):
            got.append(snap.round)
            time.sleep(0.05)  # a consumer ~10x slower than the publisher

        try:
            sub = Subscriber(addr, g, every=1, on_snapshot=slow,
                             queue_max=2)
            time.sleep(0.2)
            t0 = time.monotonic()
            for rnd in range(rounds):
                v = float(rnd)
                big[0] = v
                tbl.publish(g, rnd, {"x": big, "p": np.array([v]),
                                     "round": np.array([v])})
                time.sleep(0.005)
            publish_wall = time.monotonic() - t0
            # skip-to-latest: the publisher's cadence is ITS OWN — a
            # reader at 50 ms/frame must not stretch 20 publishes
            # toward its ~1 s pace
            assert publish_wall < 2.0, publish_wall
            time.sleep(1.5)
            skipped = sub.skipped_rounds
            sub.close()
            assert got == sorted(set(got)), got
            assert skipped > 0, "slow reader never skipped"
            assert len(got) < rounds, "a slow consumer cannot see all"
        finally:
            srv.stop()
            tbl.drop(g)

    def test_late_subscriber_catches_up_to_current_round(self):
        """A subscriber attaching AFTER the latest publish (replica
        restart, converged trainer) must still receive the current
        round when its cursor is below it — not wait forever for a
        future publish."""
        from bluefog_tpu.serving import table
        from bluefog_tpu.serving.subscriber import Subscriber

        tbl = table()
        g = _uniq("sub_late")
        srv, addr = _serve()
        try:
            for rnd in range(6):
                tbl.publish(g, rnd, _stamped(rnd))
            # all publishing is DONE before the subscriber exists
            sub = Subscriber(addr, g, every=1)
            snap = sub.get(timeout_s=5.0)
            assert snap is not None and snap.round == 5, snap
            sub.close()
        finally:
            srv.stop()
            tbl.drop(g)

    def test_keepalives_flow_while_pushes_not_due(self):
        """A steady stream of NOT-DUE publishes (large stride) must not
        starve the keepalive cadence: the reader's idle timeout on a
        healthy connection would otherwise churn reconnects forever."""
        from bluefog_tpu.serving import table
        from bluefog_tpu.serving.subscriber import Subscriber

        tbl = table()
        g = _uniq("sub_idle")
        srv, addr = _serve()
        try:
            sub = Subscriber(addr, g, every=1000, idle_timeout_s=1.2,
                             reconnect=dict(base_s=0.02, budget=4))
            deadline = time.monotonic() + 3.0
            rnd = 0
            while time.monotonic() < deadline:
                tbl.publish(g, rnd, _stamped(rnd))
                rnd += 1
                time.sleep(0.15)  # publishes flow, pushes never due
            assert sub.error is None, sub.error
            assert sub.resumes == 0, "idle timeout tripped on a " \
                "healthy connection"
            sub.close()
        finally:
            srv.stop()
            tbl.drop(g)

    def test_replica_surfaces_subscription_failure_fast(self):
        from bluefog_tpu.serving.replica import ServingReplica

        srv, addr = _serve()
        srv.stop()  # nothing listening: the subscription must die fast
        rep = ServingReplica(addr, _uniq("rep_dead"),
                             reconnect=dict(base_s=0.01, cap_s=0.02,
                                            budget=2))
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="failed before"):
            rep.wait_ready(timeout_s=30.0)
        # the latched error surfaced promptly, not at the full timeout
        assert time.monotonic() - t0 < 10
        rep.close()

    def test_subscriber_latches_when_trainer_gone(self):
        from bluefog_tpu.serving.subscriber import Subscriber

        srv, addr = _serve()
        srv.stop()  # nothing listening anymore
        sub = Subscriber(addr, _uniq("sub_dead"),
                         reconnect=dict(base_s=0.01, cap_s=0.02,
                                        budget=3, seed=0))
        with pytest.raises(RuntimeError, match="budget|unreachable"):
            # get() surfaces the latched terminal error
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                sub.get(timeout_s=0.5)
        sub.close()


# ---------------------------------------------------------------------------
# malformed / truncated frame fuzz (the PR-4 harness, read-path ops)
# ---------------------------------------------------------------------------


def _valid_snapshot_request(ws, group_b):
    return (ws._HDR.pack(ws._MAGIC, ws._OP_SNAPSHOT, len(group_b))
            + group_b + ws._SNAP_REQ.pack(-1, 2)
            + ws._LEAF_NAME.pack(1) + b"x"
            + ws._LEAF_NAME.pack(1) + b"p")


def _valid_subscribe_request(ws, group_b):
    return (ws._HDR.pack(ws._MAGIC, ws._OP_SUBSCRIBE, len(group_b))
            + group_b + ws._SUB_REQ.pack(77, 1, 1, -1))


def test_fuzz_malformed_snapshot_and_subscribe_frames():
    """Truncated, bit-flipped, and absurd-length SNAPSHOT/SUBSCRIBE
    frames must never take the serving process down: at worst the one
    connection drops, and a fresh reader right after works."""
    from bluefog_tpu.runtime import window_server as ws
    from bluefog_tpu.serving import table
    from bluefog_tpu.serving.client import SnapshotClient

    tbl = table()
    g = _uniq("fuzz_frames")
    gb = g.encode()
    srv, addr = _serve()
    rng = np.random.default_rng(23)
    tbl.publish(g, 4, _stamped(4))
    try:
        for trial in range(60):
            base = (_valid_snapshot_request(ws, gb) if trial % 2 == 0
                    else _valid_subscribe_request(ws, gb))
            blob = bytearray(base)
            mode = trial % 3
            if mode == 0:  # truncate anywhere
                blob = blob[:int(rng.integers(1, len(blob)))]
            elif mode == 1:  # flip bytes after the magic
                for _ in range(int(rng.integers(1, 6))):
                    i = int(rng.integers(ws._HDR.size, len(blob)))
                    blob[i] = int(rng.integers(0, 256))
            else:  # absurd claimed leaf counts / name lengths
                off = ws._HDR.size + len(gb)
                blob[off:off + ws._SNAP_REQ.size] = ws._SNAP_REQ.pack(
                    int(rng.integers(-1, 2)), 0xFFFF)
            with socket.create_connection(addr, timeout=10) as s:
                s.settimeout(5)
                try:
                    s.sendall(blob)
                    s.shutdown(socket.SHUT_WR)
                    while s.recv(4096):
                        pass
                except OSError:
                    pass  # torn connection either way — allowed
        # fully functional for a fresh reader afterwards
        c = SnapshotClient(addr, g)
        snap = c.snapshot(min_round=4)
        assert snap.round == 4 and (snap["x"] == 4.0).all()
        c.close()
    finally:
        srv.stop()
        tbl.drop(g)


def test_chaos_spec_covers_reader_sites():
    """`bfchaos-tpu` validates the new read-path sites."""
    from bluefog_tpu.chaos import cli, parse_spec

    rules = parse_spec("read:truncate:every=7;sub:stall:s=0.25:every=3;"
                       "read:stall:s=2:prob=0.05;sub:drop:after_frames=9")
    assert [r.site for r in rules] == ["read", "sub", "read", "sub"]
    assert cli.main(["--spec", "read:drop:every=4;sub:truncate:every=6",
                     "--explain"]) == 0
    assert cli.main(["--spec", "reed:drop", "--explain"]) == 2


# ---------------------------------------------------------------------------
# acceptance: training + serving under reader chaos, end to end
# ---------------------------------------------------------------------------


_READER_CHAOS = ("read:truncate:every=5;read:stall:s=0.2:every=9;"
                 "sub:truncate:every=17;sub:stall:s=0.25:every=7")


@pytest.mark.duration_budget(150)  # pre-existing heavyweight; tier-1 coverage load-bearing
def test_chaos_acceptance_serving_under_reader_faults():
    """3 tcp training ranks + 4 subscriber processes; reader-side chaos
    tears/stalls reads and pushes on the serving hosts while the test
    SIGKILLs one subscriber and SIGSTOP/SIGCONTs another.  Every
    delivered snapshot passes an exact round-stamp audit in the
    subscriber processes; the training job's exact mass audit is
    IDENTICAL to a chaos-free run (total == n, nobody dead); surviving
    subscribers resume with nothing missed or duplicated."""
    import signal
    import tempfile

    worker = os.path.join(_REPO, "tests", "_mp_serving_worker.py")
    n = 3
    with tempfile.TemporaryDirectory() as bdir:
        name = _uniq("serve_mp")
        tr_env = clean_env()
        tr_env["BLUEFOG_TPU_CHAOS"] = _READER_CHAOS
        trainers = [
            subprocess.Popen(
                [sys.executable, worker, "train", str(r), str(n), bdir,
                 "6.0", name],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=tr_env, cwd=_REPO)
            for r in range(n)
        ]
        sub_targets = [0, 1, 2, 0]
        subs = [
            subprocess.Popen(
                [sys.executable, worker, "subscribe", str(i), str(n),
                 bdir, "4.0", name, str(sub_targets[i])],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=clean_env(), cwd=_REPO)
            for i in range(4)
        ]
        try:
            # wait for training to actually start (the 'created' barrier
            # file appears just before the loops run), then inject the
            # reader-death schedule the chaos spec cannot express
            deadline = time.monotonic() + 120
            while not os.path.exists(os.path.join(bdir, "created.0")):
                assert time.monotonic() < deadline, "trainers never started"
                time.sleep(0.1)
            time.sleep(1.5)
            subs[2].kill()                      # reader death
            time.sleep(0.3)
            os.kill(subs[3].pid, signal.SIGSTOP)  # reader stall...
            time.sleep(1.2)
            os.kill(subs[3].pid, signal.SIGCONT)  # ...and thaw

            t_out = []
            for p in trainers:
                out, _ = p.communicate(timeout=180)
                t_out.append(out)
            s_out = []
            for p in subs:
                out, _ = p.communicate(timeout=180)
                s_out.append(out)
        except subprocess.TimeoutExpired:
            for p in trainers + subs:
                p.kill()
            pytest.fail("serving acceptance timed out")
        # --- training untouched by reader chaos: exact audit, rc 0 ---
        for r, (p, out) in enumerate(zip(trainers, t_out)):
            assert p.returncode == 0, f"trainer {r} failed:\n{out}"
            assert f"TRAIN_OK {r}" in out, out
        assert "AUDIT mass=" in t_out[0], t_out[0]
        # --- the killed reader died; everyone else audited clean ---
        assert subs[2].returncode == -9, subs[2].returncode
        resumed = 0
        for i in (0, 1, 3):
            assert subs[i].returncode == 0, \
                f"subscriber {i} failed:\n{s_out[i]}"
            assert f"SERVE_OK {i}" in s_out[i], s_out[i]
            for tok in s_out[i].split():
                if tok.startswith("resumes="):
                    resumed += int(tok.split("=")[1])
        # the sub-site chaos cut push channels: somebody resumed, and
        # (asserted in-worker) without a missed or duplicated round
        assert resumed >= 1, s_out


def test_serving_replica_example_self_asserts():
    """The example IS the acceptance demo for the staleness bound: it
    asserts every delivered snapshot round-consistent and the served
    model at most K rounds stale while training progresses."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples",
                                      "serving_replica.py")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=clean_env(), cwd=_REPO, timeout=180)
    assert proc.returncode == 0, proc.stdout
    assert "serving_replica: OK" in proc.stdout, proc.stdout


# ---------------------------------------------------------------------------
# thread-mode publisher integration
# ---------------------------------------------------------------------------


def test_thread_dsgd_publishes_round_stamped_snapshots():
    """run_async_dsgd(snapshot_every=) publishes atomically per round;
    a concurrent wire reader sees only stamped, self-consistent
    (x, p, round) triples and the mass audit stays exact."""
    from bluefog_tpu import topology as T
    from bluefog_tpu.runtime.async_windows import run_async_dsgd
    from bluefog_tpu.serving import SnapshotUnavailable
    from bluefog_tpu.serving.client import SnapshotClient

    name = _uniq("thread_pub")
    srv, addr = _serve()
    seen = []
    stop = threading.Event()

    def reader():
        c = SnapshotClient(addr, f"{name}:0",
                           retry=dict(base_s=0.01, budget=4, seed=0))
        while not stop.is_set():
            try:
                snap = c.snapshot()
            except (SnapshotUnavailable, RuntimeError, OSError):
                time.sleep(0.01)
                continue
            assert int(snap["round"][0]) == snap.round, snap.round
            assert float(snap["p"][0]) > 0.0
            seen.append(snap.round)
            time.sleep(0.01)
        c.close()

    t = threading.Thread(target=reader)
    t.start()
    try:
        def loss_and_grad(r, step, params):
            w = np.asarray(params["w"], np.float64)
            return 0.5 * float(w @ w), {"w": w}

        report = run_async_dsgd(
            T.RingGraph(3), {"w": np.ones(6, np.float32)},
            loss_and_grad, lr=0.01, duration_s=1.5,
            skew=[0.002] * 3, name=name, snapshot_every=1)
        assert abs(report.total_mass - 3.0) < 1e-9
    finally:
        stop.set()
        t.join(timeout=10)
        srv.stop()
    assert seen and seen == sorted(seen), seen[:10]
    assert seen[-1] > seen[0], "reader never observed training progress"
