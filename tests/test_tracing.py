"""Fleet-wide causal tracing: the fifth observability leg.

Covers the tentpole surfaces of ``bluefog_tpu/tracing/`` plus the wire
propagation through the v2 transport:

- recorder semantics: thread-local span context, cross-thread
  begin/finish, the open-span flush snapshot (a wedged peer shows an
  OPEN span, never a missing one), lazy env activation, and a disabled
  path that is one env read + a None test;
- the ``bftrace-tpu`` analyzer against a CONSTRUCTED ground truth:
  per-edge phase decomposition, the per-round critical path naming the
  gating edge + dominant phase, overlap fraction, straggler ranking,
  chrome-trace causal flow arrows, torn-tail tolerance;
- wire propagation end to end in one process: a deposit's trace
  context rides the FEATURE_TRACE header, the owner-side
  recv/queue-wait/apply/ack spans parent to the sender's wire span,
  and the extended batch ack folds (queue_us, apply_us) back into the
  sender's ``phase_ewma`` — the control plane's slow-link-vs-slow-host
  evidence;
- 60-case malformed/truncated trace-header fuzz: header claimed but
  absent, garbage ids, truncation inside the header, an unnegotiated
  header, and a v-old peer without the feature bit — the server
  survives every case, frames apply exactly once, and tracing degrades
  silently per connection;
- tracing disabled => byte-identical jitted HLO (the PR 2/3
  discipline, asserted on both the jaxpr and the lowered HLO text);
- the 3-rank tcp dsgd acceptance run under ``server:delay`` chaos on
  one rank: ``bftrace-tpu`` names that rank's edge as the per-round
  critical path with a phase decomposition (slow-marked, like every MP
  soak).
"""

import json
import os
import socket
import struct
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np
import pytest

from tests._util import REPO as _REPO, clean_env, uniq as _uniq

import bluefog_tpu.tracing.analyze as tan
from bluefog_tpu.tracing import recorder as trc


@pytest.fixture(autouse=True)
def _fresh_recorder():
    """Every test starts and ends without a live process recorder."""
    trc.reset()
    yield
    trc.reset()


def _mk(name, n_slots, n_elems, dtype=np.float64):
    from bluefog_tpu.runtime.async_windows import AsyncWindow

    return AsyncWindow(name, n_slots=n_slots, n_elems=n_elems, dtype=dtype)


def _serve():
    from bluefog_tpu.runtime.window_server import WindowServer

    srv = WindowServer()
    _, port = srv.start("127.0.0.1")
    return srv, port


# ---------------------------------------------------------------------------
# 1. recorder semantics
# ---------------------------------------------------------------------------


class TestRecorder:
    def test_disabled_path_is_null(self):
        assert not trc.enabled()
        assert trc.get() is None
        assert trc.wire_ctx() is None
        with trc.span("x") as sp:
            assert sp is None  # the null context manager

    def test_lazy_env_activation(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BLUEFOG_TPU_TRACE", str(tmp_path))
        # reset() is sticky against the env (tests own the state); undo
        # the stick to exercise the lazy path the env var takes
        trc._STOPPED = False
        trc._RECORDER = None
        assert trc.enabled()
        assert trc.get().directory == str(tmp_path)

    def test_span_context_nesting_and_wire_ctx(self, tmp_path):
        rec = trc.configure(str(tmp_path), rank=3, job="jobA")
        with rec.span("round", "dsgd", round_=17) as outer:
            tid, sid, rnd = trc.wire_ctx()
            assert (tid, sid, rnd) == (outer.tid, outer.sid, 17)
            with rec.span("gossip", "dsgd") as inner:
                assert inner.par == outer.sid
                assert inner.round == 17  # inherited through the stack
        assert trc.current_ctx() is None
        rec.flush()
        spans = tan.load_traces(str(tmp_path))
        by_name = {s["name"]: s for s in spans}
        assert by_name["gossip"]["par"] == by_name["round"]["sid"]
        assert by_name["gossip"]["rank"] == 3
        assert not any(s.get("open") for s in spans)

    def test_open_span_snapshot_survives_flush(self, tmp_path):
        """A begun-but-unfinished span appears as open:true at every
        flush WITHOUT being discharged — wedged-peer forensics."""
        rec = trc.configure(str(tmp_path), rank=0)
        sp = rec.begin_span("wire", "tcp", round_=4)  # bftrace: cross-thread the test finishes it below
        rec.flush()
        spans = tan.load_traces(str(tmp_path))
        (open_sp,) = [s for s in spans if s["name"] == "wire"]
        assert open_sp.get("open") is True and open_sp["round"] == 4
        # finish from "another thread"; the closed record supersedes
        t = threading.Thread(target=sp.finish)
        t.start()
        t.join()
        rec.flush()
        spans = tan.load_traces(str(tmp_path))
        (closed,) = [s for s in spans if s["name"] == "wire"]
        assert not closed.get("open") and closed["sid"] == sp.sid

    def test_trace_id_is_coordination_free(self):
        assert trc.trace_id_for("job") == trc.trace_id_for("job")
        assert trc.trace_id_for("job") != trc.trace_id_for("job2")

    def test_rankless_process_writes_pid_file(self, tmp_path):
        """A rank-less recorder (a serving reader) must not alias
        rank 0's file — colocated processes sharing the trace dir
        would interleave appends; the analyzer reads both spellings."""
        trc.configure(str(tmp_path))  # no rank
        with trc.span("read", "tcp"):
            pass
        trc.flush()
        (path,) = tmp_path.glob("trace-pid*.jsonl")
        assert f"pid{os.getpid()}" in path.name
        assert not list(tmp_path.glob("trace-rank*.jsonl"))
        spans = tan.load_traces(str(tmp_path))
        assert [s["name"] for s in spans] == ["read"]

    def test_set_rank_pins_before_first_flush(self, tmp_path):
        trc.configure(str(tmp_path))
        trc.set_rank(5)
        with trc.span("x"):
            pass
        trc.flush()
        assert os.path.exists(str(tmp_path / "trace-rank5.jsonl"))
        trc.set_rank(6)  # later calls must not rename the identity
        assert trc.get().rank == 5


# ---------------------------------------------------------------------------
# 2. analyzer against a constructed ground truth
# ---------------------------------------------------------------------------


def _ground_truth_spans(rounds=5):
    """Two ranks; rank 1's deposits gate rank 0's rounds, queue-wait
    dominant.  Per round k (1 s cadence, synthetic clocks):

    - rank 1 round: [k, k+0.4]; compute [k+0.1, k+0.3]
    - rank 1 wire span to rank 0: [k+0.1, k+0.72] (dur 0.62)
    - rank 0 server: queue_wait [k+0.2, 0.45 s], apply [k+0.65, 0.1 s]
    - rank 0 round: [k, k+0.8] — last finisher, gated by the deposit
    """
    spans = []
    sid = 1
    for k in range(rounds):
        r1 = dict(sid=sid, par=0, tid=9, name="round", cat="dsgd",
                  rank=1, round=k, t0=float(k), dur=0.4)
        sid += 1
        comp = dict(sid=sid, par=r1["sid"], tid=9, name="compute",
                    cat="dsgd", rank=1, round=k, t0=k + 0.1, dur=0.2)
        sid += 1
        wire = dict(sid=sid, par=r1["sid"], tid=9, name="wire",
                    cat="tcp", rank=1, round=k, t0=k + 0.1, dur=0.62,
                    dst="w:0", seq=k)
        sid += 1
        qw = dict(sid=sid, par=wire["sid"], tid=9, name="queue_wait",
                  cat="tcp_srv", rank=0, round=k, t0=k + 0.2, dur=0.45)
        sid += 1
        ap = dict(sid=sid, par=wire["sid"], tid=9, name="apply",
                  cat="tcp_srv", rank=0, round=k, t0=k + 0.65, dur=0.1)
        sid += 1
        r0 = dict(sid=sid, par=0, tid=9, name="round", cat="dsgd",
                  rank=0, round=k, t0=float(k), dur=0.8)
        sid += 1
        spans += [r1, comp, wire, qw, ap, r0]
    return spans


class TestAnalyzer:
    def test_edge_phase_decomposition(self):
        graph = tan.build_graph(_ground_truth_spans())
        er = tan.edge_report(graph)
        assert set(er) == {"1->0"}
        e = er["1->0"]
        assert e["batches"] == 5
        assert e["wire_mean_s"] == pytest.approx(0.62)
        assert e["phase_mean_s"]["queue_wait"] == pytest.approx(0.45)
        assert e["phase_mean_s"]["apply"] == pytest.approx(0.1)
        assert e["phase_mean_s"]["net"] == pytest.approx(0.07)
        dom = max(e["phase_frac"], key=lambda p: e["phase_frac"][p])
        assert dom == "queue_wait"

    def test_critical_path_names_gating_edge_and_phase(self):
        graph = tan.build_graph(_ground_truth_spans())
        cp = tan.critical_path(graph)
        assert cp["gating_edge"] == [1, 0]
        assert cp["gating_rounds"] == 5
        assert cp["dominant_phase"] == "queue_wait"
        assert cp["dominant_frac"] == pytest.approx(0.45 / 0.62)

    def test_straggler_ranking_and_overlap(self):
        graph = tan.build_graph(_ground_truth_spans())
        rr = tan.round_report(graph)
        assert rr["straggler_ranking"] == [0, 1]
        assert rr["per_rank"][0]["round_mean_s"] == pytest.approx(0.8)
        ov = tan.overlap_report(graph)
        # compute [k+.1, k+.3] hides 0.2 s of the 0.62 s wire span
        assert ov[1] == pytest.approx(0.2 / 0.62)

    def test_extended_ack_fallback_without_server_spans(self):
        """Degraded mode: only the sender's trace exists (the owner
        never wrote a file) — the queue_s/apply_s the extended ack
        folded into the wire span still decompose the edge, with the
        destination recovered from the window name."""
        spans = [dict(sid=1, par=0, tid=9, name="wire", cat="tcp",
                      rank=1, round=0, t0=0.1, dur=0.62, dst="w:0",
                      queue_s=0.45, apply_s=0.1)]
        er = tan.edge_report(tan.build_graph(spans))
        assert set(er) == {"1->0"}
        assert er["1->0"]["phase_mean_s"]["queue_wait"] == \
            pytest.approx(0.45)

    def test_ack_backpressure_gate(self):
        """A slow RECEIVER gates the sender through the bounded
        in-flight window: the sender's own late-acked wire span is the
        gate, and the edge still names the receiver."""
        spans = []
        for k in range(4):
            spans.append(dict(sid=10 + k, par=0, tid=9, name="round",
                              cat="dsgd", rank=0, round=k, t0=float(k),
                              dur=0.9))
            # the ack lands at k+0.85, later than any incoming deposit
            spans.append(dict(sid=100 + k, par=0, tid=9, name="wire",
                              cat="tcp", rank=0, round=k, t0=k + 0.05,
                              dur=0.8, dst="w:2", seq=k))
        cp = tan.critical_path(tan.build_graph(spans))
        assert cp["gating_edge"] == [0, 2]
        assert cp["gating_rounds"] >= 3

    def test_torn_tail_and_open_dedup(self, tmp_path):
        path = tmp_path / "trace-rank0.jsonl"
        with open(path, "w") as f:
            f.write(json.dumps(dict(sid=1, name="a", rank=0, t0=0.0,
                                    dur=1.0)) + "\n")
            f.write(json.dumps(dict(sid=2, name="b", rank=0, t0=0.0,
                                    open=True)) + "\n")
            f.write(json.dumps(dict(sid=2, name="b", rank=0, t0=0.0,
                                    open=True, newest=True)) + "\n")
            f.write('{"sid": 3, "name": "torn')  # crashed writer
        spans = tan.load_traces(str(tmp_path))
        assert len(spans) == 2
        (b,) = [s for s in spans if s["name"] == "b"]
        assert b.get("newest") is True  # newest open snapshot wins

    def test_open_record_superseded_by_close(self, tmp_path):
        path = tmp_path / "trace-rank0.jsonl"
        with open(path, "w") as f:
            f.write(json.dumps(dict(sid=7, name="w", rank=0, t0=0.0,
                                    open=True)) + "\n")
            f.write(json.dumps(dict(sid=7, name="w", rank=0, t0=0.0,
                                    dur=2.0)) + "\n")
        spans = tan.load_traces(str(tmp_path))
        assert len(spans) == 1 and not spans[0].get("open")

    def test_chrome_trace_causal_flow_arrows(self):
        events = tan.chrome_trace(_ground_truth_spans(rounds=1))
        flows = [e for e in events if e.get("cat") == "flow"]
        # queue_wait and apply each link cross-rank to the wire span
        assert {e["ph"] for e in flows} == {"s", "f"}
        assert len([e for e in flows if e["ph"] == "s"]) == 2
        xs = [e for e in events if e.get("ph") == "X"]
        assert {e["pid"] for e in xs} == {0, 1}  # one pid per rank

    def test_cli_report_and_json(self, tmp_path):
        with open(tmp_path / "trace-rank0.jsonl", "w") as f:
            for s in _ground_truth_spans():
                f.write(json.dumps(s) + "\n")
        trace_out = str(tmp_path / "merged.json")
        proc = subprocess.run(
            [sys.executable, "-m", "bluefog_tpu.tracing",
             str(tmp_path), "--trace", trace_out],
            capture_output=True, text=True, timeout=120,
            env=clean_env(), cwd=_REPO)
        assert proc.returncode == 0, proc.stderr
        assert "CRITICAL PATH: rank 1 -> rank 0" in proc.stdout
        assert "queue_wait" in proc.stdout
        assert "straggler ranking (slowest first): 0, 1" in proc.stdout
        assert json.load(open(trace_out))  # valid chrome trace
        proc = subprocess.run(
            [sys.executable, "-m", "bluefog_tpu.tracing",
             str(tmp_path), "--json"],
            capture_output=True, text=True, timeout=120,
            env=clean_env(), cwd=_REPO)
        rep = json.loads(proc.stdout)
        assert rep["critical_path"]["gating_edge"] == [1, 0]

    def test_cli_empty_dir_fails_loud(self, tmp_path):
        assert tan.main([str(tmp_path)]) == 1


# ---------------------------------------------------------------------------
# 3. wire propagation through the live transport (one process)
# ---------------------------------------------------------------------------


class TestWirePropagation:
    def test_deposit_spans_link_across_the_wire(self, tmp_path):
        """The full causal chain in one process: the round span's
        context rides the trace header, the owner-side spans parent to
        the sender's wire span, the extended ack folds queue/apply back
        into the wire span and the per-peer phase EWMA."""
        from bluefog_tpu.runtime.window_server import PipelinedRemoteWindow

        trc.configure(str(tmp_path), rank=0)
        name = _uniq("trc_wire")
        win = _mk(name, 1, 8)
        srv, port = _serve()
        try:
            rw = PipelinedRemoteWindow(("127.0.0.1", port), name)
            assert rw.stream._trace_on  # HELLO negotiated FEATURE_TRACE
            arr = np.arange(8.0)
            with trc.span("round", "dsgd", round_=11):
                rw.deposit_async(0, arr, accumulate=True)
            rw.flush()
            buf, fresh = win.read(0, consume=True)
            assert fresh == 1  # exactly once
            np.testing.assert_allclose(buf, arr)

            phases = rw.phase_ewma()
            assert phases is not None
            assert set(phases) == {"net", "queue", "apply"}
            assert all(v >= 0 for v in phases.values())
            rw.close()
        finally:
            srv.stop()
            win.free()
        trc.flush()
        spans = tan.load_traces(str(tmp_path))
        by_name = {}
        for s in spans:
            by_name.setdefault(s["name"], []).append(s)
        for expected in ("round", "snapshot", "enqueue", "coalesce",
                         "wire", "ack_wait", "recv", "queue_wait",
                         "apply", "ack"):
            assert expected in by_name, (expected, sorted(by_name))
        (wire,) = by_name["wire"]
        (rnd,) = by_name["round"]
        assert wire["par"] == rnd["sid"]
        assert wire["round"] == 11
        # owner-side spans parent to the WIRE span (the propagated ctx)
        for srv_name in ("recv", "queue_wait", "apply", "ack"):
            (sp,) = by_name[srv_name]
            assert sp["par"] == wire["sid"], srv_name
            assert sp["round"] == 11
        # the extended ack folded the owner's timings into the sender
        assert wire["queue_s"] >= 0 and wire["apply_s"] >= 0
        assert not any(s.get("open") for s in spans)

    def test_tracing_off_degrades_silently(self, tmp_path):
        """A tracing-disabled client against the same server: no
        FEATURE_TRACE on the wire, plain acks, no trace file."""
        from bluefog_tpu.runtime.window_server import PipelinedRemoteWindow

        name = _uniq("trc_off")
        win = _mk(name, 1, 4)
        srv, port = _serve()
        try:
            rw = PipelinedRemoteWindow(("127.0.0.1", port), name)
            assert not rw.stream._trace_on
            rw.deposit_async(0, np.ones(4), accumulate=True)
            rw.flush()
            _, fresh = win.read(0, consume=True)
            assert fresh == 1
            assert rw.phase_ewma() is None
            rw.close()
        finally:
            srv.stop()
            win.free()
        assert not list(tmp_path.glob("trace-*.jsonl"))

    def test_snapshot_read_propagates_context(self, tmp_path):
        """The serving read path: the reader's snapshot_read span is
        answered by an owner-side snapshot_serve span parented to it."""
        from bluefog_tpu.serving import snapshots as snap
        from bluefog_tpu.serving.client import SnapshotClient

        trc.configure(str(tmp_path), rank=0)
        srv, port = _serve()
        group = _uniq("trc_snap")
        try:
            snap.table().publish(group, 3, {"w": np.arange(4.0)})
            cli = SnapshotClient(("127.0.0.1", port), group)
            got = cli.snapshot()
            assert got.round == 3
            cli.close()
        finally:
            srv.stop()
            snap.table().drop(group)
        trc.flush()
        spans = tan.load_traces(str(tmp_path))
        by_name = {s["name"]: s for s in spans}
        assert by_name["snapshot_serve"]["par"] == \
            by_name["snapshot_read"]["sid"]


# ---------------------------------------------------------------------------
# 4. trace-header fuzz (the wire must never trust the header)
# ---------------------------------------------------------------------------


class TestTraceHeaderFuzz:
    def test_60_case_trace_header_fuzz(self, tmp_path):
        """Malformed/truncated trace headers across 60 connections:
        the server survives every case, valid frames apply exactly
        once, invalid ones apply NOTHING (no phantom deposits), and a
        v-old peer without the feature bit works untraced."""
        from bluefog_tpu.runtime import window_server as ws

        trc.configure(str(tmp_path), rank=0)  # server-side spans live
        name = _uniq("trc_fuzz")
        win = _mk(name, 1, 8)
        srv, port = _serve()
        rng = np.random.default_rng(17)
        arr = np.ones(8)
        name_b = name.encode()
        item = ws._ITEM.pack(len(name_b), 0, 1, 1, 0, arr.size,
                             arr.nbytes)

        def hello(s, features):
            s.sendall(ws._HDR.pack(ws._MAGIC, ws._OP_HELLO, 0)
                      + ws._HELLO.pack(ws.PROTOCOL_VERSION, features))
            (granted,) = ws._STATUS.unpack(
                _recv_exactly(s, ws._STATUS.size))
            return granted

        def batch(seq, thdr):
            return (ws._HDR.pack(ws._MAGIC, ws._OP_DEPOSIT_BATCH, 0)
                    + thdr + ws._BATCH_HDR.pack(seq, 1) + item
                    + name_b + arr.tobytes())

        def _recv_exactly(s, n):
            buf = b""
            while len(buf) < n:
                got = s.recv(n - len(buf))
                if not got:
                    raise ConnectionError("closed")
                buf += got
            return buf

        want = ws.FEATURE_BATCH | ws.FEATURE_TRACE
        applied = 0
        for trial in range(60):
            mode = trial % 5
            should_apply = mode in (1, 3)
            try:
                with socket.create_connection(("127.0.0.1", port),
                                              timeout=10) as s:
                    s.settimeout(5)
                    if mode == 0:
                        # header claimed (FEATURE_TRACE granted) but
                        # ABSENT: the server misparses the batch as a
                        # header; at worst THIS connection dies
                        granted = hello(s, want)
                        assert granted & ws.FEATURE_TRACE
                        s.sendall(batch(1, b""))
                        s.shutdown(socket.SHUT_WR)
                        while s.recv(4096):
                            pass
                    elif mode == 1:
                        # garbage ids (incl. sid=0 half the time): the
                        # header PARSES, junk is ignored, the frame
                        # applies exactly once with a timed ack
                        hello(s, want)
                        thdr = ws._TRACE_HDR.pack(
                            int(rng.integers(0, 1 << 63)),
                            int(rng.integers(0, 2))
                            * int(rng.integers(1, 1 << 63)),
                            int(rng.integers(0, 1 << 32)))
                        s.sendall(batch(1, thdr))
                        ack = _recv_exactly(
                            s, ws._ACK.size + ws._ACK_TIMES.size)
                        seq, status = ws._ACK.unpack(
                            ack[:ws._ACK.size])
                        assert (seq, status) == (1, 1)
                    elif mode == 2:
                        # truncated INSIDE the trace header
                        hello(s, want)
                        cut = int(rng.integers(1, ws._TRACE_HDR.size))
                        full = batch(1, ws._TRACE_HDR.pack(7, 7, 7))
                        s.sendall(full[:ws._HDR.size + cut])
                        s.shutdown(socket.SHUT_WR)
                        while s.recv(4096):
                            pass
                    elif mode == 3:
                        # v-old peer: no FEATURE_TRACE wanted; frames
                        # carry no header; plain (8+4 byte) ack
                        granted = hello(s, ws.FEATURE_BATCH)
                        assert granted & ws.FEATURE_BATCH
                        s.sendall(batch(1, b""))
                        ack = _recv_exactly(s, ws._ACK.size)
                        seq, status = ws._ACK.unpack(ack)
                        assert (seq, status) == (1, 1)
                    else:
                        # header sent WITHOUT negotiating the bit: the
                        # 20 bytes are junk ops — connection drops,
                        # server survives, nothing applies
                        hello(s, ws.FEATURE_BATCH)
                        s.sendall(batch(1, ws._TRACE_HDR.pack(9, 9, 9)))
                        s.shutdown(socket.SHUT_WR)
                        while s.recv(4096):
                            pass
            except OSError:
                pass  # a torn connection is an allowed outcome
            # exactly-once, checked after EVERY trial: valid frames
            # landed once, malformed ones landed NOTHING
            buf, fresh = win.read(0, consume=True)
            if should_apply:
                applied += 1
                assert fresh == 1, (trial, mode, fresh)
                np.testing.assert_allclose(buf, arr)
            else:
                assert fresh == 0, (trial, mode, fresh)
        assert applied == 24  # 60 trials, modes 1 and 3

        # the server is fully healthy for a fresh traced client
        from bluefog_tpu.runtime.window_server import PipelinedRemoteWindow

        rw = PipelinedRemoteWindow(("127.0.0.1", port), name)
        try:
            rw.deposit_async(0, arr, accumulate=True)
            rw.flush()
            _, fresh = win.read(0, consume=True)
            assert fresh == 1
        finally:
            rw.close()
            srv.stop()
            win.free()


# ---------------------------------------------------------------------------
# 5. disabled => byte-identical jitted HLO
# ---------------------------------------------------------------------------


class TestHLOIdentity:
    def _gossip_program(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P

        from bluefog_tpu.ops.collectives import neighbor_allreduce
        from bluefog_tpu.parallel.api import shard_map
        from bluefog_tpu.topology import RingGraph, build_schedule

        n = 8
        mesh = Mesh(np.array(jax.devices()[:n]), ("bf",))
        sched = build_schedule(RingGraph(n))
        fn = shard_map(lambda v: neighbor_allreduce(v, sched, "bf"),
                       mesh=mesh, in_specs=(P("bf"),),
                       out_specs=P("bf"), check_vma=False)
        x = jnp.ones((n, 4), jnp.float32)
        jaxpr = str(jax.make_jaxpr(fn)(x))
        hlo = jax.jit(fn).lower(x).as_text()
        return jaxpr, hlo

    def test_identical_hlo_tracing_off_and_on(self, tmp_path,
                                              monkeypatch):
        """The acceptance gate: arming tracing cannot change compiled
        programs — byte-identical jaxpr AND lowered HLO, no callbacks
        anywhere near the traced path."""
        monkeypatch.delenv("BLUEFOG_TPU_TRACE", raising=False)
        trc.reset()
        off_jaxpr, off_hlo = self._gossip_program()
        trc.configure(str(tmp_path), rank=0)
        with trc.span("round", "dsgd", round_=0):
            on_jaxpr, on_hlo = self._gossip_program()
        assert off_jaxpr == on_jaxpr
        assert off_hlo == on_hlo


# ---------------------------------------------------------------------------
# 6. acceptance: 3-rank tcp dsgd under server:delay chaos
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestChaosCriticalPathE2E:
    def test_bftrace_names_the_delayed_ranks_edge(self, tmp_path):
        """Rank 2's window server delays inbound frames; bftrace-tpu
        must name an edge INTO rank 2 as the per-round critical path,
        with a phase decomposition attached."""
        barrier = tmp_path / "barrier"
        trace_dir = tmp_path / "trace"
        barrier.mkdir()
        trace_dir.mkdir()
        procs = [
            subprocess.Popen(
                [sys.executable,
                 os.path.join(_REPO, "tests", "_mp_tracing_worker.py"),
                 str(r), "3", str(barrier), str(trace_dir), "60"],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=clean_env(), cwd=_REPO)
            for r in range(3)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=180)
                outs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for r, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"rank {r}:\n{out}"
            assert f"TRC_MP_OK {r}" in out, f"rank {r}:\n{out}"

        rep = tan.analyze(str(trace_dir))
        assert rep["ranks"] == [0, 1, 2]
        cp = rep["critical_path"]
        assert cp.get("gating_edge"), cp
        assert cp["gating_edge"][1] == 2, cp
        assert cp["phase_frac"], cp  # the decomposition is attached
        assert 0 < cp["dominant_frac"] <= 1

        # and the operator-facing CLI line says it in words
        proc = subprocess.run(
            [sys.executable, "-m", "bluefog_tpu.tracing",
             str(trace_dir)],
            capture_output=True, text=True, timeout=120,
            env=clean_env(), cwd=_REPO)
        assert proc.returncode == 0, proc.stderr
        assert "CRITICAL PATH" in proc.stdout, proc.stdout
        assert "-> rank 2 —" in proc.stdout, proc.stdout
