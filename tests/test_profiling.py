"""Continuous profiling: sampler attribution, zero-cost disabled path,
torn-tail merge, the differential gate's exit codes, and the BF-PROF /
BF-DOC004 lint rules."""

import json
import os
import threading
import time

import pytest

import bluefog_tpu.profiling as bp
from bluefog_tpu.profiling import sampler as ps
from bluefog_tpu.profiling import report as pr
from bluefog_tpu.profiling.cli import main as prof_main
from bluefog_tpu.tracing import recorder as tr


@pytest.fixture(autouse=True)
def _no_leaked_profiler():
    """Every test leaves the process with no sampler thread and phase
    tracking off (the disabled-path tests depend on it)."""
    yield
    ps.reset()
    assert not [t for t in threading.enumerate()
                if t.name == ps.Profiler.THREAD_NAME]


def _busy_until(deadline):
    x = 0
    while time.perf_counter() < deadline:
        x += 1
    return x


# ---------------------------------------------------------------------------
# Attribution
# ---------------------------------------------------------------------------


def test_phase_attribution_80_20(tmp_path):
    """A worker spending ~80% of its wall time in a compute span and
    ~20% in a gossip span attributes within ±10 percentage points."""
    bp.configure(str(tmp_path), rank=0, hz=400)
    stop = time.perf_counter() + 1.6
    # run the workload on ITS OWN thread: the sampler never samples a
    # thread it cannot see, and the main thread carries pytest frames
    def worker():
        while time.perf_counter() < stop:
            with tr.span("compute", "test"):
                _busy_until(time.perf_counter() + 0.008)
            with tr.span("gossip", "test"):
                _busy_until(time.perf_counter() + 0.002)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    t.join()
    ps.reset()

    rep = bp.merge(str(tmp_path))
    comp = rep["phases"].get("compute", 0)
    goss = rep["phases"].get("gossip", 0)
    assert comp + goss >= 100, rep["phases"]  # enough samples to judge
    frac = comp / (comp + goss)
    assert 0.70 <= frac <= 0.90, frac
    # and the report's attribution covers the worker's share of samples
    assert rep["attributed_frac"] > 0.0
    assert rep["ranks"] == [0]


def test_phase_only_tracking_without_tracing(tmp_path):
    """span() maintains the phase map for the sampler even when tracing
    is off — and drops back to the free null CM once disarmed."""
    assert tr.span("compute", "t") is tr._NULL_CM
    bp.configure(str(tmp_path), rank=0, hz=50)
    cm = tr.span("compute", "t", round_=3)
    assert cm is not tr._NULL_CM
    with cm:
        assert tr.active_phases()[threading.get_ident()] == ("compute", 3)
    assert threading.get_ident() not in tr.active_phases()
    ps.reset()
    assert tr.span("compute", "t") is tr._NULL_CM


# ---------------------------------------------------------------------------
# Disabled path: exactly nothing
# ---------------------------------------------------------------------------


def test_disabled_no_thread_and_identical_hlo(tmp_path):
    import jax
    import jax.numpy as jnp

    assert ps.get() is None
    assert not [t for t in threading.enumerate()
                if t.name == ps.Profiler.THREAD_NAME]

    @jax.jit
    def fn(x):
        return (x * 2.0).sum()

    x = jnp.arange(8.0)
    hlo_off = fn.lower(x).compile().as_text()

    bp.configure(str(tmp_path), rank=0, hz=50)
    try:
        assert [t for t in threading.enumerate()
                if t.name == ps.Profiler.THREAD_NAME]
        hlo_on = fn.lower(x).compile().as_text()
    finally:
        ps.reset()
    assert hlo_on == hlo_off  # byte-identical: no callbacks, no hooks


def test_env_lazy_arming_and_sticky_reset(tmp_path, monkeypatch):
    monkeypatch.setenv("BLUEFOG_TPU_PROFILE", str(tmp_path))
    # a prior test's reset() left the sticky stop set (by design: env
    # alone never resurrects a stopped sampler) — model a fresh process
    monkeypatch.setattr(ps, "_STOPPED", False)
    prof = ps.get()
    assert prof is not None and prof.directory == str(tmp_path)
    ps.reset()
    # sticky: the env var alone must not resurrect a reset profiler
    assert ps.get() is None
    # but an explicit configure un-sticks
    assert ps.configure(str(tmp_path), rank=1) is ps.get()
    ps.reset()


def test_bad_hz_rejected(tmp_path):
    with pytest.raises(ValueError):
        ps.Profiler(str(tmp_path), hz=-5)
    with pytest.raises(ValueError):
        ps.Profiler(str(tmp_path), hz=5000)


# ---------------------------------------------------------------------------
# Merge: torn tails, multi-rank
# ---------------------------------------------------------------------------


def _window(rank, t0, t1, stacks):
    phases = {}
    for ph, _, n in stacks:
        phases[ph] = phases.get(ph, 0) + n
    return {"kind": "window", "t0": t0, "t1": t1, "rank": rank,
            "hz": 97.0, "samples": sum(n for _, _, n in stacks),
            "phases": phases, "stacks": stacks}


def test_merge_tolerates_torn_tail(tmp_path):
    p0 = tmp_path / "profile-rank0.jsonl"
    lines = [
        json.dumps({"kind": "meta", "rank": 0, "pid": 1, "hz": 97.0,
                    "t0": 10.0}),
        json.dumps(_window(0, 10.0, 11.0, [["compute", "a;b", 5]])),
        json.dumps(_window(0, 11.0, 12.0,
                           [["compute", "a;b", 3],
                            ["net-wait", "a;c", 2]])),
    ]
    # a crashed writer's torn tail: half a JSON object, no newline
    p0.write_text("\n".join(lines) + "\n" + '{"kind": "wind')
    p1 = tmp_path / "profile-rank1.jsonl"
    p1.write_text(json.dumps(
        _window(1, 10.5, 11.5, [["compute", "a;b", 4]])) + "\n")

    rep = pr.merge(str(tmp_path))
    assert rep["ranks"] == [0, 1]
    assert rep["samples"] == 14  # the torn record contributes nothing
    assert rep["frames"]["b"]["self"] == 12
    assert rep["frames"]["a"]["total"] == 14
    assert rep["wall_s"] == 2.0
    # folded render keeps the phase as the root frame
    folded = pr.render_folded(rep)
    assert "compute;a;b 12" in folded
    svg = pr.render_svg(rep)
    assert svg.startswith("<svg") and "compute" in svg


def test_phase_frames_names_leafs():
    rep = {"stacks": [["net-wait", "a;b;wait_loop", 7],
                      ["net-wait", "a;wait_loop", 3],
                      ["compute", "a;matmul", 9]]}
    assert pr.phase_frames(rep, "net-wait")[0] == ("wait_loop", 10)


# ---------------------------------------------------------------------------
# The differential gate
# ---------------------------------------------------------------------------


def _report_json(tmp_path, name, frames, samples):
    rep = {"kind": "bfprof_report", "samples": samples,
           "frames": {fr: {"self": n, "total": n}
                      for fr, n in frames.items()},
           "phases": {}, "phase_frac": {}, "attributed_frac": 0.0,
           "ranks": [0], "stacks": []}
    path = tmp_path / name
    path.write_text(json.dumps(rep))
    return str(path)


def test_diff_exit_codes(tmp_path, capsys):
    base = _report_json(tmp_path, "base.json",
                        {"hot": 500, "warm": 300, "cold": 200}, 1000)
    clean = _report_json(tmp_path, "clean.json",
                         {"hot": 510, "warm": 290, "cold": 200}, 1000)
    # seeded >= 20% relative regression on an established hot frame
    regr = _report_json(tmp_path, "regr.json",
                        {"hot": 700, "warm": 150, "cold": 150}, 1000)

    assert prof_main(["--diff", base, clean]) == 0
    assert prof_main(["--diff", base, regr]) == 3
    out = capsys.readouterr().out
    assert '"ok": false' in out and "hot" in out
    # a tighter threshold flips the clean pair too
    assert prof_main(["--diff", base, regr, "--threshold", "0.9"]) == 0
    # load errors exit 2, not 3
    assert prof_main(["--diff", base, str(tmp_path / "missing.json")]) == 2
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    assert prof_main(["--diff", base, str(bad)]) == 2


def test_diff_flags_new_hot_frame():
    base = {"samples": 1000, "frames": {"a": {"self": 1000}}}
    head = {"samples": 1000, "frames": {"a": {"self": 900},
                                        "newcomer": {"self": 100}}}
    v = pr.diff(base, head)
    assert not v["ok"]
    assert v["regressions"][0]["frame"] == "newcomer"
    assert v["regressions"][0]["new"] is True


def test_cli_report_and_empty_dir(tmp_path, capsys):
    assert prof_main([str(tmp_path)]) == 2  # no samples: usage error
    capsys.readouterr()
    (tmp_path / "profile-rank0.jsonl").write_text(
        json.dumps(_window(0, 0.0, 1.0, [["compute", "m:f", 10]])) + "\n")
    assert prof_main([str(tmp_path), "--top", "3"]) == 0
    out = capsys.readouterr().out
    assert "m:f" in out and "compute" in out
    svg_path = tmp_path / "fg.svg"
    assert prof_main([str(tmp_path), "--svg", str(svg_path)]) == 0
    assert svg_path.read_text().startswith("<svg")


# ---------------------------------------------------------------------------
# Wiring: runner, blackbox dump, fleet record
# ---------------------------------------------------------------------------


def test_thread_runner_profile_wiring(tmp_path):
    import jax.numpy as jnp
    import numpy as np
    from bluefog_tpu.runtime import async_windows as aw
    from bluefog_tpu.topology import RingGraph

    def loss_and_grad(rank, step, params):
        return 0.0, {"x": params["x"] * 0.0}

    report = aw.run_async_dsgd(
        RingGraph(2), {"x": jnp.zeros(4)}, loss_and_grad,
        duration_s=1.0, name=f"dsgd_prof_{os.getpid()}",
        profile=str(tmp_path))
    assert abs(report.total_mass - 2) < 1e-9
    # the runner stopped the sampler it started…
    assert ps.get() is None
    assert not [t for t in threading.enumerate()
                if t.name == ps.Profiler.THREAD_NAME]
    # …after it wrote this run's per-rank profile
    assert (tmp_path / "profile-rank0.jsonl").exists()
    rep = pr.merge(str(tmp_path))
    assert rep["samples"] > 0


def test_blackbox_dump_embeds_profile(tmp_path):
    import importlib
    bdump = importlib.import_module("bluefog_tpu.blackbox.dump")

    bp.configure(str(tmp_path / "prof"), rank=0, hz=200)
    t = threading.Thread(target=_busy_until,
                         args=(time.perf_counter() + 0.4,), daemon=True)
    t.start()
    t.join()
    path = bdump.dump("test_profile_embed",
                      directory=str(tmp_path / "bb"), rank=3)
    ps.reset()
    assert path is not None
    lines = [json.loads(line)
             for line in open(path).read().splitlines()]
    prof_lines = [ln["profile"] for ln in lines if "profile" in ln]
    assert len(prof_lines) == 1
    assert prof_lines[0]["samples"] > 0
    assert prof_lines[0]["window_s"] == ps.RECENT_WINDOW_S
    assert prof_lines[0]["stacks"]


def test_fleet_record_profile_digest_roundtrip():
    from bluefog_tpu.fleet.record import FleetRecord

    rec = FleetRecord(rank=1, round=4, t=1.0,
                      profile={"mod.py:hot": 0.62, "mod.py:warm": 0.2})
    back = FleetRecord.from_json(rec.to_json())
    assert back.profile == {"mod.py:hot": 0.62, "mod.py:warm": 0.2}
    # canonical bytes stay canonical
    assert back.to_json() == rec.to_json()
    # pre-profile records (older writers) parse with an empty digest
    old = json.loads(rec.to_json())
    del old["profile"]
    assert FleetRecord.from_json(json.dumps(old)).profile == {}


def test_recorder_recent_window():
    from bluefog_tpu.blackbox.recorder import FlightRecorder

    rec = FlightRecorder(capacity=16)
    for i in range(3):
        rec.record("tick", i=i)
    got = rec.recent(60.0)
    assert [e["i"] for e in got] == [0, 1, 2]  # oldest first
    # age the first event out of the window (the ring stores wall
    # times; aging one directly beats sleeping in a tier-1 test)
    rec._events[0]["t"] -= 120.0
    got = rec.recent(60.0)
    assert [e["i"] for e in got] == [1, 2]


# ---------------------------------------------------------------------------
# Lint rules
# ---------------------------------------------------------------------------


def test_profiling_lint_clean_on_package():
    import glob
    from bluefog_tpu.analysis.profiling_lint import check_file

    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bluefog_tpu", "profiling")
    errors = []
    for p in sorted(glob.glob(os.path.join(root, "*.py"))):
        errors += [d for d in check_file(p) if d.severity == "error"]
    assert not errors, [d.message for d in errors]


def test_profiling_lint_catches_hot_path_violations(tmp_path):
    from bluefog_tpu.analysis.profiling_lint import check_file

    bad = tmp_path / "bad_sampler.py"
    bad.write_text(
        "import sys, json, collections\n"
        "ring = collections.deque()\n"          # BF-PROF002
        "def _log(rec):\n"
        "    return json.dumps(rec)\n"          # reachable: BF-PROF001
        "def sample(lock):\n"
        "    frames = sys._current_frames()\n"
        "    with lock:\n"                      # BF-PROF001 (lock name)
        "        _log(frames)\n")
    codes = [d.code for d in check_file(str(bad))
             if d.severity == "error"]
    assert "BF-PROF002" in codes
    assert codes.count("BF-PROF001") == 2, codes


def test_cli_doc_lint_both_directions(tmp_path):
    from bluefog_tpu.analysis.doc_lint import check_cli_doc

    # the live repo agrees
    assert not [d for d in check_cli_doc() if d.severity == "error"]

    pyproject = tmp_path / "pyproject.toml"
    pyproject.write_text(
        "[project.scripts]\n"
        'bfx-tpu = "m.cli:main"\n'
        'bfy-tpu = "m.cli:other"\n')
    doc = tmp_path / "API.md"
    doc.write_text("`bfx-tpu` does things; `bfstale-tpu` was renamed.\n")
    diags = check_cli_doc(doc_path=str(doc),
                          pyproject_path=str(pyproject))
    subjects = {d.subject for d in diags if d.severity == "error"}
    assert subjects == {"bfy-tpu", "bfstale-tpu"}


def test_lint_run_all_includes_profiling_pass():
    # registration, not a full sweep (bflint runs the whole thing in
    # test_analysis): the pass list must name profiling-lint
    from bluefog_tpu.analysis import lint as L
    from bluefog_tpu.analysis.report import LintReport

    report = LintReport()
    L.profiling_pass(report, 8)
    assert any(d.code == "BF-PROF101" for d in report.diagnostics)
    assert not [d for d in report.diagnostics if d.severity == "error"]
