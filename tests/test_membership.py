"""Elastic membership: join/leave/warm-start/replan.

Covers the elasticity tentpole end to end:

1. algebra — property tests for the heal/replan calculus:
   ``heal(heal(t, a), b) == heal(t, a | b)``, row-stochasticity and
   inert-self-loop invariants under arbitrary seeded kill/rejoin
   sequences, replan determinism in the member list (the
   coordination-free contract), rejoin-readmission round trips, and the
   collapsed single-suffix name (no unbounded ``+heal(...)+heal(...)``
   growth into metric labels);
2. the state machine — JOINING/LEFT lanes of the peer-health machine
   and the HealthBoard's reserved capacity slots;
3. chaos churn grammar — ``leave@at_step`` / ``join@after_s`` rules,
   their validation, and the consumed-once join schedule;
4. thread-mode lifecycle — a rank joins a running ``run_async_dsgd``
   (warm-starting from a member's published window snapshot), a rank
   drains gracefully (mass handed off, never written off), a chaos-
   driven flapping member, all with the EXACT mass audit
   ``total + died == initial members + admissions``;
5. multi-process tcp — the acceptance scenario (a 4th process joins 3
   running ranks and warm-starts via window reads, one original rank
   drains; exact audit over the final member set) and a slow-marked
   churn soak (join + SIGKILL in one run, replan keeps the live graph
   connected).

Everything deterministic: seeded RNGs and counter triggers, no luck.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from tests._util import REPO as _REPO, clean_env, uniq as _uniq


@pytest.fixture(autouse=True)
def _chaos_isolated():
    from bluefog_tpu import chaos

    chaos.reset()
    yield
    chaos.reset()


# ---------------------------------------------------------------------------
# 1. heal/replan algebra
# ---------------------------------------------------------------------------


def _row_stochastic(w):
    np.testing.assert_allclose(w.sum(axis=1), 1.0, atol=1e-9)
    assert (w >= -1e-12).all()


class TestHealAlgebra:
    def test_heal_composes_to_union(self):
        from bluefog_tpu import topology as T

        t = T.ExponentialTwoGraph(8)
        a, b = {1, 4}, {2}
        lhs = T.heal(T.heal(t, a), b)
        rhs = T.heal(t, a | b)
        assert T.IsTopologyEquivalent(lhs, rhs)
        assert lhs.inactive == frozenset(a | b)

    def test_arbitrary_kill_rejoin_sequences_keep_invariants(self):
        # seeded random walks over the membership lattice: kill some,
        # rejoin some (heal from the ORIGINAL with the smaller dead
        # set), kill again — after every step the matrix must be
        # row-stochastic, dead rows inert self-loops, live rows never
        # referencing the dead
        from bluefog_tpu import topology as T

        rng = np.random.default_rng(7)
        for base in (T.ExponentialTwoGraph(8), T.RingGraph(6),
                     T.MeshGrid2DGraph(9)):
            n = base.size
            dead: set = set()
            for _ in range(12):
                if dead and rng.random() < 0.4:
                    dead.discard(int(rng.choice(sorted(dead))))  # rejoin
                else:
                    alive = sorted(set(range(n)) - dead)
                    if len(alive) > 1:
                        dead.add(int(rng.choice(alive)))
                healed = T.heal(base, dead)
                w = healed.weights
                _row_stochastic(w)
                for r in dead:
                    assert w[r, r] == 1.0
                    assert np.count_nonzero(w[r]) == 1
                for i in set(range(n)) - dead:
                    assert all(w[i, j] == 0.0 for j in dead)
                # composition path agrees with the direct path
                if dead:
                    step = T.heal(T.heal(base, set(list(dead)[:1])),
                                  dead - set(list(dead)[:1]))
                    assert T.IsTopologyEquivalent(healed, step)

    def test_name_collapses_to_single_suffix(self):
        from bluefog_tpu import topology as T

        t = T.ExponentialTwoGraph(6)
        h = T.heal(T.heal(T.heal(t, {1}), {2}), {3})
        assert h.name == "ExponentialTwoGraph+heal([1, 2, 3])"
        assert h.name.count("+heal") == 1
        r = T.replan(T.replan(t, [0, 1, 2, 3]), [0, 2])
        assert r.name == "ExponentialTwoGraph+replan(n=2)"
        assert r.name.count("+replan") == 1
        # mixed churn (heal -> replan -> heal) still one suffix
        m = T.heal(T.replan(h, [0, 2, 4]), {4})
        assert m.name == "ExponentialTwoGraph+heal([1, 3, 4, 5])"


class TestReplan:
    def test_deterministic_in_member_list(self):
        # the coordination-free contract: every rank computing replan
        # from the same member list (any order, any duplicates) lands
        # on the SAME matrix
        from bluefog_tpu import topology as T

        t = T.ExponentialTwoGraph(8)
        a = T.replan(t, [0, 3, 5, 6])
        b = T.replan(t, [6, 0, 5, 3, 3])
        assert np.array_equal(a.weights, b.weights)
        assert a.inactive == b.inactive == frozenset({1, 2, 4, 7})

    def test_memoryless_over_member_sets(self):
        # rejoin-readmission round trip: replanning back to the full
        # set erases all membership history
        from bluefog_tpu import topology as T

        t = T.ExponentialTwoGraph(8)
        shrunk = T.replan(t, [0, 1, 2])
        grown = T.replan(shrunk, range(8))
        assert T.IsTopologyEquivalent(grown, T.replan(t, range(8)))
        assert grown.inactive == frozenset()

    def test_every_member_count_verifies(self):
        # the acceptance invariant: every replan the runtime can emit
        # keeps the ACTIVE graph strongly connected with a nonzero
        # spectral gap — checked by the same verifier the bflint-tpu
        # sweep runs
        from bluefog_tpu import topology as T
        from bluefog_tpu.analysis.topology_check import check_topology

        base = T.ExponentialTwoGraph(9)
        for m in range(1, 10):
            rng = np.random.default_rng(m)
            members = sorted(rng.choice(9, size=m, replace=False).tolist())
            diags = check_topology(T.replan(base, members))
            errors = [d for d in diags if d.severity == "error"]
            assert not errors, [d.format() for d in errors]
            _row_stochastic(T.replan(base, members).weights)

    def test_degree_caps_scale_with_member_count(self):
        # tiny fleets afford one-step exact averaging; big ones cap
        # out-degree at ~log2(m) via the exponential family
        from bluefog_tpu import topology as T

        t = T.FullyConnectedGraph(16)
        small = T.replan(t, range(3))
        assert small.weights[0, 1] > 0 and small.weights[0, 2] > 0
        big = T.replan(t, range(16))
        degs = [big.out_degree(r) for r in range(16)]
        assert max(degs) <= 5  # ceil(log2 16) + slack, not 15

    def test_errors(self):
        from bluefog_tpu import topology as T

        t = T.RingGraph(4)
        with pytest.raises(ValueError):
            T.replan(t, [])
        with pytest.raises(ValueError):
            T.replan(t, [0, 9])

    def test_embedding_violations_are_lint_errors(self):
        # the verifier rejects a hand-built "replan" that leaks weight
        # toward an inactive rank — the bug the heal exists to stop
        from bluefog_tpu import topology as T
        from bluefog_tpu.analysis.topology_check import check_topology

        w = np.array([[0.5, 0.25, 0.25],
                      [0.5, 0.5, 0.0],
                      [0.0, 0.0, 1.0]])
        leaky = T.Topology(weights=w, name="leaky", inactive={2})
        codes = {d.code for d in check_topology(leaky)}
        assert "BF-TOPO031" in codes, codes


# ---------------------------------------------------------------------------
# 2. JOINING / LEFT state machine
# ---------------------------------------------------------------------------


class TestMembershipStates:
    def test_joining_is_sticky_until_admit(self):
        from bluefog_tpu.runtime import resilience as R

        t = [0.0]
        h = R.PeerHealth("peer", suspect_after_s=1.0, dead_after_s=3.0,
                         clock=lambda: t[0])
        h.mark_joining()
        t[0] = 100.0  # silence must NOT promote a warm-starting joiner
        assert h.poll() == R.JOINING
        h.admit()
        assert h.state == R.HEALTHY
        seq = [(a, b) for (_, a, b) in h.transitions]
        assert (R.HEALTHY, R.JOINING) in seq
        assert (R.JOINING, R.HEALTHY) in seq

    def test_left_is_sticky_and_revivable(self):
        from bluefog_tpu.runtime import resilience as R

        t = [0.0]
        h = R.PeerHealth("peer", suspect_after_s=1.0, dead_after_s=3.0,
                         clock=lambda: t[0])
        h.mark_left()
        t[0] = 100.0
        assert h.poll() == R.LEFT  # an absent peer is not a silent one
        h.mark_joining()  # the slot's next life
        assert h.state == R.JOINING
        h.admit()
        assert h.state == R.HEALTHY

    def test_board_reserved_slots_start_left(self):
        from bluefog_tpu.runtime import resilience as R

        t = [0.0]
        board = R.HealthBoard(4, suspect_after_s=0.5, dead_after_s=1.0,
                              clock=lambda: t[0], members={0, 1})
        assert board.left_ranks() == {2, 3}
        t[0] = 50.0  # reserved slots never read DEAD by silence (the
        # silent MEMBERS rightly do — absence and silence differ)
        assert board.dead_ranks() == {0, 1}
        assert not (board.dead_ranks() & {2, 3})
        board.mark_joining(2)
        assert board.joining_ranks() == {2}
        board.admit(2)
        assert board.state(2) == R.HEALTHY
        board.mark_left(2)
        assert board.left_ranks() == {2, 3}


# ---------------------------------------------------------------------------
# 3. chaos churn grammar
# ---------------------------------------------------------------------------


class TestChurnFaults:
    def test_grammar(self):
        from bluefog_tpu.chaos import parse_spec

        rules = parse_spec("rank1:leave:at_step=20; rank3:join:after_s=0.5")
        assert [r.fault for r in rules] == ["leave", "join"]
        assert rules[0].at_step == 20 and rules[1].after_s == 0.5

    @pytest.mark.parametrize("bad", [
        "rank1:leave",                  # leave needs at_step
        "rank1:leave:after_s=1",        # ... not after_s
        "rank1:join",                   # join needs after_s
        "rank1:join:at_step=1",         # ... not at_step
        "server:leave:after_frames=1",  # membership faults are rank-only
    ])
    def test_bad_specs_fail_fast(self, bad):
        from bluefog_tpu.chaos import ChaosSpecError, parse_spec

        with pytest.raises(ChaosSpecError):
            parse_spec(bad)

    def test_leave_raises_chaosleave_at_step(self):
        from bluefog_tpu import chaos

        chaos.configure("rank1:leave:at_step=5")
        chaos.check_step(1, 4)
        chaos.check_step(0, 99)
        with pytest.raises(chaos.ChaosLeave):
            chaos.check_step(1, 5)
        chaos.check_step(1, 6)  # one-shot: a rank drains once per rule

    def test_join_schedule_consumed_once(self):
        from bluefog_tpu import chaos

        chaos.configure("rank3:join:after_s=0.5; rank3:join:after_s=2.0")
        assert chaos.join_times(3) == [0.5, 2.0]
        assert chaos.join_times(3) == []  # the runner owns it now
        assert chaos.join_times(1) == []


# ---------------------------------------------------------------------------
# 4. thread-mode elastic lifecycle
# ---------------------------------------------------------------------------


def _quadratic(n):
    targets = np.stack([np.full(4, float(r + 1)) for r in range(n)])

    def loss_and_grad(r, step, params):
        w = np.asarray(params["w"], np.float64)
        diff = w - targets[r]
        return 0.5 * float(diff @ diff), {"w": diff}

    return loss_and_grad


@pytest.mark.chaos
class TestThreadElastic:
    def test_join_midrun_warmstarts_and_audit_exact(self):
        from bluefog_tpu import topology as T
        from bluefog_tpu.runtime.async_windows import run_async_dsgd
        from bluefog_tpu.runtime.resilience import ResilienceConfig

        rep = run_async_dsgd(
            T.FullyConnectedGraph(4), {"w": np.zeros(4, np.float32)},
            _quadratic(4), duration_s=2.0, skew=[0.001] * 4,
            name=_uniq("mem_join"),
            resilience=ResilienceConfig(suspect_after_s=0.2,
                                        dead_after_s=0.6),
            join_at_s={3: 0.4})
        assert rep.joined_ranks == [3]
        assert rep.left_ranks == [] and rep.dead_ranks == []
        # the EXACT audit over the grown fleet: 3 initial units of mass
        # + 1 admitted — all accounted for
        assert rep.baseline_mass == 4.0
        assert abs(rep.total_mass - 4.0) < 1e-9, rep.total_mass
        # the joiner trained meaningfully after its admission and
        # reached consensus with the incumbents (a cold zero start
        # could not, in the remaining ~1.6 s, if it had to re-mix from
        # scratch against three converged ranks)
        assert rep.steps_per_rank[3] > 20, rep.steps_per_rank
        assert rep.consensus_gap < 0.5, rep.consensus_gap
        # the board recorded the admission lane
        seq = [(a, b) for (_, a, b) in rep.health_transitions[3]]
        from bluefog_tpu.runtime import resilience as R
        assert (R.LEFT, R.JOINING) in seq, seq
        assert (R.JOINING, R.HEALTHY) in seq, seq

    def test_graceful_leave_hands_mass_off(self):
        from bluefog_tpu import topology as T
        from bluefog_tpu.metrics import registry as mreg
        from bluefog_tpu.runtime import resilience as R
        from bluefog_tpu.runtime.async_windows import run_async_dsgd
        from bluefog_tpu.runtime.resilience import ResilienceConfig

        reg = mreg.metrics_start()
        try:
            rep = run_async_dsgd(
                T.FullyConnectedGraph(3), {"w": np.zeros(4, np.float32)},
                _quadratic(3), duration_s=1.6, skew=[0.001] * 3,
                name=_uniq("mem_leave"),
                resilience=ResilienceConfig(suspect_after_s=0.2,
                                            dead_after_s=0.6),
                leave_at_s={2: 0.7})
        finally:
            snap = reg.snapshot()
            mreg.metrics_stop()
        assert rep.left_ranks == [2]
        assert rep.dead_ranks == [] and rep.died_mass == 0.0
        # the leaver's mass was HANDED OFF, not written off: the audit
        # over the remaining members reproduces the original 3 exactly
        assert rep.baseline_mass == 3.0
        assert abs(rep.total_mass - 3.0) < 1e-9, rep.total_mass
        assert rep.final_params[2] is None
        # the drain was recorded: flagged-deposit COUNTER (durable —
        # the blackbox ring can evict the event under gossip traffic)
        # plus the LEFT transition carried on the report
        assert any(k.startswith("bf_drain_deposits_total") and v >= 1
                   for k, v in snap.items()), snap
        seq = [(a, b) for (_, a, b) in rep.health_transitions[2]]
        assert (R.HEALTHY, R.LEFT) in seq, seq

    def test_chaos_driven_flapping_member(self):
        # the churn spec drives the same machinery: rank 2 joins at
        # 0.3 s, drains at its step 25, rejoins at 1.4 s — two
        # admissions, one handoff, audit exact throughout
        from bluefog_tpu import chaos, topology as T
        from bluefog_tpu.runtime.async_windows import run_async_dsgd
        from bluefog_tpu.runtime.resilience import ResilienceConfig

        chaos.configure("rank2:join:after_s=0.3; rank2:leave:at_step=25; "
                        "rank2:join:after_s=1.4")
        rep = run_async_dsgd(
            T.FullyConnectedGraph(3), {"w": np.zeros(4, np.float32)},
            _quadratic(3), duration_s=2.2, skew=[0.001] * 3,
            name=_uniq("mem_flap"),
            resilience=ResilienceConfig(suspect_after_s=0.2,
                                        dead_after_s=0.6))
        assert rep.joined_ranks == [2]
        assert 2 not in rep.left_ranks  # it came back
        # two admissions entered two units of mass; the drain between
        # them conserved the first — exact bookkeeping
        assert rep.baseline_mass == 4.0, rep.baseline_mass
        assert abs(rep.total_mass + rep.died_mass - 4.0) < 1e-9
        assert rep.steps_per_rank[2] > 25, rep.steps_per_rank

    @pytest.mark.slow
    def test_churn_soak_replan_connected_every_round(self):
        # seeded churn soak: joins, leaves, and a thread death in one
        # run; every replan the survivors could have used stays
        # strongly connected (verified by the same topology_check the
        # sweep runs) and the audit is exact at the end
        from bluefog_tpu import chaos, topology as T
        from bluefog_tpu.analysis.topology_check import check_topology
        from bluefog_tpu.runtime.async_windows import run_async_dsgd
        from bluefog_tpu.runtime.resilience import ResilienceConfig

        chaos.configure("rank4:join:after_s=0.4; rank3:leave:at_step=40; "
                        "rank2:die:at_step=120; rank3:join:after_s=2.2")
        rep = run_async_dsgd(
            T.FullyConnectedGraph(5), {"w": np.zeros(4, np.float32)},
            _quadratic(5), duration_s=3.5, skew=[0.002] * 5,
            name=_uniq("mem_soak"),
            resilience=ResilienceConfig(suspect_after_s=0.2,
                                        dead_after_s=0.6))
        assert rep.joined_ranks == [3, 4]
        assert rep.dead_ranks == [2]
        # mass: ranks 3 and 4 carry join schedules, so the initial
        # member set is {0, 1, 2} (3 units) and each admission enters
        # one more; rank 3's drain between its join and the end moved
        # mass, never destroyed it — 3 + 2 = 5, exactly
        assert rep.baseline_mass == 5.0, rep.baseline_mass
        assert abs(rep.total_mass + rep.died_mass
                   - rep.baseline_mass) < 1e-9
        # every member-set the run could have produced replans into a
        # connected graph
        base = T.FullyConnectedGraph(5)
        for m_set in ([0, 1, 2, 3], [0, 1, 2, 3, 4], [0, 1, 2, 4],
                      [0, 1, 4], [0, 1, 3, 4]):
            diags = check_topology(T.replan(base, m_set))
            assert not [d for d in diags if d.severity == "error"]


# ---------------------------------------------------------------------------
# 5. multi-process tcp: the acceptance scenario
# ---------------------------------------------------------------------------


_WORKER = os.path.join(_REPO, "tests", "_mp_membership_worker.py")


def _spawn(rank, capacity, bdir, duration, mode):
    return subprocess.Popen(
        [sys.executable, _WORKER, str(rank), str(capacity), bdir,
         str(duration), mode],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=clean_env(), cwd=_REPO)


@pytest.mark.chaos
@pytest.mark.duration_budget(150)  # pre-existing heavyweight; tier-1 coverage load-bearing
def test_mp_fourth_rank_joins_and_one_drains_audit_exact(tmp_path):
    """The acceptance scenario: 3 rank PROCESSES run dsgd over the tcp
    transport; a 4th process attaches mid-run — warm-starting from a
    neighbor's window via window reads, no checkpoint file anywhere —
    and one original rank drains gracefully.  The job finishes with an
    EXACT push-sum mass audit over the final member set {0, 2, 3}: the
    leaver's mass was conserved (handed off in drain-flagged deposits),
    the joiner's fresh p=1 was re-baselined at its admission
    rendezvous."""
    bdir = str(tmp_path)
    procs = [_spawn(r, 4, bdir, 8.0, "elastic") for r in range(3)]
    time.sleep(0.5)  # spawn the joiner EARLY: its jax startup (seconds,
    # more on a loaded host) is the real delay before it announces, and
    # the admission must settle before rank 1's late-scheduled drain
    joiner = _spawn(3, 4, bdir, 8.0, "join")
    outs = []
    try:
        for p in procs + [joiner]:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs + [joiner]:
            p.kill()
        pytest.fail("membership workers timed out:\n"
                    + "\n".join(o or "" for o in outs))
    for r, (p, out) in enumerate(zip(procs + [joiner], outs)):
        assert p.returncode == 0, f"worker {r} failed:\n{out}"
        assert f"MEMBER_MP_OK {r}" in out, out
    # the joiner audited its own warm-start (round-consistent neighbor
    # state, pulled through the window — the worker asserts the
    # blackbox evidence before printing this)
    assert "WARMSTART_OK 3" in outs[3], outs[3]


@pytest.mark.chaos
@pytest.mark.slow
def test_mp_churn_join_plus_kill_in_one_run(tmp_path):
    """Seeded churn: a 4th rank joins a 3-rank tcp job AND rank 2 is
    SIGKILLed mid-run.  The survivors admit the joiner, heal the
    corpse out via replan, and finish with the exact audit over the
    final member set {0, 1, 3} — intentional and unplanned membership
    change composing in one run."""
    bdir = str(tmp_path)
    procs = [_spawn(r, 4, bdir, 12.0, "churn") for r in range(3)]
    time.sleep(0.5)  # join early: it must settle before the 6 s kill
    joiner = _spawn(3, 4, bdir, 12.0, "churn-join")
    outs = []
    try:
        for p in procs + [joiner]:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs + [joiner]:
            p.kill()
        pytest.fail("churn workers timed out:\n"
                    + "\n".join(o or "" for o in outs))
    assert procs[2].returncode == -9, (procs[2].returncode, outs[2])
    for r in (0, 1):
        assert procs[r].returncode == 0, f"worker {r} failed:\n{outs[r]}"
        assert f"MEMBER_MP_OK {r}" in outs[r], outs[r]
    assert joiner.returncode == 0, f"joiner failed:\n{outs[3]}"
    assert "MEMBER_MP_OK 3" in outs[3], outs[3]
