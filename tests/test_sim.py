"""Fleet digital twin: the discrete-event simulator and scenario lab.

Covers, in tier-1 (fast, deterministic, no sockets):

1. event-core semantics: virtual clock, deterministic same-time
   ordering, seed derivation stability;
2. the chaos-grammar link model: one parser with the live injector,
   mirrored trigger semantics, retry-budget abandonment, partitions;
3. simulated-vs-closed-form mixing (the spectral-gap property tests at
   n in {8, 64, 512, 1024}) against the REAL MixingTracker;
4. provenance-name collapse staying O(1) under thousands of simulated
   membership events;
5. FleetSim: exact mass audits through join/leave/kill, plan
   byte-convergence over the real decide_plan, SLO replay naming the
   planted slow host, same-seed byte-identical scenario reports;
6. the scenario table contract and the ``bfsim-tpu --check`` smoke
   (trimmed suite, subprocess) — the full 1024-rank acceptance run is
   slow-marked.
"""

import json
import os
import subprocess
import sys

import pytest

from bluefog_tpu.chaos.spec import ChaosSpecError, parse_spec
from bluefog_tpu.sim.core import EventLoop, derive_seed, rng_for
from bluefog_tpu.sim.fleet import (FleetSim, SimConfig, ST_DEAD,
                                   ST_HEALTHY, ST_SUSPECT)
from bluefog_tpu.sim.mixing import run_sync_mixing
from bluefog_tpu.sim.network import FaultBox, LinkModel
from bluefog_tpu.sim.scenarios import (SCENARIO_NAMES, Scenario,
                                       build_suite, run_scenario,
                                       run_suite)
from bluefog_tpu import topology as T

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# 1. event core
# ---------------------------------------------------------------------------


class TestEventCore:
    def test_same_time_events_pop_in_schedule_order(self):
        loop = EventLoop()
        seen = []
        for k in range(16):
            loop.at(1.0, (lambda v: lambda: seen.append(v))(k))
        loop.at(0.5, lambda: seen.append("early"))
        loop.run()
        assert seen == ["early"] + list(range(16))
        assert loop.now == 1.0

    def test_scheduling_into_the_past_raises(self):
        loop = EventLoop()
        loop.at(1.0, lambda: loop.at(0.5, lambda: None))
        with pytest.raises(ValueError, match="before now"):
            loop.run()

    def test_run_until_advances_clock_to_horizon(self):
        loop = EventLoop()
        loop.at(0.25, lambda: None)
        loop.run(until=2.0)
        assert loop.now == 2.0

    def test_max_events_backstop(self):
        loop = EventLoop()

        def rearm():
            loop.after(0.001, rearm)

        loop.after(0.0, rearm)
        n = loop.run(until=1e9, max_events=100)
        assert n == 100

    def test_derive_seed_stable_and_structural(self):
        assert derive_seed("link", 3, 7) == derive_seed("link", 3, 7)
        assert derive_seed("link", 3, 7) != derive_seed("link", 7, 3)
        # pinned: the cross-machine reproducibility contract (FNV-1a)
        assert derive_seed("x") == derive_seed("x")
        a = rng_for("a", 1).random()
        b = rng_for("a", 1).random()
        assert a == b
        assert rng_for("a", 1).random() != rng_for("a", 2).random()


# ---------------------------------------------------------------------------
# 2. link model on the one chaos grammar
# ---------------------------------------------------------------------------


class TestLinkModel:
    def test_same_parser_as_the_live_injector(self):
        from bluefog_tpu import chaos

        assert chaos.parse_spec is parse_spec
        with pytest.raises(ChaosSpecError):
            FaultBox(0, "server:flood")
        with pytest.raises(ChaosSpecError):
            FaultBox(0, "rank2:die")  # needs at_step

    def test_rate_coin_is_seeded_and_per_rule(self):
        box1 = FaultBox(3, "server:drop:rate=0.5:seed=9", seed=1)
        box2 = FaultBox(3, "server:drop:rate=0.5:seed=9", seed=1)
        seq1 = [box1.fire("server") for _ in range(64)]
        seq2 = [box2.fire("server") for _ in range(64)]
        assert seq1 == seq2
        hits = sum(1 for a in seq1 if a == ("drop",))
        assert 16 <= hits <= 48  # a coin, not a constant

    def test_after_frames_and_every_and_times(self):
        box = FaultBox(0, "server:delay:ms=10:after_frames=3")
        acts = [box.fire("server") for _ in range(6)]
        assert acts == [None, None, ("delay", 0.01), None, None, None]
        box = FaultBox(0, "ack:stall:s=0.5:every=2:times=2")
        acts = [box.fire("ack") for _ in range(8)]
        assert acts == [None, ("stall", 0.5), None, ("stall", 0.5),
                        None, None, None, None]

    def test_drop_costs_a_retransmit_not_mass(self):
        links = LinkModel(latency_s=0.001, rto_s=0.05, budget_s=1.0)
        links.set_host_faults(7, "server:drop:after_frames=1")
        out = links.send(0, 7)
        assert not out.abandoned
        assert out.retries == 1
        assert out.deliver_dt == pytest.approx(0.05 + 0.001)

    def test_budget_exhaustion_abandons(self):
        links = LinkModel(latency_s=0.001, rto_s=0.05, budget_s=0.12)
        links.set_host_faults(7, "server:drop:rate=1.0")
        out = links.send(0, 7)
        assert out.abandoned

    def test_unbounded_budget_refused(self):
        with pytest.raises(ValueError, match="budget"):
            LinkModel(budget_s=0.0)

    def test_partition_cuts_both_ways_and_clears(self):
        links = LinkModel()
        links.set_partition(LinkModel.cut_between([0, 1], [2, 3]))
        assert links.send(0, 2).abandoned
        assert links.send(3, 1).abandoned
        assert not links.send(0, 1).abandoned
        links.set_partition(None)
        assert not links.send(0, 2).abandoned

    def test_one_directed_cut_kills_acks_of_the_reverse_flow(self):
        # severing ONE direction stalls both flows over the link: the
        # forward sender loses payloads, the reverse sender loses acks
        # (live TCP behavior; regression for the ack leg ignoring the
        # reverse pair)
        links = LinkModel()
        links.set_partition({(2, 5)})
        assert links.send(2, 5).abandoned   # payload path severed
        assert links.send(5, 2).abandoned   # ack path severed
        assert not links.send(2, 4).abandoned

    def test_replacing_a_fault_spec_cancels_armed_timers(self):
        # regression: timed rank faults armed from a replaced spec
        # must not still fire (heap entries become no-ops once their
        # box is superseded)
        sim = FleetSim(SimConfig(
            n_ranks=8, seed=0,
            faults={2: "rank2:sigkill:after_s=0.3"}))
        sim.loop.at(0.1, lambda: sim.set_host_faults(
            2, "rank2:sigkill:after_s=1.5"))
        sim.run(1.0)
        assert sim.alive[2]  # the t=0.3 kill was cancelled
        sim.run(2.0)
        assert not sim.alive[2]  # the replacement fired at ~1.6

    def test_trigger_semantics_lockstep_with_live_injector(self):
        """The fidelity contract: FaultBox mirrors Injector.fire's
        trigger evaluation (counters, after_frames==, every%,
        max_fires short-circuit, first-action-wins with continued
        counting).  Drive both with the same spec over the same frame
        sequence and assert IDENTICAL action streams for every
        deterministic trigger (seeded coins draw from differently
        derived streams by design, so prob/rate parity is semantic,
        not bitwise — covered by the rate test above)."""
        from bluefog_tpu.chaos.injector import Injector

        spec = ("server:delay:ms=10:after_frames=3;"
                "server:stall:s=0.5:every=4:times=2;"
                "ack:drop:after_frames=2;"
                "any:truncate:every=7:times=1")
        inj = Injector(spec)
        box = FaultBox(0, spec)
        sites = ["server", "ack", "server", "client"] * 10
        live = [inj.fire(site) for site in sites]
        simd = [box.fire(site) for site in sites]
        assert live == simd
        # and the per-rule frame counters agree
        assert [inj.stats()[i][0] for i in range(4)] == box._counters
        from bluefog_tpu.runtime import resilience as res

        assert ST_HEALTHY == res.HEALTHY
        assert ST_SUSPECT == res.SUSPECT
        assert ST_DEAD == res.DEAD


# ---------------------------------------------------------------------------
# 3. simulated vs closed-form mixing (the spectral-gap property tests)
# ---------------------------------------------------------------------------


class TestMixingFidelity:
    @pytest.mark.parametrize("n", [8, 64, 512, 1024])
    @pytest.mark.parametrize("ctor", [T.RingGraph, T.ExponentialTwoGraph])
    def test_measured_contraction_matches_lambda2(self, n, ctor):
        run = run_sync_mixing(ctor(n), rounds=300, seed=1)
        assert run.rounds_used >= 20
        assert run.measured_geomean == pytest.approx(
            run.predicted, abs=0.01), (n, ctor.__name__, run)

    @pytest.mark.parametrize("n", [8, 64, 512, 1024])
    def test_fully_connected_averages_in_one_step(self, n):
        run = run_sync_mixing(T.FullyConnectedGraph(n), rounds=5, seed=1)
        assert run.final_distance <= 1e-12

    def test_prediction_is_the_trackers(self):
        from bluefog_tpu.analysis.topology_check import spectral_gap

        topo = T.ExponentialTwoGraph(64)
        run = run_sync_mixing(topo, rounds=50, seed=0)
        assert run.predicted == pytest.approx(
            1.0 - spectral_gap(topo.weights))


# ---------------------------------------------------------------------------
# 4. provenance collapse under thousands of membership events
# ---------------------------------------------------------------------------


class TestProvenanceCollapse:
    def test_name_stays_o1_over_thousands_of_events(self):
        import re

        rng = rng_for("churn", 0)
        n = 128
        topo = T.ExponentialTwoGraph(n)
        members = set(range(n))
        suffix_re = re.compile(r"\+(heal|replan|ctl)\(")
        max_first, max_last = 0, 0
        for i in range(3000):
            op = i % 3
            if op == 0 and len(members) > n // 2:
                dead = rng.choice(sorted(members))
                members.discard(dead)
                topo = T.heal(topo, {dead})
            elif op == 1 and len(members) > n // 2:
                gone = rng.choice(sorted(members))
                members.discard(gone)
                topo = T.replan(topo, sorted(members))
            else:
                missing = sorted(set(range(n)) - members)
                if missing:
                    members.add(missing[0])
                topo = T.replan_penalized(
                    topo, sorted(members),
                    slow=sorted(members)[:2], densify=i % 3)
            # exactly ONE collapsed provenance suffix, ever (a chain
            # would accrete one "+heal(...)" per event)
            assert len(suffix_re.findall(topo.name)) == 1, topo.name
            if i < 1000:
                max_first = max(max_first, len(topo.name))
            elif i >= 2000:
                max_last = max(max_last, len(topo.name))
        # O(1) in the EVENT count: the name after 3000 events is no
        # longer than after 1000 (its length tracks the bounded member
        # set — a heal suffix lists the inactive ranks — never the
        # event history)
        assert max_last <= max_first + 32, (max_first, max_last)
        assert max_first < 16 + 6 * n

    def test_sim_churn_keeps_name_collapsed(self):
        cfg = SimConfig(n_ranks=24, capacity=32, seed=2)
        sim = FleetSim(cfg)
        for k in range(8):
            t = 0.15 + 0.1 * k
            if k % 2 == 0:
                sim.loop.at(t, (lambda r: lambda: sim.kill(r))(k))
            else:
                sim.loop.at(
                    t, (lambda r: lambda: sim.request_leave(r))(k))
            sim.loop.at(t + 0.4,
                        (lambda r: lambda: sim.join(24 + r % 8))(k))
        sim.run(2.0)
        assert sim.max_name_len < 200
        assert sim.connectivity_ok


# ---------------------------------------------------------------------------
# 5. FleetSim
# ---------------------------------------------------------------------------


class TestFleetSim:
    def test_audit_exact_through_churn(self):
        sim = FleetSim(SimConfig(n_ranks=24, capacity=32, seed=7))
        sim.loop.at(0.3, lambda: sim.request_leave(5))
        sim.loop.at(0.5, lambda: sim.kill(9))
        sim.loop.at(0.7, lambda: sim.join(24))
        sim.loop.at(0.7, lambda: sim.join(25))
        sim.run(2.5)
        xerr, perr = sim.audit()
        assert abs(xerr) < 1e-9 * sim.admissions
        assert abs(perr) < 1e-9 * sim.admissions
        assert not sim.alive[5] and not sim.alive[9]
        assert sim.alive[24] and sim.alive[25]
        assert 9 in sim.topo.inactive  # healed corpse
        # the corpse's evidence no longer votes anywhere
        for r in sim.members():
            assert 9 not in sim.ctl[r].evidence(10_000).lag_s

    def test_graceful_leave_conserves_mass_kill_writes_off(self):
        sim = FleetSim(SimConfig(n_ranks=8, seed=1))
        sim.loop.at(0.3, lambda: sim.request_leave(2))
        sim.run(1.0)
        live_p = sum(sim.p[r] + sim.mp[r] for r in sim.members())
        # the leaver handed its whole (x, p) over: nothing retained,
        # live + in-flight mass == n (the drain-conserves-mass contract)
        assert sim.p[2] + sim.mp[2] == 0.0
        assert live_p + sim._inflight_p == pytest.approx(8.0, abs=1e-9)
        sim2 = FleetSim(SimConfig(n_ranks=8, seed=1))
        sim2.loop.at(0.3, lambda: sim2.kill(2))
        sim2.run(1.0)
        live_p2 = sum(sim2.p[r] + sim2.mp[r] for r in sim2.members())
        dead_p = sim2.p[2] + sim2.mp[2]
        assert live_p2 + dead_p + sim2._inflight_p == pytest.approx(
            8.0, abs=1e-9)
        assert dead_p > 0  # written off with the corpse, not conserved

    def test_leaver_forward_chain_survives_heir_leaving(self):
        # regression: mass in flight toward a leaver whose HEIR has
        # itself since drained must walk the forward chain to a live
        # rank, not strand in a dead slot (live mass would silently
        # shrink while the all-slots audit still balanced)
        sim = FleetSim(SimConfig(
            n_ranks=8, seed=3,
            faults={4: "server:delay:ms=120:rate=1.0"}))
        # rank 0 is the heir pick (lowest live); drain it right after
        sim.loop.at(0.30, lambda: sim.request_leave(4))
        sim.loop.at(0.45, lambda: sim.request_leave(0))
        sim.run(2.5)
        live_p = sum(sim.p[r] + sim.mp[r] for r in sim.members())
        dead_p = sum(sim.p[r] + sim.mp[r]
                     for r in range(8) if not sim.alive[r])
        assert dead_p == pytest.approx(0.0, abs=1e-12)
        assert live_p + sim._inflight_p == pytest.approx(8.0, abs=1e-9)

    def test_failed_drain_rejoin_keeps_the_ledger_exact(self):
        # regression: a partitioned leaver whose handoff sends were all
        # ABANDONED retains its (x, p); rejoining it must ACCUMULATE
        # the warm-start on top of the residual, not overwrite it (the
        # overwrite destroyed ledgered mass and broke the exact audit)
        sim = FleetSim(SimConfig(n_ranks=8, seed=6))
        cut = LinkModel.cut_between([3], [r for r in range(8) if r != 3])
        sim.loop.at(0.30, lambda: sim.set_partition(cut))
        sim.loop.at(0.40, lambda: sim.request_leave(3))
        sim.loop.at(0.80, lambda: sim.set_partition(None))
        sim.loop.at(0.90, lambda: sim.join(3))
        sim.run(2.5)
        assert sim.alive[3]
        xerr, perr = sim.audit()
        assert abs(xerr) < 1e-9 * sim.admissions, xerr
        assert abs(perr) < 1e-9 * sim.admissions, perr

    def test_mid_run_timed_rank_fault_is_armed(self):
        # regression: a rank fault with after_s= installed mid-run via
        # set_host_faults was silently inert (timed rules were armed
        # only at construction); now it arms relative to install time
        sim = FleetSim(SimConfig(n_ranks=8, seed=0))
        sim.loop.at(0.05, lambda: sim.set_host_faults(
            2, "rank2:sigkill:after_s=0.1"))
        sim.run(1.0)
        assert not sim.alive[2]
        assert sim.deaths == 1

    def test_misplaced_rank_rule_refused(self):
        # a rank5 rule under host 3's entry would never be consulted
        with pytest.raises(ValueError, match="own rank's entry"):
            FleetSim(SimConfig(n_ranks=8, seed=0,
                               faults={3: "rank5:die:at_step=4"}))
        sim = FleetSim(SimConfig(n_ranks=8, seed=0))
        with pytest.raises(ValueError, match="own rank's entry"):
            sim.set_host_faults(3, "rank5:leave:at_step=4")

    def test_read_path_fault_sites_refused(self):
        # the sim models the deposit path; a read/sub rule would sit
        # inert and make a scenario's predicates vacuous — refused
        sim = FleetSim(SimConfig(n_ranks=8, seed=0))
        with pytest.raises(ValueError, match="read-path"):
            sim.set_host_faults(3, "read:stall:s=2:prob=0.5")
        with pytest.raises(ValueError, match="read-path"):
            sim.set_host_faults(3, "sub:drop:every=5")
        sim.set_host_faults(3, "any:delay:ms=5:every=3")  # fine

    def test_consensus_converges_to_fixed_point(self):
        sim = FleetSim(SimConfig(n_ranks=32, seed=3))
        sim.run(1.0)
        t, med, mx = sim.spread_history[-1]
        assert mx < 1e-9

    def test_plan_byte_convergence_over_all_ranks(self):
        # decide on EVERY rank (decide_sample >= n) and assert literal
        # byte equality of the real decide_plan outputs each epoch
        sim = FleetSim(SimConfig(
            n_ranks=16, seed=5, control=True, decide_sample=16,
            faults={3: "server:delay:ms=120:rate=1.0"}))
        sim.run(4.0)
        assert sim.plan_divergences == 0
        assert sim.plans_converged()
        assert sim.plan.version >= 1
        assert 3 in sim.plan.slow  # the real decide_plan convicted it
        blobs = {sim.ctl[r].plan.to_bytes() for r in sim.members()}
        assert len(blobs) == 1

    def test_slo_replay_names_the_slow_host(self):
        sim = FleetSim(SimConfig(
            n_ranks=16, seed=5,
            faults={3: "server:delay:ms=120:rate=1.0"}))
        sim.run(2.0)
        engine = sim.replay_slos()
        warns = [tr for tr in engine.transitions
                 if tr.slo == "straggler" and tr.to >= 1]
        assert warns and warns[0].rank == 3

    def test_lossy_link_reconnect_evidence(self):
        sim = FleetSim(SimConfig(
            n_ranks=8, seed=2,
            faults={3: "server:drop:rate=0.3:seed=5"}))
        sim.run(1.0)
        # senders to host 3 saw retransmits; the controller's evidence
        # carries them as reconnect deltas (the lossy-link channel)
        total = sum(sim._retx_total[r].get(3, 0) for r in range(8))
        assert total > 0

    def test_flash_join_does_not_false_alarm_densify(self):
        # a membership boundary's cross-set contraction ratio must not
        # read as a mixing failure: after a big join the plan may
        # retune cadence, but the densify ladder stays at 0 (the
        # MixingTracker.reset_measurement contract)
        sim = FleetSim(SimConfig(
            n_ranks=32, capacity=32, seed=4, control=True,
            initial_members=list(range(16))))
        sim.loop.at(0.5, lambda: [sim.join(r) for r in range(16, 32)])
        sim.run(2.0)
        assert len(sim.members()) == 32
        assert sim.plan.densify == 0, sim.plan

    def test_partition_climbs_the_densify_ladder(self):
        # a PARTITION is a genuine sustained mixing stall: the real
        # decide_plan's densify ladder must climb (at n=16 the top
        # rung's fully-connected rebuild is harmless)
        sim = FleetSim(SimConfig(n_ranks=16, seed=11, control=True))
        cut = LinkModel.cut_between(range(8), range(8, 16))
        sim.loop.at(0.5, lambda: sim.set_partition(cut))
        sim.run(2.5)
        assert sim.plan.densify >= 1, sim.plan

    def test_partition_detect_and_reconverge(self):
        sim = FleetSim(SimConfig(n_ranks=16, seed=11, control=True))
        cut = LinkModel.cut_between(range(8), range(8, 16))
        sim.loop.at(0.5, lambda: sim.set_partition(cut))
        sim.loop.at(1.5, lambda: sim.set_partition(None))
        sim.run(6.0)
        assert max(abs(v) for v in sim.audit()) < 1e-9 * 16
        assert sim.plans_converged()
        # reconverged after the merge
        assert sim.spread_history[-1][2] < 1e-5
        # the plan reacted while the halves were cut
        assert sim.plan_changes >= 1

    def test_same_seed_same_bytes(self):
        def one():
            sim = FleetSim(SimConfig(
                n_ranks=12, seed=9,
                faults={5: "server:delay:ms=60:rate=0.5"}))
            sim.loop.at(0.4, lambda: sim.kill(2))
            sim.run(1.5)
            return (tuple(sim.spread_history), sim.audit(),
                    sim.plan.to_bytes(), tuple(sim.x), tuple(sim.p))

        assert one() == one()


# ---------------------------------------------------------------------------
# 6. scenario table + CLI
# ---------------------------------------------------------------------------


class TestScenarioTable:
    def test_every_suite_entry_is_checked_and_bounded(self):
        for sc in build_suite(n=64):
            assert sc.accept, sc.name
            assert sc.horizon_s > 0, sc.name
            for pname, params in sc.accept:
                assert isinstance(params, dict)

    def test_scenario_without_accept_refused(self):
        with pytest.raises(ValueError, match="accept"):
            Scenario(name="x", kind="fleet", n_ranks=8,
                     horizon_s=1.0, accept=())

    def test_scenario_without_horizon_refused(self):
        with pytest.raises(ValueError, match="horizon"):
            Scenario(name="x", kind="fleet", n_ranks=8,
                     horizon_s=0.0,
                     accept=(("audit_exact", {}),))

    def test_unknown_predicate_refused(self):
        with pytest.raises(ValueError, match="unknown predicate"):
            Scenario(name="x", kind="fleet", n_ranks=8, horizon_s=1.0,
                     accept=(("no_such_predicate", {}),))

    def test_unknown_scenario_name_refused(self):
        with pytest.raises(ValueError, match="unknown scenario"):
            build_suite(n=64, names=["nope"])

    def test_scenario_report_is_deterministic(self):
        sc = build_suite(n=16, names=["diurnal_autoscale"])[0]
        a = json.dumps(run_scenario(sc), sort_keys=True)
        b = json.dumps(run_scenario(sc), sort_keys=True)
        assert a == b

    def test_failed_predicate_fails_the_suite(self):
        sc = Scenario(
            name="impossible", kind="fleet", n_ranks=8,
            horizon_s=0.2,
            accept=(("converged", {"eps": 1e-300, "metric": "max"}),))
        rep = run_scenario(sc)
        assert not rep["ok"]
        assert not rep["predicates"]["converged"]["ok"]


class TestSimCli:
    def test_check_runs_trimmed_suite(self, tmp_path):
        """The tier-1 smoke (satellite): the FULL scenario suite at a
        48-rank trim, as a subprocess, exit 0, deterministic report."""
        rep = tmp_path / "sim_report.json"
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-m", "bluefog_tpu.sim", "--check",
             "--ranks", "48", "--report", str(rep)],
            capture_output=True, text=True, env=env, cwd=_REPO,
            timeout=300)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        doc = json.loads(rep.read_text())
        assert doc["ok"] is True
        names = [r["name"] for r in doc["scenarios"]]
        assert sorted(names) == sorted(SCENARIO_NAMES)
        for r in doc["scenarios"]:
            assert r["ok"] is True, r["name"]
        # the report passes the bffleet-tpu BENCH gate
        from bluefog_tpu.fleet.dash import bench_gate_failures

        assert bench_gate_failures(doc) == []

    def test_report_bytes_are_seed_deterministic(self):
        a = json.dumps(run_suite(n=16, seed=4,
                                 names=["diurnal_autoscale"]),
                       sort_keys=True)
        b = json.dumps(run_suite(n=16, seed=4,
                                 names=["diurnal_autoscale"]),
                       sort_keys=True)
        assert a == b

    def test_usage_errors_exit_2(self):
        from bluefog_tpu.sim import cli

        assert cli.main(["--check", "--ranks", "4"]) == 2
        assert cli.main(["no_such_scenario"]) == 2
        assert cli.main([]) == 2

    def test_list_exits_0(self, capsys):
        from bluefog_tpu.sim import cli

        assert cli.main(["--list"]) == 0
        out = capsys.readouterr().out
        for name in SCENARIO_NAMES:
            assert name in out

    def test_failed_predicate_exits_3(self, monkeypatch):
        from bluefog_tpu.sim import cli

        monkeypatch.setattr(
            cli, "run_suite",
            lambda **kw: {"ok": False, "scenarios": [
                {"name": "x", "kind": "fleet", "n_ranks": 8,
                 "ok": False, "predicates": {
                     "p": {"ok": False}}}]})
        assert cli.main(["--check"]) == 3


@pytest.mark.slow
class TestFullScaleSuite:
    def test_full_1024_rank_suite(self, tmp_path):
        """The acceptance run: the whole suite at 1024 simulated ranks
        (what the committed BENCH_sim.json records)."""
        rep = tmp_path / "sim1024.json"
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-m", "bluefog_tpu.sim", "--check",
             "--ranks", "1024", "--report", str(rep)],
            capture_output=True, text=True, env=env, cwd=_REPO,
            timeout=1800)
        assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr
        doc = json.loads(rep.read_text())
        assert doc["ok"] is True and doc["n_ranks"] == 1024
