"""Peer-fault tolerance: chaos injection, reconnect/replay, self-healing.

Covers the resilience tentpole end to end:

1. primitives — :class:`Backoff` (seeded jitter determinism, mandatory
   budget), the HEALTHY→SUSPECT→DEAD→REJOINED state machine
   (:class:`PeerHealth` / :class:`HealthBoard`), and the
   :func:`topology.heal` weight re-normalization;
2. the chaos injector — spec grammar, deterministic counters/seeds, the
   ``bfchaos-tpu`` CLI;
3. the wire — DepositStream reconnect with bounded backoff, idempotent
   replay of unacked batches (including the applied-but-UNACKED ack-drop
   ambiguity and a hand-crafted duplicate frame: server-side dedup,
   zero double-applies), heartbeat liveness, DEAD on budget exhaustion;
4. self-healing gossip — kill-one-of-three mid-dsgd with the EXACT mass
   audit over the surviving set, SIGSTOP-shaped stall with DEAD→REJOINED
   re-admission and exact global mass, and the same for push-sum;
5. the satellites — FileBarrier exclusion set + rank-number timeouts,
   ``run_supervised`` restart backoff, and the
   AsyncWindow/PipelinedRemoteWindow signature-parity tripwire.

Fault tests carry the ``chaos`` marker (slow multi-process variants add
``slow``); everything is deterministic — counters and seeded RNGs, no
luck involved.
"""

import inspect
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from tests._util import REPO as _REPO, clean_env, uniq as _uniq


@pytest.fixture(autouse=True)
def _chaos_isolated():
    """No chaos spec leaks between tests (the injector is process-global
    and env-lazy, like the metrics/blackbox registries)."""
    from bluefog_tpu import chaos

    chaos.reset()
    yield
    chaos.reset()


# ---------------------------------------------------------------------------
# 1. primitives
# ---------------------------------------------------------------------------


class TestBackoff:
    def test_seeded_schedule_is_reproducible(self):
        from bluefog_tpu.runtime.resilience import Backoff

        a = list(Backoff(base_s=0.05, cap_s=1.0, budget=6, seed=7))
        b = list(Backoff(base_s=0.05, cap_s=1.0, budget=6, seed=7))
        assert a == b and len(a) == 6
        # exponential shape under the jitter envelope, capped
        for k, d in enumerate(a):
            nominal = min(0.05 * 2 ** k, 1.0)
            assert 0.5 * nominal <= d <= 1.5 * nominal

    def test_budget_exhaustion_raises(self):
        from bluefog_tpu.runtime.resilience import Backoff, BudgetExhausted

        bo = Backoff(budget=2, jitter=0.0)
        bo.next_delay()
        bo.next_delay()
        with pytest.raises(BudgetExhausted):
            bo.next_delay()

    def test_bound_is_mandatory(self):
        # an unbounded Backoff is exactly what BF-RES001 exists to
        # reject; the constructor refuses to build one
        from bluefog_tpu.runtime.resilience import Backoff

        with pytest.raises(ValueError):
            Backoff(budget=None, deadline_s=None)

    def test_deadline_bound(self):
        from bluefog_tpu.runtime.resilience import Backoff, BudgetExhausted

        bo = Backoff(base_s=0.01, budget=None, deadline_s=0.0,
                     jitter=0.0)
        bo.next_delay()  # first draw starts the clock
        time.sleep(0.01)
        with pytest.raises(BudgetExhausted):
            bo.next_delay()

    def test_max_total_quotes_detection_deadline(self):
        from bluefog_tpu.runtime.resilience import Backoff

        bo = Backoff(base_s=0.1, cap_s=0.4, factor=2.0, jitter=0.5,
                     budget=4)
        # 0.1 + 0.2 + 0.4 + 0.4, worst-case jitter 1.5x
        assert abs(bo.max_total_s() - 1.1 * 1.5) < 1e-9


class TestHealthStateMachine:
    def _clocked(self):
        t = [0.0]
        from bluefog_tpu.runtime.resilience import PeerHealth

        h = PeerHealth("peer", suspect_after_s=1.0, dead_after_s=3.0,
                       clock=lambda: t[0])
        return h, t

    def test_silence_promotes_suspect_then_dead(self):
        from bluefog_tpu.runtime import resilience as R

        h, t = self._clocked()
        assert h.poll() == R.HEALTHY
        t[0] = 1.5
        assert h.poll() == R.SUSPECT
        t[0] = 3.5
        assert h.poll() == R.DEAD
        # DEAD is sticky under further silence
        t[0] = 10.0
        assert h.poll() == R.DEAD

    def test_suspect_recovers_and_dead_rejoins(self):
        from bluefog_tpu.runtime import resilience as R

        h, t = self._clocked()
        t[0] = 1.5
        h.poll()
        assert h.note_ok() == R.HEALTHY  # SUSPECT -> HEALTHY directly
        t[0] = 10.0
        h.poll()
        assert h.state == R.DEAD
        assert h.note_ok() == R.REJOINED  # evidence of life
        # REJOINED is sticky until the gossip loop re-admits at a round
        # boundary — poll() must not silently flip it either way
        t[0] = 20.0
        assert h.poll() == R.REJOINED
        h.admit()
        assert h.state == R.HEALTHY
        # the full cycle is on the transition log
        seq = [(a, b) for (_, a, b) in h.transitions]
        assert (R.DEAD, R.REJOINED) in seq and (R.REJOINED, R.HEALTHY) in seq

    def test_hard_failure_promotes_suspect(self):
        from bluefog_tpu.runtime import resilience as R

        h, _ = self._clocked()
        assert h.note_failure() == R.SUSPECT  # an RST beats silence

    def test_health_board_detects_silent_rank(self):
        from bluefog_tpu.runtime import resilience as R

        t = [0.0]
        board = R.HealthBoard(3, suspect_after_s=0.5, dead_after_s=1.0,
                              clock=lambda: t[0])
        for r in range(3):
            board.beat(r)
        t[0] = 1.5
        board.beat(0)
        board.beat(1)  # rank 2 is silent
        assert board.dead_ranks() == {2}
        board.beat(2)  # it speaks again
        assert board.state(2) == R.REJOINED
        assert board.dead_ranks() == set()  # REJOINED is not DEAD
        board.admit(2)
        assert board.state(2) == R.HEALTHY


class TestHeal:
    def test_renormalizes_over_survivors(self):
        from bluefog_tpu import topology as T

        topo = T.FullyConnectedGraph(4)
        healed = T.heal(topo, [3])
        w = healed.weights
        # row-stochastic (Topology.__post_init__ enforces it; assert
        # anyway — it IS the invariant)
        np.testing.assert_allclose(w.sum(axis=1), 1.0, atol=1e-12)
        # survivors no longer reference the corpse, proportions kept
        assert (w[:3, 3] == 0).all()
        np.testing.assert_allclose(w[:3, :3], 1.0 / 3.0)
        # dead row is an inert self-loop; rank indices stay stable
        assert w[3, 3] == 1.0 and (w[3, :3] == 0).all()
        assert healed.size == topo.size

    def test_relative_weights_preserved(self):
        from bluefog_tpu import topology as T

        w = np.array([[0.5, 0.2, 0.3],
                      [0.1, 0.6, 0.3],
                      [0.25, 0.25, 0.5]])
        healed = T.heal(T.Topology(weights=w, name="t"), [2])
        # row 0 drops col 2 and rescales by 0.7: 5/7, 2/7
        np.testing.assert_allclose(healed.weights[0, :2],
                                   [0.5 / 0.7, 0.2 / 0.7])

    def test_isolated_survivor_becomes_self_loop(self):
        from bluefog_tpu import topology as T

        # star: leaves only talk to the center; kill the center
        topo = T.StarGraph(4, center_rank=0)
        healed = T.heal(topo, [0])
        for r in range(1, 4):
            assert healed.weights[r, r] == 1.0

    def test_rejoin_is_heal_with_smaller_dead_set(self):
        from bluefog_tpu import topology as T

        topo = T.FullyConnectedGraph(3)
        assert T.IsTopologyEquivalent(T.heal(topo, []), topo)
        # re-admission: healing with the rejoined rank removed restores
        # the original matrix
        assert T.IsTopologyEquivalent(
            T.heal(topo, set()), T.heal(topo, {1} - {1}))

    def test_errors(self):
        from bluefog_tpu import topology as T

        topo = T.RingGraph(3)
        with pytest.raises(ValueError):
            T.heal(topo, [5])
        with pytest.raises(ValueError):
            T.heal(topo, [0, 1, 2])


# ---------------------------------------------------------------------------
# 2. chaos injector
# ---------------------------------------------------------------------------


class TestChaosSpec:
    def test_grammar_round_trip(self):
        from bluefog_tpu.chaos import parse_spec

        rules = parse_spec("server:drop:after_frames=3; "
                           "ack:delay:ms=20:prob=0.5:seed=9; "
                           "rank2:sigkill:at_step=8; "
                           "rank1:sigstop:after_s=0.5:for_s=1.0")
        assert [r.fault for r in rules] == ["drop", "delay", "sigkill",
                                            "sigstop"]
        assert rules[0].after_frames == 3 and rules[2].rank == 2
        assert rules[3].for_s == 1.0

    @pytest.mark.parametrize("bad", [
        "", "server", "server:frobnicate", "rank:die:at_step=1",
        "rankX:die:at_step=1", "server:drop:nonsense=1",
        "server:delay:prob=2.0", "rank1:die",        # die needs at_step
        "rank1:sigkill",                             # needs a trigger
        "bogus:drop:after_frames=1",
    ])
    def test_bad_specs_fail_fast(self, bad):
        from bluefog_tpu.chaos import ChaosSpecError, parse_spec

        with pytest.raises(ChaosSpecError):
            parse_spec(bad)

    def test_counter_trigger_is_deterministic_and_one_shot(self):
        from bluefog_tpu.chaos import Injector

        inj = Injector("server:drop:after_frames=3")
        hits = [inj.fire("server") for _ in range(6)]
        assert hits == [None, None, ("drop",), None, None, None]
        # sites are independent
        assert inj.fire("client") is None

    def test_prob_trigger_is_seeded(self):
        from bluefog_tpu.chaos import Injector

        spec = "server:delay:ms=5:prob=0.3:seed=42:times=0"
        inj1, inj2 = Injector(spec), Injector(spec)
        seq1 = [inj1.fire("server") is not None for _ in range(50)]
        seq2 = [inj2.fire("server") is not None for _ in range(50)]
        assert seq1 == seq2  # same seed, same traffic -> same faults
        assert any(seq1) and not all(seq1)

    def test_rate_trigger_is_a_seeded_lossy_link(self):
        """``rate=p``: the lossy-link spelling — an independent seeded
        coin per frame that loses ~p of them, unlimited firings by
        default, deterministic per seed."""
        from bluefog_tpu.chaos import Injector, parse_spec

        (rule,) = parse_spec("server:drop:rate=0.25:seed=7")
        assert rule.rate == 0.25 and rule.max_fires() == 0  # unlimited
        spec = "server:drop:rate=0.25:seed=7"
        inj1, inj2 = Injector(spec), Injector(spec)
        seq1 = [inj1.fire("server") is not None for _ in range(400)]
        seq2 = [inj2.fire("server") is not None for _ in range(400)]
        assert seq1 == seq2  # deterministic per seed
        losses = sum(seq1)
        assert 60 <= losses <= 140, losses  # ~25% of 400 frames

    @pytest.mark.parametrize("bad", [
        "server:drop:rate=1.5",          # out of [0, 1]
        "server:drop:rate=0.1:prob=0.1",  # one coin per rule
        "rank1:die:at_step=3:rate=0.1",  # socket-site trigger only
    ])
    def test_rate_validation(self, bad):
        from bluefog_tpu.chaos import ChaosSpecError, parse_spec

        with pytest.raises(ChaosSpecError):
            parse_spec(bad)

    def test_env_lazy_and_reset(self, monkeypatch):
        from bluefog_tpu import chaos

        monkeypatch.setenv("BLUEFOG_TPU_CHAOS",
                           "server:drop:after_frames=1")
        chaos.reset()
        assert chaos.enabled()
        assert chaos.fire("server") == ("drop",)
        chaos.configure(None)
        assert not chaos.enabled()
        chaos.reset()

    def test_die_rule_raises_chaoskill(self):
        from bluefog_tpu import chaos

        chaos.configure("rank1:die:at_step=5")
        chaos.check_step(1, 4)  # not yet
        chaos.check_step(0, 99)  # wrong rank
        with pytest.raises(chaos.ChaosKill):
            chaos.check_step(1, 5)
        # one-shot: the corpse does not die twice
        chaos.check_step(1, 6)

    @pytest.mark.duration_budget(60)  # pre-existing heavyweight; tier-1 coverage load-bearing
    def test_cli_explain_grammar_and_env_passthrough(self):
        cli = [sys.executable, "-m", "bluefog_tpu.chaos"]
        env = clean_env()
        out = subprocess.run(
            cli + ["--spec", "server:drop:after_frames=2", "--explain"],
            capture_output=True, text=True, env=env, cwd=_REPO)
        assert out.returncode == 0 and "drop" in out.stdout
        assert subprocess.run(cli + ["--grammar"], capture_output=True,
                              env=env, cwd=_REPO).returncode == 0
        bad = subprocess.run(cli + ["--spec", "nope", "--explain"],
                             capture_output=True, text=True, env=env,
                             cwd=_REPO)
        assert bad.returncode == 2 and "bad spec" in bad.stderr
        run = subprocess.run(
            cli + ["--spec", "server:stall:s=1", "--",
                   sys.executable, "-c",
                   "import os; print(os.environ['BLUEFOG_TPU_CHAOS'])"],
            capture_output=True, text=True, env=env, cwd=_REPO)
        assert run.returncode == 0
        assert "server:stall:s=1" in run.stdout


# ---------------------------------------------------------------------------
# satellites: barrier exclusion, signature parity, supervisor backoff
# ---------------------------------------------------------------------------


class TestFileBarrier:
    def test_exclusion_set_skips_dead_ranks(self, tmp_path):
        from bluefog_tpu.runtime.async_windows import FileBarrier

        b = FileBarrier(str(tmp_path), 3, rank=0)
        open(os.path.join(str(tmp_path), "stage.1"), "w").close()
        b.exclude.add(2)  # rank 2 is a corpse: do not wait 120 s for it
        t0 = time.perf_counter()
        b.wait("stage", timeout_s=5.0)
        assert time.perf_counter() - t0 < 2.0

    def test_timeout_names_rank_numbers_and_records_blackbox(self, tmp_path):
        from bluefog_tpu.blackbox import recorder as bb
        from bluefog_tpu.runtime.async_windows import FileBarrier

        b = FileBarrier(str(tmp_path), 4, rank=0)
        b.exclude.add(3)
        with pytest.raises(TimeoutError) as ei:
            b.wait("audit", timeout_s=0.2)
        msg = str(ei.value)
        # rank NUMBERS, not the file paths the old message dumped
        assert "missing rank(s) [1, 2]" in msg, msg
        assert str(tmp_path) in msg  # the dir is still named once
        rec = bb.get()
        assert rec is not None
        evs = [e for e in rec.events() if e["kind"] == "barrier_timeout"]
        assert evs and evs[-1]["missing_ranks"] == [1, 2]
        assert evs[-1]["stage"] == "audit"


class TestSignatureParity:
    """Satellite: the one-loop-body-on-all-transports invariant —
    ``AsyncWindow``'s no-op aliases must track the pipelined transport's
    signatures exactly, or a loop written against one silently stops
    running on the other."""

    @staticmethod
    def _params(fn):
        return [(p.name, p.kind, p.default)
                for p in inspect.signature(fn).parameters.values()]

    def test_deposit_async_parity(self):
        from bluefog_tpu.runtime.async_windows import (AsyncWindow,
                                                       _RemoteHandle)
        from bluefog_tpu.runtime.window_server import PipelinedRemoteWindow

        want = self._params(PipelinedRemoteWindow.deposit_async)
        assert self._params(AsyncWindow.deposit_async) == want
        assert self._params(_RemoteHandle.deposit_async) == want

    def test_flush_parity_including_timeout_kwarg(self):
        from bluefog_tpu.runtime.async_windows import (AsyncWindow,
                                                       _RemoteHandle)
        from bluefog_tpu.runtime.window_server import PipelinedRemoteWindow

        want = self._params(PipelinedRemoteWindow.flush)
        assert self._params(AsyncWindow.flush) == want
        assert self._params(_RemoteHandle.flush) == want
        sig = inspect.signature(AsyncWindow.flush)
        assert sig.parameters["timeout_s"].default is None


class TestSupervisorBackoff:
    SCRIPT = """\
import os, sys
marker = sys.argv[1]
if not os.path.exists(marker):
    open(marker, "w").close()
    sys.exit(7)
sys.exit(0)
"""

    def test_restart_waits_with_backoff(self, tmp_path):
        from bluefog_tpu.utils.failure import run_supervised

        script = tmp_path / "crash_once.py"
        script.write_text(self.SCRIPT)
        marker = str(tmp_path / "crashed")
        t0 = time.perf_counter()
        rc = run_supervised(
            [sys.executable, str(script), marker], max_restarts=2,
            restart_backoff_s=0.4, restart_jitter=0.0)
        elapsed = time.perf_counter() - t0
        assert rc == 0
        assert elapsed >= 0.4, elapsed  # the one restart waited

    def test_zero_backoff_restores_immediate_restart(self, tmp_path):
        from bluefog_tpu.utils.failure import run_supervised

        script = tmp_path / "crash_once.py"
        script.write_text(self.SCRIPT)
        marker = str(tmp_path / "crashed")
        rc = run_supervised(
            [sys.executable, str(script), marker], max_restarts=2,
            restart_backoff_s=0.0)
        assert rc == 0


# ---------------------------------------------------------------------------
# 3. the wire: reconnect, replay, dedup, heartbeats
# ---------------------------------------------------------------------------


def _serve(name, n_elems=8):
    from bluefog_tpu.runtime.async_windows import AsyncWindow
    from bluefog_tpu.runtime.window_server import WindowServer

    win = AsyncWindow(name, n_slots=1, n_elems=n_elems, dtype=np.float64)
    srv = WindowServer()
    _, port = srv.start("127.0.0.1")
    return win, srv, port


_FAST = dict(base_s=0.02, cap_s=0.2, budget=6, seed=0)


@pytest.mark.chaos
class TestStreamReconnectReplay:
    def _run_deposits(self, name, port, rounds=20, **stream_kw):
        from bluefog_tpu.runtime.window_server import DepositStream

        st = DepositStream(("127.0.0.1", port), reconnect=_FAST,
                           **stream_kw)
        total = np.zeros(8)
        try:
            for i in range(rounds):
                v = np.full(8, float(i + 1))
                st.deposit_async(name.encode(), 0, v, accumulate=True)
                total += v
                st.flush(timeout_s=30)
        finally:
            st.close()
        return st, total

    def test_transient_drop_reconnects_and_replays_exactly_once(self):
        from bluefog_tpu import chaos
        from bluefog_tpu.metrics import registry as mreg
        from bluefog_tpu.runtime import resilience as R

        name = _uniq("res_drop")
        win, srv, port = _serve(name)
        reg = mreg.metrics_start()
        chaos.configure("server:drop:after_frames=6")
        try:
            st, total = self._run_deposits(name, port)
            got, fresh = win.read(0, consume=False)
            # EXACT value and EXACT apply count: reconnect replayed the
            # torn batch once, never twice
            assert np.array_equal(got, total)
            assert fresh == 20
            snap = reg.snapshot()
            assert any("bf_reconnects_total" in k and v >= 1
                       for k, v in snap.items()), snap
            # health dipped to SUSPECT during the outage and recovered
            seq = [(a, b) for (_, a, b) in st.health.transitions]
            assert (R.HEALTHY, R.SUSPECT) in seq
            assert st.health.state == R.HEALTHY
        finally:
            mreg.metrics_stop()
            srv.stop()
            win.free()

    def test_applied_but_unacked_batch_is_not_double_applied(self):
        # the ack-drop ambiguity: the server APPLIES a batch, then the
        # connection dies before the ack leaves.  The STREAM_ATTACH
        # reply (applied high-water mark) retires it client-side; the
        # seq dedup would catch it server-side.  Either way: exactly
        # once.
        from bluefog_tpu import chaos

        name = _uniq("res_ackdrop")
        win, srv, port = _serve(name)
        chaos.configure("ack:drop:after_frames=3")
        try:
            _, total = self._run_deposits(name, port, rounds=10)
            got, fresh = win.read(0, consume=False)
            assert np.array_equal(got, total)
            assert fresh == 10  # the ambiguous batch applied ONCE
        finally:
            srv.stop()
            win.free()

    def test_client_truncated_frame_replayed_not_partially_applied(self):
        from bluefog_tpu import chaos

        name = _uniq("res_trunc")
        win, srv, port = _serve(name)
        chaos.configure("client:truncate:after_frames=4")
        try:
            _, total = self._run_deposits(name, port, rounds=12)
            got, fresh = win.read(0, consume=False)
            assert np.array_equal(got, total)
            assert fresh == 12
        finally:
            srv.stop()
            win.free()

    def test_handcrafted_duplicate_batch_is_deduped_server_side(self):
        # simulate a zombie replaying a frame the server already applied
        # on the SAME connection: the server must ack it as applied
        # without touching the table (the belt-and-braces half of
        # exactly-once, independent of the client's attach bookkeeping)
        import socket as socklib
        import struct

        from bluefog_tpu.runtime import window_server as ws

        name = _uniq("res_dup")
        win, srv, port = _serve(name)
        try:
            s = socklib.create_connection(("127.0.0.1", port), timeout=10)
            s.sendall(ws._HDR.pack(ws._MAGIC, ws._OP_HELLO, 0)
                      + ws._HELLO.pack(ws.PROTOCOL_VERSION,
                                       ws.FEATURE_BATCH
                                       | ws.FEATURE_RESUME))
            (granted,) = ws._STATUS.unpack(s.recv(8))
            assert granted >= 0
            s.sendall(ws._HDR.pack(ws._MAGIC, ws._OP_STREAM_ATTACH, 0)
                      + ws._ATTACH.pack(12345, 1))
            (applied,) = ws._STATUS.unpack(s.recv(8))
            assert applied == 0
            payload = np.full(8, 3.0).tobytes()
            nb = name.encode()
            frame = (ws._HDR.pack(ws._MAGIC, ws._OP_DEPOSIT_BATCH, 0)
                     + ws._BATCH_HDR.pack(1, 1)
                     + ws._ITEM.pack(len(nb), 0, 1, 1, 0, 8, len(payload))
                     + nb + payload)
            s.sendall(frame)
            seq, status = struct.unpack("<Iq", s.recv(12))
            assert (seq, status) == (1, 1)
            s.sendall(frame)  # the duplicate, verbatim
            seq, status = struct.unpack("<Iq", s.recv(12))
            assert seq == 1 and status >= 0
            got, fresh = win.read(0, consume=False)
            np.testing.assert_array_equal(got, np.full(8, 3.0))
            assert fresh == 1  # ONE apply, not two
            # a stale epoch can never steal the stream back
            s2 = socklib.create_connection(("127.0.0.1", port),
                                           timeout=10)
            s2.sendall(ws._HDR.pack(ws._MAGIC, ws._OP_HELLO, 0)
                       + ws._HELLO.pack(ws.PROTOCOL_VERSION,
                                        ws.FEATURE_BATCH
                                        | ws.FEATURE_RESUME))
            s2.recv(8)
            s2.sendall(ws._HDR.pack(ws._MAGIC, ws._OP_STREAM_ATTACH, 0)
                       + ws._ATTACH.pack(12345, 1))  # not newer
            (rc,) = ws._STATUS.unpack(s2.recv(8))
            assert rc == ws._ERR_STALE_EPOCH
            s2.close()
            s.close()
        finally:
            srv.stop()
            win.free()

    def test_latched_batch_error_survives_connection_death(self):
        # a REJECTED deposit whose negative ack died with the connection
        # must NOT be retired as success by the reconnect: the server
        # latches the stream's first batch error and the attach reply
        # reports it, so the client fails as loudly as the lost ack
        # would have made it
        from bluefog_tpu import chaos
        from bluefog_tpu.runtime.window_server import DepositStream

        name = _uniq("res_latch")
        win, srv, port = _serve(name)
        chaos.configure("ack:drop:after_frames=1")
        st = DepositStream(("127.0.0.1", port), reconnect=_FAST)
        try:
            st.deposit_async(b"res_no_such_window", 0, np.ones(8))
            with pytest.raises(RuntimeError, match="no such window"):
                st.flush(timeout_s=30)
        finally:
            st.close()
            srv.stop()
            win.free()

    def test_budget_exhaustion_marks_peer_dead(self):
        from bluefog_tpu.runtime import resilience as R
        from bluefog_tpu.runtime.window_server import DepositStream

        name = _uniq("res_dead")
        win, srv, port = _serve(name)
        st = DepositStream(("127.0.0.1", port),
                           reconnect=dict(base_s=0.01, cap_s=0.05,
                                          budget=3, seed=0))
        try:
            srv.stop()  # the peer is gone for good
            st.deposit_async(name.encode(), 0, np.ones(8))
            with pytest.raises(RuntimeError, match="unreachable"):
                st.flush(timeout_s=30)
            assert st.health.state == R.DEAD
            # terminal: later deposits fail fast, no zombie retry loop
            with pytest.raises(RuntimeError):
                st.deposit_async(name.encode(), 0, np.ones(8))
        finally:
            st.close()
            win.free()

    def test_heartbeat_keeps_idle_stream_health_fresh(self):
        from bluefog_tpu.metrics import registry as mreg
        from bluefog_tpu.runtime import resilience as R
        from bluefog_tpu.runtime.window_server import DepositStream

        name = _uniq("res_hb")
        win, srv, port = _serve(name)
        reg = mreg.metrics_start()
        st = DepositStream(("127.0.0.1", port), reconnect=_FAST,
                           heartbeat_interval_s=0.05,
                           suspect_after_s=0.5, dead_after_s=10.0)
        try:
            time.sleep(0.6)  # idle: several heartbeat round trips
            assert st.health.state == R.HEALTHY
            snap = reg.snapshot()
            rtts = [v for k, v in snap.items()
                    if k.startswith("bf_peer_heartbeat_rtt_seconds_count")]
            assert rtts and rtts[0] >= 2, snap
        finally:
            st.close()
            mreg.metrics_stop()
            srv.stop()
            win.free()


def test_close_racing_recover_never_installs_fresh_socket():
    """Regression for the close-vs-reconnect race the BF-CONC003
    thread-shared-state audit surfaced (PR 9): if close() set _closed
    while _recover() was mid-connect, the old code installed the fresh
    socket anyway — close() had already read (and would close) the OLD
    one, leaking the new socket and parking the ack thread in recv on a
    connection nobody would ever close.  _recover must refuse the
    install once _closed is set, closing the fresh socket itself."""
    from bluefog_tpu.runtime.window_server import DepositStream

    name = _uniq("res_close_race")
    win, srv, port = _serve(name)
    try:
        st = DepositStream(("127.0.0.1", port), reconnect=_FAST)
        fresh = []
        real_connect = st._connect_once

        def racing_connect(timeout_s):
            sock = real_connect(timeout_s)
            fresh.append(sock)
            # deterministically lose the race: close() marks the stream
            # closed at the exact moment the reconnect's connect lands
            with st._cv:
                st._closed = True
            return sock

        old = st._sock
        st._connect_once = racing_connect
        assert st._recover("seeded close race") is False
        assert st._sock is old, "fresh socket must not be installed"
        assert fresh and fresh[0].fileno() == -1, \
            "refused fresh socket must be closed, not leaked"
        st.close()
    finally:
        srv.stop()
        win.free()


# ---------------------------------------------------------------------------
# 4. self-healing gossip (thread mode — deterministic, in-process)
# ---------------------------------------------------------------------------


def _quadratic(n):
    targets = np.stack([np.full(4, float(r + 1)) for r in range(n)])

    def loss_and_grad(r, step, params):
        w = np.asarray(params["w"], np.float64)
        diff = w - targets[r]
        return 0.5 * float(diff @ diff), {"w": diff}

    return loss_and_grad


@pytest.mark.chaos
class TestSelfHealingGossip:
    def test_dsgd_kill_one_of_three_exact_audit_over_survivors(self):
        from bluefog_tpu import chaos, topology as T
        from bluefog_tpu.runtime.async_windows import run_async_dsgd
        from bluefog_tpu.runtime.resilience import ResilienceConfig

        chaos.configure("rank2:die:at_step=8")
        cfg = ResilienceConfig(suspect_after_s=0.1, dead_after_s=0.3)
        rep = run_async_dsgd(
            T.FullyConnectedGraph(3), {"w": np.zeros(4, np.float32)},
            _quadratic(3), duration_s=2.0,
            skew=[0.001, 0.002, 0.003], name=_uniq("res_kill"),
            resilience=cfg)
        assert rep.dead_ranks == [2]
        # the EXACT audit: surviving mass + the corpse's last will + the
        # in-flight mass stranded in its landing slots == n, to float
        # round-off — nothing leaked, nothing double-counted
        assert abs(rep.total_mass + rep.died_mass - 3.0) < 1e-9
        assert 0.0 < rep.died_mass < 1.5
        # survivors detected the death within the configured deadline
        # and kept training long past the kill step
        assert rep.steps_per_rank[2] == 8
        assert min(rep.steps_per_rank[0], rep.steps_per_rank[1]) > 50
        # and they converged among themselves (survivor consensus)
        assert rep.consensus_gap < 0.5, rep.consensus_gap
        assert rep.final_params[2] is None

    def test_dsgd_stall_is_dead_then_rejoined_mass_exact(self):
        # the SIGSTOP/SIGCONT shape in thread clothing: rank 1 freezes
        # past the dead deadline (declared DEAD, healed away), thaws,
        # beats again (REJOINED), and is re-admitted at the next round
        # boundary — and because nobody actually died, the ORIGINAL
        # global audit stays exact: sum p == n
        from bluefog_tpu import chaos, topology as T
        from bluefog_tpu.runtime import resilience as R
        from bluefog_tpu.runtime.async_windows import run_async_dsgd
        from bluefog_tpu.runtime.resilience import ResilienceConfig

        chaos.configure("rank1:stall:at_step=6:s=0.8")
        cfg = ResilienceConfig(suspect_after_s=0.15, dead_after_s=0.35)
        rep = run_async_dsgd(
            T.FullyConnectedGraph(3), {"w": np.zeros(4, np.float32)},
            _quadratic(3), duration_s=2.5,
            skew=[0.001, 0.001, 0.001], name=_uniq("res_stall"),
            resilience=cfg)
        assert rep.dead_ranks == []  # it came back
        assert abs(rep.total_mass - 3.0) < 1e-9, rep.total_mass
        # the stalled rank resumed stepping after the freeze
        assert rep.steps_per_rank[1] > 6 + 10, rep.steps_per_rank
        # the health timeline shows the full DEAD -> REJOIN -> re-admit
        # cycle (carried on the report — the blackbox ring may have
        # evicted the early events under gossip traffic)
        seq = [(a, b) for (_, a, b) in rep.health_transitions[1]]
        assert (R.SUSPECT, R.DEAD) in seq, seq
        assert (R.DEAD, R.REJOINED) in seq, seq
        assert (R.REJOINED, R.HEALTHY) in seq, seq
        assert seq.index((R.SUSPECT, R.DEAD)) \
            < seq.index((R.DEAD, R.REJOINED)) \
            < seq.index((R.REJOINED, R.HEALTHY))

    def test_pushsum_kill_one_survivor_consensus_and_exact_mass(self):
        from bluefog_tpu import chaos, topology as T
        from bluefog_tpu.runtime.async_windows import run_async_pushsum
        from bluefog_tpu.runtime.resilience import ResilienceConfig

        chaos.configure("rank2:die:at_step=5")
        cfg = ResilienceConfig(suspect_after_s=0.1, dead_after_s=0.3)
        x0 = np.array([[1.0], [2.0], [9.0]])
        rep = run_async_pushsum(
            T.FullyConnectedGraph(3), x0, tol=1e-4, timeout_s=10.0,
            name=_uniq("res_ps"), resilience=cfg)
        assert rep.dead_ranks == [2]
        assert abs(rep.total_mass + rep.died_mass - 3.0) < 1e-9
        # survivors reached consensus (on the mass-weighted surviving
        # average, NOT the original mean — rank 2 took mass with it)
        assert rep.converged, (rep.max_abs_err, rep.steps_per_rank)
        alive = [0, 1]
        spread = np.abs(rep.estimates[alive]
                        - rep.estimates[alive].mean(axis=0)).max()
        assert spread < 1e-3


# ---------------------------------------------------------------------------
# 5. multi-process: real SIGKILL / SIGSTOP through the TCP transport
# ---------------------------------------------------------------------------


def _run_resilience_workers(mode, nproc=3, duration="3.5", timeout=240):
    import tempfile

    with tempfile.TemporaryDirectory() as bdir:
        worker = os.path.join(_REPO, "tests", "_mp_resilience_worker.py")
        procs = [
            subprocess.Popen(
                [sys.executable, worker, str(r), str(nproc), bdir,
                 duration, mode],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=clean_env(), cwd=_REPO)
            for r in range(nproc)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=timeout)
                outs.append(out)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail(f"resilience workers ({mode}) timed out:\n"
                        + "\n".join(o or "" for o in outs))
        return procs, outs


@pytest.mark.chaos
@pytest.mark.duration_budget(60)  # pre-existing heavyweight; tier-1 coverage load-bearing
def test_mp_sigkill_one_of_three_survivors_heal_and_audit_exactly():
    """The acceptance scenario: one of three rank PROCESSES is SIGKILLed
    mid-dsgd.  The survivors' deposit streams fail, reconnect attempts
    exhaust their budget (the configured detection deadline), the peer is
    declared DEAD and healed out of the mixing weights, survivors finish
    the run, and rank 0's audit over the surviving set matches the
    post-heal baseline EXACTLY — replay double-applied nothing, the
    healed weights leaked nothing."""
    procs, outs = _run_resilience_workers("kill2")
    # rank 2 died by SIGKILL (-9); the survivors exited clean
    assert procs[2].returncode == -9, (procs[2].returncode, outs[2])
    for r in (0, 1):
        assert procs[r].returncode == 0, f"worker {r} failed:\n{outs[r]}"
        assert f"RES_MP_OK {r}" in outs[r], outs[r]


@pytest.mark.chaos
@pytest.mark.slow
def test_mp_sigstop_sigcont_rejoin_round_trip():
    """SIGSTOP a rank for ~1 s mid-run, SIGCONT it (the chaos helper
    child thaws it): the survivors' peer health dips to SUSPECT and
    recovers, nobody is declared dead, and the global mass audit stays
    exact — a paused peer costs latency, never mass."""
    procs, outs = _run_resilience_workers("sigstop1", duration="4.0")
    for r in range(3):
        assert procs[r].returncode == 0, f"worker {r} failed:\n{outs[r]}"
        assert f"RES_MP_OK {r}" in outs[r], outs[r]
