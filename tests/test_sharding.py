"""Unified sharding subsystem tests: ONE rule table governs params,
optimizer state, and window buffers; gossip-of-meshes is numerically
identical to the gathered single-chip reference.

The two acceptance invariants pinned here (ISSUE 10):

- changing a SINGLE rule re-shards the param, its optimizer state, and
  its window buffer consistently (``TestOneRuleGovernsAllThree``);
- sharded-leaf gossip over a rank×shard mesh is allclose (1e-12) to the
  gathered reference for ring/exponential topologies, including the
  exact per-coordinate mass audit through a heal
  (``TestShardedGossipEquivalence``).  The zero-gather-on-the-hot-path
  half lives in the BF-SHD003 jaxpr check (tests/test_analysis.py).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from bluefog_tpu import topology as T
from bluefog_tpu.sharding import (
    GossipMesh,
    Rule,
    RuleTable,
    ShardView,
    ShardingRuleError,
    UnmatchedLeafError,
    UnusedRuleError,
    gather_tree,
    inner_coords,
    named_leaves,
    num_shards,
    opt_state_specs,
    reassemble_vectors,
    record_shard_savings,
    run_sharded_gossip,
    shard_shape,
    shard_size_ratio,
    shard_slices,
    shard_tree,
    tree_wire_bytes,
)
from bluefog_tpu.runtime.async_windows import TreePacker

AXES = {"fsdp": 2, "tp": 2}


def _params():
    """A transformer-shaped pytree: 2-d kernels, 1-d biases, a scalar."""
    rng = np.random.default_rng(7)
    return {
        "emb": {"kernel": rng.standard_normal((8, 4))},
        "blk": {
            "up": {"kernel": rng.standard_normal((4, 8)),
                   "bias": rng.standard_normal((8,))},
            "down": {"kernel": rng.standard_normal((8, 4))},
            "ln": {"scale": np.ones((4,)), "count": np.zeros(())},
        },
    }


def _table(axes=AXES):
    return RuleTable([
        (r"up/kernel$", P(None, "tp")),
        (r"down/kernel$", P("tp", None)),
        (r"emb/kernel$", P("fsdp", None)),
        (".*", P()),
    ], axes=axes)


def _flat(tree):
    return np.concatenate(
        [np.asarray(x, np.float64).ravel()
         for x in jax.tree_util.tree_leaves(tree)])


# ---------------------------------------------------------------------------
# Rule resolution
# ---------------------------------------------------------------------------


class TestRuleResolution:
    def test_first_match_wins(self):
        t = RuleTable([("kernel$", P("tp")), ("up/kernel$", P("fsdp"))])
        assert t.resolve("blk/up/kernel", (8,)) == P("tp")

    def test_first_match_wins_property(self):
        """Seeded sweep: resolution always returns the FIRST matching
        rule, regardless of how many later rules also match."""
        rng = np.random.default_rng(0)
        pool = ["kernel", "bias", "scale", "up", "down", "emb"]
        for _ in range(30):
            k = int(rng.integers(2, 6))
            pats = [rng.choice(pool) for _ in range(k)] + [".*"]
            t = RuleTable([(p, P("tp") if i % 2 else P())
                           for i, p in enumerate(pats)])
            name = "/".join(rng.choice(pool, size=3))
            expected = next(r.spec for r in t.rules if r.matches(name))
            assert t.resolve(name, (4, 4)) == expected

    def test_scalars_never_partitioned(self):
        t = RuleTable([(".*", P("tp"))])
        assert t.resolve("count", ()) == P()
        assert t.resolve("one", (1,)) == P()
        # ... even with no matching rule at all
        assert RuleTable([]).resolve("count", ()) == P()

    def test_unmatched_leaf_raises(self):
        t = RuleTable([("kernel$", P("tp"))])
        with pytest.raises(UnmatchedLeafError):
            t.resolve("blk/bias", (8,))

    def test_spec_longer_than_leaf_raises(self):
        t = RuleTable([("kernel$", P("tp", None, "fsdp"))])
        with pytest.raises(ShardingRuleError):
            t.resolve("kernel", (8, 4))

    def test_unknown_axis_rejected_at_construction(self):
        with pytest.raises(ShardingRuleError):
            RuleTable([("kernel$", P("nope"))], axes={"tp": 2})

    def test_bad_regex_rejected_at_construction(self):
        with pytest.raises(Exception):
            Rule("(unclosed", P())

    def test_string_spec_is_one_axis_not_characters(self):
        # P(*"tp") would char-splat into P('t', 'p') — axes that exist
        # nowhere, so the leaf silently replicates on the wire
        r = Rule("kernel$", "tp")
        assert r.spec == P("tp")
        t = RuleTable([("kernel$", "tp"), (".*", P())], axes={"tp": 2})
        assert t.resolve("blk/kernel", (8, 4)) == P("tp")

    def test_moe_tp_graft_covers_real_model_naming(self):
        # the tp graft must match MoETransformerLM's ACTUAL leaf names
        # (fused qkv/kernel, row-parallel proj/kernel, no up/down) —
        # a dead grafted rule means a half-applied Megatron placement
        from bluefog_tpu.models.moe import moe_param_rules

        params = {
            "block_0": {
                "qkv": {"kernel": jnp.zeros((8, 24)),
                        "bias": jnp.zeros((24,))},
                "proj": {"kernel": jnp.zeros((8, 8)),
                         "bias": jnp.zeros((8,))},
                "moe": {"router": jnp.zeros((8, 4)),
                        "wi": jnp.zeros((4, 8, 16)),
                        "wo": jnp.zeros((4, 16, 8))},
                "ln1": {"scale": jnp.zeros((8,))},
            },
            "tok": {"embedding": jnp.zeros((32, 8))},
        }
        table = moe_param_rules(tp_axis="tp")
        table.check(params)  # full coverage, no dead rules
        assert table.resolve("block_0/qkv/kernel", (8, 24)) == \
            P(None, "tp")
        assert table.resolve("block_0/proj/kernel", (8, 8)) == \
            P("tp", None)
        assert table.resolve("block_0/moe/wi", (4, 8, 16)) == P("ep")
        assert table.resolve("tok/embedding", (32, 8)) == P()

    def test_coverage_both_directions(self):
        t = RuleTable([("kernel$", P(None, "tp")), ("dead_pattern$", P())])
        unmatched, unused = t.coverage(_params())
        assert "blk/up/bias" in unmatched
        assert "blk/ln/count" not in unmatched  # scalar exempt
        assert unused == ["dead_pattern$"]
        with pytest.raises(UnmatchedLeafError):
            t.check(_params())
        t2 = RuleTable([("never_matches$", P()), (".*", P())])
        with pytest.raises(UnusedRuleError):
            t2.check(_params())

    def test_full_coverage_resolves_everything(self):
        t = _table()
        assert t.coverage(_params()) == ([], [])
        specs = t.resolve_tree(_params())
        for (name, _), (_, spec) in zip(
                named_leaves(_params()),
                named_leaves(specs, is_leaf=lambda x: isinstance(x, P))):
            assert isinstance(spec, P), name

    def test_replaced_swaps_exactly_one_rule(self):
        t = _table()
        t2 = t.replaced(r"up/kernel$", P("fsdp", None))
        assert t2.resolve("blk/up/kernel", (4, 8)) == P("fsdp", None)
        assert t2.resolve("blk/down/kernel", (8, 4)) == P("tp", None)
        assert len(t2) == len(t)
        with pytest.raises(KeyError):
            t.replaced("no_such_pattern", P())


# ---------------------------------------------------------------------------
# Optimizer-state derivation
# ---------------------------------------------------------------------------


class TestOptStateInheritance:
    def test_adam_moments_inherit_param_spec(self):
        params = _params()
        t = _table()
        state = jax.eval_shape(optax.adam(1e-3).init, params)
        specs = opt_state_specs(t, params, state)
        flat = dict(named_leaves(specs,
                                 is_leaf=lambda x: isinstance(x, P)))
        for moment in ("mu", "nu"):
            key = next(k for k in flat
                       if moment in k and k.endswith("up/kernel"))
            assert flat[key] == P(None, "tp")
            key = next(k for k in flat
                       if moment in k and k.endswith("emb/kernel"))
            assert flat[key] == P("fsdp", None)
        count = next(k for k in flat if k.endswith("count"))
        assert flat[count] == P()

    def test_unshadowed_nonscalar_falls_back_to_table(self):
        params = {"w": np.zeros((4, 4))}
        t = RuleTable([("w$", P("tp")), ("slot$", P("fsdp", None))])
        state = {"0": {"w": np.zeros((4, 4)), "slot": np.zeros((2, 2))}}
        specs = opt_state_specs(t, params, state)
        assert specs["0"]["w"] == P("tp")          # inherited (suffix+shape)
        assert specs["0"]["slot"] == P("fsdp", None)  # direct resolution
        # ... and with no rule either, the leak is loud
        t2 = RuleTable([("w$", P("tp"))])
        with pytest.raises(UnmatchedLeafError):
            opt_state_specs(t2, params, state)

    def test_shape_mismatch_does_not_inherit(self):
        # a leaf whose path shadows a param but whose SHAPE differs is
        # not that param's moment — it must resolve on its own
        params = {"w": np.zeros((4, 4))}
        t = RuleTable([("w$", P("tp"))])
        state = {"mu": {"w": np.zeros((8, 8))}}
        specs = opt_state_specs(t, params, state)
        assert specs["mu"]["w"] == P("tp")  # via its own 'w$' rule
        # spec comes from direct resolution, not shape-blind inheritance:
        # a rule that only the param path could satisfy now fails loudly
        t3 = RuleTable([(r"^w$", P("tp"))])
        with pytest.raises(UnmatchedLeafError):
            opt_state_specs(t3, params, state)

    def test_optimizer_state_specs_api(self):
        from bluefog_tpu.optim import optimizer_state_specs

        params = _params()
        specs = optax_specs = optimizer_state_specs(
            _table(), params, optax.chain(optax.clip(1.0),
                                          optax.adam(1e-3)))
        flat = dict(named_leaves(optax_specs,
                                 is_leaf=lambda x: isinstance(x, P)))
        assert any(v == P(None, "tp") for v in flat.values())
        assert specs is not None


# ---------------------------------------------------------------------------
# Shard geometry (host side)
# ---------------------------------------------------------------------------


class TestShardGeometry:
    def test_shard_shape_and_ratio(self):
        assert shard_shape((8, 4), P("tp", None), AXES) == (4, 4)
        assert shard_shape((8, 4), P(("fsdp", "tp")), AXES) == (2, 4)
        assert shard_shape((8, 4), P(), AXES) == (8, 4)
        assert shard_size_ratio(P("tp", None), AXES) == 2
        assert shard_size_ratio(P(("fsdp", "tp")), AXES) == 4
        assert shard_size_ratio(P(), AXES) == 1
        # an axis the mesh lacks is one shard — {} is the reference
        assert shard_shape((8, 4), P("tp"), {}) == (8, 4)

    def test_ragged_shard_refused(self):
        with pytest.raises(ValueError, match="not divisible"):
            shard_shape((7,), P("tp"), AXES)

    def test_slices_tile_exactly(self):
        """Every coordinate's slice lands once; the union is the whole
        leaf — no overlap, no gap, for single- and multi-axis dims."""
        for spec in (P("tp", None), P(None, "fsdp"), P(("fsdp", "tp")),
                     P("fsdp", "tp")):
            hits = np.zeros((8, 4), np.int32)
            for coord in inner_coords(AXES):
                hits[shard_slices((8, 4), spec, AXES, coord)] += 1
            # each element is covered by exactly num_shards/ratio coords
            expected = num_shards(AXES) // shard_size_ratio(spec, AXES)
            assert (hits == expected).all(), spec

    def test_multi_axis_row_major(self):
        # ('fsdp', 'tp') on one dim: fsdp is the outer (slower) axis
        a = np.arange(8)
        got = {}
        for coord in inner_coords(AXES):
            sl = shard_slices((8,), P(("fsdp", "tp")), AXES, coord)
            got[(coord["fsdp"], coord["tp"])] = list(a[sl])
        assert got[(0, 0)] == [0, 1]
        assert got[(0, 1)] == [2, 3]
        assert got[(1, 0)] == [4, 5]
        assert got[(1, 1)] == [6, 7]

    def test_inner_coords_row_major_order(self):
        coords = inner_coords({"a": 2, "b": 2})
        assert coords == [{"a": 0, "b": 0}, {"a": 0, "b": 1},
                          {"a": 1, "b": 0}, {"a": 1, "b": 1}]
        assert inner_coords({}) == [{}]

    def test_shard_view_validates_coord(self):
        with pytest.raises(ValueError):
            ShardView(specs=P(), axes=AXES, coord={"tp": 0})  # fsdp missing
        with pytest.raises(ValueError):
            ShardView(specs=P(), axes=AXES, coord={"tp": 2, "fsdp": 0})

    def test_gossip_mesh_geometry(self):
        gm = GossipMesh(4, {"fsdp": 2, "tp": 2})
        assert gm.inner_size == 4
        assert gm.axis_sizes == {"bf": 4, "fsdp": 2, "tp": 2}
        assert len(gm.coords()) == 4
        with pytest.raises(ValueError):
            GossipMesh(0, {})
        with pytest.raises(ValueError):
            GossipMesh(2, {"bf": 2})


# ---------------------------------------------------------------------------
# Host shard/gather + spec-aware TreePacker
# ---------------------------------------------------------------------------


class TestHostShardGather:
    def test_shard_gather_roundtrip(self):
        params = _params()
        specs = _table().resolve_tree(params)
        shards = {}
        for coord in inner_coords(AXES):
            view = ShardView(specs=specs, axes=AXES, coord=coord)
            shards[tuple(coord[n] for n in AXES)] = shard_tree(params, view)
        out = gather_tree(params, specs, AXES, shards)
        np.testing.assert_allclose(_flat(out), _flat(params), atol=0)

    def test_missing_coordinate_raises(self):
        params = _params()
        specs = _table().resolve_tree(params)
        view = ShardView(specs=specs, axes=AXES,
                         coord={"fsdp": 0, "tp": 0})
        shards = {(0, 0): shard_tree(params, view)}
        with pytest.raises(KeyError, match="missing shard"):
            gather_tree(params, specs, AXES, shards)

    def test_mis_shaped_shard_refused(self):
        params = {"w": np.zeros((8,))}
        specs = {"w": P("tp")}
        shards = {}
        for coord in inner_coords({"tp": 2}):
            shards[(coord["tp"],)] = {"w": np.zeros((3,))}  # wrong size
        with pytest.raises(ValueError, match="shape"):
            gather_tree(params, specs, {"tp": 2}, shards)


class TestSpecAwareTreePacker:
    def test_pack_full_and_shard_shaped(self):
        params = _params()
        specs = _table().resolve_tree(params)
        view = ShardView(specs=specs, axes=AXES,
                         coord={"fsdp": 1, "tp": 0})
        packer = TreePacker(params, np.float64, sharding=view)
        full_dim = sum(np.asarray(x).size
                       for x in jax.tree_util.tree_leaves(params))
        assert packer.size < full_dim  # shard-local vector is smaller
        vec = packer.pack(params)                  # full tree -> slices
        shard = packer.unpack(vec, as_jax=False)   # shard-shaped leaves
        np.testing.assert_allclose(
            _flat(shard), _flat(shard_tree(params, view)), atol=0)
        vec2 = packer.pack(shard)                  # shard-shaped repack
        np.testing.assert_allclose(vec, vec2, atol=0)

    def test_wrong_shape_is_an_error(self):
        params = {"w": np.zeros((8, 4))}
        view = ShardView(specs={"w": P("tp", None)}, axes={"tp": 2},
                         coord={"tp": 0})
        packer = TreePacker(params, np.float64, sharding=view)
        with pytest.raises(ValueError, match="neither"):
            packer.pack({"w": np.zeros((5, 4))})

    def test_reassemble_vectors_roundtrip(self):
        params = _params()
        specs = _table().resolve_tree(params)
        vectors = {}
        for coord in inner_coords(AXES):
            view = ShardView(specs=specs, axes=AXES, coord=coord)
            vectors[tuple(coord[n] for n in AXES)] = TreePacker(
                params, np.float64, sharding=view).pack(params)
        out = reassemble_vectors(params, specs, AXES, vectors)
        np.testing.assert_allclose(_flat(out), _flat(params), atol=0)


# ---------------------------------------------------------------------------
# Gossip-of-meshes numerical equivalence
# ---------------------------------------------------------------------------


def _rank_params(n):
    rng = np.random.default_rng(11)
    base = _params()
    return [jax.tree_util.tree_map(
        lambda a: np.asarray(a) + rng.standard_normal(np.shape(a)),
        base) for _ in range(n)]


class TestShardedGossipEquivalence:
    @pytest.mark.parametrize("topo", [T.RingGraph(4),
                                      T.ExponentialTwoGraph(4)],
                             ids=lambda t: t.name)
    def test_matches_gathered_reference(self, topo):
        p0 = _rank_params(topo.size)
        table = _table()
        ref = run_sharded_gossip(topo, p0, table, {}, rounds=6)
        shd = run_sharded_gossip(topo, p0, table, AXES, rounds=6)
        for a, b in zip(ref.params, shd.params):
            np.testing.assert_allclose(_flat(b), _flat(a), atol=1e-12)
        # per-coordinate exact mass audit
        assert set(shd.total_mass) == {
            tuple(c[n] for n in AXES) for c in inner_coords(AXES)}
        for mass in shd.total_mass.values():
            assert abs(mass - topo.size) < 1e-9

    def test_mass_audit_exact_through_heal(self):
        topo = T.RingGraph(4)
        p0 = _rank_params(4)
        table = _table()
        kw = dict(rounds=8, heal_after=3, dead_ranks=[2])
        ref = run_sharded_gossip(topo, p0, table, {}, **kw)
        shd = run_sharded_gossip(topo, p0, table, AXES, **kw)
        assert shd.dead_ranks == [2] and shd.params[2] is None
        for mass in shd.total_mass.values():
            assert abs(mass - 4.0) < 1e-9  # deaths included, none lost
        for r in (0, 1, 3):
            np.testing.assert_allclose(_flat(shd.params[r]),
                                       _flat(ref.params[r]), atol=1e-12)

    def test_wire_accounting(self):
        topo = T.RingGraph(4)
        p0 = _rank_params(4)
        table = _table()
        shd = run_sharded_gossip(topo, p0, table, AXES, rounds=2)
        ref = run_sharded_gossip(topo, p0, table, {}, rounds=2)
        assert ref.saved_bytes_per_deposit == 0
        # sharded deposits ship strictly less; shard+saved == full
        full = ref.shard_bytes_per_deposit
        assert shd.shard_bytes_per_deposit < full
        assert shd.shard_bytes_per_deposit + shd.saved_bytes_per_deposit \
            == full
        sb, fb = tree_wire_bytes(p0[0], table.resolve_tree(p0[0]), AXES)
        assert (sb, fb) == (shd.shard_bytes_per_deposit, full)

    def test_dead_ranks_without_heal_rejected(self):
        with pytest.raises(ValueError):
            run_sharded_gossip(T.RingGraph(4), _rank_params(4), _table(),
                               {}, rounds=2, dead_ranks=[1])


# ---------------------------------------------------------------------------
# THE acceptance invariant: one rule, three leaf families
# ---------------------------------------------------------------------------


class TestOneRuleGovernsAllThree:
    def test_single_rule_change_reshards_all_families(self):
        from bluefog_tpu.ops.windows import win_create, win_partition
        from bluefog_tpu.optim import optimizer_state_specs

        params = _params()
        sched = T.build_schedule(T.RingGraph(4))
        opt = optax.adam(1e-3)

        def all_three(table):
            pspec = table.resolve_tree(params)
            ospec = optimizer_state_specs(table, params, opt)
            win = win_create(params, sched, "bf", rule_table=table)
            return pspec, ospec, win_partition(win)

        t1 = _table()
        p1, o1, w1 = all_three(t1)
        assert p1["blk"]["up"]["kernel"] == P(None, "tp")
        assert w1["blk/up/kernel"] == P(None, "tp")
        oflat1 = dict(named_leaves(o1,
                                   is_leaf=lambda x: isinstance(x, P)))
        mukey = next(k for k in oflat1
                     if "mu" in k and k.endswith("up/kernel"))
        assert oflat1[mukey] == P(None, "tp")

        # change ONE rule ...
        t2 = t1.replaced(r"up/kernel$", P("fsdp", None))
        p2, o2, w2 = all_three(t2)
        # ... and all three families re-shard consistently
        assert p2["blk"]["up"]["kernel"] == P("fsdp", None)
        assert w2["blk/up/kernel"] == P("fsdp", None)
        oflat2 = dict(named_leaves(o2,
                                   is_leaf=lambda x: isinstance(x, P)))
        assert oflat2[mukey] == P("fsdp", None)
        # every OTHER leaf is untouched in all three families
        for key in ("blk/down/kernel", "emb/kernel", "blk/up/bias"):
            assert w1[key] == w2[key]
        assert p1["blk"]["down"]["kernel"] == p2["blk"]["down"]["kernel"]

        # and the re-sharded table still gossips equivalently
        p0 = _rank_params(4)
        ref = run_sharded_gossip(T.RingGraph(4), p0, t2, {}, rounds=4)
        shd = run_sharded_gossip(T.RingGraph(4), p0, t2, AXES, rounds=4)
        for a, b in zip(ref.params, shd.params):
            np.testing.assert_allclose(_flat(b), _flat(a), atol=1e-12)


# ---------------------------------------------------------------------------
# Dual-source-of-truth (parallel/tensor.py satellite)
# ---------------------------------------------------------------------------


class TestDualSourceOfTruth:
    def _boxed(self, disagree=False):
        import flax.linen as nn

        return {
            "blk": {
                "up": {"kernel": nn.Partitioned(
                           jnp.zeros((4, 8)),
                           names=(None, None) if disagree
                           else (None, "tp")),
                       "bias": nn.Partitioned(jnp.zeros((8,)),
                                              names=("tp",))},
                "down": {"kernel": nn.Partitioned(jnp.zeros((8, 4)),
                                                  names=("tp", None))},
            },
        }

    def _tensor_table(self):
        from bluefog_tpu.parallel.tensor import tp_param_rules

        return tp_param_rules()

    def test_agreement_is_empty(self):
        from bluefog_tpu.parallel.tensor import (box_specs,
                                                 check_rule_agreement)

        template = self._boxed()
        assert check_rule_agreement(template, self._tensor_table()) == []
        specs = box_specs(template)
        assert specs["blk"]["up"]["kernel"] == P(None, "tp")
        assert specs["blk"]["up"]["bias"] == P("tp")

    def test_planted_disagreement_is_caught(self):
        """The regression: a box silently contradicting the table must
        raise, not let the gradient correction scale by one story while
        the wire shards by the other."""
        from bluefog_tpu.parallel.tensor import (PartitionDisagreement,
                                                 check_rule_agreement,
                                                 tp_value_and_grad)

        template = self._boxed(disagree=True)
        mism = check_rule_agreement(template, self._tensor_table())
        assert [m[0] for m in mism] == ["blk/up/kernel"]
        with pytest.raises(PartitionDisagreement, match="up/kernel"):
            tp_value_and_grad(lambda p: 0.0, template,
                              rule_table=self._tensor_table())

    def test_correction_from_table_matches_box_path(self, devices8):
        """tp_correct_grads resolved through the rule table computes the
        SAME correction as the legacy box-metadata path."""
        from bluefog_tpu.parallel.tensor import (make_hybrid_mesh,
                                                 tp_correct_grads)
        from bluefog_tpu.parallel.api import shard_map

        template = self._boxed()
        table = self._tensor_table()
        mesh = make_hybrid_mesh({"tp": 2}, devices=devices8[:2])
        grads = {
            "blk": {"up": {"kernel": jnp.arange(32.0).reshape(4, 8),
                           "bias": jnp.ones((8,))},
                    "down": {"kernel": jnp.arange(32.0).reshape(8, 4)}},
        }

        def body(g):
            via_box = tp_correct_grads(g, template)
            via_table = tp_correct_grads(g, template, rule_table=table)
            return via_box, via_table

        spec = jax.tree_util.tree_map(lambda _: P(), grads)
        out_box, out_table = shard_map(
            body, mesh=mesh, in_specs=(spec,), out_specs=(spec, spec),
            check_vma=False)(grads)
        for a, b in zip(jax.tree_util.tree_leaves(out_box),
                        jax.tree_util.tree_leaves(out_table)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=0)


# ---------------------------------------------------------------------------
# sharded_neighbor_allreduce (ops layer)
# ---------------------------------------------------------------------------


class TestShardedNeighborAllreduce:
    def test_numerics_on_hybrid_mesh(self, devices8):
        """Gossip over bf with tp-sharded leaves on a (bf=4, tp=2) mesh
        matches the closed-form W @ x of the mixing matrix."""
        from bluefog_tpu.ops import collectives as C
        from bluefog_tpu.parallel.api import shard_map
        from bluefog_tpu.parallel.tensor import make_hybrid_mesh

        topo = T.RingGraph(4)
        sched = T.build_schedule(topo)
        mesh = make_hybrid_mesh({"bf": 4, "tp": 2}, devices=devices8)
        table = RuleTable([("w$", P(None, None, "tp")), (".*", P())])
        x = {"w": jnp.broadcast_to(
            jnp.arange(4.0).reshape(4, 1, 1), (4, 8, 6)).copy(),
            "b": jnp.broadcast_to(jnp.arange(4.0).reshape(4, 1),
                                  (4, 8)).copy()}

        def body(xl):
            return C.sharded_neighbor_allreduce(
                xl, sched, "bf", rule_table=table,
                inner_axes={"tp": 2})

        in_specs = {"w": P("bf", None, "tp"), "b": P("bf", None)}
        out = shard_map(body, mesh=mesh, in_specs=(in_specs,),
                        out_specs=in_specs, check_vma=False)(x)
        w = topo.weights
        for key in ("w", "b"):
            got = np.asarray(out[key], np.float64).reshape(4, -1)
            want = w @ np.asarray(x[key], np.float64).reshape(4, -1)
            np.testing.assert_allclose(got, want, atol=1e-6)

    def test_spec_on_gossip_axis_rejected(self):
        from bluefog_tpu.ops import collectives as C

        sched = T.build_schedule(T.RingGraph(4))
        with pytest.raises(ValueError, match="GOSSIP axis"):
            C.sharded_neighbor_allreduce(
                {"w": jnp.zeros((8,))}, sched, "bf",
                specs={"w": P("bf")}, inner_axes={"tp": 2})

    def test_table_required_and_exclusive(self):
        from bluefog_tpu.ops import collectives as C

        sched = T.build_schedule(T.RingGraph(4))
        with pytest.raises(ValueError, match="rule table"):
            C.sharded_neighbor_allreduce({"w": jnp.zeros((8,))}, sched,
                                         "bf")
        with pytest.raises(ValueError, match="not both"):
            C.sharded_neighbor_allreduce(
                {"w": jnp.zeros((8,))}, sched, "bf",
                rule_table=RuleTable([(".*", P())]),
                specs={"w": P()})


# ---------------------------------------------------------------------------
# Pipeline stage specs through the table
# ---------------------------------------------------------------------------


class TestStageParamSpecs:
    def test_stage_leading_dim_plus_table_inner(self):
        from bluefog_tpu.parallel.pipeline import stage_param_specs

        table = RuleTable([(r"up/kernel$", P(None, "tp")), (".*", P())])
        stacked = {"up": {"kernel": jnp.zeros((2, 2, 8, 4)),
                          "bias": jnp.zeros((2, 2, 4))}}
        specs = stage_param_specs(table, stacked)
        assert specs["up"]["kernel"] == P("pp", None, None, "tp")
        assert specs["up"]["bias"] == P("pp", None)


# ---------------------------------------------------------------------------
# Windows + metrics
# ---------------------------------------------------------------------------


class TestWindowPartition:
    def test_declaration_readback(self):
        from bluefog_tpu.ops.windows import win_create, win_partition

        sched = T.build_schedule(T.RingGraph(4))
        table = _table()
        win = win_create(_params(), sched, "bf", rule_table=table)
        decl = win_partition(win)
        assert decl["blk/up/kernel"] == P(None, "tp")
        assert decl["blk/ln/count"] == P()
        # undeclared (legacy) windows read back None
        legacy = win_create(_params(), sched, "bf")
        assert win_partition(legacy) is None

    def test_rule_table_and_partition_exclusive(self):
        from bluefog_tpu.ops.windows import win_create

        sched = T.build_schedule(T.RingGraph(4))
        with pytest.raises(ValueError, match="not both"):
            win_create(_params(), sched, "bf", rule_table=_table(),
                       partition=_table().resolve_tree(_params()))


class TestWireSavingsMetrics:
    @pytest.fixture(autouse=True)
    def _metrics(self):
        from bluefog_tpu.metrics import registry as mreg

        mreg.metrics_stop()
        mreg._STOPPED = False
        self.reg = mreg.metrics_start()
        yield
        mreg.metrics_stop()
        mreg._STOPPED = False

    def test_counters_record_per_leaf_savings(self):
        params = _params()
        specs = _table().resolve_tree(params)
        shard_b, saved_b = record_shard_savings(params, specs, AXES,
                                               deposits=3)
        snap = self.reg.snapshot()
        sharded = {k: v for k, v in snap.items()
                   if k.startswith("bf_sharded_bytes_total")}
        saved = {k: v for k, v in snap.items()
                 if k.startswith("bf_gather_bytes_saved_total")}
        assert sum(sharded.values()) == shard_b * 3
        assert sum(saved.values()) == saved_b * 3
        # labels carry the leaf path and the mentioned axes
        assert any("blk/up/kernel" in k and "tp" in k for k in sharded)
        # replicated leaves save nothing
        assert not any("blk/up/bias" in k for k in saved)


# ---------------------------------------------------------------------------
# Sharded serving replica (read boundary)
# ---------------------------------------------------------------------------


class TestShardedServingReplica:
    def _publish(self, tbl, group, rnd, template, specs, axes, scale=1.0):
        scaled = jax.tree_util.tree_map(
            lambda a: np.asarray(a, np.float64) * scale, template)
        for ci, coord in enumerate(inner_coords(axes)):
            view = ShardView(specs=specs, axes=axes, coord=coord)
            vec = TreePacker(template, np.float64,
                             sharding=view).pack(scaled)
            tbl.publish(f"{group}:{ci}", rnd,
                        {"x": vec, "p": np.array([1.0]),
                         "round": np.array([float(rnd)])})

    def test_round_consistent_reassembly_under_skew(self):
        import time

        from bluefog_tpu.runtime.window_server import WindowServer
        from bluefog_tpu.serving import ShardedServingReplica, table
        from tests._util import uniq

        template = _params()
        tbl_rules = _table()
        specs = tbl_rules.resolve_tree(template)
        srv = WindowServer()
        addr = srv.start("127.0.0.1")
        rep = None
        try:
            tbl = table()
            g = uniq("shard_replica")
            self._publish(tbl, g, 5, template, specs, AXES)
            rep = ShardedServingReplica(addr, g, template, tbl_rules,
                                        AXES, timeout_s=5.0)
            assert rep.wait_ready(20.0) == 5
            np.testing.assert_allclose(_flat(rep.params()),
                                       _flat(template), atol=1e-12)

            # skew: ONE coordinate advances to round 6 — serving must
            # not mix rounds, so the served round stays 5
            view0 = ShardView(specs=specs, axes=AXES,
                              coord=inner_coords(AXES)[0])
            vec0 = TreePacker(template, np.float64, sharding=view0).pack(
                jax.tree_util.tree_map(
                    lambda a: np.asarray(a, np.float64) * 2.0, template))
            tbl.publish(f"{g}:0", 6, {"x": vec0, "p": np.array([1.0]),
                                      "round": np.array([6.0])})
            time.sleep(0.5)
            assert rep.round == 5
            np.testing.assert_allclose(_flat(rep.params()),
                                       _flat(template), atol=1e-12)

            # the stragglers land -> round 6 becomes complete and serves
            self._publish(tbl, g, 6, template, specs, AXES, scale=2.0)
            deadline = time.monotonic() + 20.0
            while rep.round < 6 and time.monotonic() < deadline:
                time.sleep(0.05)
            assert rep.round == 6
            np.testing.assert_allclose(_flat(rep.params()),
                                       _flat(template) * 2.0, atol=1e-12)
            assert rep.staleness_rounds(8) == 2
        finally:
            if rep is not None:
                rep.close()
            srv.stop()
