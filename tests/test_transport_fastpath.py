"""Hot-path raw speed: shm fast path, striped DCN, autotune, overlap.

The three raw-speed attacks of the transport hot path, each tested
against the invariant it is NOT allowed to spend — the exact push-sum
mass audit:

1. **same-host shm fast path** — ``DepositStream(shm=True)`` routes
   deposits through the named-shm window table instead of loopback TCP,
   falling back transparently (``shm_fallback`` blackbox event) on any
   capability failure, and recovering torn shm writes by re-delivery
   over the wire — exactly once either way;
2. **striped DCN** — ``StripedDepositStream`` spreads window names over
   N parallel connections (``stripe_of``), fences ALL stripes on flush,
   actuates ``TransportPlan`` grow/shrink without stranding a deposit,
   and rolls per-stripe ack EWMAs up into the one
   ``bf_peer_ack_ewma_seconds{peer=}`` gauge as max-of-stripes (the PR-8
   slow-peer detector reads it unchanged);
3. **compute/gossip overlap** — :class:`DoubleBuffer` stages landed
   deposits under compute and folds them at the round boundary in slot
   order, bit-identical to the serial fold over the same deposits.

Plus the pure autotune decision function's hysteresis/no-flap/cooldown
properties and the MP acceptance scenario (kill-one-rank under the shm
route, exact audit — ``_mp_fastpath_worker.py``).
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from bluefog_tpu.runtime import native
from tests._util import REPO as _REPO, clean_env, uniq as _uniq

_NATIVE = native.load() is not None


@pytest.fixture(autouse=True)
def _chaos_isolated():
    from bluefog_tpu import chaos

    chaos.reset()
    yield
    chaos.reset()


def _serve(names, n_elems=8, *, shm=False):
    """Owner-side window table + server in THIS process (the depositing
    stream still runs its full client path against it)."""
    from bluefog_tpu.runtime.async_windows import (AsyncWindow,
                                                   shm_unlink_window)
    from bluefog_tpu.runtime.window_server import WindowServer

    wins = {}
    for nm in names:
        if shm:
            shm_unlink_window(nm)
        wins[nm] = AsyncWindow(nm, n_slots=1, n_elems=n_elems,
                               dtype=np.float64, shm=shm)
    srv = WindowServer()
    _, port = srv.start("127.0.0.1")
    return wins, srv, port


# ---------------------------------------------------------------------------
# 1. same-host shm fast path
# ---------------------------------------------------------------------------


@pytest.mark.skipif(not _NATIVE, reason="shm windows need native runtime")
class TestShmFastPath:
    def test_shm_deposits_exactly_once_and_metered(self):
        from bluefog_tpu.metrics import registry as mreg
        from bluefog_tpu.runtime.window_server import DepositStream

        name = _uniq("fp_shm")
        wins, srv, port = _serve([name], shm=True)
        reg = mreg.metrics_start()
        st = DepositStream(("127.0.0.1", port), shm=True)
        try:
            total = np.zeros(8)
            for i in range(15):
                v = np.full(8, float(i + 1))
                st.deposit_async(name.encode(), 0, v, accumulate=True)
                total += v
            st.flush(timeout_s=30)
            got, fresh = wins[name].read(0, consume=False)
            # EXACT value and EXACT apply count through the table route
            assert np.array_equal(got, total)
            assert fresh == 15
            # every deposit really rode shm, none silently fell to TCP
            assert st.shm_deposits == 15
            snap = reg.snapshot()
            assert any(k.startswith("bf_shm_deposits_total") and v == 15.0
                       for k, v in snap.items()), snap
        finally:
            mreg.metrics_stop()
            st.close()
            srv.stop()
            for w in wins.values():
                w.free()

    def test_fallback_when_owner_windows_not_shm(self):
        # the detection-failure path: owner's windows are process-local
        # (not shm-backed) — the stream latches shm off after one probe,
        # records the blackbox breadcrumb, and the deposits land over
        # TCP with identical semantics
        from bluefog_tpu.blackbox import recorder as bb
        from bluefog_tpu.runtime.window_server import DepositStream

        name = _uniq("fp_fall")
        wins, srv, port = _serve([name], shm=False)
        rec = bb.configure(rank=0)
        st = DepositStream(("127.0.0.1", port), shm=True)
        try:
            total = np.zeros(8)
            for i in range(8):
                v = np.full(8, float(i + 1))
                st.deposit_async(name.encode(), 0, v, accumulate=True)
                total += v
            st.flush(timeout_s=30)
            got, fresh = wins[name].read(0, consume=False)
            assert np.array_equal(got, total)
            assert fresh == 8
            assert st.shm_deposits == 0
            kinds = [e["kind"] for e in rec.events()]
            assert "shm_fallback" in kinds, kinds
        finally:
            bb.reset()
            st.close()
            srv.stop()
            for w in wins.values():
                w.free()

    def test_remote_host_is_never_probed(self):
        # the detection rule itself: loopback/local names say yes, a
        # TEST-NET-3 address (guaranteed not this machine) says no —
        # so a cross-host stream never even probes for shm windows
        from bluefog_tpu.runtime.window_server import _is_local_host

        assert not _is_local_host("203.0.113.7")
        assert _is_local_host("127.0.0.1")
        assert _is_local_host("localhost")

    def test_torn_shm_write_redelivers_over_tcp_exactly_once(self):
        # the torn-write model: a chaos 'client' fault fires BEFORE the
        # atomic table accumulate, so the shm write is absent (never
        # half-applied); recovery is re-delivery of THAT deposit over
        # the TCP wire — total applied exactly once
        from bluefog_tpu import chaos
        from bluefog_tpu.blackbox import recorder as bb
        from bluefog_tpu.runtime.window_server import DepositStream

        name = _uniq("fp_torn")
        wins, srv, port = _serve([name], shm=True)
        rec = bb.configure(rank=0)
        chaos.configure("client:truncate:times=1")
        st = DepositStream(("127.0.0.1", port), shm=True)
        try:
            total = np.zeros(8)
            for i in range(10):
                v = np.full(8, float(i + 1))
                st.deposit_async(name.encode(), 0, v, accumulate=True)
                total += v
            st.flush(timeout_s=30)
            got, fresh = wins[name].read(0, consume=False)
            # the torn deposit arrived over TCP, everything else over
            # shm — and the window saw each deposit exactly once
            assert np.array_equal(got, total)
            assert fresh == 10
            assert st.shm_deposits < 10
            kinds = [e["kind"] for e in rec.events()]
            assert "shm_fallback" in kinds, kinds
        finally:
            bb.reset()
            st.close()
            srv.stop()
            for w in wins.values():
                w.free()


# ---------------------------------------------------------------------------
# 2. striped DCN stream
# ---------------------------------------------------------------------------


class TestStripedStream:
    def test_striped_routing_exactly_once_across_windows(self):
        from bluefog_tpu.runtime.window_server import (StripedDepositStream,
                                                       stripe_of)

        names = [_uniq(f"fp_str{i}") for i in range(6)]
        wins, srv, port = _serve(names)
        st = StripedDepositStream(("127.0.0.1", port), n_stripes=3)
        try:
            assert st.n_stripes == 3
            # the name set must actually exercise >1 stripe for this to
            # test routing (deterministic, so assert it)
            stripes_hit = {stripe_of(nm.encode(), 3) for nm in names}
            assert len(stripes_hit) > 1, stripes_hit
            totals = {nm: np.zeros(8) for nm in names}
            for i in range(8):
                for nm in names:
                    v = np.full(8, float(i + 1))
                    st.deposit_async(nm.encode(), 0, v, accumulate=True)
                    totals[nm] += v
            st.flush(timeout_s=30)  # fences EVERY stripe
            for nm in names:
                got, fresh = wins[nm].read(0, consume=False)
                assert np.array_equal(got, totals[nm]), nm
                assert fresh == 8, nm
        finally:
            st.close()
            srv.stop()
            for w in wins.values():
                w.free()

    def test_apply_plan_grow_shrink_never_strands_a_deposit(self):
        from bluefog_tpu.blackbox import recorder as bb
        from bluefog_tpu.control import TransportPlan
        from bluefog_tpu.metrics import registry as mreg
        from bluefog_tpu.runtime.window_server import StripedDepositStream

        names = [_uniq(f"fp_plan{i}") for i in range(4)]
        wins, srv, port = _serve(names)
        reg = mreg.metrics_start()
        rec = bb.configure(rank=0)
        st = StripedDepositStream(("127.0.0.1", port), n_stripes=1)
        try:
            totals = {nm: np.zeros(8) for nm in names}

            def deposit_round(i):
                for nm in names:
                    v = np.full(8, float(i + 1))
                    st.deposit_async(nm.encode(), 0, v, accumulate=True)
                    totals[nm] += v

            deposit_round(0)
            st.apply_plan(TransportPlan(version=1, round=1, stripes=4,
                                        coalesce_bytes=1 << 20))
            assert st.n_stripes == 4
            assert st.plan_version == 1
            deposit_round(1)
            # shrink FENCES the closing stripes before closing them —
            # round 1's deposits on stripes 1-3 must not strand
            st.apply_plan(TransportPlan(version=2, round=2, stripes=1,
                                        coalesce_bytes=4 << 20))
            assert st.n_stripes == 1
            deposit_round(2)
            st.flush(timeout_s=30)
            for nm in names:
                got, fresh = wins[nm].read(0, consume=False)
                assert np.array_equal(got, totals[nm]), nm
                assert fresh == 3, nm
            peer = f"127.0.0.1:{port}"
            snap = reg.snapshot()
            assert snap.get(f'bf_stripe_streams{{peer="{peer}"}}') == 1.0
            kinds = [e["kind"] for e in rec.events()]
            assert "stripe_open" in kinds and "stripe_close" in kinds
        finally:
            st.close()
            snap = mreg.current().snapshot()
            # gauge zeroed on close: a dead stream advertises no stripes
            assert snap.get(
                f'bf_stripe_streams{{peer="127.0.0.1:{port}"}}') == 0.0
            bb.reset()
            mreg.metrics_stop()
            srv.stop()
            for w in wins.values():
                w.free()

    def test_ack_ewma_rollup_is_max_of_stripes(self):
        from bluefog_tpu.metrics import registry as mreg
        from bluefog_tpu.runtime.window_server import StripedDepositStream

        name = _uniq("fp_ewma")
        wins, srv, port = _serve([name])
        reg = mreg.metrics_start()
        st = StripedDepositStream(("127.0.0.1", port), n_stripes=2)
        try:
            for i in range(6):
                st.deposit_async(name.encode(), 0, np.ones(8),
                                 accumulate=True)
                st.flush(timeout_s=30)
            # rollup: the stream-level EWMA is the max over stripes that
            # have evidence, and it feeds the ONE per-peer gauge the
            # slow-peer detector polls
            per_stripe = [s.ack_ewma() for s in st._stripes
                          if s.ack_ewma() is not None]
            assert per_stripe, "no stripe collected ack evidence"
            assert st.ack_ewma() == max(per_stripe)
            snap = reg.snapshot()
            key = f'bf_peer_ack_ewma_seconds{{peer="127.0.0.1:{port}"}}'
            assert snap.get(key) == st.ack_ewma(), snap
        finally:
            st.close()
            mreg.metrics_stop()
            srv.stop()
            for w in wins.values():
                w.free()


# ---------------------------------------------------------------------------
# 3. transport autotune (pure decision function)
# ---------------------------------------------------------------------------


class TestTransportAutotune:
    def _p0(self, **kw):
        from bluefog_tpu.control import TransportPlan

        return TransportPlan(**kw)

    def test_widen_on_slow_net_dominated_acks(self):
        from bluefog_tpu.control import decide_transport_plan

        p0 = self._p0()
        p1 = decide_transport_plan(
            p0, 10, ack_ewma_s=0.08,
            phase_s={"net": 0.06, "queue": 0.01, "apply": 0.01})
        assert (p1.stripes, p1.version) == (2, 1)
        assert p1.coalesce_bytes == p0.coalesce_bytes // 2

    def test_slow_host_is_not_widened_into(self):
        # apply/queue-dominated latency: more stripes would just queue
        # more at the same busy owner — plan must not change
        from bluefog_tpu.control import decide_transport_plan

        p0 = self._p0()
        p1 = decide_transport_plan(
            p0, 10, ack_ewma_s=0.08,
            phase_s={"net": 0.01, "queue": 0.03, "apply": 0.04})
        assert p1 is p0

    def test_hysteresis_band_never_flaps(self):
        # evidence oscillating BETWEEN the exit and enter thresholds:
        # the plan must stay byte-stable through the whole sweep
        from bluefog_tpu.control import (TransportConfig,
                                         decide_transport_plan)

        cfg = TransportConfig()
        plan = self._p0(version=3, round=0, stripes=2)
        for r, ack in enumerate([0.021, 0.049, 0.030, 0.045, 0.025],
                                start=cfg.cooldown_rounds):
            nxt = decide_transport_plan(plan, r, ack_ewma_s=ack, cfg=cfg)
            assert nxt is plan, (r, ack)

    def test_cooldown_freezes_a_fresh_plan(self):
        from bluefog_tpu.control import decide_transport_plan

        p1 = decide_transport_plan(
            self._p0(), 10, ack_ewma_s=0.08)
        assert p1.version == 1 and p1.round == 10
        # violently slow evidence inside the cooldown: frozen
        p2 = decide_transport_plan(p1, 10 + 15, ack_ewma_s=0.5)
        assert p2 is p1
        p3 = decide_transport_plan(p1, 10 + 16, ack_ewma_s=0.5)
        assert p3.version == 2 and p3.stripes == 4

    def test_narrow_on_recovery_and_floor_saturation(self):
        from bluefog_tpu.control import decide_transport_plan

        wide = self._p0(version=5, round=0, stripes=4,
                        coalesce_bytes=1 << 20)
        p1 = decide_transport_plan(wide, 100, ack_ewma_s=0.001)
        assert (p1.stripes, p1.version) == (2, 6)
        floor = self._p0(version=7, round=0, stripes=1,
                         coalesce_bytes=16 << 20)
        p2 = decide_transport_plan(floor, 100, ack_ewma_s=0.001)
        assert p2 is floor  # saturated at the floor: no version churn

    def test_no_evidence_never_tunes(self):
        from bluefog_tpu.control import decide_transport_plan

        p0 = self._p0()
        assert decide_transport_plan(p0, 50, ack_ewma_s=None) is p0

    def test_plan_canonical_bytes_roundtrip(self):
        from bluefog_tpu.control import TransportPlan

        p = TransportPlan(version=9, round=144, stripes=8,
                          coalesce_bytes=1 << 19)
        q = TransportPlan.from_bytes(p.to_bytes())
        assert p == q and p.to_bytes() == q.to_bytes()


# ---------------------------------------------------------------------------
# 4. compute/gossip overlap (DoubleBuffer)
# ---------------------------------------------------------------------------


class TestOverlapBuffer:
    def _window(self, name, slots=3, n=9):
        from bluefog_tpu.runtime.async_windows import AsyncWindow

        return AsyncWindow(name, n_slots=slots, n_elems=n,
                           dtype=np.float64)

    def test_fold_is_bit_identical_to_serial_at_fixed_seed(self):
        # the byte-identity contract: the staged fold applies the SAME
        # floating-point op sequence as the serial consume over the same
        # landed deposits — per-slot accumulation in deposit order
        # (done by the window table in both paths), fold in slot order
        from bluefog_tpu.runtime.async_windows import DoubleBuffer

        rng = np.random.default_rng(7)
        deposits = [(k, rng.standard_normal(9)) for k in (0, 1, 1, 2, 0)]

        # serial: land everything, read slots in order, fold
        win_s = self._window(_uniq("fp_ser"))
        try:
            for k, v in deposits:
                win_s.deposit(k, v, accumulate=True)
            x_s = np.zeros(8)
            p_s = 1.0
            for k in range(3):
                buf, fresh = win_s.read(k, consume=True)
                if fresh > 0:
                    x_s = x_s + buf[:-1]
                    p_s = p_s + buf[-1]
        finally:
            win_s.free()

        # overlapped: same deposits, harvester staged them under
        # "compute", boundary fold in slot order
        win_o = self._window(_uniq("fp_ovl"))
        db = DoubleBuffer(win_o, [0, 1, 2], 9, poll_s=0.0001)
        try:
            db.begin()
            for k, v in deposits:
                win_o.deposit(k, v, accumulate=True)
            deadline = time.time() + 5.0
            while db.staged_mass() == 0.0 and time.time() < deadline:
                db.begin()
                time.sleep(0.002)
            staged, _busy = db.apply_staged()
            x_o = np.zeros(8)
            p_o = 1.0
            for k, buf, fresh in staged:
                if fresh > 0:
                    x_o = x_o + buf[:-1]
                    p_o = p_o + buf[-1]
        finally:
            db.close()
            win_o.free()

        # bit-identical, not merely close
        assert np.array_equal(x_s, x_o)
        assert p_s == p_o

    def test_close_returns_leftovers_and_is_idempotent(self):
        from bluefog_tpu.runtime.async_windows import DoubleBuffer

        win = self._window(_uniq("fp_close"))
        db = DoubleBuffer(win, [0, 1, 2], 9, poll_s=0.0001)
        try:
            db.begin()
            win.deposit(1, np.full(9, 2.0), accumulate=True)
            deadline = time.time() + 5.0
            while db.staged_mass() == 0.0 and time.time() < deadline:
                time.sleep(0.002)
            left = db.close()
            assert [k for k, _, _ in left] == [1]
            assert float(left[0][1][-1]) == 2.0
            assert db.close() == []  # idempotent, nothing double-drained
        finally:
            win.free()

    def test_overlap_run_exact_mass_and_gauge_thread_mode(self):
        # the runner-level invariant: overlap moves WHEN mixing applies,
        # never mass — a full thread-mode dsgd run with the harvester on
        # conserves sum(p) == n exactly, and reports the overlap gauge
        from bluefog_tpu import topology as T
        from bluefog_tpu.metrics import registry as mreg
        from bluefog_tpu.runtime.async_windows import run_async_dsgd

        reg = mreg.metrics_start()
        try:
            rep = run_async_dsgd(
                T.RingGraph(4), np.ones(6),
                lambda r, s, z: (float(z @ z), 2 * z),
                duration_s=10.0, stop_after_steps=25,
                name=_uniq("fp_run"), overlap=True)
            assert abs(rep.total_mass - 4.0) < 1e-9, rep.total_mass
            # stop_after_steps halts the RUN when the first rank hits
            # the cap; every rank must still have made progress
            assert max(rep.steps_per_rank) >= 25, rep.steps_per_rank
            assert min(rep.steps_per_rank) > 0, rep.steps_per_rank
            snap = reg.snapshot()
            ovs = {k: v for k, v in snap.items()
                   if k.startswith("bf_overlap_fraction")}
            assert ovs, snap  # per-rank gauge was published
            assert all(0.0 <= v <= 1.0 for v in ovs.values()), ovs
        finally:
            mreg.metrics_stop()


# ---------------------------------------------------------------------------
# 5. multi-process acceptance: kill-one-rank under the shm route
# ---------------------------------------------------------------------------


@pytest.mark.chaos
@pytest.mark.skipif(not _NATIVE, reason="shm windows need native runtime")
@pytest.mark.duration_budget(60)  # MP acceptance scenario; subprocess startup dominates
def test_mp_kill_one_rank_shm_route_exact_audit():
    """One of three rank PROCESSES is SIGKILLed mid-dsgd while deposits
    ride the same-host shm fast path and a server-side chaos drop churns
    the TCP leg: survivors heal and rank 0's post-heal mass audit is
    EXACT, with ``bf_shm_deposits_total`` proving the audit really ran
    through shared memory (see ``_mp_fastpath_worker.py``)."""
    import tempfile

    with tempfile.TemporaryDirectory() as bdir:
        worker = os.path.join(_REPO, "tests", "_mp_fastpath_worker.py")
        procs = [
            subprocess.Popen(
                [sys.executable, worker, str(r), "3", bdir, "3.5"],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=clean_env(), cwd=_REPO)
            for r in range(3)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=240)
                outs.append(out)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail("fastpath MP workers timed out:\n"
                        + "\n".join(o or "" for o in outs))
    assert procs[2].returncode == -9, (procs[2].returncode, outs[2])
    for r in (0, 1):
        assert procs[r].returncode == 0, f"worker {r} failed:\n{outs[r]}"
        assert f"FP_MP_OK {r}" in outs[r], outs[r]
