"""Static-analysis suite: each pass must CATCH its seeded violation and
stay quiet on every healthy built-in program.

The violations seeded here are the exact failure classes ISSUE/ADVICE
identified as silent at runtime: overlapping collective-id leases
(skewed-kernel handshake absorption), non-stochastic mixing rows
(per-round parameter rescaling), a disconnected period-union schedule
(rank pairs that never exchange information), and a non-bijective
ppermute (deadlock / double-delivery on a real mesh).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from bluefog_tpu import topology as T
from bluefog_tpu.analysis import (
    GLOBAL_LEASES,
    LeaseRegistry,
    LintError,
    LintReport,
    check_dynamic_schedules,
    check_mixing_matrix,
    check_permutation,
    check_schedule,
    check_topology,
    lint_step_fn,
    plan_gossip_leases,
    spectral_gap,
)
from bluefog_tpu.analysis.lint import run_all
from bluefog_tpu.ops import collectives as C
from bluefog_tpu.ops import pallas_gossip
from bluefog_tpu.optim import (
    GT_COLLECTIVE_ID_RANGES,
    DistributedGradientTrackingOptimizer,
    DistributedNeighborAllreduceOptimizer,
)
from bluefog_tpu.parallel.api import shard_map
from tests._util import REPO, clean_env

AXIS = "bf"


def _codes(diags):
    return {d.code for d in diags}


def _errors(diags):
    return [d for d in diags if d.severity == "error"]


# ---------------------------------------------------------------------------
# collective-id allocator / auditor
# ---------------------------------------------------------------------------


class TestLeaseRegistry:
    def test_overlapping_leases_caught(self):
        reg = LeaseRegistry()
        reg.lease("y_mix", base=1024, used=10, limit=1600)
        reg.lease("params_mix", base=1536, used=10, limit=2048)
        diags = reg.audit()
        assert "BF-ID010" in _codes(_errors(diags))

    def test_disjoint_leases_clean(self):
        reg = LeaseRegistry()
        reg.lease("y_mix", base=1024, used=10, limit=1536)
        reg.lease("params_mix", base=1536, used=10, limit=2048)
        assert not _errors(reg.audit())

    def test_exclusive_group_exempts_switch_branches(self):
        # the branches of one lax.switch are mutually exclusive at runtime
        # and legitimately share a base — same group, no overlap report
        reg = LeaseRegistry()
        reg.lease("dyn[0]", base=1024, used=4, limit=1536,
                  exclusive_group="switch0")
        reg.lease("dyn[1]", base=1024, used=4, limit=1536,
                  exclusive_group="switch0")
        assert not _errors(reg.audit())
        # ...but a DIFFERENT dynamic call sharing the base is still flagged
        reg.lease("dyn2[0]", base=1024, used=4, limit=1536,
                  exclusive_group="switch1")
        assert "BF-ID010" in _codes(_errors(reg.audit()))

    def test_used_overrunning_limit_caught(self):
        reg = LeaseRegistry()
        reg.lease("greedy", base=1024, used=600, limit=1536)
        assert "BF-ID005" in _codes(_errors(reg.audit()))

    def test_base_outside_family_caught(self):
        reg = LeaseRegistry()
        reg.lease("stray", base=100, used=1, limit=2048)
        assert "BF-ID002" in _codes(_errors(reg.audit()))

    def test_window_family_disjoint_from_gossip(self):
        reg = LeaseRegistry()
        reg.lease("gossip", base=1024, used=1024, limit=2048)
        reg.lease("window:w0", base=2048, used=4, limit=3072,
                  family="windows")
        assert not _errors(reg.audit())

    def test_scope_isolates_and_restores(self):
        reg = LeaseRegistry()
        reg.lease("outer", base=1024, used=1, limit=2048)
        with reg.scope():
            assert reg.leases == []
            reg.lease("inner", base=1024, used=1, limit=2048)
            assert [r.owner for r in reg.leases] == ["inner"]
        assert [r.owner for r in reg.leases] == ["outer"]

    def test_plan_gossip_leases_matches_chunk_plan(self):
        tree = {"w": jnp.zeros((1 << 20,), jnp.float32)}  # 4 MiB on wire
        expected = sum(pallas_gossip.leaf_chunk_count(l)
                       for l in jax.tree_util.tree_leaves(tree))
        reg = LeaseRegistry()
        (rec,) = plan_gossip_leases([("opt", tree, (1024, 1536))],
                                    registry=reg)
        assert rec.used == expected
        assert not _errors(reg.audit())


class TestOptimizerLeases:
    def test_gt_declared_ranges_disjoint(self):
        (alo, ahi) = GT_COLLECTIVE_ID_RANGES["y_mix"]
        (blo, bhi) = GT_COLLECTIVE_ID_RANGES["params_mix"]
        assert min(ahi, bhi) <= max(alo, blo)  # no overlap
        assert alo >= 1024 and bhi <= 2048

    def test_gt_split_audits_clean_at_scale(self):
        # ResNet-18-sized fused buffer: the configuration ADVICE.md's
        # medium finding showed could silently overlap pre-limit
        fused = {"p": jnp.zeros((11_000_000,), jnp.float32)}
        with GLOBAL_LEASES.scope() as reg:
            plan_gossip_leases(
                [("gt/y_mix", fused, GT_COLLECTIVE_ID_RANGES["y_mix"]),
                 ("gt/params_mix", fused,
                  GT_COLLECTIVE_ID_RANGES["params_mix"])],
                registry=reg)
            assert not _errors(reg.audit())


# ---------------------------------------------------------------------------
# topology verifier
# ---------------------------------------------------------------------------


class TestTopologyChecks:
    def test_non_stochastic_matrix_caught(self):
        w = np.full((4, 4), 0.5)  # rows sum to 2
        diags = check_mixing_matrix(w, name="bad_rows")
        assert "BF-TOPO003" in _codes(_errors(diags))

    def test_negative_weight_caught(self):
        w = np.eye(4)
        w[0, 0], w[0, 1] = 1.5, -0.5
        assert "BF-TOPO002" in _codes(_errors(check_mixing_matrix(w)))

    def test_disconnected_graph_caught(self):
        # two isolated 2-cliques: stochastic but consensus splits
        block = np.full((2, 2), 0.5)
        w = np.block([[block, np.zeros((2, 2))],
                      [np.zeros((2, 2)), block]])
        diags = check_mixing_matrix(w, name="split")
        assert "BF-TOPO007" in _codes(_errors(diags))

    def test_zero_diagonal_caught(self):
        w = np.array([[0.0, 1.0], [1.0, 0.0]])  # periodic: oscillates
        assert "BF-TOPO005" in _codes(_errors(check_mixing_matrix(w)))

    def test_row_only_stochastic_warns_not_errors(self):
        star = T.StarGraph(8, center_rank=0)
        diags = check_topology(star)
        assert not _errors(diags)
        assert "BF-TOPO004" in {d.code for d in diags
                                if d.severity == "warning"}

    def test_require_doubly_stochastic_promotes_to_error(self):
        star = T.StarGraph(8, center_rank=0)
        diags = check_topology(star, require_doubly_stochastic=True)
        assert "BF-TOPO004" in _codes(_errors(diags))

    @pytest.mark.parametrize("size", [2, 4, 8])
    def test_all_builtin_topologies_clean(self, size):
        for topo in [
            T.ExponentialTwoGraph(size),
            T.ExponentialGraph(size, base=2),
            T.SymmetricExponentialGraph(size),
            T.RingGraph(size, 0),
            T.RingGraph(size, 1),
            T.RingGraph(size, 2),
            T.MeshGrid2DGraph(size),
            T.StarGraph(size),
            T.FullyConnectedGraph(size),
        ]:
            assert not _errors(check_topology(topo)), topo.name
            assert not _errors(check_schedule(T.build_schedule(topo))), \
                topo.name

    def test_spectral_gap_extremes(self):
        assert spectral_gap(T.FullyConnectedGraph(8)) == pytest.approx(1.0)
        block = np.full((2, 2), 0.5)
        split = np.block([[block, np.zeros((2, 2))],
                          [np.zeros((2, 2)), block]])
        assert spectral_gap(split) == pytest.approx(0.0, abs=1e-9)

    def test_non_permutation_schedule_slot_caught(self):
        good = T.build_schedule(T.RingGraph(8, 1))
        bad = T.GossipSchedule(
            size=8,
            perms=(((0, 1), (0, 2)),),  # rank 0 sends twice in one slot
            self_weights=good.self_weights,
            recv_weights=good.recv_weights,
            recv_src=good.recv_src,
            is_circulant=False,
            name="bad")
        assert "BF-TOPO010" in _codes(_errors(check_schedule(bad)))


class TestDynamicSchedules:
    def test_builtin_one_peer_periods_clean(self):
        for name, topos in [
            ("one_peer_exp2", T.one_peer_exponential_two_schedules(8)),
            ("one_peer_ring", T.one_peer_ring_schedules(8)),
        ]:
            diags = check_dynamic_schedules(topos, name=name)
            assert not _errors(diags), name
            assert "BF-TOPO101" in _codes(diags)

    def test_disconnected_period_union_caught(self):
        # every phase only pairs (0,1) and (2,3): ranks {0,1} and {2,3}
        # never exchange information no matter how long training runs
        pair = np.block([[np.full((2, 2), 0.5), np.zeros((2, 2))],
                         [np.zeros((2, 2)), np.full((2, 2), 0.5)]])
        diags = check_dynamic_schedules([pair, pair], name="never_crosses")
        assert "BF-TOPO022" in _codes(_errors(diags))

    def test_empty_schedule_caught(self):
        assert "BF-TOPO020" in _codes(_errors(check_dynamic_schedules([])))

    def test_per_phase_disconnection_allowed(self):
        # one-peer phases are individually disconnected BY DESIGN; only
        # the union matters — no BF-TOPO007 from any phase
        topos = T.one_peer_exponential_two_schedules(8)
        diags = check_dynamic_schedules(topos, name="one_peer")
        assert "BF-TOPO007" not in _codes(diags)


# ---------------------------------------------------------------------------
# jaxpr comm-lint
# ---------------------------------------------------------------------------


def _mesh(devices8):
    return Mesh(np.array(devices8), (AXIS,))


def _smap(mesh, body):
    return shard_map(body, mesh=mesh, in_specs=(P(AXIS),),
                     out_specs=P(AXIS), check_vma=False)


class TestJaxprLint:
    def test_check_permutation_duplicates(self):
        diags = check_permutation([(0, 1), (0, 2)], 4)
        assert "BF-COMM001" in _codes(_errors(diags))
        diags = check_permutation([(0, 2), (1, 2)], 4)
        assert "BF-COMM001" in _codes(_errors(diags))
        assert not _errors(check_permutation([(0, 1), (1, 0)], 4))

    def test_check_permutation_out_of_range(self):
        assert "BF-COMM003" in _codes(
            _errors(check_permutation([(0, 9)], 8)))

    def test_non_bijective_ppermute_in_traced_step_caught(self, devices8):
        # jax traces a duplicate-destination perm cleanly — the lint is
        # the only pre-run check (module docstring's motivating case)
        mesh = _mesh(devices8)

        def bad_step(x):
            return lax.ppermute(x, AXIS, [(0, 3), (1, 3), (2, 4)])

        diags = lint_step_fn(_smap(mesh, bad_step),
                             jnp.zeros((8, 4)), name="bad_step")
        assert "BF-COMM001" in _codes(_errors(diags))

    def test_gossip_step_clean(self, devices8):
        mesh = _mesh(devices8)
        sched = T.build_schedule(T.ExponentialTwoGraph(8))

        def step(x):
            return C.neighbor_allreduce(x, sched, AXIS)

        diags = lint_step_fn(_smap(mesh, step), jnp.zeros((8, 4)),
                             name="gossip")
        assert not _errors(diags)
        assert "BF-COMM100" in _codes(diags)

    def test_host_callback_warned(self, devices8):
        mesh = _mesh(devices8)

        def chatty(x):
            jax.debug.callback(lambda v: None, x)
            return x

        diags = lint_step_fn(_smap(mesh, chatty), jnp.zeros((8, 4)),
                             name="chatty")
        assert "BF-COMM010" in {d.code for d in diags
                                if d.severity == "warning"}

    def test_trace_failure_is_a_diagnostic_not_a_crash(self):
        def broken(x):
            raise RuntimeError("boom")

        diags = lint_step_fn(broken, jnp.zeros(4), name="broken")
        assert "BF-COMM020" in _codes(_errors(diags))

    @pytest.mark.parametrize("make_opt", [
        lambda: DistributedNeighborAllreduceOptimizer(
            optax.sgd(0.05), topology=T.ExponentialTwoGraph(8),
            axis_name=AXIS),
        lambda: DistributedGradientTrackingOptimizer(
            optax.sgd(0.05), T.MeshGrid2DGraph(8), AXIS),
    ], ids=["dsgd", "gradient_tracking"])
    def test_distributed_optimizers_lint_clean(self, devices8, make_opt):
        mesh = _mesh(devices8)
        opt = make_opt()

        def body(c):
            w0 = jnp.zeros_like(c)
            st = opt.init(w0)

            def step(carry, _):
                w, s = carry
                upd, s = opt.update(w - c, s, w)
                return (optax.apply_updates(w, upd), s), None

            (w, _), _ = lax.scan(step, (w0, st), None, length=2)
            return w

        diags = lint_step_fn(_smap(mesh, body), jnp.zeros((8, 4)),
                             name="opt_step")
        assert not _errors(diags)


# ---------------------------------------------------------------------------
# op-layer integration: collective_id_limit (the ADVICE fixes)
# ---------------------------------------------------------------------------


class TestCollectiveIdLimit:
    def test_forced_pallas_over_limit_raises(self, monkeypatch):
        # a 2 KiB cap makes an 8K-float leaf need >1024 invocations: the
        # plan can NEVER fit the gossip family, so forced pallas must
        # refuse at trace time rather than bleed into sibling ids
        monkeypatch.setenv("BLUEFOG_TPU_PALLAS_MAX_BYTES", "2048")
        sched = T.build_schedule(T.RingGraph(8, 1))
        x = jnp.zeros((1 << 20,), jnp.float32)
        with pytest.raises(ValueError, match="collective-id limit"):
            C.neighbor_allreduce(x, sched, AXIS, backend="pallas")

    def test_forced_pallas_respects_caller_limit(self, monkeypatch):
        # fits the family bound [1024, 2048) but NOT the caller's
        # [1024, 1040) lease — the pre-fix code would accept this and
        # overlap the sibling's ids (ADVICE medium)
        monkeypatch.setenv("BLUEFOG_TPU_PALLAS_MAX_BYTES", str(64 << 10))
        sched = T.build_schedule(T.RingGraph(8, 1))
        x = jnp.zeros((1 << 20,), jnp.float32)  # 4 MiB -> 64 invocations
        with pytest.raises(ValueError, match="collective-id limit"):
            C.neighbor_allreduce(x, sched, AXIS, backend="pallas",
                                 collective_id_base=1024,
                                 collective_id_limit=1040)

    def test_auto_over_limit_falls_back_to_xla(self, devices8, monkeypatch):
        # on backend='auto' an over-limit chunk plan must take the
        # (slower, correct) XLA path instead of hard-failing the run
        # (ADVICE low).  CPU auto-resolves to XLA before the chunk plan,
        # so force the pallas resolution to reach the fallback branch.
        monkeypatch.setattr(pallas_gossip, "on_tpu_platform", lambda: True)
        monkeypatch.setenv("BLUEFOG_TPU_PALLAS_MAX_BYTES", "2048")
        mesh = _mesh(devices8)
        sched = T.build_schedule(T.RingGraph(8, 1))
        x = jnp.arange(8 * (1 << 20), dtype=jnp.float32)
        x = x.reshape(8, -1) / x.size

        out = _smap(mesh, lambda v: C.neighbor_allreduce(
            v, sched, AXIS, backend="auto"))(x)
        ref = _smap(mesh, lambda v: C.neighbor_allreduce(
            v, sched, AXIS, backend="xla"))(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-6)

    def test_bad_limit_rejected(self, monkeypatch):
        monkeypatch.setenv("BLUEFOG_TPU_PALLAS_MAX_BYTES", str(4 << 20))
        sched = T.build_schedule(T.RingGraph(8, 1))
        with pytest.raises(ValueError, match="must lie in"):
            C.neighbor_allreduce(jnp.zeros(16), sched, AXIS,
                                 backend="pallas",
                                 collective_id_base=1536,
                                 collective_id_limit=1536)


# ---------------------------------------------------------------------------
# report plumbing + CLI
# ---------------------------------------------------------------------------


class TestReport:
    def test_raise_if_errors(self):
        from bluefog_tpu.analysis import Diagnostic

        rep = LintReport([Diagnostic("error", "BF-ID010", "overlap")])
        assert not rep.ok
        with pytest.raises(LintError, match="BF-ID010"):
            rep.raise_if_errors()
        assert LintReport([Diagnostic("info", "BF-ID100", "fine")]).ok

    def test_invalid_severity_rejected(self):
        from bluefog_tpu.analysis import Diagnostic

        with pytest.raises(ValueError):
            Diagnostic("fatal", "BF-X", "nope")


class TestLintCli:
    def test_run_all_clean_on_own_programs(self):
        # the acceptance bar: every pass green over the repo's own
        # topologies, optimizers, and examples (trace pass included)
        report = run_all(size=8)
        assert report.ok, report.format()

    # pre-existing heavyweight (a fresh interpreter + the full
    # no-trace sweep): ~20s under full-suite load, and each new lint
    # pass (13 now, protocol pass included) legitimately extends it —
    # load-bearing tier-1 coverage, so a reviewed override instead of
    # slow-marking
    @pytest.mark.duration_budget(60)
    def test_cli_exits_zero(self):
        # the tier-1/CI hook: the module CLI itself (subprocess, fresh
        # interpreter) must exit 0 on the repo as committed.  --no-trace
        # keeps it to seconds; the traced passes run in-process above.
        proc = subprocess.run(
            [sys.executable, "-m", "bluefog_tpu.analysis.lint",
             "--no-trace", "--size", "8"],
            capture_output=True, text=True, timeout=300,
            cwd=REPO, env=clean_env())
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "lint: OK" in proc.stdout


# ---------------------------------------------------------------------------
# satellite guards (ADVICE lows)
# ---------------------------------------------------------------------------


class TestMoEGuards:
    def test_top2_router_rejects_single_expert(self):
        from bluefog_tpu.ops.moe import top2_router

        with pytest.raises(ValueError, match="num_experts >= 2"):
            top2_router(jnp.zeros((4, 8)), jnp.zeros((8, 1)),
                        num_experts=1, capacity=4)

    def test_moe_config_rejects_top2_single_expert(self):
        from bluefog_tpu.models.moe import GPTConfig, MoEConfig

        with pytest.raises(ValueError, match="num_experts >= 2"):
            MoEConfig(gpt=GPTConfig.tiny(), num_experts=1, router="top2")
        with pytest.raises(ValueError, match="unknown router"):
            MoEConfig(gpt=GPTConfig.tiny(), num_experts=4, router="top3")


# ---------------------------------------------------------------------------
# BF-WIN: pipelined window deposits must fence before their barrier
# ---------------------------------------------------------------------------


class TestWindowLint:
    def test_seeded_violation_unfenced_deposits(self):
        # the exact bug the rule exists for: fire-and-forget deposits, a
        # barrier that the mass audit trusts, and no flush in between
        from bluefog_tpu.analysis.window_lint import check_pipelined_flush

        src = (
            "def loop(peers, slots, payload, barrier, win, n_in):\n"
            "    for step in range(100):\n"
            "        for j in peers:\n"
            "            peers[j].deposit_async(slots[j], payload)\n"
            "    barrier.wait('stopped')\n"
            "    for k in range(n_in):\n"
            "        win.read(k, consume=True)\n"
        )
        diags = check_pipelined_flush(src, filename="seeded.py")
        assert any(d.code == "BF-WIN001" and d.severity == "error"
                   for d in diags), [d.format() for d in diags]

    def test_fenced_loop_is_clean(self):
        from bluefog_tpu.analysis.window_lint import check_pipelined_flush

        src = (
            "def loop(peers, slots, payload, barrier):\n"
            "    for step in range(100):\n"
            "        for j in peers:\n"
            "            peers[j].deposit_async(slots[j], payload)\n"
            "    for j in peers:\n"
            "        peers[j].flush()\n"
            "    barrier.wait('stopped')\n"
        )
        assert not check_pipelined_flush(src, filename="clean.py")

    def test_never_fenced_deposits_warn(self):
        from bluefog_tpu.analysis.window_lint import check_pipelined_flush

        src = (
            "def fire(peer, payload):\n"
            "    peer.deposit_async(0, payload)\n"
        )
        diags = check_pipelined_flush(src, filename="warn.py")
        assert [d.code for d in diags] == ["BF-WIN002"]
        assert diags[0].severity == "warning"

    def test_pipelined_ctor_receiver_deposit_counts(self):
        # .deposit() on a name bound from PipelinedRemoteWindow(...) is a
        # pipelined site too (the sync-spelling trap)
        from bluefog_tpu.analysis.window_lint import check_pipelined_flush

        src = (
            "def loop(addr, payload, barrier):\n"
            "    pw = PipelinedRemoteWindow(addr, 'w')\n"
            "    pw.deposit_async(0, payload)\n"
            "    barrier.wait('stopped')\n"
        )
        diags = check_pipelined_flush(src, filename="ctor.py")
        assert any(d.code == "BF-WIN001" for d in diags)

    def test_real_dsgd_loop_is_fenced(self):
        # the repo's own mp-dsgd body deposits pipelined and MUST stay
        # fenced — this is the regression tripwire for future edits
        import inspect

        from bluefog_tpu.analysis.window_lint import check_pipelined_flush
        from bluefog_tpu.runtime import async_windows

        diags = check_pipelined_flush(
            inspect.getsource(async_windows), filename="async_windows.py")
        assert not [d for d in diags if d.severity == "error"], \
            [d.format() for d in diags]

    def test_nested_deposit_closure_exempt_from_never_fenced(self):
        # a deposit closure whose CALLER fences (the bench's one_round
        # shape) must not trip BF-WIN002; BF-WIN001 still applies when
        # the closure itself races a barrier
        from bluefog_tpu.analysis.window_lint import check_pipelined_flush

        src = (
            "def run(stream, names, payloads):\n"
            "    def one_round():\n"
            "        for nm, p in zip(names, payloads):\n"
            "            stream.deposit_async(nm, 0, p)\n"
            "    for _ in range(10):\n"
            "        one_round()\n"
            "    stream.flush()\n"
        )
        assert not check_pipelined_flush(src, filename="closure.py")

    def test_window_pass_runs_in_sweep(self):
        # the bflint-tpu sweep includes the window pass (BF-WIN100 info)
        # and reports NO warnings of its own on the repo as committed
        # (false positives would break warnings-as-errors gating)
        report = run_all(size=8, trace=False)
        assert report.has("BF-WIN100"), report.format(verbose=True)
        assert report.ok, report.format()
        assert not [d for d in report.warnings
                    if d.code.startswith("BF-WIN")], report.format()

    def test_seeded_violation_mid_step_staged_apply(self):
        # BF-WIN004: folding the overlap buffer's staged round-(k-1)
        # mass from a hot-loop helper with no boundary vocabulary —
        # stale mixing applied mid-step
        from bluefog_tpu.analysis.window_lint import check_pipelined_flush

        src = (
            "def step(db, x, p):\n"
            "    staged, busy = db.apply_staged()\n"
            "    for k, buf, fresh in staged:\n"
            "        x += buf[:-1]\n"
            "        p += buf[-1]\n"
        )
        diags = check_pipelined_flush(src, filename="seeded.py")
        assert any(d.code == "BF-WIN004" and d.severity == "error"
                   for d in diags), [d.format() for d in diags]

    def test_boundary_named_staged_apply_is_clean(self):
        # the sanctioned shape: the apply lives in a function whose name
        # carries the round-boundary vocabulary (the runner's
        # fold_staged_at_round_boundary closure); module level is NOT ok
        from bluefog_tpu.analysis.window_lint import check_pipelined_flush

        src = (
            "def fold_staged_at_round_boundary(db, x, p):\n"
            "    staged, busy = db.apply_staged()\n"
            "    for k, buf, fresh in staged:\n"
            "        x += buf[:-1]\n"
            "        p += buf[-1]\n"
            "    return p\n"
        )
        assert not check_pipelined_flush(src, filename="clean.py")
        diags = check_pipelined_flush("db.apply_staged()\n",
                                      filename="mod.py")
        assert [d.code for d in diags] == ["BF-WIN004"]

    def test_overlap_apply_sites_are_boundary_only_in_repo(self):
        # repo-clean: both runners' overlap folds must keep their
        # boundary-vocabulary names — a rename or a new mid-loop call
        # site of apply_staged trips this before it ships
        import inspect

        from bluefog_tpu.analysis.window_lint import check_pipelined_flush
        from bluefog_tpu.runtime import async_windows

        src = inspect.getsource(async_windows)
        assert "apply_staged" in src  # the overlap path exists
        diags = check_pipelined_flush(src, filename="async_windows.py")
        assert not [d for d in diags if d.code == "BF-WIN004"], \
            [d.format() for d in diags]


# ---------------------------------------------------------------------------
# BF-RES: reconnect/retry loops must carry a budget or deadline
# ---------------------------------------------------------------------------


class TestResilienceLint:
    def test_seeded_violation_unbounded_reconnect(self):
        # the exact bug the rule exists for: while True around a connect
        # with no budget — the peer is never declared DEAD, the gossip
        # never heals, and a restarting peer's port is hammered forever
        from bluefog_tpu.analysis.resilience_lint import check_retry_budgets

        src = (
            "import socket\n"
            "def reconnect_forever(addr):\n"
            "    while True:\n"
            "        try:\n"
            "            return socket.create_connection(addr)\n"
            "        except OSError:\n"
            "            pass\n"
        )
        diags = check_retry_budgets(src, filename="seeded.py")
        assert any(d.code == "BF-RES001" and d.severity == "error"
                   for d in diags), [d.format() for d in diags]

    def test_itertools_count_is_unbounded_too(self):
        from bluefog_tpu.analysis.resilience_lint import check_retry_budgets

        src = (
            "import itertools, socket\n"
            "def reconnect(addr):\n"
            "    for _ in itertools.count():\n"
            "        try:\n"
            "            return socket.create_connection(addr)\n"
            "        except OSError:\n"
            "            pass\n"
        )
        diags = check_retry_budgets(src, filename="count.py")
        assert any(d.code == "BF-RES001" for d in diags)

    def test_backoff_iteration_is_clean(self):
        # the blessed shape: iterate a resilience.Backoff (budget by
        # construction) — exactly what DepositStream._recover does
        from bluefog_tpu.analysis.resilience_lint import check_retry_budgets

        src = (
            "import socket\n"
            "from bluefog_tpu.runtime.resilience import Backoff\n"
            "def reconnect(addr):\n"
            "    for delay in Backoff(budget=5):\n"
            "        try:\n"
            "            return socket.create_connection(addr)\n"
            "        except OSError:\n"
            "            continue\n"
        )
        assert not check_retry_budgets(src, filename="clean.py")

    def test_bounded_for_and_explicit_counter_are_clean(self):
        from bluefog_tpu.analysis.resilience_lint import check_retry_budgets

        src = (
            "import socket\n"
            "def a(addr):\n"
            "    for _ in range(5):\n"
            "        try:\n"
            "            return socket.create_connection(addr)\n"
            "        except OSError:\n"
            "            pass\n"
            "def b(addr, max_attempts):\n"
            "    attempts = 0\n"
            "    while True:\n"
            "        attempts += 1\n"
            "        if attempts > max_attempts:\n"
            "            raise OSError('unreachable')\n"
            "        try:\n"
            "            return socket.create_connection(addr)\n"
            "        except OSError:\n"
            "            pass\n"
        )
        assert not check_retry_budgets(src, filename="bounded.py")

    def test_plain_loops_without_connect_ignored(self):
        from bluefog_tpu.analysis.resilience_lint import check_retry_budgets

        src = (
            "def serve(sock):\n"
            "    while True:\n"
            "        data = sock.recv(4096)\n"
            "        if not data:\n"
            "            return\n"
        )
        assert not check_retry_budgets(src, filename="serve.py")

    def test_resilience_pass_runs_in_sweep_and_repo_is_clean(self):
        # the bflint-tpu sweep includes the pass (BF-RES100 info) and
        # the repo's own runtime — including DepositStream._recover and
        # run_supervised's restart loop — lints clean, for BOTH rules
        # (unbounded retries AND mid-round admissions)
        report = run_all(size=8, trace=False)
        assert report.has("BF-RES100"), report.format(verbose=True)
        assert not [d for d in report.diagnostics
                    if d.code in ("BF-RES001", "BF-RES002")], \
            report.format()


class TestAdmissionLint:
    """BF-RES002: an admission path without a round-boundary/quiesce
    marker is an error — re-admitting a peer mid-round changes the
    mixing weights under in-flight deposits (the torn state the exact
    mass audit exists to catch)."""

    def test_seeded_violation_midround_admission(self):
        from bluefog_tpu.analysis.resilience_lint import (
            check_admission_paths)

        src = (
            "def readmit_peer(board, peer):\n"
            "    if board.state(peer) == 3:\n"
            "        board.admit(peer)\n"
        )
        diags = check_admission_paths(src, filename="seeded.py")
        assert any(d.code == "BF-RES002" and d.severity == "error"
                   for d in diags), [d.format() for d in diags]

    def test_fenced_admission_is_clean(self):
        # the blessed shape: fence/flush (or a heal/replan/barrier) in
        # the same function marks the round boundary
        from bluefog_tpu.analysis.resilience_lint import (
            check_admission_paths)

        src = (
            "def gossip_round(board, peer, peers):\n"
            "    for h in peers:\n"
            "        h.flush()\n"
            "    board.admit(peer)\n"
        )
        assert not check_admission_paths(src, filename="clean.py")

    def test_heal_vocabulary_marks_the_boundary(self):
        from bluefog_tpu.analysis.resilience_lint import (
            check_admission_paths)

        src = (
            "def boundary(board, topo, dead, rejoined):\n"
            "    plan = heal(topo, dead - rejoined)\n"
            "    for j in rejoined:\n"
            "        board.admit(j)\n"
            "    return plan\n"
        )
        assert not check_admission_paths(src, filename="healclean.py")

    def test_state_machine_primitive_is_exempt(self):
        # the definition of admit() itself cannot mention its caller's
        # barrier — the rule is for callers
        from bluefog_tpu.analysis.resilience_lint import (
            check_admission_paths)

        src = (
            "class Core:\n"
            "    def admit(self):\n"
            "        self._set(0, admitted=True)\n"
        )
        assert not check_admission_paths(src, filename="prim.py")

    def test_functions_without_admission_ignored(self):
        from bluefog_tpu.analysis.resilience_lint import (
            check_admission_paths)

        src = (
            "def plain(x):\n"
            "    return x + 1\n"
        )
        assert not check_admission_paths(src, filename="plain.py")


# ---------------------------------------------------------------------------
# BF-CTL: controller actuation only at round boundaries
# ---------------------------------------------------------------------------


class TestControlLint:
    """BF-CTL001: a CommPlan actuation (apply_plan / set_comm_every /
    set_codec / *actuate*) outside a round-boundary/quiesce context is
    an error — the BF-RES002 invariant on the control plane."""

    def test_seeded_violation_midround_actuation(self):
        from bluefog_tpu.analysis.control_lint import check_actuation_paths

        src = (
            "def retune(ctl, topo, members):\n"
            "    topo2 = ctl.apply_plan(topology=topo, members=members)\n"
            "    return topo2\n"
        )
        diags = check_actuation_paths(src, filename="seeded.py")
        assert any(d.code == "BF-CTL001" and d.severity == "error"
                   for d in diags), [d.format() for d in diags]

    def test_seeded_violation_midround_codec_and_cadence(self):
        from bluefog_tpu.analysis.control_lint import check_actuation_paths

        for call in ("stream.set_codec('f32')",
                     "set_comm_every(state, 4)"):
            src = f"def tune(stream, state):\n    {call}\n"
            diags = check_actuation_paths(src, filename="seeded2.py")
            assert any(d.code == "BF-CTL001" for d in diags), call

    def test_boundary_vocabulary_is_clean(self):
        from bluefog_tpu.analysis.control_lint import check_actuation_paths

        src = (
            "def actuate_at_round_boundary(ctl, topo, members, peers):\n"
            "    for h in peers:\n"
            "        h.flush()\n"
            "    return ctl.apply_plan(topology=topo, members=members)\n"
        )
        assert not check_actuation_paths(src, filename="clean.py")

    def test_boundary_vocabulary_matches_whole_words_only(self):
        # `background` must not pass as "round", `self.health` as
        # "heal", `flushed_bytes` as "flush" — the serving-lint
        # whole-word discipline applies here too
        from bluefog_tpu.analysis.control_lint import check_actuation_paths

        src = (
            "def tune(ctl, topo, members, background, flushed_bytes):\n"
            "    if ctl.health and background:\n"
            "        return ctl.apply_plan(topology=topo,\n"
            "                              members=members)\n"
        )
        diags = check_actuation_paths(src, filename="sneaky.py")
        assert any(d.code == "BF-CTL001" for d in diags), \
            [d.format() for d in diags]
        # while real snake-case markers still pass
        src_ok = (
            "def tune_at_round_boundary(ctl, topo, members):\n"
            "    return ctl.apply_plan(topology=topo, members=members)\n"
        )
        assert not check_actuation_paths(src_ok, filename="ok.py")

    def test_actuation_primitive_itself_is_exempt(self):
        from bluefog_tpu.analysis.control_lint import check_actuation_paths

        src = (
            "class CommController:\n"
            "    def apply_plan(self, *, topology, members):\n"
            "        return plan_topology(topology, members, self.plan)\n"
        )
        assert not check_actuation_paths(src, filename="prim.py")

    def test_functions_without_actuation_ignored(self):
        from bluefog_tpu.analysis.control_lint import check_actuation_paths

        assert not check_actuation_paths(
            "def plain(x):\n    return x + 1\n", filename="plain.py")

    def test_repo_control_surfaces_clean(self):
        """The sweep's own targets — the control package and the
        runtime loops it is wired into — carry no BF-CTL001."""
        import glob

        from bluefog_tpu.analysis.control_lint import check_file

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        targets = sorted(glob.glob(os.path.join(
            root, "bluefog_tpu", "control", "*.py")))
        targets += sorted(glob.glob(os.path.join(
            root, "bluefog_tpu", "runtime", "*.py")))
        assert targets
        errs = [d for p in targets for d in check_file(p)
                if d.severity == "error"]
        assert not errs, [d.format() for d in errs]


# ---------------------------------------------------------------------------
# BF-SRV: snapshot consumers must check the round stamp
# ---------------------------------------------------------------------------


class TestServingLint:
    def test_seeded_violation_blind_consumer(self):
        # the exact bug the rule exists for: pull a snapshot, serve its
        # leaves, never look at the round — warm-up garbage and stale
        # models get served silently
        from bluefog_tpu.analysis.serving_lint import (
            check_snapshot_consumers)

        src = (
            "import bluefog_tpu.serving as serving\n"
            "\n"
            "def serve(client, inp):\n"
            "    snap = client.snapshot()\n"
            "    return snap.leaves['x'] @ inp\n"
        )
        diags = check_snapshot_consumers(src, filename="seeded.py")
        assert any(d.code == "BF-SRV001" and d.severity == "error"
                   for d in diags), [d.format() for d in diags]

    def test_round_checked_consumer_is_clean(self):
        from bluefog_tpu.analysis.serving_lint import (
            check_snapshot_consumers)

        src = (
            "import bluefog_tpu.serving as serving\n"
            "\n"
            "def serve(client, inp, cursor):\n"
            "    snap = client.snapshot()\n"
            "    if snap.round <= cursor:\n"
            "        return None\n"
            "    return snap.leaves['x'] @ inp\n"
        )
        assert not check_snapshot_consumers(src, filename="clean.py")

    def test_min_round_kwarg_delegates_the_check(self):
        # min_round=/pin_round= on the call IS the check (the client
        # enforces the bound); no further vocabulary required
        from bluefog_tpu.analysis.serving_lint import (
            check_snapshot_consumers)

        src = (
            "def serve(addr, inp, floor):\n"
            "    c = SnapshotClient(addr, 'job:0')\n"
            "    snap = c.snapshot(min_round=floor)\n"
            "    return snap.leaves['x'] @ inp\n"
        )
        assert not check_snapshot_consumers(src, filename="kwarg.py")

    def test_retriable_handler_counts_as_checking(self):
        from bluefog_tpu.analysis.serving_lint import (
            check_snapshot_consumers)

        src = (
            "import bluefog_tpu.serving as serving\n"
            "\n"
            "def serve(client, inp):\n"
            "    try:\n"
            "        snap = client.snapshot()\n"
            "    except serving.SnapshotUnavailable:\n"
            "        return None\n"
            "    return snap.leaves['x'] @ inp\n"
        )
        assert not check_snapshot_consumers(src, filename="handler.py")

    def test_unrelated_snapshot_apis_not_flagged(self):
        # metrics.export.snapshot() (and anything else named snapshot)
        # is out of scope unless the module imports bluefog_tpu.serving
        # or the receiver is a SnapshotClient
        from bluefog_tpu.analysis.serving_lint import (
            check_snapshot_consumers)

        src = (
            "def export(registry):\n"
            "    return registry.snapshot()\n"
        )
        assert not check_snapshot_consumers(src, filename="metrics.py")

    def test_serving_pass_runs_in_sweep(self):
        # the bflint-tpu sweep includes the serving pass (BF-SRV100
        # info) and reports NO BF-SRV findings on the repo as committed
        report = run_all(size=8, trace=False)
        assert report.has("BF-SRV100"), report.format(verbose=True)
        assert report.ok, report.format()
        assert not [d for d in report.warnings
                    if d.code.startswith("BF-SRV")], report.format()

    def test_round_substring_does_not_suppress(self):
        # 'background'/'workaround' contain 'round' as a substring —
        # they are NOT a round-stamp check and must not silence the rule
        from bluefog_tpu.analysis.serving_lint import (
            check_snapshot_consumers)

        src = (
            "import bluefog_tpu.serving as serving\n"
            "\n"
            "def serve(client, background, workaround):\n"
            "    snap = client.snapshot()\n"
            "    return snap.leaves['x'] + background + workaround\n"
        )
        diags = check_snapshot_consumers(src, filename="substr.py")
        assert any(d.code == "BF-SRV001" for d in diags), \
            [d.format() for d in diags]

    def test_rounds_plural_word_counts(self):
        from bluefog_tpu.analysis.serving_lint import (
            check_snapshot_consumers)

        src = (
            "import bluefog_tpu.serving as serving\n"
            "\n"
            "def serve(client, replica, live):\n"
            "    snap = client.snapshot()\n"
            "    if replica.staleness_rounds(live) > 4:\n"
            "        return None\n"
            "    return snap.leaves['x']\n"
        )
        assert not check_snapshot_consumers(src, filename="plural.py")


# ---------------------------------------------------------------------------
# Pass 8: whole-repo concurrency lint (BF-CONC)
# ---------------------------------------------------------------------------


class TestConcurrencyLint:
    """Each BF-CONC rule must CATCH its seeded violation, honor its
    waiver, stay quiet on the healthy shape — and the repo as committed
    must sweep clean."""

    def _check(self, src, filename="seed.py"):
        from bluefog_tpu.analysis.concurrency_lint import check_sources

        return check_sources([(filename, src)])

    def test_seeded_abba_cycle_is_error(self):
        # the textbook deadlock: two locks nested in opposite orders on
        # two code paths of the same class
        src = (
            "import threading\n"
            "\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "\n"
            "    def fwd(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n"
            "\n"
            "    def rev(self):\n"
            "        with self._b:\n"
            "            with self._a:\n"
            "                pass\n"
        )
        model, diags = self._check(src)
        errs = [d for d in _errors(diags) if d.code == "BF-CONC001"]
        assert errs, [d.format() for d in diags]
        assert "opposite orders" in errs[0].message
        # both edges are in the model, and the cycle names both locks
        assert ("seed.S._a", "seed.S._b") in model.edges
        assert ("seed.S._b", "seed.S._a") in model.edges

    def test_consistent_order_is_clean(self):
        # same two locks, same nesting direction everywhere: no cycle
        src = (
            "import threading\n"
            "\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "\n"
            "    def fwd(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n"
            "\n"
            "    def also_fwd(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n"
        )
        _, diags = self._check(src)
        assert not _errors(diags), [d.format() for d in diags]

    def test_long_cycle_is_not_length_capped(self):
        # a 5-way ring of nestings (a->b->c->d->e->a) deadlocks just
        # like ABBA; the cycle search must not silently cap the length
        src = (
            "import threading\n"
            "\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "        self._c = threading.Lock()\n"
            "        self._d = threading.Lock()\n"
            "        self._e = threading.Lock()\n"
            "\n"
            "    def ab(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n"
            "\n"
            "    def bc(self):\n"
            "        with self._b:\n"
            "            with self._c:\n"
            "                pass\n"
            "\n"
            "    def cd(self):\n"
            "        with self._c:\n"
            "            with self._d:\n"
            "                pass\n"
            "\n"
            "    def de(self):\n"
            "        with self._d:\n"
            "            with self._e:\n"
            "                pass\n"
            "\n"
            "    def ea(self):\n"
            "        with self._e:\n"
            "            with self._a:\n"
            "                pass\n"
        )
        model, diags = self._check(src)
        assert len(model.find_cycles()) == 1
        errs = [d for d in _errors(diags) if d.code == "BF-CONC001"]
        assert errs, [d.format() for d in diags]
        assert "opposite orders" in errs[0].message

    def test_self_deadlock_through_helper_is_error(self):
        # the PR-1 engine() shape: a plain Lock re-acquired through a
        # same-module helper called inside the critical section
        src = (
            "import threading\n"
            "\n"
            "_mu = threading.Lock()\n"
            "\n"
            "def helper():\n"
            "    with _mu:\n"
            "        pass\n"
            "\n"
            "def outer():\n"
            "    with _mu:\n"
            "        helper()\n"
        )
        _, diags = self._check(src)
        errs = [d for d in _errors(diags) if d.code == "BF-CONC001"]
        assert errs, [d.format() for d in diags]
        assert "re-acquired" in errs[0].message

    def test_rlock_reentry_is_legal(self):
        src = (
            "import threading\n"
            "\n"
            "_mu = threading.RLock()\n"
            "\n"
            "def helper():\n"
            "    with _mu:\n"
            "        pass\n"
            "\n"
            "def outer():\n"
            "    with _mu:\n"
            "        helper()\n"
        )
        _, diags = self._check(src)
        assert not _errors(diags), [d.format() for d in diags]

    def test_seeded_hold_and_block_is_error(self):
        # blocking socket recv under a lock a daemon worker also takes:
        # a wedged peer parks the worker forever
        src = (
            "import threading\n"
            "\n"
            "class W:\n"
            "    def __init__(self, sock):\n"
            "        self._mu = threading.Lock()\n"
            "        self._sock = sock\n"
            "        t = threading.Thread(target=self._watch, daemon=True)\n"
            "        t.start()\n"
            "\n"
            "    def _watch(self):\n"
            "        with self._mu:\n"
            "            self._beat = 1\n"
            "\n"
            "    def fetch(self):\n"
            "        with self._mu:\n"
            "            return self._sock.recv(4)\n"
        )
        model, diags = self._check(src)
        errs = [d for d in _errors(diags) if d.code == "BF-CONC002"]
        assert errs, [d.format() for d in diags]
        assert "recv" in errs[0].message
        # the model knows WHY: the lock is async-acquired by _watch
        assert "seed:W._watch" in model.async_locks["seed.W._mu"]

    def test_recv_exact_helper_counts_as_blocking(self):
        # the package's wire reads go through the _recv_exact helper,
        # not bare sock.recv — a lock held across it must flag exactly
        # like the raw call (regression: the set once listed the
        # underscore-less name and never matched)
        src = (
            "import threading\n"
            "\n"
            "def _recv_exact(sock, n):\n"
            "    return sock.recv(n)\n"
            "\n"
            "class W:\n"
            "    def __init__(self, sock):\n"
            "        self._mu = threading.Lock()\n"
            "        self._sock = sock\n"
            "        t = threading.Thread(target=self._watch, daemon=True)\n"
            "        t.start()\n"
            "\n"
            "    def _watch(self):\n"
            "        with self._mu:\n"
            "            self._beat = 1\n"
            "\n"
            "    def helper(self):\n"
            "        return _recv_exact(self._sock, 4)\n"
            "\n"
            "    def fetch(self):\n"
            "        with self._mu:\n"
            "            return self.helper()\n"
        )
        _, diags = self._check(src)
        errs = [d for d in _errors(diags) if d.code == "BF-CONC002"]
        assert errs, [d.format() for d in diags]
        assert "_recv_exact" in errs[0].message

    def test_holds_ok_waiver_downgrades_to_info(self):
        src = (
            "import threading\n"
            "\n"
            "class W:\n"
            "    def __init__(self, sock):\n"
            "        self._mu = threading.Lock()\n"
            "        self._sock = sock\n"
            "        t = threading.Thread(target=self._watch, daemon=True)\n"
            "        t.start()\n"
            "\n"
            "    def _watch(self):\n"
            "        with self._mu:\n"
            "            self._beat = 1\n"
            "\n"
            "    def fetch(self):\n"
            "        with self._mu:\n"
            "            return self._sock.recv(4)"
            "  # bfverify: holds-ok reviewed ack fence\n"
        )
        _, diags = self._check(src)
        assert not _errors(diags), [d.format() for d in diags]
        waived = [d for d in diags if d.code == "BF-CONC002W"]
        assert waived and "reviewed ack fence" in waived[0].message

    def test_bare_waiver_without_reason_waives_nothing(self):
        # a reasonless token must NOT suppress the finding
        src = (
            "import threading\n"
            "\n"
            "class W:\n"
            "    def __init__(self, sock):\n"
            "        self._mu = threading.Lock()\n"
            "        self._sock = sock\n"
            "        t = threading.Thread(target=self._watch, daemon=True)\n"
            "        t.start()\n"
            "\n"
            "    def _watch(self):\n"
            "        with self._mu:\n"
            "            self._beat = 1\n"
            "\n"
            "    def fetch(self):\n"
            "        with self._mu:\n"
            "            return self._sock.recv(4)  # bfverify: holds-ok\n"
        )
        _, diags = self._check(src)
        assert any(d.code == "BF-CONC002" for d in _errors(diags)), \
            [d.format() for d in diags]

    def test_timed_blocking_call_is_exempt(self):
        # an explicit timeout= bounds the call: connect-with-deadline
        # under a shared lock is a latency bug at worst, not a wedge —
        # the same call with no deadline still flags
        base = (
            "import socket\n"
            "import threading\n"
            "\n"
            "class W:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n"
            "        t = threading.Thread(target=self._watch, daemon=True)\n"
            "        t.start()\n"
            "\n"
            "    def _watch(self):\n"
            "        with self._mu:\n"
            "            self._beat = 1\n"
            "\n"
            "    def dial(self, addr):\n"
            "        with self._mu:\n"
            "            return socket.create_connection(%s)\n"
        )
        _, diags = self._check(base % "addr, timeout=5.0")
        assert not [d for d in _errors(diags) if d.code == "BF-CONC002"], \
            [d.format() for d in diags]
        _, diags = self._check(base % "addr")
        assert [d for d in _errors(diags) if d.code == "BF-CONC002"], \
            [d.format() for d in diags]

    def test_blocking_without_shared_lock_is_clean(self):
        # blocking under a lock NO async context touches: fine (the
        # only waiter is another synchronous caller of the same API)
        src = (
            "import threading\n"
            "\n"
            "class W:\n"
            "    def __init__(self, sock):\n"
            "        self._mu = threading.Lock()\n"
            "        self._sock = sock\n"
            "\n"
            "    def fetch(self):\n"
            "        with self._mu:\n"
            "            return self._sock.recv(4)\n"
        )
        _, diags = self._check(src)
        assert not _errors(diags), [d.format() for d in diags]

    def test_seeded_unlocked_shared_attr_is_warning(self):
        src = (
            "import threading\n"
            "\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self.count = 0\n"
            "        t = threading.Thread(target=self._run, daemon=True)\n"
            "        t.start()\n"
            "\n"
            "    def _run(self):\n"
            "        self.count = 1\n"
            "\n"
            "    def read(self):\n"
            "        return self.count\n"
        )
        _, diags = self._check(src)
        hits = [d for d in diags if d.code == "BF-CONC003"]
        assert hits and hits[0].severity == "warning", \
            [d.format() for d in diags]
        assert "count" in hits[0].message

    def test_common_lock_silences_shared_attr(self):
        src = (
            "import threading\n"
            "\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n"
            "        self.count = 0\n"
            "        t = threading.Thread(target=self._run, daemon=True)\n"
            "        t.start()\n"
            "\n"
            "    def _run(self):\n"
            "        with self._mu:\n"
            "            self.count = 1\n"
            "\n"
            "    def read(self):\n"
            "        with self._mu:\n"
            "            return self.count\n"
        )
        _, diags = self._check(src)
        assert not any(d.code == "BF-CONC003" for d in diags), \
            [d.format() for d in diags]

    def test_shared_ok_waiver_honored(self):
        src = (
            "import threading\n"
            "\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self.count = 0\n"
            "        t = threading.Thread(target=self._run, daemon=True)\n"
            "        t.start()\n"
            "\n"
            "    def _run(self):\n"
            "        self.count = 1"
            "  # bfverify: shared-ok GIL-atomic int store\n"
            "\n"
            "    def read(self):\n"
            "        return self.count\n"
        )
        _, diags = self._check(src)
        assert not any(d.code == "BF-CONC003" for d in diags), \
            [d.format() for d in diags]

    def test_condvar_wait_outside_while_is_info(self):
        src = (
            "import threading\n"
            "\n"
            "class P:\n"
            "    def __init__(self):\n"
            "        self._cv = threading.Condition()\n"
            "\n"
            "    def get(self):\n"
            "        with self._cv:\n"
            "            self._cv.wait()\n"
        )
        _, diags = self._check(src)
        hits = [d for d in diags if d.code == "BF-CONC010"]
        assert hits and hits[0].severity == "info", \
            [d.format() for d in diags]

    def test_condvar_wait_in_while_is_clean(self):
        src = (
            "import threading\n"
            "\n"
            "class P:\n"
            "    def __init__(self):\n"
            "        self._cv = threading.Condition()\n"
            "        self._ready = False\n"
            "\n"
            "    def get(self):\n"
            "        with self._cv:\n"
            "            while not self._ready:\n"
            "                self._cv.wait()\n"
        )
        _, diags = self._check(src)
        assert not any(d.code == "BF-CONC010" for d in diags), \
            [d.format() for d in diags]

    def test_condition_aliases_its_underlying_lock(self):
        # Condition(existing_lock) is ONE ordering identity with it —
        # cv-nested-under-its-own-lock must not fabricate an edge
        src = (
            "import threading\n"
            "\n"
            "class T:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n"
            "        self._cv = threading.Condition(self._mu)\n"
        )
        model, _ = self._check(src)
        cv = model.locks["seed.T._cv"]
        assert model.resolve_alias("seed.T._cv") == "seed.T._mu", cv

    def test_repo_sweeps_clean(self):
        # the acceptance bar: every BF-CONC001/002 on the tree is fixed
        # or carries a reasoned waiver; warnings triaged to zero
        from bluefog_tpu.analysis.concurrency_lint import check_package

        model, diags = check_package()
        assert not _errors(diags), [d.format() for d in diags]
        assert not [d for d in diags if d.severity == "warning"], \
            [d.format() for d in diags]
        # the model actually saw the runtime (not an empty scan)
        assert len(model.locks) >= 30, len(model.locks)
        assert model.thread_entries, "no thread entry points found?"

    def test_concurrency_pass_runs_in_sweep(self):
        from bluefog_tpu.analysis.lint import concurrency_pass

        report = LintReport()
        concurrency_pass(report, 4)
        assert report.has("BF-CONC100"), report.format(verbose=True)
        assert report.ok, report.format()

    def test_bfverify_cli_exits_zero(self):
        # the standalone CLI over the repo as committed: graph + tables
        # print, no error findings survive, exit 0
        proc = subprocess.run(
            [sys.executable, "-m",
             "bluefog_tpu.analysis.concurrency_lint", "--dot", "-"],
            capture_output=True, text=True, timeout=120,
            cwd=REPO, env=clean_env())
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "bfverify: OK" in proc.stdout
        assert "digraph lock_order" in proc.stdout
        assert "lock-order edges" in proc.stdout


class TestShardingLint:
    """BF-SHD: the unified rule table vs the leaf families it governs —
    coverage leaks (001), window-declaration drift (002), and a gather
    on the gossip hot path (003, by jaxpr inspection)."""

    def _tree(self):
        return {"blk": {"up": {"kernel": jnp.zeros((4, 8)),
                               "bias": jnp.zeros((8,))},
                        "ln": {"count": jnp.zeros(())}}}

    def test_seeded_violation_unmatched_leaf(self):
        from bluefog_tpu.analysis.sharding_lint import check_rule_coverage
        from bluefog_tpu.sharding import RuleTable

        table = RuleTable([("kernel$", P(None, "tp"))])  # no catch-all
        diags = check_rule_coverage(table, self._tree())
        errs = _errors(diags)
        assert errs and all(d.code == "BF-SHD001" for d in errs)
        assert any("up/bias" in d.message for d in errs)
        # the scalar is exempt — it resolves replicated, not leaked
        assert not any("count" in d.message for d in errs)

    def test_seeded_violation_dead_rule(self):
        from bluefog_tpu.analysis.sharding_lint import check_rule_coverage
        from bluefog_tpu.sharding import RuleTable

        table = RuleTable([("typod_pattern$", P("tp")), (".*", P())])
        diags = check_rule_coverage(table, self._tree())
        assert any(d.code == "BF-SHD001" and "typod_pattern" in d.message
                   for d in _errors(diags))

    def test_clean_coverage(self):
        from bluefog_tpu.analysis.sharding_lint import check_rule_coverage
        from bluefog_tpu.sharding import RuleTable

        table = RuleTable([("kernel$", P(None, "tp")), (".*", P())])
        assert not check_rule_coverage(table, self._tree())

    def test_seeded_violation_window_declaration_drift(self):
        from bluefog_tpu.analysis.sharding_lint import (
            check_window_partition)
        from bluefog_tpu.ops.windows import win_create
        from bluefog_tpu.sharding import RuleTable

        created_under = RuleTable([("kernel$", P(None, "tp")), (".*", P())])
        live = RuleTable([("kernel$", P("tp", None)), (".*", P())])
        sched = T.build_schedule(T.RingGraph(4))
        win = win_create(self._tree(), sched, AXIS,
                         rule_table=created_under)
        diags = check_window_partition(win, live)
        assert any(d.code == "BF-SHD002" and "kernel" in d.message
                   for d in diags)
        # same table -> clean
        assert not check_window_partition(win, created_under)
        # undeclared (legacy) window -> the one-shot warning
        legacy = win_create(self._tree(), sched, AXIS)
        diags = check_window_partition(legacy, live)
        assert [d.code for d in diags] == ["BF-SHD002"]
        assert "declares no partition" in diags[0].message

    def test_seeded_violation_gather_on_hot_path(self, devices8):
        from bluefog_tpu.analysis.sharding_lint import check_shard_local
        from bluefog_tpu.parallel.tensor import make_hybrid_mesh

        mesh = make_hybrid_mesh({"bf": 4, "tp": 2}, devices=devices8)

        def gathers(x):
            return lax.all_gather(x, "tp", tiled=True)

        fn = shard_map(gathers, mesh=mesh, in_specs=(P("tp"),),
                       out_specs=P(), check_vma=False)
        diags = check_shard_local(fn, jnp.zeros((8,)),
                                  inner_axes={"tp": 2})
        assert any(d.code == "BF-SHD003" for d in _errors(diags))

    def test_clean_sharded_gossip_step(self, devices8):
        from bluefog_tpu.analysis.sharding_lint import check_shard_local
        from bluefog_tpu.parallel.tensor import make_hybrid_mesh
        from bluefog_tpu.sharding import RuleTable

        mesh = make_hybrid_mesh({"bf": 4, "tp": 2}, devices=devices8)
        sched = T.build_schedule(T.RingGraph(4))
        table = RuleTable([("w$", P(None, "tp")), (".*", P())])

        def step(x):
            return C.sharded_neighbor_allreduce(
                x, sched, AXIS, rule_table=table, inner_axes={"tp": 2})

        fn = shard_map(step, mesh=mesh,
                       in_specs=({"w": P("bf", "tp")},),
                       out_specs={"w": P("bf", "tp")}, check_vma=False)
        diags = check_shard_local(fn, {"w": jnp.zeros((4, 8))},
                                  inner_axes={"tp": 2})
        assert not _errors(diags), [d.format() for d in diags]
        assert any(d.code == "BF-SHD103" for d in diags)

    def test_trace_failure_is_a_finding(self):
        from bluefog_tpu.analysis.sharding_lint import check_shard_local

        def boom(x):
            raise RuntimeError("no trace for you")

        diags = check_shard_local(boom, jnp.zeros((4,)),
                                  inner_axes={"tp": 2})
        assert [d.code for d in diags] == ["BF-SHD020"]

    def test_repo_sharding_pass_clean(self):
        """The sweep's own pass over the repo's default tables finds no
        errors (repo-clean)."""
        from bluefog_tpu.analysis import lint as L

        report = LintReport()
        L.sharding_pass(report, 8)
        errs = [d for d in report.diagnostics if d.severity == "error"]
        assert not errs, [d.format() for d in errs]
        assert any(d.code == "BF-SHD100" for d in report.diagnostics)


class TestTracingLint:
    """BF-TRC001: an explicit begin_span without a finally-guaranteed
    finish (or a reasoned cross-thread waiver) leaks a forever-open
    span — a completed phase then reads as wedged."""

    def test_seeded_violation_unguarded_begin(self):
        from bluefog_tpu.analysis.tracing_lint import check_span_discharge

        src = (
            "def send(rec, sock, data):\n"
            "    sp = rec.begin_span('wire', 'tcp')\n"
            "    sock.sendall(data)\n"
            "    sp.finish()\n"  # skipped when sendall raises
        )
        diags = check_span_discharge(src, filename="seeded.py")
        assert any(d.code == "BF-TRC001" and d.severity == "error"
                   for d in diags), [d.format() for d in diags]

    def test_finally_guarded_begin_is_clean(self):
        from bluefog_tpu.analysis.tracing_lint import check_span_discharge

        src = (
            "def send(rec, sock, data):\n"
            "    sp = rec.begin_span('wire', 'tcp')\n"
            "    try:\n"
            "        sock.sendall(data)\n"
            "    finally:\n"
            "        sp.finish()\n"
        )
        assert not check_span_discharge(src, filename="clean.py")

    def test_cross_thread_waiver_needs_a_reason(self):
        from bluefog_tpu.analysis.tracing_lint import check_span_discharge

        waived = (
            "def send(rec):\n"
            "    sp = rec.begin_span(  # bftrace: cross-thread ack "
            "reader finishes it\n"
            "        'wire', 'tcp')\n"
        )
        assert not check_span_discharge(waived, filename="waived.py")
        bare = (
            "def send(rec):\n"
            "    sp = rec.begin_span('wire')  # bftrace: cross-thread\n"
        )
        diags = check_span_discharge(bare, filename="bare.py")
        assert any(d.code == "BF-TRC001" for d in diags), \
            "a waiver without a reason must still be an error"

    def test_nested_function_judged_against_its_own_body(self):
        from bluefog_tpu.analysis.tracing_lint import check_span_discharge

        # the OUTER function's try/finally must not excuse a begin
        # inside a nested def that has no guard of its own
        src = (
            "def outer(rec):\n"
            "    def worker():\n"
            "        sp = rec.begin_span('apply')\n"
            "        sp.finish()\n"
            "    try:\n"
            "        worker()\n"
            "    finally:\n"
            "        rec.flush().finish()\n"
        )
        diags = check_span_discharge(src, filename="nested.py")
        assert any(d.code == "BF-TRC001" for d in diags), \
            [d.format() for d in diags]

    def test_nested_guard_cannot_vouch_for_outer_begin(self):
        from bluefog_tpu.analysis.tracing_lint import check_span_discharge

        # the reverse false negative: a finally-finish inside a nested
        # helper must not excuse the OUTER function's leaked begin
        src = (
            "def outer(rec, other):\n"
            "    sp = rec.begin_span('wire')\n"
            "    def helper():\n"
            "        try:\n"
            "            pass\n"
            "        finally:\n"
            "            other.finish()\n"
            "    helper()\n"
        )
        diags = check_span_discharge(src, filename="vouch.py")
        assert any(d.code == "BF-TRC001" for d in diags), \
            [d.format() for d in diags]

    def test_module_level_begin_is_error(self):
        from bluefog_tpu.analysis.tracing_lint import check_span_discharge

        diags = check_span_discharge("sp = rec.begin_span('x')\n",
                                     filename="mod.py")
        assert any(d.code == "BF-TRC001" for d in diags)

    def test_span_context_manager_is_never_flagged(self):
        from bluefog_tpu.analysis.tracing_lint import check_span_discharge

        src = (
            "def round_(rec):\n"
            "    with rec.span('gossip', 'dsgd'):\n"
            "        pass\n"
        )
        assert not check_span_discharge(src, filename="cm.py")

    def test_repo_tracing_pass_clean(self):
        """The standard sweep's tracing pass over the repo itself:
        every real begin_span is guarded or carries a reasoned
        cross-thread waiver."""
        from bluefog_tpu.analysis import lint as L

        report = LintReport()
        L.tracing_pass(report, 8)
        errs = [d for d in report.diagnostics if d.severity == "error"]
        assert not errs, [d.format() for d in errs]
        assert any(d.code == "BF-TRC100" for d in report.diagnostics)


class TestDocLint:
    def test_repo_doc_matches_registry(self):
        from bluefog_tpu.analysis.doc_lint import check_transport_doc

        diags = check_transport_doc()
        assert not _errors(diags), [d.format() for d in diags]

    def test_missing_code_is_error(self, tmp_path):
        from bluefog_tpu.analysis.doc_lint import check_transport_doc
        from bluefog_tpu.runtime import wire_status as ws

        doc = tmp_path / "transport.md"
        codes = [c for c in ws.WIRE_V2_CODES if c != ws.ERR_BUSY]
        doc.write_text("status codes: " +
                       ", ".join(str(c) for c in codes) + "\n")
        diags = check_transport_doc(str(doc))
        errs = [d for d in _errors(diags) if d.code == "BF-DOC001"]
        assert errs and str(ws.ERR_BUSY) in errs[0].message, \
            [d.format() for d in diags]

    def test_stray_doc_code_is_error(self, tmp_path):
        from bluefog_tpu.analysis.doc_lint import check_transport_doc
        from bluefog_tpu.runtime import wire_status as ws

        doc = tmp_path / "transport.md"
        codes = list(ws.WIRE_V2_CODES) + [-199]
        doc.write_text("status codes: " +
                       ", ".join(str(c) for c in codes) + "\n")
        diags = check_transport_doc(str(doc))
        errs = [d for d in _errors(diags) if d.code == "BF-DOC001"]
        assert errs and "-199" in errs[0].message, \
            [d.format() for d in diags]

    def test_unassigned_gap_is_tolerated(self, tmp_path):
        # the doc may (should) mention the deliberately-unassigned -103
        from bluefog_tpu.analysis.doc_lint import check_transport_doc
        from bluefog_tpu.runtime import wire_status as ws

        doc = tmp_path / "transport.md"
        codes = list(ws.WIRE_V2_CODES) + list(ws.UNASSIGNED_CODES)
        doc.write_text("status codes: " +
                       ", ".join(str(c) for c in codes) + "\n")
        assert not _errors(check_transport_doc(str(doc)))

    # -------------------------------------------------- BF-DOC002 (metrics)
    def test_repo_metrics_doc_matches_live_names(self):
        """Both directions clean on the repo itself — every emitted
        bf_* metric has a doc row and no doc row is stale."""
        from bluefog_tpu.analysis.doc_lint import check_metrics_doc

        diags = check_metrics_doc()
        assert not _errors(diags), [d.format() for d in diags]
        assert any(d.code == "BF-DOC101" for d in diags)

    @staticmethod
    def _metric_src_tree(tmp_path, body: str):
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "mod.py").write_text(body)
        return str(pkg)

    def test_undocumented_metric_is_error(self, tmp_path):
        from bluefog_tpu.analysis.doc_lint import check_metrics_doc

        src = self._metric_src_tree(
            tmp_path,
            "def f(reg):\n"
            "    reg.counter('bf_documented_total').inc()\n"
            "    reg.gauge('bf_renamed_new_name').set(1.0)\n")
        doc = tmp_path / "metrics.md"
        doc.write_text("| `bf_documented_total` | counter |\n")
        errs = [d for d in _errors(check_metrics_doc(str(doc), src))
                if d.code == "BF-DOC002"]
        assert len(errs) == 1
        assert "bf_renamed_new_name" in errs[0].message

    def test_stale_doc_row_is_error(self, tmp_path):
        """The renamed-metric drift the sweep previously missed: the
        old name's doc row survives the rename."""
        from bluefog_tpu.analysis.doc_lint import check_metrics_doc

        src = self._metric_src_tree(
            tmp_path,
            "def f(reg):\n"
            "    reg.counter('bf_new_name_total').inc()\n")
        doc = tmp_path / "metrics.md"
        doc.write_text("| `bf_new_name_total` | counter |\n"
                       "| `bf_old_name_total` | counter |\n")
        errs = [d for d in _errors(check_metrics_doc(str(doc), src))
                if d.code == "BF-DOC002"]
        assert len(errs) == 1
        assert "bf_old_name_total" in errs[0].message

    def test_hist_expansion_spelling_normalizes(self, tmp_path):
        """A doc that spells `bf_x_seconds_p99` documents the
        histogram `bf_x_seconds`, and an FFI-style bf_* literal
        outside a metric call is not a metric."""
        from bluefog_tpu.analysis.doc_lint import check_metrics_doc

        src = self._metric_src_tree(
            tmp_path,
            "def f(reg, lib):\n"
            "    reg.histogram('bf_x_seconds').observe(0.1)\n"
            "    lib.symbol('bf_win_create')\n"
            "    count(None, [('bf_tuple_total', 1)])\n")
        doc = tmp_path / "metrics.md"
        doc.write_text("rows: `bf_x_seconds_p99`, `bf_tuple_total`\n")
        diags = check_metrics_doc(str(doc), src)
        assert not _errors(diags), [d.format() for d in diags]


class TestFleetLint:
    """BF-FLT001: an alert/SLO threshold without its hysteresis twin or
    a declared window is an error — the ControlConfig discipline
    applied to the fleet plane's spec sites."""

    def test_seeded_violation_enter_without_exit(self):
        from bluefog_tpu.analysis.fleet_lint import check_slo_specs

        src = ("spec = SLOSpec(name='x', signal='round_p99_s',\n"
               "               warn_enter=1.0, window=4)\n")
        diags = check_slo_specs(src, filename="seeded.py")
        assert any(d.code == "BF-FLT001" and d.severity == "error"
                   and "warn_exit" in d.message for d in diags), \
            [d.format() for d in diags]

    def test_seeded_violation_no_window(self):
        from bluefog_tpu.analysis.fleet_lint import check_slo_specs

        src = ("spec = SLOSpec(name='x', signal='round_p99_s',\n"
               "               warn_enter=1.0, warn_exit=0.5)\n")
        diags = check_slo_specs(src, filename="seeded2.py")
        assert any(d.code == "BF-FLT001" and "window" in d.message
                   for d in diags), [d.format() for d in diags]

    def test_seeded_violation_bare_threshold(self):
        from bluefog_tpu.analysis.fleet_lint import check_slo_specs

        src = "rule = AlertRule(threshold=5, window=4)\n"
        diags = check_slo_specs(src, filename="seeded3.py")
        assert any(d.code == "BF-FLT001" and "threshold" in d.message
                   for d in diags), [d.format() for d in diags]

    def test_full_spec_and_unrelated_calls_clean(self):
        from bluefog_tpu.analysis.fleet_lint import check_slo_specs

        src = (
            "spec = SLOSpec(name='x', signal='round_p99_s',\n"
            "               warn_enter=1.0, warn_exit=0.5, window=4,\n"
            "               page_enter=4.0, page_exit=2.0)\n"
            # alert-ish names with no threshold kwargs are fine
            "eng = SLOEngine((spec,), rank=3)\n"
            "ctl.note_alert(2, suspect=True)\n"
            # non-alert calls with enter-style kwargs are out of scope
            "cfg = ControlConfig(slow_enter=4.0)\n"
        )
        assert not check_slo_specs(src, filename="clean.py")

    def test_positional_form_left_to_runtime(self):
        from bluefog_tpu.analysis.fleet_lint import check_slo_specs

        # positional/config-dict spellings are the runtime validator's
        # job (SLOSpec.__post_init__ raises on unpaired thresholds)
        src = "spec = SLOSpec('x', 'round_p99_s', 1.0, 0.5, 4)\n"
        assert not check_slo_specs(src, filename="positional.py")

    def test_fleet_package_is_repo_clean(self):
        import glob

        from bluefog_tpu.analysis.fleet_lint import check_file

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        errs = []
        for pat in ("bluefog_tpu/fleet/*.py", "bluefog_tpu/runtime/*.py",
                    "examples/*.py", "benchmarks/*.py"):
            for path in glob.glob(os.path.join(root, pat)):
                errs += [d for d in check_file(path)
                         if d.severity == "error"]
        assert not errs, [d.format() for d in errs]


class TestSimLint:
    """BF-SIM001: the simulator's determinism contract (no wall clock,
    no ambient RNG inside bluefog_tpu/sim/) and the scenario-table
    discipline (every Scenario(...) call site declares accept= and a
    bounded horizon_s=)."""

    def test_seeded_wall_clock_violation(self):
        from bluefog_tpu.analysis.sim_lint import check_determinism

        src = "import time\ndef handler():\n    return time.time()\n"
        diags = check_determinism(src, filename="seeded_sim.py")
        assert any(d.code == "BF-SIM001" and d.severity == "error"
                   and "VIRTUAL clock" in d.message for d in diags), \
            [d.format() for d in diags]

    def test_seeded_ambient_rng_violation(self):
        from bluefog_tpu.analysis.sim_lint import check_determinism

        src = ("import random\nimport numpy as np\n"
               "a = random.random()\nb = np.random.rand(3)\n")
        diags = check_determinism(src, filename="seeded_sim2.py")
        assert sum(1 for d in diags if d.code == "BF-SIM001") == 2, \
            [d.format() for d in diags]

    def test_seeded_generators_are_clean(self):
        from bluefog_tpu.analysis.sim_lint import check_determinism

        src = ("import random\nimport numpy as np\n"
               "r = random.Random(7)\nv = r.random()\n"
               "g = np.random.default_rng(7)\n")
        assert not check_determinism(src, filename="clean_sim.py")

    def test_scenario_missing_accept_or_horizon(self):
        from bluefog_tpu.analysis.sim_lint import check_scenario_table

        src = ("s = Scenario(name='x', kind='fleet', n_ranks=8,\n"
               "             horizon_s=1.0)\n"
               "t = Scenario(name='y', kind='fleet', n_ranks=8,\n"
               "             accept=(('audit_exact', {}),))\n")
        diags = check_scenario_table(src, filename="seeded_sc.py")
        msgs = [d.message for d in diags if d.code == "BF-SIM001"]
        assert any("accept=" in m for m in msgs), msgs
        assert any("horizon_s=" in m for m in msgs), msgs

    def test_scenario_splat_left_to_runtime(self):
        from bluefog_tpu.analysis.sim_lint import check_scenario_table

        # **kwargs spellings are the runtime validator's job
        # (Scenario.__post_init__ raises on a missing accept/horizon)
        src = "s = Scenario(**cfg)\n"
        assert not check_scenario_table(src, filename="splat.py")

    def test_determinism_rule_scoped_to_sim_package(self):
        from bluefog_tpu.analysis.sim_lint import check_file

        # a wall-clock call OUTSIDE bluefog_tpu/sim/ is not this
        # lint's business (the fleet publisher reads time.time by
        # design); only the scenario-table rule applies there
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        path = os.path.join(root, "bluefog_tpu", "fleet", "record.py")
        assert not [d for d in check_file(path) if d.severity == "error"]

    def test_sim_package_is_repo_clean(self):
        import glob

        from bluefog_tpu.analysis.sim_lint import check_file

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        errs = []
        for pat in ("bluefog_tpu/sim/*.py", "examples/*.py",
                    "benchmarks/*.py"):
            for path in glob.glob(os.path.join(root, pat)):
                errs += [d for d in check_file(path)
                         if d.severity == "error"]
        assert not errs, [d.format() for d in errs]


# ---------------------------------------------------------------------------
# Pass 13: wire-protocol verifier (bfwire-tpu)
# ---------------------------------------------------------------------------


class TestWireLint:
    """BF-WIRE001..004 on synthetic sources (one seeded + one clean per
    code), the waiver grammar, the registry staleness satellite, and
    the repo-clean sweep.  The state-machine layer (BF-WIRE005) has its
    own conformance suite in tests/test_wire_verify.py."""

    @staticmethod
    def _check(*sources):
        from bluefog_tpu.analysis.protocol_check import check_sources

        return check_sources(list(sources))

    # ------------------------------------------------ BF-WIRE001 (layout)
    def test_conflicting_struct_formats_caught(self):
        _, diags = self._check(
            ("a.py", "import struct\n_FRAME = struct.Struct('<Iq')\n"),
            ("b.py", "import struct\n_FRAME = struct.Struct('<IqB')\n"))
        errs = [d for d in _errors(diags) if d.code == "BF-WIRE001"]
        assert errs and "CONFLICTING" in errs[0].message, \
            [d.format() for d in diags]

    def test_packed_never_unpacked_caught(self):
        _, diags = self._check(("a.py", (
            "import struct\n"
            "_ONLY = struct.Struct('<q')\n"
            "def emit(sock, n):\n"
            "    sock.sendall(_ONLY.pack(n))\n")))
        errs = [d for d in _errors(diags) if d.code == "BF-WIRE001"]
        assert errs and "no protocol module ever unpacks" in \
            errs[0].message, [d.format() for d in diags]

    def test_inline_struct_call_caught(self):
        _, diags = self._check(("a.py", (
            "import struct\n"
            "def emit(sock, n):\n"
            "    sock.sendall(struct.pack('<q', n))\n")))
        errs = [d for d in _errors(diags) if d.code == "BF-WIRE001"]
        assert errs and "hand-rolled" in errs[0].message

    def test_per_op_imbalance_caught(self):
        # op 0 packs _REQ; the decode side unpacks it only under op 1 —
        # the other side of the frame drifted to a different dispatch
        _, diags = self._check(("a.py", (
            "import struct\n"
            "_MAGIC = 7\n"
            "_OP_A = 0\n"
            "_OP_B = 1\n"
            "_HDR = struct.Struct('<IBH')\n"
            "_REQ = struct.Struct('<q')\n"
            "def send(sock, n):\n"
            "    sock.sendall(_HDR.pack(_MAGIC, _OP_A, 0)"
            " + _REQ.pack(n))\n"
            "def handle(sock, op, payload):\n"
            "    magic, op, nl = _HDR.unpack(payload)\n"
            "    if op == _OP_B:\n"
            "        (x,) = _REQ.unpack(payload)\n")))
        errs = [d for d in _errors(diags) if d.code == "BF-WIRE001"]
        assert any("op 0 packs struct _REQ" in d.message for d in errs), \
            [d.format() for d in diags]

    def test_balanced_ops_clean(self):
        _, diags = self._check(("a.py", (
            "import struct\n"
            "_MAGIC = 7\n"
            "_OP_A = 0\n"
            "_HDR = struct.Struct('<IBH')\n"
            "_REQ = struct.Struct('<q')\n"
            "def send(sock, n):\n"
            "    sock.sendall(_HDR.pack(_MAGIC, _OP_A, 0)"
            " + _REQ.pack(n))\n"
            "def handle(sock, op, payload):\n"
            "    magic, op, nl = _HDR.unpack(payload)\n"
            "    if op == _OP_A:\n"
            "        (x,) = _REQ.unpack(payload)\n")))
        assert not [d for d in _errors(diags) if d.code == "BF-WIRE001"]

    # ------------------------------------------------------ waiver grammar
    def test_reasoned_waiver_downgrades_to_info(self):
        _, diags = self._check(("a.py", (
            "import struct\n"
            "# bfwire: layout-ok decoder lives in the relay binary\n"
            "_ONLY = struct.Struct('<q')\n"
            "def emit(sock, n):\n"
            "    sock.sendall(_ONLY.pack(n))\n")))
        assert not [d for d in _errors(diags) if d.code == "BF-WIRE001"]
        infos = [d for d in diags if d.code == "BF-WIRE001W"]
        assert infos and "relay binary" in infos[0].message

    def test_bare_waiver_token_waives_nothing(self):
        _, diags = self._check(("a.py", (
            "import struct\n"
            "# bfwire: layout-ok\n"
            "_ONLY = struct.Struct('<q')\n"
            "def emit(sock, n):\n"
            "    sock.sendall(_ONLY.pack(n))\n")))
        assert [d for d in _errors(diags) if d.code == "BF-WIRE001"]

    # ------------------------------------------------ BF-WIRE002 (status)
    def test_unregistered_status_literal_caught(self):
        # no registry in the synthetic source: the live wire_status
        # table is the fallback ground truth, and -142 is not in it
        _, diags = self._check(("a.py", (
            "def reply(self, sock):\n"
            "    self._send_status(-142)\n")))
        errs = [d for d in _errors(diags) if d.code == "BF-WIRE002"]
        assert errs and "-142" in errs[0].message

    def test_registered_status_emit_clean(self):
        _, diags = self._check(("a.py", (
            "def reply(self, sock):\n"
            "    self._send_status(-106)\n")))
        assert not [d for d in _errors(diags) if d.code == "BF-WIRE002"]

    def test_retriable_code_raised_terminal_caught(self):
        _, diags = self._check(("a.py", (
            "_ERR_BUSY = -106\n"
            "_RETRIABLE = frozenset({_ERR_BUSY})\n"
            "def check(rc):\n"
            "    if rc == _ERR_BUSY:\n"
            "        raise RuntimeError('busy')\n")))
        errs = [d for d in _errors(diags) if d.code == "BF-WIRE002"]
        assert errs and "RETRIABLE per wire_status" in errs[0].message

    def test_terminal_code_raised_retriable_caught(self):
        _, diags = self._check(("a.py", (
            "_ERR_GONE = -105\n"
            "def check(rc):\n"
            "    if rc == _ERR_GONE:\n"
            "        raise ConnectionError('retry?')\n")))
        errs = [d for d in _errors(diags) if d.code == "BF-WIRE002"]
        assert errs and "TERMINAL per wire_status" in errs[0].message

    def test_matching_handling_clean(self):
        _, diags = self._check(("a.py", (
            "_ERR_BUSY = -106\n"
            "_RETRIABLE = frozenset({_ERR_BUSY})\n"
            "def check(rc):\n"
            "    if rc == _ERR_BUSY:\n"
            "        raise ConnectionError('backing off')\n")))
        assert not [d for d in _errors(diags) if d.code == "BF-WIRE002"]

    def test_stale_unassigned_codes_caught(self):
        from bluefog_tpu.analysis.protocol_check import check_registry

        diags = check_registry(codes=(-100, -101, -104), unassigned=())
        assert diags and diags[0].code == "BF-WIRE002"
        assert "-102" in diags[0].message and "-103" in diags[0].message
        # the live registry's gap list is generated, hence never stale
        assert not check_registry()

    # ------------------------------------------------- BF-WIRE003 (gates)
    _GATE_PRELUDE = ("import struct\n"
                     "_MAGIC = 7\n"
                     "_OP_STREAM_ATTACH = 6\n"
                     "_HDR = struct.Struct('<IBH')\n")

    def test_ungated_feature_op_caught(self):
        _, diags = self._check(("a.py", self._GATE_PRELUDE + (
            "def attach(sock):\n"
            "    sock.sendall(_HDR.pack(_MAGIC, _OP_STREAM_ATTACH, 0))\n"
        )))
        errs = [d for d in _errors(diags) if d.code == "BF-WIRE003"]
        assert errs and "FEATURE_RESUME" in errs[0].message

    def test_gate_evidence_in_scope_clean(self):
        _, diags = self._check(("a.py", self._GATE_PRELUDE + (
            "def attach(sock, granted):\n"
            "    if granted & FEATURE_RESUME:\n"
            "        sock.sendall(_HDR.pack(_MAGIC,"
            " _OP_STREAM_ATTACH, 0))\n")))
        assert not [d for d in _errors(diags) if d.code == "BF-WIRE003"]

    def test_gate_ok_waiver_downgrades_to_info(self):
        _, diags = self._check(("a.py", self._GATE_PRELUDE + (
            "def attach(sock):\n"
            "    # bfwire: gate-ok caller negotiated the bit\n"
            "    sock.sendall(_HDR.pack(_MAGIC, _OP_STREAM_ATTACH, 0))\n"
        )))
        assert not [d for d in _errors(diags) if d.code == "BF-WIRE003"]
        assert any(d.code == "BF-WIRE003W" for d in diags)

    # ------------------------------------------------ BF-WIRE004 (bounds)
    _BOUND_PRELUDE = ("import struct\n"
                      "import numpy as np\n"
                      "_MAX_BLOB = 1024\n"
                      "_CNT = struct.Struct('<q')\n"
                      "def send(sock, n):\n"
                      "    sock.sendall(_CNT.pack(n))\n")

    def test_unguarded_wire_length_caught(self):
        _, diags = self._check(("a.py", self._BOUND_PRELUDE + (
            "def read(sock):\n"
            "    (n,) = _CNT.unpack(_recv_exact(sock, 8))\n"
            "    return np.empty(n)\n")))
        errs = [d for d in _errors(diags) if d.code == "BF-WIRE004"]
        assert errs and "'n'" in errs[0].message and \
            "np" not in errs[0].subject

    def test_bounded_wire_length_clean(self):
        _, diags = self._check(("a.py", self._BOUND_PRELUDE + (
            "def read(sock):\n"
            "    (n,) = _CNT.unpack(_recv_exact(sock, 8))\n"
            "    if n < 0 or n > _MAX_BLOB:\n"
            "        raise ValueError('bad frame')\n"
            "    return np.empty(n)\n")))
        assert not [d for d in _errors(diags) if d.code == "BF-WIRE004"]

    # --------------------------------------------------------- repo sweep
    def test_repo_protocol_surface_is_clean(self):
        from bluefog_tpu.analysis.protocol_check import check_package

        model, diags = check_package()
        assert not _errors(diags), [d.format() for d in _errors(diags)]
        assert any(d.code == "BF-WIRE100" for d in diags)
        assert any(d.code == "BF-WIRE101" for d in diags)
        # the triaged waivers surface as infos, never silently
        assert any(d.code == "BF-WIRE001W" for d in diags)
        # the model actually covers the protocol surface
        assert len(model.files) == 7
        assert model.structs and model.uses and model.status_sites

    def test_cli_exits_zero_on_repo(self):
        proc = subprocess.run(
            [sys.executable, "-m",
             "bluefog_tpu.analysis.protocol_check", "--verbose"],
            capture_output=True, text=True, timeout=300,
            cwd=REPO, env=clean_env())
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "bfwire: OK" in proc.stdout
        assert "deposit-stream:" in proc.stdout  # state counts reported


class TestFeatureDocLint:
    """BF-DOC003: the transport doc's HELLO feature-bit paragraph <->
    the live FEATURE_* constants, both directions with value
    agreement."""

    @staticmethod
    def _live_bits():
        from bluefog_tpu.runtime import window_server as ws

        return {n[len("FEATURE_"):]: v for n, v in vars(ws).items()
                if n.startswith("FEATURE_") and isinstance(v, int)}

    @staticmethod
    def _doc(tmp_path, pairs):
        doc = tmp_path / "transport.md"
        doc.write_text("HELLO feature bits: " + ", ".join(
            "%d `%s`" % (v, n) for n, v in pairs) + ".\n")
        return str(doc)

    def test_repo_feature_doc_matches_live_bits(self):
        from bluefog_tpu.analysis.doc_lint import check_feature_doc

        diags = check_feature_doc()
        assert not _errors(diags), [d.format() for d in diags]
        assert any(d.code == "BF-DOC102" for d in diags)

    def test_missing_bit_is_error(self, tmp_path):
        from bluefog_tpu.analysis.doc_lint import check_feature_doc

        live = self._live_bits()
        path = self._doc(tmp_path, [(n, v) for n, v in live.items()
                                    if n != "DELTA"])
        errs = [d for d in _errors(check_feature_doc(path))
                if d.code == "BF-DOC003"]
        assert len(errs) == 1 and "FEATURE_DELTA" in errs[0].message

    def test_wrong_value_is_error(self, tmp_path):
        from bluefog_tpu.analysis.doc_lint import check_feature_doc

        live = self._live_bits()
        path = self._doc(tmp_path,
                         [(n, 999 if n == "TRACE" else v)
                          for n, v in live.items()])
        errs = [d for d in _errors(check_feature_doc(path))
                if d.code == "BF-DOC003"]
        assert len(errs) == 1 and "999" in errs[0].message

    def test_stale_doc_entry_is_error(self, tmp_path):
        from bluefog_tpu.analysis.doc_lint import check_feature_doc

        pairs = list(self._live_bits().items()) + [("WORMHOLE", 4096)]
        path = self._doc(tmp_path, pairs)
        errs = [d for d in _errors(check_feature_doc(path))
                if d.code == "BF-DOC003"]
        assert len(errs) == 1 and "WORMHOLE" in errs[0].message

    def test_missing_paragraph_is_error(self, tmp_path):
        from bluefog_tpu.analysis.doc_lint import check_feature_doc

        doc = tmp_path / "transport.md"
        doc.write_text("no feature bit paragraph here\n")
        errs = [d for d in _errors(check_feature_doc(str(doc)))
                if d.code == "BF-DOC003"]
        assert errs and "paragraph" in errs[0].message
