"""bench.py resilience: degraded mode + perf sanity gates.

Round 3 ended with BENCH_r03.json as a bare failure record (rc=1, relay
refused device init) — no perf artifact at all.  The verdict's directive:
device-init failure must emit last-good cached metrics flagged stale plus
AOT compile-only evidence and exit 0 (a round can never end with nothing),
and perf numbers must carry plausibility gates (the relay has produced
measured "peaks" off by >1000x from any physical chip).
"""

import json
import os
import sys

import pytest

from tests._util import REPO as _REPO, load_script


@pytest.fixture(scope="module")
def bench():
    return load_script("bench.py")


class FakeDev:
    def __init__(self, kind):
        self.device_kind = kind


class TestNominalSpec:
    def test_known_kinds(self, bench):
        assert bench.nominal_spec([FakeDev("TPU v5 lite")]) == (197.0, 819.0)
        assert bench.nominal_spec([FakeDev("TPU v5p")]) == (459.0, 2765.0)
        assert bench.nominal_spec([FakeDev("TPU v4")]) == (275.0, 1228.0)
        assert bench.nominal_spec([FakeDev("TPU v6 lite")]) == (918.0, 1640.0)

    def test_longest_match_wins(self, bench):
        # "v5 lite" contains "v5"-family substrings; must not fall through
        # to a shorter key with different numbers
        tf, _ = bench.nominal_spec([FakeDev("tpu v5 lite chip")])
        assert tf == 197.0

    def test_unknown_kind(self, bench):
        assert bench.nominal_spec([FakeDev("QuantumAbacus 3000")]) == (None,
                                                                       None)


class TestSanityGates:
    def test_plausible_peak_uses_measured(self, bench):
        # 160 TF measured on a 197 TF chip: plausible
        f = bench.perf_sanity_fields(
            [FakeDev("TPU v5 lite")], peak_flops=160e12,
            achieved_flops=80e12, best_mem=None, flops_per_step=0,
            best_batch=128, best_ips=1000.0)
        assert f["measured_peak_plausible"] is True
        assert f["mfu_denominator"] == "measured_peak"
        assert f["mfu"] == f["mfu_vs_measured"] == 0.5

    def test_non_physical_peak_falls_back_to_spec(self, bench):
        # the round-3 failure shape: ~1000 PFLOP/s "measured" on one chip
        f = bench.perf_sanity_fields(
            [FakeDev("TPU v5 lite")], peak_flops=1000e15,
            achieved_flops=100e12, best_mem=None, flops_per_step=0,
            best_batch=128, best_ips=1000.0)
        assert f["measured_peak_plausible"] is False
        assert f["mfu_denominator"] == "nominal_spec"
        assert f["mfu"] == pytest.approx(100e12 / 197e12, rel=1e-3)
        # both denominators are still visible to the reader
        assert "mfu_vs_measured" in f and "mfu_vs_nominal" in f

    def test_mfu_above_one_is_flagged(self, bench):
        f = bench.perf_sanity_fields(
            [FakeDev("TPU v5 lite")], peak_flops=150e12,
            achieved_flops=400e12, best_mem=None, flops_per_step=0,
            best_batch=128, best_ips=1000.0)
        assert f["mfu_plausible"] is False

    def test_mfu_plausible_emitted_true_on_healthy_runs(self, bench):
        # the key must be PRESENT either way — absence is ambiguous
        f = bench.perf_sanity_fields(
            [FakeDev("TPU v5 lite")], peak_flops=160e12,
            achieved_flops=80e12, best_mem=None, flops_per_step=0,
            best_batch=128, best_ips=1000.0)
        assert f["mfu_plausible"] is True

    def test_relay_error_classifier(self, bench):
        relay = RuntimeError(
            "Unable to initialize backend 'axon': UNAVAILABLE: TPU backend "
            "setup/compile error (Unavailable).")
        broken = RuntimeError(
            "Unable to initialize backend 'tpu': UNKNOWN: TPU "
            "initialization failed: No jellyfish device found.")
        assert bench._is_relay_unavailable(relay) is True
        assert bench._is_relay_unavailable(broken) is False

    def test_roofline_estimate(self, bench):
        mem = {"temp": 8 << 30, "args": 100 << 20}  # 8 GiB act, 100 MiB args
        f = bench.perf_sanity_fields(
            [FakeDev("TPU v5 lite")], peak_flops=150e12,
            achieved_flops=50e12, best_mem=mem,
            flops_per_step=128 * 12.27e9, best_batch=128, best_ips=10000.0)
        r = f["roofline_estimate"]
        assert r["hbm_bytes_per_step_est"] == mem["temp"] + mem["args"]
        # 8.1 GiB over 819 GB/s ~ 10.6 ms; compute 1.57 TF over 197 TF ~ 8 ms
        assert r["min_step_ms_memory"] == pytest.approx(10.6, abs=0.5)
        assert r["bound"] == "memory"
        assert r["measured_step_ms"] == pytest.approx(12.8, abs=0.1)

    def test_unknown_device_reports_unverified(self, bench):
        f = bench.perf_sanity_fields(
            [FakeDev("mystery")], peak_flops=100e12, achieved_flops=10e12,
            best_mem=None, flops_per_step=0, best_batch=1, best_ips=1.0)
        assert f["mfu_denominator"] == "measured_peak_unverified"
        assert "nominal_peak_tflops_per_sec" not in f


class TestDegradedMode:
    def test_emits_stale_cache_and_exits_zero(self, bench, monkeypatch,
                                              tmp_path, capsys):
        cache = tmp_path / "cache.json"
        cache.write_text(json.dumps({
            "metric": "resnet50_images_per_sec_per_chip",
            "value": 97262.15, "unit": "images/sec/chip", "batch": 128,
            "vs_baseline": 270.173, "cached_at": "yesterday"}))
        monkeypatch.setattr(bench, "CACHE_PATH", str(cache))
        monkeypatch.setattr(bench, "_aot_overlap_evidence",
                            lambda: {"collective_windows": 12,
                                     "overlapped_fraction": 1.0})
        with pytest.raises(SystemExit) as exc:
            bench._degraded_exit("relay wedged (test)")
        assert exc.value.code == 0
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["stale"] is True
        assert out["value"] == 97262.15  # last-good number, not nothing
        assert out["degraded_reason"] == "relay wedged (test)"
        assert out["aot_overlap"]["overlapped_fraction"] == 1.0

    def test_no_cache_still_emits_artifact(self, bench, monkeypatch,
                                           tmp_path, capsys):
        monkeypatch.setattr(bench, "CACHE_PATH",
                            str(tmp_path / "missing.json"))
        monkeypatch.setattr(bench, "_aot_overlap_evidence",
                            lambda: {"error": "skipped in test"})
        with pytest.raises(SystemExit) as exc:
            bench._degraded_exit("no cache case")
        assert exc.value.code == 0
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["stale"] is True and out["value"] is None
        assert "cache_error" in out

    @staticmethod
    def _run_tiny_bench(cache_path, *, force: bool):
        import subprocess

        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
            "BFTPU_BENCH_CACHE": str(cache_path),
            "BFTPU_DEVICE_INIT_TIMEOUT_S": "120",
        })
        if force:
            env["BFTPU_BENCH_CACHE_FORCE"] = "1"
        else:
            env.pop("BFTPU_BENCH_CACHE_FORCE", None)
        return subprocess.run(
            [sys.executable, "bench.py", "--batch", "2", "--image-size",
             "32", "--steps", "2", "--warmup", "1", "--skip-peak"],
            capture_output=True, text=True, env=env, cwd=_REPO, timeout=540)

    def test_success_path_end_to_end_on_cpu_mesh(self, tmp_path):
        """The driver's primary artifact is a SUCCESSFUL bench run; CI
        covers that path too: a tiny pinned run on the 8-device CPU mesh
        must emit the full JSON contract and (force-flagged) write the
        redirected cache."""
        cache = tmp_path / "cache.json"
        proc = self._run_tiny_bench(cache, force=True)
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        assert out["metric"] == "resnet50_images_per_sec_per_chip"
        assert out["value"] > 0
        assert out["batch"] == 2 and out["sweep"]
        assert out["flops_source"] in ("xla_cost_analysis", "analytic")
        cached = json.loads(cache.read_text())
        assert cached["value"] == out["value"] and "cached_at" in cached

    def test_cpu_platform_never_writes_the_cache(self, tmp_path):
        """The platform gate is authoritative: without the force flag a CPU
        run must NOT write even a redirected cache (and says so), so a
        debug run can never replace the last-good on-chip numbers that
        degraded mode later emits."""
        cache = tmp_path / "cache.json"
        proc = self._run_tiny_bench(cache, force=False)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert json.loads(proc.stdout.strip().splitlines()[-1])["value"] > 0
        assert not cache.exists()
        assert "not updating the last-good cache" in proc.stderr

    def test_repo_cache_is_valid_seed(self):
        """The COMMITTED BENCH_CACHE.json must parse and carry a real
        number, or degraded mode at the driver's capture emits nothing.
        (Deliberately not bench.CACHE_PATH: an ambient BFTPU_BENCH_CACHE
        would redirect that away from the repo seed under test.)"""
        with open(os.path.join(_REPO, "BENCH_CACHE.json")) as f:
            cached = json.load(f)
        assert cached["metric"] == "resnet50_images_per_sec_per_chip"
        assert cached["value"] > 0
        assert "cached_at" in cached
