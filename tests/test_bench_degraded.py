"""bench.py resilience: degraded mode + perf sanity gates.

Round 3 ended with BENCH_r03.json as a bare failure record (rc=1, relay
refused device init) — no perf artifact at all.  The verdict's directive:
device-init failure must emit last-good cached metrics flagged stale plus
AOT compile-only evidence and exit 0 (a round can never end with nothing),
and perf numbers must carry plausibility gates (the relay has produced
measured "peaks" off by >1000x from any physical chip).

Marked ``slow``: the rescue-ladder end-to-end paths spawn full bench.py
subprocess runs (~8 minutes total in this container — over half the
tier-1 870s budget), so the budgeted run (``-m 'not slow'``) excludes
this module and the full suite (plain ``pytest``) keeps it.
"""

import json
import os
import sys

import pytest

from tests._util import REPO as _REPO, load_script

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def bench():
    return load_script("bench.py")


class FakeDev:
    def __init__(self, kind):
        self.device_kind = kind


class TestNominalSpec:
    def test_known_kinds(self, bench):
        assert bench.nominal_spec([FakeDev("TPU v5 lite")]) == (197.0, 819.0)
        assert bench.nominal_spec([FakeDev("TPU v5p")]) == (459.0, 2765.0)
        assert bench.nominal_spec([FakeDev("TPU v4")]) == (275.0, 1228.0)
        assert bench.nominal_spec([FakeDev("TPU v6 lite")]) == (918.0, 1640.0)

    def test_longest_match_wins(self, bench):
        # "v5 lite" contains "v5"-family substrings; must not fall through
        # to a shorter key with different numbers
        tf, _ = bench.nominal_spec([FakeDev("tpu v5 lite chip")])
        assert tf == 197.0

    def test_unknown_kind(self, bench):
        assert bench.nominal_spec([FakeDev("QuantumAbacus 3000")]) == (None,
                                                                       None)


class TestSanityGates:
    def test_plausible_peak_uses_measured(self, bench):
        # 160 TF measured on a 197 TF chip: plausible
        f = bench.perf_sanity_fields(
            [FakeDev("TPU v5 lite")], peak_flops=160e12,
            achieved_flops=80e12, best_mem=None, flops_per_step=0,
            best_batch=128, best_ips=1000.0)
        assert f["measured_peak_plausible"] is True
        assert f["mfu_denominator"] == "measured_peak"
        assert f["mfu"] == f["mfu_vs_measured"] == 0.5

    def test_non_physical_peak_falls_back_to_spec(self, bench):
        # the round-3 failure shape: ~1000 PFLOP/s "measured" on one chip
        f = bench.perf_sanity_fields(
            [FakeDev("TPU v5 lite")], peak_flops=1000e15,
            achieved_flops=100e12, best_mem=None, flops_per_step=0,
            best_batch=128, best_ips=1000.0)
        assert f["measured_peak_plausible"] is False
        assert f["mfu_denominator"] == "nominal_spec"
        assert f["mfu"] == pytest.approx(100e12 / 197e12, rel=1e-3)
        # both denominators are still visible to the reader
        assert "mfu_vs_measured" in f and "mfu_vs_nominal" in f

    def test_mfu_above_one_is_flagged(self, bench):
        f = bench.perf_sanity_fields(
            [FakeDev("TPU v5 lite")], peak_flops=150e12,
            achieved_flops=400e12, best_mem=None, flops_per_step=0,
            best_batch=128, best_ips=1000.0)
        assert f["mfu_plausible"] is False

    def test_mfu_plausible_emitted_true_on_healthy_runs(self, bench):
        # the key must be PRESENT either way — absence is ambiguous
        f = bench.perf_sanity_fields(
            [FakeDev("TPU v5 lite")], peak_flops=160e12,
            achieved_flops=80e12, best_mem=None, flops_per_step=0,
            best_batch=128, best_ips=1000.0)
        assert f["mfu_plausible"] is True

    def test_relay_error_classifier(self, bench):
        relay = RuntimeError(
            "Unable to initialize backend 'axon': UNAVAILABLE: TPU backend "
            "setup/compile error (Unavailable).")
        broken = RuntimeError(
            "Unable to initialize backend 'tpu': UNKNOWN: TPU "
            "initialization failed: No jellyfish device found.")
        assert bench._is_relay_unavailable(relay) is True
        assert bench._is_relay_unavailable(broken) is False

    def test_roofline_estimate(self, bench):
        mem = {"temp": 8 << 30, "args": 100 << 20}  # 8 GiB act, 100 MiB args
        f = bench.perf_sanity_fields(
            [FakeDev("TPU v5 lite")], peak_flops=150e12,
            achieved_flops=50e12, best_mem=mem,
            flops_per_step=128 * 12.27e9, best_batch=128, best_ips=10000.0)
        r = f["roofline_estimate"]
        assert r["hbm_bytes_per_step_est"] == mem["temp"] + mem["args"]
        # 8.1 GiB over 819 GB/s ~ 10.6 ms; compute 1.57 TF over 197 TF ~ 8 ms
        assert r["min_step_ms_memory"] == pytest.approx(10.6, abs=0.5)
        assert r["bound"] == "memory"
        assert r["measured_step_ms"] == pytest.approx(12.8, abs=0.1)

    def test_unknown_device_reports_unverified(self, bench):
        f = bench.perf_sanity_fields(
            [FakeDev("mystery")], peak_flops=100e12, achieved_flops=10e12,
            best_mem=None, flops_per_step=0, best_batch=1, best_ips=1.0)
        assert f["mfu_denominator"] == "measured_peak_unverified"
        assert "nominal_peak_tflops_per_sec" not in f


class TestDegradedMode:
    def test_emits_stale_cache_and_exits_zero(self, bench, monkeypatch,
                                              tmp_path, capsys):
        cache = tmp_path / "cache.json"
        cache.write_text(json.dumps({
            "metric": "resnet50_images_per_sec_per_chip",
            "value": 97262.15, "unit": "images/sec/chip", "batch": 128,
            "vs_baseline": 270.173, "cached_at": "yesterday"}))
        monkeypatch.setattr(bench, "CACHE_PATH", str(cache))
        monkeypatch.setattr(bench, "_aot_overlap_evidence",
                            lambda: {"collective_windows": 12,
                                     "overlapped_fraction": 1.0})
        with pytest.raises(SystemExit) as exc:
            bench._degraded_exit("relay wedged (test)")
        assert exc.value.code == 0
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["stale"] is True
        assert out["value"] == 97262.15  # last-good number, not nothing
        assert out["degraded_reason"] == "relay wedged (test)"
        assert out["aot_overlap"]["overlapped_fraction"] == 1.0

    def test_no_cache_still_emits_artifact(self, bench, monkeypatch,
                                           tmp_path, capsys):
        monkeypatch.setattr(bench, "CACHE_PATH",
                            str(tmp_path / "missing.json"))
        monkeypatch.setattr(bench, "_aot_overlap_evidence",
                            lambda: {"error": "skipped in test"})
        with pytest.raises(SystemExit) as exc:
            bench._degraded_exit("no cache case")
        assert exc.value.code == 0
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["stale"] is True and out["value"] is None
        assert "cache_error" in out

    @staticmethod
    def _run_tiny_bench(cache_path, *, force: bool):
        import subprocess

        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
            "BFTPU_BENCH_CACHE": str(cache_path),
            "BFTPU_DEVICE_INIT_TIMEOUT_S": "120",
        })
        if force:
            env["BFTPU_BENCH_CACHE_FORCE"] = "1"
        else:
            env.pop("BFTPU_BENCH_CACHE_FORCE", None)
        return subprocess.run(
            [sys.executable, "bench.py", "--batch", "2", "--image-size",
             "32", "--steps", "2", "--warmup", "1", "--skip-peak"],
            capture_output=True, text=True, env=env, cwd=_REPO, timeout=540)

    def test_success_path_end_to_end_on_cpu_mesh(self, tmp_path):
        """The driver's primary artifact is a SUCCESSFUL bench run; CI
        covers that path too: a tiny pinned run on the 8-device CPU mesh
        must emit the full JSON contract and (force-flagged) write the
        redirected cache."""
        cache = tmp_path / "cache.json"
        proc = self._run_tiny_bench(cache, force=True)
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        assert out["metric"] == "resnet50_images_per_sec_per_chip"
        assert out["value"] > 0
        assert out["batch"] == 2 and out["sweep"]
        assert out["flops_source"] in ("xla_cost_analysis", "analytic")
        cached = json.loads(cache.read_text())
        assert cached["value"] == out["value"] and "cached_at" in cached

    def test_cpu_platform_never_writes_the_cache(self, tmp_path):
        """The platform gate is authoritative: without the force flag a CPU
        run must NOT write even a redirected cache (and says so), so a
        debug run can never replace the last-good on-chip numbers that
        degraded mode later emits."""
        cache = tmp_path / "cache.json"
        proc = self._run_tiny_bench(cache, force=False)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert json.loads(proc.stdout.strip().splitlines()[-1])["value"] > 0
        assert not cache.exists()
        assert "not updating the last-good cache" in proc.stderr

    def test_repo_cache_is_valid_seed(self):
        """The COMMITTED BENCH_CACHE.json must parse and carry a real
        number, or degraded mode at the driver's capture emits nothing.
        (Deliberately not bench.CACHE_PATH: an ambient BFTPU_BENCH_CACHE
        would redirect that away from the repo seed under test.)"""
        with open(os.path.join(_REPO, "BENCH_CACHE.json")) as f:
            cached = json.load(f)
        assert cached["metric"] == "resnet50_images_per_sec_per_chip"
        assert cached["value"] > 0
        assert "cached_at" in cached


class TestBestCorroboratedWins:
    """The cache holds the BEST credible number, not merely the latest: a
    pinned A/B run at a deliberately suboptimal batch/stem must not clobber
    the sweep optimum that degraded mode would later fall back to."""

    GOOD = {"metric": "resnet50_images_per_sec_per_chip", "value": 2510.0,
            "wall_clock_plausible": True, "batch": 256}

    def test_worse_corroborated_run_keeps_cache(self, bench):
        new = {"metric": "resnet50_images_per_sec_per_chip", "value": 2054.0,
               "wall_clock_plausible": True, "batch": 1024}
        assert bench._cached_beats(self.GOOD, new)

    def test_better_run_replaces_cache(self, bench):
        new = {"metric": "resnet50_images_per_sec_per_chip", "value": 2600.0,
               "wall_clock_plausible": True, "batch": 256}
        assert not bench._cached_beats(self.GOOD, new)

    def test_suspect_cache_entry_never_survives(self, bench):
        # cached value from a corrupt wall clock (uncorroborated) loses even
        # to a slower — but real — new measurement
        prev = dict(self.GOOD, value=284420.0, wall_clock_plausible=False)
        new = {"metric": "resnet50_images_per_sec_per_chip", "value": 2510.0,
               "wall_clock_plausible": True}
        assert not bench._cached_beats(prev, new)

    def test_trace_derived_cache_entry_is_credible(self, bench):
        # a sweep whose wall clock was corrupt but whose VALUE was demoted
        # to the trace-derived rate is ground truth, not suspect: a slower
        # corroborated A/B run must not clobber it
        prev = {"metric": "resnet50_images_per_sec_per_chip", "value": 2601.0,
                "wall_clock_plausible": False,
                "value_source": "profiler_trace"}
        new = {"metric": "resnet50_images_per_sec_per_chip", "value": 2054.0,
               "wall_clock_plausible": True, "batch": 1024}
        assert bench._cached_beats(prev, new)

    def test_traceless_tpu_run_never_clobbers_credible_cache(self, bench):
        # the documented corrupt case: trace capture OOMed, wall clock
        # claims 284k img/s — no wall_clock_plausible field at all.  The
        # credible cache must survive regardless of the claimed value.
        new = {"metric": "resnet50_images_per_sec_per_chip",
               "value": 284420.0, "value_source": "wall_clock"}
        assert bench._cached_beats(self.GOOD, new)

    def test_different_metric_or_empty_cache_is_replaced(self, bench):
        assert not bench._cached_beats(None, self.GOOD)
        assert not bench._cached_beats({"metric": "other", "value": 1e9,
                                        "wall_clock_plausible": True},
                                       self.GOOD)


class TestTraceCorroboration:
    """The profiler trace as timing ground truth (round-4 finding).

    On-chip evidence this round: at identical code and batch, the wall
    clock through the axon relay claimed a 3.6 ms step while the device's
    own trace recorded ~98 ms of op time per step — the wall clock can be
    corrupt by ~27x.  bench.py therefore cross-checks the wall clock
    against the trace's per-step device op time and reports the
    trace-derived throughput when the wall clock is impossible (a step
    cannot complete faster than the device spent executing its ops).
    """

    def test_healthy_wall_clock_is_kept(self, bench):
        # wall 100 ms/step vs device op time 80 ms: plausible (overhead on
        # top of device time) -> wall clock stays the headline
        ips, fields = bench.reconcile_timing(256, 2560.0, 80.0)
        assert ips == 2560.0
        assert fields["value_source"] == "wall_clock"
        assert fields["wall_clock_plausible"] is True
        assert fields["trace_device_step_ms"] == 80.0

    def test_corrupt_wall_clock_falls_back_to_trace(self, bench):
        # wall claims 3.6 ms/step; device spent 98 ms -> impossible
        wall_ips = 1024 / 3.6e-3
        ips, fields = bench.reconcile_timing(1024, wall_ips, 98.0)
        assert fields["value_source"] == "profiler_trace"
        assert fields["wall_clock_plausible"] is False
        assert abs(ips - 1024 / 98e-3) < 1.0
        assert fields["value_wall_clock"] == round(wall_ips, 2)

    def test_no_trace_keeps_wall_clock(self, bench):
        ips, fields = bench.reconcile_timing(128, 1000.0, None)
        assert ips == 1000.0 and fields == {"value_source": "wall_clock"}

    def test_trace_jitter_tolerance(self, bench):
        # wall marginally below device time (envelope jitter): tolerated
        ips, fields = bench.reconcile_timing(256, 256 / 95e-3, 100.0)
        assert fields["wall_clock_plausible"] is True
        assert fields["value_source"] == "wall_clock"

    def test_trace_step_ms_from_synthetic_trace(self, bench, tmp_path):
        """_trace_device_step_ms reads a TensorBoard-layout trace and
        averages device op time over PROFILE_STEPS, selecting only the
        'XLA Ops' thread (not step envelopes)."""
        import gzip

        run_dir = tmp_path / "plugins" / "profile" / "2026_07_31"
        run_dir.mkdir(parents=True)
        events = [
            {"ph": "M", "pid": 7, "name": "process_name",
             "args": {"name": "/device:TPU:0"}},
            {"ph": "M", "pid": 7, "tid": 1, "name": "thread_name",
             "args": {"name": "XLA Ops"}},
            {"ph": "M", "pid": 7, "tid": 2, "name": "thread_name",
             "args": {"name": "XLA Modules"}},
            # 3 steps x 2 ops of 1000 us on the op thread = 6000 us total
            *[{"ph": "X", "pid": 7, "tid": 1, "name": f"fusion.{i}",
               "ts": i * 1000, "dur": 1000} for i in range(6)],
            # module envelope spanning everything: must NOT be counted
            {"ph": "X", "pid": 7, "tid": 2, "name": "jit_step",
             "ts": 0, "dur": 6000},
        ]
        with gzip.open(run_dir / "host.trace.json.gz", "wt") as f:
            json.dump({"traceEvents": events}, f)
        got = bench._trace_device_step_ms(str(tmp_path))
        assert got is not None
        assert abs(got - 6000 / 1e3 / bench.PROFILE_STEPS) < 1e-9

    def test_host_only_trace_returns_none(self, bench, tmp_path):
        """A CPU-only capture (no device pid / XLA Ops thread) must not be
        used as timing ground truth."""
        import gzip

        run_dir = tmp_path / "plugins" / "profile" / "r"
        run_dir.mkdir(parents=True)
        events = [{"ph": "X", "pid": 1, "tid": 1, "name": "python",
                   "ts": 0, "dur": 500}]
        with gzip.open(run_dir / "host.trace.json.gz", "wt") as f:
            json.dump({"traceEvents": events}, f)
        assert bench._trace_device_step_ms(str(tmp_path)) is None

    def test_device_pid_without_op_threads_is_not_divided(self, bench,
                                                          tmp_path):
        """A trace with a TPU pid but no labeled 'XLA Ops' threads cannot
        distinguish chips from extra per-device streams (DMA etc.), so it
        must not be used as a per-chip timing floor at all — dividing the
        lane sum by stream count would understate the floor and weaken the
        corruption detector exactly on malformed traces."""
        import gzip

        run_dir = tmp_path / "plugins" / "profile" / "r"
        run_dir.mkdir(parents=True)
        events = [
            {"ph": "M", "pid": 7, "name": "process_name",
             "args": {"name": "/device:TPU:0"}},
            # two unlabeled streams under the device pid
            {"ph": "X", "pid": 7, "tid": 1, "name": "fusion.1",
             "ts": 0, "dur": 98000},
            {"ph": "X", "pid": 7, "tid": 2, "name": "dma", "ts": 0,
             "dur": 10000},
        ]
        with gzip.open(run_dir / "host.trace.json.gz", "wt") as f:
            json.dump({"traceEvents": events}, f)
        assert bench._trace_device_step_ms(str(tmp_path)) is None


class TestProvablyCorruptHeadline:
    """A sweep whose wall clock beats the chip's physical peak with no
    device trace to demote to must go DEGRADED (cache + stale flag), never
    print the corrupt value as the headline — observed live: 727k img/s
    'measured' (mfu 116.8) while the relay exported host-only traces."""

    def test_uncorroborated_superphysical_is_corrupt(self, bench):
        out = {"value_source": "wall_clock", "mfu_vs_nominal": 116.8}
        assert bench._headline_provably_corrupt(out)

    def test_trace_corroborated_run_is_kept(self, bench):
        # wall_clock_plausible present (either verdict) = the trace judged
        # it — reconcile_timing already handled any demotion
        out = {"value_source": "wall_clock", "mfu_vs_nominal": 116.8,
               "wall_clock_plausible": True}
        assert not bench._headline_provably_corrupt(out)

    def test_trace_derived_headline_is_kept(self, bench):
        out = {"value_source": "profiler_trace", "mfu_vs_nominal": 0.31}
        assert not bench._headline_provably_corrupt(out)

    def test_physical_mfu_is_kept(self, bench):
        out = {"value_source": "wall_clock", "mfu_vs_nominal": 0.31}
        assert not bench._headline_provably_corrupt(out)

    def test_cpu_run_without_spec_is_kept(self, bench):
        assert not bench._headline_provably_corrupt(
            {"value_source": "wall_clock", "mfu_vs_nominal": None})
        assert not bench._headline_provably_corrupt(
            {"value_source": "wall_clock"})


class TestRescueLadder:
    """Round-4 verdict #1: after a failed sweep, bench must walk a
    descending-batch ladder with device buffers freed between compiles
    and only then fall back to the cache."""

    def test_first_success_wins(self, bench):
        calls, freed = [], []

        def attempt(b):
            calls.append(b)
            if b > 32:
                raise MemoryError("RESOURCE_EXHAUSTED: out of memory")
            return ("result", b)

        got = bench.rescue_ladder(attempt, free=lambda: freed.append(1) or 7,
                                  log=lambda m: None)
        assert got == (32, ("result", 32))
        assert calls == [128, 64, 32]  # stops at the first success
        # memory freed BEFORE every attempt, including the first
        assert len(freed) == 3

    def test_total_failure_returns_none(self, bench):
        def attempt(b):
            raise RuntimeError("UNAVAILABLE: relay wedged")

        assert bench.rescue_ladder(attempt, log=lambda m: None) is None

    def test_any_exception_moves_down_a_rung(self, bench):
        """Relay failures are often NOT RESOURCE_EXHAUSTED (opaque
        UNAVAILABLE/INTERNAL) — the ladder must not care."""
        seen = []

        def attempt(b):
            seen.append(b)
            if b != 16:
                raise ValueError("INTERNAL: something opaque")
            return "ok"

        assert bench.rescue_ladder(attempt, log=lambda m: None) == (16, "ok")
        assert seen == [128, 64, 32, 16]

    def test_free_device_memory_runs_on_cpu(self):
        """The buffer sweep must be safe to call anywhere (returns a
        count, never raises).  Subprocess: it deletes EVERY live array in
        its process, which would poison other tests' cached arrays."""
        import subprocess

        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.config.update('jax_platforms', 'cpu')\n"
             "from tests._util import load_script\n"
             "import jax.numpy as jnp\n"
             "bench = load_script('bench.py')\n"
             "x = jnp.ones((8, 8)) + 1\n"
             "n = bench._free_device_memory()\n"
             "assert isinstance(n, int) and n >= 1, n\n"
             "print('FREED', n)\n"],
            capture_output=True, text=True, cwd=_REPO, timeout=120)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "FREED" in proc.stdout

    def test_sweep_collapse_lands_fresh_number_via_ladder(self, tmp_path):
        """Integration: main()'s empty-results path must call the ladder
        and headline its fresh point instead of degrading to cache.
        Subprocess: the ladder's buffer-freeing deletes every live array
        in its process."""
        import subprocess

        driver = tmp_path / "driver.py"
        driver.write_text(f"""
import json, sys
sys.path.insert(0, {str(_REPO)!r})
import jax
jax.config.update('jax_platforms', 'cpu')
from tests._util import load_script
bench = load_script('bench.py')
real_run, attempts = bench.run, []

def failing_run(args, batch):
    attempts.append(batch)
    if batch > 16:
        # deliberately NOT an OOM: an opaque relay error on the first
        # sweep point leaves results empty (the sweep's own halving only
        # handles RESOURCE_EXHAUSTED) — exactly the collapse the ladder
        # exists for
        raise RuntimeError('UNAVAILABLE: relay wedged mid-compile')
    return real_run(args, batch)

bench.run = failing_run
sys.argv = ['bench.py', '--image-size', '32', '--steps', '2',
            '--warmup', '1', '--skip-peak']
bench.main()
print('ATTEMPTS', json.dumps(attempts), file=sys.stderr)
""")
        env = dict(os.environ,
                   JAX_PLATFORMS="cpu",
                   XLA_FLAGS="--xla_force_host_platform_device_count=8",
                   PALLAS_AXON_POOL_IPS="")
        proc = subprocess.run([sys.executable, str(driver)],
                              capture_output=True, text=True, cwd=_REPO,
                              env=env, timeout=540)
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        assert out["batch"] == 16 and out["value"] > 0
        assert "stale" not in out
        # sweep died at 128 (non-OOM, no results), then the ladder walked
        # 128/64/32 (failing) -> 16 (landed fresh; 8 never needed)
        attempts = json.loads(
            [l for l in proc.stderr.splitlines()
             if l.startswith("ATTEMPTS")][-1].split(" ", 1)[1])
        assert attempts == [128, 128, 64, 32, 16]
