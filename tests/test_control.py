"""Self-tuning communication control plane (bluefog_tpu.control).

1. plan/evidence — canonical byte encodings, clamping, json round
   trips (NaN included), newest-round-wins canonicalization, torn/
   missing barrier-dir records tolerated;
2. convergence — the PROPERTY the coordinator-free design rests on:
   N independent controllers fed the same disseminated records (in any
   order) produce byte-identical CommPlans, over seeded random
   evidence;
3. no-flap — hysteresis holds the plan steady under telemetry
   oscillating around a single threshold, and cooldowns bound the
   change rate under genuinely oscillating regimes;
4. decision table — slow-set enter/exit (lag ratio, reconnect deltas,
   the max_slow_frac cap), densify ladder, codec backoff, cadence
   band;
5. penalized replan — determinism, ring-spine strong connectivity,
   degree reduction, composition/memorylessness, provenance-name
   collapse;
6. wire telemetry — DepositStream ack EWMA accessor + reconnect
   counter + codec-ceiling discipline against a live WindowServer;
7. integration — thread-mode run_async_dsgd(control=...) with one
   deliberately slow rank: the fleet converges on a plan that drops
   the slow rank's edges and the EXACT mass audit holds through every
   plan change; a slow-marked MP tcp scenario does the same under a
   chaos lossy link (tests/_mp_control_worker.py).

Everything deterministic: seeded RNGs, counter triggers, pure decision
functions.
"""

import json
import math
import os
import random
import subprocess
import sys
import time

import numpy as np
import pytest

from tests._util import REPO as _REPO, clean_env, uniq as _uniq

from bluefog_tpu.control import (CODEC_LADDER, CommController, CommPlan,
                                 ControlConfig, Evidence, EvidenceBoard,
                                 canonicalize, decide_plan, plan_topology,
                                 read_evidence, write_evidence)


@pytest.fixture(autouse=True)
def _chaos_isolated():
    from bluefog_tpu import chaos

    chaos.reset()
    yield
    chaos.reset()


# ---------------------------------------------------------------------------
# 1. plan / evidence encodings
# ---------------------------------------------------------------------------


class TestCommPlan:
    def test_bytes_roundtrip(self):
        p = CommPlan(version=3, round=40, slow=(5, 1), densify=1,
                     gossip_every=2, codec_level=1)
        q = CommPlan.from_bytes(p.to_bytes())
        assert q == p
        assert q.slow == (1, 5)  # normalized sorted

    def test_canonical_bytes_are_key_sorted_json(self):
        blob = CommPlan(version=1, round=2).to_bytes()
        d = json.loads(blob)
        assert list(d) == sorted(d)

    def test_clamping(self):
        p = CommPlan(densify=99, gossip_every=0, codec_level=99)
        assert p.densify == 2
        assert p.gossip_every == 1
        assert p.codec_level == len(CODEC_LADDER) - 1

    def test_codec_property(self):
        assert CommPlan(codec_level=0).codec is None
        assert CommPlan(codec_level=1).codec == "f32"
        assert CommPlan(codec_level=2).codec == "topk"


class TestControlConfig:
    def test_hysteresis_bands_enforced(self):
        with pytest.raises(ValueError, match="slow_exit < slow_enter"):
            ControlConfig(slow_enter=2.0, slow_exit=2.0)
        with pytest.raises(ValueError, match="densify"):
            ControlConfig(densify_enter=0.01, densify_exit=0.02)
        with pytest.raises(ValueError, match="grow_lo < grow_hi"):
            ControlConfig(grow_hi=0.5, grow_lo=0.9)
        with pytest.raises(ValueError, match="cooldown"):
            ControlConfig(cooldown_rounds=0)
        with pytest.raises(ValueError, match="max_codec_level"):
            ControlConfig(max_codec_level=7)


class TestEvidence:
    def test_json_roundtrip_including_nan(self):
        ev = Evidence(rank=2, round=17, lag_s={3: 0.25, 1: 0.001},
                      states={3: 1}, reconnects={3: 2},
                      mixing_excess=float("nan"),
                      consensus_growth=1.25)
        back = Evidence.from_json(ev.to_json())
        assert back.rank == 2 and back.round == 17
        assert back.lag_s == {3: 0.25, 1: 0.001}
        assert back.reconnects == {3: 2}
        assert math.isnan(back.mixing_excess)
        assert back.consensus_growth == 1.25
        # canonical: two encodings of the same record are identical
        assert back.to_json() == ev.to_json()

    def test_canonicalize_newest_round_per_rank_sorted(self):
        evs = [Evidence(rank=1, round=5), Evidence(rank=0, round=9),
               Evidence(rank=1, round=8), Evidence(rank=2, round=1)]
        out = canonicalize(evs)
        assert [e.rank for e in out] == [0, 1, 2]
        assert out[1].round == 8

    def test_records_roundtrip_and_torn_tolerated(self, tmp_path):
        d = str(tmp_path)
        write_evidence(d, Evidence(rank=0, round=3, lag_s={1: 0.1}))
        write_evidence(d, Evidence(rank=2, round=4))
        # a torn/garbage record and a missing one must both be skipped
        with open(os.path.join(d, "ctlev.1"), "w") as f:
            f.write('{"rank": 1, "rou')
        out = read_evidence(d, 4)
        assert sorted(e.rank for e in out) == [0, 2]
        assert out[0].lag_s == {1: 0.1}

    def test_board_newest_round_wins(self):
        b = EvidenceBoard()
        b.publish(Evidence(rank=1, round=8, lag_s={0: 0.5}))
        b.publish(Evidence(rank=1, round=4, lag_s={0: 0.1}))
        (ev,) = b.snapshot()
        assert ev.round == 8 and ev.lag_s == {0: 0.5}

    def test_state_constants_match_resilience(self):
        # control is an import-leaf package, so it spells the two
        # health states it consumes locally — pin them to the canonical
        # values
        from bluefog_tpu.control import controller as C
        from bluefog_tpu.runtime import resilience as res

        assert C._ST_SUSPECT == res.SUSPECT
        assert C._ST_DEAD == res.DEAD


# ---------------------------------------------------------------------------
# 2. plan convergence (the coordinator-free property)
# ---------------------------------------------------------------------------


def _random_evidence(rng, n, round_):
    evs = []
    for r in range(n):
        lag = {j: rng.choice([0.001, 0.003, 0.05, 0.4])
               for j in range(n) if j != r and rng.random() < 0.8}
        rec = {j: rng.choice([0, 0, 0, 1, 3])
               for j in lag if rng.random() < 0.3}
        evs.append(Evidence(
            rank=r, round=round_ + rng.randrange(3), lag_s=lag,
            states={j: rng.choice([0, 0, 1]) for j in lag},
            reconnects=rec,
            mixing_excess=rng.choice([float("nan"), -0.05, 0.3]),
            consensus_growth=rng.choice([float("nan"), 0.5, 0.9, 1.3])))
    return evs


class TestPlanConvergence:
    def test_same_records_byte_identical_plans(self):
        """N ranks, same disseminated records (any order) -> the SAME
        CommPlan, byte for byte — over 30 seeded random evidence
        multisets and three decision generations each."""
        n = 8
        cfg = ControlConfig(cooldown_rounds=2, min_lag_s=0.002,
                            max_codec_level=2)
        for trial in range(30):
            rng = random.Random(1000 + trial)
            ctls = [CommController(r, n, config=cfg) for r in range(n)]
            for gen in range(3):
                rnd = 10 + gen * 10
                evs = _random_evidence(rng, n, rnd)
                blobs = set()
                for c in ctls:
                    shuffled = list(evs)
                    rng2 = random.Random(trial * 100 + c.rank)
                    rng2.shuffle(shuffled)
                    blobs.add(c.decide(rnd, shuffled).to_bytes())
                assert len(blobs) == 1, (trial, gen, blobs)

    def test_decide_plan_is_pure(self):
        rng = random.Random(7)
        evs = _random_evidence(rng, 4, 10)
        cfg = ControlConfig()
        prev = CommPlan()
        a = decide_plan(prev, 10, evs, cfg)
        b = decide_plan(prev, 10, tuple(reversed(evs)), cfg)
        assert a.to_bytes() == b.to_bytes()


# ---------------------------------------------------------------------------
# 3. no-flap: hysteresis + cooldown
# ---------------------------------------------------------------------------


def _lag_evidence(n, round_, lag_of_3):
    return [Evidence(rank=r, round=round_,
                     lag_s={3: lag_of_3, (r + 1) % n: 0.01})
            for r in range(n) if r != 3]


class TestNoFlap:
    def test_oscillation_inside_band_never_flaps(self):
        """Telemetry oscillating INSIDE the hysteresis band (above exit,
        below enter) holds the plan at its current state forever —
        both before the peer ever entered and after it entered."""
        n, cfg = 4, ControlConfig(cooldown_rounds=1, min_lag_s=0.001,
                                  slow_enter=4.0, slow_exit=2.0)
        c = CommController(0, n, config=cfg)
        # fleet median ~0.01 -> enter at 0.04, exit at 0.02
        for k in range(40):  # oscillate in (exit, enter): no entry ever
            c.decide(k, _lag_evidence(n, k, 0.025 + 0.01 * (k % 2)))
        assert c.plan.version == 0, c.plan
        # drive it IN (above enter), then oscillate inside the band:
        # it entered once and STAYS in — no release, no re-entry churn
        c.decide(50, _lag_evidence(n, 50, 0.5))
        assert c.plan.slow == (3,) and c.plan.version == 1
        for k in range(51, 90):
            c.decide(k, _lag_evidence(n, k, 0.025 + 0.01 * (k % 2)))
        assert c.plan.slow == (3,)
        assert c.plan.version == 1, "plan flapped inside the band"

    def test_cooldown_bounds_change_rate(self):
        """Even telemetry oscillating ACROSS both bands cannot change
        the plan more often than once per cooldown window."""
        n, cool = 4, 8
        cfg = ControlConfig(cooldown_rounds=cool, min_lag_s=0.001)
        c = CommController(0, n, config=cfg)
        for k in range(80):
            lag = 0.5 if (k // 2) % 2 == 0 else 0.001  # wild swings
            c.decide(k, _lag_evidence(n, k, lag))
        assert c.plan_changes <= 80 // cool + 1, c.plan_changes

    def test_cooldown_refuses_early_change(self):
        cfg = ControlConfig(cooldown_rounds=16, min_lag_s=0.001)
        c = CommController(0, 4, config=cfg)
        p1 = c.decide(10, _lag_evidence(4, 10, 0.5))
        assert p1.version == 1
        p2 = c.decide(12, _lag_evidence(4, 12, 0.001))  # inside cooldown
        assert p2 is p1
        p3 = c.decide(10 + 16, _lag_evidence(4, 26, 0.001))
        assert p3.version == 2 and p3.slow == ()


# ---------------------------------------------------------------------------
# 4. decision table
# ---------------------------------------------------------------------------


class TestDecisionTable:
    CFG = ControlConfig(cooldown_rounds=1, min_lag_s=0.001,
                        max_codec_level=2)

    def test_slow_enter_by_lag_ratio_median_of_reporters(self):
        plan = decide_plan(CommPlan(), 10,
                           _lag_evidence(4, 10, 0.5), self.CFG)
        assert plan.slow == (3,)

    def test_one_confused_reporter_cannot_convict(self):
        evs = [Evidence(rank=0, round=10, lag_s={3: 9.0, 1: 0.01}),
               Evidence(rank=1, round=10, lag_s={3: 0.01, 2: 0.01}),
               Evidence(rank=2, round=10, lag_s={3: 0.01, 0: 0.01})]
        plan = decide_plan(CommPlan(), 10, evs, self.CFG)
        assert plan.slow == ()  # median over reporters is healthy

    def test_slow_enter_by_reconnect_delta(self):
        evs = [Evidence(rank=r, round=10, lag_s={(r + 1) % 4: 0.01},
                        reconnects={3: 1}) for r in range(3)]
        plan = decide_plan(CommPlan(), 10, evs, self.CFG)
        assert plan.slow == (3,)

    def test_release_requires_clean_lag_and_no_reconnects(self):
        prev = CommPlan(version=1, round=0, slow=(3,))
        # still reconnecting -> held
        evs = [Evidence(rank=r, round=10, lag_s={3: 0.001, 1: 0.01},
                        reconnects={3: 1}) for r in (0, 2)]
        plan = decide_plan(prev, 10, evs, self.CFG)
        assert plan.slow == (3,)
        # clean lag below exit AND quiet wire -> released
        evs = [Evidence(rank=r, round=20, lag_s={3: 0.001, 1: 0.01})
               for r in (0, 2)]
        plan = decide_plan(prev, 20, evs, self.CFG)
        assert plan.slow == ()

    def test_max_slow_frac_cap_prefers_worst(self):
        # three peers far above the healthy fleet median, but the cap
        # only lets the worst two of the eight LIVE reporters be
        # penalized (the reporter count is the live-fleet proxy —
        # capacity would let a shrunk elastic fleet be penalized
        # wholesale)
        lags = {1: 0.5, 2: 0.9, 3: 0.7, 4: 0.001,
                5: 0.001, 6: 0.001, 7: 0.001}
        evs = [Evidence(rank=r, round=10,
                        lag_s={j: v for j, v in lags.items() if j != r})
               for r in range(8)]
        plan = decide_plan(CommPlan(), 10, evs,
                           ControlConfig(cooldown_rounds=1,
                                         min_lag_s=0.001,
                                         max_slow_frac=0.25))
        assert plan.slow == (2, 3)  # worst two of eight (cap = 2)
        # a lone reporter may still penalize ONE peer (cap floors at 1)
        plan1 = decide_plan(CommPlan(), 10, [evs[0]],
                            ControlConfig(cooldown_rounds=1,
                                          min_lag_s=0.001,
                                          max_slow_frac=0.25))
        assert plan1.slow == (2,)

    def test_slow_enter_by_majority_suspicion(self):
        # a wedged peer can have an unremarkable ack EWMA (the last ack
        # before the wedge was fast): a MAJORITY of reporters holding it
        # SUSPECT/DEAD is entry evidence in its own right
        evs = [Evidence(rank=r, round=10, lag_s={(r + 1) % 4: 0.01},
                        states={3: 1}) for r in range(3)]
        plan = decide_plan(CommPlan(), 10, evs, self.CFG)
        assert plan.slow == (3,)
        # a single suspicious reporter among three is not a majority
        evs = [Evidence(rank=0, round=10, lag_s={1: 0.01}, states={3: 1}),
               Evidence(rank=1, round=10, lag_s={2: 0.01}),
               Evidence(rank=2, round=10, lag_s={0: 0.01})]
        assert decide_plan(CommPlan(), 10, evs, self.CFG).slow == ()

    def test_any_suspicion_holds_a_penalized_peer(self):
        prev = CommPlan(version=1, round=0, slow=(3,))
        evs = [Evidence(rank=r, round=20, lag_s={3: 0.001, 1: 0.01},
                        states={3: 1} if r == 0 else {})
               for r in (0, 2)]
        plan = decide_plan(prev, 20, evs, self.CFG)
        assert plan.slow == (3,)  # one suspicious reporter holds it in

    def test_densify_ladder_up_and_down(self):
        cfg = self.CFG
        evs = [Evidence(rank=0, round=10, lag_s={1: 0.01},
                        mixing_excess=0.5)]
        p1 = decide_plan(CommPlan(), 10, evs, cfg)
        assert p1.densify == 1
        evs = [Evidence(rank=0, round=20, lag_s={1: 0.01},
                        mixing_excess=0.0)]
        p2 = decide_plan(p1, 20, evs, cfg)
        assert p2.densify == 0

    def test_densify_top_rung_is_size_aware(self):
        """The digital twin's scale-blindness finding, fixed: above
        ``densify_full_max`` live reporters the ladder tops out at the
        symmetric-exponential rung (level 1) — the one-step exact
        averager (level 2, ~m^2 edges) stays reachable only for small
        fleets, so fleet-scale runs can keep the ladder ENABLED."""
        cfg = ControlConfig(cooldown_rounds=1, min_lag_s=0.001,
                            densify_full_max=16)
        # a SMALL fleet under sustained excess climbs to the top rung
        small = [Evidence(rank=r, round=10, lag_s={1: 0.01},
                          mixing_excess=0.5) for r in range(8)]
        p = decide_plan(CommPlan(densify=1, version=1), 10, small, cfg)
        assert p.densify == 2
        # a LARGE fleet (reporter count is the live-member proxy) is
        # capped at the symmetric-exponential rung no matter how long
        # the excess persists
        big = [Evidence(rank=r, round=10, lag_s={1: 0.01},
                        mixing_excess=0.5) for r in range(64)]
        p = decide_plan(CommPlan(densify=1, version=1), 10, big, cfg)
        assert p.densify == 1
        p2 = decide_plan(p, 20, [Evidence(rank=r, round=20,
                                          lag_s={1: 0.01},
                                          mixing_excess=0.5)
                                 for r in range(64)], cfg)
        assert p2.densify == 1  # held at the cap, not oscillating
        # a previously-FC plan shrinking INTO a big fleet is stepped
        # back down to the capped rung
        p3 = decide_plan(CommPlan(densify=2, version=1), 30, big, cfg)
        assert p3.densify == 1

    def test_densify_full_max_validated(self):
        with pytest.raises(ValueError, match="densify_full_max"):
            ControlConfig(densify_full_max=0)

    def test_codec_backs_off_when_consensus_grows(self):
        prev = CommPlan(version=1, round=0, codec_level=2)
        evs = [Evidence(rank=0, round=10, lag_s={1: 0.01},
                        consensus_growth=1.5)]
        plan = decide_plan(prev, 10, evs, self.CFG)
        assert plan.codec_level == 1
        assert plan.gossip_every == 1

    def test_codec_rearms_toward_ceiling_when_contracting(self):
        prev = CommPlan(version=1, round=0, codec_level=0)
        evs = [Evidence(rank=0, round=10, lag_s={1: 0.01},
                        consensus_growth=0.5)]
        plan = decide_plan(prev, 10, evs, self.CFG)
        assert plan.codec_level == 1

    def test_codec_never_exceeds_config_ceiling(self):
        cfg = ControlConfig(cooldown_rounds=1, min_lag_s=0.001,
                            max_codec_level=0)
        prev = CommPlan(version=1, round=0, codec_level=2)
        evs = [Evidence(rank=0, round=10, lag_s={1: 0.01},
                        consensus_growth=0.5)]
        plan = decide_plan(prev, 10, evs, cfg)
        assert plan.codec_level == 0

    def test_cadence_stretches_only_under_slow_links(self):
        # contracting comfortably + NO slow links: cadence stays 1
        evs = [Evidence(rank=0, round=10, lag_s={1: 0.01},
                        consensus_growth=0.5)]
        plan = decide_plan(CommPlan(), 10, evs, self.CFG)
        assert plan.gossip_every == 1
        # contracting comfortably + a slow link: stretch
        evs = _lag_evidence(4, 20, 0.5)
        evs = [Evidence(rank=e.rank, round=e.round, lag_s=e.lag_s,
                        consensus_growth=0.5) for e in evs]
        plan2 = decide_plan(CommPlan(), 20, evs, self.CFG)
        assert plan2.slow == (3,) and plan2.gossip_every == 2

    def test_cadence_shrinks_when_consensus_grows(self):
        prev = CommPlan(version=1, round=0, gossip_every=4)
        evs = [Evidence(rank=0, round=10, lag_s={1: 0.01},
                        consensus_growth=1.5)]
        plan = decide_plan(prev, 10, evs, self.CFG)
        assert plan.gossip_every == 2

    def test_empty_or_stale_evidence_keeps_plan(self):
        prev = CommPlan(version=2, round=0, slow=(1,))
        assert decide_plan(prev, 50, [], self.CFG) is prev


def _phase_lag_evidence(n, round_, lag_of_3, phases):
    return [Evidence(rank=r, round=round_,
                     lag_s={3: lag_of_3, (r + 1) % n: 0.01},
                     phase_s={3: dict(phases)})
            for r in range(n) if r != 3]


class TestPhaseEvidence:
    """Tracing-fed link-vs-host split: the same lag conviction routes
    to the codec (slow LINK, net-dominated) or the ring spine (slow
    HOST / no phase evidence) — pure and byte-convergent either way."""

    CFG = ControlConfig(cooldown_rounds=1, min_lag_s=0.001,
                        max_codec_level=2)

    def test_evidence_phase_roundtrip_and_canonical(self):
        ev = Evidence(rank=0, round=9, lag_s={3: 0.5},
                      phase_s={3: {"net": 0.4, "queue": 0.05,
                                   "apply": 0.05}})
        back = Evidence.from_json(ev.to_json())
        assert back.phase_s == {3: {"net": 0.4, "queue": 0.05,
                                    "apply": 0.05}}
        assert back.to_json() == ev.to_json()
        # non-finite phase values are dropped at canonicalization
        ev2 = Evidence(rank=0, round=9,
                       phase_s={3: {"net": float("nan")}})
        assert ev2.phase_s == {}

    def test_pre_tracing_record_parses_and_decides_identically(self):
        old = ('{"consensus_growth":null,"lag_s":{"1":0.01,"3":0.5},'
               '"mixing_excess":null,"rank":0,"reconnects":{},'
               '"round":10,"states":{}}')
        ev = Evidence.from_json(old)
        assert ev.phase_s == {}
        plan = decide_plan(CommPlan(), 10, [ev] + _lag_evidence(
            4, 10, 0.5)[1:], self.CFG)
        assert plan.slow == (3,)  # the phase-blind table, unchanged

    def test_net_dominated_lag_routes_to_codec_not_spine(self):
        evs = _phase_lag_evidence(4, 10, 0.5,
                                  {"net": 0.4, "queue": 0.05,
                                   "apply": 0.05})
        plan = decide_plan(CommPlan(), 10, evs, self.CFG)
        assert plan.slow == ()        # no ring-spine penalty
        assert plan.codec_level == 1  # one rung harder instead

    def test_host_dominated_lag_stays_spine_territory(self):
        evs = _phase_lag_evidence(4, 10, 0.5,
                                  {"net": 0.05, "queue": 0.35,
                                   "apply": 0.10})
        plan = decide_plan(CommPlan(), 10, evs, self.CFG)
        assert plan.slow == (3,)
        assert plan.codec_level == 0

    def test_growth_backoff_does_not_cancel_link_remedy(self):
        """A convicted link-slow peer must get SOME remedy even when
        the grow_hi band backs the codec off the same window: the +1
        bump would be cancelled by the -1, so the diversion falls back
        to the spine instead of silently dropping the remedy."""
        prev = CommPlan(version=1, round=0, codec_level=1)
        evs = [Evidence(rank=e.rank, round=e.round, lag_s=e.lag_s,
                        phase_s=e.phase_s, consensus_growth=1.5)
               for e in _phase_lag_evidence(
                   4, 20, 0.5, {"net": 0.4, "queue": 0.05,
                                "apply": 0.05})]
        plan = decide_plan(prev, 20, evs, self.CFG)
        assert plan.codec_level == 0   # the grow_hi back-off held
        assert plan.slow == (3,)       # the spine is the fallback

    def test_grow_lo_rearm_already_is_the_link_remedy(self):
        """When grow_lo re-armed the codec the same window, the codec
        already rose — no double bump, no spine."""
        evs = [Evidence(rank=e.rank, round=e.round, lag_s=e.lag_s,
                        phase_s=e.phase_s, consensus_growth=0.5)
               for e in _phase_lag_evidence(
                   4, 10, 0.5, {"net": 0.4, "queue": 0.05,
                                "apply": 0.05})]
        plan = decide_plan(CommPlan(), 10, evs, self.CFG)
        assert plan.slow == ()
        assert plan.codec_level == 1  # one rung, not two

    def test_no_codec_headroom_falls_back_to_spine(self):
        """A convicted peer always gets SOME remedy: at the codec
        ceiling, a link-slow peer still takes the spine penalty."""
        cfg = ControlConfig(cooldown_rounds=1, min_lag_s=0.001,
                            max_codec_level=0)
        evs = _phase_lag_evidence(4, 10, 0.5,
                                  {"net": 0.4, "queue": 0.05,
                                   "apply": 0.05})
        plan = decide_plan(CommPlan(), 10, evs, cfg)
        assert plan.slow == (3,)

    def test_lossy_or_suspected_is_never_diverted(self):
        """Reconnect/suspicion evidence stays spine territory even
        when the phases look net-dominated — a flapping peer is not
        fixed by a smaller payload."""
        evs = [Evidence(rank=r, round=10, lag_s={3: 0.5, 1: 0.01},
                        reconnects={3: 1},
                        phase_s={3: {"net": 0.4, "queue": 0.01,
                                     "apply": 0.01}})
               for r in (0, 1, 2)]
        plan = decide_plan(CommPlan(), 10, evs, self.CFG)
        assert plan.slow == (3,)

    def test_byte_convergence_with_phase_records(self):
        import random

        evs = _phase_lag_evidence(4, 10, 0.5,
                                  {"net": 0.4, "queue": 0.05,
                                   "apply": 0.05})
        plans = []
        for seed in range(6):
            shuffled = list(evs)
            random.Random(seed).shuffle(shuffled)
            plans.append(decide_plan(CommPlan(), 10, shuffled,
                                     self.CFG).to_bytes())
        assert len(set(plans)) == 1

    def test_controller_plumbs_phase_to_evidence(self):
        ctl = CommController(0, 4)
        ctl.note_peer(3, lag_s=0.5,
                      phase_s={"net": 0.4, "queue": 0.05,
                               "apply": 0.05})
        ctl.note_peer(2, lag_s=0.01, phase_s=None)  # tracing off
        ev = ctl.evidence(10)
        assert ev.phase_s == {3: {"apply": 0.05, "net": 0.4,
                                  "queue": 0.05}}
        ctl.forget_peer(3)
        assert ctl.evidence(11).phase_s == {}

    def test_retain_peers_drops_stale_phase(self):
        ctl = CommController(0, 4)
        ctl.note_peer(3, phase_s={"net": 1.0})
        ctl.retain_peers([1, 2])
        assert ctl.evidence(5).phase_s == {}

    def test_link_net_frac_validated(self):
        with pytest.raises(ValueError):
            ControlConfig(link_net_frac=0.0)
        with pytest.raises(ValueError):
            ControlConfig(link_net_frac=1.5)


# ---------------------------------------------------------------------------
# 5. penalized replan
# ---------------------------------------------------------------------------


class TestPenalizedReplan:
    def test_deterministic_and_memoryless(self):
        from bluefog_tpu import topology as T

        base = T.ExponentialTwoGraph(8)
        a = T.replan_penalized(base, [0, 2, 4, 6], slow=[4], densify=1)
        b = T.replan_penalized(T.replan(base, [0, 1]), [0, 2, 4, 6],
                               slow=[4, 7], densify=1)  # 7 not a member
        np.testing.assert_allclose(a.weights, b.weights)
        # ONE collapsed provenance suffix, never a chain
        assert b.name.count("+") == 1 and "+ctl(" in b.name

    def test_no_penalty_no_densify_is_replan(self):
        from bluefog_tpu import topology as T

        base = T.ExponentialTwoGraph(8)
        mem = [0, 1, 3, 5, 7]
        np.testing.assert_allclose(
            T.replan_penalized(base, mem).weights,
            T.replan(base, mem).weights)

    def test_slow_peer_degree_reduced_to_ring_spine(self):
        from bluefog_tpu import topology as T

        base = T.ExponentialTwoGraph(8)
        full = T.replan_penalized(base, range(8))
        pen = T.replan_penalized(base, range(8), slow=[3])
        assert pen.in_degree(3) == 1 and pen.out_degree(3) == 1
        assert pen.in_degree(3) < full.in_degree(3)
        # the spine: sorted-member ring edges 2->3 and 3->4 survive
        assert pen.weights[3, 2] > 0 and pen.weights[4, 3] > 0

    def test_every_plan_strongly_connected_and_stochastic(self):
        """Seeded sweep over member sets, slow sets, and densify
        levels: every actuatable plan passes the full topology verifier
        (row-stochastic, strongly connected active submatrix, inert
        inactive rows)."""
        from bluefog_tpu import topology as T
        from bluefog_tpu.analysis.topology_check import check_topology

        base = T.ExponentialTwoGraph(9)
        rng = random.Random(42)
        for _ in range(40):
            m = rng.randrange(1, 10)
            members = sorted(rng.sample(range(9), m))
            n_slow = rng.randrange(0, m + 1)
            slow = rng.sample(members, n_slow)
            topo = T.replan_penalized(base, members, slow=slow,
                                      densify=rng.randrange(3))
            errs = [d for d in check_topology(topo)
                    if d.severity == "error"]
            assert not errs, (members, slow, [d.message for d in errs])

    def test_plan_topology_ignores_nonmember_slow(self):
        from bluefog_tpu import topology as T

        base = T.ExponentialTwoGraph(6)
        plan = CommPlan(version=1, slow=(2, 5))
        topo = plan_topology(base, [0, 1, 2, 3], plan)
        assert topo.inactive == frozenset({4, 5})
        assert topo.in_degree(2) == 1  # member slow applied


# ---------------------------------------------------------------------------
# 6. wire telemetry (ack EWMA, reconnect counter, codec ceiling)
# ---------------------------------------------------------------------------


class TestWireTelemetry:
    def test_ack_ewma_and_reconnects_accessors(self):
        from bluefog_tpu.runtime.window_server import (DepositStream,
                                                       WindowServer)
        from bluefog_tpu.runtime.async_windows import AsyncWindow

        srv = WindowServer()
        srv.start("127.0.0.1")
        wname = _uniq("ctl_ewma")
        win = AsyncWindow(wname, 2, 8, np.float64)
        try:
            st = DepositStream(srv.address)
            assert st.ack_ewma() is None  # no ack yet
            assert st.reconnects == 0
            for _ in range(4):
                st.deposit_async(wname.encode(), 0,
                                 np.ones(8, np.float64))
            st.flush(10.0)
            ewma = st.ack_ewma()
            assert ewma is not None and 0 < ewma < 5.0
            st.close()
        finally:
            win.free()
            srv.stop()

    def test_set_codec_ceiling_discipline(self):
        from bluefog_tpu.runtime.window_server import (DepositStream,
                                                       WindowServer)

        srv = WindowServer()
        srv.start("127.0.0.1")
        try:
            st = DepositStream(srv.address)  # ceiling: none
            st.set_codec(None)  # stepping down/level is always fine
            with pytest.raises(ValueError, match="ceiling"):
                st.set_codec("f32")
            st.close()
            st2 = DepositStream(srv.address, codec="topk")
            st2.set_codec("f32")   # whole ladder below the ceiling
            st2.set_codec(None)
            st2.set_codec("topk")  # back up to the ceiling
            st2.close()
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# 7. integration
# ---------------------------------------------------------------------------


def _zero_grad(n_ranks):
    def loss_and_grad(rank, step, params):
        import jax

        return 0.0, jax.tree_util.tree_map(
            lambda a: np.zeros_like(a), params)

    return loss_and_grad


class TestThreadIntegration:
    def test_slow_rank_penalized_audit_exact(self):
        """One rank 50x slower than the rest: every controller
        converges on a plan with that rank's edges dropped, the run's
        EXACT mass audit holds through all plan changes, and the fast
        ranks still reach consensus."""
        from bluefog_tpu import topology as T
        from bluefog_tpu.runtime.async_windows import run_async_dsgd

        rep = run_async_dsgd(
            T.ExponentialTwoGraph(4),
            {"w": np.arange(8.0, dtype=np.float32)}, _zero_grad(4),
            duration_s=4.0, skew=[0.002, 0.002, 0.002, 0.25],
            name=_uniq("ctl_thread"),
            control=ControlConfig(evidence_every=4, cooldown_rounds=8,
                                  min_lag_s=0.02))
        assert abs(rep.total_mass - 4.0) < 1e-9 * 4, rep.total_mass
        assert rep.control_plan is not None
        assert 3 in rep.control_plan.slow, rep.control_plan
        assert rep.plan_changes >= 1
        assert rep.consensus_gap < 1e-6, rep.consensus_gap
        # the slow rank still made progress (ring spine, not eviction)
        assert min(rep.steps_per_rank) >= 1

    def test_stop_after_steps_time_to_target(self):
        from bluefog_tpu import topology as T
        from bluefog_tpu.runtime.async_windows import run_async_dsgd

        rep = run_async_dsgd(
            T.FullyConnectedGraph(3),
            {"w": np.zeros(4, np.float32)}, _zero_grad(3),
            duration_s=30.0, skew=[0.001] * 3,
            name=_uniq("ctl_target"), stop_after_steps=25)
        assert rep.wall_time_s < 20.0  # ended on steps, not duration
        assert max(rep.steps_per_rank) >= 25
        assert abs(rep.total_mass - 3.0) < 1e-9 * 3

    def test_chaos_killed_rank_evidence_stops_voting(self):
        """Control + resilience + a chaos thread death: the corpse's
        frozen evidence record is filtered out of every later decide
        (the MP tombstone discipline, thread-mode twin), the survivors
        keep a working plan, and the audit stays exact:
        total + died == n."""
        from bluefog_tpu import chaos, topology as T
        from bluefog_tpu.runtime.async_windows import run_async_dsgd
        from bluefog_tpu.runtime.resilience import ResilienceConfig

        chaos.configure("rank2:die:at_step=30")
        rep = run_async_dsgd(
            T.FullyConnectedGraph(4),
            {"w": np.arange(8.0, dtype=np.float32)}, _zero_grad(4),
            duration_s=3.0, skew=[0.002, 0.002, 0.002, 0.2],
            name=_uniq("ctl_corpse"),
            resilience=ResilienceConfig(suspect_after_s=0.2,
                                        dead_after_s=0.6),
            control=ControlConfig(evidence_every=4, cooldown_rounds=8,
                                  min_lag_s=0.02))
        assert rep.dead_ranks == [2]
        assert abs(rep.total_mass + rep.died_mass - 4.0) < 1e-9 * 4
        assert rep.control_plan is not None

    def test_control_requires_tcp_in_mp_mode(self, tmp_path):
        from bluefog_tpu import topology as T
        from bluefog_tpu.runtime.async_windows import (FileBarrier,
                                                       run_async_dsgd_rank)

        with pytest.raises(ValueError, match="tcp"):
            run_async_dsgd_rank(
                T.FullyConnectedGraph(2), 0, {"w": np.zeros(2)},
                _zero_grad(2), barrier=FileBarrier(str(tmp_path), 2, 0),
                transport="shm", control=ControlConfig())

    def test_control_requires_resilience_in_mp_mode(self, tmp_path):
        # heartbeats are what keep a penalized (idle) stream's lag
        # evidence fresh — control without them could never release a
        # recovered peer, so the combination is rejected up front
        from bluefog_tpu import topology as T
        from bluefog_tpu.runtime.async_windows import (FileBarrier,
                                                       run_async_dsgd_rank)

        with pytest.raises(ValueError, match="resilience"):
            run_async_dsgd_rank(
                T.FullyConnectedGraph(2), 0, {"w": np.zeros(2)},
                _zero_grad(2), barrier=FileBarrier(str(tmp_path), 2, 0),
                transport="tcp", control=ControlConfig())

    def test_codec_ceiling_requires_matching_wire_codec(self, tmp_path):
        from bluefog_tpu import topology as T
        from bluefog_tpu.runtime.async_windows import (FileBarrier,
                                                       run_async_dsgd_rank)
        from bluefog_tpu.runtime.resilience import ResilienceConfig

        with pytest.raises(ValueError, match="wire_codec"):
            run_async_dsgd_rank(
                T.FullyConnectedGraph(2), 0, {"w": np.zeros(2)},
                _zero_grad(2), barrier=FileBarrier(str(tmp_path), 2, 0),
                transport="tcp", resilience=ResilienceConfig(),
                control=ControlConfig(max_codec_level=2))


_WORKER = os.path.join(_REPO, "tests", "_mp_control_worker.py")


@pytest.mark.slow
@pytest.mark.chaos
def test_mp_lossy_link_controller_drops_edges_audit_exact(tmp_path):
    """The MP acceptance scenario: 4 rank PROCESSES over the tcp
    transport, rank 3's server behind a chaos lossy/slow link
    (``server:delay:rate=`` + ``server:drop:rate=``).  The controllers
    converge on a plan that reduces rank 3 to the ring spine (evidence
    disseminated through barrier-dir records; ack-EWMA/heartbeat
    telemetry), every rank reaches its step target, and rank 0's EXACT
    push-sum mass audit holds — the plan moved edges, never mass."""
    bdir = str(tmp_path)
    procs = [subprocess.Popen(
        [sys.executable, _WORKER, str(r), "4", bdir, "45.0"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=clean_env(), cwd=_REPO) for r in range(4)]
    deadline = time.time() + 170
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=max(5.0,
                                               deadline - time.time()))
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("control MP workers timed out")
        outs.append(out)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {r} failed:\n{out}"
    assert "CTL_MP_OK 0" in outs[0]
