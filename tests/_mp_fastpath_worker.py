"""Same-host shm fast-path multi-process test worker (one process/rank).

argv: <rank> <nranks> <barrier_dir> <duration_s>

One mode, the acceptance scenario for the raw-speed hot path: a 3-rank
TCP-transport dsgd run with ``stream_options={"shm": True}`` — deposits
route through the named-shm window table instead of the loopback wire —
under two simultaneous faults:

- rank 2 SIGKILLs itself mid-run (the kill-one-rank leg: survivors must
  detect the death through the TCP control channel, heal, and finish);
- rank 1's window SERVER drops a connection once (``server:drop``), so
  the TCP leg under the shm route reconnects and replays exactly once
  while shm deposits keep flowing.

Rank 0 asserts the exact post-heal mass audit AND that the shm route
really carried deposits (``bf_shm_deposits_total`` > 0: the audit was
exercised through shared memory, not a silent TCP fallback).

Prints ``FP_MP_OK <rank>`` on success (rank 2 prints nothing — dead).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""

import numpy as np


def main():
    rank, nranks = int(sys.argv[1]), int(sys.argv[2])
    barrier_dir, duration_s = sys.argv[3], float(sys.argv[4])

    import jax

    jax.config.update("jax_platforms", "cpu")

    from bluefog_tpu import chaos
    from bluefog_tpu.metrics import registry as mreg
    from bluefog_tpu.runtime.async_windows import (FileBarrier,
                                                   run_async_dsgd_rank)
    from bluefog_tpu.runtime.resilience import ResilienceConfig
    from bluefog_tpu.topology import FullyConnectedGraph

    reg = mreg.metrics_start()
    topo = FullyConnectedGraph(nranks)
    targets = np.stack([np.full(4, float(r + 1)) for r in range(nranks)])
    params0 = {"w": np.zeros(4, np.float32)}

    def loss_and_grad(r, step, params):
        w = np.asarray(params["w"], np.float64)
        diff = w - targets[r]
        return 0.5 * float(diff @ diff), {"w": diff}

    if rank == 2:
        chaos.configure("rank2:sigkill:at_step=12")
    elif rank == 1:
        # one server-side connection drop, aimed past the attach
        # handshakes into heartbeat steady state (0.25 s cadence, two
        # inbound connections): the TCP control/fallback leg under the
        # shm route must reconnect + resume exactly once
        chaos.configure("server:drop:after_frames=12:times=1")
    cfg = ResilienceConfig(
        suspect_after_s=0.3, dead_after_s=5.0,
        reconnect_base_s=0.05, reconnect_cap_s=0.3,
        reconnect_budget=4, seed=rank, barrier_timeout_s=20.0)

    report = run_async_dsgd_rank(
        topo, rank, params0, loss_and_grad,
        barrier=FileBarrier(barrier_dir, nranks, rank),
        lr=0.05, duration_s=duration_s, skew_s=0.004,
        name=f"fp_mp_{os.path.basename(barrier_dir)}",
        transport="tcp", tcp_bind="127.0.0.1",
        stream_options={"shm": True}, resilience=cfg)

    snap = reg.snapshot()
    shm_total = sum(v for k, v in snap.items()
                    if k.startswith("bf_shm_deposits_total"))
    # every live rank's deposits rode the shm table (the fast path
    # engaged for real — this is the assertion that makes the mass
    # audit below an audit OF the shm route)
    assert shm_total > 0, snap

    if rank == 0:
        assert report is not None
        assert report.dead_ranks == [2], report.dead_ranks
        # the EXACT audit over the surviving set: every unit of push-sum
        # mass the survivors held at the post-heal rendezvous is still
        # among them at the end — shm deposits applied exactly once,
        # the dropped TCP connection replayed exactly once
        assert report.baseline_mass is not None
        assert abs(report.total_mass - report.baseline_mass) \
            <= 1e-9 * nranks, (report.total_mass, report.baseline_mass)
        assert report.steps_per_rank[0] > 40, report.steps_per_rank
        assert report.steps_per_rank[1] > 40, report.steps_per_rank
        assert report.steps_per_rank[2] == 0, report.steps_per_rank
        assert report.final_params[2] is None

    print(f"FP_MP_OK {rank}", flush=True)


if __name__ == "__main__":
    main()
