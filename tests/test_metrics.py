"""Metrics & health observability subsystem tests.

Covers the acceptance surface of the subsystem:

1. registry semantics (labelled counters/gauges/histograms, kind
   conflicts, callback gauges, thread safety);
2. comm-hook byte accounting with CLOSED-FORM expected bytes for a known
   topology (ring: every rank ships its shard once per slot);
3. consensus-distance / mixing-rate health gauges on a toy mesh;
4. JSONL round-trip through the dash CLI (subprocess, the operator
   path);
5. the zero-overhead contract: with metrics disabled the hooks are the
   IDENTITY (same object back) and instrumented jitted programs contain
   no host callbacks; with metrics enabled the callbacks are unordered
   (the analysis lint's BF-COMM012 regression guard for the PR-1 XLA
   abort class fires on ordered ones).
"""

import json
import os
import subprocess
import sys
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from bluefog_tpu.metrics import comm as mcomm
from bluefog_tpu.metrics import export as mexp
from bluefog_tpu.metrics import health as mhealth
from bluefog_tpu.metrics import registry as mreg
from bluefog_tpu.parallel.api import shard_map
from bluefog_tpu.topology import FullyConnectedGraph, RingGraph, build_schedule

N = 8


@pytest.fixture(autouse=True)
def _metrics_clean():
    """Every test starts and ends with metrics OFF (no env leak, no
    registry leak into later tests' trace-time gates).  The sticky-stop
    flag is reset so each test sees the subsystem's pristine state —
    stop-stickiness is itself under test below."""
    os.environ.pop("BLUEFOG_TPU_METRICS", None)
    mreg.metrics_stop()
    mreg._STOPPED = False
    yield
    os.environ.pop("BLUEFOG_TPU_METRICS", None)
    mreg.metrics_stop()
    mreg._STOPPED = False


def _mesh():
    return Mesh(np.array(jax.devices()[:N]), ("bf",))


def _smap(fn, n_in=1):
    return shard_map(fn, mesh=_mesh(), in_specs=(P("bf"),) * n_in,
                     out_specs=P("bf"), check_vma=False)


# ---------------------------------------------------------------------------
# 1. registry semantics
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_counter_accumulates_per_label_set(self):
        reg = mreg.MetricsRegistry()
        c = reg.counter("bytes_total")
        c.inc(10, op="a")
        c.inc(5, op="a")
        c.inc(1, op="b")
        c.inc(2)  # empty label set is its own series
        snap = reg.snapshot()
        assert snap['bytes_total{op="a"}'] == 15
        assert snap['bytes_total{op="b"}'] == 1
        assert snap["bytes_total"] == 2

    def test_label_order_is_irrelevant(self):
        reg = mreg.MetricsRegistry()
        reg.counter("c").inc(1, a="1", b="2")
        reg.counter("c").inc(1, b="2", a="1")
        (value,) = [v for k, v in reg.snapshot().items() if k.startswith("c")]
        assert value == 2

    def test_counter_rejects_decrease(self):
        reg = mreg.MetricsRegistry()
        with pytest.raises(ValueError, match="cannot decrease"):
            reg.counter("c").inc(-1)

    def test_kind_conflict_raises(self):
        reg = mreg.MetricsRegistry()
        reg.counter("m")
        with pytest.raises(TypeError, match="already registered as counter"):
            reg.gauge("m")

    def test_gauge_holds_last_value(self):
        reg = mreg.MetricsRegistry()
        reg.gauge("g").set(1.0)
        reg.gauge("g").set(4.5)
        assert reg.snapshot()["g"] == 4.5

    def test_histogram_aggregates_and_quantiles(self):
        reg = mreg.MetricsRegistry()
        h = reg.histogram("h")
        for v in range(1, 101):
            h.observe(float(v))
        snap = reg.snapshot()
        assert snap["h_count"] == 100
        assert snap["h_sum"] == 5050
        assert snap["h_min"] == 1 and snap["h_max"] == 100
        assert snap["h_p50"] == 50
        assert snap["h_p99"] == 99

    def test_gauge_fn_evaluated_at_snapshot(self):
        reg = mreg.MetricsRegistry()
        box = {"v": 1.0}
        reg.gauge_fn("age", lambda: box["v"])
        assert reg.snapshot()["age"] == 1.0
        box["v"] = 7.0
        assert reg.snapshot()["age"] == 7.0
        reg.gauge_fn("boom", lambda: 1 / 0)
        assert np.isnan(reg.snapshot()["boom"])  # raising fn -> NaN

    def test_thread_safety_exact_total(self):
        reg = mreg.MetricsRegistry()
        c = reg.counter("c")

        def worker():
            for _ in range(1000):
                c.inc(1, t="x")

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert reg.snapshot()['c{t="x"}'] == 8000

    def test_off_by_default(self):
        assert mreg.current() is None
        assert not mreg.metrics_active()

    def test_env_var_lazily_activates(self, tmp_path):
        os.environ["BLUEFOG_TPU_METRICS"] = str(tmp_path / "m.jsonl")
        assert mreg.current() is not None

    def test_stop_is_sticky_under_env_var(self, tmp_path):
        """metrics_stop() must stick even with BLUEFOG_TPU_METRICS set:
        a later instrumented call lazily resurrecting the subsystem
        would re-attach the writer over the finalized JSONL."""
        path = tmp_path / "m.jsonl"
        os.environ["BLUEFOG_TPU_METRICS"] = str(path)
        reg = mreg.current()
        reg.counter("c").inc(1)
        mexp.step(0)
        mreg.metrics_stop()
        size_after_stop = path.stat().st_size
        assert size_after_stop > 0  # step line + summary line survive
        mcomm.inc("c", 1)  # instrumented host path must NOT resurrect
        assert mreg.current() is None
        assert path.stat().st_size == size_after_stop
        # explicit restart in the same process APPENDS (no truncation)
        mreg.metrics_start(str(path))
        mexp.step(1)
        assert path.stat().st_size > size_after_stop

    def test_remove_gauge_fn_drops_stale_value(self):
        reg = mreg.metrics_start()
        reg.gauge_fn("age", lambda: 3.0)
        assert reg.snapshot()["age"] == 3.0
        reg.remove_gauge_fn("age")
        assert "age" not in reg.snapshot()  # no frozen last reading


# ---------------------------------------------------------------------------
# 2. comm-hook byte accounting (closed form for a known topology)
# ---------------------------------------------------------------------------


class TestCommAccounting:
    def test_neighbor_allreduce_ring_closed_form(self):
        """Ring, one f32 leaf of 16 elements per rank: every rank ships
        its 64-byte shard once per schedule slot per round, and the
        callback fires once per rank — so after R rounds the counter
        must read exactly N * slots * 64 * R."""
        from bluefog_tpu.ops.collectives import neighbor_allreduce

        sched = build_schedule(RingGraph(N))
        reg = mreg.metrics_start()
        fn = jax.jit(_smap(lambda v: neighbor_allreduce(v, sched, "bf")))
        x = jnp.ones((N, 16), jnp.float32)
        fn(x)
        jax.effects_barrier()
        per_rank = 16 * 4  # bytes of one rank's shard
        key = (f'bf_comm_bytes_total{{backend="xla",'
               f'op="neighbor_allreduce",schedule="{sched.name}"}}')
        snap = reg.snapshot()
        assert snap[key] == N * sched.num_slots * per_rank
        rkey = key.replace("bf_comm_bytes_total", "bf_comm_rounds_total")
        mkey = key.replace("bf_comm_bytes_total", "bf_comm_messages_total")
        assert snap[rkey] == N
        assert snap[mkey] == N * sched.num_slots  # one leaf
        fn(x)  # second round doubles everything
        jax.effects_barrier()
        assert reg.snapshot()[key] == 2 * N * sched.num_slots * per_rank

    def test_dynamic_records_taken_branch_cost(self):
        """The dynamic switch records ONE round per step with the taken
        branch's cost selected by the traced phase index: ring (2 slots)
        and fully-connected (7 slots) phases must account differently."""
        from bluefog_tpu.ops.collectives import neighbor_allreduce_dynamic

        scheds = [build_schedule(RingGraph(N)),
                  build_schedule(FullyConnectedGraph(N))]
        reg = mreg.metrics_start()

        def run(step):
            jax.jit(_smap(
                lambda v: neighbor_allreduce_dynamic(
                    v, scheds, step, "bf")))(jnp.ones((N, 4), jnp.float32))
            jax.effects_barrier()

        # backend label carries the RESOLVED transport (xla on this CPU
        # mesh), never the literal 'auto'
        key = ('bf_comm_bytes_total{backend="xla",'
               'op="neighbor_allreduce_dynamic",schedule="dynamic[2]"}')
        run(0)
        after_ring = reg.snapshot()[key]
        assert after_ring == N * scheds[0].num_slots * 16
        run(1)
        assert (reg.snapshot()[key] - after_ring
                == N * scheds[1].num_slots * 16)

    def test_window_deliver_accounts_bytes(self):
        from bluefog_tpu.ops import windows as W

        sched = build_schedule(RingGraph(N))
        reg = mreg.metrics_start()

        def body(xs):
            st = W.win_create(xs, sched, "bf", name="mwin")
            st = W.win_put(st, xs, "bf")
            out, _ = W.win_update(st, "bf")
            return out

        jax.jit(_smap(body))(jnp.ones((N, 8), jnp.float32))
        jax.effects_barrier()
        snap = reg.snapshot()
        (bkey,) = [k for k in snap if k.startswith("bf_comm_bytes_total")
                   and 'op="win_put"' in k]
        assert snap[bkey] == N * sched.num_slots * 8 * 4
        (ukey,) = [k for k in snap
                   if k.startswith("bf_window_update_rounds_total")]
        assert snap[ukey] == N

    def test_choco_records_compression_ratio(self):
        from bluefog_tpu.ops import compression as CP

        sched = build_schedule(RingGraph(N))
        comp = CP.random_block_k(0.25)
        reg = mreg.metrics_start()

        def body(xs):
            st = CP.choco_init(xs, sched)
            out, _ = CP.choco_gossip(xs, st, sched, "bf", compressor=comp)
            return out

        jax.jit(_smap(body))(jnp.ones((N, 64), jnp.float32))
        jax.effects_barrier()
        snap = reg.snapshot()
        assert snap['bf_compression_ratio{compressor="random_block_k"}'] \
            == pytest.approx(0.25)
        (bkey,) = [k for k in snap if k.startswith("bf_comm_bytes_total")]
        # wire = 25% of the dense 64*4 bytes, per slot, per rank
        assert snap[bkey] == pytest.approx(N * sched.num_slots * 0.25 * 256)

    def test_async_window_staleness_metrics(self):
        from bluefog_tpu.runtime.async_windows import AsyncWindow

        reg = mreg.metrics_start()
        win = AsyncWindow("metrics_test_win", 2, 4, np.float64)
        try:
            win.deposit(0, np.ones(4))
            win.read(0, consume=True)   # 1 fresh
            win.read(0, consume=True)   # stale
            snap = reg.snapshot()
            assert snap['bf_window_deposit_bytes_total{transport="local",'
                        'window="metrics_test_win"}'] == 32
            assert snap['bf_window_stale_reads_total'
                        '{window="metrics_test_win"}'] == 1
            assert snap['bf_window_fresh_per_read_count'
                        '{window="metrics_test_win"}'] == 2
        finally:
            win.free()


# ---------------------------------------------------------------------------
# 3. health gauges on a toy mesh
# ---------------------------------------------------------------------------


class TestHealth:
    def test_consensus_distance_traced_matches_oracle(self):
        fn = jax.jit(_smap(
            lambda v: mhealth.consensus_distance(v, "bf")[None]))
        xs = jnp.arange(N, dtype=jnp.float32)[:, None] * jnp.ones((N, 2))
        got = np.asarray(fn(xs))
        want = np.abs(np.arange(N) - 3.5) * np.sqrt(2)
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_consensus_distance_stacked_matches_traced(self):
        xs = np.random.default_rng(0).standard_normal((N, 5)).astype(
            np.float32)
        host = mhealth.consensus_distance_stacked({"w": xs})
        fn = jax.jit(_smap(
            lambda v: mhealth.consensus_distance(v, "bf")[None]))
        dev = np.asarray(fn(jnp.asarray(xs))).max()
        assert host == pytest.approx(float(dev), rel=1e-5)

    def test_mixing_tracker_measured_vs_predicted(self):
        from bluefog_tpu.analysis.topology_check import spectral_gap

        sched = build_schedule(RingGraph(N))
        reg = mreg.metrics_start()
        tracker = mhealth.MixingTracker(sched)
        lam2 = 1.0 - spectral_gap(sched.mixing_matrix())
        assert tracker.predicted == pytest.approx(lam2)
        assert tracker.update(10.0) is None  # first sample: no ratio yet
        assert tracker.update(6.0) == pytest.approx(0.6)
        snap = reg.snapshot()
        assert snap["bf_mixing_contraction_measured"] == pytest.approx(0.6)
        assert snap["bf_mixing_contraction_predicted"] == pytest.approx(lam2)
        assert snap["bf_mixing_excess"] == pytest.approx(0.6 - lam2)
        assert snap["bf_consensus_distance"] == 6.0

    def test_mixing_tracker_scales_prediction_to_feed_cadence(self):
        """An epoch-level feeder passes rounds_per_update=R and the
        prediction becomes |lambda_2|^R — same scale as the measured
        epoch ratio."""
        from bluefog_tpu.analysis.topology_check import spectral_gap

        sched = build_schedule(RingGraph(N))
        lam2 = 1.0 - spectral_gap(sched.mixing_matrix())
        t = mhealth.MixingTracker(sched, rounds_per_update=5)
        assert t.predicted == pytest.approx(lam2 ** 5)
        with pytest.raises(ValueError, match="rounds_per_update"):
            mhealth.MixingTracker(sched, rounds_per_update=0)

    def test_mixing_tracker_rebase_after_heal(self):
        """Regression for the stale-prediction bug: ``predicted`` was
        computed once at construction, so after a heal/replan the
        bf_mixing_excess alarm compared measured contraction against
        the OLD topology's |lambda_2|.  rebase(schedule) re-anchors it
        — heal a ring, the excess gauge re-baselines — and understands
        Topology.inactive (the healed matrix's inert identity rows must
        not read as |lambda_2| = 1)."""
        from bluefog_tpu.analysis.topology_check import spectral_gap
        from bluefog_tpu.topology import heal

        ring = RingGraph(6)
        reg = mreg.metrics_start()
        tracker = mhealth.MixingTracker(ring)
        lam2_ring = 1.0 - spectral_gap(ring.weights)
        assert tracker.predicted == pytest.approx(lam2_ring)
        tracker.update(10.0)
        tracker.update(9.0)
        excess_before = reg.snapshot()["bf_mixing_excess"]
        assert excess_before == pytest.approx(0.9 - lam2_ring)
        # rank 2 dies; the healed path graph mixes SLOWER (bigger
        # |lambda_2|) — without rebase, the old baseline would read the
        # healthy healed fleet as permanently broken
        healed = heal(ring, [2])
        new_pred = tracker.rebase(healed)
        live = sorted(set(range(6)) - {2})
        sub = healed.weights[np.ix_(live, live)]
        lam2_healed = 1.0 - spectral_gap(sub)
        assert new_pred == pytest.approx(lam2_healed)
        assert lam2_ring < new_pred < 1.0  # active submatrix, not the
        # inert identity row's eigenvalue 1
        tracker.update(8.7)
        snap = reg.snapshot()
        assert snap["bf_mixing_contraction_predicted"] == pytest.approx(
            lam2_healed)
        assert snap["bf_mixing_excess"] == pytest.approx(
            8.7 / 9.0 - lam2_healed)
        # a controller stretching the gossip cadence re-anchors the
        # feed-window exponent through the same call
        assert tracker.rebase(healed, rounds_per_update=3) \
            == pytest.approx(lam2_healed ** 3)
        with pytest.raises(ValueError, match="rounds_per_update"):
            tracker.rebase(healed, rounds_per_update=0)

    def test_mixing_tracker_reset_measurement_at_membership_boundary(self):
        """The measurement twin of rebase: a distance measured over one
        member set must not ratio against a distance over another — a
        join widens disagreement and the cross-boundary ratio reads as
        a mixing failure (the fleet simulator caught this marching the
        densify ladder to fully-connected).  reset_measurement() drops
        the previous sample so the next update yields no ratio."""
        tracker = mhealth.MixingTracker(RingGraph(6))
        assert tracker.update(10.0) is None  # first sample
        assert tracker.update(9.0) == pytest.approx(0.9)
        tracker.reset_measurement()
        # the membership boundary: disagreement jumped to 30 over a
        # grown fleet — no ratio, instead of a spurious 30/9
        assert tracker.update(30.0) is None
        assert tracker.update(27.0) == pytest.approx(0.9)

    def test_heartbeat_age_gauge(self):
        from bluefog_tpu.utils.failure import Heartbeat

        reg = mreg.metrics_start()
        hb = Heartbeat(timeout_s=60, action="callback")
        with hb:
            hb.beat(0)
            (key,) = [k for k in reg.snapshot()
                      if k.startswith("bf_heartbeat_age_seconds")]
            age = reg.snapshot()[key]
            assert 0.0 <= age < 60.0


# ---------------------------------------------------------------------------
# 4. JSONL round-trip through the dash CLI
# ---------------------------------------------------------------------------


class TestExportAndDash:
    def test_jsonl_round_trip_through_dash_cli(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        reg = mreg.metrics_start(path)
        for s in range(4):
            reg.counter("bf_comm_bytes_total").inc(256, op="na")
            reg.gauge("bf_consensus_distance").set(8.0 / (s + 1))
            mexp.step(s)
        mexp.detach_writer()  # flush + summary line

        with open(path) as f:
            lines = [json.loads(l) for l in f if l.strip()]
        assert len(lines) == 5 and lines[-1].get("summary") is True

        proc = subprocess.run(
            [sys.executable, "-m", "bluefog_tpu.metrics.dash", path],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stderr
        assert 'bf_comm_bytes_total{op="na"}' in proc.stdout
        assert "1024" in proc.stdout  # cumulative total
        assert "256" in proc.stdout   # per-step delta
        assert "bf_consensus_distance" in proc.stdout

    def test_dash_counter_deltas_and_percentiles(self):
        from bluefog_tpu.metrics.dash import summarize

        series = {"x_total": [100.0, 300.0, 600.0]}
        (row,) = summarize([0, 1, 2], series)
        assert row["type"] == "counter"
        assert row["total"] == 600
        assert row["per_step_mean"] == pytest.approx(200.0)
        assert row["p50"] == 200 and row["p99"] == 300

    def test_dash_rejects_empty_file(self, tmp_path):
        from bluefog_tpu.metrics.dash import main

        p = tmp_path / "empty.jsonl"
        p.write_text("")
        assert main([str(p)]) == 1

    def test_dash_since_rebaselines_counters(self):
        """--since must difference against the last PRE-window value —
        the first in-window delta is 200 (300-100), not the whole
        cumulative 300."""
        from bluefog_tpu.metrics.dash import summarize

        series = {"x_total": [100.0, 300.0, 600.0]}
        (row,) = summarize([0, 1, 2], series, since=1)
        assert row["points"] == 2
        assert row["per_step_mean"] == pytest.approx(250.0)
        assert row["p50"] == 200 and row["p99"] == 300
        assert row["total"] == 600  # the total column stays cumulative

    @staticmethod
    def _hist_series(label: str, counts, sums, p50, p99):
        base = "bf_tcp_ack_latency_seconds"
        n = len(counts)
        return {
            f"{base}_count{{peer=\"{label}\"}}": list(counts),
            f"{base}_sum{{peer=\"{label}\"}}": list(sums),
            f"{base}_min{{peer=\"{label}\"}}": [p50] * n,
            f"{base}_max{{peer=\"{label}\"}}": [p99] * n,
            f"{base}_p50{{peer=\"{label}\"}}": [p50] * n,
            f"{base}_p99{{peer=\"{label}\"}}": [p99] * n,
        }

    def test_dash_histogram_per_label_breakdown(self):
        """A labeled histogram's six expansion series fold into ONE
        `hist` row per label value — per-peer ack latency reads as one
        row per peer, not p50/p99 collapsed across labels."""
        from bluefog_tpu.metrics.dash import summarize

        series = {
            **self._hist_series("a", [2.0, 4.0, 6.0], [0.2, 0.4, 0.6],
                                0.1, 0.12),
            **self._hist_series("b", [1.0, 2.0, 3.0], [1.0, 2.0, 3.0],
                                1.0, 1.5),
            # an incomplete suffix family is NOT a histogram: a
            # freestanding gauge ending in _count must survive as-is
            "stray_count": [5.0, 6.0, 7.0],
        }
        rows = summarize([0, 1, 2], series)
        by_name = {r["series"]: r for r in rows}
        ra = by_name['bf_tcp_ack_latency_seconds{peer="a"}']
        rb = by_name['bf_tcp_ack_latency_seconds{peer="b"}']
        assert ra["type"] == rb["type"] == "hist"
        assert ra["points"] == 6 and rb["points"] == 3
        assert ra["per_step_mean"] == pytest.approx(0.1)
        assert ra["p99"] == pytest.approx(0.12)
        assert rb["per_step_mean"] == pytest.approx(1.0)
        assert by_name["stray_count"]["type"] == "gauge"
        # no raw expansion rows leak through alongside the fold
        assert not any("_p50{" in n or "_count{" in n for n in by_name)

    def test_dash_histogram_since_windows_count_and_sum(self):
        from bluefog_tpu.metrics.dash import summarize

        series = self._hist_series("a", [2.0, 4.0, 6.0],
                                   [0.2, 0.4, 0.6], 0.1, 0.12)
        (row,) = summarize([0, 1, 2], series, since=1)
        assert row["points"] == 4  # 6 - the pre-window 2
        assert row["total"] == pytest.approx(0.4)
        assert row["per_step_mean"] == pytest.approx(0.1)

    def test_dash_cli_since_and_hist_flags(self, tmp_path):
        """End-to-end: a run with a labeled histogram renders hist rows
        through the CLI, and --since narrows the window."""
        path = str(tmp_path / "m.jsonl")
        reg = mreg.metrics_start(path)
        for s in range(4):
            reg.counter("bf_comm_bytes_total").inc(256, op="na")
            reg.histogram("bf_tcp_ack_latency_seconds").observe(
                0.01 * (s + 1), peer="p0")
            mexp.step(s)
        mexp.detach_writer()

        proc = subprocess.run(
            [sys.executable, "-m", "bluefog_tpu.metrics.dash", path,
             "--since", "2", "--json"],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stderr
        rows = {r["series"]: r for r in json.loads(proc.stdout)}
        hist = rows['bf_tcp_ack_latency_seconds{peer="p0"}']
        assert hist["type"] == "hist"
        assert hist["points"] == 2  # steps 2 and 3 only
        counter = rows['bf_comm_bytes_total{op="na"}']
        assert counter["per_step_mean"] == pytest.approx(256.0)

    def test_dash_follow_tails_live_file(self, tmp_path):
        """--follow: the dash re-reads a GROWING JSONL, renders new
        data, and exits 0 when the run's summary line lands — the live
        half of the one-shot dash (fleet-plane satellite, PR 12)."""
        import time as _time

        path = str(tmp_path / "m.jsonl")
        with open(path, "w") as f:
            f.write(json.dumps(
                {"step": 0, "metrics": {"bf_x_total": 1.0}}) + "\n")
        proc = subprocess.Popen(
            [sys.executable, "-m", "bluefog_tpu.metrics.dash", path,
             "--follow", "--interval", "0.2"],
            stdout=subprocess.PIPE, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        try:
            # wait for the first rendered frame...
            first = []
            deadline = _time.time() + 90
            while _time.time() < deadline:
                line = proc.stdout.readline()
                first.append(line)
                if "step record(s)" in line:
                    break
            assert any("step record(s)" in ln for ln in first), first
            # ...then the run appends more data and finishes
            with open(path, "a") as f:
                f.write(json.dumps({"step": 1, "metrics": {
                    "bf_x_total": 2.0, "bf_late_total": 7.0}}) + "\n")
                f.write(json.dumps({"summary": True, "metrics": {
                    "bf_x_total": 2.0, "bf_late_total": 7.0}}) + "\n")
            rest, _ = proc.communicate(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
        out = "".join(first) + rest
        assert proc.returncode == 0, out
        # a later frame rendered the late-appended series and the
        # summary marker ended the loop
        assert out.count("step record(s)") >= 2, out
        assert "bf_late_total" in rest
        assert "summary line present" in out

    def test_dash_follow_waits_for_missing_file(self, tmp_path):
        """--follow on a not-yet-created path waits instead of exiting
        (the run may not have opened its writer yet)."""
        import time as _time

        path = str(tmp_path / "later.jsonl")
        proc = subprocess.Popen(
            [sys.executable, "-m", "bluefog_tpu.metrics.dash", path,
             "--follow", "--interval", "0.2"],
            stdout=subprocess.PIPE, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        try:
            line = proc.stdout.readline()  # the waiting notice
            assert "waiting" in line, line
            with open(path, "w") as f:
                f.write(json.dumps({"summary": True, "metrics":
                                    {"bf_x_total": 1.0}}) + "\n")
            out, _ = proc.communicate(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
        assert proc.returncode == 0, out
        assert "summary line present" in out

    def test_prometheus_text_format(self):
        reg = mreg.metrics_start()
        reg.counter("bf_comm_bytes_total", "bytes shipped").inc(64, op="x")
        reg.gauge("bf_consensus_distance").set(1.5)
        text = mexp.prometheus_text(reg)
        assert "# TYPE bf_comm_bytes_total counter" in text
        assert 'bf_comm_bytes_total{op="x"} 64.0' in text
        assert "# TYPE bf_consensus_distance gauge" in text
        assert "# HELP bf_comm_bytes_total bytes shipped" in text

    def test_step_is_noop_when_disabled(self, tmp_path):
        assert mexp.step(0) is None


# ---------------------------------------------------------------------------
# 5. zero overhead when disabled + no-ordered-callback guard
# ---------------------------------------------------------------------------


class TestDisabledOverheadAndLint:
    def test_hooks_are_identity_when_disabled(self):
        x = jnp.ones((4,))
        assert mcomm.record_collective(
            x, op="o", bytes_per_round=1, messages_per_round=1) is x
        assert mcomm.count(x, [("c", 1.0)]) is x

    def test_disabled_jaxpr_has_no_callbacks(self):
        """The acceptance gate: instrumented collective + optimizer paths
        traced with metrics OFF must contain zero host callbacks."""
        import optax

        from bluefog_tpu.optim import DistributedNeighborAllreduceOptimizer

        opt = DistributedNeighborAllreduceOptimizer(
            optax.sgd(0.1), topology=RingGraph(N), axis_name="bf")

        def body(xs):
            st = opt.init(xs)
            upd, _ = opt.update(xs, st, xs)
            return optax.apply_updates(xs, upd)

        text = str(jax.make_jaxpr(_smap(body))(jnp.ones((N, 4))))
        assert "callback" not in text

    def test_enabled_jaxpr_uses_only_unordered_callbacks(self):
        from bluefog_tpu.analysis.jaxpr_lint import lint_jaxpr
        from bluefog_tpu.ops.collectives import neighbor_allreduce

        sched = build_schedule(RingGraph(N))
        mreg.metrics_start()
        closed = jax.make_jaxpr(_smap(
            lambda v: neighbor_allreduce(v, sched, "bf")))(jnp.ones((N, 4)))
        text = str(closed)
        assert "io_callback" in text  # instrumentation is present...
        diags = lint_jaxpr(closed, name="instrumented_gossip")
        codes = [d.code for d in diags]
        assert "BF-COMM012" not in codes      # ...and is NOT ordered
        assert "BF-COMM010" in codes          # plain callback warning only
        assert not any(d.severity == "error" for d in diags)

    def test_lint_flags_ordered_io_callback_as_error(self):
        """Seeded violation for the PR-1 abort class: an ordered
        io_callback on a jitted path must be an ERROR (BF-COMM012), not
        the generic callback warning."""
        from jax.experimental import io_callback

        from bluefog_tpu.analysis.jaxpr_lint import lint_jaxpr

        def bad(x):
            z = io_callback(lambda v: np.float32(0.0),
                            jax.ShapeDtypeStruct((), jnp.float32), x,
                            ordered=True)
            return x + z

        closed = jax.make_jaxpr(bad)(jnp.float32(1.0))
        diags = lint_jaxpr(closed, name="seeded_ordered_callback")
        bad_diags = [d for d in diags if d.code == "BF-COMM012"]
        assert bad_diags and bad_diags[0].severity == "error"
        assert "ordered" in bad_diags[0].message

    def test_instrumented_program_differentiable(self):
        """The custom_jvp shell: metrics-instrumented collectives must
        still trace under jax.grad."""
        from bluefog_tpu.ops.collectives import neighbor_allreduce

        sched = build_schedule(RingGraph(N))
        mreg.metrics_start()

        def body(xs):
            loss = jnp.sum(neighbor_allreduce(xs, sched, "bf") ** 2)
            return jax.grad(lambda v: jnp.sum(
                neighbor_allreduce(v, sched, "bf") ** 2))(xs) * 0 + loss[None]

        out = jax.jit(_smap(body))(jnp.ones((N, 4)))
        jax.effects_barrier()
        assert np.isfinite(np.asarray(out)).all()
