"""Model-zoo sanity tests: shapes, dtypes, parameter counts."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bluefog_tpu.models import BertConfig, BertEncoder, LeNet5, ResNet18, ResNet50


def n_params(tree):
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(tree))


def test_lenet_forward():
    m = LeNet5()
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((2, 28, 28, 1)))
    out = m.apply(v, jnp.zeros((4, 28, 28, 1)))
    assert out.shape == (4, 10)
    assert out.dtype == jnp.float32
    assert 40_000 < n_params(v) < 80_000  # classic LeNet-5 ~61k params


@pytest.mark.duration_budget(60)  # pre-existing heavyweight; tier-1 coverage load-bearing
def test_resnet18_forward():
    m = ResNet18(num_classes=10, dtype=jnp.float32)
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=False)
    out = m.apply(v, jnp.zeros((2, 32, 32, 3)), train=False)
    assert out.shape == (2, 10)
    total = n_params(v["params"])
    assert 10e6 < total < 13e6  # ResNet-18 ~11.2M (head 10 classes)


def test_resnet50_param_count():
    m = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    v = jax.eval_shape(
        lambda k: m.init(k, jnp.zeros((1, 224, 224, 3), jnp.bfloat16), train=False),
        jax.random.PRNGKey(0),
    )
    total = n_params(v["params"])
    assert 25e6 < total < 26e6  # canonical ResNet-50: 25.56M


def test_resnet_batchnorm_mutable_update():
    m = ResNet18(num_classes=10, dtype=jnp.float32)
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=True)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
    out, mut = m.apply(v, x, train=True, mutable=["batch_stats"])
    changed = jax.tree_util.tree_map(
        lambda a, b: not np.allclose(a, b), v["batch_stats"], mut["batch_stats"]
    )
    assert any(jax.tree_util.tree_leaves(changed))


def test_s2d_stem_exact_equivalence():
    """The 4x4/s1 conv on space-to-depth input computes the IDENTICAL
    function as the reference 7x7/s2 stem when its kernel is the
    constructive embedding — the proof the "s2d" stem is the same model
    family, not an approximation."""
    from bluefog_tpu.models import s2d_stem_kernel_from_7x7, space_to_depth

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 32, 32, 3)), jnp.float32)
    w7 = jnp.asarray(rng.normal(size=(7, 7, 3, 16)) * 0.1, jnp.float32)

    ref = jax.lax.conv_general_dilated(
        x, w7, window_strides=(2, 2), padding=[(3, 3), (3, 3)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    w4 = jnp.asarray(s2d_stem_kernel_from_7x7(w7))
    got = jax.lax.conv_general_dilated(
        space_to_depth(x, 2), w4, window_strides=(1, 1),
        padding=[(2, 1), (2, 1)], dimension_numbers=("NHWC", "HWIO", "NHWC"))
    assert ref.shape == got.shape == (2, 16, 16, 16)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               rtol=1e-5, atol=1e-5)


def test_s2d_stem_model_shapes_and_prefolded_input():
    """ResNet(stem="s2d") matches the reference stem's output shape and
    accepts either raw [N,H,W,3] or pre-folded [N,H/2,W/2,12] input with
    identical results (the data pipeline may fold on host)."""
    from bluefog_tpu.models import space_to_depth

    m = ResNet18(num_classes=10, dtype=jnp.float32, stem="s2d")
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    v = m.init(jax.random.PRNGKey(0), x, train=False)
    out_raw = m.apply(v, x, train=False)
    out_folded = m.apply(v, space_to_depth(x, 2), train=False)
    assert out_raw.shape == (2, 10)
    np.testing.assert_allclose(np.asarray(out_raw), np.asarray(out_folded),
                               rtol=1e-6, atol=1e-6)
    # same downstream trunk: non-stem param tree shapes match the 7x7 model
    v7 = ResNet18(num_classes=10, dtype=jnp.float32).init(
        jax.random.PRNGKey(0), x, train=False)
    s2d_shapes = jax.tree_util.tree_map(lambda a: a.shape, v["params"])
    ref_shapes = jax.tree_util.tree_map(lambda a: a.shape, v7["params"])
    assert s2d_shapes["conv_init"]["kernel"] == (4, 4, 12, 64)
    del s2d_shapes["conv_init"], ref_shapes["conv_init"]
    assert s2d_shapes == ref_shapes


@pytest.mark.duration_budget(90)  # pre-existing heavyweight; tier-1 coverage load-bearing
def test_vit_tiny_forward_and_grad():
    from bluefog_tpu.models import ViT, ViTConfig

    cfg = ViTConfig.tiny()
    m = ViT(cfg)
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    x = jax.random.normal(jax.random.PRNGKey(1), (3, 32, 32, 3))
    out = m.apply(v, x)
    assert out.shape == (3, 10)
    assert out.dtype == jnp.float32

    def loss(p):
        return (m.apply(p, x) ** 2).mean()

    g = jax.grad(loss)(v)
    assert np.isfinite(
        np.asarray([np.sum(np.asarray(t, np.float64))
                    for t in jax.tree_util.tree_leaves(g)])).all()


def test_vit_base_param_count():
    from bluefog_tpu.models import ViT, ViTConfig

    m = ViT(ViTConfig.base())
    v = jax.eval_shape(
        lambda k: m.init(k, jnp.zeros((1, 224, 224, 3), jnp.bfloat16)),
        jax.random.PRNGKey(0))
    total = n_params(v["params"])
    assert 85e6 < total < 88e6  # canonical ViT-B/16: ~86.6M


@pytest.mark.duration_budget(60)  # pre-existing heavyweight; tier-1 coverage load-bearing
def test_vit_remat_matches():
    from bluefog_tpu.models import ViT, ViTConfig

    cfg = ViTConfig.tiny()
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 32, 32, 3))
    m = ViT(cfg)
    v = m.init(jax.random.PRNGKey(0), x)
    m_r = ViT(dataclasses.replace(cfg, remat=True))
    np.testing.assert_allclose(
        np.asarray(m.apply(v, x)), np.asarray(m_r.apply(v, x)),
        rtol=1e-6, atol=1e-6)


def test_bert_tiny_forward():
    cfg = BertConfig.tiny()
    m = BertEncoder(cfg, num_classes=3)
    ids = jnp.zeros((2, 16), jnp.int32)
    v = m.init(jax.random.PRNGKey(0), ids)
    out = m.apply(v, ids)
    assert out.shape == (2, 3)
    # sequence-embedding mode
    m2 = BertEncoder(cfg)
    v2 = m2.init(jax.random.PRNGKey(0), ids)
    seq = m2.apply(v2, ids)
    assert seq.shape == (2, 16, cfg.hidden_size)


def test_bert_attention_mask():
    cfg = BertConfig.tiny()
    m = BertEncoder(cfg, num_classes=2)
    ids = jnp.ones((1, 8), jnp.int32)
    v = m.init(jax.random.PRNGKey(0), ids)
    mask_full = jnp.ones((1, 8), bool)
    mask_half = jnp.array([[True] * 4 + [False] * 4])
    o1 = m.apply(v, ids, attention_mask=mask_full)
    o2 = m.apply(v, ids, attention_mask=mask_half)
    assert not np.allclose(np.asarray(o1), np.asarray(o2))


class TestRemat:
    """cfg.remat wraps blocks in jax.checkpoint: identical outputs and
    gradients, less saved-activation memory (the HBM lever — SURVEY.md §7
    design stance / task brief)."""

    @pytest.mark.duration_budget(90)  # pre-existing heavyweight; tier-1 coverage load-bearing
    def test_transformer_remat_matches(self):
        import optax

        from bluefog_tpu.models.transformer import GPTConfig, TransformerLM

        toks = jnp.zeros((2, 16), jnp.int32).at[:, 3].set(5)
        lm = TransformerLM(GPTConfig.tiny())
        lm_r = TransformerLM(
            dataclasses.replace(GPTConfig.tiny(), remat=True))
        params = lm.init(jax.random.PRNGKey(0), toks)

        def loss(m):
            def f(p):
                lg = m.apply(p, toks)
                return optax.softmax_cross_entropy_with_integer_labels(
                    lg, jnp.roll(toks, -1, -1)).mean()
            return f

        l0, g0 = jax.value_and_grad(loss(lm))(params)
        l1, g1 = jax.value_and_grad(loss(lm_r))(params)
        np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
        for a, b in zip(jax.tree_util.tree_leaves(g0),
                        jax.tree_util.tree_leaves(g1)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)

    @pytest.mark.duration_budget(60)  # pre-existing heavyweight; tier-1 coverage load-bearing
    def test_bert_remat_matches(self):
        from bluefog_tpu.models.bert import BertConfig, BertEncoder

        ids = jnp.ones((2, 12), jnp.int32)
        m = BertEncoder(BertConfig.tiny(), num_classes=3)
        m_r = BertEncoder(
            dataclasses.replace(BertConfig.tiny(), remat=True), num_classes=3)
        params = m.init(jax.random.PRNGKey(0), ids)

        def f(mm):
            return lambda p: jnp.sum(mm.apply(p, ids) ** 2)

        l0, g0 = jax.value_and_grad(f(m))(params)
        l1, g1 = jax.value_and_grad(f(m_r))(params)
        np.testing.assert_allclose(float(l0), float(l1), rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(g0),
                        jax.tree_util.tree_leaves(g1)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4)
