"""Routing policy for the Pallas RDMA gossip transport.

`backend='auto'` must provably choose per the stated conditions
(pallas_gossip.auto_gossip_backend): real TPU + multi-device + circulant +
small-enough payloads -> pallas; anything else -> XLA.  The policy is pure
and cheap, so every branch is asserted directly; integration (the XLA side
of auto on the CPU mesh + interpret-mode kernel parity) is covered by
test_collectives.py / test_pallas_gossip.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bluefog_tpu.ops import pallas_gossip as pg
from bluefog_tpu.topology import ExponentialTwoGraph, RingGraph, StarGraph
from bluefog_tpu.topology.schedule import build_schedule

SMALL = jnp.zeros((1024,), jnp.float32)          # 4 KiB
BIG = jnp.zeros((2 << 20,), jnp.float32)         # 8 MiB > 4 MiB cutoff


@pytest.fixture
def on_tpu(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")


def test_auto_is_xla_on_cpu():
    sched = build_schedule(RingGraph(8))
    assert jax.default_backend() == "cpu"
    assert pg.auto_gossip_backend(sched, SMALL) == "xla"


def test_auto_picks_pallas_on_tpu_small_circulant(on_tpu):
    for topo in (RingGraph(8), ExponentialTwoGraph(8)):
        assert pg.auto_gossip_backend(build_schedule(topo), SMALL) == "pallas"
    # pytrees: every leaf within the cutoff
    tree = {"a": SMALL, "b": jnp.zeros((16, 16), jnp.bfloat16)}
    assert pg.auto_gossip_backend(build_schedule(RingGraph(8)), tree) == "pallas"


def test_auto_gossip_has_no_size_cutoff(on_tpu):
    """Gossip chunks oversized leaves at the op layer, so auto routes ANY
    size to pallas — this is what makes the RDMA kernels the real default
    under fuse_apply's flat optimizer buffers (round-4 verdict: the 4 MiB
    cutoff + fusion silently cancelled the kernels out of the default
    training path)."""
    sched = build_schedule(RingGraph(8))
    assert pg.auto_gossip_backend(sched, BIG) == "pallas"
    assert pg.auto_gossip_backend(sched, {"a": SMALL, "b": BIG}) == "pallas"


def test_window_deliver_keeps_size_cutoff(on_tpu):
    """The window transport cannot chunk (persistent landing buffers), so
    for it the cap stays a routing cutoff."""
    sched = build_schedule(RingGraph(8))
    assert pg.auto_gossip_backend(sched, BIG, chunkable=False) == "xla"
    assert pg.auto_gossip_backend(
        sched, {"a": SMALL, "b": BIG}, chunkable=False) == "xla"
    assert pg.auto_gossip_backend(sched, SMALL, chunkable=False) == "pallas"
    # and the cutoff is tunable
    import os
    os.environ["BLUEFOG_TPU_PALLAS_MAX_BYTES"] = str(1 << 30)
    try:
        assert pg.auto_gossip_backend(sched, BIG, chunkable=False) == "pallas"
    finally:
        del os.environ["BLUEFOG_TPU_PALLAS_MAX_BYTES"]


def test_nonpositive_cap_disables_kernels(on_tpu, monkeypatch):
    """MAX_BYTES=0 was the de facto 'always XLA' setting before chunking;
    it must keep meaning that under auto — and raise loudly (not
    ZeroDivisionError) if pallas is forced anyway."""
    monkeypatch.setenv("BLUEFOG_TPU_PALLAS_MAX_BYTES", "0")
    sched = build_schedule(RingGraph(8))
    assert pg.auto_gossip_backend(sched, SMALL) == "xla"
    assert pg.auto_gossip_backend(sched, SMALL, chunkable=False) == "xla"
    with pytest.raises(ValueError, match="must be positive"):
        pg.leaf_chunk_count(SMALL)


def test_leaf_chunk_plan():
    # 8 MiB f32 leaf at the default 4 MiB cap -> 2 chunks; bf16 ships at
    # half the bytes -> 1 chunk at 4 MiB
    assert pg.leaf_wire_bytes(BIG) == 8 << 20
    assert pg.leaf_chunk_count(BIG) == 2
    assert pg.leaf_chunk_count(BIG.astype(jnp.bfloat16)) == 1
    assert pg.leaf_chunk_count(SMALL) == 1
    # a ResNet-50-sized fused f32 buffer (~25.5M params, ~102 MiB wire)
    fused = jax.ShapeDtypeStruct((25_500_000,), jnp.float32)
    assert pg.leaf_chunk_count(fused) == 25
    assert pg.leaf_chunk_count(fused, limit=1 << 30) == 1


def test_auto_rejects_non_circulant_and_single_device(on_tpu):
    star = build_schedule(StarGraph(8))
    assert pg.circulant_shifts(star) is None
    assert pg.auto_gossip_backend(star, SMALL) == "xla"

    from bluefog_tpu.topology.graphs import Topology
    solo = build_schedule(Topology(weights=np.ones((1, 1)), name="solo"))
    assert pg.auto_gossip_backend(solo, SMALL) == "xla"


def test_auto_rejects_zero_slot_schedules(on_tpu):
    """A multi-device identity topology builds a circulant schedule with ZERO
    slots (no edges); auto must take XLA — the grid-free kernel cannot lower
    with no receive buffers."""
    from bluefog_tpu.topology.graphs import Topology

    ident = build_schedule(Topology(weights=np.eye(8), name="identity8"))
    assert ident.num_slots == 0 and ident.is_circulant
    assert pg.auto_gossip_backend(ident, SMALL) == "xla"


def test_deliver_pallas_zero_slot_returns_bufs_unchanged():
    """The window transport has the same degenerate case as gossip: no
    out-neighbors -> slot buffers unchanged, no kernel built."""
    from jax.sharding import Mesh, PartitionSpec as P

    from bluefog_tpu.parallel.api import shard_map
    from bluefog_tpu.topology.graphs import Topology

    sched = build_schedule(Topology(weights=np.eye(8), name="identity8"))
    assert not pg.is_pallas_supported(sched)  # and the guard below holds too
    mesh = Mesh(np.array(jax.devices()[:8]), ("bf",))
    payload = jnp.ones((8, 4), jnp.float32)
    bufs = jnp.zeros((8, 0, 4), jnp.float32)  # K=0 slots
    out = jax.jit(shard_map(
        lambda p, b: pg.deliver_pallas(p[0], b[0], sched, "bf",
                                       accumulate=False)[None],
        mesh=mesh, in_specs=(P("bf"), P("bf")), out_specs=P("bf"),
        check_vma=False))(payload, bufs)
    assert out.shape == (8, 0, 4)


def test_pallas_zero_slot_degenerates_to_self_term():
    """Forced backend='pallas' on a 0-slot schedule returns sw*x instead of
    crashing in kernel lowering (interpret-free: no kernel is built)."""
    from jax.sharding import Mesh, PartitionSpec as P

    from bluefog_tpu.parallel.api import shard_map
    from bluefog_tpu.topology.graphs import Topology

    sched = build_schedule(Topology(weights=np.eye(8), name="identity8"))
    mesh = Mesh(np.array(jax.devices()[:8]), ("bf",))
    xs = jnp.arange(8 * 4, dtype=jnp.float32).reshape(8, 4)
    out = jax.jit(shard_map(
        lambda v: pg.neighbor_allreduce_pallas(v[0], sched, "bf")[None],
        mesh=mesh, in_specs=(P("bf"),), out_specs=P("bf"),
        check_vma=False))(xs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(xs), rtol=1e-6)


def test_gate_predicates_agree(on_tpu):
    """is_pallas_supported and 'auto' routing share ONE platform predicate
    (on_tpu_platform) — they can never disagree about the same schedule
    (round-3 advisory: the old gates split on the axon relay)."""
    from bluefog_tpu.topology.graphs import Topology

    for topo in (RingGraph(8), ExponentialTwoGraph(8), StarGraph(8),
                 Topology(weights=np.ones((1, 1)), name="solo"),
                 Topology(weights=np.eye(8), name="identity8")):
        sched = build_schedule(topo)
        assert pg.is_pallas_supported(sched) == \
            (pg.auto_gossip_backend(sched, SMALL) == "pallas"), topo.name


def test_gate_predicates_agree_on_cpu():
    sched = build_schedule(RingGraph(8))
    assert not pg.on_tpu_platform()
    assert not pg.is_pallas_supported(sched)
    assert pg.auto_gossip_backend(sched, SMALL) == "xla"


def test_window_base_collision_raises(monkeypatch):
    """Two distinct window names in one CRC32 bucket would share barrier
    semaphores; the registry refuses the second claimant."""
    import zlib

    # operate on a copy so neither the probe claim nor the 'stable_window'
    # claim below leaks into the process-global registry
    monkeypatch.setattr(pg, "_claimed_bases", dict(pg._claimed_bases))
    bucket = zlib.crc32(b"collision_probe") % (1 << 20)
    monkeypatch.setitem(pg._claimed_bases, bucket, "earlier_window")
    with pytest.raises(ValueError, match="collides"):
        pg.window_collective_id_base("collision_probe")
    # same-name re-derivation is always fine (idempotent claims)
    base = pg.window_collective_id_base("stable_window")
    assert pg.window_collective_id_base("stable_window") == base


def test_window_base_released_on_free(monkeypatch):
    """A freed window releases its bucket: per-experiment window names in a
    long-lived process must not accumulate spurious collisions."""
    import zlib

    monkeypatch.setattr(pg, "_claimed_bases", dict(pg._claimed_bases))
    pg.window_collective_id_base("ephemeral_win")
    bucket = zlib.crc32(b"ephemeral_win") % (1 << 20)
    monkeypatch.setitem(pg._claimed_bases, bucket, "ephemeral_win")
    pg.release_window_collective_id("ephemeral_win")
    assert bucket not in pg._claimed_bases
    # releasing someone ELSE's bucket is a no-op
    pg.window_collective_id_base("other_win")
    pg.release_window_collective_id("not_the_owner")
    assert zlib.crc32(b"other_win") % (1 << 20) in pg._claimed_bases

    # end-to-end: bf.win_free releases, so re-creating under a name that
    # shares the bucket (here: the same name) never raises
    import bluefog_tpu as bf
    from bluefog_tpu.topology import RingGraph
    import jax.numpy as jnp

    bf.init(topology=RingGraph(8))
    x = jnp.ones((8, 4), jnp.float32)
    for _ in range(3):
        assert bf.win_create(x, "recycled_win")
        bf.win_put(x, "recycled_win")
        bf.win_free("recycled_win")


def test_kill_switch(on_tpu, monkeypatch):
    sched = build_schedule(RingGraph(8))
    monkeypatch.setenv("BLUEFOG_TPU_PALLAS_GOSSIP", "0")
    assert pg.auto_gossip_backend(sched, SMALL) == "xla"


def test_neighbor_allreduce_consults_policy(monkeypatch):
    """backend='auto' actually dispatches on the policy's answer."""
    from bluefog_tpu.ops import collectives as C

    calls = {}

    def fake_policy(sched, x, **kw):
        calls["hit"] = True
        return "xla"

    monkeypatch.setattr(pg, "auto_gossip_backend", fake_policy)
    from jax.sharding import Mesh, PartitionSpec as P

    from bluefog_tpu.parallel.api import shard_map

    sched = build_schedule(RingGraph(8))
    mesh = Mesh(np.array(jax.devices()[:8]), ("bf",))
    fn = jax.jit(shard_map(
        lambda v: C.neighbor_allreduce(v, sched, "bf", backend="auto"),
        mesh=mesh, in_specs=(P("bf"),), out_specs=P("bf"), check_vma=False))
    out = fn(jnp.ones((8, 4), jnp.float32))
    jax.block_until_ready(out)
    assert calls.get("hit"), "auto did not consult auto_gossip_backend"


def test_win_put_consults_policy(monkeypatch):
    """The window transport's backend='auto' routes through the same
    policy as gossip (deliver = the RDMA kernels in put/acc mode)."""
    import bluefog_tpu as bf

    calls = {}
    real = pg.auto_gossip_backend

    def fake_policy(sched, x, **kw):
        calls["hit"] = True
        # the window transport must declare itself non-chunkable
        assert kw.get("chunkable") is False
        return real(sched, x, **kw)

    monkeypatch.setattr(pg, "auto_gossip_backend", fake_policy)
    bf.init(topology=RingGraph(8))
    x = jnp.ones((8, 4), jnp.float32)
    assert bf.win_create(x, "routing_probe")
    bf.win_put(x, "routing_probe")
    assert calls.get("hit"), "window auto did not consult auto_gossip_backend"
    bf.win_free("routing_probe")
