"""Dynamic lock-order tripwire (bluefog_tpu.utils.lockcheck).

1. unit — two threads forced into an ABBA inversion raise
   :class:`LockOrderViolation` DETERMINISTICALLY (the cycle-closing
   acquire is trapped before it blocks, so the test fails loudly
   instead of hanging); warn mode records without raising; reentrant
   and timed acquires add no false edges; same-class instance pairs
   are reported but never fatal; a condvar wait keeps the held-set
   honest across the release/re-acquire;
2. env arm — a subprocess launched with ``BLUEFOG_TPU_LOCKCHECK=1``
   runs checked with no code changes;
3. integration — the thread-mode dsgd + serving + control loops run
   under the tripwire and the observed lock-order graph has ZERO
   cycles: the runtime's real interleavings validate the static model
   (tests/test_analysis.py::TestConcurrencyLint) against reality.
"""

import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from bluefog_tpu.utils import lockcheck
from bluefog_tpu.utils.lockcheck import LockOrderViolation
from tests._util import REPO, clean_env, uniq


@pytest.fixture(autouse=True)
def _tripwire_isolated():
    """Every test starts with a clean edge table and ends disarmed."""
    lockcheck.reset()
    yield
    lockcheck.disable()
    lockcheck.reset()


def _multinode_cycles():
    return [c for c in lockcheck.cycles() if len(c) > 1]


# ---------------------------------------------------------------------------
# 1. unit
# ---------------------------------------------------------------------------


class TestTripwireUnit:
    def test_off_mode_is_transparent(self):
        a = lockcheck.lock("off.a")
        b = lockcheck.lock("off.b")
        with a:
            with b:
                pass
        with b:
            with a:  # ABBA — but the tripwire is off
                pass
        assert lockcheck.edges() == {}
        assert lockcheck.violations() == []

    def test_abba_cycle_detected_deterministically(self):
        # thread 1 teaches the table A -> B and exits; thread 2 then
        # attempts B -> A.  The inversion is caught at the ACQUIRE (no
        # real deadlock needed, no timing window): deterministic.
        lockcheck.enable()
        a = lockcheck.lock("abba.a")
        b = lockcheck.lock("abba.b")

        def forward():
            with a:
                with b:
                    pass

        t1 = threading.Thread(target=forward)
        t1.start()
        t1.join()
        assert ("abba.a", "abba.b") in lockcheck.edges()

        caught = []

        def backward():
            try:
                with b:
                    with a:
                        pass
            except LockOrderViolation as e:
                caught.append(e)

        t2 = threading.Thread(target=backward)
        t2.start()
        t2.join()
        assert len(caught) == 1, caught
        assert "ABBA" in str(caught[0])
        v = lockcheck.violations()
        assert v and v[0]["held"] == "abba.b" and v[0]["wanted"] == "abba.a"

    def test_warn_mode_records_without_raising(self):
        lockcheck.enable(raise_on_cycle=False)
        a = lockcheck.lock("warn.a")
        b = lockcheck.lock("warn.b")
        with a:
            with b:
                pass
        with b:
            with a:  # inversion recorded, not raised
                pass
        assert len(lockcheck.violations()) == 1
        assert _multinode_cycles() == [["warn.a", "warn.b"]]

    def test_cycle_records_blackbox_event(self):
        from bluefog_tpu.blackbox import recorder

        recorder.configure()
        try:
            lockcheck.enable(raise_on_cycle=False)
            a = lockcheck.lock("bb.a")
            b = lockcheck.lock("bb.b")
            with a:
                with b:
                    pass
            with b:
                with a:
                    pass
            rec = recorder.get()
            evts = [e for e in rec.events()
                    if e["kind"] == "lock_order_cycle"]
            assert evts and evts[0]["held"] == "bb.b", evts
        finally:
            recorder.reset()

    def test_plain_lock_self_reacquire_raises_before_blocking(self):
        # the PR-1 engine() shape live: a thread blocking on the plain
        # lock it already holds can never succeed — the tripwire must
        # raise, not hang.  Raises even in warn mode (continuing IS the
        # deadlock), so run it in warn mode to pin that down.
        lockcheck.enable(raise_on_cycle=False)
        mu = lockcheck.lock("selfdead.mu")
        with mu:
            with pytest.raises(LockOrderViolation, match="self-deadlock"):
                mu.acquire()
        v = lockcheck.violations()
        assert v and v[0].get("self_deadlock") is True
        assert v[0]["held"] == "selfdead.mu"

    def test_rlock_reentry_is_not_an_edge(self):
        lockcheck.enable()
        r = lockcheck.rlock("re.r")
        with r:
            with r:  # legal reentry: no self-edge, no violation
                pass
        assert lockcheck.edges() == {}

    def test_timed_acquire_adds_no_edge_but_holds(self):
        lockcheck.enable()
        a = lockcheck.lock("timed.a")
        b = lockcheck.lock("timed.b")
        with a:
            assert b.acquire(timeout=1.0)  # deadline: cannot deadlock
            b.release()
        assert ("timed.a", "timed.b") not in lockcheck.edges()
        # but a blocking acquire UNDER a timed hold still records the
        # held lock as the edge source (holding is holding)
        assert b.acquire(timeout=1.0)
        try:
            with a:
                pass
        finally:
            b.release()
        assert ("timed.b", "timed.a") in lockcheck.edges()

    def test_same_class_instances_report_but_never_raise(self):
        # two peers' locks share one class name: nesting them records a
        # same-class self-edge for the report, not a violation
        lockcheck.enable()
        p1 = lockcheck.lock("peer.cv")
        p2 = lockcheck.lock("peer.cv")
        with p1:
            with p2:
                pass
        e = lockcheck.edges()
        assert e[("peer.cv", "peer.cv")]["same_class"] is True
        assert lockcheck.violations() == []

    def test_condvar_wait_keeps_held_set_honest(self):
        # across cv.wait() the underlying lock is released and
        # re-acquired; locks the waiter still holds must order BEFORE
        # the re-acquire, and the held-set must balance to empty
        lockcheck.enable()
        outer = lockcheck.lock("cvh.outer")
        cv = lockcheck.condition("cvh.cv")
        done = threading.Event()

        def waiter():
            with outer:
                with cv:
                    cv.wait(timeout=0.5)
            done.set()

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        with cv:
            cv.notify_all()
        t.join(timeout=5)
        assert done.is_set()
        assert ("cvh.outer", "cvh.cv") in lockcheck.edges()
        assert _multinode_cycles() == []

    def test_reset_clears_the_table(self):
        lockcheck.enable()
        a = lockcheck.lock("rst.a")
        b = lockcheck.lock("rst.b")
        with a:
            with b:
                pass
        assert lockcheck.edges()
        lockcheck.reset()
        assert lockcheck.edges() == {}
        assert lockcheck.violations() == []

    def test_locks_created_before_enable_are_tracked(self):
        # the package creates its locks at import time; a test that
        # enables the tripwire later must still see them
        a = lockcheck.lock("late.a")
        b = lockcheck.lock("late.b")
        lockcheck.enable()
        with a:
            with b:
                pass
        assert ("late.a", "late.b") in lockcheck.edges()


# ---------------------------------------------------------------------------
# 2. env arm: BLUEFOG_TPU_LOCKCHECK=1 needs no code changes
# ---------------------------------------------------------------------------


class TestEnvArm:
    def test_env_var_arms_and_traps_in_subprocess(self):
        code = (
            "import threading\n"
            "from bluefog_tpu.utils import lockcheck\n"
            "assert lockcheck.enabled()\n"
            "a = lockcheck.lock('env.a'); b = lockcheck.lock('env.b')\n"
            "t = threading.Thread(target=lambda: (a.acquire(), "
            "b.acquire(), b.release(), a.release()))\n"
            "t.start(); t.join()\n"
            "hit = []\n"
            "def inv():\n"
            "    try:\n"
            "        with b:\n"
            "            with a:\n"
            "                pass\n"
            "    except lockcheck.LockOrderViolation:\n"
            "        hit.append(1)\n"
            "t2 = threading.Thread(target=inv); t2.start(); t2.join()\n"
            "assert hit, 'inversion not trapped'\n"
            "print('TRAPPED')\n"
        )
        env = clean_env()
        env["BLUEFOG_TPU_LOCKCHECK"] = "1"
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=120, cwd=REPO, env=env)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "TRAPPED" in proc.stdout

    def test_env_off_means_off(self):
        code = (
            "from bluefog_tpu.utils import lockcheck\n"
            "assert not lockcheck.enabled()\n"
            "print('OFF')\n"
        )
        env = clean_env()
        env["BLUEFOG_TPU_LOCKCHECK"] = "0"
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=120, cwd=REPO, env=env)
        assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# 3. integration: the real thread-mode loops under the tripwire
# ---------------------------------------------------------------------------


def _zero_grad():
    def loss_and_grad(rank, step, params):
        return 0.0, {k: np.zeros_like(v) for k, v in params.items()}

    return loss_and_grad


class TestRuntimeUnderTripwire:
    """Drive the real loops with raise-on-cycle armed: any ABBA the
    static model missed fails the test at the acquire, and the edge
    table must end cycle-free."""

    def test_thread_dsgd_loop_is_cycle_free(self):
        from bluefog_tpu import topology as T
        from bluefog_tpu.runtime.async_windows import run_async_dsgd

        lockcheck.enable()
        report = run_async_dsgd(
            T.RingGraph(3), {"w": np.ones(6, np.float32)},
            _zero_grad(), lr=0.01, duration_s=1.0, skew=[0.002] * 3,
            name=uniq("lc_dsgd"))
        assert abs(report.total_mass - 3.0) < 1e-9
        assert lockcheck.violations() == []
        assert _multinode_cycles() == []
        # prove tracking was live for the whole run (which package locks
        # NEST during it depends on which caches earlier tests already
        # warmed, so assert liveness directly, not on a specific edge)
        probe_a = lockcheck.lock("probe.a")
        probe_b = lockcheck.lock("probe.b")
        with probe_a:
            with probe_b:
                pass
        assert ("probe.a", "probe.b") in lockcheck.edges()

    def test_serving_loop_is_cycle_free(self):
        from bluefog_tpu import topology as T
        from bluefog_tpu.runtime.async_windows import run_async_dsgd
        from bluefog_tpu.runtime.window_server import WindowServer
        from bluefog_tpu.serving import SnapshotUnavailable
        from bluefog_tpu.serving.client import SnapshotClient

        lockcheck.enable()
        name = uniq("lc_serve")
        srv = WindowServer()
        addr = srv.start("127.0.0.1")
        stop = threading.Event()
        seen = []

        def reader():
            c = SnapshotClient(addr, f"{name}:0",
                               retry=dict(base_s=0.01, budget=4, seed=0))
            while not stop.is_set():
                try:
                    seen.append(c.snapshot().round)
                except (SnapshotUnavailable, RuntimeError, OSError):
                    pass
                time.sleep(0.01)
            c.close()

        t = threading.Thread(target=reader)
        t.start()
        try:
            run_async_dsgd(
                T.RingGraph(3), {"w": np.ones(6, np.float32)},
                _zero_grad(), lr=0.01, duration_s=1.5,
                skew=[0.002] * 3, name=name, snapshot_every=1)
        finally:
            stop.set()
            t.join(timeout=10)
            srv.stop()
        assert seen, "reader never saw a snapshot"
        assert lockcheck.violations() == []
        assert _multinode_cycles() == []

    def test_control_loop_is_cycle_free(self):
        from bluefog_tpu import topology as T
        from bluefog_tpu.control import ControlConfig
        from bluefog_tpu.runtime.async_windows import run_async_dsgd

        lockcheck.enable()
        report = run_async_dsgd(
            T.ExponentialTwoGraph(4), {"w": np.zeros(8, np.float32)},
            _zero_grad(), duration_s=2.0,
            skew=[0.002, 0.002, 0.002, 0.05],
            name=uniq("lc_ctl"),
            control=ControlConfig(evidence_every=4, cooldown_rounds=8,
                                  min_lag_s=0.02))
        assert abs(report.total_mass - 4.0) < 1e-9 * 4
        assert lockcheck.violations() == []
        assert _multinode_cycles() == []
