"""Flash-attention backend numerics — needs a real TPU backend (the CPU test
mesh uses the dense path; the kernel itself is Pallas TPU-only).

Under pytest these SKIP: tests/conftest.py pins the CPU platform before any
test module imports, so ``jax.default_backend()`` is ``'cpu'`` here.  To run
the numerics against the chip, execute the file directly (no conftest):

    python tests/test_flash_attention.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))  # direct run

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bluefog_tpu.ops.ring_attention import _flash_eligible, local_attention

tpu_only = pytest.mark.skipif(
    jax.default_backend() not in ("tpu", "axon"),
    reason="flash kernel needs a TPU backend")


def _rand(shape, key):
    return jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)


@tpu_only
@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(causal):
    B, T, H, D = 2, 256, 4, 64
    q, k, v = (_rand((B, T, H, D), i) for i in range(3))
    dense = local_attention(q, k, v, causal=causal, backend="dense")
    flash = local_attention(q, k, v, causal=causal, backend="flash")
    np.testing.assert_allclose(
        np.asarray(flash), np.asarray(dense), atol=2e-2, rtol=2e-2)


@tpu_only
def test_flash_grads_match_dense():
    B, T, H, D = 1, 128, 2, 64
    q, k, v = (_rand((B, T, H, D), i) for i in range(3))

    def loss(backend):
        def f(q, k, v):
            return jnp.sum(local_attention(q, k, v, causal=True,
                                           backend=backend) ** 2)
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    gd, gf = loss("dense"), loss("flash")
    for a, b in zip(gd, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-2, rtol=5e-2)


def test_eligibility_gate():
    q = jnp.zeros((1, 256, 2, 64))
    k = jnp.zeros((1, 256, 2, 64))
    on_tpu = jax.default_backend() in ("tpu", "axon")
    assert _flash_eligible(q, k, True, 0, 0) == on_tpu
    # traced/unequal offsets, short or ragged T: never eligible
    assert not _flash_eligible(q, k, True, 0, 128)          # shifted causal
    assert not _flash_eligible(q, k, True, jnp.zeros(()), 0)  # traced offset
    assert not _flash_eligible(q[:, :96], k[:, :96], False, 0, 0)  # T % 128
    assert not _flash_eligible(q, k[:, :128], False, 0, 0)  # Tq != Tk


def test_forced_flash_on_ineligible_raises():
    q = k = v = jnp.zeros((1, 256, 2, 64))
    with pytest.raises(ValueError, match="flash"):
        # shifted causal offsets are never flash-eligible, on any backend
        local_attention(q, k, v, causal=True, q_offset=0, k_offset=128,
                        backend="flash")


if __name__ == "__main__":
    # direct execution path — real chip, no conftest CPU pin
    test_eligibility_gate()
    test_forced_flash_on_ineligible_raises()
    for c in (False, True):
        test_flash_matches_dense(c)
    test_flash_grads_match_dense()
    print("OK (flash numerics verified on", jax.default_backend(), ")")
