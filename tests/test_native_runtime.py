"""Native C++ host runtime: build, timeline writer, async engine, logging.

Mirrors the reference's host-side C++ test surface (tensor_queue /
handle_manager / timeline; SURVEY.md §2.1) — here exercised through the
ctypes bindings exactly as the framework uses them.
"""

import ctypes
import json
import os
import threading
import time

import pytest

from bluefog_tpu.runtime import native


@pytest.fixture(scope="module")
def lib():
    lib = native.load()
    if lib is None:
        pytest.skip("native runtime unavailable (no g++?)")
    return lib


def test_build_produces_library(lib):
    assert os.path.exists(native._LIB_PATH)


def test_log_level_roundtrip(lib):
    old = lib.bf_log_level()
    try:
        lib.bf_set_log_level(2)
        assert lib.bf_log_level() == 2
        lib.bf_log(2, b"info message from test")
        lib.bf_log(0, b"suppressed trace message")
    finally:
        lib.bf_set_log_level(old)


def test_timeline_writer_emits_valid_chrome_trace(tmp_path, lib):
    path = tmp_path / "trace.json"
    w = native.TimelineWriter(str(path))
    w.begin(b"neighbor_allreduce.grad", b"comm", 1)
    time.sleep(0.002)
    w.end(b"neighbor_allreduce.grad", b"comm", 1)
    w.instant(b"step", b"marker")
    w.close()

    events = json.loads(path.read_text())
    assert [e["ph"] for e in events] == ["B", "E", "i"]
    b, e, _ = events
    assert b["name"] == "neighbor_allreduce.grad"
    assert b["cat"] == "comm"
    assert e["ts"] >= b["ts"]


def test_timeline_double_start_fails(tmp_path, lib):
    path = tmp_path / "t.json"
    w = native.TimelineWriter(str(path))
    try:
        assert lib.bf_timeline_start(str(tmp_path / "t2.json").encode()) != 0
    finally:
        w.close()


def test_engine_enqueue_poll_synchronize(lib):
    eng = native.Engine()
    assert eng.native
    ran = threading.Event()
    h = eng.enqueue(ran.set, op="test", name="set_event")
    assert eng.synchronize(h, timeout_s=5) == 0
    assert ran.is_set()
    assert eng.poll(h) is False  # cleared handle reads as not-done


def test_engine_preserves_fifo_order(lib):
    eng = native.Engine()
    order = []
    handles = [
        eng.enqueue((lambda i=i: order.append(i)), name=f"op{i}")
        for i in range(32)
    ]
    for h in handles:
        eng.synchronize(h, timeout_s=5)
    assert order == list(range(32))


def test_engine_propagates_exceptions(lib):
    eng = native.Engine()

    def boom():
        raise ValueError("host op failed")

    h = eng.enqueue(boom)
    with pytest.raises(ValueError, match="host op failed"):
        eng.synchronize(h, timeout_s=5)


def test_engine_overlaps_with_main_thread(lib):
    """The engine thread runs ops while the main thread keeps working —
    the reference's comm/compute overlap contract (SURVEY.md §3.3)."""
    eng = native.Engine()
    started = threading.Event()
    release = threading.Event()

    def blocker():
        started.set()
        release.wait(timeout=10)

    h = eng.enqueue(blocker, name="blocker")
    assert started.wait(timeout=5)
    assert eng.poll(h) is False
    assert eng.pending_count() >= 1
    release.set()
    eng.synchronize(h, timeout_s=5)
    assert eng.pending_count() == 0


def test_engine_wait_timeout(lib):
    eng = native.Engine()
    release = threading.Event()
    h = eng.enqueue(lambda: release.wait(timeout=10), name="slow")
    with pytest.raises(TimeoutError):
        eng.synchronize(h, timeout_s=0.05)
    release.set()
    eng.synchronize(h, timeout_s=5)


def test_engine_wait_all(lib):
    eng = native.Engine()
    counter = []
    for i in range(8):
        eng.enqueue(lambda i=i: counter.append(i))
    eng.wait_all(timeout_s=5)
    assert len(counter) == 8


def test_py_engine_fallback_same_semantics():
    eng = native.PyEngine()
    try:
        out = []
        h1 = eng.enqueue(lambda: out.append(1))
        h2 = eng.enqueue(lambda: out.append(2))
        eng.synchronize(h1, timeout_s=5)
        eng.synchronize(h2, timeout_s=5)
        assert out == [1, 2]

        def boom():
            raise RuntimeError("py boom")

        with pytest.raises(RuntimeError, match="py boom"):
            eng.synchronize(eng.enqueue(boom), timeout_s=5)
        with pytest.raises(KeyError):
            eng.synchronize(10_000)
    finally:
        eng.shutdown()


def test_unknown_handle_raises(lib):
    eng = native.Engine()
    with pytest.raises(KeyError):
        eng.synchronize(99_999)


def test_wait_all_reraises_and_clears(lib):
    """wait_all must surface op failures (e.g. failed checkpoint IO) and
    clear handles so long runs don't leak the handle table."""
    eng = native.Engine()

    def boom():
        raise OSError("disk full")

    eng.enqueue(lambda: None)
    eng.enqueue(boom)
    eng.enqueue(lambda: None)
    with pytest.raises(OSError, match="disk full"):
        eng.wait_all(timeout_s=5)
    eng.wait_all(timeout_s=5)  # survivors drained, errors not re-raised twice
    assert eng.pending_count() == 0
    with native._handles_lock:
        assert not native._handles  # no trampoline leak


def test_callback_status_does_not_collide_with_sentinels(lib):
    """A raw C-level status of -1/-2 must not masquerade as unknown-handle
    or timeout (bf_wait reports status out-of-band)."""
    status = ctypes.c_int(123)
    cb = native._CALLBACK_T(lambda _arg: -2)
    h = lib.bf_enqueue(b"test", b"neg_status", cb, None)
    assert h >= 0
    rc = lib.bf_wait(h, 5000, ctypes.byref(status))
    assert rc == 0
    assert status.value == -2
    lib.bf_clear(h)


def test_engine_restarts_after_shutdown(lib):
    eng = native.Engine()
    eng.shutdown()
    out = []
    h = eng.enqueue(lambda: out.append(1))  # auto-restarts the thread
    eng.synchronize(h, timeout_s=5)
    assert out == [1]


def test_handles_valid_across_engine_instances(lib):
    a, b = native.Engine(), native.Engine()

    def boom():
        raise ValueError("cross-instance")

    h = a.enqueue(boom)
    with pytest.raises(ValueError, match="cross-instance"):
        b.synchronize(h, timeout_s=5)


def test_py_engine_restarts_after_shutdown():
    eng = native.PyEngine()
    eng.shutdown()
    out = []
    h = eng.enqueue(lambda: out.append(1))  # auto-restarts, like native
    eng.synchronize(h, timeout_s=5)
    assert out == [1]
    eng.shutdown()


def test_py_engine_double_shutdown_then_enqueue():
    """A stale shutdown sentinel must not kill the restarted worker."""
    eng = native.PyEngine()
    eng.shutdown()
    eng.shutdown()  # idempotent: no second sentinel
    out = []
    h = eng.enqueue(lambda: out.append(1))
    eng.synchronize(h, timeout_s=5)
    assert out == [1]
    eng.shutdown()
