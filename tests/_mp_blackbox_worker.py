"""One rank of the SIGSTOP hang-forensics test (tests/test_blackbox.py).

Each rank process plays a synchronous ring "gossip": per round it records
``collective_begin``, deposits to its ring neighbors through the TCP
window-server transport (when the native runtime is available — the
FileBarrier alone carries the rendezvous otherwise), rendezvouses at a
FileBarrier, records ``collective_end`` and beats its watchdog.  When the
parent SIGSTOPs one rank, the survivors block at the barrier, their
watchdogs time out and write blackbox dumps, and ``bfblackbox-tpu`` must
name the stopped rank and the (step, collective-id) it never completed.

argv: rank world barrier_dir steps [slow_rank]
env:  BLUEFOG_TPU_BLACKBOX_DIR (incident dir), set by the parent.
"""

import os
import sys
import time

rank = int(sys.argv[1])
world = int(sys.argv[2])
barrier_dir = sys.argv[3]
steps = int(sys.argv[4])
slow_rank = int(sys.argv[5]) if len(sys.argv) > 5 else -1

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["BLUEFOG_TPU_RANK"] = str(rank)
os.environ["BLUEFOG_TPU_WORLD"] = str(world)

import numpy as np  # noqa: E402

from bluefog_tpu.blackbox import recorder  # noqa: E402
from bluefog_tpu.runtime import native  # noqa: E402
from bluefog_tpu.runtime.async_windows import AsyncWindow, FileBarrier  # noqa: E402
from bluefog_tpu.utils.failure import Heartbeat  # noqa: E402

rec = recorder.get()
assert rec is not None, "blackbox recording must be on for this test"
bar = FileBarrier(barrier_dir, world, rank)
peers = sorted({(rank - 1) % world, (rank + 1) % world})

# Window-server transport where the native runtime exists; the barrier is
# the collective either way, so the forensics path is identical.
server = None
remotes = {}
win = None
if native.load() is not None:
    from bluefog_tpu.runtime.window_server import RemoteWindow, WindowServer

    win = AsyncWindow(f"bbx{os.path.basename(barrier_dir)}:{rank}", 2, 4,
                      np.float64)
    server = WindowServer()
    _, port = server.start("127.0.0.1")
    tmp = os.path.join(barrier_dir, f"addr.{rank}.tmp")
    with open(tmp, "w") as f:
        f.write(str(port))
    os.replace(tmp, os.path.join(barrier_dir, f"addr.{rank}"))

bar.wait("created", timeout_s=120)

if server is not None:
    from bluefog_tpu.runtime.window_server import RemoteWindow

    for p in peers:
        with open(os.path.join(barrier_dir, f"addr.{p}")) as f:
            port = int(f.read().strip())
        remotes[p] = RemoteWindow(
            ("127.0.0.1", port),
            f"bbx{os.path.basename(barrier_dir)}:{p}")

hb = Heartbeat(timeout_s=2.5, action="callback")
hb.start()
hb.beat(-1)
print("READY", flush=True)
bar.wait("start", timeout_s=120)

payload = np.full(4, float(rank), np.float64)
for step in range(steps):
    key = ("ring", rank, step)
    rec.begin("collective", key=key, op="ring_round", cid="ring_round#0",
              step=step, rank=rank, peers=peers)
    for p, rw in remotes.items():
        rw.deposit(0 if p == peers[0] else 1, payload, accumulate=True)
    bar.wait(f"round{step}", timeout_s=300)
    rec.end("collective", key=key, op="ring_round", cid="ring_round#0",
            step=step, rank=rank)
    hb.beat(step)
    print(f"STEP {step}", flush=True)
    if rank == slow_rank:
        # a window between rounds for the parent's SIGSTOP to land
        # deterministically OUTSIDE a round
        time.sleep(0.5)

hb.stop()
for rw in remotes.values():
    rw.close()
if server is not None:
    server.stop()
    win.free()
print("DONE", flush=True)
