"""Multi-process test worker (launched by test_multiprocess.py, one OS
process per rank — the analog of the reference's ``mpirun -np N pytest``
harness, SURVEY.md §4).

argv: <process_id> <num_processes> <coordinator_port>

Each process owns 2 virtual CPU devices; the global mesh spans
``2 * num_processes`` devices across real process boundaries, with gloo
carrying the cross-process collectives.  Asserts, printing MP_WORKER_OK on
success:

1. loud rendezvous via ``initialize_cluster`` (explicit args);
2. ``process_rank``/``process_count`` and a spanning ``bf.init`` context;
3. closed-form gossip (neighbor_allreduce) ACROSS the process boundary;
4. closed-form global allreduce;
5. hierarchical gossip with the process boundary as the machine boundary,
   in BOTH forms — flat mesh and the two-level (machine, local) mesh whose
   outer axis crosses processes (the multi-slice/DCN shape);
6. ``win_mutex`` is a real cross-process lock: racing read-modify-write
   increments on the coordination-service KV never lose an update;
7. ``win_mutex_break`` recovers a stale lock whose owner died (timeout
   names the dead owner; after break the mutex is acquirable again) —
   the manual path, still needed for lease-less keys;
8. a LEASED lock whose owner died auto-recovers with no manual break
   (the lease expired, the next contender steals through the break
   subkey), while a LIVE slow holder is never stolen (its heartbeat
   refreshes the lease faster than it expires);
9. ``win_mutex_sweep`` clears exactly the expired-lease keys (the
   supervisor-restart janitor).
"""

import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np

LOCAL_DEVICES = 2
MUTEX_ITERS = 15


def main():
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    from bluefog_tpu.runtime.launch import initialize_cluster

    initialize_cluster(f"127.0.0.1:{port}", nproc, pid,
                       initialization_timeout=60)

    import bluefog_tpu as bf
    from bluefog_tpu.ops import collectives as C
    from bluefog_tpu.parallel.api import shard_map, win_mutex
    from bluefog_tpu.topology import RingGraph
    from bluefog_tpu.topology.schedule import build_schedule

    assert jax.process_count() == nproc, jax.process_count()
    assert bf.process_rank() == pid
    n = nproc * LOCAL_DEVICES
    assert len(jax.devices()) == n

    ctx = bf.init(topology=RingGraph(n))
    assert ctx.size == n
    # rank(): mesh-rank of this controller's first device
    assert bf.rank() == pid * LOCAL_DEVICES, bf.rank()

    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    sched = build_schedule(RingGraph(n))
    xs_global = np.arange(n * 3, dtype=np.float32).reshape(n, 3)
    local = xs_global[pid * LOCAL_DEVICES:(pid + 1) * LOCAL_DEVICES]
    xs = multihost_utils.host_local_array_to_global_array(
        local, ctx.mesh, P(ctx.axis_name))

    # 3. gossip across the process boundary, closed form: out = W @ xs
    f = jax.jit(shard_map(
        lambda v: C.neighbor_allreduce(v, sched, ctx.axis_name),
        mesh=ctx.mesh, in_specs=(P(ctx.axis_name),),
        out_specs=P(ctx.axis_name), check_vma=False))
    out = f(xs)
    want = RingGraph(n).weights @ xs_global
    for shard in out.addressable_shards:
        row = shard.index[0].start  # global row of this local shard
        np.testing.assert_allclose(
            np.asarray(shard.data), want[row:row + 1], rtol=1e-6, atol=1e-6)

    # 4. global allreduce (mean) across both processes
    g = jax.jit(shard_map(
        lambda v: C.allreduce(v, ctx.axis_name, average=True),
        mesh=ctx.mesh, in_specs=(P(ctx.axis_name),), out_specs=P(ctx.axis_name),
        check_vma=False))
    mean_out = g(xs)
    for shard in mean_out.addressable_shards:
        np.testing.assert_allclose(
            np.asarray(shard.data)[0], xs_global.mean(axis=0), rtol=1e-6)

    # 5. hierarchical gossip with the PROCESS boundary as the machine
    # boundary — both forms: flat mesh (axis_index_groups) and the
    # two-level (machine, local) mesh whose outer axis crosses processes
    # (the multi-slice/DCN shape).  Closed form: machine means, then W @ m.
    ctx2 = bf.init(topology=RingGraph(n), local_size=LOCAL_DEVICES,
                   machine_topology=RingGraph(nproc), use_ici_order=False)
    assert bf.machine_rank() == pid and bf.local_rank() == 0
    msched = ctx2.machine_schedule
    means = xs_global.reshape(nproc, LOCAL_DEVICES, -1).mean(axis=1)
    want_h = (RingGraph(nproc).weights @ means)

    flat_fn = jax.jit(shard_map(
        lambda v: C.hierarchical_neighbor_allreduce(
            v, msched, ctx2.axis_name, local_size=LOCAL_DEVICES),
        mesh=ctx2.mesh, in_specs=(P(ctx2.axis_name),),
        out_specs=P(ctx2.axis_name), check_vma=False))
    xs2 = multihost_utils.host_local_array_to_global_array(
        local, ctx2.mesh, P(ctx2.axis_name))
    for shard in flat_fn(xs2).addressable_shards:
        row = shard.index[0].start
        np.testing.assert_allclose(
            np.asarray(shard.data)[0], want_h[row // LOCAL_DEVICES],
            rtol=1e-6, atol=1e-6)

    spec2 = P((ctx2.machine_axis_name, ctx2.local_axis_name))
    two_fn = jax.jit(shard_map(
        lambda v: C.hierarchical_neighbor_allreduce_2d(
            v, msched, machine_axis=ctx2.machine_axis_name,
            local_axis=ctx2.local_axis_name),
        mesh=ctx2.hier_mesh, in_specs=(spec2,), out_specs=spec2,
        check_vma=False))
    xs3 = multihost_utils.host_local_array_to_global_array(
        local, ctx2.hier_mesh, spec2)
    for shard in two_fn(xs3).addressable_shards:
        row = shard.index[0].start
        np.testing.assert_allclose(
            np.asarray(shard.data)[0], want_h[row // LOCAL_DEVICES],
            rtol=1e-6, atol=1e-6)

    # 6. win_mutex: cross-process read-modify-write must not lose updates
    from jax._src.distributed import global_state
    client = global_state.client
    if pid == 0:
        client.key_value_set("mp_counter", "0")
    client.wait_at_barrier("mutex_start", 30_000)
    for _ in range(MUTEX_ITERS):
        with win_mutex("mp_test"):
            v = int(client.blocking_key_value_get("mp_counter", 10_000))
            time.sleep(0.002)  # widen the race window
            client.key_value_set("mp_counter", str(v + 1),
                                 allow_overwrite=True)
    client.wait_at_barrier("mutex_end", 60_000)
    total = int(client.blocking_key_value_get("mp_counter", 10_000))
    assert total == nproc * MUTEX_ITERS, (
        f"lost updates: counter {total} != {nproc * MUTEX_ITERS}")

    # 7. win_mutex_break: a dead owner's stale lock blocks acquisition
    # (TimeoutError naming the owner), break clears it, and the mutex is
    # acquirable again — the MPI_Win_unlock_all-after-failure analog.
    from bluefog_tpu.parallel.api import win_mutex_break

    if pid == 0:
        client.key_value_set("bluefog_tpu/win_mutex/stale_probe",
                             "999:1:1")  # an owner that no longer exists
    client.wait_at_barrier("break_start", 30_000)
    if pid == 1:
        try:
            with win_mutex("stale_probe", timeout_s=0.5):
                raise AssertionError("acquired a lock a dead owner holds")
        except TimeoutError as e:
            assert "999:1:1" in str(e), e  # names the dead owner
        assert win_mutex_break("stale_probe") is True
        with win_mutex("stale_probe", timeout_s=5):
            pass  # recovered
    client.wait_at_barrier("break_end", 60_000)

    # 8a. expired lease -> automatic recovery, no manual break anywhere.
    # A dead leased holder leaves exactly this state behind: a value with
    # a lease stamp in the past and no heartbeat refreshing it.
    from bluefog_tpu.parallel.api import (_LEASE_MARK, _WIN_MUTEX_PREFIX,
                                          win_mutex_sweep)

    if pid == 0:
        client.key_value_set(
            _WIN_MUTEX_PREFIX + "lease_probe",
            f"999:1:1{_LEASE_MARK}{time.time() - 5.0:.3f}")
    client.wait_at_barrier("lease_start", 30_000)
    if pid == 1:
        t0 = time.monotonic()
        with win_mutex("lease_probe", timeout_s=15):
            pass  # stolen from the dead owner automatically
        # expected ~2-3s: the contender must watch the value stay
        # unchanged for the confirmation window before it may steal
        assert time.monotonic() - t0 < 12, "auto-recovery took too long"
    client.wait_at_barrier("lease_mid", 60_000)

    # 8b. a live holder with a SHORT lease and a LONGER critical section is
    # never stolen: the heartbeat out-refreshes the lease (and every
    # refresh resets contenders' unchanged-value confirmation clocks).
    if pid == 0:
        with win_mutex("live_probe", lease_s=3.0):
            client.wait_at_barrier("live_held", 30_000)
            time.sleep(4.0)  # > one full lease period
        client.wait_at_barrier("live_done", 60_000)
    else:
        client.wait_at_barrier("live_held", 30_000)
        try:
            with win_mutex("live_probe", timeout_s=1.5):
                raise AssertionError("stole a LIVE holder's lock")
        except TimeoutError:
            pass
        client.wait_at_barrier("live_done", 60_000)
        with win_mutex("live_probe", timeout_s=10):
            pass  # released normally: acquirable
    client.wait_at_barrier("live_end", 60_000)

    # 9. sweep clears exactly the expired-lease keys
    if pid == 0:
        now = time.time()
        client.key_value_set(_WIN_MUTEX_PREFIX + "sweep_a",
                             f"9:1:1{_LEASE_MARK}{now - 60:.3f}")
        client.key_value_set(_WIN_MUTEX_PREFIX + "sweep_b",
                             f"9:2:2{_LEASE_MARK}{now - 60:.3f}")
        client.key_value_set(_WIN_MUTEX_PREFIX + "sweep_live",
                             f"9:3:3{_LEASE_MARK}{now + 600:.3f}")
        removed = win_mutex_sweep()
        assert removed == 2, f"sweep removed {removed}, expected 2"
        # the unexpired key survived
        assert client.key_value_try_get(_WIN_MUTEX_PREFIX + "sweep_live")
        client.key_value_delete(_WIN_MUTEX_PREFIX + "sweep_live")
    client.wait_at_barrier("sweep_end", 60_000)

    print(f"MP_WORKER_OK {pid}", flush=True)


if __name__ == "__main__":
    main()
