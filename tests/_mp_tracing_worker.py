"""Causal-tracing multi-process acceptance worker (one process per rank).

argv: <rank> <capacity> <barrier_dir> <trace_dir> <steps>

Every rank runs a tcp dsgd loop with ``BLUEFOG_TPU_TRACE`` armed at the
shared ``trace_dir`` (one ``trace-rank<k>.jsonl`` per rank — the
one-process-per-rank shape ``set_rank`` pins).  Rank 2's window SERVER
runs behind ``server:delay`` chaos, so every deposit INTO rank 2 crawls
and its senders feel it through the bounded in-flight window — the
edge ``bftrace-tpu`` must then name as the per-round critical path.

Prints ``TRC_MP_OK <rank>`` on success; the TEST process merges the
trace files and asserts the attribution.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""


def main():
    rank, capacity = int(sys.argv[1]), int(sys.argv[2])
    barrier_dir, trace_dir = sys.argv[3], sys.argv[4]
    steps = int(sys.argv[5])

    # arm tracing BEFORE the package imports (env-lazy, like blackbox)
    os.environ["BLUEFOG_TPU_TRACE"] = trace_dir
    if rank == 2:
        # rank 2's server delays EVERY inbound frame 40 ms (rate=1 —
        # a probabilistic rate leaves unlucky runs where the healthy
        # ranks' ping-pong gating time rivals the chaos edge): every
        # deposit toward it is slow, its senders back-pressure on the
        # bounded in-flight window, and the 0->2 / 1->2 edges carry
        # the fleet's gating wall-clock by a wide margin
        os.environ["BLUEFOG_TPU_CHAOS"] = "server:delay:ms=40:rate=1:seed=3"

    import numpy as np

    from bluefog_tpu.runtime.async_windows import (FileBarrier,
                                                   run_async_dsgd_rank)
    from bluefog_tpu.runtime.resilience import ResilienceConfig
    from bluefog_tpu.topology import ExponentialTwoGraph

    def loss_and_grad(r, step, params):
        # zero-gradient pure averaging: consensus dynamics without a
        # jax dependency in the hot loop
        return 0.0, {"w": np.zeros_like(np.asarray(params["w"]))}

    rep = run_async_dsgd_rank(
        ExponentialTwoGraph(capacity), rank,
        {"w": np.arange(32.0, dtype=np.float64)}, loss_and_grad,
        barrier=FileBarrier(barrier_dir, capacity, rank),
        duration_s=120.0, skew_s=0.002,
        name=f"trc_mp_{os.path.basename(barrier_dir)}",
        transport="tcp", tcp_bind="127.0.0.1",
        resilience=ResilienceConfig(
            barrier_timeout_s=90.0, reconnect_budget=8, seed=rank),
        stop_after_steps=steps,
        stream_options=dict(max_in_flight=2, max_queue_items=4))

    if rank == 0:
        assert rep is not None
        assert abs(rep.total_mass - capacity) <= 1e-9 * capacity, \
            rep.total_mass
        assert min(rep.steps_per_rank) >= steps, rep.steps_per_rank

    # land the spans before exit (the atexit hook would too; explicit
    # beats implicit for a subprocess the test will immediately read)
    from bluefog_tpu.tracing import recorder as trc

    trc.flush()
    print(f"TRC_MP_OK {rank}", flush=True)


if __name__ == "__main__":
    main()
