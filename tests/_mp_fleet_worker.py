"""Fleet health plane multi-process test worker (one OS process per rank).

argv: <rank> <n_ranks> <barrier_dir> <variant: chaos|clean> <steps>

Every rank runs ``run_async_dsgd_rank(transport="tcp",
fleet=FleetConfig(every=1))`` — the telemetry publisher appends one
``fleet.<rank>`` record per round into the barrier directory.  Under
the ``chaos`` variant rank 2's window SERVER delays EVERY inbound
frame 150 ms (``server:delay:ms=150:rate=1.0`` — a deterministic
straggler): its senders' ack EWMAs toward it blow up, their records
carry the lag, and the ``bffleet-tpu --check`` replay the test runs
afterwards must name rank 2 and exit nonzero — while the ``clean``
twin replays to exit 0.

Rank 0 additionally asserts the EXACT push-sum mass audit (total ==
n to 1e-9·n) — the publisher reads telemetry, it never moves mass.

Prints ``FLEET_MP_OK <rank>`` on success.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""

SLOW_RANK = 2
CHAOS_SPEC = "server:delay:ms=150:rate=1.0:seed=1"


def main():
    rank, n = int(sys.argv[1]), int(sys.argv[2])
    barrier_dir, variant, steps = sys.argv[3], sys.argv[4], int(sys.argv[5])

    if variant == "chaos" and rank == SLOW_RANK:
        os.environ["BLUEFOG_TPU_CHAOS"] = CHAOS_SPEC

    import numpy as np

    from bluefog_tpu.fleet import FleetConfig
    from bluefog_tpu.runtime.async_windows import (FileBarrier,
                                                   run_async_dsgd_rank)
    from bluefog_tpu.topology import FullyConnectedGraph

    def loss_and_grad(r, step, params):
        # zero-gradient pure averaging: consensus dynamics, no jax
        return 0.0, {"w": np.zeros_like(np.asarray(params["w"]))}

    rep = run_async_dsgd_rank(
        FullyConnectedGraph(n), rank,
        {"w": np.arange(32.0, dtype=np.float64)}, loss_and_grad,
        barrier=FileBarrier(barrier_dir, n, rank),
        duration_s=60.0,
        # ~50 ms rounds: the 150 ms chaos ack latency lands within the
        # first few rounds' EWMAs, so detection latency is measured in
        # rounds, not in EWMA warm-up time
        skew_s=0.05,
        name=f"fleet_mp_{os.path.basename(barrier_dir)}",
        transport="tcp", tcp_bind="127.0.0.1",
        # every rank carries the same step target: without elastic
        # stopped-detection, one rank stopping early would just idle at
        # the stop barrier while the others burn duration_s
        stop_after_steps=steps,
        fleet=FleetConfig(every=1))

    if rank == 0:
        assert rep is not None
        assert abs(rep.total_mass - n) <= 1e-9 * n, rep.total_mass
        assert rep.dead_ranks == [], rep.dead_ranks
        assert min(rep.steps_per_rank) >= steps, rep.steps_per_rank

    print(f"FLEET_MP_OK {rank}", flush=True)


if __name__ == "__main__":
    main()
