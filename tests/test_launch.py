"""Launcher CLI (bfrun-tpu analog): simulate mode, env propagation, timeline."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_cli(cli_args, *, env_extra=None, timeout=180):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.runtime.launch"] + cli_args,
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )


def test_simulate_gives_virtual_devices(tmp_path):
    script = tmp_path / "probe.py"
    script.write_text(
        "import jax\n"
        "assert jax.devices()[0].platform == 'cpu', jax.devices()\n"
        "assert len(jax.devices()) == 8, jax.devices()\n"
        "print('DEVICES', len(jax.devices()))\n"
    )
    r = _run_cli(["--simulate", "8", str(script)])
    assert r.returncode == 0, r.stderr
    assert "DEVICES 8" in r.stdout


def test_env_propagation_and_script_args(tmp_path):
    script = tmp_path / "probe.py"
    script.write_text(
        "import os, sys\n"
        "print('VAR', os.environ['BF_TEST_VAR'])\n"
        "print('ARGS', sys.argv[1:])\n"
    )
    r = _run_cli(["-x", "BF_TEST_VAR=hello", "--num-processes", "1",
                  str(script), "--lr", "0.1"])
    assert r.returncode == 0, r.stderr
    assert "VAR hello" in r.stdout
    assert "ARGS ['--lr', '0.1']" in r.stdout


def test_bare_env_flag_requires_existing_var(tmp_path):
    script = tmp_path / "probe.py"
    script.write_text("print('ran')\n")
    r = _run_cli(["-x", "BF_DEFINITELY_UNSET_VAR", str(script)])
    assert r.returncode != 0
    assert "not set" in (r.stderr + r.stdout)


def test_timeline_flag_writes_trace(tmp_path):
    script = tmp_path / "probe.py"
    trace = tmp_path / "trace.json"
    script.write_text(
        "from bluefog_tpu.utils import timeline\n"
        "with timeline.timeline_context('launcher_span'):\n"
        "    pass\n"
        "timeline.timeline_stop()\n"
    )
    r = _run_cli(["--simulate", "2", "--timeline", str(trace), str(script)])
    assert r.returncode == 0, r.stderr
    events = json.loads(trace.read_text())
    assert any(e["name"] == "launcher_span" for e in events)


def test_interactive_repl_smoke():
    """ibfrun-tpu (the ibfrun analog) brings the framework up and serves a
    REPL: pipe a command stream in, assert the banner, evaluated output,
    and a clean exit."""
    import subprocess
    import sys

    from tests._util import REPO, clean_env

    code = "print('SIZE', bf.size(), ctx.axis_name)\n"
    env = clean_env()
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    proc = subprocess.run(
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms','cpu')\n"
         "from bluefog_tpu.runtime.launch import interactive_main\n"
         "interactive_main(['--topology', 'ring'])"],
        input=code, capture_output=True, text=True, env=env, cwd=REPO,
        timeout=300)
    assert proc.returncode == 0, proc.stderr[-1000:]
    banner_and_out = proc.stdout + proc.stderr  # code.interact banners -> stderr
    assert "bluefog_tpu interactive" in banner_and_out
    assert "topology=ring" in banner_and_out
    assert "SIZE 8" in proc.stdout
