"""Async-DSGD multi-process test worker (one OS process per rank).

argv: <rank> <nranks> <barrier_dir> <duration_s> <skew_ms> [transport]

Runs one rank of :func:`run_async_dsgd_rank` over a ring: cross-process
``MPI_Put``-style deposits through named-shm windows, NO barrier in the
training loop, deliberately skewed step rates.  Rank 0 audits the returned
report and asserts the two invariants the reference's one-sided path
guarantees (SURVEY §3.4):

1. **mass conservation** — push-sum mass (sum of p) stays exactly the world
   size under arbitrary cross-process interleaving;
2. **convergence under skew** — every rank's de-biased iterate lands near
   the TRUE (plain-mean) optimum of the per-rank quadratics despite the
   rate skew: the push-sum ``p`` weighting is precisely the de-biasing that
   keeps a fast rank from dominating (Nedić & Olshevsky) — observed
   empirically here, with a small consensus gap, while the measured step
   counts confirm the skew really happened.

Prints ASYNC_MP_OK <rank> on success.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""

import numpy as np


def main():
    rank, nranks = int(sys.argv[1]), int(sys.argv[2])
    barrier_dir, duration_s = sys.argv[3], float(sys.argv[4])
    skew_ms = float(sys.argv[5])
    transport = sys.argv[6] if len(sys.argv) > 6 else "shm"

    import jax

    jax.config.update("jax_platforms", "cpu")

    from bluefog_tpu.runtime.async_windows import (FileBarrier,
                                                   run_async_dsgd_rank)
    from bluefog_tpu.topology import RingGraph

    topo = RingGraph(nranks)
    # per-rank quadratic: 0.5*||w - c_r||^2 ; global optimum = mean of c_r,
    # async stationary point = step-rate-weighted mean of c_r
    targets = np.stack([np.full(4, float(r + 1)) for r in range(nranks)])
    params0 = {"w": np.zeros(4, np.float32)}

    def loss_and_grad(r, step, params):
        w = np.asarray(params["w"], np.float64)
        diff = w - targets[r]
        return 0.5 * float(diff @ diff), {"w": diff}

    report = run_async_dsgd_rank(
        topo, rank, params0, loss_and_grad,
        barrier=FileBarrier(barrier_dir, nranks, rank),
        lr=0.05, duration_s=duration_s, skew_s=skew_ms / 1000.0,
        name=f"dsgd_mp_test_{os.path.basename(barrier_dir)}",
        transport=transport, tcp_bind="127.0.0.1")

    if rank == 0:
        assert report is not None
        # 1. mass conservation is EXACT (f64 sums of halving fractions)
        assert abs(report.total_mass - nranks) < 1e-9 * nranks, \
            f"mass leaked: {report.total_mass} != {nranks}"
        # skew really happened: rank 0 (no extra sleep) outstepped the
        # slowest rank, and everyone took real steps
        steps = report.steps_per_rank
        assert min(steps) >= 5, steps
        assert steps[0] > 1.5 * steps[-1], \
            f"no skew observed in step counts {steps}"
        # 2. convergence: near the TRUE mean optimum — the p de-biasing
        # cancels the rate skew (a fast rank holds proportionally less mass,
        # so its extra gradient steps carry proportionally less weight)
        c_mean = targets.mean(0)
        spread = float(np.abs(targets - c_mean).max())
        zs = np.stack([np.asarray(p["w"], np.float64)
                       for p in report.final_params])
        err = float(np.abs(zs - c_mean).max())
        assert err < 0.35 * spread, \
            f"far from mean optimum: err={err}, spread={spread}"
        gap = report.consensus_gap
        assert gap < 0.25 * spread, f"consensus gap {gap} vs spread {spread}"
        # rank 0's LOCAL loss is consistent with an iterate inside the
        # 0.35*spread band already asserted on the parameters (for
        # heterogeneous targets the local loss does NOT go to zero: at
        # exact consensus rank 0 still pays 0.5*||c_mean - c_0||^2, which
        # for n >= 3 equals its cold-start loss — so bound the loss by
        # the quadratic's value over the allowed parameter band instead
        # of pinning it to the consensus point)
        # NOTE the last recorded loss is MID-TRAINING (evaluated before
        # the final drain folds in-flight mass in), and between merges a
        # rank's de-biased iterate legitimately excursions toward its own
        # local optimum — so the band uses a wider deviation than the
        # 0.35*spread asserted on the post-drain parameters above
        l0 = report.losses[0]
        dist = np.abs(c_mean - targets[0])
        dev = 0.5 * spread
        lo = 0.5 * float((np.maximum(dist - dev, 0.0) ** 2).sum())
        hi = 0.5 * float(((dist + dev) ** 2).sum())
        assert lo <= l0[-1] <= hi, (l0[-1], lo, hi)

    print(f"ASYNC_MP_OK {rank}", flush=True)


if __name__ == "__main__":
    main()
