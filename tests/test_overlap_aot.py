"""Comm/compute overlap as a REGRESSION TEST, via AOT TPU compilation.

The overlap contract (reference SURVEY.md §3.3: gossip rides under
backprop) is checkable without hardware: the PJRT topology API compiles for
a v5e:2x4 slice offline, and the scheduled HLO shows whether compute sits
inside the async collective windows.  Skips cleanly when libtpu / the
topology API is unavailable.

Marked ``slow``: loading the AOT TPU topology costs ~8 minutes of fixture
setup in this container — more than half the tier-1 870s budget for one
module — so the budgeted run (``-m 'not slow'``) excludes it and the full
suite (plain ``pytest``) keeps it.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bluefog_tpu.utils.inspect import collective_overlap_report

pytestmark = pytest.mark.slow


def test_gossip_step_overlaps_in_compiled_tpu_schedule(tpu_aot_topology):
    # (benchmarks/overlap_report.py compiles the same harness shape with a
    # heavier model for the published numbers; this test stays small so the
    # suite remains fast)
    topo = tpu_aot_topology
    n = len(topo.devices)  # single source for every shape below
    mesh = Mesh(np.array(topo.devices), ("bf",))

    from bluefog_tpu.models import LeNet5
    from bluefog_tpu.optim import DistributedNeighborAllreduceOptimizer
    from bluefog_tpu.parallel.api import shard_map
    from bluefog_tpu.topology import ExponentialTwoGraph
    from bluefog_tpu.topology.schedule import build_schedule

    model = LeNet5(num_classes=10)
    sched = build_schedule(ExponentialTwoGraph(n))
    opt = DistributedNeighborAllreduceOptimizer(
        optax.sgd(0.1), topology=sched, axis_name="bf")

    def step(p_blk, x_blk, y_blk):
        p = jax.tree_util.tree_map(lambda t: t[0], p_blk)
        st = opt.init(p)

        def loss_fn(p):
            return optax.softmax_cross_entropy_with_integer_labels(
                model.apply(p, x_blk[0]), y_blk[0]).mean()

        loss, g = jax.value_and_grad(loss_fn)(p)
        upd, st = opt.update(g, st, p)
        p = optax.apply_updates(p, upd)
        return jax.tree_util.tree_map(lambda t: t[None], p), loss[None]

    fn = jax.jit(shard_map(
        step, mesh=mesh, in_specs=(P("bf"),) * 3,
        out_specs=(P("bf"), P("bf")), check_vma=False))

    batch = 8
    params = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((batch, 28, 28, 1))),
        jax.random.PRNGKey(0))

    def stacked(t):
        return jax.ShapeDtypeStruct((n,) + t.shape, t.dtype,
                                    sharding=NamedSharding(mesh, P("bf")))

    args = (
        jax.tree_util.tree_map(stacked, params),
        jax.ShapeDtypeStruct((n, batch, 28, 28, 1), jnp.float32,
                             sharding=NamedSharding(mesh, P("bf"))),
        jax.ShapeDtypeStruct((n, batch), jnp.int32,
                             sharding=NamedSharding(mesh, P("bf"))),
    )
    rep = collective_overlap_report(fn, *args)
    # the fused gossip emits async start/done pairs...
    assert rep["pairs"] > 0, rep
    # ...and the latency-hiding scheduler puts real compute inside windows
    assert rep["overlapped_fraction"] > 0, rep
