"""Pipeline-parallel tests: the GPipe scan pipeline must match the sequential
layer stack exactly — forward and backward — and compose with tp on a 2-level
mesh.  (No reference counterpart; SURVEY.md §2.3: PP absent upstream.)"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from bluefog_tpu.parallel.api import shard_map
from bluefog_tpu.parallel.pipeline import pipeline_apply, stack_stage_params
from bluefog_tpu.parallel.tensor import make_hybrid_mesh

D = 16
L = 8          # layers
PP = 4         # stages
MICRO = 6      # microbatches
MB = 4         # micro batch size


def make_layers(key):
    kw, kb = jax.random.split(key)
    return {
        "w": jax.random.normal(kw, (L, D, D)) / np.sqrt(D),
        "b": 0.01 * jax.random.normal(kb, (L, D)),
    }


def apply_layer(w, b, x):
    return jnp.tanh(x @ w + b)


def sequential_ref(layers, xs):
    """(MICRO, MB, D) through all L layers in order."""
    def one(x):
        for i in range(L):
            x = apply_layer(layers["w"][i], layers["b"][i], x)
        return x
    return jax.vmap(one)(xs)


def stage_fn(stage_params, x):
    def body(x, wb):
        return apply_layer(wb[0], wb[1], x), None
    out, _ = lax.scan(body, x, (stage_params["w"], stage_params["b"]))
    return out


def test_stack_stage_params_shapes():
    layers = make_layers(jax.random.PRNGKey(0))
    staged = stack_stage_params(layers, PP)
    assert staged["w"].shape == (PP, L // PP, D, D)
    assert staged["b"].shape == (PP, L // PP, D)
    with pytest.raises(ValueError):
        stack_stage_params(layers, 3)


def test_pipeline_forward_matches_sequential(devices8):
    mesh = make_hybrid_mesh({"pp": PP}, devices=devices8[:PP])
    layers = make_layers(jax.random.PRNGKey(0))
    staged = stack_stage_params(layers, PP)
    xs = jax.random.normal(jax.random.PRNGKey(1), (MICRO, MB, D))
    ref = sequential_ref(layers, xs)

    def body(staged_local, xs):
        sp = jax.tree_util.tree_map(lambda t: t[0], staged_local)
        out = pipeline_apply(stage_fn, sp, xs, pp_axis="pp", num_stages=PP)
        # broadcast the last stage's (only valid) output to every stage
        last = lax.axis_index("pp") == PP - 1
        return lax.psum(jnp.where(last, out, 0.0), "pp")

    out = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P("pp"), P()), out_specs=P(),
        check_vma=False))(staged, xs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_grads_match_sequential(devices8):
    mesh = make_hybrid_mesh({"pp": PP}, devices=devices8[:PP])
    layers = make_layers(jax.random.PRNGKey(0))
    staged = stack_stage_params(layers, PP)
    xs = jax.random.normal(jax.random.PRNGKey(1), (MICRO, MB, D))
    tgt = jax.random.normal(jax.random.PRNGKey(2), (MICRO, MB, D))

    def ref_loss(layers):
        return jnp.mean((sequential_ref(layers, xs) - tgt) ** 2)

    gref = jax.grad(ref_loss)(layers)
    gref_staged = stack_stage_params(gref, PP)

    def body(staged_local, xs):
        sp = jax.tree_util.tree_map(lambda t: t[0], staged_local)

        def loss_fn(sp):
            out = pipeline_apply(stage_fn, sp, xs, pp_axis="pp",
                                 num_stages=PP)
            # masked LOCAL loss — do NOT psum inside the differentiated
            # function (its transpose would scale every grad by pp)
            last = lax.axis_index("pp") == PP - 1
            return jnp.sum(jnp.where(last, (out - tgt) ** 2, 0.0)) / tgt.size

        loss, g = jax.value_and_grad(loss_fn)(sp)
        loss = lax.psum(loss, "pp")  # reporting only
        return (loss[None], jax.tree_util.tree_map(lambda t: t[None], g))

    loss, g = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P("pp"), P()),
        out_specs=(P("pp"), P("pp")), check_vma=False))(staged, xs)

    ref_loss_val = float(ref_loss(layers))
    np.testing.assert_allclose(np.asarray(loss), ref_loss_val, rtol=1e-5)
    for name in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g[name]),
                                   np.asarray(gref_staged[name]), atol=1e-5,
                                   err_msg=f"grad mismatch for {name}")


def test_pipeline_with_tp_inner_axis(devices8):
    """pp=4 outer x tp=2 inner: the stage matmul sharded column-wise over tp
    with a gather; forward still matches sequential."""
    mesh = make_hybrid_mesh({"pp": PP, "tp": 2}, devices=devices8)
    layers = make_layers(jax.random.PRNGKey(0))
    staged = stack_stage_params(layers, PP)
    xs = jax.random.normal(jax.random.PRNGKey(1), (MICRO, MB, D))
    ref = sequential_ref(layers, xs)

    def tp_stage_fn(sp, x):
        # column-shard each layer's W over tp, all_gather the outputs
        def body(x, wb):
            w, b = wb
            i = lax.axis_index("tp")
            wl = lax.dynamic_slice_in_dim(w, i * (D // 2), D // 2, axis=1)
            y = lax.all_gather(x @ wl, "tp", axis=x.ndim - 1, tiled=True)
            return jnp.tanh(y + b), None
        out, _ = lax.scan(body, x, (sp["w"], sp["b"]))
        return out

    def body(staged_local, xs):
        sp = jax.tree_util.tree_map(lambda t: t[0], staged_local)
        out = pipeline_apply(tp_stage_fn, sp, xs, pp_axis="pp", num_stages=PP)
        last = lax.axis_index("pp") == PP - 1
        # psum over 'pp' only: tp ranks hold identical replicas already
        return lax.psum(jnp.where(last, out, 0.0), "pp")

    out = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P("pp"), P()), out_specs=P(),
        check_vma=False))(staged, xs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
