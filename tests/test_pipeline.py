"""Pipeline-parallel tests: the GPipe scan pipeline must match the sequential
layer stack exactly — forward and backward — and compose with tp on a 2-level
mesh.  (No reference counterpart; SURVEY.md §2.3: PP absent upstream.)"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from bluefog_tpu.parallel.api import shard_map
from bluefog_tpu.parallel.pipeline import pipeline_apply, stack_stage_params
from bluefog_tpu.parallel.tensor import make_hybrid_mesh

D = 16
L = 8          # layers
PP = 4         # stages
MICRO = 6      # microbatches
MB = 4         # micro batch size


def make_layers(key):
    kw, kb = jax.random.split(key)
    return {
        "w": jax.random.normal(kw, (L, D, D)) / np.sqrt(D),
        "b": 0.01 * jax.random.normal(kb, (L, D)),
    }


def apply_layer(w, b, x):
    return jnp.tanh(x @ w + b)


def sequential_ref(layers, xs):
    """(MICRO, MB, D) through all L layers in order."""
    def one(x):
        for i in range(L):
            x = apply_layer(layers["w"][i], layers["b"][i], x)
        return x
    return jax.vmap(one)(xs)


def stage_fn(stage_params, x):
    def body(x, wb):
        return apply_layer(wb[0], wb[1], x), None
    out, _ = lax.scan(body, x, (stage_params["w"], stage_params["b"]))
    return out


def test_stack_stage_params_shapes():
    layers = make_layers(jax.random.PRNGKey(0))
    staged = stack_stage_params(layers, PP)
    assert staged["w"].shape == (PP, L // PP, D, D)
    assert staged["b"].shape == (PP, L // PP, D)
    with pytest.raises(ValueError):
        stack_stage_params(layers, 3)


def test_pipeline_forward_matches_sequential(devices8):
    mesh = make_hybrid_mesh({"pp": PP}, devices=devices8[:PP])
    layers = make_layers(jax.random.PRNGKey(0))
    staged = stack_stage_params(layers, PP)
    xs = jax.random.normal(jax.random.PRNGKey(1), (MICRO, MB, D))
    ref = sequential_ref(layers, xs)

    def body(staged_local, xs):
        sp = jax.tree_util.tree_map(lambda t: t[0], staged_local)
        out = pipeline_apply(stage_fn, sp, xs, pp_axis="pp", num_stages=PP)
        # broadcast the last stage's (only valid) output to every stage
        last = lax.axis_index("pp") == PP - 1
        return lax.psum(jnp.where(last, out, 0.0), "pp")

    out = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P("pp"), P()), out_specs=P(),
        check_vma=False))(staged, xs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_grads_match_sequential(devices8):
    mesh = make_hybrid_mesh({"pp": PP}, devices=devices8[:PP])
    layers = make_layers(jax.random.PRNGKey(0))
    staged = stack_stage_params(layers, PP)
    xs = jax.random.normal(jax.random.PRNGKey(1), (MICRO, MB, D))
    tgt = jax.random.normal(jax.random.PRNGKey(2), (MICRO, MB, D))

    def ref_loss(layers):
        return jnp.mean((sequential_ref(layers, xs) - tgt) ** 2)

    gref = jax.grad(ref_loss)(layers)
    gref_staged = stack_stage_params(gref, PP)

    def body(staged_local, xs):
        sp = jax.tree_util.tree_map(lambda t: t[0], staged_local)

        def loss_fn(sp):
            out = pipeline_apply(stage_fn, sp, xs, pp_axis="pp",
                                 num_stages=PP)
            # masked LOCAL loss — do NOT psum inside the differentiated
            # function (its transpose would scale every grad by pp)
            last = lax.axis_index("pp") == PP - 1
            return jnp.sum(jnp.where(last, (out - tgt) ** 2, 0.0)) / tgt.size

        loss, g = jax.value_and_grad(loss_fn)(sp)
        loss = lax.psum(loss, "pp")  # reporting only
        return (loss[None], jax.tree_util.tree_map(lambda t: t[None], g))

    loss, g = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P("pp"), P()),
        out_specs=(P("pp"), P("pp")), check_vma=False))(staged, xs)

    ref_loss_val = float(ref_loss(layers))
    np.testing.assert_allclose(np.asarray(loss), ref_loss_val, rtol=1e-5)
    for name in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g[name]),
                                   np.asarray(gref_staged[name]), atol=1e-5,
                                   err_msg=f"grad mismatch for {name}")


def test_pipeline_with_tp_inner_axis(devices8):
    """pp=4 outer x tp=2 inner: the stage matmul sharded column-wise over tp
    with a gather; forward still matches sequential."""
    mesh = make_hybrid_mesh({"pp": PP, "tp": 2}, devices=devices8)
    layers = make_layers(jax.random.PRNGKey(0))
    staged = stack_stage_params(layers, PP)
    xs = jax.random.normal(jax.random.PRNGKey(1), (MICRO, MB, D))
    ref = sequential_ref(layers, xs)

    def tp_stage_fn(sp, x):
        # column-shard each layer's W over tp, all_gather the outputs
        def body(x, wb):
            w, b = wb
            i = lax.axis_index("tp")
            wl = lax.dynamic_slice_in_dim(w, i * (D // 2), D // 2, axis=1)
            y = lax.all_gather(x @ wl, "tp", axis=x.ndim - 1, tiled=True)
            return jnp.tanh(y + b), None
        out, _ = lax.scan(body, x, (sp["w"], sp["b"]))
        return out

    def body(staged_local, xs):
        sp = jax.tree_util.tree_map(lambda t: t[0], staged_local)
        out = pipeline_apply(tp_stage_fn, sp, xs, pp_axis="pp", num_stages=PP)
        last = lax.axis_index("pp") == PP - 1
        # psum over 'pp' only: tp ranks hold identical replicas already
        return lax.psum(jnp.where(last, out, 0.0), "pp")

    out = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P("pp"), P()), out_specs=P(),
        check_vma=False))(staged, xs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


# ---------------------------------------------------------------------------
# 1F1B schedule (round-5): same math as GPipe, S-deep stash instead of M
# ---------------------------------------------------------------------------

from bluefog_tpu.parallel.pipeline import (  # noqa: E402
    pipeline_train_step_1f1b,
    pipeline_train_step_gpipe,
)


def _staged_grad_ref(layers, xs, tgt):
    """Sequential autodiff reference, regrouped per stage."""
    def ref_loss(layers):
        out = sequential_ref(layers, xs)
        return jnp.sum((out - tgt) ** 2)
    loss, g = jax.value_and_grad(ref_loss)(layers)
    return float(loss), stack_stage_params(g, PP)


def _sq_loss(head_params, y, t):
    del head_params
    return jnp.sum((y - t) ** 2)


@pytest.mark.parametrize("schedule", ["1f1b", "gpipe", "gpipe_remat"])
def test_train_step_grads_match_sequential(devices8, schedule):
    """Both pipeline training schedules must reproduce the sequential
    model's loss and per-stage gradients exactly (f32 tolerance)."""
    mesh = make_hybrid_mesh({"pp": PP}, devices=devices8[:PP])
    layers = make_layers(jax.random.PRNGKey(0))
    staged = stack_stage_params(layers, PP)
    xs = jax.random.normal(jax.random.PRNGKey(1), (MICRO, MB, D))
    tgt = jax.random.normal(jax.random.PRNGKey(2), (MICRO, MB, D))
    ref_loss, gref = _staged_grad_ref(layers, xs, tgt)

    step = (pipeline_train_step_1f1b if schedule == "1f1b"
            else pipeline_train_step_gpipe)
    kw = {"remat": True} if schedule == "gpipe_remat" else {}

    def body(staged_local, xs):
        sp = jax.tree_util.tree_map(lambda t: t[0], staged_local)
        loss, g, _, _ = step(stage_fn, sp, xs, tgt, _sq_loss,
                             pp_axis="pp", num_stages=PP, **kw)
        loss = lax.psum(loss, "pp")  # nonzero on last stage only
        return loss[None], jax.tree_util.tree_map(lambda t: t[None], g)

    loss, g = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P("pp"), P()),
        out_specs=(P("pp"), P("pp")), check_vma=False))(staged, xs)

    np.testing.assert_allclose(np.asarray(loss), ref_loss, rtol=1e-5)
    for name in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(g[name]), np.asarray(gref[name]), atol=1e-4,
            err_msg=f"{schedule} grad mismatch for {name}")


def test_1f1b_matches_gpipe_bitwise_shapes(devices8):
    """The two schedules agree with each other on loss + grads + input
    cotangents (embed-chaining contract) for M not a multiple of S."""
    M = 7  # exercises uneven drain
    mesh = make_hybrid_mesh({"pp": PP}, devices=devices8[:PP])
    layers = make_layers(jax.random.PRNGKey(3))
    staged = stack_stage_params(layers, PP)
    xs = jax.random.normal(jax.random.PRNGKey(4), (M, MB, D))
    tgt = jax.random.normal(jax.random.PRNGKey(5), (M, MB, D))

    def run(step, **kw):
        def body(staged_local, xs):
            sp = jax.tree_util.tree_map(lambda t: t[0], staged_local)
            loss, g, _, dxs = step(stage_fn, sp, xs, tgt, _sq_loss,
                                   pp_axis="pp", num_stages=PP,
                                   collect_input_grads=True, **kw)
            first = lax.axis_index("pp") == 0
            dxs = lax.psum(jnp.where(first, dxs, 0.0), "pp")
            return (lax.psum(loss, "pp")[None],
                    jax.tree_util.tree_map(lambda t: t[None], g), dxs)
        return jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P("pp"), P()),
            out_specs=(P("pp"), P("pp"), P()), check_vma=False))(staged, xs)

    l1, g1, dx1 = run(pipeline_train_step_1f1b)
    l2, g2, dx2 = run(pipeline_train_step_gpipe)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), rtol=1e-6)
    for name in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g1[name]),
                                   np.asarray(g2[name]), atol=1e-5)
    np.testing.assert_allclose(np.asarray(dx1), np.asarray(dx2), atol=1e-5)


def test_1f1b_embed_and_head_stages(devices8):
    """Non-shape-preserving rim stages: int tokens -> embedding outside the
    pipeline (backward chained through input_grads) and a projection head
    inside loss_fn (its grads accumulated by the step).  Must match the
    sequential embed->layers->head model's autodiff end-to-end."""
    V, M = 11, 6
    mesh = make_hybrid_mesh({"pp": PP}, devices=devices8[:PP])
    layers = make_layers(jax.random.PRNGKey(6))
    staged = stack_stage_params(layers, PP)
    emb = jax.random.normal(jax.random.PRNGKey(7), (V, D)) / np.sqrt(D)
    head = {"w": jax.random.normal(jax.random.PRNGKey(8), (D, V)) / np.sqrt(D)}
    toks = jax.random.randint(jax.random.PRNGKey(9), (M, MB), 0, V)
    tgt = jax.random.randint(jax.random.PRNGKey(10), (M, MB), 0, V)

    def head_loss(head_params, y, t):
        logits = y @ head_params["w"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.sum(jnp.take_along_axis(logp, t[..., None], -1))

    def ref_loss(emb, layers, head):
        x = emb[toks]
        out = sequential_ref(layers, x)
        return sum(head_loss(head, out[m], tgt[m]) for m in range(M))

    rl, (ge_ref, gl_ref, gh_ref) = jax.value_and_grad(
        ref_loss, argnums=(0, 1, 2))(emb, layers, head)
    gl_ref = stack_stage_params(gl_ref, PP)

    def body(staged_local, emb, head, toks):
        sp = jax.tree_util.tree_map(lambda t: t[0], staged_local)
        xs = emb[toks]  # embed at the rim, on every stage (replicated)
        loss, g, gh, dxs = pipeline_train_step_1f1b(
            stage_fn, sp, xs, tgt, head_loss, pp_axis="pp", num_stages=PP,
            head_params=head, collect_input_grads=True)
        # chain the input cotangents through the embedding's backward
        first = lax.axis_index("pp") == 0
        dxs = lax.psum(jnp.where(first, dxs, 0.0), "pp")
        _, emb_vjp = jax.vjp(lambda e: e[toks], emb)
        (ge,) = emb_vjp(dxs)
        return (lax.psum(loss, "pp")[None],
                jax.tree_util.tree_map(lambda t: t[None], g),
                lax.psum(gh["w"], "pp"), ge)

    loss, g, gh, ge = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P("pp"), P(), P(), P()),
        out_specs=(P("pp"), P("pp"), P(), P()), check_vma=False))(
            staged, emb, head, toks)

    np.testing.assert_allclose(np.asarray(loss), float(rl), rtol=1e-5)
    for name in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g[name]),
                                   np.asarray(gl_ref[name]), atol=1e-4)
    np.testing.assert_allclose(np.asarray(gh), np.asarray(gh_ref["w"]),
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(ge), np.asarray(ge_ref), atol=1e-4)
