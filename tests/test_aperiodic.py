"""Aperiodic (per-call arbitrary edge set) dynamic topology gossip.

The reference changes the topology per call via ``src_weights=`` with no
recompilation concern (eager MPI); the XLA answer is
``neighbor_allreduce_aperiodic``: circulant-rotation decomposition with the
mixing matrix as *data* (SURVEY.md §7 hard-part #2).  Tests assert

1. closed-form correctness ``out == W @ xs`` for random irregular matrices,
2. **one compile** across many different edge sets (the core requirement),
3. the jittable one-peer exp2 matrix builder matches the schedule variant,
4. the optimizer integration (callable topology) trains without retracing.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from bluefog_tpu.parallel.api import shard_map

from bluefog_tpu.ops.collectives import neighbor_allreduce_aperiodic
from bluefog_tpu.optim import DistributedNeighborAllreduceOptimizer
from bluefog_tpu.topology.dynamic import (
    one_peer_exp2_mixing_matrix,
    one_peer_exponential_two_schedules,
)
from bluefog_tpu.topology.schedule import build_schedule

N = 8


def _mesh():
    return Mesh(np.array(jax.devices()[:N]), ("bf",))


def _random_mixing_matrix(rng, n=N, max_degree=3):
    """Row-stochastic W with a random edge set of random in-degrees."""
    w = np.zeros((n, n))
    for i in range(n):
        deg = rng.integers(0, max_degree + 1)
        nbrs = rng.choice([j for j in range(n) if j != i],
                          size=deg, replace=False)
        weights = rng.random(deg + 1) + 0.1
        weights /= weights.sum()
        w[i, i] = weights[0]
        for j, wt in zip(nbrs, weights[1:]):
            w[i, j] = wt
    return w


@pytest.fixture
def gossip_fn():
    mesh = _mesh()
    traces = {"count": 0}

    def fn(xs, w):
        traces["count"] += 1
        return neighbor_allreduce_aperiodic(xs, w, "bf")

    jitted = jax.jit(shard_map(
        fn, mesh=mesh, in_specs=(P("bf"), P()), out_specs=P("bf"),
        check_vma=False,
    ))
    return jitted, traces


def test_matches_dense_oracle_many_edge_sets_one_compile(gossip_fn):
    jitted, traces = gossip_fn
    rng = np.random.default_rng(0)
    xs = rng.standard_normal((N, 5, 3)).astype(np.float32)
    for _ in range(6):
        w = _random_mixing_matrix(rng)
        got = jitted(jnp.asarray(xs), jnp.asarray(w, jnp.float32))
        want = np.einsum("ij,jkl->ikl", w, xs)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5,
                                   atol=1e-5)
    assert traces["count"] == 1, (
        f"aperiodic gossip retraced {traces['count']}x across changing edge "
        "sets; the edge set must be data, not program")


def test_pytree_and_dtypes(gossip_fn):
    jitted, _ = gossip_fn
    rng = np.random.default_rng(1)
    w = _random_mixing_matrix(rng)
    tree = {
        "a": rng.standard_normal((N, 4)).astype(np.float32),
        "b": rng.standard_normal((N, 2, 2)).astype(np.float32),
    }
    got = jitted({k: jnp.asarray(v) for k, v in tree.items()},
                 jnp.asarray(w, jnp.float32))
    for key in tree:
        want = np.einsum("ij,j...->i...", w, tree[key])
        np.testing.assert_allclose(np.asarray(got[key]), want, rtol=1e-5,
                                   atol=1e-5)


def test_bf16_accumulates_in_f32(gossip_fn):
    jitted, _ = gossip_fn
    rng = np.random.default_rng(2)
    w = _random_mixing_matrix(rng)
    xs = rng.standard_normal((N, 16)).astype(np.float32)
    got = jitted(jnp.asarray(xs, jnp.bfloat16), jnp.asarray(w, jnp.float32))
    assert got.dtype == jnp.bfloat16
    want = np.einsum("ij,jk->ik", w, xs)
    np.testing.assert_allclose(np.asarray(got, np.float32), want, rtol=0.05,
                               atol=0.05)


def test_one_peer_exp2_matrix_matches_schedules():
    """The jittable matrix builder reproduces the precompiled schedule
    period exactly (same weights, same edges, for every phase)."""
    topos = one_peer_exponential_two_schedules(N)
    for step in range(2 * len(topos)):
        w = np.asarray(one_peer_exp2_mixing_matrix(N, step))
        want = topos[step % len(topos)].weights
        np.testing.assert_allclose(w, want, atol=1e-7)


def test_one_peer_exp2_matrix_traced_step():
    f = jax.jit(lambda s: one_peer_exp2_mixing_matrix(N, s))
    for step in range(4):
        np.testing.assert_allclose(
            np.asarray(f(step)),
            np.asarray(one_peer_exp2_mixing_matrix(N, step)), atol=1e-7)


class TestDegreeCapped:
    """max_rotations=D: runtime-shift rotation slots (D * ceil(log2 n)
    ppermutes) instead of the full n-1 decomposition — the program-size
    answer for pod-scale meshes (VERDICT r3 weak #3)."""

    def _jit(self, cap):
        mesh = _mesh()
        return jax.jit(shard_map(
            lambda xs, w: neighbor_allreduce_aperiodic(
                xs, w, "bf", max_rotations=cap),
            mesh=mesh, in_specs=(P("bf"), P()), out_specs=P("bf"),
            check_vma=False))

    def test_matches_oracle_within_cap(self):
        jitted = self._jit(3)
        rng = np.random.default_rng(7)
        xs = rng.standard_normal((N, 5)).astype(np.float32)
        for _ in range(4):
            # <= 3 distinct nonzero shifts --> <= 3 active rotations
            w = np.zeros((N, N))
            shifts = rng.choice(range(1, N), size=3, replace=False)
            for i in range(N):
                w[i, i] = 0.4
                for s in shifts:
                    w[i, (i - s) % N] = 0.2
            got = jitted(jnp.asarray(xs), jnp.asarray(w, jnp.float32))
            want = w @ xs
            np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5,
                                       atol=1e-5)

    def test_one_peer_needs_one_slot(self):
        jitted = self._jit(1)
        xs = np.random.default_rng(8).standard_normal((N, 4)).astype(
            np.float32)
        for step in range(4):
            w = np.asarray(one_peer_exp2_mixing_matrix(N, step))
            got = jitted(jnp.asarray(xs), jnp.asarray(w, jnp.float32))
            np.testing.assert_allclose(np.asarray(got), w @ xs, rtol=1e-5,
                                       atol=1e-5)

    def test_cap_overflow_poisons_with_nan(self):
        """More active rotations than slots must be LOUD (NaN), never a
        silently dropped edge."""
        jitted = self._jit(2)
        xs = np.ones((N, 3), np.float32)
        w = np.full((N, N), 1.0 / N)  # full graph: n-1 active rotations
        got = np.asarray(jitted(jnp.asarray(xs), jnp.asarray(w, jnp.float32)))
        assert np.isnan(got).all()

    def test_fuzz_cap_vs_full_and_overflow(self):
        """Randomized: for random circulant-sparse W, capped == full when
        the cap covers the active rotations, NaN-poisoned when it cannot."""
        rng = np.random.default_rng(11)
        xs = rng.standard_normal((N, 4)).astype(np.float32)
        jit_cache = {}

        def run(cap):
            if cap not in jit_cache:
                jit_cache[cap] = self._jit(cap)
            return jit_cache[cap]

        for trial in range(8):
            n_active = int(rng.integers(1, 5))
            shifts = rng.choice(range(1, N), size=n_active, replace=False)
            w = np.zeros((N, N))
            for i in range(N):
                w[i, i] = 0.5
                for s in shifts:
                    w[i, (i - s) % N] = 0.5 / n_active
            got = run(4)(jnp.asarray(xs), jnp.asarray(w, jnp.float32))
            np.testing.assert_allclose(np.asarray(got), w @ xs, rtol=1e-5,
                                       atol=1e-5, err_msg=f"trial {trial}")
            if n_active > 1:
                under = run(n_active - 1)(jnp.asarray(xs),
                                          jnp.asarray(w, jnp.float32))
                assert np.isnan(np.asarray(under)).all(), (
                    f"trial {trial}: cap {n_active - 1} < {n_active} active "
                    "rotations must poison, not drop edges")

    def test_compile_census_n64(self):
        """Program-size census at n=64 (pod-scale proxy): the capped
        program must contain an order-of-magnitude fewer collective
        permutes than the full decomposition's 63.  Lowering census runs
        on an ABSTRACT 64-device mesh (no need for 64 real devices;
        constructed through the version-portable compat helper — the
        installed jax's AbstractMesh takes a (name, size) shape tuple)."""
        from bluefog_tpu.parallel.api import abstract_mesh

        n = 64
        mesh64 = abstract_mesh((n,), ("bf",))

        def lower(cap):
            fn = jax.jit(shard_map(
                lambda xs, w: neighbor_allreduce_aperiodic(
                    xs, w, "bf", max_rotations=cap),
                mesh=mesh64, in_specs=(P("bf"), P()), out_specs=P("bf"),
                check_vma=False))
            return fn.lower(
                jax.ShapeDtypeStruct((n, 8), jnp.float32),
                jax.ShapeDtypeStruct((n, n), jnp.float32)).as_text()

        full = lower(None)
        capped = lower(3)
        count_full = full.count("collective_permute")
        count_capped = capped.count("collective_permute")
        # full: one per rotation (63); capped: 3 slots x ceil(log2 64) = 18
        assert count_full >= n - 1, count_full
        assert count_capped <= 3 * 6, count_capped
        assert count_capped < count_full / 3
        assert len(capped) < len(full), (len(capped), len(full))


def _optimizer_harness(opt, mesh):
    """(init, jitted_step) over the stacked rank representation for an
    optimizer — shared by the callable-topology tests."""
    init = jax.jit(shard_map(
        lambda q: jax.tree_util.tree_map(
            lambda t: jnp.asarray(t)[None], opt.init(q[0])),
        mesh=mesh, in_specs=(P("bf"),), out_specs=P("bf"), check_vma=False))

    def step_fn(p, st, g):
        upd, st = opt.update(g, st, p)
        return optax.apply_updates(p, upd), st

    jitted = jax.jit(shard_map(
        lambda q, s, g: jax.tree_util.tree_map(
            lambda t: t[None],
            step_fn(q[0], jax.tree_util.tree_map(lambda t: t[0], s), g[0])),
        mesh=mesh, in_specs=(P("bf"),) * 3, out_specs=P("bf"),
        check_vma=False))
    return init, jitted


def test_optimizer_callable_topology_respects_cap():
    """max_rotations reaches the optimizer's aperiodic path: a capped
    one-peer training run is bit-compatible with the uncapped one, and the
    cap is rejected outside the aperiodic mode."""
    mesh = _mesh()

    def run(cap):
        opt = DistributedNeighborAllreduceOptimizer(
            optax.sgd(0.1), topology=functools.partial(
                one_peer_exp2_mixing_matrix, N),
            axis_name="bf", atc=True, max_rotations=cap)
        init, jitted = _optimizer_harness(opt, mesh)
        rng = np.random.default_rng(4)
        p = jnp.asarray(rng.standard_normal((N, 6)), jnp.float32)
        st = init(p)
        for step in range(3):
            g = jnp.asarray(rng.standard_normal((N, 6)), jnp.float32)
            p, st = jitted(p, st, g)
        return np.asarray(p)

    np.testing.assert_allclose(run(1), run(None), rtol=1e-5, atol=1e-6)

    from bluefog_tpu.topology import RingGraph
    with pytest.raises(ValueError, match="callable-topology"):
        DistributedNeighborAllreduceOptimizer(
            optax.sgd(0.1), topology=RingGraph(N), axis_name="bf",
            max_rotations=2)


def test_optimizer_callable_topology_one_compile():
    """DistributedNeighborAllreduceOptimizer(topology=callable) gossips a
    different edge set every step inside ONE compiled train step, and the
    result matches manually applying W to the post-SGD params."""
    mesh = _mesh()
    opt = DistributedNeighborAllreduceOptimizer(
        optax.sgd(0.1), topology=functools.partial(
            one_peer_exp2_mixing_matrix, N),
        axis_name="bf", atc=True)

    def step_fn(p, st, g):
        upd, st = opt.update(g, st, p)
        return optax.apply_updates(p, upd), st

    rng = np.random.default_rng(3)
    p0 = jnp.asarray(rng.standard_normal((N, 6)), jnp.float32)

    init = jax.jit(shard_map(
        lambda p: jax.tree_util.tree_map(
            lambda t: jnp.asarray(t)[None], opt.init(p[0])),
        mesh=mesh, in_specs=(P("bf"),), out_specs=P("bf"), check_vma=False))
    st = init(p0)

    jitted = jax.jit(shard_map(
        lambda p, st, g: jax.tree_util.tree_map(
            lambda t: t[None],
            step_fn(p[0], jax.tree_util.tree_map(lambda t: t[0], st), g[0])),
        mesh=mesh, in_specs=(P("bf"),) * 3, out_specs=P("bf"),
        check_vma=False))

    p, want = p0, np.asarray(p0)
    for step in range(4):
        g = jnp.asarray(rng.standard_normal((N, 6)), jnp.float32)
        p, st = jitted(p, st, g)
        w = np.asarray(one_peer_exp2_mixing_matrix(N, step))
        want = w @ (want - 0.1 * np.asarray(g))  # ATC: W (p + update)
    np.testing.assert_allclose(np.asarray(p), want, rtol=1e-5, atol=1e-5)
