"""Sequence parallelism: ring attention + Ulysses vs full-attention oracle.

Strategy mirrors the framework's test pyramid (SURVEY.md §4): an 8-virtual-
device CPU mesh stands in for the TPU slice, and closed-form/oracle
equivalence is asserted — here the oracle is single-device full attention on
the gathered sequence.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from bluefog_tpu.parallel.api import shard_map  # version-portable check_vma/check_rep

from bluefog_tpu.models.transformer import GPTConfig, TransformerLM
from bluefog_tpu.ops.ring_attention import (
    all_to_all_attention,
    local_attention,
    ring_attention,
    zigzag_shard,
    zigzag_unshard,
)

N = 8
B, T_LOCAL, H, D = 2, 16, 8, 32
T = N * T_LOCAL


def _mesh():
    return Mesh(np.array(jax.devices()[:N]), ("sp",))


def _qkv(seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (B, T, H, D)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


def _sharded(fn):
    """Run fn over sequence-sharded q/k/v, returning the gathered output."""
    mesh = _mesh()
    return jax.jit(shard_map(
        fn, mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"), check_vma=False,
    ))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    q, k, v = _qkv()
    want = local_attention(q, k, v, causal=causal)
    got = _sharded(functools.partial(ring_attention, axis_name="sp",
                                     causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_all_to_all_attention_matches_full(causal):
    q, k, v = _qkv(seed=1)
    want = local_attention(q, k, v, causal=causal)
    got = _sharded(functools.partial(all_to_all_attention, axis_name="sp",
                                     causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_ring_attention_gradients_match_full():
    q, k, v = _qkv(seed=2)

    def loss_full(q, k, v):
        return (local_attention(q, k, v, causal=True) ** 2).sum()

    ring = _sharded(functools.partial(ring_attention, axis_name="sp",
                                      causal=True))

    def loss_ring(q, k, v):
        return (ring(q, k, v) ** 2).sum()

    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_full, g_ring):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_long_sequence_tiled(causal):
    """T = 1024 parity with the inner flash-style tiling engaged: t_local =
    128 with kv_tile = 64 forces the lax.scan tile path (2 tiles per block)
    and, for causal, the lax.switch block-skipping dispatch."""
    b, h, d, t = 1, 2, 16, 1024
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q, k, v = (jax.random.normal(kk, (b, t, h, d)) for kk in ks)
    want = local_attention(q, k, v, causal=causal)
    ring = _sharded(functools.partial(ring_attention, axis_name="sp",
                                      causal=causal, kv_tile=64))
    got = ring(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)

    # gradients flow through the tiled/remat scan and the switch branches
    g_full = jax.grad(lambda a, b_, c: (local_attention(a, b_, c,
                                                        causal=causal) ** 2).sum(),
                      argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(lambda a, b_, c: (ring(a, b_, c) ** 2).sum(),
                      argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_full, g_ring):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=1e-4, atol=1e-4)


def test_zigzag_shard_roundtrip():
    x = jnp.arange(B * T * 3, dtype=jnp.float32).reshape(B, T, 3)
    z = zigzag_shard(x, N)
    assert z.shape == x.shape
    np.testing.assert_array_equal(np.asarray(zigzag_unshard(z, N)),
                                  np.asarray(x))
    # rank 0's shard = chunks 0 and 2N-1 of the global sequence
    c = T // (2 * N)
    np.testing.assert_array_equal(
        np.asarray(z[:, :2 * c]),
        np.asarray(jnp.concatenate([x[:, :c], x[:, (2 * N - 1) * c:]], 1)))


def test_ring_attention_zigzag_causal_matches_full():
    """Load-balanced causal layout: zigzag-shard in, zigzag-unshard out,
    exact parity with the full-attention oracle."""
    q, k, v = _qkv(seed=3)
    want = local_attention(q, k, v, causal=True)
    ring = _sharded(functools.partial(ring_attention, axis_name="sp",
                                     causal=True, layout="zigzag"))
    got = zigzag_unshard(
        ring(zigzag_shard(q, N), zigzag_shard(k, N), zigzag_shard(v, N)), N)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_ring_attention_zigzag_gradients_match_full():
    q, k, v = _qkv(seed=4)

    ring = _sharded(functools.partial(ring_attention, axis_name="sp",
                                      causal=True, layout="zigzag"))

    def loss_ring(q, k, v):
        out = zigzag_unshard(
            ring(zigzag_shard(q, N), zigzag_shard(k, N), zigzag_shard(v, N)),
            N)
        return (out ** 2).sum()

    def loss_full(q, k, v):
        return (local_attention(q, k, v, causal=True) ** 2).sum()

    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_full, g_ring):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=1e-4, atol=1e-4)


def test_ring_attention_zigzag_tiled_long_sequence():
    """Zigzag with the scan-tile inner path engaged (kv_tile < chunk)."""
    b, h, d, t = 1, 2, 16, 1024
    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    q, k, v = (jax.random.normal(kk, (b, t, h, d)) for kk in ks)
    want = local_attention(q, k, v, causal=True)
    ring = _sharded(functools.partial(ring_attention, axis_name="sp",
                                      causal=True, layout="zigzag",
                                      kv_tile=32))
    got = zigzag_unshard(
        ring(zigzag_shard(q, N), zigzag_shard(k, N), zigzag_shard(v, N)), N)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_ring_attention_zigzag_bf16():
    """bf16 inputs through the zigzag ring: f32 online-softmax state keeps
    the result within bf16 tolerance of the f32 oracle."""
    q, k, v = (t.astype(jnp.bfloat16) for t in _qkv(seed=5))
    want = local_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                           v.astype(jnp.float32), causal=True)
    ring = _sharded(functools.partial(ring_attention, axis_name="sp",
                                      causal=True, layout="zigzag"))
    got = zigzag_unshard(
        ring(zigzag_shard(q, N), zigzag_shard(k, N), zigzag_shard(v, N)), N)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want), rtol=0.05, atol=0.05)


def test_ring_attention_bf16_stable():
    q, k, v = _qkv(seed=3, dtype=jnp.bfloat16)
    got = _sharded(functools.partial(ring_attention, axis_name="sp",
                                     causal=True))(q, k, v)
    assert got.dtype == jnp.bfloat16
    assert np.isfinite(np.asarray(got, np.float32)).all()
    want = local_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=0.1, atol=0.1)


def test_head_count_guard():
    mesh = _mesh()
    q = k = v = jnp.zeros((B, T, 4, D))  # 4 heads < 8 devices

    def f(q, k, v):
        return all_to_all_attention(q, k, v, "sp")

    with pytest.raises(ValueError, match="not divisible"):
        shard_map(f, mesh=mesh,
                  in_specs=(P(None, "sp"),) * 3,
                  out_specs=P(None, "sp"),
                  check_vma=False)(q, k, v)


def test_transformer_lm_sequence_parallel_matches_single_device():
    """The model forward with ring attention inside shard_map equals the
    single-device full-sequence forward — long context is a drop-in."""
    cfg = GPTConfig.tiny()
    model = TransformerLM(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (B, T), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(1), tokens)
    want = model.apply(params, tokens)

    mesh = _mesh()

    def fwd(params, tokens):
        t_local = tokens.shape[1]
        offset = jax.lax.axis_index("sp") * t_local
        attn = functools.partial(ring_attention, axis_name="sp", causal=True)
        return model.apply(params, tokens, attn_fn=attn,
                           position_offset=offset)

    got = jax.jit(shard_map(
        fwd, mesh=mesh,
        in_specs=(P(), P(None, "sp")),
        out_specs=P(None, "sp"), check_vma=False,
    ))(params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_transformer_lm_ulysses_matches_single_device():
    cfg = GPTConfig.tiny()  # 4 heads — use a 4-device mesh axis
    model = TransformerLM(cfg)
    n = 4
    tokens = jax.random.randint(jax.random.PRNGKey(0), (B, n * T_LOCAL), 0,
                                cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(1), tokens)
    want = model.apply(params, tokens)

    mesh = Mesh(np.array(jax.devices()[:n]), ("sp",))

    def fwd(params, tokens):
        t_local = tokens.shape[1]
        offset = jax.lax.axis_index("sp") * t_local
        attn = functools.partial(all_to_all_attention, axis_name="sp",
                                 causal=True)
        return model.apply(params, tokens, attn_fn=attn,
                           position_offset=offset)

    got = jax.jit(shard_map(
        fwd, mesh=mesh,
        in_specs=(P(), P(None, "sp")),
        out_specs=P(None, "sp"), check_vma=False,
    ))(params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_flash_block_sizes_clamp():
    """Tile edges must exactly divide T (kernel requirement) and default to
    512 — the edge the on-chip tune measured 3.5-5x faster than the
    library's 128 default (PROFILE.md, flash_attention_bench --tune)."""
    from bluefog_tpu.ops.ring_attention import _flash_block_sizes

    assert _flash_block_sizes(1024).block_q == 512
    assert _flash_block_sizes(4096).block_q == 512
    assert _flash_block_sizes(384).block_q == 128   # 256 does not divide 384
    assert _flash_block_sizes(128).block_q == 128
    assert _flash_block_sizes(4096, 1024).block_q == 1024
    assert _flash_block_sizes(2048, 128).block_q == 128
    for t in (128, 384, 1024, 4096):
        bs = _flash_block_sizes(t)
        assert t % bs.block_q == 0 and t % bs.block_k == 0
        assert bs.block_k <= bs.block_k_major
