"""TFRecord container + tf.Example codec + DistributedLoader integration.

Mirrors the reference test strategy (SURVEY.md §4): closed-form round-trip
assertions over generated on-disk shards, corruption detection, and the
DistributedSampler contract (disjoint rank shards covering every example).
"""

import os
import struct

import numpy as np
import pytest

from bluefog_tpu.data.tfrecord import (
    TFRecordSource,
    TFRecordWriter,
    crc32c,
    decode_example,
    encode_example,
    image_classification_decoder,
    read_records,
    write_image_classification_shards,
)


def test_crc32c_known_vectors():
    # canonical CRC32C test vectors (RFC 3720 / kernel test suite)
    assert crc32c(b"") == 0
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"\x00" * 32) == 0x8A9136AA


def test_crc32c_matches_python_fallback():
    from bluefog_tpu.data import tfrecord as tfr

    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=1000, dtype=np.uint8).tobytes()
    native = crc32c(data)
    table = tfr._py_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = int(table[(crc ^ b) & 0xFF]) ^ (crc >> 8)
    assert native == (crc ^ 0xFFFFFFFF)


def test_record_roundtrip(tmp_path):
    path = str(tmp_path / "a.tfrecord")
    payloads = [b"hello", b"", b"x" * 10_000, b"\x00\xff" * 7]
    with TFRecordWriter(path) as w:
        for p in payloads:
            w.write(p)
    assert list(read_records(path, verify=True)) == payloads


def test_corruption_detected(tmp_path):
    path = str(tmp_path / "bad.tfrecord")
    with TFRecordWriter(path) as w:
        w.write(b"payload-one")
        w.write(b"payload-two")
    data = bytearray(open(path, "rb").read())
    data[-7] ^= 0x40  # flip a bit inside the second payload
    open(path, "wb").write(bytes(data))
    with pytest.raises(ValueError, match="checksum"):
        list(read_records(path, verify=True))
    # verify=False trusts the framing (lengths intact) and still reads
    assert len(list(read_records(path, verify=False))) == 2


def test_truncation_detected(tmp_path):
    path = str(tmp_path / "trunc.tfrecord")
    with TFRecordWriter(path) as w:
        w.write(b"payload-one" * 10)
    data = open(path, "rb").read()
    open(path, "wb").write(data[:-20])
    with pytest.raises(ValueError, match="framing|truncated"):
        list(read_records(path, verify=False))


def test_example_codec_roundtrip():
    features = {
        "image": b"\x01\x02\x03\x04",
        "shape": np.asarray([2, 2, 1], np.int64),
        "label": np.asarray([7], np.int64),
        "weights": np.asarray([0.5, -1.25], np.float32),
        "neg": np.asarray([-3], np.int64),
    }
    got = decode_example(encode_example(features))
    assert got["image"] == [b"\x01\x02\x03\x04"]
    np.testing.assert_array_equal(got["shape"], [2, 2, 1])
    np.testing.assert_array_equal(got["label"], [7])
    np.testing.assert_allclose(got["weights"], [0.5, -1.25])
    np.testing.assert_array_equal(got["neg"], [-3])


def _make_shards(tmp_path, n=48, hw=8, classes=10, shard_size=20):
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, size=(n, hw, hw, 3), dtype=np.uint8)
    labels = rng.integers(0, classes, size=n).astype(np.int64)
    paths = write_image_classification_shards(
        str(tmp_path), images, labels, shard_size=shard_size)
    return images, labels, paths


def test_source_random_access(tmp_path):
    images, labels, paths = _make_shards(tmp_path)
    assert len(paths) == 3  # 48 / 20 -> 20+20+8
    src = TFRecordSource(str(tmp_path / "*.tfrecord"), verify=True)
    assert len(src) == 48
    # arbitrary gather order, across shard boundaries
    idx = np.asarray([47, 0, 20, 19, 21, 5])
    imgs, labs = src[idx]
    np.testing.assert_array_equal(imgs, images[idx])
    np.testing.assert_array_equal(labs, labels[idx])
    assert imgs.dtype == np.uint8


def test_distributed_loader_over_tfrecords(tmp_path, devices8):
    """The DistributedSampler contract holds over on-disk shards: one epoch
    covers every example exactly once, disjointly across ranks."""
    import bluefog_tpu as bf

    images, labels, _ = _make_shards(tmp_path, n=64)
    bf.init()
    from bluefog_tpu.data import DistributedLoader

    src = TFRecordSource(str(tmp_path / "*.tfrecord"))
    loader = DistributedLoader(src, per_rank_batch=2, device_put=True)
    assert loader.steps_per_epoch == 4  # 64 / 8 ranks / 2 per batch

    seen = []
    for ximgs, ylabs in loader.epoch(0):
        assert ximgs.shape == (8, 2, 8, 8, 3)
        assert ylabs.shape == (8, 2)
        seen.append(np.asarray(ximgs).reshape(-1, 8, 8, 3))
    seen = np.concatenate(seen)
    assert len(seen) == 64
    # every on-disk example appears exactly once across ranks and steps
    seen_keys = sorted(map(bytes, seen.reshape(64, -1)))
    want_keys = sorted(map(bytes, images.reshape(64, -1)))
    assert seen_keys == want_keys
