"""The profiler sink-table tooling, end to end on a CPU trace.

PROFILE.md §4's per-op table waits on a live chip, but the TOOLING must
not: jax.profiler traces capture on any backend, so CI proves the whole
path (trace dir discovery → trace.json.gz parse → device-time aggregation
→ table) works before the chip ever answers.
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp

from tests._util import REPO


def test_profile_summary_end_to_end(tmp_path):
    trace_dir = str(tmp_path / "trace")
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((256, 256))
    f(x).block_until_ready()  # compile outside the trace
    with jax.profiler.trace(trace_dir):
        for _ in range(3):
            f(x).block_until_ready()

    proc = subprocess.run(
        [sys.executable, os.path.join("benchmarks", "profile_summary.py"),
         trace_dir],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert proc.returncode == 0, proc.stderr[-1000:]
    # a real table came out: headered rows with durations and percentages
    assert "%" in proc.stdout
    assert any(ln.strip() for ln in proc.stdout.splitlines()[1:]), \
        proc.stdout
