"""The profiler sink-table tooling, end to end on a CPU trace.

PROFILE.md §4's per-op table waits on a live chip, but the TOOLING must
not: jax.profiler traces capture on any backend, so CI proves the whole
path (trace dir discovery → trace.json.gz parse → device-time aggregation
→ table) works before the chip ever answers.
"""

import os
import subprocess
import sys

import pytest

from tests._util import REPO, clean_env


@pytest.mark.duration_budget(90)  # pre-existing heavyweight; tier-1 coverage load-bearing
def test_profile_summary_end_to_end(tmp_path):
    trace_dir = str(tmp_path / "trace")
    # capture in a FRESH process: the pytest process may already hold (or
    # have torn down) a profiler session from other tests, and a second
    # in-process jax.profiler.trace can fail order-dependently
    capture = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "import jax.numpy as jnp\n"
        "f = jax.jit(lambda x: (x @ x).sum())\n"
        "x = jnp.ones((256, 256))\n"
        "f(x).block_until_ready()\n"
        f"with jax.profiler.trace({trace_dir!r}):\n"
        "    for _ in range(3):\n"
        "        f(x).block_until_ready()\n"
    )
    proc = subprocess.run([sys.executable, "-c", capture],
                          capture_output=True, text=True, env=clean_env(),
                          cwd=REPO, timeout=300)
    assert proc.returncode == 0, proc.stderr[-1000:]

    proc = subprocess.run(
        [sys.executable, os.path.join("benchmarks", "profile_summary.py"),
         trace_dir],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert proc.returncode == 0, proc.stderr[-1000:]
    # a real table came out: headered rows with durations and percentages
    assert "%" in proc.stdout
    assert any(ln.strip() for ln in proc.stdout.splitlines()[1:]), \
        proc.stdout
