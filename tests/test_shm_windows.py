"""Cross-process shared-memory windows (csrc/windows.cc shm mode).

The round-3 verdict's one semantic gap vs the reference (missing #1): the
passive-target window table only crossed *threads*.  These tests prove
deposits now cross real OS process boundaries — the ``MPI_Put`` semantic of
upstream ``bluefog/common/mpi_controller.cc`` Win* (SURVEY §3.4) — with
owner-create / peer-attach ordering freedom, stale-segment recovery, and an
end-to-end 2-process skewed asynchronous DSGD run (mass conservation +
convergence asserted inside the workers).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from bluefog_tpu.runtime import native
from bluefog_tpu.runtime.async_windows import (AsyncWindow,
                                               shm_unlink_window)
from tests._util import REPO as _REPO, clean_env as _clean_env, uniq as _uniq

pytestmark = pytest.mark.skipif(
    native.load() is None, reason="native runtime unavailable (shm windows "
    "require process-shared pthread mutexes)")


def _run(code: str, timeout=120) -> subprocess.CompletedProcess:
    return subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, env=_clean_env(), cwd=_REPO,
                          timeout=timeout)


def test_deposit_crosses_process_boundary():
    """A subprocess attaches this process's window and deposits; the owner
    observes the payload with NO participation in the transfer."""
    name = _uniq("shm_basic")
    win = AsyncWindow(name, n_slots=2, n_elems=5, dtype=np.float64, shm=True)
    try:
        payload = np.arange(5, dtype=np.float64) + 0.25
        code = (
            "import os\n"
            "os.environ['JAX_PLATFORMS']='cpu'\n"
            "os.environ['PALLAS_AXON_POOL_IPS']=''\n"
            "import numpy as np\n"
            "from bluefog_tpu.runtime.async_windows import AsyncWindow\n"
            f"w = AsyncWindow({name!r}, attach=True)\n"
            "assert w.n_slots == 2 and w.n_elems == 5, (w.n_slots, w.n_elems)\n"
            "assert w.dtype == np.float64\n"
            "p = np.arange(5, dtype=np.float64) + 0.25\n"
            "w.deposit(1, p, accumulate=True)\n"
            "w.deposit(1, p, accumulate=True)\n"  # accumulates: 2x payload
            "w.deposit(0, 10 * p, accumulate=False)\n"  # put: replaces
            "w.free()\n"
            "print('DEPOSITED')\n"
        )
        out = _run(code)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "DEPOSITED" in out.stdout

        buf, fresh = win.read(1, consume=True)
        assert fresh == 2
        np.testing.assert_allclose(buf, 2 * payload)
        buf, fresh = win.read(0, consume=False)
        assert fresh == 1
        np.testing.assert_allclose(buf, 10 * payload)
        # consume-exactly-once: slot 1 was zero-filled by the consuming read
        buf, fresh = win.read(1, consume=False)
        assert fresh == 0
        np.testing.assert_allclose(buf, 0.0)
    finally:
        win.free()


def test_self_buffer_visible_across_processes():
    """set_self in the subprocess; read_self here (passive win_get)."""
    name = _uniq("shm_self")
    win = AsyncWindow(name, n_slots=1, n_elems=3, dtype=np.float32, shm=True)
    try:
        code = (
            "import os\n"
            "os.environ['JAX_PLATFORMS']='cpu'\n"
            "os.environ['PALLAS_AXON_POOL_IPS']=''\n"
            "import numpy as np\n"
            "from bluefog_tpu.runtime.async_windows import AsyncWindow\n"
            f"w = AsyncWindow({name!r}, attach=True)\n"
            "w.set_self(np.array([7, 8, 9], np.float32))\n"
            "w.free()\n"
        )
        out = _run(code)
        assert out.returncode == 0, out.stdout + out.stderr
        np.testing.assert_allclose(win.read_self(), [7.0, 8.0, 9.0])
    finally:
        win.free()


def test_concurrent_cross_process_accumulates_never_lose_updates():
    """Two writer PROCESSES hammer the same slot with accumulates; the
    process-shared mutex must serialize the read-modify-writes exactly
    (no lost update, no torn sum) — the MPI_Accumulate atomicity contract."""
    name = _uniq("shm_race")
    reps = 300
    win = AsyncWindow(name, n_slots=1, n_elems=8, dtype=np.float64, shm=True)
    try:
        code = (
            "import os, sys\n"
            "os.environ['JAX_PLATFORMS']='cpu'\n"
            "os.environ['PALLAS_AXON_POOL_IPS']=''\n"
            "import numpy as np\n"
            "from bluefog_tpu.runtime.async_windows import AsyncWindow\n"
            f"w = AsyncWindow({name!r}, attach=True)\n"
            "p = np.full(8, float(sys.argv[1]))\n"
            f"for _ in range({reps}):\n"
            "    w.deposit(0, p, accumulate=True)\n"
            "w.free()\n"
        )
        procs = [subprocess.Popen(
            [sys.executable, "-c", code, str(v)], env=_clean_env(),
            cwd=_REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True) for v in (1.0, 3.0)]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=120)
                outs.append(out)
        finally:
            # never orphan a writer against a freed segment (timeout or a
            # first-proc failure must reap the sibling too)
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()
        for p, out in zip(procs, outs):
            assert p.returncode == 0, out
        buf, fresh = win.read(0, consume=True)
        assert fresh == 2 * reps
        np.testing.assert_allclose(buf, np.full(8, reps * (1.0 + 3.0)))
    finally:
        win.free()


def test_fuzz_against_reference_model():
    """Randomized op sequence vs a pure-Python model of the table: the
    rewritten segment layout (csrc/windows.cc) must agree on every deposit
    count, freshness counter, and buffer value."""
    name = _uniq("shm_fuzz")
    rng = np.random.default_rng(3)
    k, n = 3, 5
    win = AsyncWindow(name, n_slots=k, n_elems=n, dtype=np.float64, shm=True)
    model = {s: {"buf": np.zeros(n), "dep": 0, "fresh": 0} for s in range(k)}
    try:
        for step in range(300):
            slot = int(rng.integers(k))
            if rng.random() < 0.6:
                v = rng.standard_normal(n)
                acc = bool(rng.random() < 0.7)
                got = win.deposit(slot, v, accumulate=acc)
                m = model[slot]
                m["buf"] = m["buf"] + v if acc else v.copy()
                m["dep"] += 1
                m["fresh"] += 1
                assert got == m["dep"], step
            else:
                consume = bool(rng.random() < 0.5)
                buf, fresh = win.read(slot, consume=consume)
                m = model[slot]
                assert fresh == m["fresh"], step
                np.testing.assert_allclose(buf, m["buf"], atol=1e-12,
                                           err_msg=f"step {step}")
                if consume:
                    m["buf"] = np.zeros(n)
                    m["fresh"] = 0
    finally:
        win.free()


def test_attach_timeout_is_loud():
    with pytest.raises(RuntimeError, match="did not publish"):
        AsyncWindow(_uniq("shm_nobody"), attach=True, attach_timeout_s=0.05)


def test_stale_segment_recovery():
    """A crashed owner (os._exit skips destructors) leaves the segment
    behind; creating again names the stale segment and shm_unlink_window
    recovers — the failure-cleanup path a real launcher needs."""
    name = _uniq("shm_stale")
    code = (
        "import os\n"
        "os.environ['JAX_PLATFORMS']='cpu'\n"
        "os.environ['PALLAS_AXON_POOL_IPS']=''\n"
        "import numpy as np\n"
        "from bluefog_tpu.runtime.async_windows import AsyncWindow\n"
        f"AsyncWindow({name!r}, 1, 4, np.float32, shm=True)\n"
        "os._exit(0)\n"  # crash: no free, no atexit, no dtors
    )
    out = _run(code)
    assert out.returncode == 0, out.stdout + out.stderr
    with pytest.raises(ValueError, match="stale"):
        AsyncWindow(name, 1, 4, np.float32, shm=True)
    assert shm_unlink_window(name) is True
    win = AsyncWindow(name, 1, 4, np.float32, shm=True)
    win.free()
    assert shm_unlink_window(name) is False  # free already unlinked


@pytest.mark.parametrize("transport", ["shm", "tcp"])
def test_async_dsgd_two_skewed_processes(transport):
    """End-to-end: 2 OS processes run skewed asynchronous DSGD through the
    cross-process windows (VERDICT r3 directive #2) — over named shared
    memory (same-host) AND over the TCP window server (the cross-host/DCN
    shape, exercised here on loopback).  Mass conservation, skew, and
    convergence are asserted inside rank 0 (see _mp_async_worker.py)."""
    import tempfile

    with tempfile.TemporaryDirectory() as bdir:
        worker = os.path.join(_REPO, "tests", "_mp_async_worker.py")
        nproc = 2
        # ~3-5x realized step-rate skew: large enough that lockstep SPMD
        # could never produce it, small enough that the constant-lr
        # equilibrium stays near the mean optimum under machine-load jitter
        # (a free-running rank makes the final state timing-sensitive).
        # The tcp transport needs a wider gap: its pipelined sender/ack
        # threads raise every rank's per-step floor on small CI hosts,
        # which would otherwise swamp a 2 ms skew.
        skews_ms = ["0.5", "2.5"] if transport == "shm" else ["0.5", "10.0"]
        procs = [
            subprocess.Popen(
                [sys.executable, worker, str(r), str(nproc), bdir, "2.0",
                 skews_ms[r], transport],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                env=_clean_env(), cwd=_REPO)
            for r in range(nproc)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=180)
                outs.append(out)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail("async MP workers timed out:\n" + "\n".join(
                o or "" for o in outs))
        for r, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"worker {r} failed:\n{out}"
            assert f"ASYNC_MP_OK {r}" in out, f"worker {r} output:\n{out}"
