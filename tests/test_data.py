"""Input-pipeline tests: sharding discipline, determinism, prefetch, and
end-to-end consumption by a gossip train step.

The reference's sampler contract (disjoint shards, full coverage, per-epoch
reshuffle) comes from its examples' use of torch DistributedSampler
(SURVEY.md §2.2 "Examples"); asserted here in pure numpy terms.
"""

import numpy as np
import pytest

import bluefog_tpu as bf
from bluefog_tpu.data import (
    ArraySource,
    DistributedLoader,
    SyntheticClassificationSource,
    prefetch_to_device,
)


def make_source(n=64, d=3):
    x = np.arange(n * d, dtype=np.float32).reshape(n, d)
    y = np.arange(n, dtype=np.int32)
    return ArraySource(x, y)


class TestArraySource:
    def test_gather(self):
        src = make_source()
        x, y = src[np.array([3, 1])]
        assert y.tolist() == [3, 1]
        np.testing.assert_array_equal(x[0], src.arrays[0][3])

    def test_length_mismatch(self):
        with pytest.raises(ValueError):
            ArraySource(np.zeros(3), np.zeros(4))


class TestDistributedLoader:
    def test_disjoint_full_coverage(self):
        bf.init()
        n_ranks = bf.size()
        src = make_source(n=8 * n_ranks * 2)
        loader = DistributedLoader(src, per_rank_batch=8, device_put=False)
        seen = []
        for batch in loader.epoch(0):
            x, y = batch
            assert x.shape == (n_ranks, 8, 3)
            assert y.shape == (n_ranks, 8)
            seen.extend(y.reshape(-1).tolist())
        # every example exactly once across all ranks and steps
        assert sorted(seen) == list(range(len(src)))

    def test_epoch_reshuffle_deterministic(self):
        bf.init()
        src = make_source(n=64 * bf.size())
        loader = DistributedLoader(src, per_rank_batch=8, device_put=False,
                                   seed=7)
        e0a = [y.tolist() for _, y in loader.epoch(0)]
        e0b = [y.tolist() for _, y in loader.epoch(0)]
        e1 = [y.tolist() for _, y in loader.epoch(1)]
        assert e0a == e0b          # same (seed, epoch) → same order
        assert e0a != e1           # new epoch → new permutation

    def test_remainder_dropped_static_shape(self):
        bf.init()
        n_ranks = bf.size()
        src = make_source(n=8 * n_ranks + 5)  # awkward remainder
        loader = DistributedLoader(src, per_rank_batch=4, device_put=False)
        shapes = {tuple(x.shape) for x, _ in loader.epoch(0)}
        assert shapes == {(n_ranks, 4, 3)}

    def test_too_small_source_raises(self):
        bf.init()
        with pytest.raises(ValueError):
            DistributedLoader(make_source(n=2), per_rank_batch=8)

    def test_device_put_sharded(self):
        bf.init()
        ctx = bf.get_context()
        src = make_source(n=16 * bf.size())
        loader = DistributedLoader(src, per_rank_batch=4, prefetch=2)
        x, y = next(iter(loader))
        assert x.sharding.spec[0] == ctx.axis_name

    def test_train_step_consumption(self):
        """One gossip SGD step straight off the loader (integration)."""
        import jax
        import jax.numpy as jnp
        import optax
        from jax.sharding import PartitionSpec as P

        from bluefog_tpu.optim import DistributedNeighborAllreduceOptimizer
        from bluefog_tpu.parallel.api import shard_map
        from bluefog_tpu.topology import RingGraph

        bf.init(topology=RingGraph(len(jax.devices())))
        ctx = bf.get_context()
        n = ctx.size
        src = make_source(n=8 * n)
        loader = DistributedLoader(src, per_rank_batch=8)
        w = bf.rank_shard(bf.rank_stack(jnp.zeros((3,))))
        opt = DistributedNeighborAllreduceOptimizer(
            optax.sgd(0.1), topology=ctx.schedule, axis_name=ctx.axis_name)

        def step(w_blk, x_blk, y_blk):
            w, x, y = w_blk[0], x_blk[0], y_blk[0]
            st = opt.init(w)
            g = jax.grad(
                lambda w: jnp.mean((x @ w - y.astype(jnp.float32)) ** 2))(w)
            upd, st = opt.update(g, st, w)
            return (w + upd)[None]

        fn = jax.jit(shard_map(
            step, mesh=ctx.mesh, in_specs=(P(ctx.axis_name),) * 3,
            out_specs=P(ctx.axis_name), check_vma=False))
        for x, y in loader.epoch(0):
            w = fn(w, x, y)
        assert np.isfinite(np.asarray(w)).all()


class TestSyntheticSource:
    def test_deterministic_per_index(self):
        src = SyntheticClassificationSource(
            100, shape=(8, 8, 1), num_classes=10, seed=3)
        a_img, a_lab = src[np.array([5, 9])]
        b_img, b_lab = src[np.array([9, 5])]
        np.testing.assert_array_equal(a_lab, b_lab[::-1])
        np.testing.assert_array_equal(a_img[0], b_img[1])

    def test_shapes(self):
        src = SyntheticClassificationSource(50, shape=(28, 28, 1),
                                            num_classes=10)
        img, lab = src[np.arange(4)]
        assert img.shape == (4, 28, 28, 1)
        assert (0 <= lab).all() and (lab < 10).all()


class TestPrefetch:
    def test_order_and_completeness(self):
        out = list(prefetch_to_device(iter(range(10)), size=3))
        assert out == list(range(10))

    def test_exception_propagates(self):
        def gen():
            yield 1
            raise RuntimeError("boom")

        it = prefetch_to_device(gen(), size=2)
        assert next(it) == 1
        with pytest.raises(RuntimeError, match="boom"):
            next(it)

    def test_size_zero_passthrough(self):
        assert list(prefetch_to_device(iter([1, 2]), size=0)) == [1, 2]


def test_subset_view_bounds_and_indexing():
    """Subset (the gates' train/test splitter): correct window, validated
    bounds, no negative-index wraparound."""
    import numpy as np
    import pytest
    from bluefog_tpu.data import ArraySource, Subset

    src = ArraySource(np.arange(100), np.arange(100) * 2)
    sub = Subset(src, 10, 30)
    assert len(sub) == 20
    a, b = sub[np.array([0, 19])]
    assert list(a) == [10, 29] and list(b) == [20, 58]
    with pytest.raises(IndexError):
        sub[np.array([20])]
    with pytest.raises(IndexError):
        sub[np.array([-1])]
    with pytest.raises(ValueError):
        Subset(src, 50, 40)
    with pytest.raises(ValueError):
        Subset(src, 0, 101)
