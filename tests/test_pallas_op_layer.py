"""The FULL op layers through the pallas branch under TPU-interpret.

test_pallas_gossip.py exercises the bare kernels; these tests force
``backend='pallas'`` through the real op-layer code paths —
``ops/collectives.neighbor_allreduce`` (pytree dispatch, collective-id
enumeration) and the window family (``win_put``/``win_accumulate`` deliver
with name-derived collective-id bases and in-edge masks) — with
``BLUEFOG_TPU_PALLAS_INTERPRET=1`` routing the kernels through Mosaic
emulation on the CPU mesh, asserted equal to the XLA backend.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from bluefog_tpu.ops import collectives as C, windows as W
from bluefog_tpu.parallel.api import shard_map
from bluefog_tpu.topology import ExponentialTwoGraph, RingGraph
from bluefog_tpu.topology.schedule import build_schedule

N = 8


@pytest.fixture(autouse=True)
def _interpret(monkeypatch):
    monkeypatch.setenv("BLUEFOG_TPU_PALLAS_INTERPRET", "1")


def _mesh():
    return Mesh(np.array(jax.devices()[:N]), ("bf",))


def _run(body, *inputs):
    return jax.jit(shard_map(
        body, mesh=_mesh(), in_specs=(P("bf"),) * len(inputs),
        out_specs=P("bf"), check_vma=False))(*inputs)


def test_gossip_op_layer_pallas_matches_xla():
    sched = build_schedule(ExponentialTwoGraph(N))
    tree = {
        "a": jnp.arange(N * 6, dtype=jnp.float32).reshape(N, 6),
        "b": jnp.arange(N * 4, dtype=jnp.float32).reshape(N, 2, 2) / 7.0,
    }

    def body(backend):
        def fn(xs):
            return C.neighbor_allreduce(xs, sched, "bf", backend=backend)
        return fn

    got = _run(body("pallas"), tree)
    want = _run(body("xla"), tree)
    for k in tree:
        np.testing.assert_allclose(np.asarray(got[k]), np.asarray(want[k]),
                                   rtol=1e-5, atol=1e-6)


def test_two_windows_one_program_distinct_semaphores():
    """Gradient-tracking's shape: TWO windows delivered in ONE jitted
    program.  Their name-derived collective-id bases must stay distinct
    after the interpret-mode compact remap (a raw modulo fold collided
    1/30 of name pairs — regression for that), or one kernel's handshake
    absorbs the other's."""
    from bluefog_tpu.ops.pallas_gossip import _interpret_collective_id

    # distinct originals always map to distinct compact ids
    seen = {_interpret_collective_id(cid)
            for cid in (7, 1024, 2048, 2048 + 27 * 30720, 2**29 + 5)}
    assert len(seen) == 5

    sched = build_schedule(RingGraph(N))
    xs = jnp.arange(N * 3, dtype=jnp.float32).reshape(N, 3)

    def body(backend, suffix):
        def fn(v):
            sx = W.win_create(v, sched, "bf", name=f"gt_x_{suffix}")
            sy = W.win_create(2 * v, sched, "bf", name=f"gt_y_{suffix}")
            sx = W.win_put(sx, v, "bf", backend=backend)
            sy = W.win_accumulate(sy, 2 * v, "bf", backend=backend)
            ox, _ = W.win_update(sx, "bf")
            oy, _ = W.win_update(sy, "bf")
            return ox + oy
        return fn

    got = _run(body("pallas", "pl"), xs)
    want = _run(body("xla", "x"), xs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_window_family_pallas_matches_xla():
    """win_put + win_accumulate + win_update through the pallas deliver
    branch (two leaves -> two collective ids off the name-derived base)."""
    sched = build_schedule(RingGraph(N))
    tree = {
        "w": jnp.arange(N * 5, dtype=jnp.float32).reshape(N, 5),
        "b": jnp.arange(N, dtype=jnp.float32).reshape(N, 1) * 3.0,
    }

    def body(backend, wname):
        def fn(xs):
            st = W.win_create(xs, sched, "bf", name=wname)
            st = W.win_put(st, xs, "bf", backend=backend)
            st = W.win_accumulate(st, xs, "bf", backend=backend)
            out, _ = W.win_update(st, "bf")
            return out
        return fn

    got = _run(body("pallas", "pl_probe"), tree)
    want = _run(body("xla", "xla_probe"), tree)
    for k in tree:
        np.testing.assert_allclose(np.asarray(got[k]), np.asarray(want[k]),
                                   rtol=1e-5, atol=1e-6)
