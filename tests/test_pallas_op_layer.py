"""The FULL op layers through the pallas branch under TPU-interpret.

test_pallas_gossip.py exercises the bare kernels; these tests force
``backend='pallas'`` through the real op-layer code paths —
``ops/collectives.neighbor_allreduce`` (pytree dispatch, collective-id
enumeration) and the window family (``win_put``/``win_accumulate`` deliver
with name-derived collective-id bases and in-edge masks) — with
``BLUEFOG_TPU_PALLAS_INTERPRET=1`` routing the kernels through Mosaic
emulation on the CPU mesh, asserted equal to the XLA backend.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from bluefog_tpu.ops import collectives as C, windows as W
from bluefog_tpu.parallel.api import shard_map
from bluefog_tpu.topology import ExponentialTwoGraph, RingGraph
from bluefog_tpu.topology.schedule import build_schedule

N = 8


@pytest.fixture(autouse=True)
def _interpret(monkeypatch):
    monkeypatch.setenv("BLUEFOG_TPU_PALLAS_INTERPRET", "1")


def _mesh():
    return Mesh(np.array(jax.devices()[:N]), ("bf",))


def _run(body, *inputs):
    return jax.jit(shard_map(
        body, mesh=_mesh(), in_specs=(P("bf"),) * len(inputs),
        out_specs=P("bf"), check_vma=False))(*inputs)


def test_gossip_op_layer_pallas_matches_xla():
    sched = build_schedule(ExponentialTwoGraph(N))
    tree = {
        "a": jnp.arange(N * 6, dtype=jnp.float32).reshape(N, 6),
        "b": jnp.arange(N * 4, dtype=jnp.float32).reshape(N, 2, 2) / 7.0,
    }

    def body(backend):
        def fn(xs):
            return C.neighbor_allreduce(xs, sched, "bf", backend=backend)
        return fn

    got = _run(body("pallas"), tree)
    want = _run(body("xla"), tree)
    for k in tree:
        np.testing.assert_allclose(np.asarray(got[k]), np.asarray(want[k]),
                                   rtol=1e-5, atol=1e-6)


def test_two_windows_one_program_distinct_semaphores():
    """Gradient-tracking's shape: TWO windows delivered in ONE jitted
    program.  Their name-derived collective-id bases must stay distinct
    after the interpret-mode compact remap (a raw modulo fold collided
    1/30 of name pairs — regression for that), or one kernel's handshake
    absorbs the other's."""
    from bluefog_tpu.ops.pallas_gossip import _interpret_collective_id

    # distinct originals always map to distinct compact ids
    seen = {_interpret_collective_id(cid)
            for cid in (7, 1024, 2048, 2048 + 27 * 30720, 2**29 + 5)}
    assert len(seen) == 5

    sched = build_schedule(RingGraph(N))
    xs = jnp.arange(N * 3, dtype=jnp.float32).reshape(N, 3)

    def body(backend, suffix):
        def fn(v):
            sx = W.win_create(v, sched, "bf", name=f"gt_x_{suffix}")
            sy = W.win_create(2 * v, sched, "bf", name=f"gt_y_{suffix}")
            sx = W.win_put(sx, v, "bf", backend=backend)
            sy = W.win_accumulate(sy, 2 * v, "bf", backend=backend)
            ox, _ = W.win_update(sx, "bf")
            oy, _ = W.win_update(sy, "bf")
            return ox + oy
        return fn

    got = _run(body("pallas", "pl"), xs)
    want = _run(body("xla", "x"), xs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_window_family_pallas_matches_xla():
    """win_put + win_accumulate + win_update through the pallas deliver
    branch (two leaves -> two collective ids off the name-derived base)."""
    sched = build_schedule(RingGraph(N))
    tree = {
        "w": jnp.arange(N * 5, dtype=jnp.float32).reshape(N, 5),
        "b": jnp.arange(N, dtype=jnp.float32).reshape(N, 1) * 3.0,
    }

    def body(backend, wname):
        def fn(xs):
            st = W.win_create(xs, sched, "bf", name=wname)
            st = W.win_put(st, xs, "bf", backend=backend)
            st = W.win_accumulate(st, xs, "bf", backend=backend)
            out, _ = W.win_update(st, "bf")
            return out
        return fn

    got = _run(body("pallas", "pl_probe"), tree)
    want = _run(body("xla", "xla_probe"), tree)
    for k in tree:
        np.testing.assert_allclose(np.asarray(got[k]), np.asarray(want[k]),
                                   rtol=1e-5, atol=1e-6)


def test_gossip_chunked_leaf_matches_xla(monkeypatch):
    """A leaf beyond the per-invocation cap splits into cap-sized chunks
    (one kernel + collective id each) and must reproduce the XLA gossip
    bit-for-bit at f32 tolerance.  Cap shrunk to 4 KiB so a 4,100-float
    leaf chunks 5-ways under emulation."""
    monkeypatch.setenv("BLUEFOG_TPU_PALLAS_MAX_BYTES", str(4 << 10))
    sched = build_schedule(ExponentialTwoGraph(N))
    # deliberately NOT a multiple of the chunk size: exercises the uneven
    # tail chunk (array_split) and per-chunk tile padding
    tree = {"big": jnp.arange(N * 4100, dtype=jnp.float32).reshape(N, 4100)
                   / 997.0,
            "small": jnp.arange(N * 3, dtype=jnp.float32).reshape(N, 3)}

    from bluefog_tpu.ops import pallas_gossip as pg
    calls = []
    real = pg.neighbor_allreduce_pallas

    def spy(leaf, *a, **kw):
        calls.append((int(np.prod(leaf.shape)), kw.get("collective_id")))
        return real(leaf, *a, **kw)

    monkeypatch.setattr(pg, "neighbor_allreduce_pallas", spy)

    def body(backend):
        def fn(xs):
            return C.neighbor_allreduce(xs, sched, "bf", backend=backend)
        return fn

    got = _run(body("pallas"), tree)
    want = _run(body("xla"), tree)
    for k in tree:
        np.testing.assert_allclose(np.asarray(got[k]), np.asarray(want[k]),
                                   rtol=1e-5, atol=1e-6)
    # 4100 floats = 16,400 B at a 4,096 B cap -> 5 chunks + 1 small leaf,
    # with six DISTINCT collective ids in the gossip range
    chunk_calls = [c for c in calls if c[0] != 3]
    assert len(chunk_calls) == 5, calls
    ids = {cid for _, cid in calls}
    assert len(ids) == 6 and all(1024 <= i < 2048 for i in ids), calls


def test_default_optimizer_path_selects_chunked_pallas(monkeypatch):
    """THE round-4 verdict gate for the fuse_apply x auto-routing
    contradiction: the DEFAULT optimizer path (backend='auto', fused
    buffers) on a TPU mesh must actually exercise the RDMA kernels — the
    fused flat buffer CHUNKS instead of silently falling back to XLA —
    and produce the same training step as the XLA backend."""
    import optax
    import bluefog_tpu as bf
    from bluefog_tpu.optim import DistributedNeighborAllreduceOptimizer
    from bluefog_tpu.ops import pallas_gossip as pg
    from bluefog_tpu.topology import ExponentialTwoGraph

    # pretend the CPU mesh is a TPU slice (interpret mode executes the
    # kernels); shrink the cap so the fused buffer (5,000 floats = 20 KB)
    # needs 3 chunks at 8 KiB
    monkeypatch.setattr(pg, "on_tpu_platform", lambda: True)
    monkeypatch.setenv("BLUEFOG_TPU_PALLAS_MAX_BYTES", str(8 << 10))

    calls = []
    real = pg.neighbor_allreduce_pallas

    def spy(leaf, *a, **kw):
        calls.append(int(np.prod(leaf.shape)))
        return real(leaf, *a, **kw)

    monkeypatch.setattr(pg, "neighbor_allreduce_pallas", spy)

    params = {"w1": jnp.ones((N, 40, 100), jnp.float32),
              "w2": jnp.ones((N, 1000), jnp.float32)}
    grads = jax.tree_util.tree_map(
        lambda t: jnp.broadcast_to(
            jnp.arange(N, dtype=jnp.float32).reshape((N,) + (1,) *
                                                     (t.ndim - 1)), t.shape),
        params)

    def run_step():
        opt = DistributedNeighborAllreduceOptimizer(
            optax.sgd(0.1), topology=ExponentialTwoGraph(N), axis_name="bf")

        def body(p, g):
            st = opt.init(p)
            upd, _ = opt.update(g, st, p)
            return optax.apply_updates(p, upd)

        return jax.jit(shard_map(
            body, mesh=_mesh(), in_specs=(P("bf"), P("bf")),
            out_specs=P("bf"), check_vma=False))(params, grads)

    got = run_step()
    assert calls, "default optimizer path never reached the pallas kernels"
    # fused buffer = 5,000 floats -> ceil(20,000 B / 8,192 B) = 3 chunks
    assert len(calls) == 3 and sum(calls) == 5000, calls

    # numerics: the same step on the forced-XLA path
    monkeypatch.setenv("BLUEFOG_TPU_PALLAS_GOSSIP", "0")
    calls.clear()
    want = run_step()
    assert not calls, "kill switch must force XLA"
    for k in params:
        np.testing.assert_allclose(np.asarray(got[k]), np.asarray(want[k]),
                                   rtol=1e-5, atol=1e-6)
