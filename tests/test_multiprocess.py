"""Multi-process correctness: real OS processes, real cross-process
collectives, real distributed locks.

The reference runs its distributed suite under ``mpirun -np N pytest``
(SURVEY.md §4).  The equivalent here: this module spawns N worker processes
(``tests/_mp_worker.py``) that rendezvous through ``initialize_cluster``,
build one global mesh spanning the process boundary (2 virtual CPU devices
per process, gloo transport), and assert closed-form gossip/allreduce plus
cross-process ``win_mutex`` exclusion.  Plus: rendezvous failure must be
LOUD when a cluster was explicitly requested.
"""

import os
import socket
import subprocess
import sys

import pytest

from tests._util import REPO as _REPO, clean_env

_WORKER = os.path.join(_REPO, "tests", "_mp_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _clean_env():
    # the workers configure their own platform/device-count (2 each) and
    # pin cpu themselves before importing jax
    return clean_env(cpu_pin=False)


@pytest.mark.parametrize("nproc", [2])
@pytest.mark.duration_budget(240)  # pre-existing heavyweight; tier-1 coverage load-bearing
def test_cluster_spans_processes(nproc):
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(pid), str(nproc), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=_clean_env(), cwd=_REPO)
        for pid in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multi-process workers timed out:\n" +
                    "\n".join(o or "" for o in outs))
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"MP_WORKER_OK {pid}" in out, f"worker {pid} output:\n{out}"


@pytest.mark.duration_budget(60)  # pre-existing heavyweight; tier-1 coverage load-bearing
def test_rendezvous_timeout_kills_the_process():
    """An explicitly requested cluster that cannot rendezvous must never
    degrade to silent single-process training.  In this jaxlib the
    distributed runtime's fatal check terminates the process on rendezvous
    timeout before Python sees an exception — maximally loud: assert the
    process died nonzero and never reached the code after initialize."""
    port = _free_port()
    code = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "os.environ['PALLAS_AXON_POOL_IPS'] = ''\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from bluefog_tpu.runtime.launch import initialize_cluster\n"
        f"initialize_cluster('127.0.0.1:{port}', 2, 0, "
        "initialization_timeout=3)\n"
        "print('SILENT_FALLBACK')\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        env=_clean_env(), cwd=_REPO, timeout=120)
    assert out.returncode != 0, (
        "rendezvous timeout did not fail the process:\n" + out.stdout)
    assert "SILENT_FALLBACK" not in out.stdout


def test_win_mutex_break_single_controller_noop():
    """Single controller: a holder's death is process death — break is a
    documented no-op returning False (never drops a live RLock)."""
    import bluefog_tpu as bf

    bf.init()
    with bf.win_mutex("solo"):
        assert bf.win_mutex_break("solo") is False
    assert bf.win_mutex_break("solo") is False


def test_rendezvous_exception_policy(monkeypatch):
    """When initialize raises a catchable error: explicit cluster arguments
    escalate to RuntimeError; the fully-auto-detected call only warns."""
    import jax

    from bluefog_tpu.runtime import launch

    def boom(**kwargs):
        raise ValueError("no cluster here")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    with pytest.raises(RuntimeError, match="rendezvous failed"):
        launch.initialize_cluster("127.0.0.1:1", 2, 0)
    launch.initialize_cluster()  # auto-detect: warn, no raise
