"""Failure detection: hang watchdog + supervised restart-from-checkpoint.

SURVEY.md §5: the reference has no failure story (a dead rank kills the MPI
job, nothing recovers).  These tests assert the TPU build's minimum:

- a silent hang is *detected* (heartbeat deadline) and *recovered* in-process
  (HangError → run_with_restart restores the checkpoint and re-enters);
- a killed worker process is restarted by the supervisor and resumes from
  its latest checkpoint (losing only post-checkpoint progress).
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from bluefog_tpu.utils.checkpoint import CheckpointManager, run_with_restart
from bluefog_tpu.utils.failure import HangError, Heartbeat, run_supervised

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestHeartbeat:
    def test_no_hang_no_action(self):
        fired = []
        hb = Heartbeat(0.2, action="callback", on_hang=lambda: fired.append(1))
        with hb:
            for _ in range(5):
                time.sleep(0.05)
                hb.beat()
        assert not fired
        assert hb.hangs_detected == 0

    def test_hang_detected_via_callback(self):
        fired = threading.Event()
        hb = Heartbeat(0.15, action="callback", on_hang=fired.set)
        with hb:
            assert fired.wait(3.0), "watchdog never fired"
        assert hb.hangs_detected >= 1

    def test_hang_raises_in_target_thread(self):
        """A Python-level hang (interruptible wait loop) gets HangError
        injected and unwinds."""
        hb = Heartbeat(0.2, action="raise", grace_s=5.0)
        with hb, pytest.raises(HangError):
            while True:  # the "wedged" loop — never beats
                time.sleep(0.01)
        assert hb.hangs_detected == 1

    @pytest.mark.duration_budget(60)  # pre-existing heavyweight; tier-1 coverage load-bearing
    def test_run_with_restart_recovers_from_hang(self, tmp_path):
        """The full loop: train 3 steps, checkpoint, hang; the watchdog
        raises; run_with_restart restores step 3's checkpoint and the second
        attempt finishes all 6 steps."""
        mgr = CheckpointManager(str(tmp_path), async_save=False)
        attempts = []

        def train(state, start, hb):
            attempts.append(start)
            x = np.asarray(state["x"])
            for step in range(start, 6):
                x = x + 1.0
                mgr.save(step, {"x": x})
                hb.beat(step)
                if step == 3 and len(attempts) == 1:
                    while True:  # wedge: stop beating, keep "running"
                        time.sleep(0.01)
            return {"x": x}

        # timeout must comfortably exceed one orbax save (observed up to
        # ~1.1 s in this container under load): a deadline tighter than a
        # save can fire MID-SAVE before the first beat, injecting
        # HangError into the checkpoint machinery instead of the wedge
        out = run_with_restart(
            train, mgr, {"x": np.zeros(2)}, max_restarts=2,
            recoverable=(), heartbeat_timeout_s=3.0, heartbeat_grace_s=10.0)
        mgr.close()
        # attempt 1 started at 0 and wedged after saving step 3;
        # attempt 2 resumed at 4 and finished
        assert attempts == [0, 4]
        np.testing.assert_allclose(np.asarray(out["x"]), [6.0, 6.0])


class TestSupervisor:
    WORKER = r"""
import os, sys
import numpy as np
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["PALLAS_AXON_POOL_IPS"] = ""
import jax
jax.config.update("jax_platforms", "cpu")
from bluefog_tpu.utils.checkpoint import CheckpointManager

ckpt = {ckpt!r}
mgr = CheckpointManager(ckpt, async_save=False)
step0 = mgr.latest_step()
start = 0 if step0 is None else step0 + 1
x = np.zeros(2) if step0 is None else np.asarray(
    mgr.restore(step0, template={{"x": np.zeros(2)}})["x"])
for step in range(start, 6):
    x = x + 1.0
    mgr.save(step, {{"x": x}})
    if step == 2 and step0 is None:
        os._exit(17)  # simulated worker death mid-training (first run only)
mgr.close()
print("WORKER_DONE", x.tolist())
"""

    def test_killed_worker_restarts_from_checkpoint(self, tmp_path):
        ckpt = str(tmp_path / "ckpt")
        script = tmp_path / "worker.py"
        script.write_text(self.WORKER.format(repo=_REPO, ckpt=ckpt))
        rc = run_supervised([sys.executable, str(script)], max_restarts=2,
                            restart_backoff_s=0.05)
        assert rc == 0
        mgr = CheckpointManager(ckpt, async_save=False)
        assert mgr.latest_step() == 5
        out = mgr.restore(5, template={"x": np.zeros(2)})
        mgr.close()
        # first run died at step 2 (after saving), second resumed at 3:
        # the counter still reaches exactly 6 — no lost or repeated steps
        np.testing.assert_allclose(np.asarray(out["x"]), [6.0, 6.0])

    def test_supervisor_gives_up(self, tmp_path):
        script = tmp_path / "always_dies.py"
        script.write_text("import sys; sys.exit(9)\n")
        rc = run_supervised([sys.executable, str(script)], max_restarts=2,
                            restart_backoff_s=0.05)
        assert rc == 9
