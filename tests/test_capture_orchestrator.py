"""benchmarks/capture_onchip.py orchestration logic.

The one-shot harvest runs unattended the moment a chip window opens, so
its two guards are driver-critical: a DEGRADED bench (stale flag anywhere
in full stdout) must stop the capture before later stages hang on the
wedged relay, and a timed-out stage must preserve the child's partial
output (the only wedge diagnostic there will ever be).
"""

import importlib.util
import json
import os
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def cap():
    spec = importlib.util.spec_from_file_location(
        "capture_onchip", os.path.join(_REPO, "benchmarks",
                                       "capture_onchip.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_run_stage_success_returns_full_stdout(cap, capsys):
    ok, stdout = cap.run_stage(
        "probe", [sys.executable, "-c", "print('x' * 3000); print('MARK')"],
        timeout_s=60)
    assert ok is True
    # FULL stdout comes back (the stale scan must not be limited to a
    # tail: the marker can sit >2000 chars before the end)
    assert "MARK" in stdout and len(stdout) > 3000
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["stage"] == "probe" and line["ok"] is True


def test_run_stage_failure_and_stderr_tail(cap, capsys):
    ok, _ = cap.run_stage(
        "boom", [sys.executable, "-c",
                 "import sys; print('partial'); sys.exit(3)"],
        timeout_s=60)
    assert ok is False
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "partial" in line["tail"]


def test_run_stage_timeout_keeps_partial_output(cap, capsys):
    # timeout must comfortably exceed interpreter startup on a loaded box,
    # or the child is killed before it ever prints
    ok, _ = cap.run_stage(
        "hang", [sys.executable, "-u", "-c",
                 "import time; print('got this far', flush=True); "
                 "time.sleep(120)"],
        timeout_s=15)
    assert ok is False
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "TIMEOUT" in line["tail"]
    assert "got this far" in line["tail"], (
        "a timed-out stage must keep the child's partial output — it is "
        "the only wedge diagnostic")
