"""benchmarks/capture_onchip.py orchestration logic.

The one-shot harvest runs unattended the moment a chip window opens, so
its two guards are driver-critical: a DEGRADED bench (stale flag anywhere
in full stdout) must stop the capture before later stages hang on the
wedged relay, and a timed-out stage must preserve the child's partial
output (the only wedge diagnostic there will ever be).
"""

import json
import os
import sys

import pytest

from tests._util import load_script


@pytest.fixture(scope="module")
def cap():
    return load_script(os.path.join("benchmarks", "capture_onchip.py"))


def test_run_stage_success_returns_full_stdout(cap, capsys):
    ok, stdout = cap.run_stage(
        "probe", [sys.executable, "-c", "print('x' * 3000); print('MARK')"],
        timeout_s=60)
    assert ok is True
    # FULL stdout comes back (the stale scan must not be limited to a
    # tail: the marker can sit >2000 chars before the end)
    assert "MARK" in stdout and len(stdout) > 3000
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["stage"] == "probe" and line["ok"] is True


def test_run_stage_failure_and_stderr_tail(cap, capsys):
    ok, _ = cap.run_stage(
        "boom", [sys.executable, "-c",
                 "import sys; print('partial'); sys.exit(3)"],
        timeout_s=60)
    assert ok is False
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "partial" in line["tail"]


@pytest.mark.duration_budget(60)  # pre-existing heavyweight; tier-1 coverage load-bearing
def test_run_stage_timeout_keeps_partial_output(cap, capsys):
    # the flat cost IS the timeout; it must still comfortably exceed
    # interpreter startup on a loaded box or the child never prints
    ok, _ = cap.run_stage(
        "hang", [sys.executable, "-u", "-c",
                 "import time; print('got this far', flush=True); "
                 "time.sleep(120)"],
        timeout_s=10)
    assert ok is False
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "TIMEOUT" in line["tail"]
    assert "got this far" in line["tail"], (
        "a timed-out stage must keep the child's partial output — it is "
        "the only wedge diagnostic")
