"""Pipelined zero-copy DCN window transport (wire v2).

Covers the tentpole surfaces of the batched deposit engine
(``runtime/window_server.py``):

- protocol-version negotiation: a v1 client against the v2 server is
  rejected with a clear error (status ``-101``), not silently corrupted;
  a HELLO with the wrong version likewise; codec features must be
  negotiated before the server accepts compressed items;
- the batched multi-deposit wire op: multi-window/multi-slot batches,
  one ack, exactly-once counts, per-item error isolation (a bad item
  cannot desync its neighbors in the same frame);
- pipelined semantics: fire-and-forget with payload-snapshot, ``flush``
  as a real fence (owner observes everything on return), deferred errors
  surfacing loudly at the fence;
- wire codecs (f32 / top-k) through the server into the table, and the
  wire_codec ``kept`` arithmetic staying in lockstep with the device
  compressor's ``_kept`` (the "reuse, not fork" contract);
- malformed/truncated-frame fuzz of the batched parser: garbage never
  crashes the serving process — at worst the one connection drops and
  fresh clients still work;
- the multi-process pipelined dsgd run: the mass-conservation audit
  stays EXACT through the pipelined transport (the flush fence before
  the "stopped" barrier is what makes it exact).

These tests run against whichever window table the host has (native or
the pure-Python fallback) — the transport must behave identically on
both, so there is deliberately NO native skip here.
"""

import os
import socket
import struct
import subprocess
import sys

import numpy as np
import pytest

from tests._util import REPO as _REPO, clean_env, uniq as _uniq


def _mk(name, n_slots, n_elems, dtype=np.float64):
    from bluefog_tpu.runtime.async_windows import AsyncWindow

    return AsyncWindow(name, n_slots=n_slots, n_elems=n_elems, dtype=dtype)


def _serve():
    from bluefog_tpu.runtime.window_server import WindowServer

    srv = WindowServer()
    _, port = srv.start("127.0.0.1")
    return srv, port


def _recv_exactly(sock, n):
    buf = b""
    while len(buf) < n:
        got = sock.recv(n - len(buf))
        assert got, "server closed mid-reply"
        buf += got
    return buf


# ---------------------------------------------------------------------------
# version negotiation
# ---------------------------------------------------------------------------


def test_v1_client_is_rejected_loudly():
    """A v1-magic frame gets ONE clear error status back (-101), exactly
    where the old client blocks on its reply — then the connection drops."""
    name = _uniq("wt_v1")
    win = _mk(name, 1, 4)
    srv, port = _serve()
    try:
        hdr = struct.Struct("<IBH")
        body = struct.Struct("<iBBq")
        status = struct.Struct("<q")
        with socket.create_connection(("127.0.0.1", port), timeout=10) as s:
            nb = name.encode()
            msg = (hdr.pack(0xBF_51_0E_01, 0, len(nb)) + nb +
                   body.pack(0, 1, 1, 4) + np.ones(4).tobytes())
            s.sendall(msg)
            (rc,) = status.unpack(s.recv(8))
            assert rc == -101, rc
            assert s.recv(1) == b""  # server dropped the connection
    finally:
        srv.stop()
        win.free()


def test_hello_wrong_version_rejected():
    from bluefog_tpu.runtime.window_server import (_HDR, _HELLO, _MAGIC,
                                                   _OP_HELLO, _STATUS)

    srv, port = _serve()
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=10) as s:
            s.sendall(_HDR.pack(_MAGIC, _OP_HELLO, 0) + _HELLO.pack(3, 0))
            (rc,) = _STATUS.unpack(s.recv(8))
            assert rc == -101, rc
    finally:
        srv.stop()


def test_codec_requires_negotiation():
    """A batch item claiming a codec the connection never negotiated is
    rejected per-item (the frame survives; the client sees the error at
    its fence), and the client-side HELLO surfaces unsupported feature
    requests as a clear exception."""
    from bluefog_tpu.runtime import window_server as ws

    name = _uniq("wt_nego")
    win = _mk(name, 1, 8)
    srv, port = _serve()
    try:
        # hand-build a batch with codec=f32 on a connection with NO hello
        with socket.create_connection(("127.0.0.1", port), timeout=10) as s:
            nb = name.encode()
            payload = np.ones(8, np.float32)
            item = ws._ITEM.pack(len(nb), 0, 1, 1, 1, 8, payload.nbytes)
            s.sendall(ws._HDR.pack(ws._MAGIC, ws._OP_DEPOSIT_BATCH, 0)
                      + ws._BATCH_HDR.pack(7, 1) + item + nb
                      + payload.tobytes())
            seq, rc = ws._ACK.unpack(s.recv(12))
            assert seq == 7 and rc == -102, (seq, rc)
        buf, fresh = win.read(0, consume=True)
        assert fresh == 0  # nothing landed
    finally:
        srv.stop()
        win.free()


def test_kept_matches_device_compressor():
    """wire_codec.kept is the numpy twin of ops.compression._kept — the
    'reusing quantize/top-k' contract, enforced instead of imported (the
    host path must not drag jax into socket threads)."""
    jax = pytest.importorskip("jax")  # noqa: F841 — compression imports jax
    from bluefog_tpu.ops.compression import _kept, top_k
    from bluefog_tpu.runtime import wire_codec

    for n in (1, 2, 3, 7, 100, 1023, 65536):
        for r in (0.01, 0.1, 0.25, 0.5, 0.9, 1.0):
            assert wire_codec.kept(n, r) == _kept(n, r), (n, r)
    # and the top-k support matches the device compressor's support
    rng = np.random.default_rng(0)
    x = rng.standard_normal(64).astype(np.float32)
    comp = top_k(0.25)
    dev = np.asarray(comp.decompress(
        comp.compress(jax.numpy.asarray(x), None), None,
        jax.numpy.asarray(x)))
    views, nbytes = wire_codec.encode(x, wire_codec.CODEC_TOPK,
                                      topk_ratio=0.25)
    wire = b"".join(bytes(v) for v in views)
    host = wire_codec.decode(wire_codec.CODEC_TOPK, memoryview(wire),
                             64, np.float32)
    np.testing.assert_allclose(host, dev, rtol=1e-6)


# ---------------------------------------------------------------------------
# batched deposits + pipelined semantics
# ---------------------------------------------------------------------------


def test_batch_multi_window_roundtrip():
    """One DepositStream batches deposits for SEVERAL windows/slots of the
    same peer into shared frames; every deposit lands exactly once."""
    from bluefog_tpu.runtime.window_server import DepositStream

    n1, n2 = _uniq("wt_a"), _uniq("wt_b")
    wa = _mk(n1, 2, 4)
    wb = _mk(n2, 1, 6)
    srv, port = _serve()
    try:
        st = DepositStream(("127.0.0.1", port))
        pa = np.arange(4, dtype=np.float64)
        pb = np.ones(6)
        for k in range(5):
            st.deposit_async(n1.encode(), 0, pa)
            st.deposit_async(n1.encode(), 1, 2 * pa, accumulate=False)
            st.deposit_async(n2.encode(), 0, pb)
        st.flush(timeout_s=30)
        buf, fresh = wa.read(0, consume=True)
        assert fresh == 5
        np.testing.assert_allclose(buf, 5 * pa)
        buf, fresh = wa.read(1, consume=True)
        assert fresh == 5
        np.testing.assert_allclose(buf, 2 * pa)  # put, not accumulate
        buf, fresh = wb.read(0, consume=True)
        assert fresh == 5
        np.testing.assert_allclose(buf, 5.0)
        st.close()
    finally:
        srv.stop()
        wa.free()
        wb.free()


def test_pipelined_snapshot_semantics_and_fence():
    """The hot-loop contract: the caller reuses ONE payload buffer,
    mutating it immediately after deposit_async — the wire must carry the
    value at enqueue time, and flush() must be a real fence (owner sees
    every deposit once flush returns)."""
    from bluefog_tpu.runtime.window_server import PipelinedRemoteWindow

    name = _uniq("wt_snap")
    win = _mk(name, 1, 8)
    srv, port = _serve()
    try:
        pw = PipelinedRemoteWindow(("127.0.0.1", port), name)
        buf = np.zeros(8)
        expect = np.zeros(8)
        for k in range(100):
            buf[:] = k
            pw.deposit_async(0, buf, accumulate=True)
            expect += k
        pw.flush(timeout_s=30)
        got, fresh = win.read(0, consume=True)
        assert fresh == 100
        np.testing.assert_allclose(got, expect)
        pw.close()
    finally:
        srv.stop()
        win.free()


def test_pipelined_errors_surface_at_fence():
    """Fire-and-forget deposits into a missing window cannot raise at the
    call — the error must latch and surface LOUDLY at flush (or the next
    deposit), never silently vanish."""
    from bluefog_tpu.runtime.window_server import DepositStream

    srv, port = _serve()
    try:
        st = DepositStream(("127.0.0.1", port))
        st.deposit_async(b"no_such_window", 0, np.ones(4))
        with pytest.raises(RuntimeError, match="no such window|failed"):
            st.flush(timeout_s=30)
        st.close()
    finally:
        srv.stop()


def test_batch_bad_item_does_not_desync_good_items():
    """Per-item wire_bytes keeps the batched stream parseable past a bad
    item: deposits before AND after the bad one in the same frame land."""
    from bluefog_tpu.runtime import window_server as ws

    name = _uniq("wt_mix")
    win = _mk(name, 1, 4)
    srv, port = _serve()
    try:
        nb = name.encode()
        good = np.full(4, 2.0)
        bad_nb = b"missing_win"
        with socket.create_connection(("127.0.0.1", port), timeout=10) as s:
            frames = [ws._HDR.pack(ws._MAGIC, ws._OP_DEPOSIT_BATCH, 0),
                      ws._BATCH_HDR.pack(1, 3)]
            for wname, arr in ((nb, good), (bad_nb, good), (nb, good)):
                frames.append(ws._ITEM.pack(
                    len(wname), 0, 1, 1, 0, 4, arr.nbytes))
                frames.append(wname)
                frames.append(arr.tobytes())
            s.sendall(b"".join(frames))
            seq, rc = ws._ACK.unpack(s.recv(12))
            assert seq == 1 and rc == -3, (seq, rc)  # first error reported
        buf, fresh = win.read(0, consume=True)
        assert fresh == 2  # both good items landed despite the middle one
        np.testing.assert_allclose(buf, 4.0)
    finally:
        srv.stop()
        win.free()


def test_dense_item_wire_bytes_must_match_exactly():
    """A dense (codec none) item whose wire_bytes disagrees with
    n_elems*itemsize — under OR over (within the topk bound) — is
    rejected per item and the CONNECTION SURVIVES: later frames on the
    same socket still ack and apply.  Regression: an under-length dense
    payload used to blow up inside the apply worker, killing the applier
    thread and wedging every later batch on that connection."""
    from bluefog_tpu.runtime import window_server as ws

    name = _uniq("wt_exact")
    win = _mk(name, 1, 8)
    srv, port = _serve()
    arr = np.ones(8)
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=10) as s:
            for bad_wire in (56, 72):  # -8 and +8 vs the true 64
                payload = b"x" * bad_wire
                s.sendall(ws._HDR.pack(ws._MAGIC, ws._OP_DEPOSIT_BATCH, 0)
                          + ws._BATCH_HDR.pack(5, 1)
                          + ws._ITEM.pack(len(name.encode()), 0, 1, 1, 0,
                                          8, bad_wire)
                          + name.encode() + payload)
                seq, rc = ws._ACK.unpack(_recv_exactly(s, 12))
                assert seq == 5 and rc == -2, (bad_wire, seq, rc)
            # the same connection still works after both bad items
            s.sendall(_valid_batch_bytes(ws, name.encode(), arr, seq=6))
            seq, rc = ws._ACK.unpack(_recv_exactly(s, 12))
            assert seq == 6 and rc == 1, (seq, rc)
        buf, fresh = win.read(0, consume=True)
        assert fresh == 1
        np.testing.assert_allclose(buf, arr)
    finally:
        srv.stop()
        win.free()


@pytest.mark.duration_budget(60)  # pre-existing heavyweight; tier-1 coverage load-bearing
def test_wire_codecs_end_to_end():
    from bluefog_tpu.runtime.window_server import DepositStream

    name = _uniq("wt_codec")
    win = _mk(name, 2, 64)
    srv, port = _serve()
    rng = np.random.default_rng(3)
    x = rng.standard_normal(64)
    try:
        st = DepositStream(("127.0.0.1", port), codec="f32")
        st.deposit_async(name.encode(), 0, x, accumulate=False)
        st.flush(timeout_s=30)
        got, fresh = win.read(0, consume=True)
        assert fresh == 1
        np.testing.assert_allclose(got, x.astype(np.float32), rtol=1e-6)
        st.close()

        st = DepositStream(("127.0.0.1", port), codec="topk",
                           topk_ratio=0.25)
        st.deposit_async(name.encode(), 1, x, accumulate=False)
        st.flush(timeout_s=30)
        got, fresh = win.read(1, consume=True)
        assert fresh == 1
        k = 16
        idx = np.argsort(-np.abs(x))[:k]
        dense = np.zeros(64)
        dense[idx] = x[idx].astype(np.float32)
        np.testing.assert_allclose(got, dense, rtol=1e-6)
        st.close()
    finally:
        srv.stop()
        win.free()


def test_deferred_ack_singles_and_flush_op():
    """The deferred-ack wire flag: singles stream without per-deposit
    status; the FLUSH op returns the applied count, or the first latched
    error (then clears it)."""
    from bluefog_tpu.runtime import window_server as ws

    name = _uniq("wt_defer")
    win = _mk(name, 1, 4)
    srv, port = _serve()
    try:
        nb = name.encode()
        p = np.ones(4)
        with socket.create_connection(("127.0.0.1", port), timeout=10) as s:
            def dep(target, flags=ws._FLAG_ACCUMULATE | ws._FLAG_DEFERRED_ACK):
                s.sendall(ws._HDR.pack(ws._MAGIC, ws._OP_DEPOSIT, len(target))
                          + target + ws._BODY.pack(0, flags, 1, 4)
                          + p.tobytes())

            def flush():
                s.sendall(ws._HDR.pack(ws._MAGIC, ws._OP_FLUSH, 0))
                (rc,) = ws._STATUS.unpack(s.recv(8))
                return rc

            dep(nb)
            dep(nb)
            dep(nb)
            assert flush() == 3
            assert flush() == 0  # counter cleared
            dep(b"missing_win")  # latches -3, payload eaten
            dep(nb)              # still applies
            assert flush() == -3  # first error wins, then state resets
            assert flush() == 0
        buf, fresh = win.read(0, consume=True)
        assert fresh == 4
        np.testing.assert_allclose(buf, 4.0)
    finally:
        srv.stop()
        win.free()


# ---------------------------------------------------------------------------
# malformed / truncated frame fuzz
# ---------------------------------------------------------------------------


def _valid_batch_bytes(ws, name_b, arr, seq=9):
    return (ws._HDR.pack(ws._MAGIC, ws._OP_DEPOSIT_BATCH, 0)
            + ws._BATCH_HDR.pack(seq, 1)
            + ws._ITEM.pack(len(name_b), 0, 1, 1, 0, arr.size, arr.nbytes)
            + name_b + arr.tobytes())


def test_fuzz_malformed_and_truncated_batch_frames():
    """Randomly truncated and bit-flipped batch frames must never take the
    server down: each bad stream at worst loses ITS connection, and a
    fresh client immediately afterwards works.  (The parser's worst
    enemies: lying lengths, unknown codecs, counts that overrun.)"""
    from bluefog_tpu.runtime import window_server as ws
    from bluefog_tpu.runtime.window_server import RemoteWindow

    name = _uniq("wt_fuzz")
    win = _mk(name, 1, 8)
    srv, port = _serve()
    rng = np.random.default_rng(11)
    arr = np.ones(8)
    base = _valid_batch_bytes(ws, name.encode(), arr)
    try:
        for trial in range(60):
            blob = bytearray(base)
            mode = trial % 3
            if mode == 0:  # truncate anywhere (mid-header, mid-payload)
                blob = blob[:int(rng.integers(1, len(blob)))]
            elif mode == 1:  # flip bytes after the magic (keep it ours)
                for _ in range(int(rng.integers(1, 6))):
                    i = int(rng.integers(ws._HDR.size, len(blob)))
                    blob[i] = int(rng.integers(0, 256))
            else:  # absurd claimed lengths in the item header
                off = ws._HDR.size + ws._BATCH_HDR.size
                item = ws._ITEM.pack(
                    len(name.encode()), 0, 1, 1, 0,
                    int(rng.integers(1, 1 << 40)),
                    int(rng.integers(1, 1 << 40)))
                blob[off:off + ws._ITEM.size] = item
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=10) as s:
                s.settimeout(5)
                try:
                    s.sendall(blob)
                    s.shutdown(socket.SHUT_WR)
                    while s.recv(4096):
                        pass
                except OSError:
                    pass  # connection torn either way — that is allowed
        # the server must still be fully functional for a fresh client
        rw = RemoteWindow(("127.0.0.1", port), name)
        win.read(0, consume=True)  # discard whatever fuzz landed
        assert rw.deposit(0, arr, accumulate=True) >= 1
        buf, fresh = win.read(0, consume=True)
        assert fresh == 1
        np.testing.assert_allclose(buf, arr)
        rw.close()
    finally:
        srv.stop()
        win.free()


def test_truncated_payload_never_applies_partially():
    """A connection dying mid-payload must not deposit a partial buffer:
    the item only applies after its full payload arrived."""
    from bluefog_tpu.runtime import window_server as ws

    name = _uniq("wt_trunc")
    win = _mk(name, 1, 1024)
    srv, port = _serve()
    arr = np.ones(1024)
    try:
        full = _valid_batch_bytes(ws, name.encode(), arr)
        with socket.create_connection(("127.0.0.1", port), timeout=10) as s:
            s.sendall(full[:len(full) - 512])  # half the payload missing
        import time

        time.sleep(0.2)  # let the handler observe the EOF
        buf, fresh = win.read(0, consume=True)
        assert fresh == 0, "partial payload must never be applied"
    finally:
        srv.stop()
        win.free()


# ---------------------------------------------------------------------------
# multi-process pipelined dsgd: the audit stays exact
# ---------------------------------------------------------------------------


def _run_dsgd_workers(transport, nproc=2, duration="1.5"):
    import tempfile

    with tempfile.TemporaryDirectory() as bdir:
        worker = os.path.join(_REPO, "tests", "_mp_async_worker.py")
        # the worker asserts rank 0 outsteps the LAST rank by >1.5x, so
        # the last rank must carry the largest skew; the margins are wider
        # than the shm test's because the pipelined transport adds
        # background threads whose scheduling noise inflates every rank's
        # per-step floor on small CI hosts
        # (3 rank processes over 2 CI cores run ~25 ms/step from CPU
        # contention alone — double that when the host throttles — so the
        # slow rank's skew must dominate even an inflated per-step floor
        # for the worker's >1.5x assertion to have margin)
        skews_ms = ["0.5", "12.0"] if nproc == 2 else ["0.5", "2.0", "45.0"]
        procs = [
            subprocess.Popen(
                [sys.executable, worker, str(r), str(nproc), bdir,
                 duration, skews_ms[r], transport],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=clean_env(), cwd=_REPO)
            for r in range(nproc)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=240)
                outs.append(out)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail("pipelined dsgd workers timed out:\n"
                        + "\n".join(o or "" for o in outs))
        for r, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"worker {r} failed:\n{out}"
            assert f"ASYNC_MP_OK {r}" in out, f"worker {r} output:\n{out}"


@pytest.mark.duration_budget(60)  # pre-existing heavyweight; tier-1 coverage load-bearing
def test_pipelined_dsgd_mass_audit_exact_two_processes():
    """Two OS processes, pipelined TCP deposits, skewed step rates: the
    worker asserts mass conservation EXACTLY (sum p == n to 1e-9·n) plus
    convergence — the flush fence before the 'stopped' barrier is what
    makes the audit exact under fire-and-forget deposits."""
    _run_dsgd_workers("tcp", nproc=2)


@pytest.mark.slow
def test_pipelined_dsgd_mass_audit_soak_three_processes():
    """Soak variant: three ranks, longer run, more in-flight overlap."""
    _run_dsgd_workers("tcp", nproc=3, duration="5.0")
