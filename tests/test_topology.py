"""Topology-library unit tests — mirrors the reference's
``test/common/topology_util_test.py`` pyramid layer (SURVEY.md §4): pure
pytest, no devices: row-stochasticity, neighbor sets, equivalence, dynamic
generators, schedule lowering round-trips."""

import math

import numpy as np
import pytest

from bluefog_tpu.topology import (
    ExponentialGraph,
    ExponentialTwoGraph,
    FullyConnectedGraph,
    GetDynamicOnePeerSendRecvRanks,
    GetInnerOuterExpo2DynamicSendRecvRanks,
    GetInnerOuterRingDynamicSendRecvRanks,
    GetRecvWeights,
    GetSendWeights,
    GossipSchedule,
    IsRegularGraph,
    IsTopologyEquivalent,
    MeshGrid2DGraph,
    RingGraph,
    StarGraph,
    SymmetricExponentialGraph,
    Topology,
    build_schedule,
    dynamic_topologies_from_generator,
    one_peer_exponential_two_schedules,
    one_peer_ring_schedules,
    remap_topology,
)

ALL_SIZES = [2, 3, 4, 7, 8, 16]


def _constructors(size):
    return [
        ExponentialTwoGraph(size),
        ExponentialGraph(size, base=3),
        SymmetricExponentialGraph(size),
        RingGraph(size, 0),
        RingGraph(size, 1),
        RingGraph(size, 2),
        MeshGrid2DGraph(size),
        StarGraph(size),
        FullyConnectedGraph(size),
    ]


@pytest.mark.parametrize("size", ALL_SIZES)
def test_row_stochastic_and_nonnegative(size):
    for topo in _constructors(size):
        w = topo.weights
        assert np.allclose(w.sum(axis=1), 1.0), topo.name
        assert (w >= 0).all(), topo.name


def test_exponential_two_neighbors():
    topo = ExponentialTwoGraph(8)
    # rank 0 sends to +1, +2, +4
    assert topo.out_neighbors(0) == [1, 2, 4]
    assert topo.in_neighbors(0) == [4, 6, 7]
    # uniform 1/(indeg+1) weights
    assert math.isclose(topo.self_weight(0), 0.25)
    assert all(math.isclose(w, 0.25) for w in GetRecvWeights(topo, 0)[1].values())


def test_exponential_non_power_of_two():
    topo = ExponentialTwoGraph(6)
    assert topo.out_neighbors(0) == [1, 2, 4]
    assert np.allclose(topo.weights.sum(axis=1), 1.0)


def test_ring_styles():
    bi = RingGraph(5, 0)
    assert bi.in_neighbors(2) == [1, 3]
    assert math.isclose(bi.self_weight(2), 1 / 3)
    right = RingGraph(5, 1)
    assert right.in_neighbors(2) == [1]
    assert right.out_neighbors(2) == [3]
    left = RingGraph(5, 2)
    assert left.in_neighbors(2) == [3]
    # size-2 ring: the two directions coincide
    tiny = RingGraph(2, 0)
    assert tiny.in_neighbors(0) == [1]
    assert math.isclose(tiny.self_weight(0), 0.5)


def test_mesh_grid_doubly_stochastic():
    topo = MeshGrid2DGraph(6)  # 2x3 grid
    w = topo.weights
    assert np.allclose(w.sum(axis=0), 1.0)  # column-stochastic too (MH weights)
    assert np.allclose(w, w.T)
    assert IsRegularGraph(topo)
    # corner rank 0 of the 2x3 grid: neighbors are 1 (right) and 3 (below)
    assert topo.in_neighbors(0) == [1, 3]


def test_mesh_grid_explicit_shape():
    topo = MeshGrid2DGraph(8, shape=(2, 4))
    assert topo.size == 8
    with pytest.raises(ValueError):
        MeshGrid2DGraph(8, shape=(3, 3))


def test_star():
    topo = StarGraph(5, center_rank=2)
    assert topo.in_neighbors(2) == [0, 1, 3, 4]
    assert topo.in_neighbors(0) == [2]
    assert math.isclose(topo.self_weight(2), 1 / 5)
    assert math.isclose(topo.self_weight(0), 1 / 2)


def test_fully_connected_exact_average():
    topo = FullyConnectedGraph(4)
    x = np.array([1.0, 2.0, 3.0, 10.0])
    assert np.allclose(topo.weights @ x, x.mean())


def test_equivalence_and_remap():
    a, b = ExponentialTwoGraph(8), ExponentialTwoGraph(8)
    assert IsTopologyEquivalent(a, b)
    assert not IsTopologyEquivalent(a, RingGraph(8))
    assert not IsTopologyEquivalent(a, ExponentialTwoGraph(4))
    assert not IsTopologyEquivalent(a, None)
    perm = list(reversed(range(8)))
    r = remap_topology(a, perm)
    assert not IsTopologyEquivalent(a, r) or a.size == 1
    assert IsTopologyEquivalent(a, remap_topology(r, perm))  # involution


def test_send_recv_weights_duality():
    topo = ExponentialTwoGraph(8)
    for r in range(8):
        _, send = GetSendWeights(topo, r)
        for dst, w in send.items():
            self_w, recv = GetRecvWeights(topo, dst)
            assert math.isclose(recv[r], w)
            del self_w


def test_from_edges_uniform_weights():
    topo = Topology.from_edges(4, [(0, 1), (2, 1), (1, 0)])
    assert math.isclose(topo.weights[1, 0], 1 / 3)
    assert math.isclose(topo.weights[1, 2], 1 / 3)
    assert math.isclose(topo.weights[1, 1], 1 / 3)
    assert math.isclose(topo.weights[3, 3], 1.0)


def test_networkx_round_trip():
    nx = pytest.importorskip("networkx")
    topo = MeshGrid2DGraph(6)
    g = topo.to_networkx()
    back = Topology.from_networkx(g)
    assert IsTopologyEquivalent(topo, back)
    del nx


# -- schedules ---------------------------------------------------------------


@pytest.mark.parametrize("size", ALL_SIZES)
def test_schedule_reproduces_mixing_matrix(size):
    for topo in _constructors(size):
        sched = build_schedule(topo)
        assert np.allclose(sched.mixing_matrix(), topo.weights, atol=1e-9), topo.name


def test_circulant_fast_path():
    assert build_schedule(ExponentialTwoGraph(8)).is_circulant
    assert build_schedule(RingGraph(8)).is_circulant
    assert build_schedule(FullyConnectedGraph(4)).is_circulant
    assert not build_schedule(StarGraph(8)).is_circulant
    assert not build_schedule(MeshGrid2DGraph(6)).is_circulant


def test_schedule_slot_counts():
    # circulant: one slot per shift class
    assert build_schedule(ExponentialTwoGraph(8)).num_slots == 3
    assert build_schedule(RingGraph(8)).num_slots == 2
    # star(n): greedy coloring needs >= n-1 slots at the hub
    s = build_schedule(StarGraph(5))
    assert s.num_slots >= 4
    for perm in s.perms:
        srcs = [a for a, _ in perm]
        dsts = [b for _, b in perm]
        assert len(set(srcs)) == len(srcs)
        assert len(set(dsts)) == len(dsts)


# -- dynamic generators ------------------------------------------------------


def test_one_peer_generator_cycles():
    topo = ExponentialTwoGraph(8)
    gen = GetDynamicOnePeerSendRecvRanks(topo, 0)
    seen = [next(gen) for _ in range(6)]
    # cycles through out-neighbors 1,2,4 and in-neighbors 7,6,4 (offset order)
    assert [s for s, _ in seen] == [[1], [2], [4], [1], [2], [4]]
    assert [r for _, r in seen] == [[7], [6], [4], [7], [6], [4]]


def test_dynamic_topologies_consistent():
    topo = ExponentialTwoGraph(8)
    topos = dynamic_topologies_from_generator(
        8, lambda r: GetDynamicOnePeerSendRecvRanks(topo, r), num_steps=6
    )
    assert len(topos) == 6
    for t in topos:
        assert np.allclose(t.weights.sum(axis=1), 1.0)
        for r in range(8):
            assert t.in_degree(r) == 1
            assert t.out_degree(r) == 1


def test_one_peer_exp2_schedules():
    topos = one_peer_exponential_two_schedules(8)
    assert len(topos) == 3
    for k, t in enumerate(topos):
        assert t.in_neighbors(0) == [(0 - 2**k) % 8]
        assert math.isclose(t.self_weight(0), 0.5)
    # product over one period mixes mass from every rank to every rank
    prod = np.eye(8)
    for t in topos:
        prod = t.weights @ prod
    assert (prod > 0).all()


def test_one_peer_ring_schedules():
    topos = one_peer_ring_schedules(8)
    assert len(topos) == 2
    assert topos[0].in_neighbors(0) == [7]
    assert topos[1].in_neighbors(0) == [1]


def test_inner_outer_generators_consistent():
    for factory in (
        lambda r: GetInnerOuterRingDynamicSendRecvRanks(8, 2, r),
        lambda r: GetInnerOuterExpo2DynamicSendRecvRanks(8, 2, r),
    ):
        topos = dynamic_topologies_from_generator(8, factory, num_steps=8)
        for t in topos:
            for r in range(8):
                assert t.in_degree(r) <= 1


def test_bad_weight_matrix_rejected():
    with pytest.raises(ValueError):
        Topology(weights=np.array([[0.5, 0.2], [0.5, 0.5]]))
    with pytest.raises(ValueError):
        Topology(weights=np.array([[1.5, -0.5], [0.0, 1.0]]))


class TestICIRingOrder:
    """ici_ring_order must produce a path where consecutive devices are one
    torus hop apart (SURVEY.md §7: ring -> ICI torus ring is exact)."""

    class FakeDev:
        def __init__(self, id, coords):
            self.id = id
            self.coords = coords

    @staticmethod
    def _torus_dist(a, b, dims):
        return sum(min(abs(x - y), d - abs(x - y))
                   for x, y, d in zip(a, b, dims))

    @pytest.mark.parametrize("dims", [(4, 4), (2, 4), (4, 2, 2)])
    def test_consecutive_are_adjacent(self, dims):
        import itertools

        from bluefog_tpu.topology.mapping import ici_ring_order

        devs = [self.FakeDev(i, c) for i, c in
                enumerate(itertools.product(*[range(d) for d in dims]))]
        # scramble to prove the sort does the work
        import random as _r
        _r.Random(0).shuffle(devs)
        ordered = ici_ring_order(devs)
        assert len(ordered) == len(devs)
        for a, b in zip(ordered, ordered[1:]):
            assert self._torus_dist(a.coords, b.coords, dims) == 1, (
                f"{a.coords} -> {b.coords} is not one hop")
        # the closing edge matters too: ring topologies wrap last -> first
        assert self._torus_dist(ordered[-1].coords, ordered[0].coords,
                                dims) == 1

    def test_no_coords_falls_back_to_id(self):
        from bluefog_tpu.topology.mapping import ici_ring_order

        class Bare:
            def __init__(self, id):
                self.id = id

        devs = [Bare(3), Bare(0), Bare(2), Bare(1)]
        assert [d.id for d in ici_ring_order(devs)] == [0, 1, 2, 3]
