"""Dtype coverage across the op surface — the reference supports fp16/32/64
plus integer allreduce via per-dtype extension entry points and a custom fp16
MPI sum (`bluefog/torch/mpi_ops.cc` per-dtype enqueue fns, `common/half.h`;
SURVEY.md §2.1, §4 "over dtypes fp16/32/64").  The SPMD equivalents here are
dtype-polymorphic; these tests pin the contract:

- outputs preserve the input dtype,
- low-precision gossip accumulates in f32 (half.h's concern),
- integer and bool collectives work where the semantics are exact.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import bluefog_tpu as bf
from bluefog_tpu.ops import collectives as C
from bluefog_tpu.ops import windows as W
from bluefog_tpu.parallel.api import shard_map
from bluefog_tpu.topology import ExponentialTwoGraph, RingGraph
from bluefog_tpu.topology.schedule import build_schedule

N = 8
FLOAT_DTYPES = [jnp.float32, jnp.bfloat16, jnp.float16]
INT_DTYPES = [jnp.int32, jnp.uint32]


def run_spmd(fn, *args, n=N):
    ctx = bf.get_context()
    return jax.jit(shard_map(
        fn, mesh=ctx.mesh, in_specs=(P(ctx.axis_name),) * len(args),
        out_specs=P(ctx.axis_name), check_vma=False))(*args)


def rank_values(dtype, shape=(8,)):
    base = jnp.arange(N, dtype=jnp.float32).reshape((N,) + (1,) * len(shape))
    return jnp.broadcast_to(base, (N,) + shape).astype(dtype)


@pytest.mark.parametrize("dtype", FLOAT_DTYPES, ids=str)
def test_neighbor_allreduce_float_dtypes(dtype):
    bf.init(topology=RingGraph(N))
    sched = build_schedule(RingGraph(N))
    x = rank_values(dtype)

    out = run_spmd(
        lambda b: C.neighbor_allreduce(b[0], sched, "bf")[None], x)
    assert out.dtype == dtype
    # ring: out_r = (x_{r-1} + x_r + x_{r+1}) / 3; exact values are small
    # ints/3 — f32 accumulation keeps bf16/f16 within one ulp of x/3
    W_mat = np.asarray(RingGraph(N).weights)
    expected = W_mat @ np.arange(N, dtype=np.float64)
    got = np.asarray(out, np.float64)[:, 0]
    # bf16 holds ~8 mantissa bits → ~0.4% relative error on values near 4
    tol = {jnp.float32: 1e-6, jnp.bfloat16: 5e-2, jnp.float16: 1e-2}[dtype]
    np.testing.assert_allclose(got, expected, atol=tol)


@pytest.mark.parametrize("dtype", INT_DTYPES, ids=str)
def test_allreduce_sum_int(dtype):
    bf.init()
    x = rank_values(dtype)
    out = run_spmd(
        lambda b: C.allreduce(b[0], "bf", average=False)[None], x)
    assert out.dtype == dtype
    assert int(out[0, 0]) == sum(range(N))


def test_broadcast_int_and_bool():
    bf.init()
    x = rank_values(jnp.int32)
    out = run_spmd(lambda b: C.broadcast(b[0], 3, "bf")[None], x)
    assert out.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(out), 3)

    flags = (jnp.arange(N) % 2 == 0)[:, None]
    out = run_spmd(lambda b: C.broadcast(b[0], 2, "bf")[None], flags)
    assert out.dtype == jnp.bool_
    assert np.asarray(out).all()


@pytest.mark.parametrize("dtype", FLOAT_DTYPES, ids=str)
def test_allgather_and_neighbor_allgather_dtypes(dtype):
    bf.init(topology=RingGraph(N))
    sched = build_schedule(RingGraph(N))
    x = rank_values(dtype)

    out = run_spmd(lambda b: C.allgather(b[0], "bf")[None], x)
    assert out.dtype == dtype
    np.testing.assert_array_equal(
        np.asarray(out[0], np.float32)[:, 0], np.arange(N, dtype=np.float32))

    def nag(b):
        slots, mask = C.neighbor_allgather(b[0], sched, "bf")
        del mask
        return slots[None]

    slots = run_spmd(nag, x)
    assert slots.dtype == dtype


@pytest.mark.parametrize("dtype", FLOAT_DTYPES, ids=str)
def test_window_roundtrip_dtypes(dtype):
    """win_create → win_put(1/3) → win_update keeps dtype and stays accurate
    in low precision (f32 weighting inside, half.h-style)."""
    bf.init(topology=RingGraph(N))
    sched = build_schedule(RingGraph(N))
    x = rank_values(dtype)

    def step(b):
        leaf = b[0]
        st = W.win_create(leaf, sched, "bf")
        st = W.win_put(st, leaf, "bf", dst_weight=1.0 / 3.0)
        out, st = W.win_update(st, "bf",
                               self_weight=1.0 / 3.0,
                               recv_weights=jnp.ones((sched.num_slots,)))
        return out[None]

    out = run_spmd(step, x)
    assert out.dtype == dtype
    # out_r = x_r/3 + (x_{r-1} + x_{r+1})/3 = ring average * 3/3
    W_mat = np.asarray(RingGraph(N).weights)
    expected = W_mat @ np.arange(N, dtype=np.float64)
    got = np.asarray(out, np.float64)[:, 0]
    np.testing.assert_allclose(got, expected, atol=2e-2)


def test_optimizer_bf16_params_finite():
    """A gossip SGD step on bf16 parameters stays finite and bf16."""
    import optax

    from bluefog_tpu.optim import DistributedNeighborAllreduceOptimizer

    bf.init(topology=ExponentialTwoGraph(N))
    ctx = bf.get_context()
    opt = DistributedNeighborAllreduceOptimizer(
        optax.sgd(0.1), topology=ctx.schedule, axis_name=ctx.axis_name)
    w = bf.rank_shard(bf.rank_stack(jnp.ones((16,), jnp.bfloat16)))

    def step(w_blk):
        w = w_blk[0]
        st = opt.init(w)
        g = w * jnp.asarray(0.5, jnp.bfloat16)
        upd, st = opt.update(g, st, w)
        import optax as ox
        return ox.apply_updates(w, upd)[None]

    out = run_spmd(step, w)
    assert out.dtype == jnp.bfloat16
    assert np.isfinite(np.asarray(out, np.float32)).all()


def test_mixed_dtype_pytree_gossip():
    """Pytrees mixing bf16/f32 leaves gossip leaf-wise with per-leaf dtypes."""
    bf.init(topology=RingGraph(N))
    sched = build_schedule(RingGraph(N))
    tree = {"a": rank_values(jnp.bfloat16), "b": rank_values(jnp.float32)}

    def step(blk):
        local = jax.tree_util.tree_map(lambda t: t[0], blk)
        out = C.neighbor_allreduce(local, sched, "bf")
        return jax.tree_util.tree_map(lambda t: t[None], out)

    ctx = bf.get_context()
    out = jax.jit(shard_map(
        step, mesh=ctx.mesh, in_specs=(P(ctx.axis_name),),
        out_specs=P(ctx.axis_name), check_vma=False))(tree)
    assert out["a"].dtype == jnp.bfloat16
    assert out["b"].dtype == jnp.float32
