"""Per-round timeline spans emitted from INSIDE the jitted step.

The reference's timeline.cc records per-tensor stage events as the engine
executes (SURVEY.md §5); the SPMD analog must come from inside the compiled
program — ``utils.timeline.device_stage`` io_callbacks.  Asserts: span
presence per rank per step, B-before-E ordering, and zero footprint when the
timeline is off.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from bluefog_tpu.ops import collectives as C
from bluefog_tpu.parallel.api import shard_map
from bluefog_tpu.topology import ExponentialTwoGraph, RingGraph
from bluefog_tpu.topology.schedule import build_schedule
from bluefog_tpu.utils import timeline as T

N = 8


@pytest.fixture(autouse=True)
def _isolated_timeline(monkeypatch):
    """The feature under test is env/global-state driven: make sure no
    ambient BLUEFOG_TPU_TIMELINE or leaked writer bleeds into a test."""
    monkeypatch.delenv("BLUEFOG_TPU_TIMELINE", raising=False)
    T.timeline_stop()
    yield
    T.timeline_stop()


def _mesh():
    return Mesh(np.array(jax.devices()[:N]), ("bf",))


def _gossip_fn(sched):
    return jax.jit(shard_map(
        lambda v: C.neighbor_allreduce(v, sched, "bf"),
        mesh=_mesh(), in_specs=(P("bf"),), out_specs=P("bf"),
        check_vma=False))


def _load_events(path):
    with open(path) as f:
        return json.load(f)


def test_gossip_rounds_emit_runtime_spans(tmp_path):
    trace = str(tmp_path / "trace.json")
    sched = build_schedule(ExponentialTwoGraph(N))
    T.timeline_start(trace)
    try:
        fn = _gossip_fn(sched)  # traced while the timeline is active
        x = jnp.arange(N * 4, dtype=jnp.float32).reshape(N, 4)
        steps = 3
        for _ in range(steps):
            x = fn(x)
        jax.block_until_ready(x)
    finally:
        T.timeline_stop()

    events = [e for e in _load_events(trace)
              if e["name"] == "bf.neighbor_allreduce"]
    # device_stage emits chrome ASYNC events (ph b/e with per-instance
    # ids) so same-name instances can never render as crossed durations
    begins = [e for e in events if e["ph"] == "b"]
    ends = [e for e in events if e["ph"] == "e"]
    # one b and one e per rank per step, in per-rank lanes
    assert len(begins) == steps * N, (len(begins), steps * N)
    assert len(ends) == steps * N
    assert {e["tid"] for e in events} == set(range(N))
    for tid in range(N):
        lane = sorted((e["ts"], e["ph"], e["id"]) for e in events
                      if e["tid"] == tid)
        phases = [ph for _, ph, _ in lane]
        assert phases[0] == "b" and phases[-1] == "e"
        assert phases.count("b") == steps and phases.count("e") == steps
        # every span instance has a unique id, opened exactly once and
        # closed exactly once — the no-mis-nest guarantee
        b_ids = [i for _, ph, i in lane if ph == "b"]
        e_ids = [i for _, ph, i in lane if ph == "e"]
        assert len(set(b_ids)) == steps
        assert sorted(b_ids) == sorted(e_ids)


def test_no_timeline_no_callbacks():
    """With no active timeline at trace time, the compiled gossip contains
    no host callbacks (zero runtime footprint)."""
    assert T._get() is None  # guaranteed by _isolated_timeline
    sched = build_schedule(RingGraph(N))
    fn = _gossip_fn(sched)
    x = jnp.ones((N, 4), jnp.float32)
    hlo = fn.lower(x).compile().as_text()
    assert "custom-call" not in hlo.lower() or "callback" not in hlo.lower()
    jax.block_until_ready(fn(x))


def test_dynamic_topology_spans_compile(tmp_path):
    """The lax.switch dynamic-gossip path still compiles and runs with the
    timeline active (callbacks inside switch branches)."""
    from bluefog_tpu.topology.dynamic import one_peer_exponential_two_schedules

    trace = str(tmp_path / "trace_dyn.json")
    scheds = [build_schedule(t)
              for t in one_peer_exponential_two_schedules(N)]
    T.timeline_start(trace)
    try:
        fn = jax.jit(shard_map(
            lambda v, s: C.neighbor_allreduce_dynamic(v, scheds, s, "bf"),
            mesh=_mesh(), in_specs=(P("bf"), P()), out_specs=P("bf"),
            check_vma=False))
        x = jnp.ones((N, 4), jnp.float32)
        for step in range(2):
            x = fn(x, jnp.asarray(step))
        jax.block_until_ready(x)
    finally:
        T.timeline_stop()
    events = [e for e in _load_events(trace)
              if e["name"] == "bf.neighbor_allreduce"]
    assert len(events) >= 2 * N  # B+E per rank per step


def test_gossip_stays_differentiable_with_timeline(tmp_path):
    """Profiling must not break training: grad through an instrumented
    collective works with the timeline active (io_callback has no JVP rule;
    device_stage's custom_jvp shell keeps tangents flowing)."""
    trace = str(tmp_path / "trace_g.json")
    sched = build_schedule(RingGraph(N))
    T.timeline_start(trace)
    try:
        def loss(v):
            out = C.neighbor_allreduce(v, sched, "bf")
            return (out ** 2).sum()

        fn = jax.jit(shard_map(
            jax.grad(loss), mesh=_mesh(), in_specs=(P("bf"),),
            out_specs=P("bf"), check_vma=False))
        g = fn(jnp.arange(N * 4, dtype=jnp.float32).reshape(N, 4))
        jax.block_until_ready(g)
        assert np.isfinite(np.asarray(g)).all()
    finally:
        T.timeline_stop()
    # the primal's spans were still emitted
    events = [e for e in _load_events(trace)
              if e["name"] == "bf.neighbor_allreduce"]
    assert events


def test_hierarchical_spans(tmp_path):
    trace = str(tmp_path / "trace_h.json")
    msched = build_schedule(RingGraph(4))
    T.timeline_start(trace)
    try:
        fn = jax.jit(shard_map(
            lambda v: C.hierarchical_neighbor_allreduce(
                v, msched, "bf", local_size=2),
            mesh=_mesh(), in_specs=(P("bf"),), out_specs=P("bf"),
            check_vma=False))
        jax.block_until_ready(fn(jnp.ones((N, 4), jnp.float32)))
    finally:
        T.timeline_stop()
    events = [e for e in _load_events(trace)
              if e["name"] == "bf.hierarchical_neighbor_allreduce"]
    assert {e["ph"] for e in events} == {"b", "e"}


def test_window_op_spans(tmp_path):
    """win_put / win_update emit paired B/E spans from inside the jitted
    step (the reference's per-tensor stages cover the window family too)."""
    import jax.numpy as jnp

    from bluefog_tpu.ops import windows as W

    trace = str(tmp_path / "trace_w.json")
    sched = build_schedule(RingGraph(N))
    T.timeline_start(trace)
    try:
        def step(v):
            st = W.win_create(v, sched, "bf", name="span_probe")
            st = W.win_put(st, v, "bf", backend="xla")
            st = W.win_accumulate(st, v, "bf", backend="xla")
            st = W.win_get(st, "bf")
            out, st = W.win_update(st, "bf")
            out2, _ = W.win_update_then_collect(st, "bf")
            return out + out2

        fn = jax.jit(shard_map(
            step, mesh=_mesh(), in_specs=(P("bf"),), out_specs=P("bf"),
            check_vma=False))
        jax.block_until_ready(fn(jnp.ones((N, 4), jnp.float32)))
    finally:
        T.timeline_stop()
    for name in ("bf.win_put", "bf.win_accumulate", "bf.win_get",
                 "bf.win_update", "bf.win_update_then_collect"):
        events = [e for e in _load_events(trace) if e["name"] == name]
        assert {e["ph"] for e in events} == {"b", "e"}, name


def test_hierarchical_2d_spans(tmp_path):
    """The two-level-mesh path emits the same B/E gossip spans as the flat
    path, with lanes = linearized (machine, local) ranks."""
    import jax.numpy as jnp
    from jax.sharding import Mesh

    trace = str(tmp_path / "trace_h2.json")
    msched = build_schedule(RingGraph(4))
    mesh2 = Mesh(np.array(jax.devices()[:N]).reshape(4, 2), ("m", "l"))
    T.timeline_start(trace)
    try:
        fn = jax.jit(shard_map(
            lambda v: C.hierarchical_neighbor_allreduce_2d(
                v, msched, machine_axis="m", local_axis="l"),
            mesh=mesh2, in_specs=(P(("m", "l")),), out_specs=P(("m", "l")),
            check_vma=False))
        jax.block_until_ready(fn(jnp.ones((N, 4), jnp.float32)))
    finally:
        T.timeline_stop()
    events = [e for e in _load_events(trace)
              if e["name"] == "bf.hierarchical_neighbor_allreduce_2d"]
    assert {e["ph"] for e in events} == {"b", "e"}
    assert {e["tid"] for e in events} == set(range(N))


def test_async_window_host_spans(tmp_path):
    """AsyncWindow deposit/read emit host-side B/E spans when a timeline is
    recording — the genuinely-asynchronous path's observability (the jitted
    window family's spans cannot see host-loop deposits) — and skip span
    bookkeeping entirely when none is (timeline_active guard)."""
    import numpy as np

    from bluefog_tpu.runtime.async_windows import AsyncWindow

    trace = str(tmp_path / "trace_aw.json")
    T.timeline_start(trace)
    try:
        win = AsyncWindow("span_aw", 1, 4, np.float64)
        win.deposit(0, np.ones(4), accumulate=True)
        win.deposit(0, np.ones(4), accumulate=False)
        win.read(0, consume=True)
        win.free()
    finally:
        T.timeline_stop()
    names = {e["name"] for e in _load_events(trace)}
    for want in ("win_accumulate.span_aw", "win_put.span_aw",
                 "win_update.span_aw"):
        assert want in names, (want, names)
    assert not T.timeline_active()


def test_concurrent_same_name_activities_are_thread_safe(tmp_path):
    """start/end_activity from many threads with ONE span name: per-thread
    annotation stacks mean no thread ever pops (and __exit__s) another
    thread's jax TraceAnnotation, and no exception escapes."""
    import threading

    trace = str(tmp_path / "trace_mt.json")
    T.timeline_start(trace)
    errors = []
    try:
        def worker():
            try:
                for _ in range(50):
                    T.timeline_start_activity("shared_span", "mt")
                    T.timeline_end_activity("shared_span", "mt")
            except BaseException as e:  # must never happen
                errors.append(e)

        ts = [threading.Thread(target=worker) for _ in range(6)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    finally:
        T.timeline_stop()
    assert not errors, errors
    events = [e for e in _load_events(trace) if e["name"] == "shared_span"]
    assert len([e for e in events if e["ph"] == "B"]) == 300
    assert len([e for e in events if e["ph"] == "E"]) == 300


def test_interleaved_async_spans_never_cross(tmp_path):
    """Two data-independent same-name span instances landing b b e e in
    one lane: FIFO id pairing must produce two NON-crossing intervals
    (the old B/E name-matching rendered them crossed)."""
    trace = str(tmp_path / "trace_x.json")
    tl = T.Timeline(trace, flush_interval_s=60)
    tl.begin_async("gossip", "g", tid=3)
    tl.begin_async("gossip", "g", tid=3)
    assert len(tl.open_spans()) == 2
    tl.end_async("gossip", "g", tid=3)
    tl.end_async("gossip", "g", tid=3)
    assert tl.open_spans() == []
    tl.close()
    events = [e for e in _load_events(trace) if e["name"] == "gossip"]
    assert [e["ph"] for e in events] == ["b", "b", "e", "e"]
    # FIFO: first end closes the FIRST begin — intervals nest/abut, never
    # cross, and each instance id appears exactly once per phase
    assert events[0]["id"] == events[2]["id"]
    assert events[1]["id"] == events[3]["id"]
    assert events[0]["id"] != events[1]["id"]


def test_flush_is_incremental_append(tmp_path):
    """flush() drains and APPENDS only the new events instead of
    rewriting the whole array each time (O(n^2) IO over a long run);
    close() terminates the array into valid JSON."""
    trace = str(tmp_path / "trace_f.json")
    tl = T.Timeline(trace, flush_interval_s=3600)  # flusher effectively off
    for i in range(100):
        tl.instant(f"ev{i}")
    tl.flush()
    size1 = os.path.getsize(trace)
    tl.flush()  # nothing new: the file must not be touched
    assert os.path.getsize(trace) == size1
    for i in range(100, 110):
        tl.instant(f"ev{i}")
    tl.flush()
    size2 = os.path.getsize(trace)
    # the second batch appended far less than a full rewrite would have
    assert size1 < size2 < 2 * size1
    tl.close()
    events = _load_events(trace)
    assert [e["name"] for e in events] == [f"ev{i}" for i in range(110)]


def test_empty_timeline_closes_to_valid_json(tmp_path):
    trace = str(tmp_path / "trace_e.json")
    tl = T.Timeline(trace, flush_interval_s=3600)
    tl.close()
    assert _load_events(trace) == []
