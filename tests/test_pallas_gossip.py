"""Pallas RDMA kernel tests under TPU-interpret emulation
(``pltpu.InterpretParams`` runs the Mosaic semantics — semaphores, remote
DMAs — on the CPU mesh).  This validates the genuine TPU one-sided path
(SURVEY.md §7 hard-part #1) without multi-chip hardware."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import bluefog_tpu as bf
from bluefog_tpu.ops import pallas_gossip
from bluefog_tpu.parallel.api import shard_map
from bluefog_tpu.topology import (
    ExponentialTwoGraph,
    MeshGrid2DGraph,
    RingGraph,
    build_schedule,
    one_peer_exponential_two_schedules,
)

N = 8


def _run(body, *inputs, n_out=1):
    bf.init()
    ctx = bf.get_context()
    f = jax.jit(shard_map(
        body, mesh=ctx.mesh, in_specs=(P("bf"),) * len(inputs),
        out_specs=(P("bf"),) * n_out if n_out > 1 else P("bf"),
        check_vma=False,
    ))
    return f(*inputs)


def rank_values(shape=(4,)):
    base = jnp.arange(N, dtype=jnp.float32).reshape((N,) + (1,) * len(shape))
    return jnp.broadcast_to(base, (N,) + shape)


@pytest.mark.parametrize("topo_fn", [
    lambda: RingGraph(N),
    lambda: ExponentialTwoGraph(N),
    lambda: one_peer_exponential_two_schedules(N)[1],
], ids=["ring", "exp2", "one_peer_phase1"])
def test_pallas_gossip_matches_closed_form(topo_fn):
    topo = topo_fn()
    sched = build_schedule(topo)

    def body(xs):
        return pallas_gossip.neighbor_allreduce_pallas(
            xs[0], sched, "bf", interpret=True
        )[None]

    out = _run(body, rank_values((5,)))
    ref = (topo.weights @ np.arange(N, dtype=np.float64)[:, None]).repeat(5, 1)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6)


def test_pallas_gossip_unaligned_shape_and_bf16():
    """Padding path: a (3, 7) bf16 tensor (not tile-aligned)."""
    topo = RingGraph(N)
    sched = build_schedule(topo)

    def body(xs):
        return pallas_gossip.neighbor_allreduce_pallas(
            xs[0], sched, "bf", interpret=True
        )[None]

    x = rank_values((3, 7)).astype(jnp.bfloat16)
    out = _run(body, x)
    assert out.dtype == jnp.bfloat16
    ref = (topo.weights @ np.arange(N, dtype=np.float64)).reshape(N, 1, 1)
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float64), np.broadcast_to(ref, (N, 3, 7)),
        rtol=5e-2,
    )


def test_pallas_deliver_put_and_accumulate():
    topo = RingGraph(N)
    sched = build_schedule(topo)
    k = sched.num_slots

    def body(xs):
        x = xs[0]
        bufs = jnp.zeros((k,) + x.shape, x.dtype)
        bufs = pallas_gossip.deliver_pallas(
            x, bufs, sched, "bf", accumulate=False, interpret=True
        )
        bufs = pallas_gossip.deliver_pallas(
            x, bufs, sched, "bf", accumulate=True, interpret=True
        )
        return bufs[None]

    out = np.asarray(_run(body, rank_values((4,))))  # (N, k, 4)
    # slot k holds 2x the value of the rank feeding that slot (put then acc)
    for r in range(N):
        for slot in range(k):
            src = sched.recv_src[r, slot]
            np.testing.assert_allclose(out[r, slot], 2.0 * src, rtol=1e-6)


def test_pallas_deliver_bf16_wire():
    """bf16 payloads ride a bf16 wire (half the ICI bytes) through the
    window transport too; accumulate semantics match the portable path's
    leaf-dtype adds."""
    topo = RingGraph(N)
    sched = build_schedule(topo)
    k = sched.num_slots

    def body(xs):
        x = xs[0].astype(jnp.bfloat16)
        bufs = jnp.zeros((k,) + x.shape, jnp.bfloat16)
        bufs = pallas_gossip.deliver_pallas(
            x, bufs, sched, "bf", accumulate=False, interpret=True)
        bufs = pallas_gossip.deliver_pallas(
            x, bufs, sched, "bf", accumulate=True, interpret=True)
        assert bufs.dtype == jnp.bfloat16
        return bufs[None]

    out = np.asarray(_run(body, rank_values((3, 7))), np.float64)
    for r in range(N):
        for slot in range(k):
            src = sched.recv_src[r, slot]
            np.testing.assert_allclose(out[r, slot], 2.0 * src,
                                       rtol=1e-2, atol=1e-2)


def test_wire_dtype_selection_and_chunk_accounting():
    """bf16 leaves are counted at 2 bytes (the wire is bf16): half the
    chunks on the gossip path, and up to 2x the f32 cutoff still unchunked /
    within the window transport's routing cutoff."""
    import jax as _jax

    assert pallas_gossip._wire_dtype(jnp.bfloat16) == jnp.bfloat16
    assert pallas_gossip._wire_dtype(jnp.float32) == jnp.float32
    assert pallas_gossip._wire_dtype(jnp.float16) == jnp.float32

    sched = build_schedule(ExponentialTwoGraph(N))
    cutoff_elems = pallas_gossip.DEFAULT_AUTO_MAX_BYTES // 4
    f32_big = jnp.zeros((cutoff_elems + 1,), jnp.float32)
    bf16_same = jnp.zeros((cutoff_elems + 1,), jnp.bfloat16)
    assert pallas_gossip.leaf_chunk_count(f32_big) == 2
    assert pallas_gossip.leaf_chunk_count(bf16_same) == 1
    try:
        orig = _jax.default_backend
        _jax.default_backend = lambda: "tpu"
        # gossip: chunking means no size-based fallback either way
        assert pallas_gossip.auto_gossip_backend(sched, f32_big) == "pallas"
        # window transport (non-chunkable): the wire width decides
        assert pallas_gossip.auto_gossip_backend(
            sched, f32_big, chunkable=False) == "xla"
        assert pallas_gossip.auto_gossip_backend(
            sched, bf16_same, chunkable=False) == "pallas"
    finally:
        _jax.default_backend = orig


def test_pallas_rejects_non_circulant():
    sched = build_schedule(MeshGrid2DGraph(6))
    with pytest.raises(ValueError, match="circulant"):
        pallas_gossip.neighbor_allreduce_pallas(
            jnp.zeros((4,)), sched, "bf", interpret=True
        )


def test_circulant_shift_extraction():
    assert pallas_gossip.circulant_shifts(build_schedule(RingGraph(N))) == (1, N - 1)
    assert pallas_gossip.circulant_shifts(build_schedule(ExponentialTwoGraph(N))) == (1, 2, 4)
    assert pallas_gossip.circulant_shifts(build_schedule(MeshGrid2DGraph(6))) is None
